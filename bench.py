"""Benchmark harness — one JSON line for the driver.

Headline metric (BASELINE.json north star): per-step wall-clock of the
flagship config — ResNet-18 / CIFAR-10 shapes, n=8 coded workers, cyclic code
s=1 under reverse-gradient attack — on the available accelerator.

``vs_baseline``: the reference repo publishes no numbers (BASELINE.md), so the
paper's headline comparison is reported instead: speedup of the cyclic-decode
step over the geometric-median robust-aggregation step at identical model /
batch / adversary schedule (Draco's core claim — reference README.md:2,
baseline_master.py:271-276). Values > 1 mean decode beats geo-median. The
geo-median cost is linear in ``geomedian_iters``; 80 iterations is pinned to
hdmedians-level accuracy by tests/test_repetition_and_aggregation.py
(TestWeiszfeldIterationBudget), so the ratio is apples-to-apples.

Failure discipline (hardened after two driver-window kills, VERDICT r1/r2):
the process carries a HARD total wall-clock budget (default 280 s, env
``DRACO_BENCH_BUDGET`` or ``--budget``). A watchdog thread guarantees that a
structured JSON record reaches stdout before the budget expires under EVERY
failure mode — wedged tunnel probe, hung backend init, stuck compile —
and then hard-exits. Accelerator availability is established by at most two
short bounded subprocess probes (never an unbounded in-process
``jax.devices()``, which blocks ~25 min against a wedged lease). On failure
the structured ``tpu_unavailable`` record is printed IMMEDIATELY; a tiny
LeNet CPU-fallback record (≤5 steps) is appended afterwards only if minutes
remain. On the TPU path, records are emitted incrementally as each leg
completes, so the driver's tail line is always the most complete result even
if a later leg is cut short.

MFU: FLOPs per train step come from XLA's static cost analysis of the
compiled step (an analytic model of the whole program — fwd/bwd, encode,
gather, decode, update), divided by wall-clock and the chip's bf16 peak.

Flags: --steps N --warmup N --reps N --batch-size B --network NAME --cpu-mesh N
       --budget SEC --no-cpu-fallback
"""

import argparse
import json
import os
import sys
import threading
import time

_T0 = time.monotonic()
_BUDGET = [float(os.environ.get("DRACO_BENCH_BUDGET", "280"))]
_PHASE = {"name": "startup"}
_PRINTED = threading.Event()
_LAST_RECORD = {}
_EMIT_LOCK = threading.Lock()

# bf16 systolic-array peak per chip, by device_kind substring (public specs).
# MFU is reported against bf16 peak even for f32 runs (stated in the record).
_PEAK_BF16 = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


def _remaining():
    return _BUDGET[0] - (time.monotonic() - _T0)


def _emit(record):
    """Print a complete JSON record (one line) and remember it. The driver
    records the output tail, so later emissions supersede earlier ones while
    earlier ones survive a mid-run kill."""
    with _EMIT_LOCK:
        _LAST_RECORD.clear()
        _LAST_RECORD.update(record)
        print(json.dumps(record), flush=True)
        _PRINTED.set()


def _start_watchdog(metric_name):
    """Guarantee a JSON line lands before the budget expires, then hard-exit.

    The main thread may be wedged inside a C call (tunnel init, Mosaic
    compile) that ignores signals; a daemon thread + ``os._exit`` is the only
    construction that cannot be blocked by it."""

    def run():
        while True:
            rem = _remaining()
            if rem <= 3:
                break
            time.sleep(min(rem - 3, 5.0))
        # never exit mid-print: a half-written line would leave the driver an
        # unparseable tail — hold the emit lock from the printed-check all
        # the way through the exit
        with _EMIT_LOCK:
            if _PRINTED.is_set():
                os._exit(0)  # record on stdout; don't risk the driver window
            print(json.dumps({
                "metric": metric_name,
                "value": None,
                "unit": "ms/step",
                "vs_baseline": None,
                "error": "bench_budget_exceeded",
                "detail": (
                    f"watchdog fired in phase '{_PHASE['name']}' after "
                    f"{time.monotonic() - _T0:.0f}s (budget {_BUDGET[0]:.0f}s)"
                ),
            }), flush=True)
            os._exit(2)

    threading.Thread(target=run, daemon=True, name="bench-watchdog").start()


def _lint_violations():
    """Chip-window gate against the program-lint artifact
    (tools/program_lint.py → baselines_out/program_lint.json, path
    overridable via DRACO_PROGRAM_LINT_PATH for tests).

    Returns a list of "program: rule" strings for any CNN-family program —
    the family this bench times — whose artifact row reports a
    constant_bloat or host_traffic violation: the two defect classes that
    don't just skew a number but wedge the shared chip window itself (the
    638 MB module that held the tunnel 27 min, PERF.md §4; a host hop that
    serializes every scanned chunk, PERF.md §0). Negative-control rows
    (deliberately defective) are skipped. A missing or unreadable artifact
    gates nothing — the lint runs in CI, not here; this is a last line of
    defense, not the enforcement point.
    """
    path = os.environ.get("DRACO_PROGRAM_LINT_PATH",
                          "baselines_out/program_lint.json")
    try:
        with open(path) as fh:
            report = json.load(fh)
    except Exception:
        return []
    bad = []
    for row in report.get("rows", []):
        if row.get("control"):
            continue
        if row.get("route") != "cnn":  # lint_program stamps every row
            continue
        hits = set(row.get("failed_rules", [])) & {"constant_bloat",
                                                   "host_traffic"}
        for rule in sorted(hits):
            bad.append(f"{row['name']}: {rule}")
    return bad


def _probe_ok(timeout: float):
    """Probe accelerator availability in a clean subprocess (which exits and
    releases the one-client tunnel lease). Returns (ok, detail) — detail is
    the probe's stderr tail so the actual backend error (UNAVAILABLE vs
    auth vs DNS) survives into the structured failure record.

    ``DRACO_BENCH_FAKE_PROBE`` ∈ {ok, down, hang} is a test hook used by
    tests/test_bench_budget.py to exercise every failure path without
    touching the real tunnel."""
    import subprocess

    fake = os.environ.get("DRACO_BENCH_FAKE_PROBE", "")
    if fake == "ok":
        return True, ""
    if fake == "down":
        return False, "fake probe: backend down"
    if fake == "hang":
        code = "import time\ntime.sleep(10**6)\n"
    else:
        code = (
            "import sys, jax\n"
            "d = jax.devices()\n"
            "sys.exit(0 if d and d[0].platform != 'cpu' else 3)\n"
        )
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
        if r.returncode == 0:
            return True, ""
        return False, (r.stderr or "")[-300:]
    except subprocess.TimeoutExpired:
        return False, f"probe subprocess timed out after {timeout:.0f}s"
    except Exception as e:
        return False, f"{type(e).__name__}: {e}"[:300]


def _try_backend():
    """Initialize the accelerator backend under the global budget.

    At most two bounded subprocess probes (an in-process ``jax.devices()``
    against a wedged tunnel blocks ~25 min inside the plugin's retry loop,
    measured 2026-07-30); only after a probe succeeds does this process
    initialize its own backend. No re-exec, no long waits — if the tunnel is
    down we say so immediately and leave the remaining budget to the CPU
    fallback. Returns (devices, None) or (None, error_string).
    """
    import jax

    _PHASE["name"] = "probe"
    detail = ""
    for attempt in range(2):
        # leave ≥60 s of budget for the failure record + CPU fallback
        timeout = min(75.0, max(10.0, _remaining() - 60.0))
        if timeout <= 10.0 and attempt > 0:
            break
        ok, detail = _probe_ok(timeout)
        if ok:
            break
        if attempt == 0 and _remaining() > 90.0:
            time.sleep(5.0)
    else:
        ok = False
    if not ok:
        return None, f"accelerator probe failed/timed out; last: {detail}"
    _PHASE["name"] = "backend_init"
    try:
        devs = jax.devices()
        if devs and devs[0].platform != "cpu":
            return devs, None
        return None, f"only cpu devices visible: {devs}"
    except RuntimeError as e:  # backend flapped between probe and init
        return None, f"{type(e).__name__}: {e}"[:300]


def _compiled_flops(compiled):
    """Analytic FLOPs from XLA's cost analysis of the *optimized* program
    (the unoptimized-HLO figure over-counts ops the compiler fuses away,
    which would inflate MFU)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0)) if cost else 0.0
        return flops if flops > 0 else None
    except Exception:
        return None


def run(cfg_kwargs, ds, mesh, steps, warmup=1, reps=2, want_flops=False,
        info=None):
    """Per-step wall-clock of the jitted train step, plus the compile cost.

    Returns ``(dt_per_step_s, loss, flops, compile_s)``. The first-call
    compile has always been excluded from ms/step by construction (the
    ``.lower().compile()`` below runs before any timed execution); it is now
    also MEASURED and returned so the record carries ``extra.compile_ms`` —
    compile-time drift is a real regression class (a program that doubles
    its compile time eats the chip window even when ms/step holds) and
    tools/perf_watch.py tracks it round-over-round.

    The ``steps`` training steps are folded into ONE jitted ``lax.scan`` over
    batches pre-staged in HBM, and synchronisation is a device→host fetch of
    the final loss: on the dev-tunnel backend ``block_until_ready`` is only a
    *dispatch* barrier (utils/timing.py — per-launch timing there reported a
    197-TFLOP chip at 88,000 TFLOPS), so per-step Python dispatch must be off
    the timed path entirely and the one RPC round trip is measured separately
    and subtracted. The metric is the training step (fwd/bwd + encode +
    gather + decode/aggregate + update), not the host link; on real pods the
    input pipeline overlaps the step via the native prefetcher
    (draco_tpu/data/prefetch.py).
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from draco_tpu.config import TrainConfig
    from draco_tpu.runtime import WORKER_AXIS, put_global
    from draco_tpu.training.trainer import Trainer
    from draco_tpu.utils.timing import time_scanned_steps

    if os.environ.get("DRACO_BENCH_FAKE_WEDGE"):  # test hook: wedged measure
        time.sleep(10**6)

    cfg = TrainConfig(**cfg_kwargs)
    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    if info is not None:
        # logical wire ledger at the program's registered shapes
        # (obs/numerics.wire_ledger, ISSUE 10) — the record's
        # extra.wire_bytes series perf_watch tracks round-over-round
        from draco_tpu.obs import numerics as numerics_mod

        info["wire_ledger"] = numerics_mod.wire_ledger(cfg, tr.setup.dim)
    state = tr.state
    host_x, host_y = [], []
    for step in range(1, steps + 1):
        x, y = tr._host_batch(step)
        host_x.append(np.asarray(x))
        host_y.append(np.asarray(y))
    xs = put_global(np.stack(host_x), NamedSharding(mesh, P(None, WORKER_AXIS)))
    ys = put_global(np.stack(host_y), NamedSharding(mesh, P(None, WORKER_AXIS)))
    ms = put_global(
        np.stack([np.asarray(tr._adv_schedule[s]) for s in range(1, steps + 1)]),
        NamedSharding(mesh, P()),
    )
    step_fn = tr.setup.train_step
    loss_col = tr.setup.metric_names.index("loss")

    if jax.devices()[0].platform == "cpu":
        # CPU mesh (smoke runs): block_until_ready IS a real execution
        # barrier locally, and XLA:CPU executes conv thunks inside
        # while-loop bodies single-threaded — a scanned ResNet step runs
        # ~40× slower than the same step dispatched eagerly (measured:
        # 3-step scans timing out at 20 min vs 10 s/step eager). Python
        # per-step loop is both honest and usable here.
        x0 = [xs[i] for i in range(steps)]
        y0 = [ys[i] for i in range(steps)]
        m0 = [ms[i] for i in range(steps)]
        tc0 = time.perf_counter()
        compiled = step_fn.lower(state, x0[0], y0[0], m0[0]).compile()
        compile_s = time.perf_counter() - tc0
        flops = _compiled_flops(compiled) if want_flops else None
        st, metrics = compiled(state, x0[0], y0[0], m0[0])
        jax.block_until_ready(st.params)  # settle
        t0 = time.perf_counter()
        for i in range(steps):
            st, metrics = compiled(st, x0[i], y0[i], m0[i])
        jax.block_until_ready(st.params)
        dt = (time.perf_counter() - t0) / steps
        loss = float(metrics["loss"])
        tr.close()
        return dt, loss, flops, compile_s

    # The timed program IS the production chunked loop: train_many is the
    # same jitted scan Trainer._run_chunked dispatches with
    # cfg.steps_per_call = steps — bench numbers measure the path users run,
    # not a parallel harness that could drift from it.
    tc0 = time.perf_counter()
    compiled = tr.setup.train_many.lower(state, xs, ys, ms, None).compile()
    compile_s = time.perf_counter() - tc0
    # XLA cost analysis counts a scan body ONCE regardless of trip count
    # (verified on this jax: scan(L=5) and scan(L=10) report identical
    # flops), so the loop's flops figure already IS the per-step figure.
    flops = _compiled_flops(compiled) if want_flops else None

    dt, blocks = time_scanned_steps(
        compiled, state, (xs, ys, ms, None), steps=steps, warmup=warmup,
        reps=reps
    )
    loss = float(np.asarray(jax.device_get(blocks))[-1, loss_col])
    tr.close()
    return dt, loss, flops, compile_s


def measure(args, metric_name, error=None, detail=None):
    """Run the three legs, emitting a progressively more complete record
    after each (the driver keeps the tail line). Legs after the first are
    skipped when the remaining budget can't fit them."""
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh

    import jax

    ds = load_dataset("Cifar10", data_dir="./data")
    mesh = make_mesh(args.num_workers)
    dev = jax.devices()[0]
    platform = dev.platform
    device_kind = getattr(dev, "device_kind", platform)

    common = dict(
        network=args.network,
        dataset="Cifar10",
        batch_size=args.batch_size,
        lr=0.01,
        momentum=0.9,
        num_workers=args.num_workers,
        worker_fail=1,
        err_mode="rev_grad",
        max_steps=args.steps + 1,
        eval_freq=0,
        train_dir="",
        log_every=10**9,
        wire_segments=args.wire_segments,
    )

    # On a host-CPU run (the tpu-unavailable fallback) the r=2s+1 simulate
    # lanes SERIALISE on the host, so simulate-vs-geomedian measures the
    # redundancy artifact, not the decode (the reference's r× compute runs
    # concurrently across n machines). There the PREFERRED vs_baseline basis
    # is the shared leg — algebraically identical decode at 1/r the FLOPs —
    # while the headline value/flops stay the simulate leg's. Emission is
    # complete-first (VERDICT r4 weak #8): the two-leg record goes out whole
    # on the simulate basis the moment the geomedian leg lands, and the
    # shared leg, if it finishes, re-emits with the basis upgraded — so once
    # the geomedian leg lands, no later kill can strand a pending record
    # with a null ratio as the tail line. (Before the geomedian leg a null
    # ratio is unavoidable: there is no baseline to divide by yet.)
    # On accelerators the reference-parity simulate leg is the basis, full
    # stop. (BENCH_r03 showed regression-shaped 0.692 on the simulate basis
    # for exactly the serialisation reason while the same record's shared
    # leg was 2.21x.)
    cpu_basis = platform == "cpu"
    base_extra = {
        "network": args.network,
        "geomedian_iters": 80,
        "num_workers": args.num_workers,
        "batch_size_per_worker": args.batch_size,
        "dataset": ds.name,
        "platform": platform,
        "device_kind": device_kind,
        "compute_dtype": "float32",
        "vs_baseline_basis": "simulate_redundancy",
        # which loop produced the numbers: accelerators time the production
        # train_many scan with all steps fused into one device program;
        # CPU times the eager per-step loop (scanned conv steps crawl on
        # XLA:CPU — PERF.md §4). The LM analogue records the same key in
        # tools/tpu_lm_perf.py (--production-loop times the chunked
        # parallel/token_loop.py driver, PERF.md §4b).
        "steps_per_call": 1 if platform == "cpu" else args.steps,
    }

    def record(value_ms, vs_baseline, extra):
        rec = {
            "metric": metric_name,
            "value": value_ms,
            "unit": "ms/step",
            "vs_baseline": vs_baseline,
            "extra": dict(base_extra, **extra),
        }
        if error:
            rec["error"] = error
            rec["detail"] = (detail or "")[-500:]
        return rec

    # the contender: cyclic code, r=2s+1 redundant compute like the reference
    _PHASE["name"] = "cyclic_leg"
    cyc_info = {}
    t_cyclic, loss_c, flops_c, compile_c = run(
        dict(common, approach="cyclic", redundancy="simulate"),
        ds, mesh, args.steps, args.warmup, args.reps, want_flops=True,
        info=cyc_info,
    )
    ledger = cyc_info.get("wire_ledger")
    if ledger:
        # logical codeword bytes per step (all workers, f32 wire) — the
        # series the item-4 narrow wire will halve/quarter (ISSUE 10)
        base_extra["wire_bytes"] = ledger["bytes_per_step"]["f32"]
        base_extra["wire_bytes_per_worker"] = \
            ledger["bytes_per_worker"]["f32"]
        base_extra["wire_dim"] = ledger["dim"]
        # streaming segmented wire (ISSUE 16): the segment count the
        # timed program decoded with and the ledger's per-segment
        # PHYSICAL bytes — tools/segment_study.py --check and the
        # wire_study checker pin that these sum to the per-step row
        seg = ledger.get("segments") or {}
        base_extra["wire_segments"] = seg.get("count", 1)
        base_extra["wire_segment_bytes_per_step"] = \
            seg.get("physical_bytes_per_step")
    peak = _peak_flops(device_kind)
    mfu = (
        round(flops_c / t_cyclic / peak, 4)
        if (flops_c and peak and t_cyclic > 0)
        else None
    )
    cyc_extra = {
        "loss_cyclic": round(loss_c, 4),
        "flops_per_step": flops_c,
        "peak_bf16_flops": peak,
        "mfu_vs_bf16_peak": mfu,
        # first-call compile wall of the timed program, excluded from
        # ms/step by construction and recorded so perf_watch can track
        # compile-time drift round-over-round (PERF.md §8)
        "compile_ms": round(compile_c * 1000.0, 1),
    }
    _emit(record(round(t_cyclic * 1000.0, 3), None,
                 dict(cyc_extra, partial="geomedian leg pending")))

    # the baseline robust aggregator Draco positions against
    if _remaining() < 30.0:
        return _LAST_RECORD
    _PHASE["name"] = "geomedian_leg"
    t_geomed, loss_g, _, compile_g = run(
        dict(common, approach="baseline", mode="geometric_median"),
        ds, mesh, args.steps, args.warmup, args.reps,
    )
    full_extra = dict(
        cyc_extra,
        geomedian_step_ms=round(t_geomed * 1000.0, 3),
        loss_geomedian=round(loss_g, 4),
        geomedian_compile_ms=round(compile_g * 1000.0, 1),
    )
    value_ms = round(t_cyclic * 1000.0, 3)
    ratio_sim = round(t_geomed / t_cyclic, 4)
    # complete-first: this record already carries a valid ratio on the
    # simulate basis; on CPU the shared leg only *upgrades* the basis later
    if cpu_basis:
        _emit(record(value_ms, ratio_sim,
                     dict(full_extra,
                          note="host-CPU run: simulate lanes serialise; "
                               "shared-basis upgrade follows if budget "
                               "allows")))
    else:
        _emit(record(value_ms, ratio_sim, full_extra))

    def complete_without_shared(reason):
        # the previous emission is already a complete simulate-basis record;
        # re-emit only to attach why the basis upgrade didn't happen
        _emit(record(value_ms, ratio_sim,
                     dict(full_extra, shared_leg_error=reason)))

    # TPU-native fast path: identical decode semantics, each batch gradient
    # computed once (valid because SPMD adversaries are simulated, not
    # mutually-untrusting processes — config.py `redundancy`); reported
    # alongside the reference-parity number, never in its place
    if _remaining() < 30.0:
        if cpu_basis:
            complete_without_shared("budget exhausted before shared leg")
        return _LAST_RECORD
    _PHASE["name"] = "shared_leg"
    try:
        t_shared, _, _, _ = run(
            dict(common, approach="cyclic", redundancy="shared"),
            ds, mesh, args.steps, args.warmup, args.reps,
        )
        shared_extra = dict(
            full_extra,
            shared_redundancy_step_ms=round(t_shared * 1000.0, 3),
            shared_vs_geomedian=round(t_geomed / t_shared, 4),
        )
        if cpu_basis:
            base_extra["vs_baseline_basis"] = "shared_redundancy"
        ratio = round(t_geomed / t_shared, 4) if cpu_basis else ratio_sim
        _emit(record(value_ms, ratio, shared_extra))
    except Exception as e:
        print(f"bench: shared-redundancy leg failed, completing 2-leg "
              f"record: {type(e).__name__}: {e}", file=sys.stderr, flush=True)
        if cpu_basis:
            complete_without_shared(f"{type(e).__name__}: {e}")
    return _LAST_RECORD


def _cpu_fallback(args, err_detail):
    """Tiny clearly-labelled CPU-mesh measurement (LeNet, ≤5 steps) appended
    after the tpu_unavailable record — a relative decode-vs-geomedian ratio
    survives on CPU (computed from the shared leg, see the cpu_basis note in
    measure()), absolute wall-clock does not. Emitted under its OWN metric
    name (lenet_..._cpu_fallback): putting a LeNet/CPU number into the
    flagship metric's series would poison round-over-round comparisons."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    fb_args = argparse.Namespace(**vars(args))
    fb_args.network = "LeNet"
    fb_args.steps = min(args.steps, 5)
    fb_args.warmup = 0
    fb_args.reps = 1
    fb_args.batch_size = min(args.batch_size, 32)
    fb_metric = (
        f"{fb_args.network.lower()}_cifar10_cyclic_s1_revgrad_step_wallclock"
        f"_cpu_fallback"
    )
    measure(fb_args, fb_metric, error="tpu_unavailable_cpu_fallback",
            detail=err_detail)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=1,
                   help="un-timed settle executions of the steps-scan")
    p.add_argument("--reps", type=int, default=2,
                   help="timed executions of the steps-scan")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--network", type=str, default="ResNet18")
    p.add_argument("--num-workers", type=int, default=8)
    p.add_argument("--cpu-mesh", type=int, default=0)
    p.add_argument("--wire-segments", type=int, default=1,
                   help="wire segmentation S for the timed programs "
                        "(ISSUE 16); the record carries "
                        "extra.wire_segments + per-segment ledger bytes")
    p.add_argument("--budget", type=float,
                   default=float(os.environ.get("DRACO_BENCH_BUDGET", "280")),
                   help="hard total wall-clock budget in seconds; a JSON "
                        "record is guaranteed on stdout before it expires")
    p.add_argument("--no-cpu-fallback", action="store_true",
                   help="emit only the error record if the accelerator is down")
    p.add_argument("--ignore-lint", action="store_true",
                   help="time the chip even when baselines_out/"
                        "program_lint.json reports a constant-bloat/"
                        "host-traffic violation for the timed programs")
    args = p.parse_args()
    _BUDGET[0] = max(args.budget, 20.0)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    metric_name = (
        f"{args.network.lower()}_cifar10_cyclic_s1_revgrad_step_wallclock"
    )
    _start_watchdog(metric_name)

    if not args.cpu_mesh:
        if not args.ignore_lint:
            violations = _lint_violations()
            if violations:
                # refuse the chip run: these defect classes wedge the shared
                # window itself, and a wedged window is worth far more than
                # one data point (--ignore-lint overrides)
                _emit({
                    "metric": metric_name,
                    "value": None,
                    "unit": "ms/step",
                    "vs_baseline": None,
                    "error": "program_lint_violation",
                    "detail": ("refusing chip run; fix or rerun "
                               "tools/program_lint.py (or --ignore-lint): "
                               + "; ".join(violations))[:500],
                })
                return dict(_LAST_RECORD)
        devs, err = _try_backend()
        if devs is None:
            # structured failure on stdout IMMEDIATELY — everything after
            # this line is a bonus the driver may or may not see.
            _emit({
                "metric": metric_name,
                "value": None,
                "unit": "ms/step",
                "vs_baseline": None,
                "error": "tpu_unavailable",
                "detail": (err or "")[-500:],
            })
            if not args.no_cpu_fallback and _remaining() > 60.0:
                _PHASE["name"] = "cpu_fallback"
                try:
                    _cpu_fallback(args, err)
                except Exception as e:
                    print(f"bench: cpu fallback failed: "
                          f"{type(e).__name__}: {e}", file=sys.stderr,
                          flush=True)
            return dict(_LAST_RECORD)
    measure(args, metric_name)
    return dict(_LAST_RECORD)


if __name__ == "__main__":
    sys.exit(0 if main() else 1)
