"""Benchmark harness — one JSON line for the driver.

Headline metric (BASELINE.json north star): per-step wall-clock of the
flagship config — ResNet-18 / CIFAR-10 shapes, n=8 coded workers, cyclic code
s=1 under reverse-gradient attack — on the available accelerator.

``vs_baseline``: the reference repo publishes no numbers (BASELINE.md), so the
paper's headline comparison is reported instead: speedup of the cyclic-decode
step over the geometric-median robust-aggregation step at identical model /
batch / adversary schedule (Draco's core claim — reference README.md:2,
baseline_master.py:271-276). Values > 1 mean decode beats geo-median.

Flags: --steps N --warmup N --batch-size B --network NAME --cpu-mesh N (debug)
"""

import argparse
import json
import sys
import time


def run(cfg_kwargs, ds, mesh, steps, warmup):
    """Per-step wall-clock of the jitted train step.

    Batches are staged into HBM before the timed loop: the metric is the
    training step (fwd/bwd + encode + gather + decode/aggregate + update),
    not the host link. On real pods the input pipeline overlaps the step via
    the native prefetcher (draco_tpu/data/prefetch.py); under the dev tunnel
    a host→device transfer per step would swamp the measurement entirely.
    """
    import jax
    import jax.numpy as jnp

    from draco_tpu.config import TrainConfig
    from draco_tpu.training.trainer import Trainer

    cfg = TrainConfig(**cfg_kwargs)
    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    state = tr.state
    total = warmup + steps
    staged = [tr._device_batch(step) for step in range(1, total + 1)]
    masks = [jnp.asarray(tr._adv_schedule[step]) for step in range(1, total + 1)]
    jax.block_until_ready(staged)
    for step in range(1, warmup + 1):  # compile + settle
        x, y = staged[step - 1]
        state, m = tr.setup.train_step(state, x, y, masks[step - 1])
    jax.block_until_ready(state.params)
    t0 = time.perf_counter()
    for step in range(warmup + 1, total + 1):
        x, y = staged[step - 1]
        state, m = tr.setup.train_step(state, x, y, masks[step - 1])
    jax.block_until_ready(state.params)
    dt = (time.perf_counter() - t0) / steps
    tr.close()
    return dt, float(m["loss"])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--network", type=str, default="ResNet18")
    p.add_argument("--num-workers", type=int, default=8)
    p.add_argument("--cpu-mesh", type=int, default=0)
    args = p.parse_args()

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh

    ds = load_dataset("Cifar10", data_dir="./data")
    mesh = make_mesh(args.num_workers)

    common = dict(
        network=args.network,
        dataset="Cifar10",
        batch_size=args.batch_size,
        lr=0.01,
        momentum=0.9,
        num_workers=args.num_workers,
        worker_fail=1,
        err_mode="rev_grad",
        max_steps=args.warmup + args.steps + 1,
        eval_freq=0,
        train_dir="",
        log_every=10**9,
    )

    # the contender: cyclic code, r=2s+1 redundant compute like the reference
    t_cyclic, loss_c = run(
        dict(common, approach="cyclic", redundancy="simulate"),
        ds, mesh, args.steps, args.warmup,
    )
    # the baseline robust aggregator Draco positions against
    t_geomed, loss_g = run(
        dict(common, approach="baseline", mode="geometric_median"),
        ds, mesh, args.steps, args.warmup,
    )

    out = {
        "metric": f"{args.network.lower()}_cifar10_cyclic_s1_revgrad_step_wallclock",
        "value": round(t_cyclic * 1000.0, 3),
        "unit": "ms/step",
        "vs_baseline": round(t_geomed / t_cyclic, 4),
        "extra": {
            "geomedian_step_ms": round(t_geomed * 1000.0, 3),
            "num_workers": args.num_workers,
            "batch_size_per_worker": args.batch_size,
            "dataset": ds.name,
            "loss_cyclic": round(loss_c, 4),
            "loss_geomedian": round(loss_g, 4),
        },
    }
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    sys.exit(0 if main() else 1)
