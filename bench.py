"""Benchmark harness — one JSON line for the driver.

Headline metric (BASELINE.json north star): per-step wall-clock of the
flagship config — ResNet-18 / CIFAR-10 shapes, n=8 coded workers, cyclic code
s=1 under reverse-gradient attack — on the available accelerator.

``vs_baseline``: the reference repo publishes no numbers (BASELINE.md), so the
paper's headline comparison is reported instead: speedup of the cyclic-decode
step over the geometric-median robust-aggregation step at identical model /
batch / adversary schedule (Draco's core claim — reference README.md:2,
baseline_master.py:271-276). Values > 1 mean decode beats geo-median. The
geo-median cost is linear in ``geomedian_iters``; 80 iterations is pinned to
hdmedians-level accuracy by tests/test_repetition_and_aggregation.py
(TestWeiszfeldIterationBudget), so the ratio is apples-to-apples.

Failure discipline: the dev-tunnel TPU admits one client and a wedged lease
can stay Unavailable for tens of minutes, so backend init is retried with
backoff; if the accelerator never comes up the harness emits a *structured*
error record (optionally with a clearly-labelled CPU-fallback measurement)
instead of a traceback.

MFU: FLOPs per train step come from XLA's static cost analysis of the
compiled step (an analytic model of the whole program — fwd/bwd, encode,
gather, decode, update), divided by wall-clock and the chip's bf16 peak.

Flags: --steps N --warmup N --reps N --batch-size B --network NAME --cpu-mesh N
       --init-retries K --retry-wait SEC --no-cpu-fallback
"""

import argparse
import json
import sys
import time

# bf16 systolic-array peak per chip, by device_kind substring (public specs).
# MFU is reported against bf16 peak even for f32 runs (stated in the record).
_PEAK_BF16 = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


def _probe_ok(timeout: float = 300.0):
    """Probe accelerator availability in a clean subprocess (which exits and
    releases the one-client tunnel lease). Returns (ok, detail) — detail is
    the probe's stderr tail so the actual backend error (UNAVAILABLE vs
    auth vs DNS) survives into the structured failure record."""
    import subprocess

    code = (
        "import sys, jax\n"
        "d = jax.devices()\n"
        "sys.exit(0 if d and d[0].platform != 'cpu' else 3)\n"
    )
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
        if r.returncode == 0:
            return True, ""
        return False, (r.stderr or "")[-300:]
    except subprocess.TimeoutExpired:
        return False, f"probe subprocess timed out after {timeout:.0f}s"
    except Exception as e:
        return False, f"{type(e).__name__}: {e}"[:300]


def _try_backend(retries: int, wait: float):
    """Initialize the accelerator backend, retrying a wedged tunnel lease.

    Returns (devices, None) or (None, last_error_string). Availability is
    established in *bounded subprocesses first* (_probe_ok): an in-process
    ``jax.devices()`` against a wedged tunnel blocks inside the plugin's own
    retry loop for ~25 minutes per attempt (measured 2026-07-30), which
    would eat the driver's whole window; a probe subprocess is killed after
    its timeout instead, and only after a probe succeeds does this process
    initialize its own backend (a failed in-process init is sticky —
    xla_bridge caches the surviving backend set).
    """
    import os

    import jax

    probed = False
    detail = ""
    for attempt in range(max(retries, 1)):
        probed, detail = _probe_ok()
        if probed:
            break
        if attempt < retries - 1:
            time.sleep(wait)
    if not probed:
        return None, (
            f"accelerator probe failed/timed out {max(retries, 1)} times "
            f"({wait:.0f}s apart); last: {detail}"
        )
    try:
        devs = jax.devices()
        if devs and devs[0].platform != "cpu":
            return devs, None
        last = f"only cpu devices visible: {devs}"
    except RuntimeError as e:  # backend flapped between probe and init
        last = f"{type(e).__name__}: {e}"
    # a failed in-process init is sticky (xla_bridge caches the surviving
    # backend set and never re-probes the plugin), so if a fresh probe says
    # the chip is back, re-exec once for a clean init — guarded by an env
    # var so a flapping backend can't loop forever
    if not os.environ.get("DRACO_BENCH_REEXEC"):
        for _ in range(max(retries - 1, 0)):
            time.sleep(wait)
            ok, _d = _probe_ok()
            if ok:
                os.environ["DRACO_BENCH_REEXEC"] = "1"
                sys.stdout.flush()
                os.execv(sys.executable, [sys.executable] + sys.argv)
    return None, last


def _compiled_flops(compiled):
    """Analytic FLOPs from XLA's cost analysis of the *optimized* program
    (the unoptimized-HLO figure over-counts ops the compiler fuses away,
    which would inflate MFU)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0)) if cost else 0.0
        return flops if flops > 0 else None
    except Exception:
        return None


def run(cfg_kwargs, ds, mesh, steps, warmup=1, reps=2, want_flops=False):
    """Per-step wall-clock of the jitted train step.

    The ``steps`` training steps are folded into ONE jitted ``lax.scan`` over
    batches pre-staged in HBM, and synchronisation is a device→host fetch of
    the final loss: on the dev-tunnel backend ``block_until_ready`` is only a
    *dispatch* barrier (utils/timing.py — per-launch timing there reported a
    197-TFLOP chip at 88,000 TFLOPS), so per-step Python dispatch must be off
    the timed path entirely and the one RPC round trip is measured separately
    and subtracted. The metric is the training step (fwd/bwd + encode +
    gather + decode/aggregate + update), not the host link; on real pods the
    input pipeline overlaps the step via the native prefetcher
    (draco_tpu/data/prefetch.py).
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from draco_tpu.config import TrainConfig
    from draco_tpu.runtime import WORKER_AXIS, put_global
    from draco_tpu.training.trainer import Trainer
    from draco_tpu.utils.timing import time_scanned_steps

    cfg = TrainConfig(**cfg_kwargs)
    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    state = tr.state
    host_x, host_y = [], []
    for step in range(1, steps + 1):
        x, y = tr._host_batch(step)
        host_x.append(np.asarray(x))
        host_y.append(np.asarray(y))
    xs = put_global(np.stack(host_x), NamedSharding(mesh, P(None, WORKER_AXIS)))
    ys = put_global(np.stack(host_y), NamedSharding(mesh, P(None, WORKER_AXIS)))
    ms = put_global(
        np.stack([np.asarray(tr._adv_schedule[s]) for s in range(1, steps + 1)]),
        NamedSharding(mesh, P()),
    )
    step_fn = tr.setup.train_step

    if jax.devices()[0].platform == "cpu":
        # CPU mesh (smoke runs): block_until_ready IS a real execution
        # barrier locally, and XLA:CPU executes conv thunks inside
        # while-loop bodies single-threaded — a scanned ResNet step runs
        # ~40× slower than the same step dispatched eagerly (measured:
        # 3-step scans timing out at 20 min vs 10 s/step eager). Python
        # per-step loop is both honest and usable here.
        x0 = [xs[i] for i in range(steps)]
        y0 = [ys[i] for i in range(steps)]
        m0 = [ms[i] for i in range(steps)]
        compiled = step_fn.lower(state, x0[0], y0[0], m0[0]).compile()
        flops = _compiled_flops(compiled) if want_flops else None
        st, metrics = compiled(state, x0[0], y0[0], m0[0])
        jax.block_until_ready(st.params)  # compile + settle
        t0 = time.perf_counter()
        for i in range(steps):
            st, metrics = compiled(st, x0[i], y0[i], m0[i])
        jax.block_until_ready(st.params)
        dt = (time.perf_counter() - t0) / steps
        loss = float(metrics["loss"])
        tr.close()
        return dt, loss, flops

    def loop(state, xs, ys, ms):
        def body(st, batch):
            x, y, mask = batch
            st, metrics = step_fn(st, x, y, mask)
            return st, metrics["loss"]
        return jax.lax.scan(body, state, (xs, ys, ms))

    compiled = jax.jit(loop).lower(state, xs, ys, ms).compile()
    # XLA cost analysis counts a scan body ONCE regardless of trip count
    # (verified on this jax: scan(L=5) and scan(L=10) report identical
    # flops), so the loop's flops figure already IS the per-step figure.
    flops = _compiled_flops(compiled) if want_flops else None

    dt, losses = time_scanned_steps(
        compiled, state, (xs, ys, ms), steps=steps, warmup=warmup, reps=reps
    )
    loss = float(np.asarray(jax.device_get(losses))[-1])
    tr.close()
    return dt, loss, flops


def measure(args, metric_name):
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh

    import jax

    ds = load_dataset("Cifar10", data_dir="./data")
    mesh = make_mesh(args.num_workers)
    dev = jax.devices()[0]
    platform = dev.platform
    device_kind = getattr(dev, "device_kind", platform)

    common = dict(
        network=args.network,
        dataset="Cifar10",
        batch_size=args.batch_size,
        lr=0.01,
        momentum=0.9,
        num_workers=args.num_workers,
        worker_fail=1,
        err_mode="rev_grad",
        max_steps=args.steps + 1,
        eval_freq=0,
        train_dir="",
        log_every=10**9,
    )

    # the contender: cyclic code, r=2s+1 redundant compute like the reference
    t_cyclic, loss_c, flops_c = run(
        dict(common, approach="cyclic", redundancy="simulate"),
        ds, mesh, args.steps, args.warmup, args.reps, want_flops=True,
    )
    # the baseline robust aggregator Draco positions against
    t_geomed, loss_g, _ = run(
        dict(common, approach="baseline", mode="geometric_median"),
        ds, mesh, args.steps, args.warmup, args.reps,
    )
    # TPU-native fast path: identical decode semantics, each batch gradient
    # computed once (valid because SPMD adversaries are simulated, not
    # mutually-untrusting processes — config.py `redundancy`); reported
    # alongside the reference-parity number, never in its place
    try:
        t_shared, _, _ = run(
            dict(common, approach="cyclic", redundancy="shared"),
            ds, mesh, args.steps, args.warmup, args.reps,
        )
    except Exception as e:
        print(f"bench: shared-redundancy leg failed, reporting null: "
              f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
        t_shared = None

    peak = _peak_flops(device_kind)
    mfu = (
        round(flops_c / t_cyclic / peak, 4)
        if (flops_c and peak and t_cyclic > 0)
        else None
    )

    return {
        "metric": metric_name,
        "value": round(t_cyclic * 1000.0, 3),
        "unit": "ms/step",
        "vs_baseline": round(t_geomed / t_cyclic, 4),
        "extra": {
            "geomedian_step_ms": round(t_geomed * 1000.0, 3),
            "shared_redundancy_step_ms": (
                round(t_shared * 1000.0, 3) if t_shared else None
            ),
            "shared_vs_geomedian": (
                round(t_geomed / t_shared, 4) if t_shared else None
            ),
            "geomedian_iters": 80,
            "num_workers": args.num_workers,
            "batch_size_per_worker": args.batch_size,
            "dataset": ds.name,
            "loss_cyclic": round(loss_c, 4),
            "loss_geomedian": round(loss_g, 4),
            "platform": platform,
            "device_kind": device_kind,
            "flops_per_step": flops_c,
            "peak_bf16_flops": peak,
            "mfu_vs_bf16_peak": mfu,
            "compute_dtype": "float32",
        },
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=1,
                   help="un-timed settle executions of the steps-scan")
    p.add_argument("--reps", type=int, default=2,
                   help="timed executions of the steps-scan")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--network", type=str, default="ResNet18")
    p.add_argument("--num-workers", type=int, default=8)
    p.add_argument("--cpu-mesh", type=int, default=0)
    p.add_argument("--init-retries", type=int, default=4,
                   help="accelerator backend init attempts (wedged-lease weather)")
    p.add_argument("--retry-wait", type=float, default=120.0,
                   help="seconds between init attempts")
    p.add_argument("--no-cpu-fallback", action="store_true",
                   help="emit only the error record if the accelerator is down")
    args = p.parse_args()

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    metric_name = (
        f"{args.network.lower()}_cifar10_cyclic_s1_revgrad_step_wallclock"
    )

    if not args.cpu_mesh:
        devs, err = _try_backend(args.init_retries, args.retry_wait)
        if devs is None:
            # structured failure instead of a traceback; optionally still
            # measure on a CPU mesh, clearly labelled — a relative
            # cyclic-vs-geomedian ratio survives, wall-clock does not.
            record = {
                "metric": metric_name,
                "value": None,
                "unit": "ms/step",
                "vs_baseline": None,
                "error": "tpu_unavailable",
                "detail": (err or "")[-500:],
            }
            if not args.no_cpu_fallback:
                try:
                    import jax

                    jax.config.update("jax_platforms", "cpu")
                    fb = measure(args, metric_name)
                    fb["error"] = "tpu_unavailable_cpu_fallback"
                    fb["detail"] = (err or "")[-500:]
                    record = fb
                except Exception as e:  # keep the structured record at all costs
                    record["fallback_error"] = f"{type(e).__name__}: {e}"[:300]
            print(json.dumps(record))
            return record
    record = measure(args, metric_name)
    print(json.dumps(record))
    return record


if __name__ == "__main__":
    sys.exit(0 if main() else 1)
