"""Hierarchical CodedReduce tree aggregation (ISSUE 17): the plan/fold
algebra, the ledger's per-level byte sums, config validation, tree-vs-flat
detection + forensics equality under a live adversary AND a straggler drop,
K∈{1,4} × g∈{2,4} production-loop equivalence at compile_guard="raise"
with 0 steady retraces, the LM sp-route parity, the autopilot
fanout_down/fanout_up dials, and the flipped-row controls proving the
perf_watch tree gates live.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu.config import TrainConfig
from draco_tpu.coding import topology as topo
from draco_tpu.obs import numerics as nx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------------
# plan + fold algebra (jax-free units)
# --------------------------------------------------------------------------

@pytest.mark.core
def test_tree_plan_algebra():
    p = topo.tree_plan(8, 4)
    assert (p.num_groups, p.levels, p.level_fanouts) == (2, 2, (2,))
    assert p.group_slices == ((0, 4), (4, 8))
    assert p.level_widths == (2, 1)
    p = topo.tree_plan(32, 4)
    assert (p.num_groups, p.levels) == (8, 3)
    assert p.level_fanouts == (4, 2)
    assert p.level_widths == (8, 2, 1)
    # explicit depth: 8 groups over 3 combine levels of fan-in 2
    p = topo.tree_plan(32, 4, levels=4)
    assert p.level_fanouts == (4, 2, 1)
    # a depth the fan-in cannot realize is an error, not a silent clamp
    with pytest.raises(ValueError, match="cannot fold"):
        topo.tree_plan(32, 2, levels=2)
    # degenerate shapes refused loudly
    with pytest.raises(ValueError, match="num_workers % tree_fanout"):
        topo.tree_plan(10, 4)
    with pytest.raises(ValueError, match="at least 2 leaf groups"):
        topo.tree_plan(8, 8)
    with pytest.raises(ValueError, match=">= 2"):
        topo.tree_plan(8, 1)


@pytest.mark.core
def test_group_worker_fail_caps():
    """Per-group budget: the flat s capped by the small code's existence
    bound g > 4*s_g."""
    assert topo.group_worker_fail(4, 1) == 0
    assert topo.group_worker_fail(8, 1) == 1
    assert topo.group_worker_fail(8, 3) == 1
    assert topo.group_worker_fail(16, 3) == 3
    assert topo.group_worker_fail(4, 0) == 0


@pytest.mark.core
def test_tree_ledger_block_sums():
    """The leaf level's ingest bytes are EXACTLY the flat per-step bytes
    (the same n codeword rows, partitioned — no padding at the seams);
    combine levels price the decoded f32 partial traffic."""
    d = 10_000
    for n, g, dtype in ((8, 4, "f32"), (16, 4, "bf16"), (32, 8, "int8")):
        kw = {} if dtype == "f32" else {"wire_dtype": dtype}
        cfg = TrainConfig(approach="cyclic", num_workers=n, worker_fail=1,
                          adversary_count=0, redundancy="shared",
                          topology="tree", tree_fanout=g, **kw)
        led = nx.wire_ledger(cfg, d)
        tb = led["tree"]
        lb = tb["level_bytes_per_step"]
        assert len(lb) == tb["levels"]
        assert lb[0] == led["physical_bytes_per_step"]
        assert tb["ingest_bytes_per_group"] * tb["num_groups"] == lb[0]
        widths = tb["level_widths"]
        for l in range(1, tb["levels"]):
            assert lb[l] == widths[l - 1] * topo.PARTIAL_BYTES * d
        # per-NODE ingest is constant in n: fan-in * partial bytes
        assert tb["node_ingest_bytes"][1:] == [
            f * topo.PARTIAL_BYTES * d for f in tb["level_fanouts"]]


@pytest.mark.core
def test_config_rejects_bad_tree():
    base = dict(approach="cyclic", num_workers=8, worker_fail=1,
                adversary_count=0, redundancy="shared", topology="tree")
    TrainConfig(**base, tree_fanout=4).validate()
    with pytest.raises(ValueError, match="tree_fanout"):
        TrainConfig(**{**base, "num_workers": 10}, tree_fanout=4).validate()
    with pytest.raises(ValueError, match="redundancy='shared'"):
        TrainConfig(**{**base, "redundancy": "simulate"},
                    tree_fanout=4).validate()
    with pytest.raises(ValueError, match="shadow"):
        TrainConfig(**base, tree_fanout=4, shadow_wire="f32").validate()
    # declared adversary load above the worst-case per-group budget
    with pytest.raises(ValueError, match="per-group"):
        TrainConfig(**{**base, "adversary_count": 1},
                    err_mode="rev_grad", tree_fanout=4).validate()
    # g=8 has s_g=1: one adversary fits
    TrainConfig(**{**base, "num_workers": 16, "adversary_count": 1},
                err_mode="rev_grad", tree_fanout=8).validate()
    with pytest.raises(ValueError, match="maj_vote|cyclic/approx"):
        TrainConfig(approach="maj_vote", group_size=4, worker_fail=1,
                    num_workers=8, topology="tree",
                    tree_fanout=4).validate()


# --------------------------------------------------------------------------
# decode units: fold equality vs flat, live adversary + straggler drop
# --------------------------------------------------------------------------

def _tree_fixture(n=16, g=8, d=4096, seed=3):
    cfg = TrainConfig(approach="cyclic", num_workers=n, worker_fail=1,
                      adversary_count=0, redundancy="shared",
                      topology="tree", tree_fanout=g)
    tcode = topo.build_tree_code(cfg)
    rs = np.random.RandomState(seed)
    grads = jnp.asarray(rs.randn(n, d).astype(np.float32) * 0.1)
    rf = jnp.asarray(rs.choice([-1.0, 1.0], d).astype(np.float32))
    return tcode, grads, rf


@pytest.mark.core
def test_combine_partials_is_the_flat_mean():
    plan = topo.tree_plan(32, 4)
    rs = np.random.RandomState(0)
    parts = jnp.asarray(rs.randn(plan.num_groups, 64).astype(np.float32))
    out = topo.combine_partials(plan, parts)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(parts).mean(axis=0),
                               rtol=1e-6, atol=1e-7)


@pytest.mark.core
def test_tree_encode_is_blockwise_flat_encode():
    """Group j's encoded rows are the small code's flat encode of that
    group's batch rows BIT-FOR-BIT (same kernel, same operands)."""
    from draco_tpu.coding import cyclic

    tcode, grads, _ = _tree_fixture()
    e_re, e_im = topo.encode_tree(tcode, grads)
    for lo, hi in tcode.plan.group_slices:
        fr, fi = cyclic.encode_shared(tcode.group_code, grads[lo:hi])
        np.testing.assert_array_equal(np.asarray(e_re[lo:hi]),
                                      np.asarray(fr))
        np.testing.assert_array_equal(np.asarray(e_im[lo:hi]),
                                      np.asarray(fi))


@pytest.mark.core
def test_tree_detection_equals_flat_live_adversary():
    """The fold's load-bearing property: the SAME live rev_grad adversary
    decoded flat (n=16, s=1) and tree (g=8, s_g=1) flags the SAME row —
    detection P/R identical — and both aggregates stay at the true
    mean."""
    from draco_tpu.coding import cyclic

    tcode, grads, rf = _tree_fixture()
    n = tcode.plan.n
    flat = cyclic.build_cyclic_code(n, 1)
    adv_row = 11  # inside group 1 — the fold must map the accusation back
    fr, fi = cyclic.encode_shared(flat, grads)
    tr, ti = topo.encode_tree(tcode, grads)
    fr, fi = fr.at[adv_row].multiply(-50.0), fi.at[adv_row].multiply(-50.0)
    tr, ti = tr.at[adv_row].multiply(-50.0), ti.at[adv_row].multiply(-50.0)
    dec_f, hon_f, hl_f = cyclic.decode(flat, fr, fi, rf, with_health=True)
    dec_t, hon_t, hl_t = topo.decode_tree_cyclic(tcode, tr, ti, rf)
    truth = np.asarray(jnp.mean(grads, axis=0))
    np.testing.assert_allclose(np.asarray(dec_t), truth, rtol=2e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(dec_f), truth, rtol=2e-4,
                               atol=1e-5)
    fl_f = np.asarray(hl_f["flagged"], bool)
    fl_t = np.asarray(hl_t["flagged"], bool)
    np.testing.assert_array_equal(fl_t, fl_f)
    assert fl_t[adv_row] and fl_t.sum() == 1
    assert hon_t.shape == (n,)
    assert not bool(np.asarray(hon_t)[adv_row])


@pytest.mark.core
def test_tree_straggler_drop_never_accused():
    """A dropped worker decodes as an erasure in ITS group, the decode
    stays exact, and the victim is never accused — matching flat."""
    from draco_tpu.coding import cyclic

    tcode, grads, rf = _tree_fixture()
    n = tcode.plan.n
    flat = cyclic.build_cyclic_code(n, 1)
    drop = 9
    present = jnp.ones((n,), bool).at[drop].set(False)
    fr, fi = cyclic.encode_shared(flat, grads)
    tr, ti = topo.encode_tree(tcode, grads)
    dec_f, _, hl_f = cyclic.decode(flat, fr, fi, rf, present=present,
                                   with_health=True)
    dec_t, _, hl_t = topo.decode_tree_cyclic(tcode, tr, ti, rf,
                                             present=present)
    truth = np.asarray(jnp.mean(grads, axis=0))
    np.testing.assert_allclose(np.asarray(dec_t), truth, rtol=2e-4,
                               atol=1e-5)
    fl_f = np.asarray(hl_f["flagged"], bool)
    fl_t = np.asarray(hl_t["flagged"], bool)
    np.testing.assert_array_equal(fl_t, fl_f)
    assert not fl_t[drop]


@pytest.mark.core
def test_tree_approx_residual_within_bound():
    """The approx tree: root residual measured by the FLAT formula, the
    folded bound sqrt(sum bound_j^2) still certifies it under a drop."""
    from draco_tpu.coding import approx

    n, g, d = 8, 4, 2048
    cfg = TrainConfig(approach="approx", num_workers=n, worker_fail=0,
                      redundancy="shared", code_redundancy=2.0,
                      assignment_scheme="pairwise", topology="tree",
                      tree_fanout=g)
    tcode = topo.build_tree_code(cfg)
    assert tcode.family == "approx"
    rs = np.random.RandomState(5)
    grads = jnp.asarray(rs.randn(n, d).astype(np.float32) * 0.1)
    rows = topo.encode_tree(tcode, grads)
    present = jnp.ones((n,), bool).at[2].set(False)
    dec, v, hl = topo.decode_tree_approx(tcode, rows, present=present,
                                         batch_grads=grads)
    assert v.shape == (n,)
    assert float(hl["residual"]) <= float(hl["bound"]) + 1e-6
    assert 0.0 < float(hl["recovered_fraction"]) <= 1.0
    # full presence decodes the exact mean, residual at float noise
    dec0, _, hl0 = topo.decode_tree_approx(tcode, rows,
                                           batch_grads=grads)
    np.testing.assert_allclose(np.asarray(dec0),
                               np.asarray(jnp.mean(grads, axis=0)),
                               rtol=2e-4, atol=1e-5)
    assert float(hl0["residual"]) < 1e-3


# --------------------------------------------------------------------------
# production-loop equivalence: CNN Trainer, g ∈ {flat, 2, 4} × K ∈ {1, 4}
# --------------------------------------------------------------------------

DET_COLS = ("det_adv", "det_tp", "located_errors", "guard_trips",
            "skipped_steps", "present")


def _train_cfg(**kw):
    base = dict(network="FC", dataset="synthetic-mnist", batch_size=4,
                lr=0.01, momentum=0.9, num_workers=8, max_steps=6,
                eval_freq=0, train_dir="", log_every=1,
                compile_guard="raise", step_guard="on",
                incident_watch="on")
    base.update(kw)
    return TrainConfig(**base)


def _stream(train_dir):
    out = []
    with open(os.path.join(train_dir, "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if "loss" in rec and rec.get("split") != "eval":
                out.append(rec)
    return out


def _assert_detection_equal(stream_t, stream_f, n):
    from draco_tpu.obs.forensics import record_masks

    assert len(stream_t) == len(stream_f) > 0
    for rt, rf_ in zip(stream_t, stream_f):
        assert rt["step"] == rf_["step"]
        for col in DET_COLS:
            assert (col in rt) == (col in rf_), (rf_["step"], col)
            if col in rf_:
                assert rt[col] == rf_[col], (rf_["step"], col)
        mt, mf = record_masks(rt, n), record_masks(rf_, n)
        assert mt is not None and mf is not None
        for key in ("accused", "adv", "present"):
            assert mt[key] == mf[key], (rf_["step"], key)


def test_cnn_tree_loop_equivalence(tmp_path):
    """g ∈ {flat, 2, 4} × K ∈ {1, 4} on the CNN Trainer (n=8,
    worker_fail=0 so every fanout is feasible): K∈{1,4} stays bitwise
    within every topology, tree aggregates stay within float noise of
    flat, 0 steady retraces everywhere, and the status ledger carries the
    per-level tree block whose leaf level equals the flat bytes."""
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.trainer import Trainer

    ds = load_dataset("synthetic-mnist", synthetic_train=512,
                      synthetic_test=64)
    mesh = make_mesh(8)
    out = {}
    for g in (0, 2, 4):
        for k in (1, 4):
            d = str(tmp_path / f"g{g}_k{k}")
            kw = dict(approach="cyclic", worker_fail=0, adversary_count=0,
                      redundancy="shared", steps_per_call=k, train_dir=d)
            if g:
                kw.update(topology="tree", tree_fanout=g)
            tr = Trainer(_train_cfg(**kw), mesh=mesh, dataset=ds,
                         quiet=True)
            tr.run()
            snap = tr.compile_watch.snapshot()
            assert snap["steady_recompiles"] == 0, (g, k)
            out[g, k] = np.concatenate([
                np.ravel(x) for x in
                jax.tree.leaves(jax.device_get(tr.state.params))])
            tr.close()
    for g in (0, 2, 4):
        # eager vs scan-chunked bitwise within the topology
        np.testing.assert_array_equal(out[g, 1], out[g, 4])
    for g in (2, 4):
        # tree combine = mean of group means = the flat mean, to f32 noise
        np.testing.assert_allclose(out[g, 4], out[0, 4], rtol=5e-4,
                                   atol=1e-5)

    status = json.load(open(tmp_path / "g4_k4" / "status.json"))
    tb = status["wire"]["tree"]
    assert tb["fanout"] == 4 and tb["num_groups"] == 2
    assert tb["level_bytes_per_step"][0] == \
        status["wire"]["physical_bytes_per_step"]
    # flat twins carry NO tree block — the flat wire format is untouched
    status_flat = json.load(open(tmp_path / "g0_k4" / "status.json"))
    assert "tree" not in status_flat["wire"]


def test_cnn_tree_detection_parity_loop(tmp_path):
    """n=16, g=8 (s_g=1) under a LIVE rev_grad adversary, then under a
    straggler drop: the tree run's detection columns and packed forensics
    masks equal the flat run's per record, and the straggle victim is
    never accused."""
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.trainer import Trainer

    ds = load_dataset("synthetic-mnist", synthetic_train=512,
                      synthetic_test=64)
    mesh = make_mesh(16)
    cases = {
        "adv": dict(adversary_count=1, err_mode="rev_grad"),
        "strag": dict(adversary_count=0, straggle_mode="drop",
                      straggle_count=1),
    }
    for case, kw in cases.items():
        streams = {}
        for g in (0, 8):
            d = str(tmp_path / f"{case}_g{g}")
            ckw = dict(approach="cyclic", num_workers=16, worker_fail=1,
                       redundancy="shared", steps_per_call=4,
                       train_dir=d, **kw)
            if g:
                ckw.update(topology="tree", tree_fanout=g)
            tr = Trainer(_train_cfg(**ckw), mesh=mesh, dataset=ds,
                         quiet=True)
            last = tr.run()
            assert np.isfinite(last["loss"])
            assert tr.compile_watch.snapshot()["steady_recompiles"] == 0
            streams[g] = _stream(d)
            tr.close()
        _assert_detection_equal(streams[8], streams[0], 16)
        if case == "adv":
            assert any(r.get("det_tp", 0) > 0 for r in streams[8]), \
                "live adversary never detected — parity proves nothing"


# --------------------------------------------------------------------------
# LM route parity: the shared aggregate_flat_grads seam
# --------------------------------------------------------------------------

def test_lm_sp_tree_parity(tmp_path):
    """The tree fold through the LM single-shard route
    (parallel/common.aggregate_flat_grads — the seam all five LM routes
    share): g=4 vs flat at n=8, K=4 scan, strict compile sentinel —
    params within float noise, and the status wire ledger carries the
    tree block."""
    from draco_tpu.parallel import make_mesh_2d
    from draco_tpu.parallel.sp_step import train_sp

    out = {}
    for g in (0, 4):
        d = str(tmp_path / f"lm_g{g}")
        kw = dict(
            network="TransformerLM", dataset="synthetic-text",
            batch_size=2, max_steps=8, eval_freq=4, steps_per_call=4,
            seq_len=16, vocab=64, model_dim=64, model_heads=2,
            model_layers=1, approach="cyclic", worker_fail=0,
            adversary_count=0, redundancy="shared", train_dir=d)
        if g:
            kw.update(topology="tree", tree_fanout=g)
        cfg = _train_cfg(**kw)
        state, metrics = train_sp(cfg, make_mesh_2d(cfg.num_workers, 1),
                                  quiet=True)
        assert np.isfinite(metrics["loss"])
        out[g] = np.concatenate([
            np.ravel(x) for x in
            jax.tree.leaves(jax.device_get(state.params))])
    np.testing.assert_allclose(out[4], out[0], rtol=5e-4, atol=1e-5)
    status = json.load(open(tmp_path / "lm_g4" / "status.json"))
    tb = status["wire"]["tree"]
    assert tb["fanout"] == 4
    assert sum(tb["level_bytes_per_step"][:1]) == \
        status["wire"]["physical_bytes_per_step"]


# --------------------------------------------------------------------------
# autopilot fanout dials
# --------------------------------------------------------------------------

def test_autopilot_fanout_dials(tmp_path):
    """The straggler ladder's second rung (control/autopilot.py): a
    sustained straggle episode under topology='tree' fires fanout_down —
    a warm swap to the same family at half the fan-in (its own
    compile-sentinel label `_g2`) — and sustained straggle-quiet evidence
    fires fanout_up back to the configured fanout, both attributed, 0
    steady retraces, ending in the base regime."""
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.training.trainer import Trainer

    d = str(tmp_path / "ap")
    cfg = TrainConfig(
        network="FC", dataset="synthetic-mnist", batch_size=4, lr=0.02,
        momentum=0.9, num_workers=8, max_steps=20, eval_freq=4,
        train_dir=d, log_every=1, steps_per_call=4, approach="cyclic",
        worker_fail=0, adversary_count=0, redundancy="shared",
        topology="tree", tree_fanout=4, step_guard="on",
        incident_watch="on", compile_guard="raise", autopilot="on",
        # park the segment rung + family dials so the scenario isolates
        # the fanout rung; boundaries=1 fire on the first boundary with
        # the matching evidence
        autopilot_policy=("fanout_down_boundaries=1,fanout_up_boundaries=1,"
                          "segments_up_boundaries=99,"
                          "dial_down_boundaries=99,clean_boundaries=99"),
        incident_thresholds="straggle.streak=2",
        fault_spec="straggle@5-12:w5",
    )
    ds = load_dataset("synthetic-mnist", synthetic_train=512,
                      synthetic_test=64)
    tr = Trainer(cfg, dataset=ds, quiet=True)
    last = tr.run()
    snap = tr.compile_watch.snapshot()
    tr.close()
    assert np.isfinite(last["loss"]) and last["step"] == 20
    assert snap["steady_recompiles"] == 0

    rems = [json.loads(l) for l in
            open(os.path.join(d, "incidents.jsonl"))]
    rems = [e for e in rems if e.get("event") == "remediation"]
    assert [e["action"] for e in rems] == ["fanout_down", "fanout_up"]
    down, up = rems
    assert down["regime"]["tag"] == "cyclic_r1_g2"
    assert down["regime"]["tree_fanout"] == 2
    assert down["trigger"]["type"] in ("straggle", "starvation")
    assert down["evidence"]["tree_fanout_before"] == 4
    assert down["evidence"]["tree_fanout_after"] == 2
    assert down["evidence"]["executable"] == "compiled"
    assert up["regime"]["tag"] == "cyclic_r1_g4"
    assert up["evidence"]["tree_fanout_after"] == 4

    ledger = [json.loads(l) for l in
              open(os.path.join(d, "compiles.jsonl"))]
    labels = {}
    for r in ledger:
        if r["program"]:
            labels[r["program"]] = labels.get(r["program"], 0) + 1
    assert labels.get("train_many@cyclic_r1_g2[4]") == 1, labels
    assert not any(r["steady_recompile"] for r in ledger)

    st = json.load(open(os.path.join(d, "status.json")))
    assert st["state"] == "done"
    assert st["control"]["regime"]["tag"] == "cyclic_r1_g4"
    assert st["control"]["swaps"] == 2
    # the wire ledger was re-stamped back to the configured tree shape
    assert st["wire"]["tree"]["fanout"] == 4


# --------------------------------------------------------------------------
# perf_watch tree gates — the flipped-row controls
# --------------------------------------------------------------------------

def test_perf_watch_tree_gates_flipped_rows(tmp_path):
    """The ISSUE 17 fold (tools/perf_watch.fold_tree_study): the win /
    bytes_ok / detection-parity bools gate at tolerance 0; the per-level
    bytes and the crossover n are PINNED in BOTH directions."""
    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()
    path = root / "baselines_out" / "tree_study.json"
    out = root / "report.json"

    def artifact(win=True, bytes_ok=True, det_ok=True,
                 level_bytes=(4096, 1024), crossover=8):
        return {"all_ok": True, "crossover": {"critical_path_n": crossover},
                "rows": [
            {"kind": "flat", "n": 16, "decode_ms": 10.0},
            {"kind": "tree", "n": 16, "fanout": 8,
             "critical_path_ms": 6.0, "leaf_decode_ms": 5.0,
             "sequential_total_ms": 12.0, "win": win,
             "bytes_ok": bytes_ok,
             "detection": {"checked": True, "ok": det_ok,
                           "precision_tree": 1.0, "recall_tree": 1.0},
             "ledger": {"tree": {
                 "level_bytes_per_step": list(level_bytes)}},
             "ok": True},
        ]}

    path.write_text(json.dumps(artifact()))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    for key in ("tree.all_ok", "tree.crossover.critical_path_n",
                "tree.flat.n16.decode_ms", "tree.n16.g8.win",
                "tree.n16.g8.bytes_ok", "tree.n16.g8.detection_ok",
                "tree.n16.g8.level0_bytes_per_step",
                "tree.n16.g8.critical_path_ms"):
        assert key in snap["metrics"], key
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    def gated(art, *metrics):
        path.write_text(json.dumps(art))
        assert perf_watch.main(["--root", str(root), "--json",
                                str(out)]) == 1
        regs = {r["metric"] for r in
                json.loads(out.read_text())["regressions"]}
        for m in metrics:
            assert m in regs, (m, regs)

    # the tree losing its decode win gates (the acceptance bool)
    gated(artifact(win=False), "tree.n16.g8.win")
    # the byte-sum honesty pin breaking gates
    gated(artifact(bytes_ok=False), "tree.n16.g8.bytes_ok")
    # detection parity breaking gates
    gated(artifact(det_ok=False), "tree.n16.g8.detection_ok")
    # per-level bytes pinned in BOTH directions
    gated(artifact(level_bytes=(4097, 1024)),
          "tree.n16.g8.level0_bytes_per_step")
    gated(artifact(level_bytes=(4095, 1024)),
          "tree.n16.g8.level0_bytes_per_step")
    # the crossover moving is a topology change, never noise
    gated(artifact(crossover=16), "tree.crossover.critical_path_n")
