"""Adaptive coding autopilot (draco_tpu/control, ISSUE 14): policy units
(regime algebra, policy grammar, config validation), the live
quarantine → readmit → dial_down → dial_up lifecycle on BOTH production
loops driven through the shared ChunkedEngine, the warm-program-swap
contract (a family switch compiles exactly the expected new program ONCE
— its own compile-sentinel label — and returning to a previously-run
regime reuses the jitted executable, all under compile_guard="raise"),
remediation attribution (every decision names its triggering incident),
and the second-SIGTERM escalation path (resilience/supervisor.py).
"""

import json
import os

import numpy as np
import pytest

from draco_tpu.config import TrainConfig
from draco_tpu.control import autopilot as ap

# compressed hysteresis for the short test scenarios (production defaults
# are sized for long runs); straggle.streak=2 fires the detector after a
# 2-step absence streak. segments_up_boundaries parks the segment rung of
# the straggler ladder (ISSUE 16 — it would otherwise fire before the
# family dial this suite is about); the segment dial's own lifecycle is
# pinned in tests/test_segments.py.
POLICY = ("dial_down_boundaries=1,clean_boundaries=1,"
          "dial_up_boundaries=2,readmit_boundaries=2,"
          "segments_up_boundaries=99")
THRESHOLDS = "straggle.streak=2"


# --------------------------------------------------------------------------
# policy + regime units (no training)
# --------------------------------------------------------------------------

@pytest.mark.core
def test_policy_grammar_and_validation():
    assert ap.parse_policy("r_low=1.2, clean_boundaries=3") == {
        "r_low": 1.2, "clean_boundaries": 3.0}
    assert ap.parse_policy("") == {}
    with pytest.raises(ValueError, match="unknown autopilot policy"):
        ap.parse_policy("bogus=1")
    with pytest.raises(ValueError, match="not"):
        ap.parse_policy("r_low")
    # config.validate owns the dependency chain
    with pytest.raises(ValueError, match="incident_watch"):
        TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                    autopilot="on", steps_per_call=4,
                    train_dir="/tmp/x").validate()
    with pytest.raises(ValueError, match="train_dir"):
        TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                    autopilot="on", incident_watch="on", steps_per_call=4,
                    train_dir="").validate()
    with pytest.raises(ValueError, match="chunked regime"):
        TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                    autopilot="on", incident_watch="on", steps_per_call=1,
                    train_dir="/tmp/x").validate()
    with pytest.raises(ValueError, match="cyclic\\|approx"):
        TrainConfig(approach="baseline", autopilot="on",
                    incident_watch="on", steps_per_call=4,
                    train_dir="/tmp/x").validate()
    with pytest.raises(ValueError, match="unknown autopilot policy"):
        TrainConfig(autopilot_policy="nope=1").validate()


@pytest.mark.core
def test_regime_cfg_algebra():
    """regime_cfg: the approx regime drops the Byzantine knobs, sizes the
    straggler design point for the quarantined fleet, and strips the
    schedule/host fault kinds (applied at launch) while keeping in-graph
    kinds (compiled into every step body)."""
    base = TrainConfig(
        approach="cyclic", worker_fail=1, adversary_count=0,
        num_workers=8, redundancy="shared", steps_per_call=4,
        incident_watch="on", autopilot="on", train_dir="/tmp/x",
        fault_spec="adversary@5-20:w2,nan_grad@7:w3,straggle@26-40:w5",
    ).validate()
    assert ap.base_regime(base).tag == "cyclic_r3"
    target = ap.Regime("approx", 1.5, "off")
    cfg2 = ap.regime_cfg(base, target, quarantined=1)
    assert cfg2.approach == "approx" and cfg2.code_redundancy == 1.5
    assert cfg2.worker_fail == 0 and cfg2.adversary_count == 0
    assert cfg2.fault_spec == "nan_grad@7:w3"  # in-graph kind survives
    # budget covers the quarantined worker + configured load + headroom
    assert cfg2.straggler_alpha * 8 >= 2
    cfg2.validate()  # the swapped-to cfg is itself a legal config
    # dialing back up restores the base point exactly
    cfg3 = ap.regime_cfg(base, ap.base_regime(base))
    assert cfg3.approach == "cyclic" and cfg3.worker_fail == 1


@pytest.mark.core
def test_straggle_detector_streaks_and_quarantine_exclusion():
    """The straggle detector (obs/incidents.py, the dial-down evidence):
    fires on a sustained per-worker absence streak, attributed to the
    absent worker; rotating one-off drops never fire; a QUARANTINED
    worker's absence is policy, not telemetry."""
    from draco_tpu.obs import incidents as inc
    from tests.test_incidents import rec

    eng = inc.IncidentEngine(num_workers=8)
    # rotating single-step drops: no streak, no episode
    for s, absent in enumerate((1, 3, 5, 7, 2, 4, 6, 0), start=1):
        eng.observe(rec(s, present=0xFF & ~(1 << absent)))
    assert eng.open_episodes() == [] and eng.total_onsets == 0
    # worker 5 sustained: fires at the 4th consecutive absent record
    for s in range(9, 14):
        eng.observe(rec(s, present=0xFF & ~(1 << 5)))
    eps = eng.open_episodes()
    assert [e["type"] for e in eps] == ["straggle"]
    assert eps[0]["workers"] == [5] and eps[0]["onset_step"] == 12
    # quarantined worker: same absence pattern raises nothing
    eng2 = inc.IncidentEngine(num_workers=8)
    eng2.quarantined.add(5)
    for s in range(1, 10):
        eng2.observe(rec(s, present=0xFF & ~(1 << 5)))
    assert eng2.total_onsets == 0


@pytest.mark.core
def test_ledger_forgive_resets_trust_only():
    from draco_tpu.obs.forensics import AccusationLedger
    from tests.test_incidents import rec

    led = AccusationLedger(4)
    for s in range(1, 6):
        led.observe(rec(s, accused=0b0100, present=0b1111))
    assert led.trust[2] < 0.5 and led.accused[2] == 5
    led.forgive(2, 0.75)
    assert led.trust[2] == 0.75
    assert led.accused[2] == 5  # history stays


# --------------------------------------------------------------------------
# live lifecycle — CNN Trainer loop
# --------------------------------------------------------------------------

def _ledger_labels(train_dir):
    rows = [json.loads(l) for l in open(os.path.join(train_dir,
                                                     "compiles.jsonl"))]
    out = {}
    for r in rows:
        if r["program"]:
            out[r["program"]] = out.get(r["program"], 0) + 1
    return out, rows


def _events(train_dir):
    return [json.loads(l) for l in
            open(os.path.join(train_dir, "incidents.jsonl"))]


def test_autopilot_lifecycle_cnn(tmp_path):
    """The full remediation lifecycle on the coded-DP Trainer: trust
    collapse → quarantine (attributed, schedule-only, aggregate never
    corrupted), sustained straggle → dial_down to approx r=1.5 (NEW
    program compiled exactly once under its own sentinel label), clean
    window → readmit + dial_up (executable REUSED — zero new compiles),
    all under compile_guard='raise' with zero guard trips."""
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.obs.forensics import record_masks
    from draco_tpu.training.trainer import Trainer

    d = str(tmp_path / "cnn")
    cfg = TrainConfig(
        network="FC", dataset="synthetic-mnist", batch_size=4, lr=0.02,
        momentum=0.9, num_workers=8, max_steps=32, eval_freq=4,
        train_dir=d, log_every=1, steps_per_call=4, approach="cyclic",
        worker_fail=1, adversary_count=0, err_mode="rev_grad",
        redundancy="shared", step_guard="on", incident_watch="on",
        compile_guard="raise", autopilot="on", autopilot_policy=POLICY,
        incident_thresholds=THRESHOLDS,
        fault_spec="adversary@3-8:w2,straggle@13-20:w5",
    )
    ds = load_dataset("synthetic-mnist", synthetic_train=512,
                      synthetic_test=64)
    tr = Trainer(cfg, dataset=ds, quiet=True)
    last = tr.run()
    snap = tr.compile_watch.snapshot()
    tr.close()
    assert np.isfinite(last["loss"]) and last["step"] == 32

    # remediation lifecycle, in order, each attributed to an incident
    rems = [e for e in _events(d) if e["event"] == "remediation"]
    actions = [e["action"] for e in rems]
    assert actions == ["quarantine", "dial_down", "readmit", "dial_up"] \
        or actions == ["quarantine", "readmit", "dial_down", "dial_up"], \
        actions
    for e in rems:
        assert e["trigger"] and e["trigger"]["type"], e
        assert e["trigger"]["onset_step"] is not None, e
    byact = {e["action"]: e for e in rems}
    assert byact["quarantine"]["worker"] == 2
    assert byact["quarantine"]["trigger"]["type"] == "trust"
    assert byact["quarantine"]["trigger"]["workers"] == [2]
    assert byact["dial_down"]["regime"]["tag"] == "approx_r1.5"
    assert byact["dial_down"]["trigger"]["type"] in ("straggle",
                                                     "starvation")
    assert byact["dial_down"]["evidence"]["executable"] == "compiled"
    assert byact["dial_up"]["regime"]["tag"] == "cyclic_r3"
    assert byact["dial_up"]["evidence"]["executable"] == "reused"

    # warm-swap compile contract: the approx program built EXACTLY once
    # under its own label; returning to cyclic compiled nothing new; and
    # the raise-guard saw zero steady recompiles end to end
    labels, rows = _ledger_labels(d)
    assert labels.get("train_many@approx_r1.5[4]") == 1, labels
    assert labels.get("train_many[4]", 0) >= 1
    assert snap["steady_recompiles"] == 0
    assert not any(r["steady_recompile"] for r in rows)

    # the quarantined worker's rows really stopped arriving (one-chunk
    # assembly lag after the effective step), and the aggregate was never
    # corrupted: zero guard trips over the whole run
    q_eff = byact["quarantine"]["effective_step"] + cfg.steps_per_call
    readmit_step = byact["readmit"]["step"]
    recs = [json.loads(l) for l in open(os.path.join(d, "metrics.jsonl"))]
    recs = [r for r in recs if "guard_trips" in r]
    assert sum(r["guard_trips"] for r in recs) == 0.0
    for r in recs:
        masks = record_masks(r, 8)
        assert masks is not None
        if q_eff <= r["step"] <= readmit_step:
            assert not masks["present"][2], r["step"]

    # control block rides status.json (additive under schema 4) and the
    # run ends back in the base regime
    st = json.load(open(os.path.join(d, "status.json")))
    assert st["state"] == "done" and st["schema"] == 5
    c = st["control"]
    assert c["autopilot"] == "on"
    assert c["regime"]["tag"] == "cyclic_r3" == c["base_regime"]
    assert c["swaps"] == 2 and c["quarantined"] == []
    assert c["remediations"] == 4 and c["last"]["action"] == "dial_up"


# --------------------------------------------------------------------------
# live lifecycle — LM token loop (sp route)
# --------------------------------------------------------------------------

@pytest.mark.slow  # two route-setup builds + K=4 scan compiles (same
# budget class as the decode-kernel production-step suite)
def test_autopilot_dial_lm_sp(tmp_path):
    """The same dial on the LM token loop through the SAME ChunkedEngine:
    sustained straggle dials cyclic down to approx (new
    train_token_many@approx_r1.5 program, compiled once), clean evidence
    dials back up (executable reuse), 0 steady retraces."""
    from draco_tpu.parallel import make_mesh_2d
    from draco_tpu.parallel.sp_step import train_sp

    d = str(tmp_path / "lm")
    cfg = TrainConfig(
        network="TransformerLM", dataset="synthetic-text", batch_size=2,
        num_workers=8, max_steps=24, eval_freq=4, train_dir=d,
        log_every=1, steps_per_call=4, approach="cyclic", worker_fail=1,
        adversary_count=0, err_mode="rev_grad", redundancy="shared",
        seq_len=16, vocab=32, model_dim=32, model_heads=2, model_layers=1,
        step_guard="on", incident_watch="on", compile_guard="raise",
        autopilot="on", autopilot_policy=POLICY,
        incident_thresholds=THRESHOLDS,
        fault_spec="straggle@3-10:w5",
    )
    state, metrics = train_sp(cfg, make_mesh_2d(cfg.num_workers, 1),
                              quiet=True)
    assert np.isfinite(metrics["loss"])

    rems = [e for e in _events(d) if e["event"] == "remediation"]
    actions = [e["action"] for e in rems]
    assert actions == ["dial_down", "dial_up"], actions
    assert all(e["trigger"] and e["trigger"]["type"] for e in rems)
    assert rems[0]["regime"]["tag"] == "approx_r1.5"
    assert rems[0]["evidence"]["executable"] == "compiled"
    assert rems[1]["evidence"]["executable"] == "reused"

    labels, rows = _ledger_labels(d)
    assert labels.get("train_token_many@approx_r1.5[4]") == 1, labels
    assert labels.get("train_token_many[4]", 0) >= 1
    assert not any(r["steady_recompile"] for r in rows)

    st = json.load(open(os.path.join(d, "status.json")))
    assert st["state"] == "done"
    assert st["control"]["regime"]["tag"] == "cyclic_r3"
    assert st["control"]["swaps"] == 2


# --------------------------------------------------------------------------
# second-SIGTERM escalation (resilience/supervisor.py, ISSUE 14 satellite)
# --------------------------------------------------------------------------

@pytest.mark.core
def test_deliver_signal_escalates_on_second():
    from draco_tpu.resilience.supervisor import (GracefulStop,
                                                 ImmediateStopError)

    stop = GracefulStop()  # degraded holder (no __enter__): flag path
    stop.deliver_signal()
    assert stop.requested and not stop.escalated
    with pytest.raises(ImmediateStopError, match="second SIGTERM"):
        stop.deliver_signal()
    assert stop.escalated


def test_second_sigterm_forces_immediate_resumable_checkpoint(tmp_path):
    """The pinned SIGTERM→SIGTERM sequence: both events land in the first
    chunk's poll window, so the second escalates mid-run — the loop must
    write an IMMEDIATE resumable checkpoint + the terminal 'preempted'
    status (naming the escalation), and resuming from it reproduces the
    uninterrupted run bitwise."""
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.training.trainer import Trainer
    from draco_tpu.utils import checkpoint as ckpt

    ds = load_dataset("synthetic-mnist", synthetic_train=256,
                      synthetic_test=32)
    base = dict(
        network="FC", dataset="synthetic-mnist", batch_size=4, lr=0.02,
        num_workers=8, max_steps=8, eval_freq=0, log_every=1,
        steps_per_call=4, approach="cyclic", worker_fail=1,
        err_mode="rev_grad", redundancy="shared",
    )

    def pv(tr):
        import jax

        return np.concatenate([np.ravel(x) for x in jax.tree.leaves(
            jax.device_get(tr.state.params))])

    clean = Trainer(TrainConfig(**base), dataset=ds, quiet=True)
    clean.run()
    want = pv(clean)
    clean.close()

    d = str(tmp_path / "esc")
    tr = Trainer(TrainConfig(**base, train_dir=d,
                             fault_spec="sigterm@2,sigterm@3"),
                 dataset=ds, quiet=True)
    last = tr.run()
    tr.close()
    assert last == {}  # escalated: un-flushed tail records are dropped
    st = json.load(open(os.path.join(d, "status.json")))
    assert st["state"] == "preempted"
    assert "second SIGTERM" in st["cause"]
    assert st["resumable_step"] == 4
    assert 4 in ckpt.available_steps(d)

    tr2 = Trainer(TrainConfig(**base, train_dir=d, checkpoint_step=4),
                  dataset=ds, quiet=True)
    tr2.run()
    got = pv(tr2)
    tr2.close()
    np.testing.assert_array_equal(want, got)
