"""Resilience layer (draco_tpu/resilience, ISSUE 6): deterministic fault
injection, the in-graph step guard, prefetcher supervision, checkpoint
hardening, and the preemption round trip.

The load-bearing claims:

* the guard is bitwise-TRANSPARENT on clean runs (guards-enabled params ==
  unguarded params; the flipped equivalence suites additionally pin
  guard_trips == 0 under live adversaries + stragglers);
* each injected fault class ends in a classified outcome — masked, guarded
  skip, named error, or resumable preemption — never a hang or an unnamed
  traceback (the committed ``baselines_out/chaos_matrix.json`` pins the
  full fault × loop matrix; the cnn_k4 mini-matrix re-runs live here).
"""

import json
import os

import jax
import numpy as np
import pytest

from draco_tpu.config import TrainConfig
from draco_tpu.data.datasets import load_dataset
from draco_tpu.resilience import (
    FaultPlan,
    InjectedFaultError,
    SupervisedPrefetcher,
    plan_from_cfg,
    restore_with_walkback,
)
from draco_tpu.resilience.faults import apply_over_budget, apply_straggle
from draco_tpu.runtime import make_mesh
from draco_tpu.training.trainer import Trainer
from draco_tpu.utils import checkpoint as ckpt


@pytest.fixture(scope="module")
def ds():
    return load_dataset("synthetic-mnist", synthetic_train=256,
                        synthetic_test=64)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def make_cfg(**kw):
    base = dict(
        network="FC", dataset="synthetic-mnist", batch_size=4, lr=0.05,
        num_workers=8, approach="cyclic", worker_fail=1, redundancy="shared",
        err_mode="rev_grad", max_steps=4, eval_freq=0, train_dir="",
        log_every=1, compile_guard="raise", step_guard="on",
        compress_ckpt=True,
    )
    base.update(kw)
    return TrainConfig(**base)


def run_trainer(ds, mesh, tmp=None, **kw):
    tr = Trainer(make_cfg(**kw, train_dir=str(tmp) if tmp else ""),
                 mesh=mesh, dataset=ds, quiet=True)
    try:
        tr.run()
    finally:
        tr.close()
    return tr


def params_vec(tr):
    return np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(jax.device_get(tr.state.params))]
    )


def records(tmp):
    return [json.loads(l) for l in open(os.path.join(str(tmp),
                                                     "metrics.jsonl"))]


def status(tmp):
    return json.load(open(os.path.join(str(tmp), "status.json")))


# --------------------------------------------------------------------------
# fault plan: grammar + seeded determinism (the attacks.py discipline)
# --------------------------------------------------------------------------

@pytest.mark.core
def test_fault_plan_parse_grammar_and_determinism():
    p1 = FaultPlan.parse("nan_grad@5,inf_grad@6:w3,prefetch_hang@2:d7,"
                         "sigterm@9", 428, 8)
    p2 = FaultPlan.parse("nan_grad@5,inf_grad@6:w3,prefetch_hang@2:d7,"
                         "sigterm@9", 428, 8)
    assert p1 == p2  # same seed => bit-identical plan (frozen dataclasses)
    kinds = [e.kind for e in p1.events]
    assert kinds == ["nan_grad", "inf_grad", "prefetch_hang", "sigterm"]
    nan = p1.events[0]
    assert 0 <= nan.worker < 8  # seeded draw, in range
    assert FaultPlan.parse("nan_grad@5", 428, 8).events[0].worker \
        == nan.worker  # ...and stable across parses
    assert p1.events[1].worker == 3  # explicit :wN wins
    assert p1.events[2].duration_s == 7.0
    # a different seed moves the seeded worker draw eventually; the plan
    # stays valid either way
    assert FaultPlan.parse("nan_grad@5", 1, 8).events[0].worker is not None
    for bad in ("what@3", "nan_grad@0", "nan_grad@2:w9", "nan_grad"):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad, 428, 8)


@pytest.mark.core
def test_fault_plan_episode_grammar_windows_and_recurrence():
    """ISSUE 14 satellite: windowed/recurring specs — ``kind@a-b`` with
    optional ``:every<k>`` stride — parse, validate, expand to the right
    occurrence sets, and round-trip through ``FaultPlan.spec()``."""
    p = FaultPlan.parse(
        "straggle@20-60:w3:d4:every10,adversary@5-40:w2,nan_grad@8-10:w1",
        428, 8)
    churn, adv, nan = p.events
    assert list(churn.occurrences(1, 100)) == [20, 30, 40, 50, 60]
    assert list(churn.occurrences(35, 100)) == [40, 50, 60]
    assert adv.every == 1 and list(adv.occurrences(38, 39)) == [38, 39]
    assert nan.occurs_at(9) and not nan.occurs_at(11)
    # round-trip: spec() is canonical (workers pinned explicit) and
    # re-parsing reproduces the exact plan
    assert p.spec() == ("straggle@20-60:w3:d4:every10,adversary@5-40:w2,"
                        "nan_grad@8-10:w1")
    assert FaultPlan.parse(p.spec(), 428, 8) == p
    # seeded-draw workers become explicit on the way out, and stay stable
    q = FaultPlan.parse("straggle@5-9", 428, 8)
    assert f":w{q.events[0].worker}" in q.spec()
    assert FaultPlan.parse(q.spec(), 428, 8) == q
    # parse-time validation: inverted windows, strides without a window,
    # windows on one-checkpoint kinds, fractional step dwell
    for bad in ("nan_grad@9-5", "sigterm@5:every2", "ckpt_corrupt@5-9",
                "straggle@5-9:d1.5", "adversary@5:d0.5",
                "straggle@5-9:every0"):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad, 428, 8)


@pytest.mark.core
def test_episode_schedule_application():
    """Windowed events land on the host schedules exactly: adversary
    episodes mark their window (within budget), windowed straggle is
    absent exactly DURING the window, recurring churn drops d steps per
    occurrence, and the point form stays sustained-to-the-end."""
    import numpy as np

    from draco_tpu.resilience import faults as fm

    plan = FaultPlan.parse(
        "adversary@5-8:w2,straggle@10-13:w4,straggle@20-28:w5:d2:every4,"
        "straggle@30:w6", 428, 8)
    adv = fm.apply_adversary(np.zeros((35, 8), bool), plan)
    assert sorted(adv[:, 2].nonzero()[0]) == [5, 6, 7, 8]
    st = fm.apply_straggle(None, plan, 8, 34)
    assert sorted(st[:, 4].nonzero()[0]) == [10, 11, 12, 13]  # window only
    assert sorted(st[:, 5].nonzero()[0]) == [20, 21, 24, 25, 28, 29]
    assert sorted(st[:, 6].nonzero()[0]) == [30, 31, 32, 33, 34]  # to end
    # config-level: approx rejects adversary-marking kinds
    with pytest.raises(ValueError, match="not expressible"):
        make_cfg(approach="approx", worker_fail=0, redundancy="shared",
                 fault_spec="adversary@5:w2").validate()
    # config.validate() surfaces parse errors at config time
    with pytest.raises(ValueError):
        make_cfg(fault_spec="bogus@1").validate()


@pytest.mark.core
def test_over_budget_schedule_mutation():
    adv = np.zeros((10, 8), dtype=bool)
    adv[:, 0] = True  # s=1 live adversary every step
    plan = plan_from_cfg(make_cfg(fault_spec="over_budget@4"))
    out = apply_over_budget(adv, plan, worker_fail=1)
    assert out[4].sum() == 2  # pushed to s+1, exactly at the event step
    assert all(out[t].sum() == 1 for t in range(10) if t != 4)
    assert adv[4].sum() == 1  # input never mutated
    out2 = apply_over_budget(adv, plan, worker_fail=1)
    np.testing.assert_array_equal(out, out2)  # seeded => deterministic
    assert apply_over_budget(adv, None, 1) is adv  # no plan => passthrough


@pytest.mark.core
def test_straggle_schedule_mutation():
    """``straggle`` events (ISSUE 8): sustained per-worker drops overlay
    the seeded straggler schedule — to the run's end without :d, for a
    dwell of :d steps with it; an existing schedule is copied, None
    materializes a fresh table, and no-straggle plans pass through."""
    plan = plan_from_cfg(make_cfg(
        approach="approx", worker_fail=0, code_redundancy=1.5,
        fault_spec="straggle@3:w2,straggle@6:w5:d2"))
    # None in: a fresh (n_steps + 1, n) table materializes
    out = apply_straggle(None, plan, num_workers=8, n_steps=10)
    assert out.shape == (11, 8)
    assert out[3:, 2].all() and not out[:3, 2].any()  # sustained to the end
    assert out[6:8, 5].all() and not out[8:, 5].any()  # dwell 2, recovers
    assert not out[:6, 5].any()
    # existing schedule: overlay, input never mutated
    base = np.zeros((11, 8), dtype=bool)
    base[:, 0] = True
    out2 = apply_straggle(base, plan, 8, 10)
    assert out2[:, 0].all() and out2[3:, 2].all()
    assert not base[:, 2].any()
    # passthrough without straggle events / without a plan
    p2 = plan_from_cfg(make_cfg(fault_spec="nan_grad@2"))
    assert apply_straggle(base, p2, 8, 10) is base
    assert apply_straggle(base, None, 8, 10) is base
    # an explicit :w beyond the worker count is a parse error
    with pytest.raises(ValueError, match="out of range"):
        FaultPlan.parse("straggle@3:w8", 428, 8)


def test_straggle_fault_end_to_end_bitwise(ds, mesh, tmp_path):
    """A straggle@3:w3:d2 fault on the approx family: worker 3's rows stop
    arriving for steps 3-4 and return at 5, in BOTH regimes bitwise —
    every record's residual sits under its bound, the absent worker is
    never accused, and the guard never trips (within-bound decode error
    is the family's normal operating state)."""
    from draco_tpu.obs.forensics import record_masks

    vecs = {}
    for k in (1, 4):
        d = tmp_path / f"straggle_k{k}"
        tr = run_trainer(ds, mesh, tmp=d, approach="approx", worker_fail=0,
                         code_redundancy=1.5, max_steps=6, steps_per_call=k,
                         fault_spec="straggle@3:w3:d2")
        vecs[k] = params_vec(tr)
        recs = [r for r in records(d) if "loss" in r]
        assert len(recs) == 6
        for r in recs:
            masks = record_masks(r, 8)
            assert masks["present"][3] == (r["step"] not in (3, 4))
            assert masks["accused"] == (False,) * 8
            assert r["decode_residual"] <= r["decode_residual_bound"] + 1e-5
            assert r["guard_trips"] == 0.0 and r["skipped_steps"] == 0.0
        st = status(d)
        assert st["state"] == "done"
        assert st["forensics"]["accused_total"] == 0
        assert st["forensics"]["trust"] == [1.0] * 8
    np.testing.assert_array_equal(vecs[1], vecs[4])


# --------------------------------------------------------------------------
# in-graph step guard
# --------------------------------------------------------------------------

@pytest.mark.core
def test_guard_clean_run_bitwise_transparent(ds, mesh):
    """Guard on vs off on a clean run (live adversary inside budget): final
    params bitwise-identical, guard columns present and all-zero."""
    import tempfile

    d = tempfile.mkdtemp()
    on = run_trainer(ds, mesh, tmp=d, step_guard="on")
    off = run_trainer(ds, mesh, step_guard="off")
    np.testing.assert_array_equal(params_vec(on), params_vec(off))
    recs = [r for r in records(d) if "loss" in r]
    assert recs and all(r["guard_trips"] == 0.0
                        and r["skipped_steps"] == 0.0 for r in recs)
    assert status(d)["guard"] == {"trips": 0.0, "skipped_steps": 0.0}
    assert status(d)["state"] == "done"


@pytest.mark.core
def test_nan_fault_guard_skips_and_training_continues(ds, mesh, tmp_path):
    """The core chaos smoke: a non-adversarial worker emits a NaN gradient
    mid-run. Unguarded, the decode is poisoned for good; guarded, exactly
    that step is skipped (branchless passthrough) and training continues
    finite — in BOTH regimes, bitwise-identically."""
    vecs = {}
    for k in (1, 3):
        d = tmp_path / f"k{k}"
        tr = run_trainer(ds, mesh, tmp=d, steps_per_call=k,
                         fault_spec="nan_grad@2")
        vecs[k] = params_vec(tr)
        assert np.all(np.isfinite(vecs[k]))
        per_step = {r["step"]: (r["guard_trips"], r["skipped_steps"])
                    for r in records(d) if "loss" in r}
        assert per_step[2][0] >= 1 and per_step[2][1] == 1.0
        assert all(v == (0.0, 0.0) for s, v in per_step.items() if s != 2)
        assert status(d)["state"] == "done"
        assert status(d)["guard"]["skipped_steps"] == 1.0
    np.testing.assert_array_equal(vecs[1], vecs[3])
    unguarded = run_trainer(ds, mesh, step_guard="off",
                            fault_spec="nan_grad@2")
    assert not np.all(np.isfinite(params_vec(unguarded)))


def test_over_budget_fault_guarded(ds, mesh, tmp_path):
    """Adversary count pushed past the s budget: the decode cannot certify
    the step (loud residual / located > s) and the guard skips it."""
    tr = run_trainer(ds, mesh, tmp=tmp_path, fault_spec="over_budget@3")
    assert np.all(np.isfinite(params_vec(tr)))
    per_step = {r["step"]: r["skipped_steps"]
                for r in records(tmp_path) if "loss" in r}
    assert per_step[3] == 1.0
    assert sum(per_step.values()) == 1.0


# --------------------------------------------------------------------------
# prefetcher: bounded waits, named stall, supervised restart
# --------------------------------------------------------------------------

def test_prefetch_stall_is_named_not_a_hang():
    """A hung worker thread surfaces as PrefetchStallError after the bounded
    queue wait — carrying the stalled request and the last tracer span —
    instead of blocking the main loop forever."""
    import time

    from draco_tpu.data.prefetch import (PrefetchStallError,
                                         TokenChunkPrefetcher)

    def gen(step):
        if step >= 3:
            time.sleep(5)  # the hang
        return np.zeros((2, 2), np.int32)

    p = TokenChunkPrefetcher(gen, timeout_s=0.2)
    try:
        p.get((1, 2), (3, 2))  # healthy cold gather, submit (3,2) to worker
        t0 = time.perf_counter()
        with pytest.raises(PrefetchStallError) as ei:
            p.get((3, 2))
        assert time.perf_counter() - t0 < 3.0  # bounded, not the sleep
        assert ei.value.request == (3, 2)
        assert ei.value.timeout_s == 0.2
        # close() after an observed stall must NOT join the hung worker
        t0 = time.perf_counter()
        p.close()
        assert time.perf_counter() - t0 < 1.0
    finally:
        p.abandon()

    # the cold-start path is bounded too: a persistently hung source must
    # not convert the supervisor's retry into an unbounded MAIN-thread hang
    p2 = TokenChunkPrefetcher(lambda step: time.sleep(5), timeout_s=0.2)
    try:
        t0 = time.perf_counter()
        with pytest.raises(PrefetchStallError):
            p2.get((3, 2))
        assert time.perf_counter() - t0 < 3.0
    finally:
        p2.abandon()


def test_prefetch_worker_exception_propagates_by_name():
    from draco_tpu.data.prefetch import TokenChunkPrefetcher

    def gen(step):
        if step == 3:
            raise InjectedFaultError("boom at 3")
        return np.zeros((2, 2), np.int32)

    p = TokenChunkPrefetcher(gen, timeout_s=5.0)
    try:
        p.get((1, 2), (3, 2))
        with pytest.raises(InjectedFaultError):
            p.get((3, 2))
    finally:
        p.abandon()


def test_supervised_prefetcher_restarts_bounded():
    class Flaky:
        """Fails its first `fail` gets across all instances, then works."""

        built = 0
        remaining = 2

        def __init__(self):
            type(self).built += 1
            self.depth = 0

        def get(self, key):
            if type(self).remaining > 0:
                type(self).remaining -= 1
                raise InjectedFaultError("transient")
            return ("ok", key)

        def close(self):
            pass

    Flaky.built, Flaky.remaining = 0, 2
    sup = SupervisedPrefetcher(Flaky, restarts=3, backoff_s=0.001)
    assert sup.get("x") == ("ok", "x")  # two restarts masked the fault
    assert sup.restarts_used == 2 and Flaky.built == 3

    Flaky.built, Flaky.remaining = 0, 2
    sup0 = SupervisedPrefetcher(Flaky, restarts=1, backoff_s=0.001)
    with pytest.raises(InjectedFaultError):  # bounded: original error wins
        sup0.get("x")


# --------------------------------------------------------------------------
# checkpoint hardening: checksum sidecar, named corruption, walk-back, GC
# --------------------------------------------------------------------------

def _fake_state():
    return {"w": np.arange(64, dtype=np.float32).reshape(8, 8),
            "b": np.ones((8,), np.float32)}


def _abstract(state):
    return jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                        state)


@pytest.mark.core
def test_dcg_corruption_is_named_with_checksums(tmp_path):
    d = str(tmp_path)
    state = _fake_state()
    path = ckpt.save(d, 1, state, compress=True)
    assert os.path.isfile(path + ".sha256")  # sidecar written
    ckpt.verify(d, 1)  # clean bytes verify
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    with pytest.raises(ckpt.CheckpointCorruptError) as ei:
        ckpt.load(d, 1, _abstract(state))
    # named, with path + expected/actual checksum — never a struct.error
    assert ei.value.path == path
    assert ei.value.expected and ei.value.actual
    assert ei.value.expected != ei.value.actual


def test_dcg_truncation_is_named(tmp_path):
    d = str(tmp_path)
    state = _fake_state()
    path = ckpt.save(d, 1, state, compress=True)
    raw = open(path, "rb").read()
    # remove the sidecar to prove the structural walk alone catches the
    # truncation (old checkpoints predate sidecars)
    os.remove(path + ".sha256")
    open(path, "wb").write(raw[: len(raw) // 2])
    with pytest.raises(ckpt.CheckpointCorruptError, match="truncated"):
        ckpt.load(d, 1, _abstract(state))
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.verify(d, 1)


def test_torn_header_is_corrupt_and_walkback_survives(tmp_path):
    """A sidecar-less .dcg whose MAGIC bytes are torn classifies as
    CheckpointCorruptError (not a plain ValueError the walk-back would
    die on), and walk-back retries past it."""
    d = str(tmp_path)
    state = _fake_state()
    ckpt.save(d, 2, state, compress=True)
    path = ckpt.save(d, 4, state, compress=True)
    os.remove(path + ".sha256")  # pre-hardening checkpoint: no sidecar
    raw = bytearray(open(path, "rb").read())
    raw[0] ^= 0xFF  # torn magic
    open(path, "wb").write(bytes(raw))
    with pytest.raises(ckpt.CheckpointCorruptError, match="magic"):
        ckpt.load(d, 4, _abstract(state))
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.verify(d, 4)
    _, step, skipped = restore_with_walkback(d, -1, _abstract(state))
    assert step == 2 and skipped[0][0] == 4


def test_resume_minus_one_empty_dir_starts_fresh(ds, mesh, tmp_path):
    """checkpoint_step=-1 against an empty train_dir (first incarnation
    under a restart controller) starts fresh instead of crash-looping on
    FileNotFoundError — and still matches the plain run bitwise."""
    plain = run_trainer(ds, mesh)
    fresh = run_trainer(ds, mesh, tmp=tmp_path / "empty",
                        checkpoint_step=-1)
    np.testing.assert_array_equal(params_vec(plain), params_vec(fresh))
    # an explicit positive step that is missing still errors
    with pytest.raises(FileNotFoundError):
        run_trainer(ds, mesh, tmp=tmp_path / "e2", checkpoint_step=7)


def test_terminal_states_do_not_leak_stale_keys(tmp_path):
    from draco_tpu.obs.heartbeat import RunHeartbeat

    hb = RunHeartbeat(str(tmp_path))
    hb.beat(3, 10)
    hb.terminal("preempted", cause="graceful stop on SIGTERM",
                resumable_step=3)
    out = hb.terminal("done")
    assert out["state"] == "done"
    assert "cause" not in out and "resumable_step" not in out
    assert out["step"] == 3  # run context survives


def test_restore_walkback_skips_corrupt_newest(tmp_path):
    d = str(tmp_path)
    state = _fake_state()
    ckpt.save(d, 2, state, compress=True)
    newer = {k: v + 1 for k, v in state.items()}
    path = ckpt.save(d, 4, newer, compress=True)
    raw = bytearray(open(path, "rb").read())
    raw[-5] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    loaded, step, skipped = restore_with_walkback(d, -1, _abstract(state))
    assert step == 2 and len(skipped) == 1 and skipped[0][0] == 4
    np.testing.assert_array_equal(loaded["w"], state["w"])
    # nothing loadable at all => the corruption error propagates
    raw2 = bytearray(open(os.path.join(d, "model_step_2.dcg"), "rb").read())
    raw2[-5] ^= 0xFF
    open(os.path.join(d, "model_step_2.dcg"), "wb").write(bytes(raw2))
    with pytest.raises(ckpt.CheckpointCorruptError):
        restore_with_walkback(d, -1, _abstract(state))


def test_keep_checkpoints_gc(tmp_path):
    d = str(tmp_path)
    state = _fake_state()
    for step in (1, 2, 3):
        ckpt.save(d, step, state, compress=True)  # keep=0: grows freely
    assert ckpt.available_steps(d) == [1, 2, 3]
    ckpt.save(d, 4, state, compress=True, keep=2)
    assert ckpt.available_steps(d) == [3, 4]
    assert not os.path.exists(os.path.join(d, "model_step_1.dcg.sha256"))
    # GC never deletes the newest, even at keep=1
    ckpt.save(d, 5, state, compress=True, keep=1)
    assert ckpt.available_steps(d) == [5]


# --------------------------------------------------------------------------
# terminal heartbeat states + SIGTERM round trip
# --------------------------------------------------------------------------

def test_crash_writes_terminal_status(ds, mesh, tmp_path):
    """An unsupervised injected prefetch crash escapes as the named error
    AND stamps status.json state=crashed with a one-line cause."""
    with pytest.raises(InjectedFaultError):
        run_trainer(ds, mesh, tmp=tmp_path, fault_spec="prefetch_crash@2",
                    prefetch_restarts=0)
    st = status(tmp_path)
    assert st["state"] == "crashed"
    assert "InjectedFaultError" in st["cause"]


def test_prefetch_crash_supervision_masks(ds, mesh, tmp_path):
    """With supervision on (the default), the same injected crash is fully
    masked: restart + deterministic re-gather reproduce the clean run
    bitwise."""
    clean = run_trainer(ds, mesh)
    tr = run_trainer(ds, mesh, tmp=tmp_path, fault_spec="prefetch_crash@2",
                     steps_per_call=2)
    np.testing.assert_array_equal(params_vec(clean), params_vec(tr))
    assert status(tmp_path)["state"] == "done"


def test_sigterm_resume_round_trip(ds, mesh, tmp_path):
    """SIGTERM mid-run: the loop stops at the boundary, snaps a resumable
    checkpoint, writes state=preempted — and resuming from it reproduces
    the uninterrupted run bitwise (the elasticity mechanism)."""
    clean = run_trainer(ds, mesh, eval_freq=2)
    d = tmp_path / "pre"
    run_trainer(ds, mesh, tmp=d, eval_freq=2, fault_spec="sigterm@2")
    st = status(d)
    assert st["state"] == "preempted"
    assert st["resumable_step"] == 2
    assert "SIGTERM" in st["cause"]
    assert ckpt.exists(str(d), 2)
    resumed = run_trainer(ds, mesh, tmp=d, eval_freq=2,
                          checkpoint_step=st["resumable_step"])
    np.testing.assert_array_equal(params_vec(clean), params_vec(resumed))
    assert status(d)["state"] == "done"


# --------------------------------------------------------------------------
# the fault × loop matrix: live cnn_k4 mini-matrix + the committed artifact
# --------------------------------------------------------------------------

def test_chaos_mini_matrix_cnn_k4(tmp_path):
    """Every fault class through the chunked CNN trainer via the real
    harness (tools/chaos_run.py): each cell classifies as masked / guarded
    / recovered / preempted_resumed — no hangs, no unnamed tracebacks."""
    from tools import chaos_run

    out = tmp_path / "chaos.json"
    rc = chaos_run.main(["--loops", "cnn_k4", "--out", str(out),
                         "--workdir", str(tmp_path / "work")])
    data = json.load(open(out))
    assert rc == 0, data
    assert data["all_ok"]
    # straggle is the approx family's cell (a sustained drop on an exact
    # code just re-tests the over_budget locator failure), the adversary
    # episode runs on the dedicated random-attack loops (cnn_rand_*,
    # ISSUE 14), and the drift episode on the autopilot wire-dial loop
    # (ap_wire_*, ISSUE 15) — every other fault class runs here
    assert {r["fault"] for r in data["rows"]} \
        == set(chaos_run.FAULTS) - {"straggle"} \
        - set(chaos_run.RAND_FAULTS) - set(chaos_run.WIRE_FAULTS)
    outcomes = {r["fault"]: r["outcome"] for r in data["rows"]}
    assert outcomes["nan_grad"] == "guarded"
    assert outcomes["over_budget"] == "guarded"
    assert outcomes["prefetch_crash"] == "masked"
    assert outcomes["sigterm"] == "preempted_resumed"
    assert outcomes["ckpt_corrupt"] == "recovered_walkback"
    assert outcomes["ckpt_truncate"] == "recovered_walkback"


@pytest.mark.core
def test_committed_chaos_matrix_covers_every_fault_class():
    """The committed artifact (the full matrix: CNN + two LM routes, eager
    + chunked) shows every fault class handled — the perf_watch fold gates
    on any cell flipping."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "baselines_out", "chaos_matrix.json")
    data = json.load(open(path))
    assert data["all_ok"]
    from tools import chaos_run

    assert set(data["fault_classes"]) == set(chaos_run.FAULTS)
    assert all(v["ok"] for v in data["fault_classes"].values())
    loops = {r["loop"] for r in data["rows"]}
    # coded-DP trainer + >= 2 LM routes + the approx family (ISSUE 8),
    # eager and chunked regimes
    assert {"cnn_k1", "cnn_k4", "lm_k1", "lm_k4", "lm_tp_k4",
            "approx_k1", "approx_k4", "cnn_rand_k1", "cnn_rand_k4"} <= loops
    assert not any(r["outcome"] == "FAILED" for r in data["rows"])
    # the approx cells: straggle degrades boundedly (victim absent, never
    # accused, every residual within its bound), nan_grad stays guarded
    # AND attributed, sigterm still round-trips bitwise
    approx = {(r["loop"], r["fault"]): r for r in data["rows"]
              if r["loop"].startswith("approx")}
    for k in ("approx_k1", "approx_k4"):
        assert approx[(k, "straggle")]["outcome"] == "degraded_bounded"
        assert approx[(k, "straggle")]["never_accused"]
        assert approx[(k, "nan_grad")]["outcome"] == "guarded"
        assert approx[(k, "nan_grad")]["attributed"]
        assert approx[(k, "sigterm")]["outcome"] == "preempted_resumed"
    # the tree topology cells (ISSUE 17): sigterm round-trips on both tree
    # loops, and the subtree-straggle cell (an entire leaf group absent at
    # once) degrades boundedly with the straggle incident attributed to
    # exactly the victim group — none of them ever accused
    assert {"cnn_tree_k4", "approx_tree_k4"} <= loops
    tree = {(r["loop"], r["fault"]): r for r in data["rows"]
            if "_tree" in r["loop"]}
    assert tree[("cnn_tree_k4", "sigterm")]["outcome"] == \
        "preempted_resumed"
    assert tree[("approx_tree_k4", "sigterm")]["outcome"] == \
        "preempted_resumed"
    sub = tree[("approx_tree_k4", "subtree_straggle")]
    assert sub["outcome"] == "degraded_bounded"
    assert sub["never_accused"]
    assert sub["incident"]["raised"] == ["straggle"]
    # every committed cell carries an incident verdict with ok true
    # (obs/incidents.py, ISSUE 13): the expected incident type raised with
    # the right worker attribution, nothing spurious — and the attributed
    # fault classes really raised their attributed incident
    for r in data["rows"]:
        assert isinstance(r.get("incident"), dict), r
        assert r["incident"]["ok"], r
    for r in data["rows"]:
        if r["fault"] == "nan_grad":
            assert "nonfinite" in r["incident"]["raised"], r
        if r["fault"] == "over_budget":
            assert "guard" in r["incident"]["raised"], r
        if r["fault"] == "straggle":
            # the sustained drop raises the attributed straggle incident
            # (ISSUE 14 — the autopilot's dial-down evidence)
            assert r["incident"]["raised"] == ["straggle"], r
        if r["fault"] == "adversary":
            # the seeded random attack (ISSUE 14 satellite): detected,
            # attributed and excised — one within-budget step opens NO
            # incident (trust EW is the hysteresis)
            assert r["outcome"] == "attributed_excised", r
            assert r["attributed"] and r["detected"], r
            assert r["incident"]["raised"] == [], r
        if r["fault"] in ("sigterm", "ckpt_corrupt", "ckpt_truncate"):
            assert r["incident"]["raised"] == [], r
    # perf_watch folds the matrix: a masked->crashed flip gates nonzero
    from tools import perf_watch

    metrics = {}
    perf_watch.fold_chaos(root, metrics)
    assert metrics["chaos.all_ok"]["value"] == 1.0
    broken = {k: dict(v, value=0.0) if k.startswith("chaos.") else v
              for k, v in metrics.items()}
    report = perf_watch.compare(metrics, broken, {})
    assert not report["ok"]
    assert any(r["metric"].startswith("chaos.")
               for r in report["regressions"])
