"""The REAL narrow coded wire (ISSUE 15): bf16/int8 codewords end-to-end.

What this file pins, layer by layer:

  * λ=0 exact-path bitwise equality — the regularized-solver plumbing
    (coding/linalg, coding/cyclic) must leave the f32 wire's solves
    bit-for-bit untouched, and an explicit ``wire_dtype="f32"`` config
    must train bit-identically to the default.
  * The narrow buffers are REALLY narrow (bf16 / int8 element types, not
    dequantized f32 copies), roundtrip within the dtype's noise, and the
    int8 shared-draw stochastic rounding quantizes bitwise-identical rows
    bitwise-identically — maj_vote's soundness condition on the wire.
  * Narrow-mode training: bounded end-to-end error vs the f32 twin,
    detection P/R unchanged under a live adversary, zero guard trips —
    eager (K=1) vs chunked (K=4) bitwise-equal WITHIN a wire dtype, on
    the CNN loop and the LM routes including the real w×tp GSPMD mesh
    under compile_guard="raise".
  * The PR 10 blocker: at n=32 s=3 the UNREGULARIZED locator amplifies
    quantization noise past any usable threshold; the λ-regularized
    locator (signal-scale normalisation + syndrome-significance gate +
    spread-rank subset + noise-floor cutoff) restores the margin while
    still locating live adversaries exactly.
  * Narrow-ingest kernel parity: the Pallas in-tile dequant variants
    (ops/decode_kernels) match the widened-XLA path bitwise in interpret
    mode.
  * The autopilot wire dial: numerics_drift evidence emits a
    ``wire_widen`` remediation, sustained clean evidence a
    ``wire_narrow`` back toward the configured dtype.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu import rng as drng, runtime
from draco_tpu.coding import approx as approx_mod
from draco_tpu.coding import cyclic as cyclic_mod
from draco_tpu.coding import linalg as linalg_mod
from draco_tpu.config import TrainConfig
from draco_tpu.obs import numerics as nx
from draco_tpu.training.step import build_train_setup

NW = 8


# --------------------------------------------------------------------------
# λ plumbing: exact path bitwise, regularized path well-defined
# --------------------------------------------------------------------------


@pytest.mark.core
def test_lam_zero_paths_bitwise():
    rs = np.random.RandomState(0)
    a = jnp.asarray(rs.randn(6, 6).astype(np.float32))
    b = jnp.asarray(rs.randn(6).astype(np.float32))
    x0 = linalg_mod.truncated_lstsq(a, b, 1e-5)
    x1 = linalg_mod.truncated_lstsq(a, b, 1e-5, lam=0.0)
    assert np.array_equal(np.asarray(x0), np.asarray(x1))
    ab = jnp.asarray(rs.randn(4, 6, 6).astype(np.float32))
    bb = jnp.asarray(rs.randn(4, 6).astype(np.float32))
    j0 = linalg_mod.jacobi_lstsq(ab, bb, 1e-5)
    j1 = linalg_mod.jacobi_lstsq(ab, bb, 1e-5, lam=0.0)
    assert np.array_equal(np.asarray(j0), np.asarray(j1))
    ar, ai = (jnp.asarray(rs.randn(5, 5).astype(np.float32))
              for _ in range(2))
    br, bi = (jnp.asarray(rs.randn(5).astype(np.float32)) for _ in range(2))
    c0 = linalg_mod.complex_solve(ar, ai, br, bi, rcond=1e-5)
    c1 = linalg_mod.complex_solve(ar, ai, br, bi, rcond=1e-5, lam=0.0)
    assert all(np.array_equal(np.asarray(u), np.asarray(v))
               for u, v in zip(c0, c1))


@pytest.mark.core
def test_lam_drops_noise_floor_directions():
    """The λ path keeps directions above λ exact and zeroes those below
    (the truncated_lstsq noise-floor semantics)."""
    u = np.linalg.qr(np.random.RandomState(1).randn(4, 4))[0]
    a = jnp.asarray((u @ np.diag([1.0, 0.5, 1e-3, 1e-6]) @ u.T
                     ).astype(np.float32))
    b = jnp.asarray(np.ones(4, np.float32))
    # λ between the two small σ: the 1e-6 direction must vanish, the rest
    # solve exactly (compare against numpy pinv with the same cutoff)
    x = np.asarray(linalg_mod.truncated_lstsq(a, b, 1e-8, lam=1e-4))
    ainv = u @ np.diag([1.0, 2.0, 1e3, 0.0]) @ u.T
    assert np.allclose(x, ainv @ np.ones(4), rtol=1e-3)


# --------------------------------------------------------------------------
# narrow buffers
# --------------------------------------------------------------------------


@pytest.mark.core
def test_narrow_buffers_are_really_narrow():
    x = jnp.asarray(np.random.RandomState(0).randn(4, 1000)
                    .astype(np.float32))
    b16 = nx.narrow_wire_rows(x, "bf16", 256)
    assert b16["q"].dtype == jnp.bfloat16
    w = nx.widen_wire_rows(b16, "bf16", 256)
    assert w.dtype == jnp.float32
    assert float(jnp.max(jnp.abs(w - x) / (jnp.abs(x) + 1e-9))) < 2 ** -8
    i8 = nx.narrow_wire_rows(x, "int8", 256)
    assert i8["q"].dtype == jnp.int8
    assert i8["scale"].shape == (4, 4)  # ceil(1000/256) blocks per row
    w8 = nx.widen_wire_rows(i8, "int8", 256)
    # per-block absmax/127 scale: error bounded by half a level per block
    bmax = np.asarray(nx._block_absmax(jnp.abs(x), 256))
    assert np.all(np.abs(np.asarray(w8) - np.asarray(x))
                  <= bmax / 127.0 * 0.51 + 1e-9)


@pytest.mark.core
def test_int8_shared_draw_row_identical():
    """Stochastic rounding with the shared (d,) draw quantizes identical
    rows identically — the maj_vote soundness condition on the wire."""
    base = np.random.RandomState(0).randn(1000).astype(np.float32)
    g = jnp.asarray(np.stack([base, base, base * 2, base * 2]))
    key = jax.random.key(7)
    for mode in ("bf16", "int8"):
        buf = nx.narrow_wire_rows(g, mode, 256, key)
        w = np.asarray(nx.widen_wire_rows(buf, mode, 256))
        assert np.array_equal(w[0], w[1])
        assert np.array_equal(w[2], w[3])
        assert not np.array_equal(w[0], w[2])


@pytest.mark.core
def test_real_wire_matches_shadow_quantizer_bitwise():
    """The REAL wire's narrow-then-widen pipeline is BITWISE the shadow
    quantizer (obs/numerics.quantize_rows) under every mode — nearest and
    shared-draw stochastic, bf16 and int8, ragged block tail included.
    This is the 'calibration transfers' contract: the committed shadow
    study (PERF.md §13) priced exactly the arithmetic the real wire ships,
    so the two implementations may never drift apart."""
    x = np.random.RandomState(3).randn(5, 1000).astype(np.float32)
    x[0, 7] = np.inf
    x[2, 11] = np.nan  # non-finite maps to 0 in BOTH paths
    g = jnp.asarray(x)
    for mode in ("bf16", "int8"):
        for key in (None, jax.random.key(13)):
            shadow = np.asarray(nx.quantize_rows(g, mode, 192, key))
            real = np.asarray(nx.widen_wire_rows(
                nx.narrow_wire_rows(g, mode, 192, key), mode, 192))
            np.testing.assert_array_equal(shadow, real)


@pytest.mark.core
def test_wire_ledger_reports_materialized_dtype():
    cfg = TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                      wire_dtype="int8", redundancy="shared")
    led = nx.wire_ledger(cfg, 1000)
    assert led["wire_dtype"] == "int8"
    assert led["physical_bytes_per_worker"] == led["bytes_per_worker"]["int8"]
    assert led["physical_bytes_per_step"] \
        == led["bytes_per_worker"]["int8"] * 8
    # the narrow ratios the acceptance pins: bf16 exactly 0.5, int8
    # 0.25 + the per-block scale overhead
    per = led["bytes_per_worker"]
    assert per["bf16"] * 2 == per["f32"]
    assert per["int8"] / per["f32"] <= 0.26


@pytest.mark.core
def test_wire_dtype_validation():
    ok = TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                     wire_dtype="bf16", redundancy="shared")
    ok.validate()
    with pytest.raises(ValueError, match="coded approach"):
        TrainConfig(approach="baseline", wire_dtype="bf16").validate()
    with pytest.raises(ValueError, match="mutually exclusive"):
        TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                    wire_dtype="bf16", shadow_wire="bf16",
                    redundancy="shared").validate()
    # an unmeasured large-s shape routes to the approx family
    with pytest.raises(ValueError, match="approach=approx"):
        TrainConfig(approach="cyclic", worker_fail=3, num_workers=16,
                    wire_dtype="int8", redundancy="shared").validate()
    # ... which accepts the narrow wire (no locator to amplify noise)
    TrainConfig(approach="approx", worker_fail=0, num_workers=16,
                wire_dtype="int8", redundancy="shared",
                code_redundancy=1.5).validate()
    # the measured blocker shape is in the committed table
    TrainConfig(approach="cyclic", worker_fail=3, num_workers=32,
                wire_dtype="int8", redundancy="shared").validate()


# --------------------------------------------------------------------------
# the PR 10 blocker: n=32 s=3
# --------------------------------------------------------------------------


def _encode_quantized(code, dtype, adv_rows, seed=100, d=4096):
    rs = np.random.RandomState(seed)
    g = rs.randn(code.n, d).astype(np.float32) * 0.05
    enc_re, enc_im = cyclic_mod.encode_shared(code, jnp.asarray(g))
    adv = np.zeros(code.n, bool)
    if adv_rows:
        adv[rs.choice(code.n, adv_rows, replace=False)] = True
        m = jnp.asarray(adv)[:, None]
        enc_re = jnp.where(m, -100.0 * enc_re, enc_re)
        enc_im = jnp.where(m, -100.0 * enc_im, enc_im)
    buf_re = nx.narrow_wire_rows(enc_re, dtype, 256)
    buf_im = nx.narrow_wire_rows(enc_im, dtype, 256)
    return (nx.widen_wire_rows(buf_re, dtype, 256),
            nx.widen_wire_rows(buf_im, dtype, 256), adv,
            jnp.asarray(rs.randn(d).astype(np.float32)))


@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_regularized_locator_solves_n32_s3_blocker(dtype):
    """λ=0 reproduces the PR 10 finding (no-adversary honest deviations
    amplified past ANY usable threshold); the committed λ restores the
    margin under the committed threshold while still locating and
    flagging live adversaries exactly."""
    code = cyclic_mod.build_cyclic_code(32, 3)
    lam = nx.wire_locator_lambda(dtype)
    tol = nx.wire_rel_tol(32, 3, dtype)
    assert 0.0 < tol < 1.0

    # no adversary: the rank-deficient regime. The amplification is
    # subset-conditioning dependent, so the blocker is a worst-case over
    # trials (exactly how the study measures it)
    hmax0 = hmax1 = 0.0
    for seed in range(100, 108):
        enc_re, enc_im, _, f = _encode_quantized(code, dtype, 0,
                                                 seed=seed)
        _, _, h0 = cyclic_mod.decode(code, enc_re, enc_im, f,
                                     with_health=True, rel_tol=1e9,
                                     lam=0.0)
        _, _, h1 = cyclic_mod.decode(code, enc_re, enc_im, f,
                                     with_health=True, rel_tol=tol,
                                     lam=lam)
        hmax0 = max(hmax0, float(jnp.max(h0["dev_rel"])))
        hmax1 = max(hmax1, float(jnp.max(h1["dev_rel"])))
        # regularized: nothing flagged on any clean trial
        assert int(jnp.sum(h1["flagged"])) == 0
    # the blocker (unregularized): honest deviations past any usable
    # threshold; regularized: every honest row under the committed one
    assert hmax0 > 1.0 > tol > hmax1

    # s live adversaries: located exactly, flagged above the threshold
    enc_re, enc_im, adv, f = _encode_quantized(code, dtype, 3)
    _, honest, h2 = cyclic_mod.decode(code, enc_re, enc_im, f,
                                      with_health=True, rel_tol=tol,
                                      lam=lam)
    honest = np.asarray(honest)
    assert not np.any(honest & adv)  # no adversary in the honest subset
    flagged = np.asarray(h2["flagged"])
    assert np.all(flagged[adv])  # every adversary flagged


# --------------------------------------------------------------------------
# narrow-mode training: CNN loop, eager vs chunked, det P/R, guard
# --------------------------------------------------------------------------


def _mk_cfg(**kw):
    base = dict(network="FC", dataset="synthetic-mnist", batch_size=4,
                num_workers=NW, lr=0.05, momentum=0.9, max_steps=8,
                eval_freq=0, train_dir="", log_every=1,
                approach="cyclic", worker_fail=1, err_mode="rev_grad",
                redundancy="shared")
    base.update(kw)
    return TrainConfig(**base)


def _run_eager(cfg, mesh, steps=4):
    setup = build_train_setup(cfg, mesh)
    adv = drng.adversary_schedule(cfg.seed, steps + 1, NW,
                                  cfg.num_adversaries)
    st = setup.state
    rows = []
    for s in range(1, steps + 1):
        x = jnp.asarray(np.random.RandomState(s)
                        .randn(NW, cfg.batch_size, 28, 28, 1)
                        .astype(np.float32))
        y = jnp.zeros((NW, cfg.batch_size), jnp.int32)
        st, m = setup.train_step(st, x, y, jnp.asarray(np.asarray(adv[s])))
        rows.append({k: np.asarray(v) for k, v in m.items()})
    pv = np.concatenate([np.ravel(t) for t in
                         jax.tree.leaves(jax.device_get(st.params))])
    return pv, rows


def _run_chunked(cfg, mesh, steps=4):
    setup = build_train_setup(cfg, mesh)
    adv = drng.adversary_schedule(cfg.seed, steps + 1, NW,
                                  cfg.num_adversaries)
    xs = jnp.asarray(np.stack([
        np.random.RandomState(s).randn(NW, cfg.batch_size, 28, 28, 1)
        .astype(np.float32) for s in range(1, steps + 1)]))
    ys = jnp.zeros((steps, NW, cfg.batch_size), jnp.int32)
    masks = jnp.asarray(np.asarray(adv[1:steps + 1]))
    st, block = setup.train_many(setup.state, xs, ys, masks, None)
    pv = np.concatenate([np.ravel(t) for t in
                         jax.tree.leaves(jax.device_get(st.params))])
    return pv, np.asarray(block), setup.metric_names


def test_f32_wire_mode_bitwise():
    """wire_dtype="f32" is the identity: bit-for-bit the default program's
    result on both execution shapes."""
    mesh = runtime.make_mesh(NW)
    p0, _ = _run_eager(_mk_cfg(), mesh)
    p1, _ = _run_eager(_mk_cfg(wire_dtype="f32"), mesh)
    assert np.array_equal(p0, p1)
    c0, b0, _ = _run_chunked(_mk_cfg(steps_per_call=4), mesh)
    c1, b1, _ = _run_chunked(_mk_cfg(steps_per_call=4, wire_dtype="f32"),
                             mesh)
    assert np.array_equal(c0, c1) and np.array_equal(b0, b1)
    assert np.array_equal(p0, c0)  # eager == chunked, unchanged


@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_cnn_narrow_wire_bounded_err_det_preserved(dtype):
    """Narrow mode: eager == chunked bitwise WITHIN the dtype; bounded
    end-to-end error vs the f32 twin; detection P/R 1.0 under the live
    adversary; zero guard trips."""
    mesh = runtime.make_mesh(NW)
    kw = dict(wire_dtype=dtype, numerics_watch="on", step_guard="on")
    p_f32, _ = _run_eager(_mk_cfg(step_guard="on"), mesh)
    p_e, rows = _run_eager(_mk_cfg(**kw), mesh)
    p_c, block, names = _run_chunked(_mk_cfg(steps_per_call=4, **kw), mesh)
    assert np.array_equal(p_e, p_c)  # K∈{1,4} bitwise within the dtype
    err = np.linalg.norm(p_e - p_f32) / np.linalg.norm(p_f32)
    assert err < (2e-2 if dtype == "bf16" else 1e-1)
    assert err > 0.0  # the narrow wire is really there
    for r in rows:
        assert r["det_tp"] == r["det_adv"] == 1  # recall 1.0
        assert r["located_errors"] == 1  # precision 1.0
        assert r["guard_trips"] == 0
    # the chunked block agrees column-for-column with the eager rows
    for j, name in enumerate(names):
        eager_col = np.asarray([r[name] for r in rows], np.float32)
        assert np.array_equal(eager_col, block[:, j]), name


def test_majvote_narrow_wire_soundness():
    """maj_vote on an int8 stochastic wire: within-group agreement and
    detection identical to the f32 wire (the shared-draw row-identity
    carried through a real training step)."""
    mesh = runtime.make_mesh(NW)
    kw = dict(approach="maj_vote", group_size=4, worker_fail=1)

    def run(wire):
        cfg = _mk_cfg(wire_dtype=wire, shadow_round="stochastic",
                      step_guard="on", **kw)
        setup = build_train_setup(cfg, mesh)
        adv = drng.adversary_schedule(cfg.seed, 4, NW, cfg.num_adversaries)
        st = setup.state
        out = []
        gids = np.arange(NW) // 4
        for s in range(1, 4):
            xg = np.random.RandomState(s).randn(2, cfg.batch_size, 28, 28, 1
                                                ).astype(np.float32)
            x = jnp.asarray(xg[gids])  # group-replicated batches
            y = jnp.zeros((NW, cfg.batch_size), jnp.int32)
            st, m = setup.train_step(st, x, y,
                                     jnp.asarray(np.asarray(adv[s])))
            out.append({k: np.asarray(v) for k, v in m.items()})
        return out

    rows_f32 = run("f32")
    rows_i8 = run("int8")
    for a, b in zip(rows_f32, rows_i8):
        assert a["vote_agree"] == b["vote_agree"]
        assert b["det_tp"] == b["det_adv"] == 1
        assert b["guard_trips"] == 0


def test_approx_narrow_wire_within_bound_slack():
    """approx on a narrow wire: the measured residual carries the
    quantization error, the guard's wire slack absorbs it (zero trips),
    and the decode stays bounded."""
    mesh = runtime.make_mesh(NW)
    kw = dict(approach="approx", worker_fail=0, code_redundancy=1.5)
    p0, _ = _run_eager(_mk_cfg(step_guard="on", **kw), mesh)
    p8, rows = _run_eager(_mk_cfg(wire_dtype="int8", step_guard="on", **kw),
                          mesh)
    err = np.linalg.norm(p8 - p0) / np.linalg.norm(p0)
    assert 0.0 < err < 1e-1
    for r in rows:
        assert r["guard_trips"] == 0
        assert r["decode_residual"] > 0.0  # the quantization is visible


# --------------------------------------------------------------------------
# narrow-ingest kernels: interpret-mode parity with the widened XLA path
# --------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_narrow_kernel_parity(dtype):
    from draco_tpu.ops import decode_kernels as dk

    rs = np.random.RandomState(0)
    n, d = 8, 5000  # ragged vs TILE_D
    code = cyclic_mod.build_cyclic_code(n, 1)
    g = rs.randn(n, d).astype(np.float32)
    enc_re, enc_im = cyclic_mod.encode_shared(code, jnp.asarray(g))
    buf_re = nx.narrow_wire_rows(enc_re, dtype, 256)
    buf_im = nx.narrow_wire_rows(enc_im, dtype, 256)
    wre = nx.widen_wire_rows(buf_re, dtype, 256)
    wim = nx.widen_wire_rows(buf_im, dtype, 256)
    v_re = jnp.asarray(rs.randn(n).astype(np.float32))
    v_im = jnp.asarray(rs.randn(n).astype(np.float32))
    ref = np.asarray(jnp.matmul(v_re, wre) - jnp.matmul(v_im, wim))
    out = np.asarray(dk.cyclic_narrow_recombine(
        v_re, v_im, (dtype, buf_re, buf_im, 256), interpret=True))
    assert np.array_equal(out, ref)

    acode = approx_mod.build_approx_code(n, 1.5)
    rows = approx_mod.encode_shared(acode, jnp.asarray(g))
    pres = np.ones(n, bool)
    pres[3] = False
    rows = rows * jnp.asarray(pres)[:, None]
    buf = nx.narrow_wire_rows(rows, dtype, 256)
    wrows = nx.widen_wire_rows(buf, dtype, 256)
    dec_x, _, h_x = approx_mod.decode(
        acode, wrows, present=jnp.asarray(pres), with_health=True,
        batch_grads=jnp.asarray(g), impl="fused")
    dec_k, _, h_k = approx_mod.decode(
        acode, wrows, present=jnp.asarray(pres), with_health=True,
        batch_grads=jnp.asarray(g), impl="pallas_interpret",
        wire=(dtype, buf, 256))
    # the decode is a per-column reduction over n rows — bitwise under
    # any d-tiling; the residual's d-length sum accumulates in tile order
    # (128-lane partials) so it is bounded-equal, not bitwise
    assert np.array_equal(np.asarray(dec_k), np.asarray(dec_x))
    np.testing.assert_allclose(np.asarray(h_k["residual"]),
                               np.asarray(h_x["residual"]), rtol=1e-5)


def test_narrow_kernel_infeasible_block_falls_back():
    """A block size that does not tile TILE_D falls back to the widened
    path instead of mis-tiling the scale grid."""
    from draco_tpu.ops import decode_kernels as dk

    assert not dk.narrow_kernel_ok(("int8", {}, {}, 300))
    assert dk.narrow_kernel_ok(("int8", {}, {}, 256))
    assert dk.narrow_kernel_ok(("bf16", {}, {}, 300))
    assert not dk.narrow_kernel_ok(None)


# --------------------------------------------------------------------------
# the LM routes: shared tail + the real w×tp mesh
# --------------------------------------------------------------------------


def test_lm_tp_mesh_narrow_wire_clean():
    """The real w×tp GSPMD mesh on a bf16 wire: K=4 chunked run completes
    under compile_guard="raise" (0 steady retraces), finite, detection
    preserved. The f32-mode bitwise contract on this mesh is pinned by the
    existing K∈{1,4} suites — this cell pins the NARROW mode."""
    from draco_tpu.parallel.mesh import make_mesh_wtp
    from draco_tpu.parallel.tp_step import train_tp

    cfg = TrainConfig(
        network="TransformerLM", dataset="synthetic-text", batch_size=2,
        num_workers=NW, approach="cyclic", worker_fail=1,
        err_mode="rev_grad", redundancy="shared", seq_len=16, vocab=32,
        model_dim=32, model_heads=2, model_layers=1, max_steps=8,
        eval_freq=0, train_dir="", log_every=1, steps_per_call=4,
        tensor_shards=2, wire_dtype="bf16", step_guard="on",
        compile_guard="raise")
    state, metrics = train_tp(cfg, make_mesh_wtp(4, 2), quiet=True)
    pv = np.concatenate([np.ravel(t) for t in
                         jax.tree.leaves(jax.device_get(state.params))])
    assert np.all(np.isfinite(pv))
    assert np.isfinite(metrics["loss"])


# --------------------------------------------------------------------------
# the autopilot wire dial (unit: no training)
# --------------------------------------------------------------------------


class _StubIncidents:
    def __init__(self):
        self._open = []
        self.episodes = []
        self.ledger = None
        self.current_masks = None
        self.quarantined = set()
        self.remediations = []

    def open_episodes(self):
        return list(self._open)

    def remediation(self, rem):
        self.remediations.append(rem)


class _StubHeartbeat:
    def __init__(self):
        self.incidents = _StubIncidents()
        self.wire = None
        self.control = None

    def set_control(self, block):
        self.control = block

    def set_wire(self, ledger):
        self.wire = ledger


class _StubClient:
    BASE_LABEL = "train_many"
    can_swap = True

    def __init__(self):
        self.setup = None
        self.switched = []

    def build_setup(self, cfg):
        return ("setup", cfg.approach, cfg.wire_dtype)

    def switch_regime(self, setup, label):
        self.switched.append((setup, label))


class _StubEngine:
    def __init__(self, client):
        self.client = client


def test_autopilot_wire_widen_and_narrow():
    from draco_tpu.control.autopilot import Autopilot

    cfg = TrainConfig(
        network="FC", dataset="synthetic-mnist", approach="cyclic",
        worker_fail=1, num_workers=NW, redundancy="shared",
        steps_per_call=4, wire_dtype="int8", incident_watch="on",
        autopilot="on", train_dir="/tmp/x").validate()
    hb = _StubHeartbeat()
    pilot = Autopilot(cfg, hb, policy={"wire_narrow_boundaries": 2.0})
    client = _StubClient()
    engine = _StubEngine(client)
    assert pilot.regime.wire_dtype == "int8"

    # a numerics_drift episode opens → the next boundary widens one step
    hb.incidents._open = [{"type": "numerics_drift", "severity": "warn",
                           "onset_step": 5, "workers": []}]
    pilot.act(8, engine)
    assert pilot.regime.wire_dtype == "bf16"
    rem = pilot.remediations[-1]
    assert rem["action"] == "wire_widen"
    assert rem["trigger"]["type"] == "numerics_drift"
    assert rem["evidence"]["wire_dtype_before"] == "int8"
    assert rem["evidence"]["wire_dtype_after"] == "bf16"
    assert client.switched and "wirebf16" in client.switched[-1][1]
    # the re-stamped wire ledger reports the WIDENED materialized dtype
    assert hb.wire is None or hb.wire["wire_dtype"] == "bf16"

    # decode_residual drift widens again, f32-ward
    hb.incidents._open = [{"type": "decode_residual", "severity": "warn",
                           "onset_step": 9, "workers": []}]
    pilot.act(12, engine)
    assert pilot.regime.wire_dtype == "f32"
    assert pilot.remediations[-1]["action"] == "wire_widen"

    # sustained clean evidence narrows back toward the CONFIGURED dtype,
    # one step per decision
    hb.incidents._open = []
    pilot.act(16, engine)
    assert pilot.regime.wire_dtype == "f32"  # hysteresis: not yet
    pilot.act(20, engine)
    assert pilot.regime.wire_dtype == "bf16"
    assert pilot.remediations[-1]["action"] == "wire_narrow"
    pilot.act(24, engine)
    pilot.act(28, engine)
    assert pilot.regime.wire_dtype == "int8"  # back at base, never past
    pilot.act(32, engine)
    pilot.act(36, engine)
    assert pilot.regime.wire_dtype == "int8"
    # warm cache: returning to the int8 regime reused the cached setup
    tags = [lbl for _, lbl in client.switched]
    assert any("wirebf16" in t for t in tags)


def test_drift_grad_fault_is_finite_and_windowed():
    """The drift_grad in-graph fault: finite scaling inside the window,
    identity outside, no victim worker required."""
    from draco_tpu.resilience import faults

    cfg = _mk_cfg(fault_spec="drift_grad@3-5")
    g = jnp.ones((NW, 16), jnp.float32)
    out2 = np.asarray(faults.corrupt_grads(g, cfg, jnp.asarray(2)))
    out4 = np.asarray(faults.corrupt_grads(g, cfg, jnp.asarray(4)))
    assert np.array_equal(out2, np.ones((NW, 16), np.float32))
    assert np.allclose(out4, faults.DRIFT_GRAD_SCALE)
    assert np.all(np.isfinite(out4))


def test_regime_carries_wire_dtype():
    from draco_tpu.control import autopilot as ap

    cfg = TrainConfig(
        approach="cyclic", worker_fail=1, num_workers=NW,
        redundancy="shared", steps_per_call=4, wire_dtype="bf16",
        incident_watch="on", autopilot="on", train_dir="/tmp/x").validate()
    base = ap.base_regime(cfg)
    assert base.wire_dtype == "bf16" and "wirebf16" in base.tag
    cfg2 = ap.regime_cfg(cfg, dataclasses.replace(base, wire_dtype="f32"))
    assert cfg2.wire_dtype == "f32"
    # the family dial carries the current wire dtype along
    tgt = ap.Regime("approx", 1.5, "off", "bf16")
    cfg3 = ap.regime_cfg(cfg, tgt)
    assert cfg3.approach == "approx" and cfg3.wire_dtype == "bf16"
