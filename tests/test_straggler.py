"""Straggler mitigation: erasure decoding, masked aggregation, present-aware
vote, and the end-to-end drop path.

The reference has no working straggler handling — its PS blocks until every
gradient arrives (baseline_master.py:112-116) and the tag-77 kill switch is
unreferenced (resnet_split.py:625-737, SURVEY.md §5.3). Here known-missing
workers are erasures: the cyclic code recovers the exact sum from any n-2s
present rows (one redundancy unit per erasure vs two per unknown error).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu import aggregation
from draco_tpu.coding import cyclic, repetition
from draco_tpu.config import TrainConfig


@pytest.fixture
def rng():
    return np.random.RandomState(11)


# --------------------------------------------------------------------------
# cyclic erasure decode
# --------------------------------------------------------------------------

@pytest.mark.parametrize("n,s,missing", [
    (9, 2, (1,)), (9, 2, (0, 4)), (9, 2, (2, 5, 7)), (9, 2, (0, 3, 6, 8)),  # e <= 2s
    (7, 1, (6,)), (7, 1, (0, 3)),
])
def test_erasure_only_exact(n, s, missing, rng):
    code = cyclic.build_cyclic_code(n, s)
    d = 256
    batch_grads = rng.randn(n, d).astype(np.float32)
    enc_re, enc_im = cyclic.encode(code, jnp.asarray(batch_grads[code.batch_ids]))
    present = np.ones(n, dtype=bool)
    present[list(missing)] = False
    # missing rows arrive as zeros
    enc_re = jnp.asarray(np.asarray(enc_re) * present[:, None])
    enc_im = jnp.asarray(np.asarray(enc_im) * present[:, None])
    rf = rng.normal(loc=1.0, size=d).astype(np.float32)
    dec, used = cyclic.decode(code, enc_re, enc_im, jnp.asarray(rf),
                              present=jnp.asarray(present))
    want = batch_grads.sum(axis=0) / n
    np.testing.assert_allclose(np.asarray(dec), want, rtol=2e-3, atol=2e-3)
    used = np.asarray(used)
    assert not used[list(missing)].any()
    assert used.sum() == n - 2 * s


@pytest.mark.parametrize("n,s,adv,missing", [(9, 2, (3,), (7,)), (11, 2, (0,), (5,))])
def test_joint_adversary_and_erasure(n, s, adv, missing, rng):
    """t adversaries + e erasures with t + e <= s: locator budget covers both."""
    from draco_tpu.attacks import inject_cyclic

    code = cyclic.build_cyclic_code(n, s)
    d = 256
    batch_grads = rng.randn(n, d).astype(np.float32)
    enc_re, enc_im = cyclic.encode(code, jnp.asarray(batch_grads[code.batch_ids]))
    adv_mask = np.zeros(n, dtype=bool)
    adv_mask[list(adv)] = True
    enc_re, enc_im = inject_cyclic(enc_re, enc_im, jnp.asarray(adv_mask), "rev_grad")
    present = np.ones(n, dtype=bool)
    present[list(missing)] = False
    enc_re = jnp.asarray(np.asarray(enc_re) * present[:, None])
    enc_im = jnp.asarray(np.asarray(enc_im) * present[:, None])
    rf = rng.normal(loc=1.0, size=d).astype(np.float32)
    dec, used = cyclic.decode(code, enc_re, enc_im, jnp.asarray(rf),
                              present=jnp.asarray(present))
    want = batch_grads.sum(axis=0) / n
    np.testing.assert_allclose(np.asarray(dec), want, rtol=5e-3, atol=5e-3)
    used = np.asarray(used)
    assert not used[list(adv)].any()
    assert not used[list(missing)].any()


# --------------------------------------------------------------------------
# masked aggregation
# --------------------------------------------------------------------------

def test_masked_mean_matches_subset(rng):
    g = rng.randn(8, 33).astype(np.float32)
    present = np.array([1, 1, 0, 1, 1, 1, 0, 1], dtype=bool)
    out = aggregation.mean(jnp.asarray(g), present=jnp.asarray(present))
    np.testing.assert_allclose(np.asarray(out), g[present].mean(0), rtol=1e-5)


def test_masked_geomedian_matches_subset(rng):
    g = rng.randn(8, 17).astype(np.float32)
    present = np.array([1, 0, 1, 1, 1, 1, 1, 0], dtype=bool)
    out = aggregation.geometric_median(jnp.asarray(g), present=jnp.asarray(present))
    sub = aggregation.geometric_median(jnp.asarray(g[present]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(sub), atol=1e-4)


def test_masked_krum_never_picks_absent_or_adversary(rng):
    n, s = 8, 1
    g = rng.randn(n, 25).astype(np.float32)
    g[2] += 1000.0  # adversary
    present = np.ones(n, dtype=bool)
    present[5] = False
    g[5] = 7777.0  # garbage in an absent row must not matter
    out = aggregation.krum(jnp.asarray(g), s, present=jnp.asarray(present))
    picked = np.asarray(out)
    assert not np.allclose(picked, g[2])
    assert not np.allclose(picked, g[5])
    # picked row is one of the present honest rows
    assert any(np.allclose(picked, g[i]) for i in range(n) if present[i] and i != 2)


def test_masked_coord_median_under_colluding_attack(rng):
    """Stragglers AND colluders together: 2 absent rows + 2 strong-ipm
    colluders among 8 — coord-median over the present rows must stay with
    the honest cluster (the attack payload is a bitwise-shared outlier per
    coordinate once the fill rows are excluded)."""
    from draco_tpu import attacks

    g = (rng.randn(8, 33) * 0.01 + 1.0).astype(np.float32)
    adv = np.asarray(np.arange(8) < 2)
    present = np.array([1, 1, 1, 0, 1, 1, 0, 1], dtype=bool)
    attacked = attacks.inject_plain(jnp.asarray(g), jnp.asarray(adv), "ipm",
                                    magnitude=-800.0, n_mal=2)
    out = aggregation.coordinate_median(attacked,
                                        present=jnp.asarray(present))
    honest = g[present & ~adv]
    assert np.abs(np.asarray(out) - honest.mean(0)).max() < 0.05


def test_vote_with_absent_members(rng):
    code = repetition.build_repetition_code(6, 3)
    d = 19
    honest = rng.randn(2, d).astype(np.float32)
    rows = np.stack([honest[0]] * 3 + [honest[1]] * 3)
    rows[1] = -55.0  # adversary in group 0
    present = np.array([1, 1, 1, 1, 0, 1], dtype=bool)  # straggler in group 1
    out = repetition.majority_vote(code, jnp.asarray(rows),
                                   present=jnp.asarray(present))
    want = (honest[0] + honest[1]) / 2  # both groups still produce winners
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-6)


def test_vote_dead_group_renormalises(rng):
    code = repetition.build_repetition_code(6, 3)
    rows = np.stack([np.full(7, float(i // 3)) for i in range(6)]).astype(np.float32)
    present = np.array([0, 0, 0, 1, 1, 1], dtype=bool)  # group 0 fully absent
    out = repetition.majority_vote(code, jnp.asarray(rows),
                                   present=jnp.asarray(present))
    np.testing.assert_allclose(np.asarray(out), np.full(7, 1.0))


# --------------------------------------------------------------------------
# config budget validation
# --------------------------------------------------------------------------

def test_config_rejects_over_budget_cyclic():
    with pytest.raises(ValueError, match="straggler budget"):
        TrainConfig(approach="cyclic", num_workers=9, worker_fail=2,
                    straggle_mode="drop", straggle_count=5).validate()
    # e <= 2s erasure-only is fine when no adversaries are live
    TrainConfig(approach="cyclic", num_workers=9, worker_fail=2,
                adversary_count=0, straggle_mode="drop",
                straggle_count=4).validate()
    # joint regime t + e <= s
    TrainConfig(approach="cyclic", num_workers=9, worker_fail=2,
                adversary_count=1, straggle_mode="drop",
                straggle_count=1).validate()


def test_config_rejects_dead_group():
    with pytest.raises(ValueError, match="group_size"):
        TrainConfig(approach="maj_vote", num_workers=6, group_size=3,
                    straggle_mode="drop", straggle_count=3).validate()


# --------------------------------------------------------------------------
# end-to-end: training with stragglers
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_cyclic_trains_through_stragglers_and_attacks():
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.trainer import Trainer

    ds = load_dataset("synthetic-mnist", synthetic_train=512, synthetic_test=64)
    cfg = TrainConfig(
        network="LeNet", dataset="synthetic-mnist", batch_size=4,
        num_workers=9, approach="cyclic", worker_fail=2,
        adversary_count=1, err_mode="rev_grad",
        straggle_mode="drop", straggle_count=1,
        redundancy="shared", max_steps=25, eval_freq=0, train_dir="",
        log_every=1000,
    )
    tr = Trainer(cfg, mesh=make_mesh(9), dataset=ds, quiet=True)
    first = tr.run(max_steps=1)
    last = tr.run(max_steps=25)
    assert np.isfinite(last["loss"])
    assert last["loss"] < first["loss"]
    assert last["present"] == 8.0
    tr.close()


@pytest.mark.slow
def test_baseline_mean_with_stragglers():
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.trainer import Trainer

    ds = load_dataset("synthetic-mnist", synthetic_train=512, synthetic_test=64)
    cfg = TrainConfig(
        network="FC", dataset="synthetic-mnist", batch_size=4,
        num_workers=8, approach="baseline", mode="normal",
        straggle_mode="drop", straggle_count=2,
        max_steps=20, eval_freq=0, train_dir="", log_every=1000,
    )
    tr = Trainer(cfg, mesh=make_mesh(8), dataset=ds, quiet=True)
    first = tr.run(max_steps=1)
    last = tr.run(max_steps=20)
    assert last["loss"] < first["loss"]
    tr.close()


def test_config_rejects_maj_vote_joint_budget():
    # one straggler + one adversary can land in the same size-3 group:
    # 3 - 1 = 2 present members, no honest majority over 1 adversary
    with pytest.raises(ValueError, match="joint budget"):
        TrainConfig(approach="maj_vote", num_workers=9, group_size=3,
                    worker_fail=1, straggle_mode="drop",
                    straggle_count=1).validate()
    # group_size=5 leaves 4 present > 2*1 — within budget
    TrainConfig(approach="maj_vote", num_workers=10, group_size=5,
                worker_fail=1, straggle_mode="drop",
                straggle_count=1).validate()


def test_config_rejects_krum_with_too_many_stragglers():
    with pytest.raises(ValueError, match="krum"):
        TrainConfig(approach="baseline", mode="krum", num_workers=8,
                    worker_fail=2, straggle_mode="drop",
                    straggle_count=4).validate()
    TrainConfig(approach="baseline", mode="krum", num_workers=8,
                worker_fail=2, straggle_mode="drop",
                straggle_count=3).validate()
