"""Pipeline parallelism (pp_step): GPipe schedule correctness.

The oracle is the same scanned block stack applied sequentially on one
logical device (pp=1): the pipeline is pure scheduling, so losses AND
per-worker gradients must match to float tolerance, for any microbatch
count. Composition with coded DP mirrors the tp/sp tests. (No reference
counterpart — the reference's Split models are gradient streaming, not
pipeline stages, /root/reference/src/model_ops/resnet_split.py:210-234;
SURVEY.md §2.3 lists PP as absent.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu.config import TrainConfig
from draco_tpu.parallel import make_mesh_wpp
from draco_tpu.parallel.pp_step import build_pp_train_setup, train_pp
from draco_tpu.parallel.sp_step import synthetic_text


def _cfg(**kw):
    base = dict(
        network="TransformerLM", dataset="synthetic-text", batch_size=4,
        lr=0.05, momentum=0.9, num_workers=2, approach="baseline",
        mode="normal", worker_fail=0, err_mode="rev_grad",
        pipeline_shards=4, seq_len=16, vocab=32, model_dim=32, model_heads=2,
        model_layers=4, max_steps=3, eval_freq=0, train_dir="", log_every=1000,
    )
    base.update(kw)
    return TrainConfig(**base)


def _toks(cfg, step=1):
    return jnp.asarray(
        synthetic_text(cfg.seed, step, cfg.num_workers, cfg.batch_size,
                       cfg.seq_len, cfg.vocab)
    )


@pytest.mark.parametrize("microbatches", [0, 2, 4])
def test_pipelined_loss_matches_sequential(microbatches):
    """w=2 × pp=4 pipelined loss == w=2 × pp=1 sequential loss, any M."""
    cfg_pp = _cfg(pp_microbatches=microbatches)
    cfg_seq = _cfg(pipeline_shards=1, pp_microbatches=1)
    pp = build_pp_train_setup(cfg_pp, make_mesh_wpp(2, 4))
    seq = build_pp_train_setup(cfg_seq, make_mesh_wpp(2, 1))
    # identical init (same seed, same module structure)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(pp.state.params["embed"]["embedding"])),
        np.asarray(jax.device_get(seq.state.params["embed"]["embedding"])),
    )
    toks = _toks(cfg_pp)
    l_pp = np.asarray(jax.device_get(pp.per_worker_loss(pp.state.params, toks)))
    l_seq = np.asarray(jax.device_get(seq.per_worker_loss(seq.state.params, toks)))
    assert l_pp.shape == (2,)
    np.testing.assert_allclose(l_pp, l_seq, rtol=1e-5, atol=1e-6)


def test_pipelined_grads_match_sequential():
    """Per-worker flat gradients agree between pp=4 and pp=1 — backward
    through the ppermute pipeline is exact, including the embed/final_ln
    cotangent psum over pp."""
    cfg_pp = _cfg()
    cfg_seq = _cfg(pipeline_shards=1, pp_microbatches=1)
    pp = build_pp_train_setup(cfg_pp, make_mesh_wpp(2, 4))
    seq = build_pp_train_setup(cfg_seq, make_mesh_wpp(2, 1))
    toks = _toks(cfg_pp)
    g_pp, l_pp = pp.per_worker_grads(pp.state.params, toks)
    g_seq, l_seq = seq.per_worker_grads(seq.state.params, toks)
    g_pp = np.asarray(jax.device_get(g_pp))
    g_seq = np.asarray(jax.device_get(g_seq))
    assert g_pp.shape == (2, pp.dim)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(l_pp)), np.asarray(jax.device_get(l_seq)),
        rtol=1e-5, atol=1e-6,
    )
    scale = np.maximum(np.abs(g_seq).max(), 1e-8)
    np.testing.assert_allclose(g_pp / scale, g_seq / scale, atol=5e-5)


def test_pp_microbatch_invariance():
    """M=2 and M=4 schedules produce the same gradients (bubble ticks are
    inert)."""
    pp2 = build_pp_train_setup(_cfg(pp_microbatches=2), make_mesh_wpp(2, 4))
    pp4 = build_pp_train_setup(_cfg(pp_microbatches=4), make_mesh_wpp(2, 4))
    toks = _toks(_cfg())
    g2, _ = pp2.per_worker_grads(pp2.state.params, toks)
    g4, _ = pp4.per_worker_grads(pp4.state.params, toks)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(g2)), np.asarray(jax.device_get(g4)),
        rtol=1e-5, atol=1e-6,
    )


def test_pp_training_learns():
    """w=4 × pp=2 baseline training drives the loss down on the synthetic
    ramp stream."""
    cfg = _cfg(num_workers=4, pipeline_shards=2, model_layers=2, max_steps=30,
               batch_size=8)
    state, metrics = train_pp(cfg, make_mesh_wpp(4, 2), steps=30, quiet=True)
    setup = build_pp_train_setup(cfg, make_mesh_wpp(4, 2))
    toks = _toks(cfg, step=1)
    first = float(setup.eval_step(setup.state.params, toks))
    last = float(setup.eval_step(state.params, toks))
    assert last < first * 0.8, (first, last)


def test_pp_composes_with_robust_aggregation():
    """geo-median aggregation over w with one live adversary still learns on
    the (w=4, pp=2) mesh, and one plain step matches pp=1 to tolerance."""
    cfg = _cfg(num_workers=4, pipeline_shards=2, model_layers=2,
               worker_fail=1, mode="geometric_median")
    mesh = make_mesh_wpp(4, 2)
    setup = build_pp_train_setup(cfg, mesh)
    toks = _toks(cfg)
    adv = jnp.asarray(np.array([False, True, False, False]))
    state, metrics = setup.train_step(setup.state, toks, adv)
    assert np.isfinite(float(metrics["loss"]))

    cfg1 = _cfg(num_workers=4, pipeline_shards=1, pp_microbatches=1,
                model_layers=2, worker_fail=1, mode="geometric_median")
    setup1 = build_pp_train_setup(cfg1, make_mesh_wpp(4, 1))
    state1, _ = setup1.train_step(setup1.state, toks, adv)
    a = np.asarray(jax.device_get(state.params["embed"]["embedding"]))
    b = np.asarray(jax.device_get(state1.params["embed"]["embedding"]))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_pp_checkpoint_resume_exact(tmp_path):
    """The shared token loop checkpoints pp's stage-sharded state; resuming
    from step 2 reproduces the uninterrupted 4-step run exactly (same
    deterministic token stream and adversary schedule)."""
    kw = dict(num_workers=2, pipeline_shards=2, model_layers=2, max_steps=4,
              eval_freq=2, train_dir=str(tmp_path) + "/")
    full, _ = train_pp(_cfg(**kw), make_mesh_wpp(2, 2), quiet=True)
    resumed, _ = train_pp(
        _cfg(**dict(kw, checkpoint_step=2, max_steps=2)), make_mesh_wpp(2, 2),
        quiet=True,
    )
    a = np.asarray(jax.device_get(full.params["embed"]["embedding"]))
    b = np.asarray(jax.device_get(resumed.params["embed"]["embedding"]))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    assert int(full.step) == int(resumed.step) == 5


def test_pp_config_validation():
    with pytest.raises(ValueError, match="must divide model_layers"):
        _cfg(model_layers=3).validate()
    with pytest.raises(ValueError, match="must divide"):
        _cfg(pp_microbatches=3).validate()
    with pytest.raises(ValueError, match="combining model-parallel axes"):
        _cfg(tensor_shards=2).validate()
    with pytest.raises(ValueError, match="requires network=TransformerLM"):
        TrainConfig(network="LeNet", pipeline_shards=2).validate()


def test_pp_worker_folding_matches_full_mesh():
    """num_workers=4 folded onto a (w=2 × pp=2) mesh (2 vmapped lanes per
    device) must reproduce the full (w=4 × pp=2) mesh trajectory — the
    worker-folding discipline tp_step already has, extended to pp (advisor
    r2)."""
    cfg = _cfg(num_workers=4, pipeline_shards=2, model_layers=2, batch_size=8)
    state_full, m_full = train_pp(cfg, make_mesh_wpp(4, 2), steps=3, quiet=True)
    state_fold, m_fold = train_pp(cfg, make_mesh_wpp(2, 2), steps=3, quiet=True)

    np.testing.assert_allclose(float(m_fold["loss"]), float(m_full["loss"]),
                               rtol=1e-4)
    flat_full = np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(state_full.params)])
    flat_fold = np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(state_fold.params)])
    np.testing.assert_allclose(flat_fold, flat_full, rtol=1e-3, atol=1e-5)
