"""Expert parallelism: Switch-MoE layer semantics, EP sharding placement,
ep=2 vs ep=1 exactness, and coded-DP composition on the (w, ep) mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from draco_tpu.config import TrainConfig
from draco_tpu.models.moe import MoeMlp
from draco_tpu.parallel import EP_AXIS, make_mesh_wep
from draco_tpu.parallel.ep_step import ep_partition_spec, train_ep


def _ep_cfg(**kw):
    base = dict(
        network="TransformerLM", dataset="synthetic-text", batch_size=2,
        num_workers=4, moe_experts=4, expert_shards=2, seq_len=32, vocab=32,
        model_dim=32, model_heads=4, model_layers=1, approach="baseline",
        mode="normal", worker_fail=0, max_steps=3, lr=0.05, momentum=0.9,
        eval_freq=0, train_dir="", log_every=1000,
    )
    base.update(kw)
    return TrainConfig(**base)


def _flat(params):
    return np.concatenate([np.ravel(x) for x in jax.tree.leaves(params)])


def test_moe_layer_shapes_and_capacity(rng):
    """Output shape; uncapped routing reproduces per-token expert outputs;
    capacity 0-ish drops tokens to zero (they ride the residual)."""
    m = MoeMlp(dim=16, experts=4, capacity_factor=4.0)  # cap >= all tokens
    x = jnp.asarray(rng.normal(size=(2, 8, 16)).astype(np.float32))
    params = m.init(jax.random.key(0), x)
    y = m.apply(params, x)
    assert y.shape == x.shape and np.isfinite(np.asarray(y)).all()

    # oracle: route each token through its argmax expert directly
    p = params["params"]
    xf = np.asarray(x).reshape(-1, 16)
    logits = xf @ np.asarray(p["router"]["kernel"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    eidx = probs.argmax(-1)
    want = np.zeros_like(xf)
    for i, e in enumerate(eidx):
        h = xf[i] @ np.asarray(p["w1"])[e] + np.asarray(p["b1"])[e, 0]
        # jax nn.gelu default: tanh approximation
        h = 0.5 * h * (1 + np.tanh(np.sqrt(2 / np.pi) * (h + 0.044715 * h**3)))
        want[i] = (h @ np.asarray(p["w2"])[e] + np.asarray(p["b2"])[e, 0]) * probs[i, e]
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 16), want,
                               rtol=1e-3, atol=1e-4)

    tiny = MoeMlp(dim=16, experts=4, capacity_factor=1e-9)  # cap = 1
    y2 = tiny.apply(params, x)
    # at most 1 token per expert survives; the rest are exactly zero
    nz_rows = (np.abs(np.asarray(y2).reshape(-1, 16)).sum(-1) > 0).sum()
    assert nz_rows <= 4


def test_ep_partition_rules_and_placement():
    cfg = _ep_cfg()
    mesh = make_mesh_wep(4, 2)
    from draco_tpu.parallel.ep_step import build_ep_train_setup

    setup = build_ep_train_setup(cfg, mesh)
    seen = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(setup.state.params)[0]:
        names = [getattr(k, "key", str(k)) for k in path]
        seen["/".join(names)] = (ep_partition_spec(path), leaf.sharding.spec)
    assert seen["block0/moe/w1"][0] == P(EP_AXIS)
    assert seen["block0/moe/w2"][0] == P(EP_AXIS)
    assert seen["block0/moe/router/kernel"][0] == P()
    assert seen["block0/qkv/kernel"][0] == P()
    for key, (want, got) in seen.items():
        assert got == want, (key, want, got)


def test_ep_matches_single_shard():
    """(4 w × 2 ep) and (4 w × 1 ep): expert parallelism is a layout choice."""
    mesh_ep = make_mesh_wep(4, 2)
    state_ep, m_ep = train_ep(_ep_cfg(), mesh_ep, steps=3, quiet=True)

    mesh_1 = make_mesh_wep(4, 1, devices=jax.devices()[:4])
    state_1, m_1 = train_ep(_ep_cfg(expert_shards=1), mesh_1, steps=3, quiet=True)

    np.testing.assert_allclose(float(m_ep["loss"]), float(m_1["loss"]), rtol=1e-4)
    np.testing.assert_allclose(
        _flat(jax.device_get(state_ep.params)),
        _flat(jax.device_get(state_1.params)),
        rtol=1e-3, atol=1e-5,
    )


def test_ep_moe_learns():
    """The MoE LM actually trains on the synthetic stream."""
    mesh = make_mesh_wep(4, 2)
    cfg = _ep_cfg(max_steps=12)
    state, metrics = train_ep(cfg, mesh, steps=12, quiet=True)
    first_state, first = train_ep(cfg, mesh, steps=1, quiet=True)
    assert float(metrics["loss"]) < float(first["loss"])


def test_ep_geomedian_under_attack():
    cfg = _ep_cfg(mode="geometric_median", worker_fail=1, err_mode="rev_grad")
    mesh = make_mesh_wep(4, 2)
    state, metrics = train_ep(cfg, mesh, steps=4, quiet=True)
    assert np.isfinite(float(metrics["loss"]))


def test_ep_validation():
    with pytest.raises(ValueError, match="expert_shards"):
        _ep_cfg(moe_experts=3).validate()
    with pytest.raises(ValueError, match="moe_experts > 0"):
        _ep_cfg(moe_experts=0).validate()
    with pytest.raises(ValueError, match="separate"):
        _ep_cfg(seq_shards=2).validate()
    with pytest.raises(ValueError, match="TransformerLM"):
        _ep_cfg(network="LeNet", dataset="synthetic-mnist").validate()
