"""End-to-end SPMD training-step tests on the 8-device virtual mesh — the
integration layer the reference verified only by running real clusters
(SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu.config import TrainConfig
from draco_tpu.data.datasets import load_dataset
from draco_tpu.runtime import make_mesh
from draco_tpu.training.trainer import Trainer


def make_cfg(**kw):
    base = dict(
        network="LeNet",
        dataset="synthetic-mnist",
        batch_size=8,
        lr=0.01,
        momentum=0.9,
        num_workers=8,
        max_steps=30,
        eval_freq=0,
        train_dir="",
        log_every=1000,
    )
    base.update(kw)
    return TrainConfig(**base)


@pytest.fixture(scope="module")
def ds():
    return load_dataset("synthetic-mnist", synthetic_train=1024, synthetic_test=256)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def run_steps(cfg, ds, mesh, n):
    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    first = None
    for step in range(1, n + 1):
        x, y = tr._device_batch(step)
        mask = jnp.asarray(tr._adv_schedule[step])
        tr.state, m = tr.setup.train_step(tr.state, x, y, mask)
        if first is None:
            first = {k: float(v) for k, v in m.items()}
    return tr, first, {k: float(v) for k, v in m.items()}


class TestBaseline:
    def test_loss_decreases(self, ds, mesh):
        tr, first, last = run_steps(make_cfg(), ds, mesh, 25)
        assert last["loss"] < first["loss"]

    def test_geomedian_resists_attack(self, ds, mesh):
        cfg = make_cfg(mode="geometric_median", worker_fail=2, err_mode="rev_grad",
                       max_steps=40)
        tr, first, last = run_steps(cfg, ds, mesh, 30)
        assert last["loss"] < first["loss"]

    def test_mean_destroyed_by_attack(self, ds, mesh):
        cfg = make_cfg(mode="normal", worker_fail=2, err_mode="rev_grad", lr=0.05)
        tr, first, last = run_steps(cfg, ds, mesh, 15)
        assert not (last["loss"] < first["loss"])  # diverges or NaN

    def test_krum_resists_attack(self, ds, mesh):
        cfg = make_cfg(mode="krum", worker_fail=2, err_mode="constant", max_steps=40)
        tr, first, last = run_steps(cfg, ds, mesh, 30)
        assert last["loss"] < first["loss"]


class TestMajVote:
    def test_vote_resists_one_adversary_per_step(self, ds, mesh):
        # 8 workers in 2 groups of 4 (honest majority everywhere). With only
        # 2 distinct batches per step the voted gradient is noisy, so a calmer
        # lr than the baseline tests.
        cfg = make_cfg(approach="maj_vote", group_size=4, worker_fail=1,
                       err_mode="rev_grad", max_steps=40)
        tr, first, last = run_steps(cfg, ds, mesh, 30)
        assert last["loss"] < first["loss"]

    @pytest.mark.parametrize("err_mode,group_size,wf", [
        ("rev_grad", 4, 1),   # reference attack, single adversary per group
        ("ipm", 4, 1),        # single omniscient adversary
        # both colluders in ONE group (group_size = n), sending bitwise-
        # identical ipm payloads — a 2-vs-6 minority the vote must discard
        # (the case where identical malicious rows could out-count honest
        # rows if the honest-majority budget were mis-checked). alie is
        # inert at n=8 (z <= 0, attacks.py warns) so ipm is the colluding
        # payload with teeth here.
        ("ipm", 8, 2),
    ])
    def test_vote_attacked_equals_clean(self, ds, mesh, err_mode, group_size,
                                        wf):
        """The filtered update must be *identical* to a no-adversary run —
        the strongest statement of vote correctness — for the reference
        attack and for colluding payloads that evade approximate rules."""
        params = {}
        for fail in (0, wf):
            cfg = make_cfg(approach="maj_vote", group_size=group_size,
                           worker_fail=fail, err_mode=err_mode, max_steps=8)
            tr, _, _ = run_steps(cfg, ds, mesh, 8)
            params[fail] = np.concatenate(
                [np.ravel(x) for x in jax.tree.leaves(jax.device_get(tr.state.params))]
            )
        np.testing.assert_array_equal(params[0], params[wf])

    def test_vote_equals_clean_mean_of_groups(self, ds, mesh):
        # with no adversaries, vote = mean over groups of the shared batch
        # gradient; training must track the plain run on the same group batches
        cfg = make_cfg(approach="maj_vote", group_size=2, worker_fail=0, max_steps=10)
        tr, first, last = run_steps(cfg, ds, mesh, 10)
        assert last["loss"] < first["loss"]


class TestCyclic:
    @pytest.mark.parametrize("redundancy", ["simulate", "shared"])
    def test_decodes_and_learns_under_attack(self, ds, mesh, redundancy):
        cfg = make_cfg(approach="cyclic", worker_fail=1, err_mode="rev_grad",
                       redundancy=redundancy, max_steps=40)
        tr, first, last = run_steps(cfg, ds, mesh, 25)
        assert last["loss"] < first["loss"]
        # decode uses exactly n - 2s rows every step (n=8, s=1)
        assert last["honest_located"] == 6.0

    def test_simulate_and_shared_agree(self, ds, mesh):
        """The r× redundant path and the compute-once path must produce the
        same parameters — they are algebraically identical programs."""
        out = {}
        for red in ("simulate", "shared"):
            cfg = make_cfg(approach="cyclic", worker_fail=1, err_mode="constant",
                           redundancy=red, max_steps=6)
            tr, _, _ = run_steps(cfg, ds, mesh, 6)
            out[red] = jax.device_get(tr.state.params)
        flat_a = np.concatenate([np.ravel(x) for x in jax.tree.leaves(out["simulate"])])
        flat_b = np.concatenate([np.ravel(x) for x in jax.tree.leaves(out["shared"])])
        np.testing.assert_allclose(flat_a, flat_b, rtol=2e-3, atol=2e-5)

    def test_layer_granularity_agrees_with_global(self, ds, mesh):
        """decode_granularity=layer runs one locator per parameter tensor
        (reference: cyclic_master.py:125-129); with per-worker corruption it
        must land on the same honest set, hence the same parameters."""
        out = {}
        for gran in ("global", "layer"):
            cfg = make_cfg(approach="cyclic", worker_fail=1, err_mode="rev_grad",
                           redundancy="shared", decode_granularity=gran,
                           max_steps=6)
            tr, _, last = run_steps(cfg, ds, mesh, 6)
            assert last["honest_located"] == 6.0
            out[gran] = jax.device_get(tr.state.params)
        flat_g = np.concatenate([np.ravel(x) for x in jax.tree.leaves(out["global"])])
        flat_l = np.concatenate([np.ravel(x) for x in jax.tree.leaves(out["layer"])])
        np.testing.assert_allclose(flat_g, flat_l, rtol=2e-3, atol=2e-5)

    def test_cyclic_matches_plain_mean_without_adversary(self, ds, mesh):
        """Decode of honest encodings == plain averaging of the same batches:
        run cyclic s=0... not allowed (s>=0 ok) — use s=1 with no actual
        corruption by err_mode=random (passthrough)."""
        cfg = make_cfg(approach="cyclic", worker_fail=1, err_mode="random",
                       redundancy="shared", max_steps=6)
        tr, first, last = run_steps(cfg, ds, mesh, 6)
        assert last["loss"] < first["loss"]


class TestBatchNormModel:
    def test_resnet_cyclic_smoke(self, mesh):
        ds = load_dataset("synthetic-cifar10", synthetic_train=256, synthetic_test=64)
        cfg = make_cfg(network="ResNet18", dataset="synthetic-cifar10", batch_size=2,
                       approach="cyclic", worker_fail=1, err_mode="rev_grad",
                       redundancy="shared", max_steps=4, lr=0.01)
        tr, first, last = run_steps(cfg, ds, mesh, 3)
        assert np.isfinite(last["loss"])
        assert last["honest_located"] == 6.0


class TestEvalAndCheckpoint:
    def test_eval_and_checkpoint_roundtrip(self, ds, mesh, tmp_path):
        cfg = make_cfg(max_steps=60, eval_freq=30, train_dir=str(tmp_path), log_every=30)
        tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
        tr.run()
        from draco_tpu.utils import checkpoint as ckpt

        assert ckpt.available_steps(str(tmp_path)) == [30, 60]
        rec = tr.evaluate(60)
        assert rec["prec1_test"] > 0.8  # synthetic blobs are easy

        # the ragged tail (256 % 100 = 56) must be scored, not dropped:
        # full-split eval in one batch == eval in uneven batches, exactly
        full = tr.evaluate(60, batch_size=len(ds.test_x))
        ragged = tr.evaluate(60, batch_size=100)
        assert ragged["prec1_test"] == pytest.approx(full["prec1_test"], abs=1e-6)
        assert ragged["prec5_test"] == pytest.approx(full["prec5_test"], abs=1e-6)

        # resume from a checkpoint and confirm the step counter fast-forwards
        cfg2 = make_cfg(max_steps=60, eval_freq=0, train_dir=str(tmp_path),
                        checkpoint_step=30)
        tr2 = Trainer(cfg2, mesh=mesh, dataset=ds, quiet=True)
        assert tr2._start_step == 31
        assert int(tr2.state.step) == 31


def _write_idx(path, arr, magic):
    payload = magic.to_bytes(4, "big")
    for d in arr.shape:
        payload += int(d).to_bytes(4, "big")
    with open(path, "wb") as f:
        f.write(payload + arr.tobytes())


def test_real_format_data_end_to_end(tmp_path, mesh):
    """The NON-synthetic branch, end to end: idx-ubyte fixture files on disk
    -> load_dataset("MNIST") -> Trainer (cyclic, under attack) -> full-split
    evaluate. This is the reference's real-data path
    (src/util.py:23-66 -> training -> distributed_evaluator.py:92-110) run in
    CI, not just loader unit tests — the data is class-conditional uint8
    blobs, so learning is observable."""
    r = np.random.RandomState(11)
    protos = r.randint(0, 256, size=(10, 28, 28)).astype(np.int16)

    def make(n, salt):
        rr = np.random.RandomState(11 + salt)
        y = rr.randint(0, 10, size=n).astype(np.uint8)
        noise = rr.randint(-20, 21, size=(n, 28, 28))
        x = np.clip(protos[y] + noise, 0, 255).astype(np.uint8)
        return x, y

    tr_x, tr_y = make(512, 1)
    te_x, te_y = make(96, 2)  # 96 % 64 != 0: the eval tail is exercised too
    _write_idx(str(tmp_path / "train-images-idx3-ubyte"), tr_x, 0x00000803)
    _write_idx(str(tmp_path / "train-labels-idx1-ubyte"), tr_y, 0x00000801)
    _write_idx(str(tmp_path / "t10k-images-idx3-ubyte"), te_x, 0x00000803)
    _write_idx(str(tmp_path / "t10k-labels-idx1-ubyte"), te_y, 0x00000801)

    real_ds = load_dataset("MNIST", data_dir=str(tmp_path))
    assert not real_ds.synthetic and real_ds.name == "MNIST"

    cfg = make_cfg(dataset="MNIST", data_dir=str(tmp_path), batch_size=4,
                   approach="cyclic", worker_fail=1, err_mode="rev_grad",
                   redundancy="shared", max_steps=30, test_batch_size=64)
    tr = Trainer(cfg, mesh=mesh, dataset=real_ds, quiet=True)
    first = tr.run(max_steps=1)
    last = tr.run(max_steps=30)
    assert np.isfinite(last["loss"]) and last["loss"] < first["loss"]
    rec = tr.evaluate(30)
    assert rec["prec1_test"] > 0.6  # blobs are easy; attack is being decoded out
    tr.close()


def test_elastic_resume_across_topology_and_approach(tmp_path, ds):
    """Beyond the reference (whose PS blocks forever on a topology change and
    resumes from a hardcoded path, baseline_master.py:54-57): a checkpoint
    written by a cyclic n=8 run restores into a geo-median n=6 run.
    Params/opt state are replicated and topology-independent, so an operator
    can shrink the fleet or swap the aggregation rule mid-training."""
    cfg8 = make_cfg(num_workers=8, approach="cyclic", worker_fail=1,
                    err_mode="rev_grad", batch_size=4, max_steps=6,
                    eval_freq=3, train_dir=str(tmp_path))
    tr8 = Trainer(cfg8, mesh=make_mesh(8), dataset=ds, quiet=True)
    tr8.run()
    tr8.close()
    from draco_tpu.utils import checkpoint as ckpt_mod

    assert 6 in ckpt_mod.available_steps(str(tmp_path))
    saved = np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(jax.device_get(tr8.state.params))])

    cfg6 = make_cfg(num_workers=6, approach="baseline",
                    mode="geometric_median", worker_fail=1,
                    err_mode="rev_grad", batch_size=4, max_steps=10,
                    eval_freq=0, train_dir=str(tmp_path), checkpoint_step=6)
    tr6 = Trainer(cfg6, mesh=make_mesh(6), dataset=ds, quiet=True)
    restored = np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(jax.device_get(tr6.state.params))])
    np.testing.assert_array_equal(restored, saved)  # exact handoff
    last = tr6.run()
    tr6.close()
    assert int(tr6.state.step) == 11  # resumed at 7, ran through 10
    assert np.isfinite(last["loss"])


def test_resume_across_schedule_family_switch(tmp_path, ds, mesh):
    """A checkpoint written under lr_schedule=constant restores into a cosine
    run (and keeps training): the opt-state pytree is schedule-invariant
    (optim.build_optimizer routes every family through the same
    chain(rule, scale_by_schedule)) — end-to-end pin of the r3 advisor
    finding that a family switch used to fail or misrestore."""
    cfg_const = make_cfg(max_steps=6, eval_freq=3, train_dir=str(tmp_path))
    tr1 = Trainer(cfg_const, mesh=mesh, dataset=ds, quiet=True)
    tr1.run()
    tr1.close()
    saved = np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(jax.device_get(tr1.state.params))])

    cfg_cos = make_cfg(max_steps=12, eval_freq=0, train_dir=str(tmp_path),
                       checkpoint_step=6, lr_schedule="cosine",
                       warmup_steps=2)
    tr2 = Trainer(cfg_cos, mesh=mesh, dataset=ds, quiet=True)
    restored = np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(jax.device_get(tr2.state.params))])
    np.testing.assert_array_equal(restored, saved)
    last = tr2.run()
    tr2.close()
    assert int(tr2.state.step) == 13 and np.isfinite(last["loss"])


def test_same_seed_training_is_bitwise_deterministic(ds, mesh):
    """SURVEY §5.2: SPMD removes the reference's MPI tag-race surface
    entirely; what remains to guarantee is determinism — two Trainer runs
    from the same seed must produce bitwise-identical parameters after
    several coded steps (the property the repetition vote's bitwise
    equality also rests on)."""
    cfg = make_cfg(approach="cyclic", worker_fail=1, err_mode="rev_grad",
                   batch_size=4, max_steps=4)
    leaves = []
    for _ in range(2):
        tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
        tr.run()
        leaves.append(jax.tree.leaves(jax.device_get(tr.state.params)))
        tr.close()
    for a, b in zip(*leaves, strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
