"""bench.py failure-discipline tests.

The driver consumes exactly one artifact from this repo — bench.py's JSON
line — and killed it in both prior rounds (BENCH_r01 rc=1, BENCH_r02 rc=124)
before any output landed. These tests pin the hardened contract: a structured
record reaches stdout quickly under every failure mode, enforced by fake-probe
hooks (DRACO_BENCH_FAKE_PROBE / DRACO_BENCH_FAKE_WEDGE) so no test touches
the real tunnel.

Reference stake: the north-star per-step wall-clock metric itself
(BASELINE.json; reference README.md:2).
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run_bench(extra_args, env_overrides, timeout=300.0):
    env = dict(os.environ)
    env.update(env_overrides)
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, BENCH] + extra_args,
        capture_output=True, text=True, cwd=REPO, timeout=timeout, env=env,
    )
    elapsed = time.monotonic() - t0
    records = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            records.append(json.loads(line))  # every emitted line must parse
    return proc, records, elapsed


class TestBenchFailureDiscipline:
    def test_probe_down_emits_structured_record_fast(self):
        """Tunnel reports down instantly -> tpu_unavailable record in <60 s."""
        proc, records, elapsed = _run_bench(
            ["--no-cpu-fallback"],
            {"DRACO_BENCH_FAKE_PROBE": "down"},
        )
        assert elapsed < 60.0, f"took {elapsed:.0f}s"
        assert proc.returncode == 0, proc.stderr[-500:]
        assert records, f"no JSON on stdout: {proc.stdout!r}"
        rec = records[-1]
        assert rec["error"] == "tpu_unavailable"
        assert rec["value"] is None
        assert rec["unit"] == "ms/step"
        assert "fake probe" in rec["detail"]

    def test_probe_hang_bounded_by_subprocess_timeout(self):
        """A wedged probe (child sleeps forever) cannot stall the harness:
        probe subprocesses are bounded and the record still lands in <60 s."""
        proc, records, elapsed = _run_bench(
            ["--no-cpu-fallback", "--budget", "40"],
            {"DRACO_BENCH_FAKE_PROBE": "hang"},
        )
        assert elapsed < 60.0, f"took {elapsed:.0f}s"
        assert records, f"no JSON on stdout: {proc.stdout!r}"
        rec = records[-1]
        assert rec["error"] in ("tpu_unavailable", "bench_budget_exceeded")
        if rec["error"] == "tpu_unavailable":
            assert "timed out" in rec["detail"]

    def test_watchdog_fires_when_measurement_wedges(self):
        """A hang past the probe (stuck compile / wedged backend call) is cut
        by the watchdog thread at the budget with a bench_budget_exceeded
        record and a hard exit — never rc 124 with an empty tail."""
        proc, records, elapsed = _run_bench(
            ["--cpu-mesh", "8", "--budget", "25"],
            {"DRACO_BENCH_FAKE_WEDGE": "1"},
        )
        assert elapsed < 90.0, f"took {elapsed:.0f}s"
        assert proc.returncode == 2
        assert records, f"no JSON on stdout: {proc.stdout!r}"
        rec = records[-1]
        assert rec["error"] == "bench_budget_exceeded"
        assert "cyclic_leg" in rec["detail"]

    @pytest.mark.slow
    def test_probe_down_cpu_fallback_appends_tiny_record(self):
        """With fallback enabled, the tpu_unavailable record is printed FIRST
        (it must survive a later kill), then a clearly-labelled LeNet CPU
        record is appended; the tail line is the most complete record."""
        proc, records, elapsed = _run_bench(
            ["--budget", "240", "--steps", "3"],
            {"DRACO_BENCH_FAKE_PROBE": "down"},
        )
        assert records, f"no JSON on stdout: {proc.stdout!r}"
        assert records[0]["error"] == "tpu_unavailable"
        assert records[0]["value"] is None
        # the budget is generous and the probe fails instantly, so the
        # fallback must actually have run — an unconditional assertion, or a
        # broken _cpu_fallback would pass vacuously (code-review r3)
        tail = records[-1]
        assert tail["error"] == "tpu_unavailable_cpu_fallback", \
            f"fallback never ran: {tail} / stderr {proc.stderr[-400:]!r}"
        assert tail["value"] is not None and tail["value"] > 0
        # fallback reports under its OWN metric name — a LeNet/CPU number
        # must never enter the flagship metric's series
        assert "lenet" in tail["metric"] and "cpu_fallback" in tail["metric"]
        assert tail["extra"]["network"] == "LeNet"
        assert tail["extra"]["platform"] == "cpu"
