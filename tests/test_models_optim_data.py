import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from draco_tpu import models, optim
from draco_tpu.data import augment, batching, datasets


class TestModels:
    @pytest.mark.parametrize(
        "name,shape",
        [
            ("LeNet", (28, 28, 1)),
            ("FC", (28, 28, 1)),
            ("ResNet18", (32, 32, 3)),
            ("VGG11", (32, 32, 3)),
            ("VGG11_bn", (32, 32, 3)),
        ],
    )
    def test_forward_shapes(self, name, shape):
        model = models.build_model(name)
        x = jnp.zeros((2,) + shape)
        variables = model.init(
            {"params": jax.random.key(0), "dropout": jax.random.key(1)}, x, train=False
        )
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)

    def test_resnet18_param_count(self):
        # CIFAR ResNet-18 has ~11.17M parameters — sanity against the standard
        model = models.build_model("ResNet18")
        v = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
        n = sum(np.prod(p.shape) for p in jax.tree.leaves(v["params"]))
        assert 11_000_000 < n < 11_400_000

    def test_lenet_param_count(self):
        # 20*25+20 + 50*20*25+50 + 800*500+500 + 500*10+10 = 431080
        model = models.build_model("LeNet")
        v = model.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)), train=False)
        n = sum(np.prod(p.shape) for p in jax.tree.leaves(v["params"]))
        assert n == 431080

    def test_heavy_models_build(self):
        # trace-only (init shapes) for the rest of the zoo
        for name in ("ResNet34", "VGG13", "VGG16"):
            model = models.build_model(name)
            out, _ = jax.eval_shape(
                lambda m=model: m.init_with_output(
                    {"params": jax.random.key(0), "dropout": jax.random.key(1)},
                    jnp.zeros((1, 32, 32, 3)),
                    train=False,
                )
            )
            assert out.shape == (1, 10)

    def test_unknown_name_raises(self):
        with pytest.raises(ValueError):
            models.build_model("AlexNet")


class TestOptim:
    def test_sgd_matches_torch(self, rng):
        import torch

        w0 = rng.randn(7, 3).astype(np.float32)
        grads = [rng.randn(7, 3).astype(np.float32) for _ in range(5)]

        tp = torch.nn.Parameter(torch.tensor(w0.copy()))
        topt = torch.optim.SGD([tp], lr=0.1, momentum=0.9)
        for g in grads:
            tp.grad = torch.tensor(g)
            topt.step()

        jopt = optim.sgd_modified(lr=0.1, momentum=0.9)
        params = {"w": jnp.asarray(w0)}
        state = jopt.init(params)
        for g in grads:
            updates, state = jopt.update({"w": jnp.asarray(g)}, state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
        np.testing.assert_allclose(np.asarray(params["w"]), tp.detach().numpy(), rtol=1e-5, atol=1e-6)

    def test_adam_matches_torch(self, rng):
        import torch

        w0 = rng.randn(4, 4).astype(np.float32)
        grads = [rng.randn(4, 4).astype(np.float32) for _ in range(4)]

        tp = torch.nn.Parameter(torch.tensor(w0.copy()))
        topt = torch.optim.Adam([tp], lr=0.01)
        for g in grads:
            tp.grad = torch.tensor(g)
            topt.step()

        jopt = optim.adam_modified(lr=0.01)
        params = {"w": jnp.asarray(w0)}
        state = jopt.init(params)
        for g in grads:
            updates, state = jopt.update({"w": jnp.asarray(g)}, state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
        np.testing.assert_allclose(np.asarray(params["w"]), tp.detach().numpy(), rtol=1e-4, atol=1e-6)


    def test_adamw_matches_torch(self, rng):
        import torch

        w0 = rng.randn(4, 4).astype(np.float32)
        grads = [rng.randn(4, 4).astype(np.float32) for _ in range(4)]

        tp = torch.nn.Parameter(torch.tensor(w0.copy()))
        topt = torch.optim.AdamW([tp], lr=0.01, weight_decay=0.05)
        for g in grads:
            tp.grad = torch.tensor(g)
            topt.step()

        jopt = optim.adamw_modified(lr=0.01, weight_decay=0.05)
        params = {"w": jnp.asarray(w0)}
        state = jopt.init(params)
        for g in grads:
            updates, state = jopt.update({"w": jnp.asarray(g)}, state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
        np.testing.assert_allclose(np.asarray(params["w"]), tp.detach().numpy(),
                                   rtol=1e-4, atol=1e-6)


    def test_opt_state_structure_invariant_across_schedules(self):
        """Resuming a checkpoint across a schedule-family switch requires the
        opt-state pytree structure not to depend on the family (r3 advisor
        finding): constant is built as a degenerate schedule inside the same
        chain, with or without clip_norm (a stateless wrapper)."""
        params = {"w": jnp.zeros((3,))}
        structures = {
            jax.tree.structure(
                optim.build_optimizer("sgd", 0.1, momentum=0.9,
                                      schedule=schedule, total_steps=100,
                                      clip_norm=clip).init(params)
            )
            for schedule in ("constant", "cosine")
            for clip in (0.0, 1.0)
        }
        assert len(structures) == 1

    def test_constant_schedule_build_matches_bare_rule(self, rng):
        """The degenerate-constant chain must update identically to the bare
        torch-parity rule it wraps."""
        w0 = rng.randn(5, 2).astype(np.float32)
        grads = [rng.randn(5, 2).astype(np.float32) for _ in range(4)]
        results = []
        for opt in (optim.build_optimizer("sgd", 0.1, momentum=0.9,
                                          schedule="constant"),
                    optim.sgd_modified(lr=0.1, momentum=0.9)):
            params = {"w": jnp.asarray(w0)}
            state = opt.init(params)
            for g in grads:
                updates, state = opt.update({"w": jnp.asarray(g)}, state, params)
                params = jax.tree.map(lambda p, u: p + u, params, updates)
            results.append(np.asarray(params["w"]))
        np.testing.assert_allclose(results[0], results[1], rtol=1e-6, atol=1e-7)

    def test_cosine_schedule_shape(self):
        sched = optim.lr_schedule("cosine", lr=0.1, warmup_steps=10,
                                  total_steps=110)
        # warmup ramps linearly to peak
        np.testing.assert_allclose(float(sched(0)), 0.01, rtol=1e-5)
        np.testing.assert_allclose(float(sched(9)), 0.1, rtol=1e-5)
        # peak right after warmup, floor (10% of peak) at the end
        np.testing.assert_allclose(float(sched(10)), 0.1, rtol=1e-5)
        np.testing.assert_allclose(float(sched(110)), 0.01, rtol=1e-4)
        # monotone decay in between
        vals = [float(sched(t)) for t in range(10, 111, 10)]
        assert vals == sorted(vals, reverse=True)

    def test_scheduled_sgd_equals_manual_lr_sequence(self, rng):
        """A scheduled rule must match running the fixed-lr rule with the
        schedule's rate at each step — the composition contract."""
        w0 = rng.randn(3, 3).astype(np.float32)
        grads = [rng.randn(3, 3).astype(np.float32) for _ in range(5)]
        sched = optim.lr_schedule("cosine", lr=0.1, warmup_steps=2,
                                  total_steps=5)

        opt = optim.build_optimizer("sgd", lr=0.1, momentum=0.9,
                                    schedule="cosine", warmup_steps=2,
                                    total_steps=5)
        params = {"w": jnp.asarray(w0)}
        state = opt.init(params)
        for g in grads:
            updates, state = opt.update({"w": jnp.asarray(g)}, state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)

        # manual: same momentum buffer algebra, rate applied per step
        buf = np.zeros_like(w0)
        w = w0.copy()
        for t, g in enumerate(grads):
            buf = g if t == 0 else 0.9 * buf + g
            w = w - float(sched(t)) * buf
        np.testing.assert_allclose(np.asarray(params["w"]), w, rtol=1e-5,
                                   atol=1e-6)


    def test_clip_norm_bounds_update(self, rng):
        """clip 1.0 on a huge gradient: the sgd (lr=1, no momentum) update's
        global norm equals the clip; a small gradient passes untouched."""
        big = {"w": jnp.full((4, 4), 100.0)}
        small = {"w": jnp.full((4, 4), 1e-3)}
        opt = optim.build_optimizer("sgd", lr=1.0, momentum=0.0,
                                    clip_norm=1.0)
        state = opt.init(big)
        up, _ = opt.update(big, state, big)
        np.testing.assert_allclose(
            float(optax.global_norm(up)), 1.0, rtol=1e-5)
        up, _ = opt.update(small, state, small)
        np.testing.assert_allclose(np.asarray(up["w"]),
                                   -np.asarray(small["w"]), rtol=1e-6)


class TestData:
    def test_synthetic_fallback_shapes(self):
        ds = datasets.load_dataset("synthetic-mnist", synthetic_train=256, synthetic_test=64)
        assert ds.train_x.shape == (256, 28, 28, 1)
        assert ds.synthetic
        ds = datasets.load_dataset("Cifar10", data_dir="/nonexistent", synthetic_train=128)
        assert ds.train_x.shape == (128, 32, 32, 3)
        assert ds.name == "synthetic-cifar10"

    def test_synthetic_learnable(self):
        # a nearest-prototype probe must beat chance by a wide margin
        ds = datasets.load_dataset("synthetic-mnist", synthetic_train=2048, synthetic_test=512)
        protos = np.stack([ds.train_x[ds.train_y == c].mean(0) for c in range(10)])
        d = ((ds.test_x[:, None] - protos[None]) ** 2).sum(axis=(2, 3, 4))
        acc = (d.argmin(1) == ds.test_y).mean()
        assert acc > 0.6

    def test_grouped_batches_identical_within_group(self):
        ds = datasets.load_dataset("synthetic-mnist", synthetic_train=512, synthetic_test=64)
        seeds = np.array([11, 22, 33])
        x, y = batching.worker_batches_grouped(ds, step=5, num_workers=6, group_size=2,
                                               batch_size=8, seeds=seeds)
        assert x.shape == (6, 8, 28, 28, 1)
        np.testing.assert_array_equal(x[0], x[1])
        np.testing.assert_array_equal(x[2], x[3])
        assert not np.array_equal(x[0], x[2])

    def test_baseline_batches_differ_across_workers(self):
        ds = datasets.load_dataset("synthetic-mnist", synthetic_train=512, synthetic_test=64)
        x, y = batching.worker_batches_baseline(ds, step=0, num_workers=4, batch_size=8, seed=428)
        assert not np.array_equal(x[0], x[1])

    def test_cyclic_global_batch_deterministic(self):
        ds = datasets.load_dataset("synthetic-mnist", synthetic_train=512, synthetic_test=64)
        x1, y1 = batching.cyclic_global_batch(ds, step=3, num_workers=8, batch_size=4, seed=428)
        x2, y2 = batching.cyclic_global_batch(ds, step=3, num_workers=8, batch_size=4, seed=428)
        np.testing.assert_array_equal(x1, x2)
        assert x1.shape == (8, 4, 28, 28, 1)
        # consecutive steps address disjoint sample ranges within an epoch
        x3, _ = batching.cyclic_global_batch(ds, step=4, num_workers=8, batch_size=4, seed=428)
        assert not np.array_equal(x1, x3)

    def test_augment_shapes_and_determinism(self):
        x = jnp.asarray(np.random.RandomState(0).randn(4, 32, 32, 3).astype(np.float32))
        k = jax.random.key(7)
        a1 = augment.augment_batch(x, k)
        a2 = augment.augment_batch(x, k)
        assert a1.shape == x.shape
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))


class TestMixedPrecision:
    """compute_dtype=bfloat16: conv/dense stacks run in bf16 (MXU full rate),
    params/BN stats/logits/gradients stay float32."""

    def test_bf16_grads_are_float32_and_finite(self):
        import jax
        import jax.numpy as jnp

        from draco_tpu.models import build_model

        model = build_model("ResNet18", dtype="bfloat16")
        x = jnp.ones((2, 32, 32, 3), jnp.float32)
        vs = model.init(jax.random.key(0), x, train=False)
        assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(vs["params"]))

        def loss_fn(params):
            logits, _ = model.apply(
                {"params": params, "batch_stats": vs["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            assert logits.dtype == jnp.float32
            return jnp.mean(logits ** 2)

        g = jax.grad(loss_fn)(vs["params"])
        leaves = jax.tree.leaves(g)
        assert all(p.dtype == jnp.float32 for p in leaves)
        assert all(bool(jnp.all(jnp.isfinite(p))) for p in leaves)

    def test_bf16_cyclic_training_learns(self):
        import numpy as np

        from draco_tpu.config import TrainConfig
        from draco_tpu.data.datasets import load_dataset
        from draco_tpu.runtime import make_mesh
        from draco_tpu.training.trainer import Trainer

        ds = load_dataset("synthetic-mnist", synthetic_train=512, synthetic_test=64)
        cfg = TrainConfig(
            network="LeNet", dataset="synthetic-mnist", batch_size=4,
            num_workers=8, approach="cyclic", worker_fail=1,
            err_mode="rev_grad", redundancy="shared",
            compute_dtype="bfloat16", max_steps=25, eval_freq=0,
            train_dir="", log_every=1000,
        )
        tr = Trainer(cfg, mesh=make_mesh(8), dataset=ds, quiet=True)
        first = tr.run(max_steps=1)
        last = tr.run(max_steps=25)
        assert np.isfinite(last["loss"])
        assert last["loss"] < first["loss"]
        tr.close()


class TestRawFileLoaders:
    """Fixture-backed tests for the raw MNIST/CIFAR file loaders — tiny
    idx-ubyte / cifar-pickle files written to tmp_path, so a format bug can't
    hide until a machine with real data (reference layouts: util.py:23-66)."""

    @staticmethod
    def _write_idx_images(path, arr, gz=False):
        import gzip as _gzip

        payload = (0x00000803).to_bytes(4, "big")
        for d in arr.shape:
            payload += int(d).to_bytes(4, "big")
        payload += arr.tobytes()
        opener = _gzip.open if gz else open
        with opener(path, "wb") as f:
            f.write(payload)

    @staticmethod
    def _write_idx_labels(path, y, gz=False):
        import gzip as _gzip

        payload = (0x00000801).to_bytes(4, "big") + int(len(y)).to_bytes(4, "big")
        payload += y.tobytes()
        opener = _gzip.open if gz else open
        with opener(path, "wb") as f:
            f.write(payload)

    @pytest.mark.parametrize("gz", [False, True])
    def test_mnist_idx_loader(self, tmp_path, gz):
        from draco_tpu.data import datasets as dsm

        r = np.random.RandomState(3)
        tr_x = r.randint(0, 256, size=(8, 28, 28), dtype=np.uint8)
        tr_y = r.randint(0, 10, size=(8,), dtype=np.uint8)
        te_x = r.randint(0, 256, size=(4, 28, 28), dtype=np.uint8)
        te_y = r.randint(0, 10, size=(4,), dtype=np.uint8)
        sfx = ".gz" if gz else ""
        self._write_idx_images(str(tmp_path / f"train-images-idx3-ubyte{sfx}"), tr_x, gz)
        self._write_idx_labels(str(tmp_path / f"train-labels-idx1-ubyte{sfx}"), tr_y, gz)
        self._write_idx_images(str(tmp_path / f"t10k-images-idx3-ubyte{sfx}"), te_x, gz)
        self._write_idx_labels(str(tmp_path / f"t10k-labels-idx1-ubyte{sfx}"), te_y, gz)

        ds = dsm._try_load_mnist(str(tmp_path))
        assert ds is not None and not ds.synthetic and ds.name == "MNIST"
        assert ds.train_x.shape == (8, 28, 28, 1) and ds.train_x.dtype == np.float32
        assert ds.test_x.shape == (4, 28, 28, 1)
        assert ds.train_y.dtype == np.int32 and ds.test_y.dtype == np.int32
        np.testing.assert_array_equal(ds.train_y, tr_y.astype(np.int32))
        # normalisation matches the reference constants (util.py:33)
        want = (tr_x.astype(np.float32) / 255.0 - dsm.MNIST_MEAN) / dsm.MNIST_STD
        np.testing.assert_allclose(ds.train_x[..., 0], want, rtol=1e-6)
        # load_dataset dispatch finds the same files
        ds2 = dsm.load_dataset("MNIST", data_dir=str(tmp_path))
        assert not ds2.synthetic

    def test_cifar10_pickle_loader(self, tmp_path):
        import pickle

        from draco_tpu.data import datasets as dsm

        r = np.random.RandomState(4)
        bdir = tmp_path / "cifar-10-batches-py"
        bdir.mkdir()
        raws, labs = [], []
        for i in range(1, 6):
            raw = r.randint(0, 256, size=(4, 3072), dtype=np.uint8)
            lab = r.randint(0, 10, size=(4,)).tolist()
            raws.append(raw)
            labs.append(lab)
            with open(bdir / f"data_batch_{i}", "wb") as f:
                pickle.dump({b"data": raw, b"labels": lab}, f)
        te_raw = r.randint(0, 256, size=(6, 3072), dtype=np.uint8)
        te_lab = r.randint(0, 10, size=(6,)).tolist()
        with open(bdir / "test_batch", "wb") as f:
            pickle.dump({b"data": te_raw, b"labels": te_lab}, f)

        ds = dsm._try_load_cifar10(str(tmp_path))
        assert ds is not None and not ds.synthetic and ds.name == "Cifar10"
        assert ds.train_x.shape == (20, 32, 32, 3) and ds.train_x.dtype == np.float32
        assert ds.test_x.shape == (6, 32, 32, 3)
        np.testing.assert_array_equal(ds.train_y, np.concatenate(labs).astype(np.int32))
        np.testing.assert_array_equal(ds.test_y, np.asarray(te_lab, np.int32))
        # CHW -> HWC transpose + per-channel normalisation (util.py:37-38)
        want0 = te_raw[0].reshape(3, 32, 32).transpose(1, 2, 0).astype(np.float32) / 255.0
        want0 = (want0 - dsm.CIFAR_MEAN) / dsm.CIFAR_STD
        np.testing.assert_allclose(ds.test_x[0], want0, rtol=1e-5)
