"""Telemetry spine (draco_tpu/obs + in-graph decode health, ISSUE 4), the
compile/retrace sentinel (obs/compile_watch.py, ISSUE 5), and per-worker
Byzantine forensics (obs/forensics.py, ISSUE 7).

Unit layer: the span tracer emits valid Chrome trace events and is a strict
no-op when disabled; the heartbeat folds per-step detection counts into
precision/recall and rewrites status.json atomically; MetricWriter buffers
to flush/close boundaries; Segments times with a monotonic clock; the
decode/vote health values are correct (and raise the fault signal beyond
the locator budget) straight off the coding primitives; trace_report folds
the artifacts; the compile sentinel attributes XLA executable builds to
labelled dispatch windows, writes the compiles.jsonl ledger + trace compile
lane, and its steady-state guard trips on a deliberately shape-polymorphic
control. The integration layer — health columns flowing through both
production loops, eager == chunked bitwise with telemetry enabled AND the
compile guard in strict mode (steady-state recompiles == 0),
trace.json/status.json/compiles.jsonl from real runs — rides the existing
K ∈ {1, 4} equivalence suites (tests/test_chunked_trainer.py,
tests/test_chunked_token_loop.py) so it costs no extra training runs.
"""

import json
import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu.obs import (
    NULL_TRACER,
    CompileWatch,
    RetraceError,
    RetraceWarning,
    RunHeartbeat,
    SpanTracer,
)
from draco_tpu.obs.tracer import NullTracer


# --------------------------------------------------------------------------
# SpanTracer
# --------------------------------------------------------------------------

@pytest.mark.core
def test_tracer_emits_valid_chrome_trace(tmp_path):
    """Nested spans, a worker-thread lane, counters, metadata — and the
    file parses as the Chrome trace event format Perfetto loads."""
    path = str(tmp_path / "trace.json")
    tr = SpanTracer(path)
    with tr.span("outer", step=1):
        with tr.span("inner"):
            pass
        tr.counter("queue_depth", 1)
    tr.instant("marker")

    def worker():
        tr.name_thread("worker-lane")
        with tr.span("worker-span"):
            pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    tr.close()

    payload = json.load(open(path))
    events = payload["traceEvents"]
    spans = {e["name"]: e for e in events if e["ph"] == "X"}
    assert set(spans) == {"outer", "inner", "worker-span"}
    for e in spans.values():  # required Chrome-trace fields, µs numbers
        assert {"ts", "dur", "pid", "tid"} <= set(e)
        assert e["dur"] >= 0
    # nesting is wall-clock containment on the same tid
    outer, inner = spans["outer"], spans["inner"]
    assert outer["tid"] == inner["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"step": 1}
    # the worker thread got its own labeled lane
    assert spans["worker-span"]["tid"] != outer["tid"]
    lanes = {e["tid"]: e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert lanes[spans["worker-span"]["tid"]] == "worker-lane"
    assert lanes[outer["tid"]] == "main"
    counters = [e for e in events if e["ph"] == "C"]
    assert counters and counters[0]["args"] == {"queue_depth": 1}
    assert any(e["ph"] == "i" for e in events)


@pytest.mark.core
def test_disabled_tracer_is_a_strict_noop(tmp_path):
    """The disabled path allocates nothing and touches no file: span()
    returns the one shared context-manager object, every method is inert,
    and a loop run with NULL_TRACER leaves no artifact."""
    assert isinstance(NULL_TRACER, NullTracer)
    assert NULL_TRACER.enabled is False
    cm1 = NULL_TRACER.span("a", k=1)
    cm2 = NULL_TRACER.span("b")
    assert cm1 is cm2  # no per-span allocation
    with cm1:
        NULL_TRACER.counter("c", 1)
        NULL_TRACER.instant("i")
        NULL_TRACER.name_thread("t")
    NULL_TRACER.flush()
    NULL_TRACER.close()
    assert list(tmp_path.iterdir()) == []
    # construction rule: no trace_dir (or a non-main process) -> the
    # singleton, never a new object
    from draco_tpu.obs import make_tracer
    assert make_tracer("", True) is NULL_TRACER
    assert make_tracer(str(tmp_path), False) is NULL_TRACER


def test_tracer_flush_is_atomic_and_incremental(tmp_path):
    path = str(tmp_path / "trace.json")
    tr = SpanTracer(path)
    with tr.span("first"):
        pass
    tr.flush()
    mid = json.load(open(path))
    assert {e["name"] for e in mid["traceEvents"] if e["ph"] == "X"} == \
        {"first"}
    with tr.span("second"):
        pass
    tr.close()
    final = json.load(open(path))
    assert {e["name"] for e in final["traceEvents"] if e["ph"] == "X"} == \
        {"first", "second"}
    assert not (tmp_path / "trace.json.tmp").exists()


# --------------------------------------------------------------------------
# RunHeartbeat
# --------------------------------------------------------------------------

@pytest.mark.core
def test_heartbeat_precision_recall_and_payload(tmp_path):
    hb = RunHeartbeat(str(tmp_path))
    for step in range(1, 5):
        hb.observe({"step": step, "loss": 2.0 - 0.1 * step, "prec1": 0.5,
                    "decode_residual": 1e-7, "located_errors": 1.0,
                    "det_tp": 1.0, "det_adv": 1.0})
    payload = hb.beat(4, total_steps=8, extra={"prefetch_depth": 1})
    on_disk = json.load(open(tmp_path / "status.json"))
    assert on_disk == payload
    assert payload["step"] == 4 and payload["total_steps"] == 8
    assert payload["steps_per_s"] > 0 and payload["eta_s"] >= 0
    assert payload["loss"] == pytest.approx(1.6)
    assert payload["prefetch_depth"] == 1
    h = payload["decode_health"]
    assert h["precision"] == 1.0 and h["recall"] == 1.0
    assert h["flagged_total"] == 4.0 and h["adv_total"] == 4.0
    assert h["decode_residual"] == pytest.approx(1e-7)
    assert not (tmp_path / "status.json.tmp").exists()
    # a missed detection shows up as recall < 1
    hb.observe({"step": 5, "loss": 1.0, "located_errors": 0.0,
                "det_tp": 0.0, "det_adv": 1.0})
    h = hb.beat(5, 8)["decode_health"]
    assert h["recall"] == pytest.approx(4 / 5) and h["precision"] == 1.0


@pytest.mark.core
def test_heartbeat_disabled_is_noop(tmp_path):
    hb = RunHeartbeat(None)
    hb.observe({"step": 1, "loss": 1.0})
    assert hb.beat(1, 10) is None
    hb2 = RunHeartbeat(str(tmp_path), enabled=False)
    assert hb2.beat(1, 10) is None
    assert list(tmp_path.iterdir()) == []
    # no health section when the route emits no detection columns
    hb3 = RunHeartbeat(str(tmp_path))
    hb3.observe({"step": 1, "loss": 1.0})
    assert "decode_health" not in hb3.beat(1, 2)


@pytest.mark.core
def test_heartbeat_schema_version(tmp_path):
    """Every status.json payload — beats AND terminals, including a
    terminal written before any beat — carries the schema version
    (consumers assert it when present, tolerate its absence)."""
    from draco_tpu.obs import STATUS_SCHEMA

    hb = RunHeartbeat(str(tmp_path))
    payload = hb.beat(1, 2)
    assert payload["schema"] == STATUS_SCHEMA
    assert json.load(open(tmp_path / "status.json"))["schema"] == \
        STATUS_SCHEMA
    hb2 = RunHeartbeat(str(tmp_path / "crash_early"))
    term = hb2.terminal("crashed", cause="boom")  # no beat ever happened
    assert term["schema"] == STATUS_SCHEMA and term["state"] == "crashed"


@pytest.mark.core
def test_heartbeat_tolerates_missing_column_families(tmp_path):
    """Optional column families (health / guard / forensics) may be absent
    per record — a baseline route emits none, eval records carry none, and
    a mixed-route train_dir interleaves both. Records without a family
    must not advance or poison its accumulators, and a TRAILING record
    without the health family must not hide the cumulative health block
    (regression: decode_health() used to key off the newest record)."""
    hb = RunHeartbeat(str(tmp_path), num_workers=4)
    hb.observe({"step": 1, "loss": 2.0, "located_errors": 1.0,
                "det_tp": 1.0, "det_adv": 1.0, "guard_trips": 0.0,
                "skipped_steps": 0.0, "decode_residual": 1e-7})
    # baseline-route record: no health, no guard, no forensics columns
    hb.observe({"step": 2, "loss": 1.9})
    payload = hb.beat(2, 4)
    h = payload["decode_health"]
    assert h["precision"] == 1.0 and h["recall"] == 1.0
    assert h["flagged_total"] == 1.0 and h["adv_total"] == 1.0
    assert h["decode_residual"] == pytest.approx(1e-7)
    assert payload["guard"] == {"trips": 0.0, "skipped_steps": 0.0}
    assert payload["loss"] == pytest.approx(1.9)  # progress still newest
    # an eval-shaped record (no step-metrics at all) is equally harmless
    hb.observe({"step": 2, "split": "eval"})
    assert hb.beat(2, 4)["decode_health"]["adv_total"] == 1.0


# --------------------------------------------------------------------------
# obs/forensics.py — packed masks, record round trip, the ledger
# --------------------------------------------------------------------------

@pytest.mark.core
def test_forensics_mask_pack_roundtrip():
    """pack -> f32 block -> host record int -> JSON -> unpack is exact for
    every n in the supported range, INCLUDING masks whose packed word is a
    float32 NaN bit pattern (workers 23..30 all accused) — the case a
    float()/JSON path would silently destroy. n > 64 raises the named
    bound."""
    from draco_tpu.obs import forensics as fx

    rng = np.random.RandomState(7)
    for n in (1, 7, 24, 31, 32, 33, 64):
        for _ in range(10):
            m = rng.rand(n) < 0.5
            packed = np.asarray(jax.jit(fx.pack_bits)(jnp.asarray(m)))
            assert packed.dtype == np.float32
            assert packed.shape == (fx.num_mask_words(n),)
            words = [fx.record_value(f"{fx.MASK_PREFIX}accused0", w)
                     for w in packed]
            words = json.loads(json.dumps(words))  # the JSONL round trip
            assert all(isinstance(w, int) for w in words)
            assert fx.unpack_bits(words, n) == tuple(bool(b) for b in m)
    # adversarial patterns: packed word is an f32 NaN / Inf bit pattern
    for n, idx in ((32, range(23, 32)), (32, range(0, 32)),
                   (31, range(23, 31))):
        m = np.array([i in idx for i in range(n)])
        packed = np.asarray(fx.pack_bits(jnp.asarray(m)))
        words = json.loads(json.dumps(
            [fx.record_value(f"{fx.MASK_PREFIX}adv0", w) for w in packed]))
        assert fx.unpack_bits(words, n) == tuple(m)
    with pytest.raises(ValueError, match="num_workers <= 64"):
        fx.num_mask_words(65)
    assert fx.mask_metric_names(8) == (
        "wmask_accused0", "wmask_present0", "wmask_adv0")
    assert len(fx.mask_metric_names(33)) == 6  # two words per kind


@pytest.mark.core
def test_forensics_pack_bits_sharded_mask_matches_replicated():
    """Regression (caught by the chaos tp cell): packing a mesh-SHARDED
    mask must agree bit-for-bit with packing the same mask replicated. The
    original pad-concat+reshape formulation shifted every bit by one under
    the GSPMD partitioner on the w×tp mesh — worker 3's accusation landed
    on bit 4 — while the fetched mask itself was correct."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from draco_tpu.obs import forensics as fx
    from draco_tpu.parallel.mesh import make_mesh_wtp
    from draco_tpu.runtime import WORKER_AXIS

    mesh = make_mesh_wtp(4, 2)
    rng = np.random.RandomState(11)
    for _ in range(8):
        mask = rng.rand(8) < 0.4
        md = jax.device_put(jnp.asarray(mask),
                            NamedSharding(mesh, P(WORKER_AXIS)))
        with mesh:
            sharded = np.asarray(jax.jit(fx.pack_bits)(md))
        replicated = np.asarray(fx.pack_bits(jnp.asarray(mask)))
        np.testing.assert_array_equal(sharded.view(np.uint32),
                                      replicated.view(np.uint32))
        assert fx.unpack_bits(
            [int(w) for w in sharded.view(np.uint32)], 8
        ) == tuple(bool(b) for b in mask)


@pytest.mark.core
def test_forensics_pack_gates_absent_workers():
    """An absent worker is never an accused worker: pack_mask_columns
    re-gates the accusation set by presence, whatever the caller passed."""
    from draco_tpu.obs import forensics as fx

    accused = jnp.asarray([True, True, False, False])
    present = jnp.asarray([True, False, True, False])
    cols = fx.pack_mask_columns(accused, present, jnp.zeros(4, bool))
    masks = fx.record_masks(
        {k: fx.record_value(k, v) for k, v in cols.items()}, 4)
    assert masks["accused"] == (True, False, False, False)
    assert masks["present"] == (True, False, True, False)
    # present=None means everyone present
    cols = fx.pack_mask_columns(accused, None, jnp.zeros(4, bool))
    masks = fx.record_masks(
        {k: fx.record_value(k, v) for k, v in cols.items()}, 4)
    assert masks["present"] == (True,) * 4
    assert masks["accused"] == (True, True, False, False)


def _mask_record(step, accused, present, adv):
    """A materialized record with packed forensics columns (host ints)."""
    from draco_tpu.obs import forensics as fx

    cols = fx.pack_mask_columns(jnp.asarray(accused, bool),
                                jnp.asarray(present, bool),
                                jnp.asarray(adv, bool))
    rec = {"step": step, "loss": 1.0}
    rec.update({k: fx.record_value(k, v) for k, v in cols.items()})
    return rec


@pytest.mark.core
def test_accusation_ledger_counters_trust_episodes():
    """The ledger folds per-step masks into per-worker counters, an EW
    trust score, and attack EPISODES: consecutive accusations are one
    episode; a present-and-clean step closes it; an ABSENT step neither
    accuses nor exonerates (the episode stays open across the gap)."""
    from draco_tpu.obs.forensics import AccusationLedger

    lg = AccusationLedger(4)
    ones = [True] * 4
    # steps 1-3: worker 1 accused (and truly adversarial)
    for step in (1, 2, 3):
        assert lg.observe(_mask_record(step, [0, 1, 0, 0], ones,
                                       [0, 1, 0, 0]))
    # step 4: worker 1 ABSENT — not accused, episode must stay open
    assert lg.observe(_mask_record(4, [0, 0, 0, 0], [1, 0, 1, 1],
                                   [0, 0, 0, 0]))
    # step 5: worker 1 back and accused again — SAME episode, extended;
    # worker 2 falsely accused (honest) — a new 1-step episode
    assert lg.observe(_mask_record(5, [0, 1, 1, 0], ones, [0, 1, 0, 0]))
    # step 6: everyone clean — both episodes close
    assert lg.observe(_mask_record(6, [0, 0, 0, 0], ones, [0, 0, 0, 0]))
    # a record with no forensics columns is ignored, not an error
    assert not lg.observe({"step": 7, "loss": 0.5})

    rows = {r["worker"]: r for r in lg.worker_rows()}
    assert rows[1]["accused"] == 4 and rows[1]["tp"] == 4
    assert rows[1]["present"] == 5  # absent step 4 not counted
    assert rows[1]["precision"] == 1.0 and rows[1]["recall"] == 1.0
    assert rows[2]["accused"] == 1 and rows[2]["fp"] == 1
    assert rows[2]["precision"] == 0.0  # falsely accused once, never adv
    assert rows[0]["accused"] == 0 and rows[0]["trust"] == 1.0
    assert rows[1]["trust"] < rows[2]["trust"] < 1.0
    eps = lg.all_episodes()
    assert len(eps) == 2 and not lg.open_episodes()
    w1 = next(e for e in eps if e["worker"] == 1)
    assert (w1["start"], w1["end"], w1["steps"]) == (1, 5, 4)
    w2 = next(e for e in eps if e["worker"] == 2)
    assert (w2["start"], w2["end"], w2["steps"]) == (5, 5, 1)
    summary = lg.summary()
    assert summary["top_suspects"][0]["worker"] == 1
    assert summary["open_episodes"] == 0 and summary["episodes_total"] == 2

    # an episode still running at the last step reports as open
    lg2 = AccusationLedger(2)
    lg2.observe(_mask_record(1, [1, 0], [1, 1], [1, 0]))
    lg2.observe(_mask_record(2, [1, 0], [1, 1], [1, 0]))
    (ep,) = lg2.open_episodes()
    assert ep["open"] and ep["steps"] == 2
    assert lg2.summary()["open_episodes"] == 1


@pytest.mark.core
def test_heartbeat_forensics_block(tmp_path):
    """status.json grows the forensics block when the route ships mask
    columns and num_workers is wired; stays absent otherwise."""
    hb = RunHeartbeat(str(tmp_path), num_workers=4)
    hb.observe(_mask_record(1, [0, 0, 1, 0], [1, 1, 1, 1], [0, 0, 1, 0]))
    payload = hb.beat(1, 2)
    fx_block = payload["forensics"]
    assert fx_block["num_workers"] == 4
    assert fx_block["top_suspects"] == [
        {"worker": 2, "accused": 1, "trust": fx_block["trust"][2]}]
    assert fx_block["open_episodes"] == 1
    # no num_workers -> no ledger -> no block (backward compatible)
    hb2 = RunHeartbeat(str(tmp_path / "plain"))
    hb2.observe(_mask_record(1, [0, 1], [1, 1], [0, 1]))
    assert "forensics" not in hb2.beat(1, 2)


@pytest.mark.core
def test_forensics_straggler_never_accused_both_codes():
    """End of the in-graph chain for both codes under straggler drops: the
    packed accusation set never contains an absent worker — an erasure is
    known-missing, not evidence (cyclic flags present rows only; the vote
    neither counts nor flags absent members; pack re-gates by presence)."""
    from draco_tpu.coding import cyclic, repetition
    from draco_tpu.obs import forensics as fx
    from draco_tpu.parallel.common import accusation_mask

    rng = np.random.RandomState(5)
    code = cyclic.build_cyclic_code(8, 1)
    g = rng.randn(8, 64).astype(np.float32)
    rf = jnp.asarray(1.0 + rng.randn(64).astype(np.float32))
    er, ei = cyclic.encode_shared(code, jnp.asarray(g))
    # worker 6 is an adversary AND worker 2 straggles (t+e <= s... s=1:
    # use an erasure-only step and an adversary-only step)
    pres = jnp.asarray(np.arange(8) != 2)
    er_d = er * pres[:, None]
    ei_d = ei * pres[:, None]
    _, _, h = cyclic.decode(code, er_d, ei_d, rf, present=pres,
                            with_health=True)
    h["bad_rows"] = fx.nonfinite_rows(jnp.asarray(g))
    accused = np.asarray(accusation_mask(h, pres))
    assert not accused[2]  # absent != accused
    assert accused.sum() == 0  # erasure-only: nobody accused

    rep = repetition.build_repetition_code(8, 4)
    rows = np.tile(rng.randn(2, 1, 16).astype(np.float32),
                   (1, 4, 1)).reshape(8, 16)
    bad = rows.copy()
    bad[5] *= -100.0  # adversary... who also straggles
    pres = jnp.asarray(np.arange(8) != 5)
    _, vh = repetition.majority_vote(rep, jnp.asarray(bad), present=pres,
                                     with_health=True)
    cols = fx.pack_mask_columns(
        vh["flagged"] | fx.nonfinite_rows(jnp.asarray(bad)), pres,
        jnp.asarray(np.arange(8) == 5))
    masks = fx.record_masks(
        {k: fx.record_value(k, v) for k, v in cols.items()}, 8)
    assert not any(masks["accused"])  # its row never arrived


@pytest.mark.core
def test_cyclic_loud_rows_attribute_beyond_budget():
    """The forensic-only loud-row mask: beyond the locator budget (2
    corrupt rows, s=1) the fitted-codeword flag set is blind to rows the
    fit absorbed, but the magnitude outliers ARE the corrupt rows — the
    accusation union must name both. In budget, loud adds nothing beyond
    the exact flag set (precision stays 1.0)."""
    from draco_tpu.coding import cyclic
    from draco_tpu.parallel.common import accusation_mask

    code = cyclic.build_cyclic_code(8, 1)
    rng = np.random.RandomState(0)
    g = rng.randn(8, 64).astype(np.float32)
    rf = jnp.asarray(1.0 + rng.randn(64).astype(np.float32))
    er, ei = cyclic.encode_shared(code, jnp.asarray(g))
    for rows in ([2, 5], [0, 4], [1, 6], [3, 7]):
        er2, ei2 = er, ei
        for r in rows:
            er2, ei2 = er2.at[r].mul(-100.0), ei2.at[r].mul(-100.0)
        _, _, h = cyclic.decode(code, er2, ei2, rf, with_health=True)
        accused = np.asarray(accusation_mask(h))
        assert set(rows) <= set(np.nonzero(accused)[0].tolist()), (
            rows, np.nonzero(accused)[0])
    # in budget: accusation == the exact flag set (no honest loud rows)
    er1, ei1 = er.at[3].mul(-100.0), ei.at[3].mul(-100.0)
    _, _, h1 = cyclic.decode(code, er1, ei1, rf, with_health=True)
    np.testing.assert_array_equal(np.asarray(accusation_mask(h1)),
                                  np.arange(8) == 3)
    # clean: nobody accused
    _, _, h0 = cyclic.decode(code, er, ei, rf, with_health=True)
    assert np.asarray(accusation_mask(h0)).sum() == 0


@pytest.mark.core
def test_nonfinite_rows_attribute_through_shared_encode():
    """A NaN gradient row smears across EVERY codeword under the shared
    algebraic encode (0·NaN = NaN), so the wire can't attribute it — the
    ingest check (nonfinite_rows on the raw rows) must, exactly."""
    from draco_tpu.coding import cyclic
    from draco_tpu.obs import forensics as fx
    from draco_tpu.parallel.common import accusation_mask

    code = cyclic.build_cyclic_code(8, 1)
    rng = np.random.RandomState(1)
    g = rng.randn(8, 64).astype(np.float32)
    g[3, 17] = np.nan
    rf = jnp.asarray(1.0 + rng.randn(64).astype(np.float32))
    er, ei = cyclic.encode_shared(code, jnp.asarray(g))
    assert not np.isfinite(np.asarray(er)).all(axis=1).any()  # all smeared
    _, _, h = cyclic.decode(code, er, ei, rf, with_health=True)
    h["bad_rows"] = fx.nonfinite_rows(jnp.asarray(g))
    np.testing.assert_array_equal(np.asarray(accusation_mask(h)),
                                  np.arange(8) == 3)
    # the (n, hat_s, d) simulate-mode stack reduces over the lane axes too
    g3 = rng.randn(4, 3, 8).astype(np.float32)
    g3[2, 1, 0] = np.inf
    np.testing.assert_array_equal(np.asarray(fx.nonfinite_rows(
        jnp.asarray(g3))), np.arange(4) == 2)


# --------------------------------------------------------------------------
# MetricWriter buffering + Segments monotonic clock (utils/metrics.py)
# --------------------------------------------------------------------------

@pytest.mark.core
def test_metric_writer_buffers_until_flush_or_close(tmp_path):
    from draco_tpu.utils.metrics import MetricWriter

    w = MetricWriter(str(tmp_path), quiet=True, buffer_records=64)
    path = tmp_path / "metrics.jsonl"
    for step in range(3):
        w.write({"step": step, "loss": 1.0})
    assert path.read_text() == ""  # buffered: no per-record file traffic
    w.flush()
    assert len(path.read_text().splitlines()) == 3
    w.write({"step": 3, "loss": 1.0})
    w.close()  # tail safety: close drains the buffer
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["step"] for r in recs] == [0, 1, 2, 3]
    assert all("time" in r for r in recs)  # record stamps stay wall-clock

    # the configurable cap: buffer_records=2 auto-flushes on the 2nd write
    w2 = MetricWriter(str(tmp_path / "b"), quiet=True, buffer_records=2)
    w2.write({"step": 0})
    assert (tmp_path / "b" / "metrics.jsonl").read_text() == ""
    w2.write({"step": 1})
    assert len((tmp_path / "b" / "metrics.jsonl").read_text()
               .splitlines()) == 2
    w2.close()


@pytest.mark.core
def test_segments_use_monotonic_clock(monkeypatch):
    """A wall-clock step backwards (NTP slew) mid-segment must not corrupt
    the duration — begin/end read time.perf_counter, not time.time."""
    import draco_tpu.utils.metrics as metrics_mod

    walltimes = iter([1e9, 1e9 - 3600.0])  # time.time jumps back an hour
    monkeypatch.setattr(metrics_mod.time, "time",
                        lambda: next(walltimes, 0.0))
    seg = metrics_mod.Segments()
    seg.begin("comp")
    seg.end()
    assert 0.0 <= seg.t["comp"] < 1.0
    assert seg.as_dict() == {"t_comp": round(seg.t["comp"], 6)}


# --------------------------------------------------------------------------
# decode / vote health straight off the coding primitives
# --------------------------------------------------------------------------

@pytest.mark.core
def test_cyclic_decode_health_flags_exactly_the_corrupt_rows():
    from draco_tpu.coding import cyclic

    code = cyclic.build_cyclic_code(8, 1)
    rng = np.random.RandomState(0)
    g = rng.randn(8, 64).astype(np.float32)
    rf = jnp.asarray(1.0 + rng.randn(64).astype(np.float32))
    er, ei = cyclic.encode_shared(code, jnp.asarray(g))
    # clean: nothing flagged, residual is float noise
    _, _, h = cyclic.decode(code, er, ei, rf, with_health=True)
    assert float(h["residual"]) < 1e-4
    assert np.asarray(h["flagged"]).sum() == 0
    # one corrupt row (rev_grad magnitude): flagged exactly, residual ~ 0
    er1, ei1 = er.at[3].mul(-99.0), ei.at[3].mul(-99.0)
    _, honest, h1 = cyclic.decode(code, er1, ei1, rf, with_health=True)
    np.testing.assert_array_equal(
        np.asarray(h1["flagged"]),
        np.arange(8) == 3)
    assert float(h1["residual"]) < 1e-4
    assert not bool(np.asarray(honest)[3])
    # erasure-only: stragglers are known-missing, never "detected"
    pres = np.arange(8) != 5
    _, _, h2 = cyclic.decode(code, er * pres[:, None], ei * pres[:, None],
                             rf, present=jnp.asarray(pres), with_health=True)
    assert np.asarray(h2["flagged"]).sum() == 0
    assert float(h2["residual"]) < 1e-4


@pytest.mark.core
def test_cyclic_decode_health_raises_fault_beyond_budget():
    """t = s+1 corruptions exceed the exactness guarantee: the health
    signal must say so — flagged count over budget and/or a loud
    residual — instead of reporting a clean decode."""
    from draco_tpu.coding import cyclic

    code = cyclic.build_cyclic_code(8, 1)
    rng = np.random.RandomState(1)
    g = rng.randn(8, 64).astype(np.float32)
    rf = jnp.asarray(1.0 + rng.randn(64).astype(np.float32))
    er, ei = cyclic.encode_shared(code, jnp.asarray(g))
    for rows in ([2, 5], [0, 4], [1, 6]):
        er2, ei2 = er, ei
        for r in rows:
            er2, ei2 = er2.at[r].mul(-99.0), ei2.at[r].mul(-99.0)
        _, _, h = cyclic.decode(code, er2, ei2, rf, with_health=True)
        flagged = int(np.asarray(h["flagged"]).sum())
        assert flagged > code.s or float(h["residual"]) > 1e-4, (
            rows, flagged, float(h["residual"]))


@pytest.mark.core
def test_cyclic_decode_layers_health_unions_layers():
    from draco_tpu.coding import cyclic

    code = cyclic.build_cyclic_code(8, 1)
    rng = np.random.RandomState(2)
    g = rng.randn(8, 24).astype(np.float32)
    rf = jnp.asarray(1.0 + rng.randn(24).astype(np.float32))
    er, ei = cyclic.encode_shared(code, jnp.asarray(g))
    # corrupt row 4 only inside the second layer's coordinates [10, 24)
    er = er.at[4, 10:].add(100.0)
    _, _, h = cyclic.decode_layers(code, er, ei, rf, [0, 10, 24],
                                   with_health=True)
    np.testing.assert_array_equal(np.asarray(h["flagged"]),
                                  np.arange(8) == 4)
    assert float(h["residual"]) < 1e-4
    assert np.ndim(h["residual"]) == 0


@pytest.mark.core
def test_majority_vote_health():
    from draco_tpu.coding import repetition

    code = repetition.build_repetition_code(8, 4)
    rng = np.random.RandomState(3)
    rows = np.tile(rng.randn(2, 1, 16).astype(np.float32),
                   (1, 4, 1)).reshape(8, 16)
    # all honest: full agreement, nothing flagged
    voted, h = repetition.majority_vote(code, jnp.asarray(rows),
                                        with_health=True)
    assert float(h["vote_agree"]) == 1.0
    assert int(h["flagged_groups"]) == 0
    assert np.asarray(h["flagged"]).sum() == 0
    # one adversary in group 1: flagged exactly, agreement drops by 1/8
    bad = rows.copy()
    bad[5] *= -100.0
    voted_b, hb = repetition.majority_vote(code, jnp.asarray(bad),
                                           with_health=True)
    np.testing.assert_array_equal(np.asarray(hb["flagged"]),
                                  np.arange(8) == 5)
    assert float(hb["vote_agree"]) == pytest.approx(7 / 8)
    assert int(hb["flagged_groups"]) == 1
    np.testing.assert_array_equal(np.asarray(voted_b), np.asarray(voted))
    # an absent member neither votes nor is flagged
    pres = np.arange(8) != 5
    _, hp = repetition.majority_vote(code, jnp.asarray(bad),
                                     present=jnp.asarray(pres),
                                     with_health=True)
    assert np.asarray(hp["flagged"]).sum() == 0
    assert float(hp["vote_agree"]) == 1.0
    # health is an opt-in second return: the bare call is unchanged
    bare = repetition.majority_vote(code, jnp.asarray(bad))
    np.testing.assert_array_equal(np.asarray(bare), np.asarray(voted_b))


# --------------------------------------------------------------------------
# CompileWatch — compile ledger + steady-state retrace guard (ISSUE 5)
# --------------------------------------------------------------------------

@pytest.mark.core
def test_compile_watch_ledger_attribution_and_trace_lane(tmp_path):
    """A labelled dispatch window's builds land in compiles.jsonl with the
    program name and lowering seconds, unlabelled builds record with
    program null, the tracer gets a compile-category lane event per build,
    and the process-wide counters advance."""
    from draco_tpu.obs.compile_watch import global_stats

    tracer = SpanTracer(str(tmp_path / "trace.json"))
    before = global_stats()
    with CompileWatch(ledger_dir=str(tmp_path), tracer=tracer) as w:
        f = jax.jit(lambda x: x * 3.0)
        x = jnp.ones(7)  # utility fill build happens OUTSIDE the label
        with w.expect("prog_a"):
            f(x)
        with w.expect("prog_a"):
            f(x)  # warm: cached, no build
        jax.jit(lambda x: x - 1.0)(x)  # unlabelled build
    tracer.close()
    after = global_stats()

    assert w.builds >= 2 and after["builds"] - before["builds"] >= w.builds
    assert w.steady_recompiles == 0
    assert w.builds_by_program.get("prog_a", 0) >= 1
    snap = w.snapshot()
    assert snap["compiles"] == w.builds
    assert snap["compile_s"] > 0 and snap["steady_recompiles"] == 0

    rows = [json.loads(l) for l in open(tmp_path / "compiles.jsonl")]
    assert len(rows) == w.builds
    labelled = [r for r in rows if r["program"] == "prog_a"]
    assert labelled and all(not r["steady_recompile"] for r in rows)
    assert all(r.get("lower_s", 0) >= 0 for r in rows)
    assert any(r["program"] is None for r in rows)  # the unlabelled builds

    trace = json.load(open(tmp_path / "trace.json"))
    compile_events = [e for e in trace["traceEvents"]
                      if e.get("cat") == "compile"]
    assert len(compile_events) == w.builds
    assert any(e["args"]["program"] == "prog_a" for e in compile_events)
    for e in compile_events:
        assert e["ph"] == "X" and e["dur"] >= 0


@pytest.mark.core
def test_compile_watch_retrace_guard_trips_on_shape_polymorphic_control():
    """The deliberately shape-polymorphic control: same label, new input
    shape each dispatch. Strict mode raises at the dispatch site after the
    warmup window; warn mode emits RetraceWarning and counts; a cold
    window paying several sub-builds (the program + operand fills) is ONE
    warmup unit and never trips."""
    w = CompileWatch(guard="raise").start()
    try:
        f = jax.jit(lambda x: x * 2.0)
        with w.expect("poly"):
            f(jnp.ones(3))  # cold window: program + fill builds — warmup
        with w.expect("poly"):
            f(jnp.ones(3))  # warm window: no builds
        assert w.steady_recompiles == 0
        with pytest.raises(RetraceError, match="steady-state recompilation"):
            with w.expect("poly"):
                f(jnp.ones((4, 4)))  # the retrace
        assert w.steady_recompiles == 1
    finally:
        w.stop()

    w2 = CompileWatch(guard="warn").start()
    try:
        g = jax.jit(lambda x: x + 2.0)
        with w2.expect("poly2"):
            g(jnp.ones(2))
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            with w2.expect("poly2"):
                g(jnp.ones((2, 2)))
        assert any(issubclass(r.category, RetraceWarning) for r in rec)
        assert w2.steady_recompiles >= 1
    finally:
        w2.stop()

    # guard="off" records but never warns/raises; unlabelled builds are
    # never guarded in any mode
    w3 = CompileWatch(guard="off").start()
    try:
        h = jax.jit(lambda x: x - 2.0)
        with w3.expect("poly3"):
            h(jnp.ones(2))
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            with w3.expect("poly3"):
                h(jnp.ones((3, 2)))
            jax.jit(lambda x: x / 2.0)(jnp.ones(5))  # unlabelled
        assert w3.steady_recompiles >= 1  # counted, silently
    finally:
        w3.stop()


@pytest.mark.core
def test_compile_watch_warmup_and_key_variants(tmp_path):
    """``key`` separates legitimate shape variants (the chunked loops'
    remainder chunks): each (name, key) label warms up independently. A
    raised warmup budget allows that many compiling windows."""
    w = CompileWatch(guard="raise").start()
    try:
        f = jax.jit(lambda x: x.sum())
        with w.expect("many", key=4):
            f(jnp.ones(4))
        with w.expect("many", key=2):  # remainder chunk: its own warmup
            f(jnp.ones(2))
        assert w.steady_recompiles == 0
        assert set(w.builds_by_program) >= {"many[4]", "many[2]"}
    finally:
        w.stop()

    w2 = CompileWatch(guard="raise", warmup=2).start()
    try:
        g = jax.jit(lambda x: x.max())
        with w2.expect("p"):
            g(jnp.ones(3))
        with w2.expect("p"):
            g(jnp.ones((2, 3)))  # second compiling window: within warmup=2
        assert w2.steady_recompiles == 0
        with pytest.raises(RetraceError):
            with w2.expect("p"):
                g(jnp.ones((3, 3)))  # third: beyond warmup
    finally:
        w2.stop()


@pytest.mark.core
def test_make_compile_watch_construction_rule(tmp_path):
    """Ledger goes next to the trace when tracing, else next to
    metrics.jsonl; non-main processes never write a ledger; config
    validates the guard mode."""
    from draco_tpu.config import TrainConfig
    from draco_tpu.obs import make_compile_watch

    cfg = TrainConfig(trace_dir=str(tmp_path / "t"),
                      train_dir=str(tmp_path / "d"))
    w = make_compile_watch(cfg, NULL_TRACER, True)
    assert w.path == str(tmp_path / "t" / "compiles.jsonl")
    w.stop()
    w = make_compile_watch(TrainConfig(train_dir=str(tmp_path / "d")),
                           NULL_TRACER, True)
    assert w.path == str(tmp_path / "d" / "compiles.jsonl")
    w.stop()
    w = make_compile_watch(cfg, NULL_TRACER, False)  # non-main process
    assert w.path is None
    w.stop()
    with pytest.raises(ValueError, match="compile_guard"):
        TrainConfig(compile_guard="explode").validate()
    with pytest.raises(ValueError, match="guard"):
        CompileWatch(guard="explode")


# --------------------------------------------------------------------------
# tools/trace_report.py
# --------------------------------------------------------------------------

@pytest.mark.core
def test_trace_report_folds_trace_and_metrics(tmp_path, capsys):
    from tools.trace_report import main, make_report

    events = [
        {"name": "dispatch", "ph": "X", "ts": 0.0, "dur": 3000.0,
         "pid": 1, "tid": 1},
        {"name": "dispatch", "ph": "X", "ts": 4000.0, "dur": 1000.0,
         "pid": 1, "tid": 1},
        {"name": "gather", "ph": "X", "ts": 3000.0, "dur": 500.0,
         "pid": 1, "tid": 2},
        {"name": "prefetch_depth", "ph": "C", "ts": 10.0, "pid": 1,
         "args": {"prefetch_depth": 1}},
    ]
    (tmp_path / "trace.json").write_text(
        json.dumps({"traceEvents": events}))
    with open(tmp_path / "metrics.jsonl", "w") as fh:
        fh.write(json.dumps({"step": 1, "loss": 2.0, "t_fetch": 0.25,
                             "t_comp": 1.0}) + "\n")
        fh.write(json.dumps({"step": 2, "loss": 1.5, "t_fetch": 0.25,
                             "t_comp": 1.0}) + "\n")
        fh.write(json.dumps({"step": 2, "split": "eval", "loss": 1.4})
                 + "\n")

    report = make_report(str(tmp_path / "trace.json"),
                         str(tmp_path / "metrics.jsonl"))
    assert report["traced_wall_ms"] == pytest.approx(5.0)
    d = report["phases"]["dispatch"]
    assert d["count"] == 2 and d["total_ms"] == pytest.approx(4.0)
    assert d["share"] == pytest.approx(0.8)
    assert report["counters"]["prefetch_depth"]["max"] == 1
    assert report["metrics"]["train_records"] == 2
    assert report["metrics"]["t_comp_total_s"] == pytest.approx(2.0)

    out_json = tmp_path / "report.json"
    rc = main([str(tmp_path), "--json", str(out_json)])
    assert rc == 0
    table = capsys.readouterr().out
    assert "dispatch" in table and "80.0%" in table
    assert json.load(open(out_json))["phases"]["gather"]["count"] == 1


@pytest.mark.core
def test_trace_report_surfaces_guard_and_decode_health(tmp_path, capsys):
    """The jax-free report header folds the PR 6 guard columns (cumulative
    trips/skips) and the run's decode-health precision/recall from the
    per-step counts — previously invisible to this path — and validates
    the status.json schema version when one is present."""
    from draco_tpu.obs import STATUS_SCHEMA
    from tools.trace_report import fold_status, main, make_report

    events = [{"name": "dispatch", "ph": "X", "ts": 0.0, "dur": 1000.0,
               "pid": 1, "tid": 1}]
    (tmp_path / "trace.json").write_text(json.dumps(
        {"traceEvents": events}))
    with open(tmp_path / "metrics.jsonl", "w") as fh:
        fh.write(json.dumps({"step": 1, "loss": 2.0, "guard_trips": 0.0,
                             "skipped_steps": 0.0, "located_errors": 1.0,
                             "det_tp": 1.0, "det_adv": 1.0}) + "\n")
        fh.write(json.dumps({"step": 2, "loss": 9.0, "guard_trips": 2.0,
                             "skipped_steps": 1.0, "located_errors": 2.0,
                             "det_tp": 1.0, "det_adv": 1.0}) + "\n")
    (tmp_path / "status.json").write_text(json.dumps(
        {"schema": STATUS_SCHEMA, "state": "done", "step": 2}))

    report = make_report(str(tmp_path / "trace.json"),
                         str(tmp_path / "metrics.jsonl"))
    m = report["metrics"]
    assert m["guard_trips"] == 2.0 and m["skipped_steps"] == 1.0
    assert m["det_precision"] == round(2 / 3, 4)  # rounded in the fold
    assert m["det_recall"] == 1.0
    assert report["run_status"]["schema"] == STATUS_SCHEMA
    rc = main([str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "guard: trips=2 skipped_steps=1" in out
    assert "decode health: precision=0.6667 recall=1.0000" in out

    # an unknown schema version is a loud failure, not a silent misfold
    (tmp_path / "status.json").write_text(json.dumps(
        {"schema": 99, "state": "done"}))
    with pytest.raises(SystemExit, match="schema 99"):
        fold_status(str(tmp_path / "status.json"))


@pytest.mark.core
def test_trace_report_tolerates_partial_artifacts(tmp_path, capsys):
    """A killed run's leftovers must still fold: missing metrics.jsonl,
    then an empty one, then one with a torn tail line — and the tracer's
    droppedEvents count is surfaced in the header instead of silently
    omitted (the trace is a sliding window when it's nonzero)."""
    from tools.trace_report import main, make_report

    events = [{"name": "dispatch", "ph": "X", "ts": 0.0, "dur": 1000.0,
               "pid": 1, "tid": 1}]
    (tmp_path / "trace.json").write_text(json.dumps(
        {"traceEvents": events, "droppedEvents": 123}))

    # missing metrics.jsonl
    report = make_report(str(tmp_path / "trace.json"),
                         str(tmp_path / "metrics.jsonl"))
    assert "metrics" not in report
    assert report["dropped_events"] == 123
    rc = main([str(tmp_path)])
    assert rc == 0
    head = capsys.readouterr().out.splitlines()[0]
    assert "DROPPED EVENTS: 123" in head

    # empty metrics.jsonl
    (tmp_path / "metrics.jsonl").write_text("")
    report = make_report(str(tmp_path / "trace.json"),
                         str(tmp_path / "metrics.jsonl"))
    assert report["metrics"]["train_records"] == 0

    # torn tail line (run killed mid-write) + blank lines
    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "loss": 2.0}) + "\n\n"
        + '{"step": 2, "los')
    report = make_report(str(tmp_path / "trace.json"),
                         str(tmp_path / "metrics.jsonl"))
    assert report["metrics"]["train_records"] == 1
    # a clean trace reports dropped_events == 0 and no header warning
    (tmp_path / "trace.json").write_text(json.dumps(
        {"traceEvents": events}))
    rc = main([str(tmp_path)])
    assert rc == 0
    head = capsys.readouterr().out.splitlines()[0]
    assert "DROPPED" not in head


# --------------------------------------------------------------------------
# obs/numerics.py — the wire & numerics observatory (ISSUE 10)
# --------------------------------------------------------------------------

@pytest.mark.core
def test_numerics_stage_columns_and_exponent_histogram():
    """Known tensor -> exact range stats: absmax/rms over finite elements,
    threshold fractions over all elements, exponent-bin fractions summing
    to the finite-nonzero fraction."""
    from draco_tpu.obs import numerics as nx

    x = jnp.asarray([1.0, -2.0, 0.5, 0.0, 2.0 ** -20, 2.0 ** 10,
                     -(2.0 ** -30), 300.0], jnp.float32)
    cols = {k: float(v) for k, v in nx.stage_columns("wire", [x],
                                                     block=4).items()}
    assert cols["nx_wire_absmax"] == pytest.approx(1024.0)
    assert cols["nx_wire_rms"] == pytest.approx(
        float(np.sqrt(np.mean(np.square(np.asarray(x))))), rel=1e-6)
    # bf16 shares f32's exponent range, so only f32 SUBNORMALS sit under
    # the bf16 subnormal minimum — and XLA:CPU flushes those to zero
    # before the stats see them, so the honest count here is 0 (the
    # column matters on non-FTZ backends and for future narrower dtypes)
    assert cols["nx_wire_uf_bf16"] == 0.0
    assert cols["nx_wire_of_bf16"] == 0.0
    assert cols["nx_wire_nonfinite"] == 0.0
    # exponent bins cover the finite nonzero elements exactly
    hist = sum(cols[f"nx_wire_exp{i}"] for i in range(nx.NUM_EXP_BINS))
    assert hist == pytest.approx(7 / 8)  # one exact zero excluded
    assert cols["nx_wire_exp5"] == pytest.approx(2 / 8)  # 2^10 and 300
    assert cols["nx_wire_exp1"] == pytest.approx(2 / 8)  # 2^-20, 2^-30
    # int8 underflow threshold is per 4-element block: in block [1,-2,.5,0]
    # nothing sits under absmax/254; in block [2^-20, 2^10, -2^-30, 300]
    # the two tiny values round to zero at scale 1024/127
    assert cols["nx_wire_uf_int8"] == pytest.approx(2 / 8)


@pytest.mark.core
def test_numerics_columns_nan_safe_sentinels():
    """An injected NaN/Inf never reaches a stats column: absmax/rms mask
    to the finite elements, the fractions stay in [0, 1], and the
    nonfinite fraction carries the fault signal (the chaos-matrix
    NaN-safety contract)."""
    from draco_tpu.obs import numerics as nx

    x = jnp.asarray([[1.0, float("nan"), 2.0, float("inf")],
                     [0.5, 1.5, -1.0, 3.0]], jnp.float32)
    cols = {k: float(v) for k, v in nx.stage_columns("grad", [x],
                                                     block=4).items()}
    assert all(np.isfinite(v) for v in cols.values()), cols
    assert cols["nx_grad_nonfinite"] == pytest.approx(2 / 8)
    assert cols["nx_grad_absmax"] == pytest.approx(3.0)
    # all-nonfinite input still yields finite sentinels
    bad = jnp.full((4,), float("nan"), jnp.float32)
    cols = {k: float(v) for k, v in nx.stage_columns("agg", [bad],
                                                     block=4).items()}
    assert all(np.isfinite(v) for v in cols.values()), cols
    assert cols["nx_agg_nonfinite"] == 1.0 and cols["nx_agg_absmax"] == 0.0


@pytest.mark.core
def test_quantize_rows_bf16_int8_and_row_identity():
    """bf16 nearest == the astype round trip; int8 per-block error is
    bounded by half an LSB of the block scale; bitwise-identical rows
    quantize bitwise-identically under BOTH rounding modes (maj_vote's
    soundness condition); stochastic rounding is deterministic per key."""
    from draco_tpu.obs import numerics as nx

    rs = np.random.RandomState(7)
    x = jnp.asarray(rs.randn(3, 40).astype(np.float32) * 10.0)
    qb = nx.quantize_rows(x, "bf16")
    np.testing.assert_array_equal(
        np.asarray(qb),
        np.asarray(x.astype(jnp.bfloat16).astype(jnp.float32)))
    qi = np.asarray(nx.quantize_rows(x, "int8", block=16))
    xn = np.asarray(x)
    # per-(row, 16-block) scale: |err| <= scale/2 = absmax/254
    for r in range(3):
        for b0 in range(0, 40, 16):
            blk = xn[r, b0:b0 + 16]
            scale = np.abs(blk).max() / 127.0
            assert np.max(np.abs(qi[r, b0:b0 + 16] - blk)) <= scale / 2 + 1e-7
    # identical rows stay identical (shared noise draw across rows)
    import jax as _jax

    same = jnp.broadcast_to(x[0], (4, 40))
    key = _jax.random.key(3)
    for mode in ("bf16", "int8"):
        q = np.asarray(nx.quantize_rows(same, mode, block=16, key=key))
        assert all(np.array_equal(q[0], q[i]) for i in range(4))
        q2 = np.asarray(nx.quantize_rows(same, mode, block=16, key=key))
        np.testing.assert_array_equal(q, q2)  # keyed == deterministic
    # int8 of a non-finite input maps to 0 (no NaN encoding on an integer
    # wire); bf16 keeps the NaN (bf16 has one)
    bad = jnp.asarray([[1.0, float("nan")]], jnp.float32)
    assert np.asarray(nx.quantize_rows(bad, "int8", block=2))[0, 1] == 0.0
    assert np.isnan(np.asarray(nx.quantize_rows(bad, "bf16"))[0, 1])


@pytest.mark.core
def test_wire_ledger_arithmetic():
    """Logical bytes ledger: cyclic ships re+im (2 words/element), others
    one; int8 adds one f32 scale per block; per-step = n x per-worker."""
    from draco_tpu.config import TrainConfig
    from draco_tpu.obs import numerics as nx

    cfg = TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                      shadow_block=256)
    led = nx.wire_ledger(cfg, 1000)
    per = led["bytes_per_worker"]
    assert per["f32"] == 2 * 4 * 1000
    assert per["bf16"] == 2 * 2 * 1000
    assert per["int8"] == 2 * 1000 + 4 * 2 * 4  # 4 blocks of 256 per half
    assert led["bytes_per_step"] == {k: v * 8 for k, v in per.items()}
    cfg2 = TrainConfig(approach="maj_vote", group_size=4, num_workers=8)
    led2 = nx.wire_ledger(cfg2, 1000)
    assert led2["bytes_per_worker"]["f32"] == 4 * 1000


@pytest.mark.core
def test_shadow_columns_sentinel_and_agreement():
    """A fault-poisoned shadow comparison lands at the finite sentinel
    (-1.0), never NaN; flag agreement counts present workers only and the
    shadow detection counts score against the seeded truth."""
    from draco_tpu.obs import numerics as nx

    agg = jnp.asarray([1.0, 2.0], jnp.float32)
    flags = jnp.asarray([False, True, False, False])
    sflags = jnp.asarray([False, True, True, False])
    present = jnp.asarray([True, True, True, False])
    adv = jnp.asarray([False, True, False, False])
    cols = nx.shadow_columns(agg, agg * 1.01, 1e-3, flags, sflags, adv,
                             present)
    vals = {k: float(v) for k, v in cols.items()}
    assert vals["shadow_err"] == pytest.approx(0.01, rel=1e-3)
    # worker 2 disagrees; worker 3 is absent and does not count
    assert vals["shadow_flag_agree"] == pytest.approx(2 / 3)
    assert vals["shadow_det_flagged"] == 2.0 and vals["shadow_det_tp"] == 1.0
    poisoned = nx.shadow_columns(
        jnp.asarray([float("nan"), 1.0]), agg, float("nan"), flags, sflags,
        adv, present)
    assert float(poisoned["shadow_err"]) == nx.SHADOW_SENTINEL
    assert float(poisoned["shadow_residual"]) == nx.SHADOW_SENTINEL


@pytest.mark.core
def test_heartbeat_numerics_and_wire_blocks(tmp_path):
    """The heartbeat folds nx_/shadow_ columns into the ``numerics``
    status block (last values, running max of the danger fractions,
    running MIN of the flag agreement) and carries the static ``wire``
    ledger stamped via set_wire — both under the current schema."""
    from draco_tpu.obs import STATUS_SCHEMA

    hb = RunHeartbeat(str(tmp_path))
    hb.set_wire({"family": "cyclic", "dim": 10,
                 "bytes_per_worker": {"f32": 80, "bf16": 40, "int8": 14}})
    hb.observe({"step": 1, "loss": 1.0, "nx_wire_absmax": 5.0,
                "nx_wire_rms": 1.0, "nx_wire_uf_int8": 0.1,
                "nx_grad_nonfinite": 0.0, "shadow_err": 0.01,
                "shadow_flag_agree": 1.0})
    hb.observe({"step": 2, "loss": 0.9, "nx_wire_absmax": 4.0,
                "nx_wire_rms": 0.9, "nx_wire_uf_int8": 0.3,
                "nx_grad_nonfinite": 0.0, "shadow_err": 0.002,
                "shadow_flag_agree": 0.5})
    payload = hb.beat(2, 4)
    assert payload["schema"] == STATUS_SCHEMA == 5
    assert payload["wire"]["bytes_per_worker"]["bf16"] == 40
    nxb = payload["numerics"]
    assert nxb["nx_wire_absmax"] == 4.0  # last value
    assert nxb["nx_wire_uf_int8_max"] == pytest.approx(0.3)  # running max
    assert nxb["shadow_err_max"] == pytest.approx(0.01)
    assert nxb["shadow_flag_agree_min"] == pytest.approx(0.5)  # running min
    # a fault-poisoned shadow comparison (the -1.0 sentinel) is COUNTED,
    # never folded into the extremes — shadow_err_max must not hide it
    hb.observe({"step": 3, "loss": 2.0, "shadow_err": -1.0,
                "shadow_residual": -1.0, "shadow_flag_agree": -1.0})
    nxb = hb.beat(3, 4)["numerics"]
    assert nxb["shadow_err_max"] == pytest.approx(0.01)  # sentinel excluded
    assert nxb["shadow_flag_agree_min"] == pytest.approx(0.5)
    assert nxb["shadow_sentinel_steps"] == 1
    # watch-free runs carry neither block
    hb2 = RunHeartbeat(str(tmp_path / "plain"))
    hb2.observe({"step": 1, "loss": 1.0})
    p2 = hb2.beat(1, 2)
    assert "numerics" not in p2 and "wire" not in p2


def test_numerics_nan_fault_live_columns_finite(tmp_path):
    """Live NaN-safety pin (ISSUE 10 satellite): under an injected
    nan_grad fault the numerics columns carry finite sentinels, the
    nonfinite-fraction column goes loud at the fault step, the rest of
    the metric block still parses, and the step guard trips."""
    from draco_tpu.config import TrainConfig
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.trainer import Trainer

    d = str(tmp_path / "run")
    ds = load_dataset("synthetic-mnist", synthetic_train=128,
                      synthetic_test=32)
    cfg = TrainConfig(network="FC", dataset="synthetic-mnist", batch_size=4,
                      num_workers=8, approach="cyclic", worker_fail=1,
                      err_mode="rev_grad", redundancy="shared", max_steps=5,
                      eval_freq=0, train_dir=d, log_every=1, step_guard="on",
                      numerics_watch="on", shadow_wire="bf16",
                      fault_spec="nan_grad@3:w2", steps_per_call=5)
    tr = Trainer(cfg, mesh=make_mesh(8), dataset=ds, quiet=True)
    tr.run()
    tr.close()
    recs = [json.loads(l) for l in open(tmp_path / "run" / "metrics.jsonl")]
    train = [r for r in recs if "loss" in r and r.get("split") != "eval"]
    assert [r["step"] for r in train] == [1, 2, 3, 4, 5]
    for r in train:
        for k, v in r.items():
            if k.startswith(("nx_", "shadow_")):
                assert np.isfinite(v), (r["step"], k, v)
    fault = train[2]
    assert fault["nx_grad_nonfinite"] > 0.0  # the fault is VISIBLE
    assert fault["guard_trips"] >= 1.0 and fault["skipped_steps"] == 1.0
    # shadow comparison at the fault step degrades to the sentinel or a
    # finite value — never NaN (columns asserted finite above); clean
    # steps stay pristine
    clean = [r for r in train if r["step"] != 3]
    assert all(r["nx_grad_nonfinite"] == 0.0 for r in clean)
    assert all(r["guard_trips"] == 0.0 for r in clean)
    status = json.load(open(tmp_path / "run" / "status.json"))
    assert status["numerics"]["nx_grad_nonfinite_max"] > 0.0
    assert status["wire"]["family"] == "cyclic"
