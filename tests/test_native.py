"""Native runtime library: decoder-core parity, compression, batch loader.

The reference's native surface is the pybind11/Eigen error-locator solve
(reference: src/c_coding.cpp:15-84) called per layer per step from
cyclic_master.py:157. Ours is a C-ABI library (native/*.cpp) whose decode
must agree with the jit decode path — these tests pin that equivalence plus
the compression format and the gather engine the trainer prefetches with.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from draco_tpu import native
from draco_tpu.coding.cyclic import build_cyclic_code, decode
from draco_tpu.utils import compress as dcomp

needs_native = pytest.mark.skipif(
    not native.AVAILABLE, reason=f"native build unavailable: {native.BUILD_ERROR}"
)


def _corrupt_rows(rng, R, rows, scale=100.0):
    R = R.copy()
    for r in rows:
        R[r] += scale * (rng.normal(size=R.shape[1]) + 1j * rng.normal(size=R.shape[1]))
    return R


@needs_native
@pytest.mark.parametrize("n,s", [(9, 2), (8, 1), (15, 3)])
def test_solve_poly_a_locates_corrupt_rows(n, s):
    rng = np.random.default_rng(1)
    code = build_cyclic_code(n, s)
    g = rng.normal(size=(n, 64)).astype(np.float32)
    R = _corrupt_rows(rng, code.w_full @ g, rows=list(range(1, 1 + s)))
    e = R @ rng.normal(size=64)
    alpha = native.solve_poly_a(n, s, e)
    z = np.exp(2j * np.pi * np.arange(n) / n)
    p = z**s - sum(alpha[j] * z**j for j in range(s))
    mags = np.abs(p)
    corrupt = set(range(1, 1 + s))
    located = set(np.argsort(mags)[:s])
    assert located == corrupt
    # clear separation: corrupt-row magnitudes far below every honest row's
    honest_min = min(m for i, m in enumerate(mags) if i not in corrupt)
    assert mags[sorted(corrupt)].max() < 1e-2 * honest_min


@needs_native
@pytest.mark.parametrize("n,s,rows", [(9, 2, (1, 5)), (9, 2, (4,)), (9, 2, ()), (8, 1, (7,))])
def test_native_decode_matches_jnp_decode(n, s, rows):
    rng = np.random.default_rng(2)
    d = 3000
    code = build_cyclic_code(n, s)
    g = rng.normal(size=(n, d)).astype(np.float32)
    R = _corrupt_rows(rng, code.w_full @ g, rows)
    f = rng.normal(size=d)

    out_c, honest_c = native.cyclic_decode_host(n, s, R, f)
    out_j, honest_j = decode(
        code,
        jnp.asarray(R.real, jnp.float32),
        jnp.asarray(R.imag, jnp.float32),
        jnp.asarray(f, jnp.float32),
    )
    truth = g.sum(0) / n
    np.testing.assert_allclose(out_c, truth, atol=5e-5)
    np.testing.assert_allclose(np.asarray(out_j), truth, atol=5e-5)
    # every actually-corrupt row must be flagged by both decoders (masks may
    # differ on spurious locator roots when fewer than s rows are corrupt)
    for r in rows:
        assert not honest_c[r]
        assert not np.asarray(honest_j)[r]
    if len(rows) == s:  # well-determined: masks agree exactly
        assert np.array_equal(honest_c, np.asarray(honest_j))


@needs_native
def test_decode_zero_gradient_syndrome():
    # all-zero gradients: syndrome vanishes, locator system is rank-deficient —
    # the ridge path (reference used SVD lstsq, c_coding.cpp:81) must not blow up
    n, s, d = 9, 2, 128
    code = build_cyclic_code(n, s)
    R = code.w_full @ np.zeros((n, d))
    out, honest = native.cyclic_decode_host(n, s, R, np.ones(d))
    np.testing.assert_allclose(out, 0.0, atol=1e-6)


def test_compress_roundtrip_all_dtypes():
    rng = np.random.default_rng(3)
    for dtype in (np.float32, np.float64, np.complex64, np.int32, np.uint8):
        a = rng.normal(size=(37, 11)) * 10
        arr = (a + 1j * a if np.issubdtype(dtype, np.complexfloating) else a).astype(dtype)
        buf = dcomp.compress(arr, level=3)
        out = dcomp.decompress(buf)
        assert out.dtype == arr.dtype and out.shape == arr.shape
        assert np.array_equal(out, arr)


def test_compress_smooth_gradients_actually_shrink():
    # structured (gradient-like) data: shuffle+deflate should win clearly
    x = np.linspace(0, 1, 200_000, dtype=np.float32).reshape(100, 2000)
    buf = dcomp.compress(x, level=3)
    assert len(buf) < 0.5 * x.nbytes


@needs_native
def test_compress_backends_byte_identical():
    rng = np.random.default_rng(4)
    arr = rng.normal(size=(64, 129)).astype(np.float32)
    buf_native = dcomp.compress(arr, level=2)
    native.AVAILABLE = False
    try:
        buf_py = dcomp.compress(arr, level=2)
        out = dcomp.decompress(buf_native)  # python path reads native bytes
    finally:
        native.AVAILABLE = True
    assert buf_native == buf_py
    assert np.array_equal(out, arr)
    assert np.array_equal(dcomp.decompress(buf_py), arr)


@needs_native
def test_batch_loader_gathers_and_overlaps():
    rng = np.random.default_rng(5)
    src = rng.normal(size=(256, 8, 8, 3)).astype(np.float32)
    L = native.BatchLoader(3)
    try:
        tickets = []
        idxs = [rng.integers(0, 256, size=32) for _ in range(6)]
        for idx in idxs:  # several outstanding at once
            tickets.append(L.submit(src, idx))
        for t, idx in zip(tickets, idxs):
            assert np.array_equal(L.wait(t), src[idx])
    finally:
        L.close()


def test_prefetcher_matches_sync_batches():
    from draco_tpu.data import batching
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.data.prefetch import BatchPrefetcher

    ds = load_dataset("synthetic-mnist", synthetic_train=96, synthetic_test=8)
    n_w, bs, seed = 4, 8, 428

    def indices_fn(step):
        return batching.indices_baseline(len(ds), step - 1, n_w, bs, seed)

    pf = BatchPrefetcher(ds, indices_fn, n_w, bs)
    try:
        for step in (1, 2, 3, 7, 8):  # includes a non-sequential jump
            x, y = pf.get(step)
            xr, yr = batching.worker_batches_baseline(ds, step - 1, n_w, bs, seed)
            assert np.array_equal(x, xr) and np.array_equal(y, yr)
    finally:
        pf.close()


@needs_native
@pytest.mark.parametrize("n,s,adv,missing", [
    (9, 2, (), (1, 5, 7)),     # erasure-only, e <= 2s
    (9, 2, (3,), (7,)),        # joint t + e <= s
])
def test_native_erasure_decode_matches_jnp(n, s, adv, missing):
    from draco_tpu.attacks import inject_cyclic

    rng = np.random.default_rng(9)
    d = 2000
    code = build_cyclic_code(n, s)
    g = rng.normal(size=(n, d)).astype(np.float32)
    from draco_tpu.coding.cyclic import encode
    enc_re, enc_im = encode(code, jnp.asarray(g[code.batch_ids]))
    adv_mask = np.zeros(n, dtype=bool); adv_mask[list(adv)] = True
    enc_re, enc_im = inject_cyclic(enc_re, enc_im, jnp.asarray(adv_mask), "rev_grad")
    present = np.ones(n, dtype=bool); present[list(missing)] = False
    R = (np.asarray(enc_re) + 1j * np.asarray(enc_im)) * present[:, None]
    f = rng.normal(size=d)

    out_c, used_c = native.cyclic_decode_host(n, s, R, f, present=present)
    out_j, used_j = decode(
        code,
        jnp.asarray(R.real, jnp.float32), jnp.asarray(R.imag, jnp.float32),
        jnp.asarray(f, jnp.float32), present=jnp.asarray(present),
    )
    truth = g.sum(0) / n
    np.testing.assert_allclose(out_c, truth, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out_j), truth, atol=1e-4)
    for r in (*adv, *missing):
        assert not used_c[r] and not np.asarray(used_j)[r]


def test_compress_preserves_scalar_and_noncontiguous_shapes():
    """Regression: ascontiguousarray promotes 0-d arrays to (1,), which broke
    compressed checkpoints of scalar leaves (e.g. the step counter)."""
    from draco_tpu.utils import compress as c

    for a in [np.asarray(True), np.asarray(3, np.int32),
              np.arange(6, dtype=np.float32).reshape(2, 3)[:, ::2]]:
        b = c.decompress(c.compress(a))
        assert b.shape == a.shape and b.dtype == a.dtype
        np.testing.assert_array_equal(b, a)
