"""The driver's entry points must keep working: entry() compiles single-chip;
dryrun_multichip(N) jits the full coded training step plus every 2-D
(w × sp/tp/pp/ep) composition over an N-device mesh and executes one step.

Run in a subprocess because dryrun_multichip pins the device count / platform
at backend init, which must not leak into this process (conftest pins 8)."""

import os
import subprocess
import sys

import pytest


@pytest.mark.parametrize("n", [16])
def test_dryrun_multichip_subprocess(n):
    """VERDICT round-1 item 9: exercise the sharding envelope at n beyond the
    reference's 8-worker cluster (w=8 rows make every 2-D composition run
    approach=cyclic with a live adversary; the 1-D path runs s=3)."""
    env = dict(os.environ)
    # the conftest pins an 8-device mesh via XLA_FLAGS (and a shell may pin
    # JAX_PLATFORMS); dryrun_multichip must choose both itself
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c",
         f"import __graft_entry__ as g; g.dryrun_multichip({n})"],
        capture_output=True, text=True, timeout=560, env=env, cwd=repo,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = r.stdout
    assert f"dryrun_multichip({n}): approach=cyclic ok" in out
    for axis in ("sp", "tp", "pp", "ep"):
        assert f"× {axis}=2) approach=cyclic" in out, (axis, out)


def test_entry_compiles():
    """entry() must lower and compile standalone (single chip / CPU)."""
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    jax.jit(fn).lower(*args).compile()
