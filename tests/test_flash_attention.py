"""Flash-attention kernel parity (interpret mode in CI; real lowering is
exercised by tools/tpu_attn_check.py on hardware).

Oracle: parallel/ring_attention.dense_attention — the streaming-softmax
reference the ring path is tested against. Forward values AND input
gradients must match: the backward pass is a hand-written two-kernel
custom VJP, the most bug-prone part."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import draco_tpu.ops.flash_attention as fa
from draco_tpu.ops.flash_attention import flash_attention
from draco_tpu.parallel.ring_attention import dense_attention


def _qkv(rng, b=2, t=256, h=2, dh=64):
    shape = (b, t, h, dh)
    return (jnp.asarray(rng.normal(size=shape).astype(np.float32)),
            jnp.asarray(rng.normal(size=shape).astype(np.float32)),
            jnp.asarray(rng.normal(size=shape).astype(np.float32)))


@pytest.mark.parametrize("dh", [64, 128])
def test_forward_matches_dense(rng, dh):
    q, k, v = _qkv(rng, dh=dh)
    want = dense_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, force=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("bq,bk", [(128, 256), (256, 128), (64, 32)])
def test_forward_uneven_blocks(rng, bq, bk):
    """T spanning several q/k blocks with bq != bk — both directions: the
    block-skip predicate must compare positions, not block indices (bq > bk
    regressed to dropping valid past keys)."""
    q, k, v = _qkv(rng, t=512, dh=64)
    want = dense_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, block_q=bq, block_k=bk, force=True,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_grads_uneven_blocks(rng):
    """bq > bk through the custom VJP (both backward kernels' predicates)."""
    q, k, v = _qkv(rng, t=256, dh=64)
    tgt = jnp.asarray(rng.normal(size=q.shape).astype(np.float32))

    def loss(attn):
        return lambda q, k, v: jnp.sum((attn(q, k, v) - tgt) ** 2)

    flash = lambda q, k, v: flash_attention(q, k, v, block_q=128, block_k=64,
                                            force=True, interpret=True)
    dense = lambda q, k, v: dense_attention(q, k, v, causal=True)
    g_f = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss(dense), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_f, g_d):
        a, b = np.asarray(a), np.asarray(b)
        scale = max(np.abs(b).max(), 1e-8)
        np.testing.assert_allclose(a / scale, b / scale, atol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_grads_match_dense(rng):
    q, k, v = _qkv(rng, t=256, dh=64)
    tgt = jnp.asarray(rng.normal(size=q.shape).astype(np.float32))

    def loss(attn):
        def f(q, k, v):
            o = attn(q, k, v)
            return jnp.sum((o - tgt) ** 2)
        return f

    flash = lambda q, k, v: flash_attention(q, k, v, force=True, interpret=True)
    dense = lambda q, k, v: dense_attention(q, k, v, causal=True)
    g_flash = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss(dense), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_flash, g_dense):
        a, b = np.asarray(a), np.asarray(b)
        scale = max(np.abs(b).max(), 1e-8)
        np.testing.assert_allclose(a / scale, b / scale, atol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_flash_through_model_matches_dense(rng, monkeypatch):
    """attn_impl=flash through the full sp-path train step (interpret-mode
    kernel forced) reproduces the dense step's loss and update — the kernel's
    custom VJP is exercised inside jax.grad of the whole model."""
    import functools

    from draco_tpu import ops
    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel import make_mesh_2d
    from draco_tpu.parallel.sp_step import build_sp_train_setup, synthetic_text

    import draco_tpu.ops.flash_attention as fa

    monkeypatch.setattr(
        fa, "flash_attention",
        functools.partial(fa.flash_attention.__wrapped__
                          if hasattr(fa.flash_attention, "__wrapped__")
                          else fa.flash_attention, force=True, interpret=True),
    )

    def cfg(attn):
        return TrainConfig(
            network="TransformerLM", dataset="synthetic-text", batch_size=2,
            num_workers=2, approach="baseline", mode="normal", worker_fail=0,
            seq_len=256, vocab=32, model_dim=32, model_heads=2, model_layers=1,
            attn_impl=attn, max_steps=1, eval_freq=0, train_dir="",
            log_every=1000,
        )

    mesh = make_mesh_2d(2, 1)
    toks = jnp.asarray(synthetic_text(428, 1, 2, 2, 256, 32))
    adv = np.zeros(2, dtype=bool)
    s_d = build_sp_train_setup(cfg("dense"), mesh)
    s_f = build_sp_train_setup(cfg("flash"), mesh)
    st_d, m_d = s_d.train_step(s_d.state, toks, adv)
    st_f, m_f = s_f.train_step(s_f.state, toks, adv)
    assert float(m_d["loss"]) == pytest.approx(float(m_f["loss"]), rel=1e-5)
    a = np.asarray(jax.device_get(st_d.params["embed"]["embedding"]))
    b = np.asarray(jax.device_get(st_f.params["embed"]["embedding"]))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_a2a_flash_inner_matches_dense(rng, monkeypatch):
    """Ulysses + flash: sp=4 head-scatter with the interpret-mode kernel as
    the inner attention reproduces the dense a2a step exactly."""
    import functools

    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel import make_mesh_2d
    from draco_tpu.parallel.sp_step import build_sp_train_setup, synthetic_text

    import draco_tpu.ops.flash_attention as fa

    orig = fa.flash_attention
    monkeypatch.setattr(
        fa, "flash_attention",
        functools.partial(orig, force=True, interpret=True),
    )

    def cfg(attn):
        return TrainConfig(
            network="TransformerLM", dataset="synthetic-text", batch_size=2,
            num_workers=2, approach="baseline", mode="normal", worker_fail=0,
            seq_shards=4, sp_attn="a2a", seq_len=256, vocab=32, model_dim=32,
            model_heads=4, model_layers=1, attn_impl=attn, max_steps=1,
            eval_freq=0, train_dir="", log_every=1000,
        )

    mesh = make_mesh_2d(2, 4)
    toks = jnp.asarray(synthetic_text(428, 1, 2, 2, 256, 32))
    adv = np.zeros(2, dtype=bool)
    s_d = build_sp_train_setup(cfg("dense"), mesh)
    s_f = build_sp_train_setup(cfg("flash"), mesh)
    st_d, m_d = s_d.train_step(s_d.state, toks, adv)
    st_f, m_f = s_f.train_step(s_f.state, toks, adv)
    assert float(m_d["loss"]) == pytest.approx(float(m_f["loss"]), rel=1e-5)
    a = np.asarray(jax.device_get(st_d.params["embed"]["embedding"]))
    b = np.asarray(jax.device_get(st_f.params["embed"]["embedding"]))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_flash_ring_trains(rng):
    """sp_attn=ring + attn_impl=flash is a supported composition
    (ring_flash_attention): the sp training step runs and learns."""
    import numpy as np

    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel import make_mesh_2d
    from draco_tpu.parallel.sp_step import train_sp

    cfg = TrainConfig(
        network="TransformerLM", dataset="synthetic-text", batch_size=2,
        num_workers=4, approach="baseline", mode="normal", worker_fail=0,
        seq_len=16, vocab=32, model_dim=32, model_heads=2, model_layers=1,
        seq_shards=2, sp_attn="ring", attn_impl="flash", max_steps=30,
        eval_freq=0, train_dir="", log_every=1000,
    )
    cfg.validate()  # previously rejected; now a first-class path
    mesh = make_mesh_2d(4, 2)
    state, metrics = train_sp(cfg, mesh, steps=30, quiet=True)
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["loss"]) < 3.0  # learned; uniform would be ln(32)=3.47


def test_fallback_off_tpu(rng):
    """Without force, non-TPU backends and non-tiling shapes take the dense
    path and still produce correct causal attention."""
    q, k, v = _qkv(rng, t=100, dh=48)  # 100 doesn't tile, 48 < lane
    want = dense_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_force_true_raises_on_non_tiling_shape(rng):
    """force=True demands the O(T·Dh) kernel; a shape that cannot tile must
    raise instead of silently running the dense O(T²) path (advisor r2)."""
    q, k, v = _qkv(rng, t=100, dh=48)  # t=100 doesn't tile
    with pytest.raises(ValueError, match="does not tile"):
        flash_attention(q, k, v, force=True)


def test_interpret_fallback_warns_once(rng):
    """interpret=True wants the kernel; a non-tiling shape falls back to
    dense with a one-time warning per shape."""
    import warnings as _w

    q, k, v = _qkv(rng, t=100, dh=48)
    fa._FALLBACK_WARNED.clear()
    with pytest.warns(UserWarning, match="falling back to dense"):
        flash_attention(q, k, v, interpret=True)
    with _w.catch_warnings():
        _w.simplefilter("error")  # second call with same shape: silent
        flash_attention(q, k, v, interpret=True)


def test_fit_block_keeps_non_default_lengths_eligible():
    """The tuned defaults (bq=512, bk=1024) must not demote lengths that
    tiled under the old 128-block defaults: _fit_block shrinks to the
    largest block that divides t (sublane- and lane-tile legal), so e.g.
    t=768/1536/2560 stay kernel-eligible instead of silently riding the
    dense fallback (r5 review finding)."""
    for t, want_bq, want_bk in [(768, 384, 768), (1536, 512, 768),
                                (2560, 512, 640), (2048, 512, 1024),
                                (256, 256, 256)]:
        bq = fa._fit_block(512, t, lane_rule=False)
        bk = fa._fit_block(1024, t, lane_rule=True)
        assert (bq, bk) == (want_bq, want_bk), (t, bq, bk)
        assert fa._kernel_eligible(t, bq, bk, 64, True, False)
    # no legal block => 0, and eligibility rejects instead of dividing by 0
    assert fa._fit_block(512, 12, lane_rule=False) == 0
    with pytest.raises(ValueError, match="does not tile"):
        flash_attention(*_qkv(np.random.RandomState(0), t=12, dh=64)[:3],
                        force=True)


def test_default_blocks_parity_t768(rng):
    """Interpret-mode parity at t=768 with DEFAULT blocks — the length the
    plain min() clamp would have broken (768 % 1024 != 0): exercises the
    divisor-aware shrink end-to-end through the public entry."""
    q, k, v = _qkv(rng, b=1, t=768, h=1, dh=64)
    want = dense_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, force=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_chip_study_shape_parity_interpret(rng):
    """Interpret-mode parity at the exact shape the hardware study runs
    first (tools/chip_jobs_r3.sh: T=1024, dh=64) — catches shape-dependent
    kernel logic bugs before the one-client tunnel is spent on them."""
    q, k, v = _qkv(rng, b=1, t=1024, h=1, dh=64)
    want = dense_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, force=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_bf16_inputs_match_dense_f32(rng):
    """bf16 q/k/v ride the MXU fast pass (matmuls in input dtype, f32
    accumulate); values must still track the f32 dense oracle to bf16
    precision, fwd and grads."""
    q, k, v = _qkv(rng, t=256, dh=64)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    want = dense_attention(q, k, v, causal=True)
    got = flash_attention(qb, kb, vb, force=True, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32), np.asarray(want),
                               rtol=0.05, atol=0.05)

    def loss(attn, *xs):
        return jnp.sum(jnp.sin(attn(*xs).astype(jnp.float32)))

    g_f = jax.grad(
        lambda q, k, v: loss(
            lambda *a: flash_attention(*a, force=True, interpret=True),
            q, k, v),
        argnums=(0, 1, 2))(qb, kb, vb)
    g_d = jax.grad(
        lambda q, k, v: loss(
            lambda *a: dense_attention(*a, causal=True), q, k, v),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_f, g_d):
        a = np.asarray(a, np.float32)
        b = np.asarray(b)
        scale = max(np.abs(b).max(), 1e-8)
        np.testing.assert_allclose(a / scale, b / scale, atol=0.06,
                                   err_msg=f"d{name} mismatch")


def test_tpu_lowering_clean_and_control():
    """The kernel must pass the Pallas TPU *lowering* — the stage every
    recorded hardware failure came from (tpu_attn.json (8,128)-tiling
    errors) — via cross-platform export on the CPU host, and a
    deliberately mis-tiled pallas_call must still raise there (negative
    control: proves the check is exercised, not skipped). Full shape
    matrix: tools/tpu_attn_lowering_check.py."""
    import jax.export
    from jax.experimental import pallas as pl

    q = jnp.zeros((2, 256, 4, 64), jnp.float32)
    f = jax.jit(lambda q, k, v: jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, force=True))
    )(q, k, v))
    jax.export.export(f, platforms=["tpu"])(q, q, q)  # raises on regression

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def bad(x):
        return pl.pallas_call(
            kern,
            grid=(4,),
            in_specs=[pl.BlockSpec((4, 12), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((4, 12), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 48), jnp.float32),
        )(x)

    with pytest.raises(ValueError, match="Pallas TPU lowering"):
        jax.export.export(jax.jit(bad), platforms=["tpu"])(
            jnp.zeros((16, 48), jnp.float32))
