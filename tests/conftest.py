"""Test harness: an 8-device virtual CPU mesh stands in for the multi-chip
TPU slice (and for the reference's mpirun-oversubscribed localhost cluster,
reference: src/README.md:8-11).

The XLA_FLAGS env must be set before jax initialises; the platform choice must
go through jax.config (this image's sitecustomize registers a remote-TPU
plugin whose config latches before test env vars apply).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running integration test")
