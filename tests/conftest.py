"""Test harness: an 8-device virtual CPU mesh stands in for the multi-chip
TPU slice (and for the reference's mpirun-oversubscribed localhost cluster,
reference: src/README.md:8-11).

The XLA_FLAGS env must be set before jax initialises; the platform choice must
go through jax.config (this image's sitecustomize registers a remote-TPU
plugin whose config latches before test env vars apply).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running integration test")
    config.addinivalue_line(
        "markers",
        "core: fast semantic lane (`pytest -m core`, ~6 min wall on the "
        "1-core CI host as of r7) — coding, vote, aggregation, "
        "native-oracle, and op-level tests, plus the program linter's "
        "--fast sweep + negative controls (~70 s of that, "
        "test_program_lint/test_program_size — PERF.md §6); the subset "
        "that gates every commit",
    )


# Three tiers (r3 verdict weak #5 — the full suite is compile-bound and >9.5
# min wall, too slow for a CI feedback loop or a judge budget):
#   pytest -m core         — ~6 min (r7), the algorithmic heart (these
#                            modules + explicit core marks incl. the
#                            program-lint fast sweep)
#   pytest -m "not slow"   — adds the jitted train-step / parallel-topology
#                            integration layer (~minutes of XLA compiles)
#   pytest                 — everything, incl. subprocess multihost drivers
#                            and interpret-mode Pallas (slowest)
_CORE_MODULES = {
    "test_coding_cyclic",
    "test_repetition_and_aggregation",
    "test_native",
    "test_ops",
    "test_straggler",
}
_SLOW_MODULES = {"test_multihost"}  # every test spawns real processes
_SLOW_TESTS = {  # individually >1 min wall: subprocess drivers of chip tools
    "test_dryrun_multichip_subprocess",
    "test_probe_down_cpu_fallback_appends_tiny_record",
    "test_tpu_lm_perf_tool",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        if mod in _CORE_MODULES:
            item.add_marker(pytest.mark.core)
        if mod in _SLOW_MODULES or item.originalname in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
