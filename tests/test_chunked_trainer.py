"""Scan-chunked trainer (cfg.steps_per_call > 1): bitwise equivalence with
the eager loop, chunk-boundary snapping, mid-chunk resume, vectorized range
batching, live schedules past the precomputed table, and the pre-r4
checkpoint format-break message.

The equivalence tests are the load-bearing ones: train_many is the SAME
coded step (fwd/bwd → encode → gather → decode → update) scan-chained K at
a time, so K ∈ {1, 4} must produce identical final parameters and an
identical metrics stream — under a live adversary AND a straggler-drop
schedule, for all three approaches. FC keeps the compiles cheap; nothing
here depends on the network.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu import rng as drng
from draco_tpu.config import TrainConfig
from draco_tpu.data import batching
from draco_tpu.data.datasets import load_dataset
from draco_tpu.runtime import make_mesh
from draco_tpu.training.trainer import Trainer


@pytest.fixture(scope="module")
def ds():
    return load_dataset("synthetic-mnist", synthetic_train=512, synthetic_test=64)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def make_cfg(**kw):
    base = dict(
        network="FC",
        dataset="synthetic-mnist",
        batch_size=4,
        lr=0.01,
        momentum=0.9,
        num_workers=8,
        max_steps=6,
        eval_freq=0,
        train_dir="",
        log_every=1,
        # strict compile sentinel (ISSUE 5): any steady-state recompilation
        # of a labelled program raises at the dispatch site, so every test
        # in this suite doubles as a 0-retrace assertion
        compile_guard="raise",
        # in-graph step guard enabled suite-wide (ISSUE 6): the guard must
        # be bitwise-transparent on clean runs — the equivalence tests
        # additionally pin guard_trips == 0 per record
        step_guard="on",
        # incident engine enabled suite-wide (ISSUE 13): host-side only,
        # so K∈{1,4} must stay bitwise with the watch ON and a clean run
        # must raise ZERO incidents (_assert_telemetry_artifacts)
        incident_watch="on",
    )
    base.update(kw)
    return TrainConfig(**base)


def params_vec(tr):
    return np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(jax.device_get(tr.state.params))]
    )


def metric_stream(train_dir):
    """[(step, {metric: value})] from metrics.jsonl, timing keys dropped —
    the cross-loop-comparable part of the record stream."""
    out = []
    with open(os.path.join(train_dir, "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if "loss" not in rec:
                continue  # eval records
            vals = {k: v for k, v in rec.items()
                    if k not in ("time", "t_fetch", "t_comp", "step")}
            out.append((rec["step"], vals))
    return out


# --------------------------------------------------------------------------
# chunked vs eager equivalence — all three approaches, adversary + stragglers
# --------------------------------------------------------------------------

# the exact coded approaches run with the numerics observatory AND the
# bf16 shadow wire enabled suite-wide (obs/numerics.py, ISSUE 10): the
# watch must not perturb the f32 path — these very tests pin K∈{1,4}
# bitwise equality with it on — and _assert_decode_health pins the shadow
# columns (flag agreement 1.0, detection preserved under quantization)
# per record. baseline stays watch-free (no coded wire, no optional
# columns — PR 4); the approx family's watch coverage lives in the LM
# suite's tp/approx wire-study cells + tools/wire_study.py, keeping this
# suite's compile bill inside the tier-1 budget.
_WATCH = dict(numerics_watch="on", shadow_wire="bf16")

APPROACHES = {
    # n=9 so the cyclic joint budget t + e <= s holds with a LIVE adversary
    # and a straggler drop in the same run (s=2, t=1, e=1, n > 4s)
    "cyclic": dict(approach="cyclic", num_workers=9, worker_fail=2,
                   adversary_count=1, err_mode="rev_grad",
                   straggle_mode="drop", straggle_count=1,
                   redundancy="shared", **_WATCH),
    "maj_vote": dict(approach="maj_vote", group_size=4, worker_fail=1,
                     err_mode="rev_grad", straggle_mode="drop",
                     straggle_count=1, **_WATCH),
    "baseline": dict(approach="baseline", mode="geometric_median",
                     worker_fail=1, err_mode="rev_grad",
                     straggle_mode="drop", straggle_count=1),
    # the approximate family (ISSUE 8): no live adversary (config.validate
    # rejects one — no Byzantine certificate), two seeded drops per step
    # inside the ⌈αn⌉ = 2 design budget — the residual-vs-bound certificate
    # is asserted per record in _assert_decode_health
    "approx": dict(approach="approx", worker_fail=0, redundancy="shared",
                   code_redundancy=1.5, straggler_alpha=0.25,
                   straggle_mode="drop", straggle_count=2),
}


@pytest.mark.parametrize("approach", sorted(APPROACHES))
def test_chunked_equals_eager_bitwise(ds, approach, tmp_path):
    """Same final params AND same metrics stream for K=1 (eager loop) vs
    K=4 (scan-chunked, with a remainder chunk since 6 % 4 != 0) — run with
    the full telemetry spine enabled (trace_dir + heartbeat, ISSUE 4),
    which must not perturb either regime."""
    kw = APPROACHES[approach]
    mesh = make_mesh(kw.get("num_workers", 8))
    out = {}
    for k in (1, 4):
        d = str(tmp_path / f"{approach}_k{k}")
        tr = Trainer(make_cfg(**kw, steps_per_call=k, train_dir=d,
                              trace_dir=d),
                     mesh=mesh, dataset=ds, quiet=True)
        # the chunked run additionally captures a jax.profiler window
        # (ISSUE 9): the capture must observe, never perturb — metrics
        # stay bitwise-equal to the unprofiled eager run, still under
        # compile_guard="raise" with 0 steady retraces
        last = tr.run(profile_dir=(d if k == 4 else None))
        out[k] = (params_vec(tr), metric_stream(d), last)
        # the sentinel saw the run's compiles and zero steady-state
        # recompiles (compile_guard="raise" would already have failed the
        # dispatch — this pins the counter surface too)
        snap = tr.compile_watch.snapshot()
        assert snap["compiles"] >= 1 and snap["steady_recompiles"] == 0
        tr.close()
    np.testing.assert_array_equal(out[1][0], out[4][0])
    assert out[1][1] == out[4][1]  # identical per-step metric values
    assert [s for s, _ in out[4][1]] == list(range(1, 7))
    # the returned last-record agrees on the training metrics too
    for key in ("loss", "prec1", "present"):
        assert out[1][2][key] == out[4][2][key]
    _assert_decode_health(approach, out[4][1], kw)
    _assert_telemetry_artifacts(tmp_path / f"{approach}_k4", approach)


def test_approx_full_participation_matches_uncoded_mean(mesh):
    """With every worker present the approx decode IS the uncoded mean
    (v = 1 feasible ⇒ u = 1 ⇒ exact, coding/approx.py): one jitted
    train_step of approach='approx' from the shared seeded init lands
    allclose (f32 solve noise) to one step of the plain baseline mean on
    the SAME batch — the acceptance pin of ISSUE 8."""
    from draco_tpu.training.step import build_train_setup

    kw = dict(APPROACHES["approx"], straggle_mode="none", straggle_count=0)
    x = np.asarray(np.random.RandomState(5).rand(8, 4, 28, 28, 1),
                   np.float32)
    y = np.asarray(np.random.RandomState(6).randint(0, 10, (8, 4)),
                   np.int32)
    mask = np.zeros(8, dtype=bool)
    vecs = {}
    for name, akw in (("approx", kw),
                      ("baseline", dict(approach="baseline", mode="normal"))):
        setup = build_train_setup(make_cfg(**akw), mesh,
                                  dataset_name="synthetic-mnist")
        state, _ = setup.train_step(setup.state, jnp.asarray(x),
                                    jnp.asarray(y), jnp.asarray(mask))
        vecs[name] = np.concatenate([
            np.ravel(v) for v in jax.tree.leaves(jax.device_get(state.params))
        ])
    np.testing.assert_allclose(vecs["approx"], vecs["baseline"],
                               rtol=1e-5, atol=1e-6)


def _assert_decode_health(approach, stream, kw):
    """Decode-health columns (in-graph, ISSUE 4) on every train record:
    detection precision AND recall are 1.0 against the seeded adversary +
    straggler schedules — flagged set == live adversary set, step by step —
    and the cyclic residual sits at float noise (the exactness guarantee
    observable). The packed per-worker forensics masks (obs/forensics,
    ISSUE 7) pin the attribution EXACTLY: accused == adversarial ∧ present
    bit for bit (per-worker precision/recall 1.0 — an absent worker is
    never an accused worker). The baseline approach has no exactness
    certificate and must emit neither health nor forensics columns."""
    from draco_tpu.obs import forensics as fx

    n = kw.get("num_workers", 8)
    adv = drng.adversary_schedule(428, 6, n, kw.get("adversary_count",
                                                    kw["worker_fail"]))
    strag = drng.straggler_schedule(428, 6, n, kw["straggle_count"])
    flag_col = {"cyclic": "located_errors", "maj_vote": "det_flagged"}
    for step, vals in stream:
        # guards enabled suite-wide: a clean run (adversary + stragglers
        # inside budget) never trips and never skips an update
        assert vals["guard_trips"] == 0.0, (step, vals)
        assert vals["skipped_steps"] == 0.0, (step, vals)
        if approach == "baseline":
            assert "det_tp" not in vals and "decode_residual" not in vals
            assert "wmask_accused0" not in vals
            assert "nx_wire_absmax" not in vals and "shadow_err" not in vals
            continue
        # numerics observatory + bf16 shadow wire (obs/numerics.py, ISSUE
        # 10) on the watch-enabled approaches: range stats sane and
        # finite, and quantization changes NO accusation — flag agreement
        # exactly 1.0 on every step, end-to-end shadow error at bf16
        # rounding scale
        if kw.get("shadow_wire"):
            assert vals["nx_wire_absmax"] > 0 and vals["nx_wire_rms"] > 0
            for stage in ("grad", "wire", "agg"):
                assert vals[f"nx_{stage}_nonfinite"] == 0.0, (step, stage)
                assert 0.0 <= vals[f"nx_{stage}_uf_int8"] <= 1.0
                assert 0.0 <= vals[f"nx_{stage}_of_bf16"] <= 1.0
            assert vals["shadow_flag_agree"] == 1.0, (step, vals)
            assert 0.0 <= vals["shadow_err"] < 0.05, (step, vals)
        if approach == "approx":
            # the residual-vs-bound certificate per record (ISSUE 8): the
            # measured decode error never exceeds the arrived support's
            # analytic optimal-decoding bound, and a full-participation
            # step decodes exactly (both sit at f32 noise)
            assert vals["decode_residual"] <= \
                vals["decode_residual_bound"] + 1e-5, (step, vals)
            if not strag[step].any():
                assert vals["decode_residual"] < 1e-4
                assert vals["decode_residual_bound"] < 1e-4
            assert 0.0 < vals["recovered_fraction"] <= 1.0
            # no located-error machinery at all on this family
            assert "det_tp" not in vals and "located_errors" not in vals
            masks = fx.record_masks(vals, n)
            assert masks is not None, (step, vals)
            assert masks["present"] == tuple(~strag[step]), step
            assert masks["adv"] == (False,) * n  # no live adversary
            # a scheduled straggler is NEVER an accused worker — the
            # family's whole accusation surface is the non-finite ingest
            # check, silent on clean runs
            assert masks["accused"] == (False,) * n, (step, masks)
            continue
        want = int((adv[step] & ~strag[step]).sum())  # detectable truth
        assert vals["det_adv"] == want, (step, vals)
        assert vals["det_tp"] == want  # recall = 1.0
        assert vals[flag_col[approach]] == want  # precision = 1.0
        # detection P/R == 1.0 PRESERVED under the bf16 shadow (the ISSUE
        # 10 acceptance pin): the shadow flag set scores identically
        assert vals["shadow_det_flagged"] == want, (step, vals)
        assert vals["shadow_det_tp"] == want
        masks = fx.record_masks(vals, n)
        assert masks is not None, (step, vals)
        assert masks["adv"] == tuple(adv[step]), step
        assert masks["present"] == tuple(~strag[step]), step
        # per-worker attribution exact: accused == adversarial ∧ present
        assert masks["accused"] == tuple(adv[step] & ~strag[step]), (
            step, masks)
        if approach == "cyclic":
            assert vals["decode_residual"] < 1e-3
        else:
            pres = int((~strag[step]).sum())
            assert vals["vote_agree"] == pytest.approx((pres - want) / pres)
            assert vals["flagged_groups"] == (1 if want else 0)


def _assert_telemetry_artifacts(run_dir, approach):
    """The K=4 run is a 2-chunk CPU-mesh run (ranges (1,4),(5,2)): its
    trace.json must parse as Chrome trace events with the host phases,
    nested prefetcher spans and counter events, and status.json must report
    detection precision/recall 1.0 (cyclic/maj_vote)."""
    trace = json.load(open(run_dir / "trace.json"))
    events = trace["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    names = {e["name"] for e in spans}
    assert {"gather", "upload", "dispatch", "sync", "flush"} <= names
    assert len([e for e in spans if e["name"] == "dispatch"]) == 2  # 2 chunks
    for e in spans:
        assert {"ts", "dur", "pid", "tid"} <= set(e) and e["dur"] >= 0
    # prefetch spans nest inside the trainer's gather span (same thread)
    gathers = [e for e in spans if e["name"] == "gather"]
    inner = [e for e in spans if e["name"].startswith("prefetch.")]
    assert inner, names
    assert any(
        g["tid"] == i["tid"] and g["ts"] <= i["ts"]
        and i["ts"] + i["dur"] <= g["ts"] + g["dur"] + 1e-3
        for i in inner for g in gathers)
    assert any(e["ph"] == "C" for e in events)  # queue-depth counters
    status = json.load(open(run_dir / "status.json"))
    assert status["step"] == 6 and status["steps_per_s"] > 0
    assert np.isfinite(status["loss"])
    assert status["prefetch_depth"] in (0, 1)
    # the heartbeat surfaces the compile counters (ISSUE 5)
    assert status["compiles"] >= 1 and status["compile_s"] > 0
    assert status["steady_recompiles"] == 0
    # the incident engine (ISSUE 13) ran on every cell of this suite and a
    # CLEAN run — live adversary + stragglers all inside budget — raises
    # ZERO incidents: the no-flapping/no-false-positive contract, at the
    # same time the bitwise assertions above prove the watch perturbs
    # nothing. No event ever fired, so no incidents.jsonl exists either.
    inc = status["incidents"]
    assert inc["total"] == 0 and inc["open"] == [] and inc["by_type"] == {}
    assert not os.path.exists(run_dir / "incidents.jsonl")
    # ... and the compile ledger sits next to the trace, attributing the
    # chunked program's builds (main chunk k=4 + remainder k=2)
    ledger = [json.loads(l) for l in open(run_dir / "compiles.jsonl")]
    labels = {r["program"] for r in ledger if r["program"]}
    assert {"train_many[4]", "train_many[2]"} <= labels
    assert not any(r["steady_recompile"] for r in ledger)
    compile_events = [e for e in events if e.get("cat") == "compile"]
    assert len(compile_events) == len(ledger) == status["compiles"]
    # the static wire-bytes ledger (ISSUE 10) rides every status payload;
    # the folded numerics block only on watch-enabled runs (the coded
    # approaches here — baseline runs watch-free)
    wire = status["wire"]
    assert wire["family"] == APPROACHES[approach]["approach"]
    assert wire["bytes_per_worker"]["f32"] == \
        (2 if approach == "cyclic" else 1) * 4 * wire["dim"]
    assert wire["bytes_per_worker"]["bf16"] * 2 == \
        wire["bytes_per_worker"]["f32"]
    if approach == "baseline":
        assert "numerics" not in status
        assert "decode_health" not in status
        assert "forensics" not in status
    elif approach == "approx":
        # residual-vs-bound certificate in the heartbeat (ISSUE 8) — and
        # the forensics interplay pin: scheduled stragglers are erasures,
        # so NO accusations, NO episodes, and the trust vector never
        # decays (absence is not evidence; obs/forensics docstring)
        health = status["decode_health"]
        assert health["decode_residual"] <= \
            health["decode_residual_bound"] + 1e-5
        assert 0.0 < health["recovered_fraction"] <= 1.0
        fxb = status["forensics"]
        assert fxb["accused_total"] == 0 and fxb["episodes_total"] == 0
        assert fxb["top_suspects"] == []
        assert fxb["trust"] == [1.0] * 8
        assert status["schema"] == 5
    else:
        health = status["decode_health"]
        assert health["precision"] == 1.0 and health["recall"] == 1.0
        assert health["adv_total"] > 0  # the adversary was really live
        # the per-worker ledger (ISSUE 7): accusations exist, every accused
        # worker was truly adversarial (per-worker precision/recall 1.0),
        # and status carries the versioned schema
        fxb = status["forensics"]
        assert fxb["accused_total"] > 0 and fxb["episodes_total"] > 0
        assert fxb["top_suspects"] and all(
            t["trust"] < 1.0 for t in fxb["top_suspects"])
        assert status["schema"] == 5
        # the folded numerics block (ISSUE 10): worst-case shadow error
        # bounded, flag agreement never dipped below 1.0
        nx = status["numerics"]
        assert nx["shadow_flag_agree_min"] == 1.0
        assert 0.0 <= nx["shadow_err_max"] < 0.05
        assert nx["nx_wire_absmax"] > 0 and nx["nx_grad_nonfinite_max"] == 0.0
    # the profiled window's device block (ISSUE 9): the capture + anchor
    # landed and the heartbeat folded the per-phase attribution — a plain
    # --profile-dir run has no scope map, so the honest state is all time
    # in the unattributed row (attributed_frac 0, device_attr docstring)
    from draco_tpu.obs import device_attr

    assert device_attr.find_capture(str(run_dir)) is not None
    anchor = device_attr.load_anchor(str(run_dir))
    assert anchor is not None and anchor["steps_profiled"] == 6
    assert anchor["tracer_ts_us"] is not None  # shared-clock anchor stamped
    dev = status["device"]
    assert dev["profiled_steps"] == 6
    assert dev["total_device_us"] > 0
    assert sum(dev["phase_fracs"].values()) == pytest.approx(1.0, abs=2e-3)
    assert dev["attributed_frac"] == 0.0 and dev["decode_share"] == 0.0


@pytest.mark.core
def test_chunked_smoke_fast(ds, mesh):
    """Tier-1/core smoke: small FC model, K=3 with a remainder chunk,
    adversary on — the chunked loop trains and the loss moves."""
    cfg = make_cfg(approach="cyclic", worker_fail=1, err_mode="rev_grad",
                   redundancy="shared", steps_per_call=3, max_steps=7,
                   log_every=1000)
    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    first = tr.run(max_steps=1)  # remainder-sized chunk (k=1)
    last = tr.run()
    tr.close()
    assert np.isfinite(last["loss"])
    assert last["loss"] < first["loss"]
    assert last["step"] == 7
    assert last["honest_located"] == 6.0


def test_chunk_ranges_snap_to_eval_and_remainder(ds, mesh):
    """Chunk boundaries: eval_freq multiples and max_steps always end a
    chunk, chunks never exceed K, and the ranges tile [start, n] exactly."""
    tr = Trainer(make_cfg(steps_per_call=4, eval_freq=6, max_steps=15),
                 mesh=mesh, dataset=ds, quiet=True)
    ranges = tr._chunk_ranges(1, 15)
    assert ranges == [(1, 4), (5, 2), (7, 4), (11, 2), (13, 3)]
    flat = [s + i for s, k in ranges for i in range(k)]
    assert flat == list(range(1, 16))
    # resume mid-grid: first chunk shortens to the next boundary
    assert tr._chunk_ranges(5, 12) == [(5, 2), (7, 4), (11, 2)]
    tr.close()


def test_resume_from_checkpoint_mid_chunk(ds, mesh, tmp_path):
    """A K=4 run checkpoints at eval boundaries (3, 6, 9); resuming from
    step 3 — mid-chunk relative to the K grid — must land on the exact same
    parameters as the uninterrupted run."""
    base = dict(approach="cyclic", worker_fail=1, err_mode="rev_grad",
                redundancy="shared", steps_per_call=4, max_steps=10,
                eval_freq=3, train_dir=str(tmp_path))
    t1 = Trainer(make_cfg(**base), mesh=mesh, dataset=ds, quiet=True)
    t1.run()
    v1 = params_vec(t1)
    t1.close()
    from draco_tpu.utils import checkpoint as ckpt

    assert ckpt.available_steps(str(tmp_path)) == [3, 6, 9]
    t2 = Trainer(make_cfg(**base, checkpoint_step=3), mesh=mesh, dataset=ds,
                 quiet=True)
    assert t2._start_step == 4
    t2.run()
    v2 = params_vec(t2)
    t2.close()
    np.testing.assert_array_equal(v1, v2)


# --------------------------------------------------------------------------
# vectorized range batching == per-step batching
# --------------------------------------------------------------------------

def test_range_indices_match_per_step():
    """Every *_range row must be bitwise identical to the per-step function —
    including across an epoch boundary (n_samples small vs the range)."""
    n, workers, bs, seed = 100, 4, 8, 428
    step0, k = 1, 9  # baseline bpe = 12: crosses no epoch; cyclic bpe = 3: crosses two
    got = batching.indices_baseline_range(n, step0, k, workers, bs, seed)
    want = np.stack([batching.indices_baseline(n, step0 + i, workers, bs, seed)
                     for i in range(k)])
    np.testing.assert_array_equal(got, want)

    seeds = drng.group_seeds(seed, 2)
    got = batching.indices_grouped_range(n, step0, k, workers, 2, bs, seeds)
    want = np.stack([batching.indices_grouped(n, step0 + i, workers, 2, bs, seeds)
                     for i in range(k)])
    np.testing.assert_array_equal(got, want)

    got = batching.indices_cyclic_range(n, step0, k, workers, bs, seed)
    want = np.stack([batching.indices_cyclic(n, step0 + i, workers, bs, seed)
                     for i in range(k)])
    np.testing.assert_array_equal(got, want)


def test_range_indices_cross_epoch_baseline():
    """Force the baseline/grouped epoch boundary too (bpe small)."""
    n, workers, bs, seed = 40, 2, 16, 7  # bpe = 2
    got = batching.indices_baseline_range(n, 0, 7, workers, bs, seed)
    want = np.stack([batching.indices_baseline(n, i, workers, bs, seed)
                     for i in range(7)])
    np.testing.assert_array_equal(got, want)


# --------------------------------------------------------------------------
# schedules stay live past the precomputed table (regression: the old
# min(step, cfg.max_steps) clamp replayed the last row forever)
# --------------------------------------------------------------------------

def test_schedule_extends_past_table(ds, mesh):
    cfg = make_cfg(approach="baseline", mode="geometric_median",
                   worker_fail=2, err_mode="rev_grad", max_steps=4,
                   straggle_mode="drop", straggle_count=1, log_every=1000)
    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    old_adv = tr._adv_schedule.copy()
    old_str = tr._straggle_schedule.copy()
    assert old_adv.shape[0] == 5
    tr.run(max_steps=12)  # block-wise callers go past cfg.max_steps
    # extended, prefix-stable, and equal to a fresh full-length draw
    assert tr._adv_schedule.shape[0] == 13
    np.testing.assert_array_equal(tr._adv_schedule[:5], old_adv)
    np.testing.assert_array_equal(
        tr._adv_schedule,
        drng.adversary_schedule(cfg.seed, 12, cfg.num_workers,
                                cfg.num_adversaries))
    np.testing.assert_array_equal(tr._straggle_schedule[:5], old_str)
    np.testing.assert_array_equal(
        tr._straggle_schedule,
        drng.straggler_schedule(cfg.seed, 12, cfg.num_workers,
                                cfg.straggle_count))
    # the tail is a live draw, not the frozen last row (whp for 2-of-8)
    tail = tr._adv_schedule[5:]
    assert not all(np.array_equal(row, old_adv[4]) for row in tail)
    tr.close()


def test_chunked_run_past_table_matches_eager(ds, mesh):
    """Both loops agree when run(max_steps) overruns cfg.max_steps — the
    chunked path extends the same schedules the eager path now uses."""
    kw = dict(approach="cyclic", worker_fail=1, err_mode="rev_grad",
              redundancy="shared", max_steps=3, log_every=1000)
    vecs = {}
    for k in (1, 4):
        tr = Trainer(make_cfg(**kw, steps_per_call=k), mesh=mesh, dataset=ds,
                     quiet=True)
        tr.run(max_steps=9)
        vecs[k] = params_vec(tr)
        tr.close()
    np.testing.assert_array_equal(vecs[1], vecs[4])


# --------------------------------------------------------------------------
# pre-r4 checkpoint format break surfaces a named error (ADVICE r4)
# --------------------------------------------------------------------------

def test_pre_r4_opt_state_restore_names_format_break(tmp_path):
    """Restoring a bare-rule (pre-unification) opt state into the current
    chain(rule, scale_by_schedule) structure must raise the explanatory
    ValueError naming the opt-state unification, not a raw pytree error."""
    import optax

    from draco_tpu.training.step import TrainState
    from draco_tpu.utils import checkpoint as ckpt

    params = {"w": jnp.ones((3,))}
    old = TrainState(params=params,
                     opt_state=optax.sgd(0.01, momentum=0.9).init(params),
                     batch_stats=None, step=jnp.asarray(1, jnp.int32))
    ckpt.save(str(tmp_path), 5, old)

    new_opt = optax.chain(optax.sgd(1.0, momentum=0.9),
                          optax.scale_by_schedule(lambda t: 0.01))
    new = TrainState(params=params, opt_state=new_opt.init(params),
                     batch_stats=None, step=jnp.asarray(1, jnp.int32))
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), jnp.asarray(x).dtype), new)
    with pytest.raises(ValueError, match="opt-state unification"):
        ckpt.load(str(tmp_path), 5, abstract)
