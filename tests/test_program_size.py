"""Serialized-program-size guard for chip-facing jits — now a thin call to
the program linter's constant-bloat rule on the registered programs.

The tunnel's remote-compile service rejects/chokes on large programs
(HTTP 413 above ~100 MB; "Broken pipe at ~27 min" at 638 MB — PERF.md §4).
Round 5 found the cyclic step closing over the d-length decode projection,
embedding d×4 bytes of CONSTANT into every serialized module. The bespoke
lowering scaffold that used to live here moved into
draco_tpu/analysis (registry + rules); these tests pin the two historical
guard points — the big-d LM program (d ≈ 3.3 M, where a closed-over (d,)
constant would dominate the module) and the CNN cyclic step — against the
same rule every other registered program now passes in
tests/test_program_lint.py / tools/program_lint.py.
"""

import pytest

pytestmark = pytest.mark.core


def _constant_bloat(name):
    from draco_tpu.analysis import get
    from draco_tpu.analysis.rules import rule_constant_bloat, trace_and_export

    prog = get(name)
    art = trace_and_export(prog.build(), platforms=prog.export_platforms)
    res = rule_constant_bloat(art)
    assert not res.get("skipped"), res
    return res


def test_lm_train_program_has_no_d_sized_constants():
    """The registered big-d LM program (the production K-fused chunked
    driver at a config where d > 3M — tp_step.lint_programs asserts the
    guard stays meaningful). A closed-over (d,) f32 would add 4d bytes;
    the honest module is a few hundred KB; the manifest threshold (2d)
    sits far from both."""
    res = _constant_bloat("lm_fold_big_bf16_many_k2")
    assert res["ok"], (
        f"{res} — a d-sized array is being embedded as a program constant "
        f"(rng.random_projection_factors_in_graph docstring / PERF.md §4)"
    )


def test_cnn_train_step_module_has_no_d_sized_constants():
    """Same guard for the CNN cyclic path (training/step.py)."""
    res = _constant_bloat("cnn_cyclic_step")
    assert res["ok"], (
        f"{res} — a d-sized array is being embedded as a program constant"
    )
