"""Serialized-program-size guard for chip-facing jits.

The tunnel's remote-compile service rejects/chokes on large programs
(HTTP 413 above ~100 MB; "Broken pipe at ~27 min" at 638 MB — PERF.md §4).
Round 5 found the cyclic step closing over the d-length decode projection,
embedding d×4 bytes of CONSTANT into every serialized module. This test
lowers the full scanned LM train step at a CI-sized config where such a
constant would dominate (d ≈ 6.5 M → +26 MB) and asserts the module stays
small — so any future closure over a d-sized array fails CI instead of
wedging a chip window.
"""

import jax
import pytest

pytestmark = pytest.mark.core


def test_lm_train_step_module_has_no_d_sized_constants():
    import jax.export

    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel.mesh import make_folded_wtp_mesh
    from draco_tpu.parallel.tp_step import build_tp_train_setup
    from tools.tpu_lm_perf import (
        build_lm_variants, make_scan_loop, stage_scan_inputs,
    )

    kw = build_lm_variants(
        batch_size=1, num_workers=8, seq_len=64, vocab=512, model_dim=256,
        model_heads=4, model_layers=4, remat=True, max_steps=3,
    )["lm_cyclic_s1_shared_bf16"]
    cfg = TrainConfig(**kw)
    mesh = make_folded_wtp_mesh(cfg.num_workers)
    setup = build_tp_train_setup(cfg, mesh)
    dim = setup.dim
    assert dim > 3_000_000  # the guard is only meaningful if d is CI-large
    xs, ms = stage_scan_inputs(cfg, 2)
    loop = make_scan_loop(setup)
    with mesh:
        exp = jax.export.export(jax.jit(loop), platforms=["cpu"])(
            setup.state, xs, ms)
    module_bytes = len(exp.mlir_module_serialized)
    # a closed-over (d,) f32 would add 4*dim bytes; the honest program is
    # a few hundred KB. Threshold sits far from both.
    assert module_bytes < 2 * dim, (
        f"serialized LM step module is {module_bytes} bytes for d={dim} — "
        f"a d-sized array is being embedded as a program constant "
        f"(rng.random_projection_factors_in_graph docstring / PERF.md §4)"
    )


def test_cnn_train_step_module_has_no_d_sized_constants():
    """Same guard for the CNN cyclic path (training/step.py) — its d≈11M
    flagship would embed a 44 MB constant."""
    import jax.export
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu import runtime
    from draco_tpu.config import TrainConfig
    from draco_tpu.training.step import build_train_setup

    cfg = TrainConfig(
        network="LeNet", dataset="synthetic-mnist", approach="cyclic",
        batch_size=2, num_workers=8, worker_fail=1, err_mode="rev_grad",
        lr=0.01, momentum=0.9, max_steps=3, eval_freq=0, train_dir="",
        log_every=10**9,
    )
    mesh = runtime.make_mesh(cfg.num_workers)
    setup = build_train_setup(cfg, mesh)
    dim = setup.dim
    x = jnp.zeros((cfg.num_workers, cfg.batch_size, 28, 28, 1), jnp.float32)
    y = jnp.zeros((cfg.num_workers, cfg.batch_size), jnp.int32)
    adv = jnp.asarray(np.arange(cfg.num_workers) == 0)
    with mesh:
        exp = jax.export.export(
            jax.jit(lambda s, x, y, m: setup.train_step(s, x, y, m)),
            platforms=["cpu"],
        )(setup.state, x, y, adv)
    module_bytes = len(exp.mlir_module_serialized)
    assert module_bytes < max(2 * dim, 2_000_000), (
        f"serialized CNN step module is {module_bytes} bytes for d={dim} — "
        f"a d-sized array is being embedded as a program constant"
    )
