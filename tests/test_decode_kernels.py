"""Fused decode kernels (ISSUE 12): the shared linalg primitives, the
kernel-vs-XLA equivalence contract (bounded-err decode, IDENTICAL
honest/flag/loud sets), interpret-mode kernel bodies, the Mosaic TPU
lowering of the registered kernel programs, and the dispatch switch.

Equivalence tolerances follow the code's own accuracy against ground
truth: at the CI shapes both lowerings sit at f32 solve noise
(~1e-6 relative) and at the n=32 s=3 erasure shapes both drift to ~5e-3
(the honest-row DFT submatrix conditioning — measured equal for the two
solvers), so the suite pins fused-vs-xla within the same envelope the
existing xla-vs-truth tests use, and pins the discrete outputs (honest /
flagged / loud) bit-identical.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu.attacks import inject_cyclic
from draco_tpu.coding import approx as approx_mod
from draco_tpu.coding import cyclic as cyclic_mod
from draco_tpu.coding import linalg as linalg_mod
from draco_tpu.ops import decode_kernels


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("value,backend,want", [
    ("auto", False, "xla"),
    ("auto", True, "pallas"),
    ("xla", True, "xla"),
    ("xla", False, "xla"),
    ("pallas", True, "pallas"),
    ("pallas", False, "fused"),  # the CPU fallback the artifacts measure
])
def test_resolve_decode_impl(value, backend, want):
    assert decode_kernels.resolve_decode_impl(value, backend) == want


def test_resolve_decode_impl_rejects_unknown():
    with pytest.raises(ValueError):
        decode_kernels.resolve_decode_impl("mosaic", True)


def test_config_validates_decode_impl():
    from draco_tpu.config import TrainConfig

    cfg = TrainConfig(network="LeNet", dataset="synthetic-mnist",
                      approach="cyclic", num_workers=8, worker_fail=1,
                      decode_impl="mosaic")
    with pytest.raises(ValueError, match="decode_impl"):
        cfg.validate()


# ---------------------------------------------------------------------------
# shared linalg primitives (coding/linalg.py)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("m", [2, 4, 6, 8])  # 2s ≤ 8 covers s ≤ 4; the
# m=10 (s=5 ceiling) case pays ~20 s of eager pair-loop dispatch for no
# new code path, so it stays out of the tier-1 budget
def test_jacobi_lstsq_matches_truncated_svd(m, rng):
    a = rng.randn(3, m, m).astype(np.float32)
    a[1, :, -1] = a[1, :, 0]  # batch 1 genuinely rank-deficient
    b = rng.randn(3, m).astype(np.float32)
    x = np.asarray(linalg_mod.jacobi_lstsq(jnp.asarray(a), jnp.asarray(b),
                                           1e-5))
    for i in range(3):
        want, *_ = np.linalg.lstsq(a[i].astype(np.float64),
                                   b[i].astype(np.float64), rcond=1e-5)
        err = np.abs(x[i] - want).max() / max(1.0, np.abs(want).max())
        assert err < 2e-3, (i, err)


def test_jacobi_lstsq_zero_system_is_zero_and_finite():
    x = np.asarray(linalg_mod.jacobi_lstsq(jnp.zeros((1, 4, 4)),
                                           jnp.ones((1, 4)), 1e-5))
    assert (x == 0).all()


@pytest.mark.parametrize("m", [2, 6, 26])
def test_gauss_inv_c_inverts(m, rng):
    ar = rng.randn(4, m, m).astype(np.float32)
    ai = rng.randn(4, m, m).astype(np.float32)
    ir, ii = linalg_mod.gauss_inv_c(jnp.asarray(ar), jnp.asarray(ai))
    a = ar + 1j * ai
    inv = np.asarray(ir) + 1j * np.asarray(ii)
    for i in range(4):
        err = np.abs(a[i] @ inv[i] - np.eye(m)).max()
        assert err < 5e-4 * m, (i, err)


def test_topk_mask_matches_lax_topk(rng):
    for n, m in ((8, 6), (16, 10), (32, 26)):
        mag = rng.rand(5, n).astype(np.float32)
        mask = np.asarray(linalg_mod.topk_mask(jnp.asarray(mag), m))
        for i in range(5):
            idx = np.asarray(jax.lax.top_k(jnp.asarray(mag[i]), m)[1])
            want = np.zeros(n, bool)
            want[np.sort(idx)] = True
            np.testing.assert_array_equal(mask[i], want)


def test_select_matrix_gathers(rng):
    mask = jnp.asarray(np.array([[1, 0, 1, 1, 0, 1, 0, 0],
                                 [0, 1, 1, 0, 1, 0, 1, 0]], bool))
    sel = np.asarray(linalg_mod.select_matrix(mask, 4))
    x = rng.randn(8, 3).astype(np.float32)
    for i in range(2):
        idx = np.where(np.asarray(mask[i]))[0]
        np.testing.assert_allclose(sel[i] @ x, x[idx])


def test_masked_median_matches_nanmedian(rng):
    x = rng.randn(6, 11).astype(np.float32)
    mask = rng.rand(6, 11) > 0.3
    mask[5] = False  # all-masked row -> NaN, like nanmedian of all-NaN
    x[0, 0] = np.nan
    mask[0, 0] = False  # NaN outside the mask must not leak (0·NaN trap)
    got = np.asarray(linalg_mod.masked_median(jnp.asarray(x),
                                              jnp.asarray(mask)))
    for i in range(6):
        if not mask[i].any():
            assert np.isnan(got[i])
            continue
        want = np.nanmedian(np.where(mask[i], x[i], np.nan))
        np.testing.assert_allclose(got[i], want, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# cyclic: fused locator vs the XLA path (the equivalence contract)
# ---------------------------------------------------------------------------

def _attacked_wire(code, rng, d, t, e):
    """Encoded wire with t live adversaries + e zero-filled stragglers."""
    n = code.n
    bg = rng.randn(n, d).astype(np.float32)
    enc_re, enc_im = cyclic_mod.encode(code, jnp.asarray(bg[code.batch_ids]))
    picks = rng.choice(n, size=t + e, replace=False)
    adv = np.zeros(n, bool)
    adv[picks[:t]] = True
    enc_re, enc_im = inject_cyclic(enc_re, enc_im, jnp.asarray(adv),
                                   "rev_grad")
    present = np.ones(n, bool)
    present[picks[t:]] = False
    enc_re = enc_re * jnp.asarray(present)[:, None]
    enc_im = enc_im * jnp.asarray(present)[:, None]
    rf = jnp.asarray(rng.normal(loc=1.0, size=d).astype(np.float32))
    pres = jnp.asarray(present) if e else None
    return bg, enc_re, enc_im, rf, adv, pres


@pytest.mark.parametrize("n,s,t,e,tol", [
    (8, 1, 1, 0, 1e-4), (11, 2, 2, 0, 1e-4), (11, 2, 1, 1, 1e-4),
    (16, 3, 3, 0, 1e-3), (32, 3, 2, 1, 2e-2), (32, 3, 3, 0, 2e-2),
])
def test_cyclic_fused_matches_xla(n, s, t, e, tol, rng):
    """Decoded bounded-err vs xla AND vs truth at the xla path's own
    accuracy envelope; honest/flagged/loud bit-identical."""
    code = cyclic_mod.build_cyclic_code(n, s)
    d = 192
    bg, er, ei, rf, adv, pres = _attacked_wire(code, rng, d, t, e)
    dx, hx, hlx = cyclic_mod.decode(code, er, ei, rf, present=pres,
                                    with_health=True, impl="xla")
    df, hf, hlf = cyclic_mod.decode(code, er, ei, rf, present=pres,
                                    with_health=True, impl="fused")
    np.testing.assert_array_equal(np.asarray(hx), np.asarray(hf))
    np.testing.assert_array_equal(np.asarray(hlx["flagged"]),
                                  np.asarray(hlf["flagged"]))
    np.testing.assert_array_equal(np.asarray(hlx["loud"]),
                                  np.asarray(hlf["loud"]))
    want = bg.sum(axis=0) / n
    scale = np.abs(want).max()
    assert np.abs(np.asarray(df) - want).max() / scale < tol
    assert np.abs(np.asarray(df) - np.asarray(dx)).max() / scale < tol
    assert not np.asarray(hf)[adv].any()
    assert float(hlf["residual"]) < 1e-3  # clean decode: solve noise only


@pytest.mark.parametrize("n,s", [(8, 1), (11, 2)])
def test_cyclic_fused_layer_matches_xla(n, s, rng):
    code = cyclic_mod.build_cyclic_code(n, s)
    d = 192
    bg, er, ei, rf, adv, _ = _attacked_wire(code, rng, d, s, 0)
    offs = [0, 40, 100, d]
    dx, hx, hlx = cyclic_mod.decode_layers(code, er, ei, rf, offs,
                                           with_health=True, impl="xla")
    df, hf, hlf = cyclic_mod.decode_layers(code, er, ei, rf, offs,
                                           with_health=True, impl="fused")
    np.testing.assert_array_equal(np.asarray(hx), np.asarray(hf))
    np.testing.assert_array_equal(np.asarray(hlx["flagged"]),
                                  np.asarray(hlf["flagged"]))
    np.testing.assert_array_equal(np.asarray(hlx["loud"]),
                                  np.asarray(hlf["loud"]))
    want = bg.sum(axis=0) / n
    scale = np.abs(want).max()
    assert np.abs(np.asarray(df) - want).max() / scale < 1e-4
    assert np.abs(np.asarray(df) - np.asarray(dx)).max() / scale < 1e-4


def test_cyclic_fused_beyond_budget_keeps_fault_signals(rng):
    """s+1 corruptions: the fused path keeps the budget-exceeded guard
    signal (flagged rows > s — coding/cyclic._locate_v docstring) and the
    loud forensic mask still names the magnitude outliers, identically to
    the xla impl."""
    code = cyclic_mod.build_cyclic_code(8, 1)
    d = 128
    bg = rng.randn(8, d).astype(np.float32)
    er, ei = cyclic_mod.encode(code, jnp.asarray(bg[code.batch_ids]))
    adv = np.zeros(8, bool)
    adv[[2, 5]] = True  # 2 > s = 1
    er, ei = inject_cyclic(er, ei, jnp.asarray(adv), "rev_grad")
    rf = jnp.asarray(rng.normal(loc=1.0, size=d).astype(np.float32))
    flags = {}
    for impl in ("xla", "fused"):
        _, _, hl = cyclic_mod.decode(code, er, ei, rf, with_health=True,
                                     impl=impl)
        assert int(np.asarray(hl["flagged"]).sum()) > code.s, impl
        # the loud forensic mask still names the magnitude outliers
        assert np.asarray(hl["loud"])[adv].all(), impl
        flags[impl] = (np.asarray(hl["flagged"]), np.asarray(hl["loud"]))
    np.testing.assert_array_equal(flags["xla"][0], flags["fused"][0])
    np.testing.assert_array_equal(flags["xla"][1], flags["fused"][1])


def test_cyclic_fused_nan_wire_accuses_nobody(rng):
    """NaN wire: decode non-finite (guard territory), flag/loud sets
    empty — same attribution discipline as the xla path."""
    code = cyclic_mod.build_cyclic_code(8, 1)
    d = 64
    er = jnp.asarray(np.full((8, d), np.nan, np.float32))
    ei = jnp.zeros((8, d), jnp.float32)
    rf = jnp.ones((d,), jnp.float32)
    for impl in ("xla", "fused"):
        dec, _, hl = cyclic_mod.decode(code, er, ei, rf, with_health=True,
                                       impl=impl)
        assert not np.isfinite(np.asarray(dec)).all(), impl
        assert not np.asarray(hl["flagged"]).any(), impl
        assert not np.asarray(hl["loud"]).any(), impl


# ---------------------------------------------------------------------------
# approx: fused decode vs the XLA path
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,r,drops", [(8, 1.5, 0), (8, 1.5, 2),
                                       (12, 2.0, 3)])
def test_approx_fused_matches_xla(n, r, drops, rng):
    code = approx_mod.build_approx_code(n, r)
    d = 257
    bg = rng.randn(n, d).astype(np.float32)
    rows = approx_mod.encode_shared(code, jnp.asarray(bg))
    present = np.ones(n, bool)
    if drops:
        present[rng.choice(n, size=drops, replace=False)] = False
    pres = jnp.asarray(present)
    dx, vx, hlx = approx_mod.decode(code, rows, present=pres,
                                    with_health=True,
                                    batch_grads=jnp.asarray(bg), impl="xla")
    df, vf, hlf = approx_mod.decode(code, rows, present=pres,
                                    with_health=True,
                                    batch_grads=jnp.asarray(bg),
                                    impl="fused")
    # identical weight solve (shared prologue): v bitwise
    np.testing.assert_array_equal(np.asarray(vx), np.asarray(vf))
    np.testing.assert_array_equal(np.asarray(hlx["bound"]),
                                  np.asarray(hlf["bound"]))
    np.testing.assert_array_equal(np.asarray(hlx["recovered_fraction"]),
                                  np.asarray(hlf["recovered_fraction"]))
    scale = max(1e-9, np.abs(np.asarray(dx)).max())
    assert np.abs(np.asarray(df) - np.asarray(dx)).max() / scale < 1e-5
    # the certificate holds on the fused path's own numbers
    assert float(hlf["residual"]) <= float(hlf["bound"]) + 1e-4
    assert abs(float(hlf["residual"]) - float(hlx["residual"])) < 1e-4


# ---------------------------------------------------------------------------
# the kernels themselves: interpret mode (CI covers the kernel body
# without a TPU) + the Mosaic TPU lowering of the registered programs
# ---------------------------------------------------------------------------

def test_cyclic_kernel_interpret_bitwise_vs_reference(rng):
    """pallas_call(interpret=True) runs the SAME locator_core the fused
    reference jits — block plumbing (grid, padding, output slicing) is the
    only difference, so the outputs are bit-identical."""
    code = cyclic_mod.build_cyclic_code(8, 1)
    d = 300
    bg = rng.randn(8, d).astype(np.float32)
    er, ei = cyclic_mod.encode(code, jnp.asarray(bg[code.batch_ids]))
    adv = np.zeros(8, bool)
    adv[3] = True
    er, ei = inject_cyclic(er, ei, jnp.asarray(adv), "rev_grad")
    rf = jnp.asarray(rng.normal(loc=1.0, size=d).astype(np.float32))
    offs = [0, 50, 128, d]  # 3 layers: exercises the L % LAYER_BLOCK pad
    out_f = cyclic_mod.decode_layers(code, er, ei, rf, offs,
                                     with_health=True, impl="fused")
    out_k = cyclic_mod.decode_layers(code, er, ei, rf, offs,
                                     with_health=True,
                                     impl="pallas_interpret")
    np.testing.assert_array_equal(np.asarray(out_f[0]), np.asarray(out_k[0]))
    np.testing.assert_array_equal(np.asarray(out_f[1]), np.asarray(out_k[1]))
    for key in ("flagged", "loud"):
        np.testing.assert_array_equal(np.asarray(out_f[2][key]),
                                      np.asarray(out_k[2][key]))
    np.testing.assert_allclose(float(out_f[2]["residual"]),
                               float(out_k[2]["residual"]), rtol=1e-6)
    assert not np.asarray(out_k[1])[:, adv].any()


def test_approx_kernel_interpret_matches_reference(rng):
    """Ragged d (not a TILE_D multiple) + a NaN payload in an absent row:
    the kernel's where-mask must drop it (0·NaN = NaN through the matvec
    otherwise) and the accumulated health scalars must match the
    reference sweep to accumulation-order noise."""
    n, d = 8, 5000
    code = approx_mod.build_approx_code(n, 1.5)
    bg = rng.randn(n, d).astype(np.float32)
    rows = np.array(approx_mod.encode_shared(code, jnp.asarray(bg)))
    present = np.ones(n, bool)
    present[2] = False
    rows[2] = np.nan
    args = dict(present=jnp.asarray(present), with_health=True,
                batch_grads=jnp.asarray(bg))
    o_f = approx_mod.decode(code, jnp.asarray(rows), impl="fused", **args)
    o_k = approx_mod.decode(code, jnp.asarray(rows),
                            impl="pallas_interpret", **args)
    assert np.isfinite(np.asarray(o_k[0])).all()
    scale = max(1e-9, np.abs(np.asarray(o_f[0])).max())
    assert np.abs(np.asarray(o_f[0]) - np.asarray(o_k[0])).max() / scale \
        < 1e-5
    assert abs(float(o_f[2]["residual"]) - float(o_k[2]["residual"])) < 1e-4
    assert float(o_k[2]["residual"]) <= float(o_k[2]["bound"]) + 1e-4


def test_kernel_programs_export_for_tpu():
    """The registered kernel-bearing lint programs pass the Python-side
    Mosaic TPU lowering via cross-platform export on this CPU host — the
    tpu_attn_lowering_check methodology, here as a plain test so a kernel
    edit that breaks the TPU lowering fails CI, not a chip window."""
    from jax import export as jexport

    progs = decode_kernels.lint_programs()
    assert {p.name for p in progs} == {"kernel_cyclic_locator",
                                       "kernel_approx_decode",
                                       "kernel_cyclic_narrow_recombine",
                                       "kernel_approx_decode_narrow",
                                       "kernel_cyclic_narrow_recombine_bf16",
                                       "kernel_approx_decode_narrow_bf16"}
    for prog in progs:
        bp = prog.build()
        exp = jexport.export(bp.fn, platforms=["tpu"])(*[
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in bp.args])
        assert len(exp.mlir_module_serialized) > 0
        assert not bp.capture_memory  # tpu_custom_call can't compile on CPU


def test_kernel_programs_registered():
    """registry.collect() carries the kernel rows (the committed
    program_lint.json must cover them — test_program_lint pins that)."""
    from draco_tpu.analysis.registry import collect

    names = {p.name for p in collect()}
    assert {"kernel_cyclic_locator", "kernel_approx_decode",
            "cnn_cyclic_layer_step", "cnn_cyclic_layer_pallas_step",
            "cnn_approx_pallas_step",
            "lm_sp_ring_approx_pallas_many_k2"} <= names


# ---------------------------------------------------------------------------
# production step bodies on the fused path: eager-vs-chunked bitwise
# WITHIN the impl, bounded-err + identical flag columns vs the xla impl
# ---------------------------------------------------------------------------

def _mini_cfg(**overrides):
    from draco_tpu.config import TrainConfig

    kw = dict(network="LeNet", dataset="synthetic-mnist", approach="cyclic",
              batch_size=2, num_workers=8, worker_fail=1,
              err_mode="rev_grad", lr=0.01, momentum=0.9, max_steps=4,
              eval_freq=0, train_dir="", log_every=10 ** 9)
    kw.update(overrides)
    return TrainConfig(**kw)


@pytest.mark.slow  # two full train-setup builds + K=4 scan compiles
# (~40 s); the decode semantics are pinned by the fast coding-level
# equivalence tests above — this is the end-to-end integration layer
@pytest.mark.parametrize("overrides", [
    dict(decode_granularity="layer"),
    dict(approach="approx", worker_fail=0, redundancy="shared",
         code_redundancy=1.5),
])
def test_train_step_fused_decode_equivalence(overrides, rng):
    """The fused decode through the REAL step body: per-step losses and
    decoded updates bounded-err vs the xla impl, every discrete telemetry
    column (flag counts, detection counts, packed forensics masks)
    bit-identical, zero retraces across the 4 eager dispatches, and the
    K=4 chunk agreeing with the 4 eager steps WITHIN each impl at
    scan-vs-eager fusion noise (the strict bitwise K∈{1,4} contract lives
    at the Trainer level — tests/test_chunked_trainer.py — and stays on
    the xla path this suite leaves untouched; raw train_step-vs-train_many
    already differs at ~3e-8 on the unmodified xla impl)."""
    import numpy as np

    from draco_tpu import rng as drng
    from draco_tpu.models import input_shape
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.step import build_train_setup

    k = 4
    mesh = make_mesh(8)
    shape = input_shape("synthetic-mnist")
    xs = rng.randn(k, 8, 2, *shape).astype(np.float32)
    ys = rng.randint(0, 10, size=(k, 8, 2)).astype(np.int32)
    adv = drng.adversary_schedule(428, k + 1, 8, 1)
    masks = jnp.asarray(np.asarray(adv[1:k + 1]))

    discrete = {"located_errors", "det_tp", "det_adv", "honest_located",
                "recovered_fraction"}
    results = {}
    for impl in ("xla", "pallas"):  # pallas resolves to fused on CPU
        setup = build_train_setup(_mini_cfg(**overrides,
                                            decode_impl=impl), mesh)
        st = setup.state
        rows = []
        for i in range(k):
            st, m = setup.train_step(st, jnp.asarray(xs[i]),
                                     jnp.asarray(ys[i]), masks[i])
            rows.append({kk: np.asarray(v) for kk, v in m.items()})
        # compile-once contract: 4 dispatches, one executable (the fused
        # dispatch tag is static — a retrace here would be the silent
        # steady-state recompile the PR 5 sentinel guards against)
        assert setup.train_step._cache_size() == 1, impl
        # K=4 chunk vs the 4 eager steps, same impl
        setup2 = build_train_setup(_mini_cfg(**overrides,
                                             decode_impl=impl), mesh)
        st_many, block = setup2.train_many(
            setup2.state, jnp.asarray(xs), jnp.asarray(ys), masks, None)
        for li, (a, b) in enumerate(zip(jax.tree.leaves(st.params),
                                        jax.tree.leaves(st_many.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=f"{impl} leaf {li}")
        block = np.asarray(block)
        for i, name in enumerate(setup2.metric_names):
            col = np.asarray([r[name] for r in rows], np.float32)
            if name in discrete or name.startswith("wmask_"):
                np.testing.assert_array_equal(block[:, i], col,
                                              err_msg=f"{impl} {name}")
            else:
                np.testing.assert_allclose(block[:, i], col, rtol=1e-4,
                                           atol=1e-5,
                                           err_msg=f"{impl} {name}")
        results[impl] = (rows, st)

    rows_x, st_x = results["xla"]
    rows_f, st_f = results["pallas"]
    for i in range(k):
        for name in rows_x[i]:
            a, b = rows_x[i][name], rows_f[i][name]
            if name in discrete or name.startswith("wmask_"):
                np.testing.assert_array_equal(a, b,
                                              err_msg=f"step {i} {name}")
            else:
                np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4,
                                           err_msg=f"step {i} {name}")
    for a, b in zip(jax.tree.leaves(st_x.params),
                    jax.tree.leaves(st_f.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)
