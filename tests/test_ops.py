"""Pallas kernel correctness: interpret mode (CPU) vs the jnp reference path.

The same kernels were validated bit-for-bit-close on a real TPU v5e chip;
here they run under the Pallas interpreter so the suite stays hardware-free.
"""

import numpy as np
import pytest

from draco_tpu.ops import coded


@pytest.fixture
def mats(rng):
    n, d = 8, 5000  # d deliberately not a multiple of TILE_D (ragged edge)
    return (
        rng.normal(size=(n, n)).astype(np.float32),
        rng.normal(size=(n, n)).astype(np.float32),
        rng.normal(size=(n, d)).astype(np.float32),
        rng.normal(size=(n, d)).astype(np.float32),
        rng.normal(size=(d,)).astype(np.float32),
        rng.normal(size=(n,)).astype(np.float32),
        rng.normal(size=(n,)).astype(np.float32),
    )


def test_complex_matmul_interpret_matches_jnp(mats):
    wr, wi, g, _, _, _, _ = mats
    out_re, out_im = coded.complex_matmul(wr, wi, g, interpret=True)
    np.testing.assert_allclose(np.asarray(out_re), wr @ g, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out_im), wi @ g, rtol=1e-4, atol=1e-4)


def test_complex_project_interpret_matches_jnp(mats):
    _, _, rr, ri, f, _, _ = mats
    e_re, e_im = coded.complex_project(rr, ri, f, interpret=True)
    np.testing.assert_allclose(np.asarray(e_re), rr @ f, rtol=1e-3, atol=1e-2)
    np.testing.assert_allclose(np.asarray(e_im), ri @ f, rtol=1e-3, atol=1e-2)


def test_complex_recombine_interpret_matches_jnp(mats):
    _, _, rr, ri, _, vr, vi = mats
    out = coded.complex_recombine(vr, vi, rr, ri, interpret=True)
    np.testing.assert_allclose(np.asarray(out), vr @ rr - vi @ ri, rtol=1e-4, atol=1e-3)


def test_ragged_edge_masked_in_projection(rng):
    # the masked edge tile must not leak padding into the reduction
    n, d = 8, coded.TILE_D + 17
    rr = rng.normal(size=(n, d)).astype(np.float32)
    f = rng.normal(size=(d,)).astype(np.float32)
    e_re, _ = coded.complex_project(rr, rr, f, interpret=True)
    np.testing.assert_allclose(np.asarray(e_re), rr @ f, rtol=1e-3, atol=1e-2)


def test_small_d_single_tile(rng):
    n, d = 8, 64
    g = rng.normal(size=(n, d)).astype(np.float32)
    wr = np.eye(n, dtype=np.float32)
    wi = np.zeros((n, n), np.float32)
    out_re, out_im = coded.complex_matmul(wr, wi, g, interpret=True)
    np.testing.assert_allclose(np.asarray(out_re), g, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_im), 0 * g, atol=1e-6)
