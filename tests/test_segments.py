"""Streaming segmented wire (ISSUE 16): the quantum/bounds algebra, the
segmented ledger, the S=1 bitwise rail, S∈{2,4} equivalence on both
production loops (bounded-err aggregate, IDENTICAL detection P/R, guard
trips and forensics masks vs S=1, under a live adversary + straggler
drops, compile_guard="raise", 0 steady retraces), the autopilot
segments_up/segments_down dials, the decode-on-arrival pipeline rails,
and the flipped-row controls proving the perf_watch segment gates live.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu.config import TrainConfig
from draco_tpu.obs import numerics as nx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

Q = nx.SEGMENT_QUANTUM


# --------------------------------------------------------------------------
# quantum + bounds algebra (jax-free units)
# --------------------------------------------------------------------------

@pytest.mark.core
def test_segment_quantum_pins_tile_d():
    """SEGMENT_QUANTUM is the jax-free mirror of the decode kernels'
    d-tile: the two constants must never drift apart, or segment cuts
    stop landing on kernel tile boundaries."""
    from draco_tpu.ops import coded

    assert nx.SEGMENT_QUANTUM == coded.TILE_D


@pytest.mark.core
def test_wire_segment_bounds_algebra():
    b = nx.wire_segment_bounds(4 * Q, 4)
    assert b == (0, Q, 2 * Q, 3 * Q, 4 * Q)
    # monotone cover with quantum-aligned interior cuts, uneven d
    d = 2 * Q + 1808
    b = nx.wire_segment_bounds(d, 2)
    assert b[0] == 0 and b[-1] == d
    assert list(b) == sorted(set(b))
    assert all(c % Q == 0 for c in b[1:-1])
    # d smaller than one quantum collapses to a single segment, never
    # sub-quantum slivers
    assert nx.wire_segment_bounds(100, 4) == (0, 100)
    assert nx.wire_segment_bounds(Q, 8) == (0, Q)
    # degenerate sizes
    assert nx.wire_segment_bounds(0, 2) == (0, 0)
    assert nx.wire_segment_bounds(d, 1) == (0, d)
    # more segments than whole quanta: every emitted segment still real
    b = nx.wire_segment_bounds(3 * Q, 8)
    assert b == (0, Q, 2 * Q, 3 * Q)
    # int8 block that does not divide the quantum: cuts fall back to the
    # scale-block granularity so no block ever straddles a cut
    b = nx.wire_segment_bounds(1000, 2, block=48)
    assert b[0] == 0 and b[-1] == 1000
    assert all(c % 48 == 0 for c in b[1:-1]) and len(b) == 3


@pytest.mark.core
def test_cfg_segment_bounds_block_alignment():
    """cfg_segment_bounds is THE one bounds source: int8 wires align cuts
    to the per-block scale granularity (the quantize-then-slice bitwise
    invariance contract), f32 wires only to the kernel d-tile."""
    f32 = TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                      redundancy="shared", wire_segments=2)
    i8 = TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                     redundancy="shared", wire_segments=2,
                     wire_dtype="int8", shadow_block=48)
    d = 2 * Q + 96
    assert nx.cfg_segment_bounds(f32, d) == nx.wire_segment_bounds(d, 2)
    assert nx.cfg_segment_bounds(i8, d) == nx.wire_segment_bounds(d, 2,
                                                                  block=48)
    # shadow_block dividing the quantum keeps the quantum cuts
    i8b = TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                      redundancy="shared", wire_segments=2,
                      wire_dtype="int8", shadow_block=64)
    assert nx.cfg_segment_bounds(i8b, d) == nx.wire_segment_bounds(d, 2)


@pytest.mark.core
def test_wire_ledger_segments_block():
    """The ledger's segments block: per-segment physical bytes sum
    EXACTLY to the per-worker/per-step rows for every wire dtype — the
    block-aligned cuts hide no padding at the seams."""
    d = 3 * Q + 1000
    for kw, s in ((dict(), 1), (dict(wire_segments=4), 4),
                  (dict(wire_segments=2, wire_dtype="int8",
                        shadow_round="stochastic"), 2),
                  (dict(wire_segments=2, wire_dtype="bf16"), 2)):
        cfg = TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                          redundancy="shared", **kw)
        led = nx.wire_ledger(cfg, d)
        seg = led["segments"]
        assert seg["count"] == len(seg["bounds"]) - 1 == s
        assert seg["bounds"][0] == 0 and seg["bounds"][-1] == d
        assert sum(seg["physical_bytes_per_worker"]) == \
            led["physical_bytes_per_worker"]
        assert sum(seg["physical_bytes_per_step"]) == \
            led["physical_bytes_per_step"]
        assert len(seg["physical_bytes_per_worker"]) == s


@pytest.mark.core
def test_config_rejects_bad_segments():
    with pytest.raises(ValueError, match="wire_segments"):
        TrainConfig(approach="cyclic", worker_fail=1, num_workers=8,
                    redundancy="shared", wire_segments=0).validate()
    with pytest.raises(ValueError, match="coded approach"):
        TrainConfig(approach="baseline", wire_segments=2).validate()
    # every coded family may segment (maj_vote wire/ledger-only)
    for ap, kw in (("cyclic", dict(worker_fail=1, redundancy="shared")),
                   ("maj_vote", dict(group_size=4, worker_fail=1)),
                   ("approx", dict(worker_fail=0, redundancy="shared",
                                   code_redundancy=1.5))):
        TrainConfig(approach=ap, num_workers=8, wire_segments=2,
                    **kw).validate()


# --------------------------------------------------------------------------
# decode units: the S=1 rail and the segmented fold
# --------------------------------------------------------------------------

def _cyclic_fixture(n=8, s=1, d=3 * Q):
    from draco_tpu.coding import cyclic

    code = cyclic.build_cyclic_code(n, s)
    rs = np.random.RandomState(7)
    grads = jnp.asarray(rs.randn(n, d).astype(np.float32) * 0.1)
    r_re, r_im = cyclic.encode_shared(code, grads)
    # one live corrupt row — the locator must find it in EVERY segment
    r_re = r_re.at[2].multiply(-50.0)
    r_im = r_im.at[2].multiply(-50.0)
    rf = jnp.asarray(rs.choice([-1.0, 1.0], d).astype(np.float32))
    return code, grads, r_re, r_im, rf


def test_cyclic_single_segment_is_the_unsegmented_decode():
    """decode_segments over the trivial (0, d) partition agrees with the
    unsegmented decode: same honest set, same health verdict, aggregate
    to float noise (the vmapped locator lowers differently, so the
    PRODUCTION S=1 bitwise rail is structural — training/step.py never
    enters the segmented path at S=1; the loop-level tests below pin
    that)."""
    from draco_tpu.coding import cyclic

    code, _, r_re, r_im, rf = _cyclic_fixture()
    dec, honest, health = cyclic.decode(code, r_re, r_im, rf,
                                        with_health=True)
    d1, h1, he1 = cyclic.decode_segments(code, r_re, r_im, rf,
                                         (0, r_re.shape[1]),
                                         with_health=True)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(d1),
                               rtol=1e-5, atol=1e-7)
    assert h1.shape == (1, code.n)
    np.testing.assert_array_equal(np.asarray(honest), np.asarray(h1[0]))
    np.testing.assert_array_equal(np.asarray(health["flagged"]),
                                  np.asarray(he1["flagged"]))
    # both residuals sit at float-noise scale; compare absolutely
    np.testing.assert_allclose(float(health["residual"]),
                               float(he1["residual"]), atol=1e-6)


@pytest.mark.parametrize("segs", [2, 3])
def test_cyclic_segmented_fold(segs):
    """S>1: bounded-err aggregate vs the unsegmented decode, every
    segment's locator finds the corrupt row (flagged fold = union is
    IDENTICAL to the unsegmented flag set), and each segment's honest
    mask keeps exactly n-2s rows."""
    from draco_tpu.coding import cyclic

    code, grads, r_re, r_im, rf = _cyclic_fixture()
    d = r_re.shape[1]
    bounds = nx.wire_segment_bounds(d, segs)
    assert len(bounds) == segs + 1
    dec, _, health = cyclic.decode(code, r_re, r_im, rf, with_health=True)
    dS, hS, heS = cyclic.decode_segments(code, r_re, r_im, rf, bounds,
                                         with_health=True)
    truth = np.asarray(jnp.sum(grads, axis=0)) / code.n
    np.testing.assert_allclose(np.asarray(dS), truth, rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dS), np.asarray(dec),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(heS["flagged"]),
                                  np.asarray(health["flagged"]))
    assert bool(heS["flagged"][2])
    assert float(heS["residual"]) < 1e-3
    hS = np.asarray(hS)
    assert hS.shape == (segs, code.n)
    assert (hS.sum(axis=1) == code.n - 2 * code.s).all()
    assert not hS[:, 2].any()  # the corrupt row never recombines


def test_approx_segmented_decode_is_exact():
    """The approx family's decode matvec is column-separable and its
    weight solve presence-only: the segmented decode equals the
    unsegmented one BITWISE, and the residual health (accumulated across
    segments before the sqrt) agrees to float noise."""
    from draco_tpu.coding import approx

    n, d = 8, 2 * Q + 512
    code = approx.build_approx_code(n, 1.5)
    rs = np.random.RandomState(11)
    grads = jnp.asarray(rs.randn(n, d).astype(np.float32) * 0.1)
    rows = approx.encode_shared(code, grads)
    present = jnp.asarray(np.array([True] * n))
    present = present.at[3].set(False).at[6].set(False)
    out, v, health = approx.decode(code, rows, present=present,
                                   with_health=True, batch_grads=grads)
    outS, vS, healthS = approx.decode_segments(
        code, rows, nx.wire_segment_bounds(d, 2), present=present,
        with_health=True, batch_grads=grads)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(outS))
    np.testing.assert_array_equal(np.asarray(v), np.asarray(vS))
    np.testing.assert_allclose(float(health["residual"]),
                               float(healthS["residual"]), rtol=1e-5)
    assert float(healthS["bound"]) == float(health["bound"])


# --------------------------------------------------------------------------
# production-loop equivalence: CNN Trainer, S ∈ {1, 2, 4} × K ∈ {1, 4}
# --------------------------------------------------------------------------

# the committed adversarial scenario (tests/test_chunked_trainer.py): a
# LIVE rev_grad adversary plus a straggler drop inside the cyclic joint
# budget (n=9, s=2, t=1, e=1), guards + incident engine on, strict
# compile sentinel — every run here is also a 0-retrace assertion
CYC = dict(approach="cyclic", num_workers=9, worker_fail=2,
           adversary_count=1, err_mode="rev_grad", straggle_mode="drop",
           straggle_count=1, redundancy="shared")

# detection / guard / forensics columns that must be IDENTICAL between a
# segmented run and its S=1 twin, step by step: the per-segment locators
# fold to ONE per-step verdict (decode_segments docstring), so P/R, guard
# trips and the packed accusation masks cannot move. (honest_located is
# deliberately absent: which honest rows recombine may shift per segment;
# loss/prec drift at f32 noise with the aggregate.)
DET_COLS = ("det_adv", "det_tp", "located_errors", "guard_trips",
            "skipped_steps", "present")


def _train_cfg(**kw):
    base = dict(network="FC", dataset="synthetic-mnist", batch_size=4,
                lr=0.01, momentum=0.9, num_workers=8, max_steps=6,
                eval_freq=0, train_dir="", log_every=1,
                compile_guard="raise", step_guard="on",
                incident_watch="on")
    base.update(kw)
    return TrainConfig(**base)


def _stream(train_dir):
    out = []
    with open(os.path.join(train_dir, "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if "loss" in rec and rec.get("split") != "eval":
                out.append(rec)
    return out


def _assert_detection_equal(stream_s, stream_1, n):
    from draco_tpu.obs.forensics import record_masks

    assert len(stream_s) == len(stream_1) > 0
    for rs_, r1 in zip(stream_s, stream_1):
        assert rs_["step"] == r1["step"]
        for col in DET_COLS:
            # routes differ in which columns they emit ("present" is
            # trainer-only) but segmented/unsegmented twins must agree
            # on the set AND the values
            assert (col in rs_) == (col in r1), (r1["step"], col)
            if col in r1:
                assert rs_[col] == r1[col], (r1["step"], col)
        assert "det_adv" in r1  # the live-adversary columns must exist
        ms, m1 = record_masks(rs_, n), record_masks(r1, n)
        assert ms is not None and m1 is not None
        # the packed forensics bitmasks fold across segments to the SAME
        # verdict: accused / adversarial / present bit for bit
        for key in ("accused", "adv", "present"):
            assert ms[key] == m1[key], (r1["step"], key)


def test_cnn_segmented_equivalence(tmp_path):
    """S ∈ {1, 2, 4} × K ∈ {1, 4} under the live adversary + straggler:
    K∈{1,4} stays bitwise within every S (the scan chain is untouched by
    segmentation); S>1 keeps a bounded-err aggregate and IDENTICAL
    detection columns + forensics masks vs S=1; the S=2 chunked run's
    status ledger and dispatch spans carry the segment count while the
    S=1 trace records stay segment-free (the bitwise rail)."""
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.trainer import Trainer

    ds = load_dataset("synthetic-mnist", synthetic_train=512,
                      synthetic_test=64)
    mesh = make_mesh(9)
    out = {}
    for s in (1, 2, 4):
        for k in (1, 4):
            d = str(tmp_path / f"s{s}_k{k}")
            tr = Trainer(_train_cfg(**CYC, steps_per_call=k,
                                    wire_segments=s, train_dir=d,
                                    trace_dir=d),
                         mesh=mesh, dataset=ds, quiet=True)
            tr.run()
            snap = tr.compile_watch.snapshot()
            assert snap["steady_recompiles"] == 0
            out[s, k] = (np.concatenate([
                np.ravel(x) for x in
                jax.tree.leaves(jax.device_get(tr.state.params))]),
                _stream(d))
            tr.close()
    for s in (1, 2, 4):
        # both loops: eager vs scan-chunked bitwise within the S
        np.testing.assert_array_equal(out[s, 1][0], out[s, 4][0])
        det = [{c: r[c] for c in DET_COLS} for r in out[s, 1][1]]
        assert det == [{c: r[c] for c in DET_COLS} for r in out[s, 4][1]]
    for s in (2, 4):
        # bounded-err aggregate, identical verdicts vs the S=1 twin
        np.testing.assert_allclose(out[s, 4][0], out[1, 4][0],
                                   rtol=5e-4, atol=1e-5)
        _assert_detection_equal(out[s, 4][1], out[1, 4][1], 9)
        assert any(out[s, 4][0] != out[1, 4][0]), \
            "segmented decode unexpectedly bitwise — rail not exercised"

    # the segmented status ledger (obs/numerics.wire_ledger)
    status = json.load(open(tmp_path / "s2_k4" / "status.json"))
    seg = status["wire"]["segments"]
    assert seg["count"] == len(seg["bounds"]) - 1 == 2
    assert sum(seg["physical_bytes_per_worker"]) == \
        status["wire"]["physical_bytes_per_worker"]
    # dispatch spans carry the live segment count ONLY when S>1
    # (control/engine.py): S=1 trace records stay byte-identical to the
    # pre-segmentation suites
    for s, want in ((1, None), (2, 2)):
        trace = json.load(open(tmp_path / f"s{s}_k4" / "trace.json"))
        spans = [e for e in trace["traceEvents"]
                 if e.get("ph") == "X" and e["name"] == "dispatch"]
        assert spans
        for e in spans:
            assert (e.get("args") or {}).get("segments") == want, (s, e)


# --------------------------------------------------------------------------
# production-loop equivalence: LM sp route, S=2 vs S=1
# --------------------------------------------------------------------------

def test_lm_sp_segmented_equivalence(tmp_path):
    """The same fold discipline through the LM single-shard route
    (parallel/common.aggregate_flat_grads — the seam all five LM routes
    share): S=2 vs S=1 under a live adversary, K=4 scan, strict compile
    sentinel — bounded-err params, identical detection columns and
    forensics masks per record."""
    from draco_tpu.parallel import make_mesh_2d
    from draco_tpu.parallel.sp_step import train_sp

    out = {}
    for s in (1, 2):
        d = str(tmp_path / f"lm_s{s}")
        cfg = _train_cfg(
            network="TransformerLM", dataset="synthetic-text",
            batch_size=2, max_steps=8, eval_freq=4, steps_per_call=4,
            seq_len=16, vocab=64, model_dim=64, model_heads=2,
            model_layers=1, approach="cyclic", worker_fail=1,
            adversary_count=1, err_mode="rev_grad", redundancy="shared",
            wire_segments=s, train_dir=d)
        state, metrics = train_sp(cfg, make_mesh_2d(cfg.num_workers, 1),
                                  quiet=True)
        assert np.isfinite(metrics["loss"])
        out[s] = (np.concatenate([
            np.ravel(x) for x in
            jax.tree.leaves(jax.device_get(state.params))]), _stream(d))
    np.testing.assert_allclose(out[2][0], out[1][0], rtol=5e-4, atol=1e-5)
    _assert_detection_equal(out[2][1], out[1][1], 8)
    # the model really spans >1 segment (else this test proves nothing)
    status = json.load(open(tmp_path / "lm_s2" / "status.json"))
    assert status["wire"]["segments"]["count"] == 2


# --------------------------------------------------------------------------
# autopilot segment dials
# --------------------------------------------------------------------------

def test_autopilot_segment_dials(tmp_path):
    """The straggler ladder's first rung (control/autopilot.py): a
    sustained straggle episode fires segments_up — a warm program swap to
    the SAME family at S=2 (its own compile-sentinel label, compiled
    once) — and sustained straggle-quiet evidence fires segments_down
    back to the configured count, both attributed, 0 steady retraces,
    ending in the base regime."""
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.training.trainer import Trainer

    d = str(tmp_path / "ap")
    cfg = TrainConfig(
        network="FC", dataset="synthetic-mnist", batch_size=4, lr=0.02,
        momentum=0.9, num_workers=8, max_steps=20, eval_freq=4,
        train_dir=d, log_every=1, steps_per_call=4, approach="cyclic",
        worker_fail=1, adversary_count=0, err_mode="rev_grad",
        redundancy="shared", step_guard="on", incident_watch="on",
        compile_guard="raise", autopilot="on",
        # the family dials are parked so the scenario isolates the
        # segment rung; segments_max=2 caps the up-dial at one swap
        autopilot_policy=("segments_up_boundaries=1,segments_max=2,"
                          "segments_down_boundaries=1,"
                          "dial_down_boundaries=99,clean_boundaries=99"),
        incident_thresholds="straggle.streak=2",
        fault_spec="straggle@5-12:w5",
    )
    ds = load_dataset("synthetic-mnist", synthetic_train=512,
                      synthetic_test=64)
    tr = Trainer(cfg, dataset=ds, quiet=True)
    last = tr.run()
    snap = tr.compile_watch.snapshot()
    tr.close()
    assert np.isfinite(last["loss"]) and last["step"] == 20
    assert snap["steady_recompiles"] == 0

    rems = [json.loads(l) for l in
            open(os.path.join(d, "incidents.jsonl"))]
    rems = [e for e in rems if e.get("event") == "remediation"]
    assert [e["action"] for e in rems] == ["segments_up", "segments_down"]
    up, down = rems
    assert up["regime"]["tag"] == "cyclic_r3_seg2"
    assert up["regime"]["wire_segments"] == 2
    assert up["trigger"]["type"] in ("straggle", "starvation")
    assert up["evidence"]["wire_segments_before"] == 1
    assert up["evidence"]["wire_segments_after"] == 2
    assert up["evidence"]["executable"] == "compiled"
    assert down["regime"]["tag"] == "cyclic_r3"
    assert down["evidence"]["wire_segments_after"] == 1

    # warm-swap compile contract: the segmented program built exactly
    # once under its own sentinel label
    ledger = [json.loads(l) for l in
              open(os.path.join(d, "compiles.jsonl"))]
    labels = {}
    for r in ledger:
        if r["program"]:
            labels[r["program"]] = labels.get(r["program"], 0) + 1
    assert labels.get("train_many@cyclic_r3_seg2[4]") == 1, labels
    assert not any(r["steady_recompile"] for r in ledger)

    st = json.load(open(os.path.join(d, "status.json")))
    assert st["state"] == "done"
    assert st["control"]["regime"]["tag"] == "cyclic_r3"
    assert st["control"]["swaps"] == 2
    # the wire ledger was re-stamped back to the single-segment shape
    assert st["wire"]["segments"]["count"] == 1


# --------------------------------------------------------------------------
# decode-on-arrival pipeline rails (control/engine.SegmentPipeline)
# --------------------------------------------------------------------------

@pytest.mark.core
def test_segment_pipeline_rails():
    """The measurement harness's two rails: pipelined interleaves
    transfer j+1 between decode j's dispatch and its drain (the overlap
    window); serial drains first, forbidding overlap by construction."""
    from draco_tpu.control.engine import SegmentPipeline
    from draco_tpu.obs.tracer import NullTracer

    calls = []

    def mk(pipelined):
        calls.clear()
        return SegmentPipeline(
            NullTracer(),
            put=lambda j, h: calls.append(("put", j)) or h * 10,
            decode=lambda j, dev: calls.append(("decode", j)) or dev + j,
            drain=lambda out: calls.append(("drain", out)),
            pipelined=pipelined)

    p = mk(True)
    res = p.run([1, 2, 3])
    assert res == [10, 21, 32]
    assert [(e["name"], e["segment"]) for e in p.events] == [
        ("segment_xfer", 0), ("segment_decode", 0),
        ("segment_xfer", 1), ("segment_drain", 0),
        ("segment_decode", 1), ("segment_xfer", 2),
        ("segment_drain", 1), ("segment_decode", 2),
        ("segment_drain", 2)]
    over, inflight = p.overlap_us()
    assert over >= 0.0 and inflight >= 0.0

    p = mk(False)
    assert p.run([1, 2, 3]) == [10, 21, 32]
    assert [(e["name"], e["segment"]) for e in p.events] == [
        ("segment_xfer", 0), ("segment_decode", 0), ("segment_drain", 0),
        ("segment_xfer", 1), ("segment_decode", 1), ("segment_drain", 1),
        ("segment_xfer", 2), ("segment_decode", 2), ("segment_drain", 2)]
    over, inflight = p.overlap_us()
    assert over == 0.0  # drain precedes the next transfer: no overlap
    assert p.run([]) == []


# --------------------------------------------------------------------------
# perf_watch segment gates — the flipped-row controls
# --------------------------------------------------------------------------

def test_perf_watch_segment_gates_flipped_rows(tmp_path):
    """The ISSUE 16 fold (tools/perf_watch.fold_segment_study): the
    pipeline-win and overlap acceptance bools gate at tolerance 0; the
    per-cell segment counts and per-segment physical bytes are PINNED in
    BOTH directions; the S=1 row's overlap is pinned at exactly 0."""
    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()
    path = root / "baselines_out" / "segment_study.json"
    out = root / "report.json"

    def artifact(win_ms=20.0, win_overlap=0.5, s1_overlap=0.0,
                 seg_bytes=(400, 400), count=2):
        return {"all_ok": True, "rows": [
            {"dtype": "f32", "segments": 1, "ms_per_step": 100.0,
             "overlap_frac": s1_overlap,
             "wire": {"segments": {"count": 1,
                                   "physical_bytes_per_worker": [800]}},
             "ok": True},
            {"dtype": "f32", "segments": 2, "ms_per_step": 80.0,
             "overlap_frac": 0.5,
             "wire": {"segments": {
                 "count": count,
                 "physical_bytes_per_worker": list(seg_bytes)}},
             "ok": True},
        ], "win": {"dtype": "f32", "segments": 2,
                   "ms_per_step_win": win_ms, "win_frac": win_ms / 100.0,
                   "overlap_frac": win_overlap}}

    path.write_text(json.dumps(artifact()))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    for key in ("segment.all_ok", "segment.win.positive",
                "segment.win.overlap_positive",
                "segment.f32.s1.overlap_frac",
                "segment.f32.s2.ms_per_step",
                "segment.f32.s2.segments_count",
                "segment.f32.s2.seg0_bytes_per_worker"):
        assert key in snap["metrics"], key
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    def gated(art, *metrics):
        path.write_text(json.dumps(art))
        assert perf_watch.main(["--root", str(root), "--json",
                                str(out)]) == 1
        regs = {r["metric"] for r in
                json.loads(out.read_text())["regressions"]}
        for m in metrics:
            assert m in regs, (m, regs)

    # the pipeline win going non-positive gates (the acceptance bool)
    gated(artifact(win_ms=-5.0), "segment.win.positive")
    # the overlap evidence vanishing gates
    gated(artifact(win_overlap=0.0), "segment.win.overlap_positive")
    # the S=1 row measuring ANY overlap means the metric broke: pinned
    gated(artifact(s1_overlap=0.1), "segment.f32.s1.overlap_frac")
    # per-segment bytes pinned in BOTH directions
    gated(artifact(seg_bytes=(401, 400)),
          "segment.f32.s2.seg0_bytes_per_worker")
    gated(artifact(seg_bytes=(399, 400)),
          "segment.f32.s2.seg0_bytes_per_worker")
    # a segment silently appearing is a wire-format change, never noise
    gated(artifact(count=3), "segment.f32.s2.segments_count")
