"""Cross-cutting LM-path behaviors: rematerialisation (must change memory
only — losses and per-worker gradients identical on every path) and
straggler erasures (the CNN path's semantics, now shared through
parallel/common.aggregate_flat_grads)."""

import jax
import numpy as np
import pytest

from draco_tpu.config import TrainConfig
from draco_tpu.parallel import make_mesh_2d, make_mesh_wpp, make_mesh_wtp
from draco_tpu.parallel.pp_step import build_pp_train_setup
from draco_tpu.parallel.sp_step import build_sp_train_setup
from draco_tpu.parallel.tp_step import build_tp_train_setup
from tests.test_parallel_pp import _cfg, _toks


def _lm_cfg(**kw):
    return _cfg(pipeline_shards=1, pp_microbatches=0, **kw)


def test_lm_attention_sees_full_tile_friendly_t(monkeypatch):
    """attn_impl='flash' on the tp/pp LM paths must hand the attention the
    FULL T-token sequence, never T-1: the pre-r5 toks[:, :-1] slice made
    t=1023 at T=1024, failing the kernel's t%8 tiling so every 'flash' LM
    run silently measured the dense fallback (commit 69ae479). Recorded
    via a probe attn_fn; also asserts the shipped default blocks accept
    the shape (the probe t must be kernel-eligible)."""
    import draco_tpu.ops.flash_attention as fa

    seen = []

    def probe(q, k, v, **kw):
        seen.append(q.shape[1])
        from draco_tpu.parallel.ring_attention import dense_attention
        return dense_attention(q, k, v, causal=True)

    monkeypatch.setattr(fa, "flash_attention", probe)

    for build, mesh, extra in [
        (build_tp_train_setup, make_mesh_wtp(2, 1), {}),
        (build_pp_train_setup, make_mesh_wpp(2, 1),
         dict(pipeline_shards=1, pp_microbatches=1)),
    ]:
        cfg = _cfg(attn_impl="flash", seq_len=16, **extra)
        setup = build(cfg, mesh)
        toks = _toks(cfg)
        seen.clear()  # drop the init pass (t = min(seq_len, 8) by design)
        setup.train_step(setup.state, toks, np.zeros(2, dtype=bool))
        assert seen, "probe attention never called"
        assert all(t == cfg.seq_len for t in seen), seen
        bq = fa._fit_block(512, seen[0], lane_rule=False)
        bk = fa._fit_block(1024, seen[0], lane_rule=True)
        assert fa._kernel_eligible(seen[0], bq, bk, 64, True, False)


def test_tp_remat_grads_exact():
    cfg0 = _lm_cfg(num_workers=4, tensor_shards=2)
    cfg1 = _lm_cfg(num_workers=4, tensor_shards=2, remat=True)
    mesh = make_mesh_wtp(4, 2)
    s0 = build_tp_train_setup(cfg0, mesh)
    s1 = build_tp_train_setup(cfg1, mesh)
    toks = _toks(cfg0)
    adv = np.zeros(4, dtype=bool)
    st0, m0 = s0.train_step(s0.state, toks, adv)
    st1, m1 = s1.train_step(s1.state, toks, adv)
    assert float(m0["loss"]) == pytest.approx(float(m1["loss"]), rel=1e-6)
    a = np.asarray(jax.device_get(st0.params["embed"]["embedding"]))
    b = np.asarray(jax.device_get(st1.params["embed"]["embedding"]))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_pp_remat_grads_exact():
    cfg0 = _cfg(num_workers=2, pipeline_shards=4)
    cfg1 = _cfg(num_workers=2, pipeline_shards=4, remat=True)
    mesh = make_mesh_wpp(2, 4)
    s0 = build_pp_train_setup(cfg0, mesh)
    s1 = build_pp_train_setup(cfg1, mesh)
    toks = _toks(cfg0)
    g0, _ = s0.per_worker_grads(s0.state.params, toks)
    g1, _ = s1.per_worker_grads(s1.state.params, toks)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(g0)), np.asarray(jax.device_get(g1)),
        rtol=1e-6, atol=1e-7,
    )


def test_lm_straggler_erasure_decode_exact():
    """LM paths now share the CNN path's straggler semantics: cyclic decode
    around <= 2s erasures reconstructs the exact clean update (the dropped
    rows' batch gradients are algebraically recovered from the code)."""
    from draco_tpu.parallel.sp_step import synthetic_text

    cfg = _lm_cfg(num_workers=8, approach="cyclic", worker_fail=1,
                  adversary_count=0)
    mesh = make_mesh_wtp(8, 1)
    setup = build_tp_train_setup(cfg, mesh)
    toks = jax.numpy.asarray(
        synthetic_text(cfg.seed, 1, 8, cfg.batch_size, cfg.seq_len, cfg.vocab)
    )
    adv = np.zeros(8, dtype=bool)
    present = np.ones(8, dtype=bool)
    present[[2, 5]] = False  # 2 erasures <= 2s
    st_clean, _ = setup.train_step(setup.state, toks, adv)
    setup2 = build_tp_train_setup(cfg, mesh)
    st_drop, _ = setup2.train_step(setup2.state, toks, adv, present)
    a = np.asarray(jax.device_get(st_clean.params["embed"]["embedding"]))
    b = np.asarray(jax.device_get(st_drop.params["embed"]["embedding"]))
    np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-5)


def test_lm_layer_decode_matches_global():
    """decode_granularity=layer (one locator per parameter tensor, the
    reference's shape — cyclic_master.py:125-129) agrees with the global
    decode when corruption is per-worker, on the LM path too."""
    from draco_tpu.parallel.sp_step import synthetic_text

    jnp = jax.numpy
    outs = {}
    for gran in ("global", "layer"):
        cfg = _lm_cfg(num_workers=8, approach="cyclic", worker_fail=1,
                      decode_granularity=gran)
        setup = build_tp_train_setup(cfg, make_mesh_wtp(8, 1))
        toks = jnp.asarray(synthetic_text(cfg.seed, 1, 8, cfg.batch_size,
                                          cfg.seq_len, cfg.vocab))
        adv = np.zeros(8, dtype=bool)
        adv[3] = True
        st, m = setup.train_step(setup.state, toks, adv)
        outs[gran] = np.asarray(
            jax.device_get(st.params["embed"]["embedding"]))
        assert np.isfinite(float(m["loss"]))
    np.testing.assert_allclose(outs["global"], outs["layer"],
                               rtol=5e-4, atol=1e-5)


def test_lm_straggler_loop_runs():
    """run_token_loop threads the straggler schedule through any LM path
    (here pp) with masked robust aggregation."""
    from draco_tpu.parallel.pp_step import train_pp

    cfg = _cfg(num_workers=4, pipeline_shards=2, model_layers=2,
               mode="geometric_median", worker_fail=1,
               straggle_mode="drop", straggle_count=1, max_steps=3)
    state, metrics = train_pp(cfg, make_mesh_wpp(4, 2), steps=3, quiet=True)
    assert np.isfinite(float(metrics["loss"]))


def test_sp_remat_ring_attention_exact():
    """remat recomputes blocks containing ring ppermute hops — the
    recompute's collectives must replay identically."""
    cfg0 = _lm_cfg(num_workers=2, seq_shards=4)
    cfg1 = _lm_cfg(num_workers=2, seq_shards=4, remat=True)
    mesh = make_mesh_2d(2, 4)
    s0 = build_sp_train_setup(cfg0, mesh)
    s1 = build_sp_train_setup(cfg1, mesh)
    toks = _toks(cfg0)
    adv = np.zeros(2, dtype=bool)
    st0, m0 = s0.train_step(s0.state, toks, adv)
    st1, m1 = s1.train_step(s1.state, toks, adv)
    assert float(m0["loss"]) == pytest.approx(float(m1["loss"]), rel=1e-6)
    a = np.asarray(jax.device_get(st0.params["embed"]["embedding"]))
    b = np.asarray(jax.device_get(st1.params["embed"]["embedding"]))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
