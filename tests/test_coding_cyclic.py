"""Property tests for the cyclic code — the tests the reference never had
(SURVEY.md §4): parity-check annihilation, exact decode∘encode recovery,
recovery under ≤ s Byzantine rows, agreement with an independent numpy oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu.coding import cyclic


def numpy_oracle_decode(code, R, rand_factor):
    """Independent float64 complex decode following the published algorithm
    (syndrome -> error locator -> honest-set recombination)."""
    n, s = code.n, code.s
    c = cyclic._dft_c(n)
    c1 = c[:, : n - 2 * s]
    c2 = c[:, n - 2 * s :]
    e = R @ rand_factor
    e2 = c2.conj().T @ e
    if s > 0:
        A = np.empty((s, s), dtype=complex)
        b = np.empty((s,), dtype=complex)
        for i in range(s):
            A[i] = e2[s - i - 1 : 2 * s - i - 1]
            b[i] = e2[2 * s - i - 1]
        alpha, *_ = np.linalg.lstsq(A, b, rcond=None)
        poly = np.concatenate([-alpha, [1.0]])
        z = np.exp(2j * np.pi * np.arange(n) / n)
        vals = np.stack([z**j for j in range(s + 1)], axis=1) @ poly
        mags = np.abs(vals)
    else:
        mags = np.ones(n)
    # top n-2s rows by locator magnitude (corrupt rows are roots -> bottom s);
    # mask marks exactly the rows used — same policy as cyclic.decode
    idx = np.sort(np.argsort(-mags, kind="stable")[: n - 2 * s])
    honest = np.zeros(n, dtype=bool)
    honest[idx] = True
    rec = c1[idx]
    e1 = np.zeros(n - 2 * s)
    e1[0] = 1.0
    v, *_ = np.linalg.lstsq(rec.T, e1, rcond=None)
    v_full = np.zeros(n, dtype=complex)
    v_full[idx] = v
    return np.real(v_full @ R) / n, honest


@pytest.mark.parametrize("n,s", [(7, 1), (8, 1), (11, 2), (15, 3)])
def test_construction_properties(n, s):
    code = cyclic.build_cyclic_code(n, s)
    # support: each row has exactly 2s+1 nonzeros on its cyclic window
    assert (code.support.sum(axis=1) == 2 * s + 1).all()
    # W respects the support up to least-squares residual
    off = code.w_full * (1 - code.support)
    assert np.abs(off).max() < 1e-7
    # parity check: C2^H annihilates the code space (coding.py:80-85's
    # manual check, automated)
    c2h = code.c2h_re + 1j * code.c2h_im
    assert np.abs(c2h @ code.w_full).max() < 1e-5
    # decodability: ones^T lies in the row space of W restricted to any
    # (n-2s)-subset of honest rows — checked via v from C1
    assert code.batch_ids.shape == (n, 2 * s + 1)


@pytest.mark.parametrize("n,s", [(7, 1), (11, 2), (15, 3)])
def test_exact_recovery_no_adversary(n, s, rng):
    code = cyclic.build_cyclic_code(n, s)
    d = 64
    batch_grads = rng.randn(n, d).astype(np.float32)
    # every worker honestly encodes its window
    g = batch_grads[code.batch_ids]  # (n, hat_s, d)
    enc_re, enc_im = cyclic.encode(code, jnp.asarray(g))
    rf = np.ones(d, dtype=np.float32)
    dec, honest = cyclic.decode(code, enc_re, enc_im, jnp.asarray(rf))
    want = batch_grads.sum(axis=0) / n
    np.testing.assert_allclose(np.asarray(dec), want, rtol=2e-4, atol=2e-4)
    # mask reports the n-2s rows used for recombination
    assert np.asarray(honest).sum() == n - 2 * s


@pytest.mark.parametrize("n,s", [(7, 1), (11, 2), (15, 3)])
@pytest.mark.parametrize("attack", ["rev_grad", "constant"])
def test_exact_recovery_under_attack(n, s, attack, rng):
    from draco_tpu.attacks import inject_cyclic

    code = cyclic.build_cyclic_code(n, s)
    d = 128
    batch_grads = rng.randn(n, d).astype(np.float32)
    g = batch_grads[code.batch_ids]
    enc_re, enc_im = cyclic.encode(code, jnp.asarray(g))
    adv = np.zeros(n, dtype=bool)
    adv[rng.choice(n, size=s, replace=False)] = True
    enc_re, enc_im = inject_cyclic(enc_re, enc_im, jnp.asarray(adv), attack)
    rf = rng.normal(loc=1.0, size=d).astype(np.float32)
    dec, honest = cyclic.decode(code, enc_re, enc_im, jnp.asarray(rf))
    want = batch_grads.sum(axis=0) / n
    np.testing.assert_allclose(np.asarray(dec), want, rtol=5e-3, atol=5e-3)
    # located honest set must exclude every adversary
    assert not np.asarray(honest)[adv].any()


def test_matches_numpy_oracle(rng):
    n, s, d = 11, 2, 96
    code = cyclic.build_cyclic_code(n, s)
    batch_grads = rng.randn(n, d).astype(np.float32)
    g = batch_grads[code.batch_ids]
    enc_re, enc_im = cyclic.encode(code, jnp.asarray(g))
    R = np.asarray(enc_re) + 1j * np.asarray(enc_im)
    adv = rng.choice(n, size=s, replace=False)
    R[adv] += -100.0 * R[adv]
    rf = rng.normal(loc=1.0, size=d)
    want, honest_np = numpy_oracle_decode(code, R, rf)
    dec, honest = cyclic.decode(
        code, jnp.asarray(R.real.astype(np.float32)), jnp.asarray(R.imag.astype(np.float32)),
        jnp.asarray(rf.astype(np.float32)),
    )
    np.testing.assert_allclose(np.asarray(dec), want, rtol=5e-3, atol=5e-3)
    np.testing.assert_array_equal(np.asarray(honest), honest_np)


def test_encode_shared_equals_encode(rng):
    n, s, d = 9, 2, 32
    code = cyclic.build_cyclic_code(n, s)
    batch_grads = rng.randn(n, d).astype(np.float32)
    g = batch_grads[code.batch_ids]
    re1, im1 = cyclic.encode(code, jnp.asarray(g))
    re2, im2 = cyclic.encode_shared(code, jnp.asarray(batch_grads))
    np.testing.assert_allclose(np.asarray(re1), np.asarray(re2), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(im1), np.asarray(im2), rtol=1e-5, atol=1e-5)


def test_decode_is_jittable():
    code = cyclic.build_cyclic_code(7, 1)
    d = 16
    r_re = jnp.zeros((7, d))
    r_im = jnp.zeros((7, d))
    rf = jnp.ones((d,))
    jitted = jax.jit(lambda a, b, c: cyclic.decode(code, a, b, c))
    dec, honest = jitted(r_re, r_im, rf)
    assert dec.shape == (d,)


@pytest.mark.parametrize("n,s", [(7, 1), (11, 2)])
def test_decode_layers_matches_global(n, s, rng):
    """Per-layer locators (reference: cyclic_master.py:125-129) agree with the
    global decode when corruption is per-worker — whole rows attacked, the
    only corruption the wire protocol admits."""
    from draco_tpu.attacks import inject_cyclic

    d = 96
    code = cyclic.build_cyclic_code(n, s)
    batch_grads = rng.randn(n, d).astype(np.float32)
    g = batch_grads[code.batch_ids]
    enc_re, enc_im = cyclic.encode(code, jnp.asarray(g))
    adv = np.zeros(n, dtype=bool)
    adv[rng.choice(n, size=s, replace=False)] = True
    enc_re, enc_im = inject_cyclic(enc_re, enc_im, jnp.asarray(adv), "rev_grad")
    rf = rng.normal(loc=1.0, size=d).astype(np.float32)
    offsets = [0, 17, 40, d]  # three unequal "layers"
    dec_g, honest_g = cyclic.decode(code, enc_re, enc_im, jnp.asarray(rf))
    dec_l, honest_l = cyclic.decode_layers(code, enc_re, enc_im, jnp.asarray(rf),
                                           offsets)
    np.testing.assert_allclose(np.asarray(dec_l), np.asarray(dec_g),
                               rtol=5e-3, atol=5e-3)
    # every layer locates the same honest set, and none admits an adversary
    assert (np.asarray(honest_l) == np.asarray(honest_g)[None, :]).all()
    want = batch_grads.sum(axis=0) / n
    np.testing.assert_allclose(np.asarray(dec_l), want, rtol=5e-3, atol=5e-3)


def test_decode_layers_erasures(rng):
    """Layer decode honours the present mask (stragglers) per layer."""
    n, s, d = 9, 2, 64
    code = cyclic.build_cyclic_code(n, s)
    batch_grads = rng.randn(n, d).astype(np.float32)
    enc_re, enc_im = cyclic.encode(code, jnp.asarray(batch_grads[code.batch_ids]))
    present = np.ones(n, dtype=bool)
    present[[2, 6]] = False
    enc_re = jnp.asarray(np.asarray(enc_re) * present[:, None])
    enc_im = jnp.asarray(np.asarray(enc_im) * present[:, None])
    rf = rng.normal(loc=1.0, size=d).astype(np.float32)
    dec, honest_l = cyclic.decode_layers(code, enc_re, enc_im, jnp.asarray(rf),
                                         [0, 20, d], present=jnp.asarray(present))
    want = batch_grads.sum(axis=0) / n
    np.testing.assert_allclose(np.asarray(dec), want, rtol=2e-3, atol=2e-3)
    assert not np.asarray(honest_l)[:, [2, 6]].any()


def test_decode_layers_jittable():
    code = cyclic.build_cyclic_code(7, 1)
    d = 24
    jitted = jax.jit(
        lambda a, b, c: cyclic.decode_layers(code, a, b, c, [0, 10, 24])
    )
    dec, honest_l = jitted(jnp.zeros((7, d)), jnp.zeros((7, d)), jnp.ones((d,)))
    assert dec.shape == (d,)
    assert honest_l.shape == (2, 7)


# ---------------------------------------------------------------------------
# scale envelope: larger n and s than the reference cluster ever ran
# (reference: 8 workers, README.md:39-47)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,s", [(16, 3), (21, 3), (32, 3), (32, 5)])
def test_construction_at_scale(n, s):
    code = cyclic.build_cyclic_code(n, s)
    assert (code.support.sum(axis=1) == 2 * s + 1).all()
    c2h = code.c2h_re + 1j * code.c2h_im
    assert np.abs(c2h @ code.w_full).max() < 1e-4


@pytest.mark.parametrize("n,s", [(16, 3), (32, 3)])
@pytest.mark.parametrize("attack", ["rev_grad", "constant"])
def test_exact_recovery_under_attack_at_scale(n, s, attack, rng):
    from draco_tpu.attacks import inject_cyclic

    code = cyclic.build_cyclic_code(n, s)
    d = 128
    batch_grads = rng.randn(n, d).astype(np.float32)
    enc_re, enc_im = cyclic.encode(code, jnp.asarray(batch_grads[code.batch_ids]))
    adv = np.zeros(n, dtype=bool)
    adv[rng.choice(n, size=s, replace=False)] = True
    enc_re, enc_im = inject_cyclic(enc_re, enc_im, jnp.asarray(adv), attack)
    rf = rng.normal(loc=1.0, size=d).astype(np.float32)
    dec, honest = cyclic.decode(code, enc_re, enc_im, jnp.asarray(rf))
    want = batch_grads.sum(axis=0) / n
    np.testing.assert_allclose(np.asarray(dec), want, rtol=1e-2, atol=1e-2)
    assert not np.asarray(honest)[adv].any()
    assert np.asarray(honest).sum() == n - 2 * s


@pytest.mark.parametrize("n,s,t,e", [(16, 3, 2, 1), (16, 3, 1, 2), (32, 3, 2, 1)])
def test_joint_adversary_and_erasure_at_scale(n, s, t, e, rng):
    """t live adversaries + e stragglers, t + e <= s, at n the reference
    never reached."""
    from draco_tpu.attacks import inject_cyclic

    code = cyclic.build_cyclic_code(n, s)
    d = 128
    batch_grads = rng.randn(n, d).astype(np.float32)
    enc_re, enc_im = cyclic.encode(code, jnp.asarray(batch_grads[code.batch_ids]))
    picks = rng.choice(n, size=t + e, replace=False)
    adv, missing = picks[:t], picks[t:]
    adv_mask = np.zeros(n, dtype=bool)
    adv_mask[adv] = True
    enc_re, enc_im = inject_cyclic(enc_re, enc_im, jnp.asarray(adv_mask), "rev_grad")
    present = np.ones(n, dtype=bool)
    present[missing] = False
    enc_re = jnp.asarray(np.asarray(enc_re) * present[:, None])
    enc_im = jnp.asarray(np.asarray(enc_im) * present[:, None])
    rf = rng.normal(loc=1.0, size=d).astype(np.float32)
    dec, used = cyclic.decode(code, enc_re, enc_im, jnp.asarray(rf),
                              present=jnp.asarray(present))
    want = batch_grads.sum(axis=0) / n
    np.testing.assert_allclose(np.asarray(dec), want, rtol=1e-2, atol=1e-2)
    used = np.asarray(used)
    assert not used[adv].any() and not used[missing].any()
