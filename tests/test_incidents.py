"""Incident engine (draco_tpu/obs/incidents.py, ISSUE 13): detector units
on synthesized column streams (onset/offset hysteresis, no flapping on a
single noisy step, worker attribution), the declarative registry +
threshold-override grammar, the incidents.jsonl event stream and its
torn-tail-tolerant replay (obs/replay.py + tools/incident_report.py), the
live production-loop wiring (clean run -> ZERO incidents AND bitwise-
unchanged training; nan_grad -> attributed nonfinite incident), and the
terminal-write coverage satellite (the SIGTERM/crash status.json carries
the final ``incidents`` block even when no beat ever did)."""

import json
import os

import numpy as np
import pytest

from draco_tpu.obs import incidents as inc
from draco_tpu.obs import replay


def rec(step, accused=0, present=0b11111111, adv=None, **cols):
    """A synthesized train record with packed forensics masks (n <= 8)."""
    r = {"step": step, "loss": 1.0, "wmask_accused0": accused,
         "wmask_present0": present,
         "wmask_adv0": accused if adv is None else adv}
    r.update(cols)
    return r


# --------------------------------------------------------------------------
# registry + thresholds
# --------------------------------------------------------------------------

@pytest.mark.core
def test_detector_registry_enumerable():
    """The detector set is declaratively registered: every spec names a
    severity, a source, and a thresholds dict carrying the hysteresis
    pair — the enumerability the chaos matrix and PERF.md §15 rest on."""
    table = inc.detector_table()
    names = {t["name"] for t in table}
    assert {"throughput", "decode_residual", "trust", "guard", "nonfinite",
            "numerics_drift", "compile_storm", "starvation"} <= names
    for t in table:
        assert t["severity"] in inc.SEVERITIES
        assert t["source"] in inc.SOURCES
        assert {"on_count", "off_count"} <= set(t["thresholds"])


@pytest.mark.core
def test_threshold_override_grammar():
    assert inc.parse_thresholds("trust.floor=0.4, guard.off_count=2") == {
        "trust.floor": 0.4, "guard.off_count": 2.0}
    assert inc.parse_thresholds("") == {}
    with pytest.raises(ValueError, match="unknown incident detector"):
        inc.parse_thresholds("bogus.floor=1")
    with pytest.raises(ValueError, match="no threshold"):
        inc.parse_thresholds("trust.bogus=1")
    with pytest.raises(ValueError, match="not"):
        inc.parse_thresholds("trust.floor")
    # config.validate rejects bad specs at config time
    from draco_tpu.config import TrainConfig

    with pytest.raises(ValueError, match="unknown incident detector"):
        TrainConfig(incident_thresholds="bogus.x=1").validate()
    with pytest.raises(ValueError, match="incident_watch"):
        TrainConfig(incident_watch="maybe").validate()


# --------------------------------------------------------------------------
# detector units on synthesized streams
# --------------------------------------------------------------------------

@pytest.mark.core
def test_trust_collapse_onset_offset_and_attribution():
    """~4 consecutive accusations pull EW trust below the 0.5 floor ->
    an attributed onset; sustained clean steps recover trust -> offset."""
    eng = inc.IncidentEngine(num_workers=4)
    for s in range(1, 8):
        eng.observe(rec(s, accused=0b0100, present=0b1111))
    opens = eng.open_episodes()
    assert len(opens) == 1 and opens[0]["type"] == "trust"
    assert opens[0]["workers"] == [2] and opens[0]["onset_step"] == 4
    for s in range(8, 20):
        eng.observe(rec(s, accused=0, present=0b1111))
    assert eng.open_episodes() == []
    (ep,) = [e for e in eng.episodes if e["type"] == "trust"]
    assert ep["offset_step"] > ep["onset_step"]
    # an ABSENT worker's trust holds: absence is an erasure, not evidence
    # for the ACCUSATION detectors — what sustained absence DOES raise is
    # the straggle incident (ISSUE 14: the autopilot's dial-down signal),
    # attributed to the absent worker
    eng2 = inc.IncidentEngine(num_workers=4)
    for s in range(1, 12):
        eng2.observe(rec(s, accused=0, present=0b1011))  # w2 always absent
    assert [e["type"] for e in eng2.open_episodes()] == ["straggle"]
    assert eng2.open_episodes()[0]["workers"] == [2]
    assert not any(e["type"] == "trust" for e in eng2.all_episodes())


@pytest.mark.core
def test_single_noisy_step_never_flaps():
    """The no-flapping contract: one loud decode_residual (on_count=2)
    and one noisy numerics record (on_count=3) open NOTHING."""
    eng = inc.IncidentEngine()
    eng.observe({"step": 1, "loss": 1.0, "decode_residual": 5.0})
    eng.observe({"step": 2, "loss": 1.0, "decode_residual": 1e-6})
    eng.observe({"step": 3, "loss": 1.0, "decode_residual": float("nan")})
    eng.observe({"step": 4, "loss": 1.0, "decode_residual": 1e-6})
    assert eng.total_onsets == 0
    # two consecutive crossings DO open (NaN counts as a crossing), and
    # the episode's onset is the first hot step
    for s, r in ((5, 2.0), (6, float("nan"))):
        eng.observe({"step": s, "loss": 1.0, "decode_residual": r})
    assert eng.total_onsets == 1
    assert eng.open_episodes()[0]["onset_step"] == 5


@pytest.mark.core
def test_approx_residual_drift_toward_bound():
    """The approx branch: EW of residual/bound crossing bound_frac fires;
    healthy ratios (~0.6, the committed straggler_study band) never do;
    an outright bound violation fires regardless of the EW."""
    eng = inc.IncidentEngine()
    for s in range(1, 12):
        eng.observe({"step": s, "loss": 1.0, "decode_residual": 0.6,
                     "decode_residual_bound": 1.0})
    assert eng.total_onsets == 0
    for s in range(12, 24):  # EW (alpha=0.25) needs ~9 steps to cross 0.95
        eng.observe({"step": s, "loss": 1.0, "decode_residual": 0.99,
                     "decode_residual_bound": 1.0})
    assert eng.total_onsets == 1
    eng2 = inc.IncidentEngine()
    for s in (1, 2):  # violation: residual ABOVE the analytic bound
        eng2.observe({"step": s, "loss": 1.0, "decode_residual": 1.5,
                      "decode_residual_bound": 1.0})
    assert eng2.total_onsets == 1
    # narrow-wire slack (ISSUE 15): on a bf16/int8 wire the measured
    # residual carries quantization error the analytic bound (drops only)
    # does not price — make_engine widens the approx branch by the dtype's
    # slack (same widening guards.assess applies), so a clean int8-wire
    # run sitting just past the bound is NOT an incident (the slack comes
    # off the measured residual before BOTH the violation check and the
    # EW drift ratio), while a real violation past the slack still fires
    eng3 = inc.IncidentEngine(
        thresholds={"decode_residual.slack": 0.1})
    for s in range(1, 12):
        eng3.observe({"step": s, "loss": 1.0, "decode_residual": 1.04,
                      "decode_residual_bound": 1.0})
    assert eng3.total_onsets == 0
    for s in (12, 13):
        eng3.observe({"step": s, "loss": 1.0, "decode_residual": 1.5,
                      "decode_residual_bound": 1.0})
    assert eng3.total_onsets == 1


@pytest.mark.core
def test_guard_burn_and_nonfinite_attribution():
    """Hard signals run at on_count=1: a guard trip and a non-finite
    ingest fraction each open immediately, attributed via the step's
    accused mask; off_count clean steps close them."""
    eng = inc.IncidentEngine(num_workers=8)
    eng.observe(rec(1))
    eng.observe(rec(2, accused=0b1000, guard_trips=1.0, skipped_steps=1.0,
                    nx_grad_nonfinite=0.01, nx_wire_nonfinite=0.0))
    assert eng.total_onsets == 2
    by_type = {e["type"]: e for e in eng.open_episodes()}
    assert by_type["guard"]["workers"] == [3]
    assert by_type["nonfinite"]["workers"] == [3]
    assert by_type["nonfinite"]["evidence"]["nonfinite_frac"] == 0.01
    for s in range(3, 9):
        eng.observe(rec(s, guard_trips=0.0, nx_grad_nonfinite=0.0,
                        nx_wire_nonfinite=0.0))
    assert eng.open_episodes() == []
    assert {e["type"] for e in eng.episodes} == {"guard", "nonfinite"}


@pytest.mark.core
def test_numerics_drift_histogram_shift():
    """The exponent histogram shifting from its own warm baseline fires
    only after on_count consecutive observations — and only once the warm
    baseline (first `warmup` watched records) exists."""
    eng = inc.IncidentEngine()

    def nxrec(step, lo):
        # all mass in bin 0 (baseline) vs bin 5 (shifted)
        hist = {f"nx_wire_exp{i}": 0.0 for i in range(6)}
        hist["nx_wire_exp0" if lo else "nx_wire_exp5"] = 1.0
        return {"step": step, "loss": 1.0, "nx_wire_uf_bf16": 0.0,
                "nx_wire_of_bf16": 0.0, **hist}

    for s in range(1, 7):  # warmup (4) + 2 stable
        eng.observe(nxrec(s, lo=True))
    eng.observe(nxrec(7, lo=False))  # single shifted step: no flap
    eng.observe(nxrec(8, lo=True))
    assert eng.total_onsets == 0
    for s in range(9, 12):  # 3 consecutive shifted steps: onset
        eng.observe(nxrec(s, lo=False))
    assert eng.total_onsets == 1
    ep = eng.open_episodes()[0]
    assert ep["type"] == "numerics_drift" and ep["severity"] == "warn"
    assert ep["evidence"]["hist_shift"] == 1.0


@pytest.mark.core
def test_throughput_regression_against_warm_baseline():
    """Beat-source: the EW steps/s falling >40% below the warm baseline
    (EW frozen after warmup_beats inter-beat rates) opens after on_count
    slow beats; recovery closes it."""
    t = [0.0]
    eng = inc.IncidentEngine(clock=lambda: t[0])

    def beat(step, dt):
        t[0] += dt
        eng.observe_beat(step, {})

    step = 0
    for _ in range(4):  # warmup: 10 steps/s
        step += 10
        beat(step, 1.0)
    assert eng.total_onsets == 0
    for _ in range(6):  # collapse to 1 step/s
        step += 10
        beat(step, 10.0)
    assert eng.total_onsets == 1
    ep = eng.open_episodes()[0]
    assert ep["type"] == "throughput"
    assert ep["evidence"]["baseline_steps_per_s"] == pytest.approx(10.0)
    for _ in range(4):  # recovery
        step += 10
        beat(step, 1.0)
    assert eng.open_episodes() == []
    # warmup_beats=0 is a legal override: the first rate becomes the
    # baseline instead of firing against None (which crashed the loop)
    t2 = [0.0]
    eng2 = inc.IncidentEngine(clock=lambda: t2[0],
                              thresholds={"throughput.warmup_beats": 0.0})
    for dt in (1.0, 1.0, 1.0):
        t2[0] += dt
        eng2.observe_beat(int(t2[0] * 10), {})
    assert eng2.total_onsets == 0


@pytest.mark.core
def test_compile_storm_and_starvation_beats():
    """compile_storm fires on any steady-recompile delta between beats;
    starvation fires on a supervised prefetcher restart, or on the queue
    depth pinned at zero for depth_beats consecutive beats."""
    eng = inc.IncidentEngine()
    eng.observe_beat(4, {"steady_recompiles": 0, "prefetch_depth": 1,
                         "prefetch_restarts": 0})
    assert eng.total_onsets == 0
    eng.observe_beat(8, {"steady_recompiles": 2, "prefetch_depth": 1,
                         "prefetch_restarts": 0})
    assert [e["type"] for e in eng.open_episodes()] == ["compile_storm"]
    eng.observe_beat(12, {"steady_recompiles": 2, "prefetch_depth": 1,
                          "prefetch_restarts": 1})
    types = {e["type"] for e in eng.open_episodes()}
    assert "starvation" in types
    # depth starving: three consecutive zero-depth beats (fresh engine)
    eng2 = inc.IncidentEngine()
    for s in (4, 8):
        eng2.observe_beat(s, {"prefetch_depth": 0})
    assert eng2.total_onsets == 0  # two zero beats: below depth_beats
    eng2.observe_beat(12, {"prefetch_depth": 0})
    assert [e["type"] for e in eng2.open_episodes()] == ["starvation"]


# --------------------------------------------------------------------------
# event stream + offline replay
# --------------------------------------------------------------------------

@pytest.mark.core
def test_event_stream_and_replay_roundtrip(tmp_path):
    """The live engine streams onset/offset lines; a fresh engine replayed
    over the same records reproduces the ledger exactly (the
    incident_report diff contract); a torn tail is tolerated; a clean run
    writes NO file."""
    from tools import incident_report

    d = tmp_path / "run"
    d.mkdir()
    recs = [rec(s, accused=(0b0010 if 3 <= s <= 9 else 0))
            for s in range(1, 16)]
    with open(d / "metrics.jsonl", "w") as fh:
        fh.write("\n".join(json.dumps(r) for r in recs) + "\n")
    eng = inc.IncidentEngine(num_workers=4,
                             out_path=str(d / "incidents.jsonl"))
    for r in recs:
        eng.observe(r)
    eng.finalize()
    events = list(replay.iter_jsonl(str(d / "incidents.jsonl")))
    assert [e["event"] for e in events] == ["onset", "offset"]
    assert events[0]["type"] == "trust" and events[0]["workers"] == [1]
    rc = incident_report.main([str(d), "--num-workers", "4"])
    assert rc == 0
    rep = json.load(open(d / "incidents_report.json"))
    assert rep["diff"]["match"] and not rep["diff"]["only_replay"]
    # a DIVERGENT ledger (hand-edited onset) exits 1 naming the divergence
    with open(d / "incidents.jsonl", "a") as fh:
        fh.write(json.dumps({"v": 1, "event": "onset", "type": "guard",
                             "severity": "critical", "source": "record",
                             "onset_step": 12, "last_step": 12, "steps": 1,
                             "workers": [0], "evidence": {}}) + "\n")
    assert incident_report.main([str(d), "--num-workers", "4"]) == 1
    # torn tail on top: still folds (the divergence verdict stands)
    with open(d / "incidents.jsonl", "a") as fh:
        fh.write('{"v": 1, "event": "ons')
    assert incident_report.main([str(d), "--num-workers", "4"]) == 1
    # clean engine: no event, no file
    eng2 = inc.IncidentEngine(num_workers=4,
                              out_path=str(d / "none.jsonl"))
    for s in range(1, 10):
        eng2.observe(rec(s))
    eng2.finalize()
    assert not os.path.exists(d / "none.jsonl")


@pytest.mark.core
def test_resumed_overlapping_stream_degrades_to_carry_through(tmp_path):
    """A resumed run APPENDS overlapping steps to metrics.jsonl: two live
    engine instances with reset state observed that stream, which one
    continuous replay engine cannot reproduce — the strict diff must
    degrade to a carry-through (exit 0), not a false DIVERGED."""
    from tools import incident_report

    d = tmp_path / "resumed"
    d.mkdir()
    recs = [rec(s) for s in range(1, 7)] + [rec(s) for s in range(4, 9)]
    with open(d / "metrics.jsonl", "w") as fh:
        fh.write("\n".join(json.dumps(r) for r in recs) + "\n")
    # a ledger entry the continuous replay would NOT reproduce
    with open(d / "incidents.jsonl", "w") as fh:
        fh.write(json.dumps({"v": 1, "event": "onset", "type": "guard",
                             "severity": "critical", "source": "record",
                             "onset_step": 5, "last_step": 5, "steps": 1,
                             "workers": [1], "evidence": {}}) + "\n")
    assert incident_report.main([str(d), "--num-workers", "8"]) == 0
    rep = json.load(open(d / "incidents_report.json"))
    assert rep["diff"]["full_coverage"] is False
    assert rep["diff"]["match"] is False  # unverified, not asserted
    # a GAP-FREE resume is detectable from the ledger itself: the second
    # engine instance's seq counter resets, so a second onset stream in
    # one file degrades the strict diff even with contiguous steps
    d2 = tmp_path / "gapfree"
    d2.mkdir()
    recs2 = [rec(s, guard_trips=float(s in (2, 7)), skipped_steps=0.0)
             for s in range(1, 10)]
    with open(d2 / "metrics.jsonl", "w") as fh:
        fh.write("\n".join(json.dumps(r) for r in recs2) + "\n")
    for lo, hi in ((1, 5), (5, 10)):  # two engine instances, appending
        eng = inc.IncidentEngine(num_workers=8,
                                 out_path=str(d2 / "incidents.jsonl"))
        for r in recs2[lo - 1:hi - 1]:
            eng.observe(r)
        eng.finalize()
    assert incident_report.main([str(d2), "--num-workers", "8"]) == 0
    rep2 = json.load(open(d2 / "incidents_report.json"))
    assert rep2["diff"]["multi_run_ledger"] is True
    assert rep2["diff"]["full_coverage"] is False


@pytest.mark.core
def test_replay_scaffold_tolerance(tmp_path):
    """obs/replay.py — the one JSONL tolerance rule: missing file, empty
    file, blank lines, torn tail, non-dict lines."""
    p = tmp_path / "m.jsonl"
    assert list(replay.iter_jsonl(str(p))) == []
    p.write_text("")
    assert replay.train_records(str(p)) == []
    p.write_text('\n{"step": 1, "loss": 1.0}\n[1,2]\n'
                 '{"step": 2, "split": "eval", "loss": 9}\n'
                 '{"step": 3, "loss": 2.0}\n{"step": 4, "lo')
    recs = replay.train_records(str(p))
    assert [r["step"] for r in recs] == [1, 3]
    assert replay.record_at_step(str(p), 3)["loss"] == 2.0
    assert replay.record_at_step(str(p), 99) is None


# --------------------------------------------------------------------------
# live production-loop wiring
# --------------------------------------------------------------------------

def _cnn_cfg(**kw):
    from draco_tpu.config import TrainConfig

    base = dict(network="FC", dataset="synthetic-mnist", approach="cyclic",
                worker_fail=1, redundancy="shared", batch_size=4,
                num_workers=8, max_steps=6, eval_freq=0, log_every=1,
                lr=0.05, step_guard="on", numerics_watch="on",
                incident_watch="on")
    base.update(kw)
    return TrainConfig(**base)


def _run(cfg):
    import jax

    from draco_tpu.training.trainer import Trainer

    t = Trainer(cfg, quiet=True)
    try:
        t.run()
    finally:
        t.close()
    return np.concatenate([np.ravel(x) for x in jax.tree.leaves(
        jax.device_get(t.state.params))])


@pytest.mark.core
def test_live_clean_run_zero_incidents_and_bitwise(tmp_path):
    """The acceptance pin: incident_watch=on on a clean run raises ZERO
    incidents, stamps the schema-4 ``incidents`` block, writes no
    incidents.jsonl — and the final params are BITWISE identical to the
    watch-off run (the engine is host-side only)."""
    d_on, d_off = str(tmp_path / "on"), str(tmp_path / "off")
    v_on = _run(_cnn_cfg(train_dir=d_on))
    v_off = _run(_cnn_cfg(train_dir=d_off, incident_watch="off"))
    np.testing.assert_array_equal(v_on, v_off)
    st = json.load(open(os.path.join(d_on, "status.json")))
    assert st["schema"] == 5 and st["state"] == "done"
    assert st["incidents"] == {"total": 0, "open": [], "by_type": {},
                               "thresholds": {}, "last": None}
    assert not os.path.exists(os.path.join(d_on, "incidents.jsonl"))
    # watch off: no block at all
    st_off = json.load(open(os.path.join(d_off, "status.json")))
    assert "incidents" not in st_off


def test_live_nan_grad_raises_attributed_incident(tmp_path):
    """nan_grad@3:w5 through the real chunked trainer: the nonfinite
    incident opens AT the fault step attributed to exactly worker 5, the
    guard incident rides along, and the offline replay reproduces the
    ledger (incident_report exit 0)."""
    from tools import incident_report

    d = str(tmp_path / "nan")
    _run(_cnn_cfg(train_dir=d, steps_per_call=3,
                  fault_spec="nan_grad@3:w5"))
    events = list(replay.iter_jsonl(os.path.join(d, "incidents.jsonl")))
    onsets = {e["type"]: e for e in events if e["event"] == "onset"}
    assert set(onsets) == {"nonfinite", "guard"}
    assert onsets["nonfinite"]["onset_step"] == 3
    assert onsets["nonfinite"]["workers"] == [5]
    assert onsets["guard"]["workers"] == [5]
    st = json.load(open(os.path.join(d, "status.json")))
    assert st["incidents"]["total"] == 2
    assert st["incidents"]["by_type"] == {"guard": 1, "nonfinite": 1}
    assert incident_report.main([d]) == 0


@pytest.mark.core
def test_open_episode_worker_growth_replays_clean(tmp_path):
    """An episode still OPEN at run end whose worker set grew after onset:
    the ledger's onset line carries the onset-time set, the replay the
    grown union — the diff must compare open episodes by identity, not by
    the moving worker set (a correct ledger must not read DIVERGED)."""
    from tools import incident_report

    d = tmp_path / "grow"
    d.mkdir()
    recs = [rec(1, accused=0b0100, nx_grad_nonfinite=0.1,
                nx_wire_nonfinite=0.0),
            rec(2, accused=0b1000, nx_grad_nonfinite=0.1,
                nx_wire_nonfinite=0.0)]
    with open(d / "metrics.jsonl", "w") as fh:
        fh.write("\n".join(json.dumps(r) for r in recs) + "\n")
    eng = inc.IncidentEngine(num_workers=8,
                             out_path=str(d / "incidents.jsonl"))
    for r in recs:
        eng.observe(r)
    eng.finalize()
    assert eng.open_episodes()[0]["workers"] == [2, 3]  # grew after onset
    onsets = [e for e in replay.iter_jsonl(str(d / "incidents.jsonl"))]
    assert onsets[0]["workers"] == [2]  # ledger froze the onset-time set
    assert incident_report.main([str(d), "--num-workers", "8"]) == 0


@pytest.mark.core
def test_replay_uses_the_runs_own_thresholds(tmp_path):
    """The live engine stamps its non-default overrides into the status
    block; the replay must fold with THOSE (e.g. make_engine's implicit
    cyclic_tol <- guard_residual_tol), not the registry defaults — a run
    with a loosened tolerance must not falsely diverge offline."""
    from tools import incident_report

    d = tmp_path / "tol"
    d.mkdir()
    # residual 0.01 x4: fires under the default 1e-3, quiet under 0.1
    recs = [{"step": s, "loss": 1.0, "decode_residual": 0.01}
            for s in range(1, 5)]
    with open(d / "metrics.jsonl", "w") as fh:
        fh.write("\n".join(json.dumps(r) for r in recs) + "\n")
    eng = inc.IncidentEngine(
        num_workers=8, out_path=str(d / "incidents.jsonl"),
        thresholds={"decode_residual.cyclic_tol": 0.1})
    for r in recs:
        eng.observe(r)
    assert eng.total_onsets == 0  # quiet under the loosened tolerance
    block = eng.status_block()
    assert block["thresholds"] == {"decode_residual.cyclic_tol": 0.1}
    with open(d / "status.json", "w") as fh:
        json.dump({"schema": 4, "state": "done", "step": 4,
                   "incidents": block,
                   "forensics": {"num_workers": 8}}, fh)
    eng.finalize()
    assert incident_report.main([str(d)]) == 0
    rep = json.load(open(d / "incidents_report.json"))
    assert rep["replayed"] == []  # no false decode_residual episode


def test_device_token_gen_clean_run_zero_incidents(tmp_path):
    """The device token-gen LM route has NO host prefetch path: its beats
    must not report a constant queue depth 0 (which read as starvation) —
    a clean ≥3-beat device-gen run raises ZERO incidents."""
    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel import make_mesh_2d
    from draco_tpu.parallel.sp_step import train_sp

    d = str(tmp_path / "devgen")
    cfg = TrainConfig(
        network="TransformerLM", dataset="synthetic-text", batch_size=2,
        num_workers=4, approach="baseline", mode="normal", worker_fail=0,
        seq_len=16, vocab=32, model_dim=32, model_heads=2, model_layers=1,
        max_steps=9, eval_freq=3, log_every=1, lr=0.05,
        token_gen="device", incident_watch="on", train_dir=d)
    train_sp(cfg, make_mesh_2d(4, 1), quiet=True)
    st = json.load(open(os.path.join(d, "status.json")))
    assert st["state"] == "done"
    assert st["incidents"]["total"] == 0, st["incidents"]
    assert "prefetch_depth" not in st  # no prefetcher, no depth claim
    assert not os.path.exists(os.path.join(d, "incidents.jsonl"))


def test_terminal_write_carries_final_incidents_block(tmp_path):
    """The satellite fix (the PR 9 ``device`` bug, re-fixed for
    ``incidents``): a SIGTERM-preempted run whose incident fired AFTER the
    last beat — here eval_freq=0, so NO beat ever runs before the stop —
    must still carry the final ``incidents`` block in its terminal
    status.json, incidents included."""
    d = str(tmp_path / "term")
    _run(_cnn_cfg(train_dir=d, eval_freq=0,
                  fault_spec="nan_grad@2:w4,sigterm@3"))
    st = json.load(open(os.path.join(d, "status.json")))
    assert st["state"] == "preempted" and st["schema"] == 5
    inc_block = st["incidents"]
    assert inc_block["total"] == 2  # nonfinite + guard, post-last-beat
    assert {e["type"] for e in inc_block["open"]} <= {"guard", "nonfinite"}
    assert inc_block["by_type"] == {"guard": 1, "nonfinite": 1}
    # the event stream survived the preemption too (flushed per event)
    onsets = [e for e in replay.iter_jsonl(
        os.path.join(d, "incidents.jsonl")) if e["event"] == "onset"]
    assert {e["type"] for e in onsets} == {"guard", "nonfinite"}
    assert all(e["workers"] == [4] for e in onsets)
