"""Scan-chunked LM token loop (cfg.steps_per_call > 1 on the TransformerLM
routes): bitwise equivalence with the eager loop, mid-chunk resume, the
in-graph token stream, and the eval/checkpoint guard split.

The equivalence tests are the load-bearing ones: ``train_token_many`` is the
SAME coded LM step (token slice → vmapped lane fwd/bwd → encode →
aggregate/decode → update) scan-chained K at a time
(parallel/common.make_token_train_many + parallel/token_loop.py), so
K ∈ {1, 4} must produce identical final parameters and an identical metrics
stream — under a live rev-grad adversary AND a straggler-drop schedule, on
both parallelism styles (sp: shard_map ring attention; tp: GSPMD folded
mesh). Tiny models keep the compiles cheap; nothing here depends on scale.
"""

import json
import os

import jax
import numpy as np
import pytest

from draco_tpu.config import TrainConfig
from draco_tpu.parallel import make_mesh_2d
from draco_tpu.parallel.mesh import make_folded_wtp_mesh
from draco_tpu.parallel.sp_step import train_sp
from draco_tpu.parallel.tp_step import build_tp_train_setup, train_tp
from draco_tpu.parallel.token_loop import run_token_loop
from draco_tpu.utils import checkpoint as ckpt


def make_cfg(**kw):
    base = dict(
        network="TransformerLM", dataset="synthetic-text", batch_size=4,
        lr=0.05, momentum=0.9, num_workers=8, approach="baseline",
        mode="normal", worker_fail=0, err_mode="rev_grad", seq_len=16,
        vocab=32, model_dim=32, model_heads=2, model_layers=1, max_steps=7,
        eval_freq=0, train_dir="", log_every=1000,
        # strict compile sentinel (ISSUE 5): a steady-state recompilation
        # of a labelled route program raises at the dispatch site, so every
        # run in this suite doubles as a 0-retrace assertion
        compile_guard="raise",
        # in-graph step guard enabled suite-wide (ISSUE 6): the guard must
        # be bitwise-transparent on clean runs — the equivalence tests
        # additionally pin guard_trips == 0 per record
        step_guard="on",
        # incident engine enabled suite-wide (ISSUE 13): host-side only,
        # so K∈{1,4} must stay bitwise with the watch ON and a clean run
        # must raise ZERO incidents (_assert_route_telemetry)
        incident_watch="on",
    )
    base.update(kw)
    return TrainConfig(**base)


def params_vec(state):
    return np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(jax.device_get(state.params))]
    )


def metric_stream(train_dir):
    """[(step, split, loss)] from metrics.jsonl, timing keys dropped — the
    cross-regime-comparable part of the record stream."""
    out = []
    with open(os.path.join(train_dir, "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            out.append((rec["step"], rec.get("split", "train"), rec["loss"]))
    return out


# --------------------------------------------------------------------------
# chunked vs eager equivalence — both parallelism styles, live rev-grad
# adversary + straggler drops, eval/checkpoint boundaries interleaved
# --------------------------------------------------------------------------

# sp: shard_map ring attention on a (4 w × 2 sp) mesh, robust aggregation;
# tp: GSPMD folded mesh, cyclic code in the joint adversary+straggler
# regime (s=2, t=1, e=1 needs n > 4s ⇒ n=9, folded onto 3 devices)
ROUTES = {
    "sp": dict(
        kw=dict(num_workers=4, seq_shards=2, mode="geometric_median",
                worker_fail=1, straggle_mode="drop", straggle_count=1),
        train=lambda cfg, prof=None: train_sp(cfg, make_mesh_2d(4, 2),
                                              quiet=True, profile_dir=prof),
    ),
    # the cyclic tp route runs with the numerics observatory + bf16 shadow
    # wire enabled (obs/numerics.py, ISSUE 10): K∈{1,4} equality must hold
    # with the watch on, and _assert_route_telemetry pins the shadow
    # columns (flag agreement 1.0, detection preserved under quantization)
    "tp": dict(
        kw=dict(num_workers=9, approach="cyclic", worker_fail=2,
                adversary_count=1, redundancy="shared",
                straggle_mode="drop", straggle_count=1,
                numerics_watch="on", shadow_wire="bf16"),
        train=lambda cfg, prof=None: train_tp(cfg, make_folded_wtp_mesh(9),
                                              quiet=True, profile_dir=prof),
    ),
    # the approximate family on the single-shard fold (ISSUE 8): no live
    # adversary (validate rejects one), two seeded drops per step inside
    # the ⌈αn⌉ = 2 budget — the per-record residual-vs-bound certificate
    # and absent≠accused are asserted in _assert_route_telemetry
    # the approx route carries the watch too (numerics + bf16 shadow on
    # the optimal-decoding family's wire) — its exact-code counterpart is
    # the tp cell above, so both observatory families are pinned on this
    # loop
    "approx": dict(
        kw=dict(num_workers=8, approach="approx", worker_fail=0,
                redundancy="shared", code_redundancy=1.5,
                straggler_alpha=0.25, straggle_mode="drop",
                straggle_count=2, numerics_watch="on", shadow_wire="bf16"),
        train=lambda cfg, prof=None: train_sp(cfg, make_mesh_2d(8, 1),
                                              quiet=True, profile_dir=prof),
    ),
}


@pytest.mark.parametrize("route", sorted(ROUTES))
def test_chunked_equals_eager_bitwise(route, tmp_path):
    """Same final params AND same metrics stream (train records at
    log_every=1 + eval records at eval_freq=3) for K=1 (eager loop) vs K=4
    (scan-chunked with remainder chunks, since the eval boundary snaps
    chunks to 3 and 7 % 3 != 0) — run with the telemetry spine enabled
    (trace_dir + heartbeat, ISSUE 4), which must not perturb either
    regime."""
    r = ROUTES[route]
    out = {}
    for k in (1, 4):
        d = str(tmp_path / f"{route}_k{k}")
        cfg = make_cfg(**r["kw"], steps_per_call=k, train_dir=d,
                       trace_dir=d, eval_freq=3, log_every=1)
        # the chunked run additionally captures a jax.profiler window
        # (ISSUE 9): the capture must observe, never perturb — metrics
        # stay bitwise-equal to the unprofiled eager run, still under
        # compile_guard="raise" with 0 steady retraces
        state, metrics = r["train"](cfg, d if k == 4 else None)
        out[k] = (params_vec(state), metric_stream(d), float(metrics["loss"]))
    np.testing.assert_array_equal(out[1][0], out[4][0])
    assert out[1][1] == out[4][1]  # identical per-step metric values
    assert [s for s, split, _ in out[4][1] if split == "train"] == list(
        range(1, 8))
    assert [s for s, split, _ in out[4][1] if split == "eval"] == [3, 6]
    assert out[1][2] == out[4][2]
    _assert_route_telemetry(route, r["kw"], tmp_path / f"{route}_k4")


def _assert_route_telemetry(route, kw, run_dir):
    """LM telemetry on the K=4 run: the cyclic route's decode-health
    columns report detection precision/recall 1.0 vs the seeded schedules
    in every train record and in status.json; trace.json carries the host
    phases plus the token prefetcher's own labeled worker-thread lane."""
    from draco_tpu import rng as drng

    recs = [json.loads(l)
            for l in open(os.path.join(run_dir, "metrics.jsonl"))]
    train = [r for r in recs if r.get("split") != "eval" and "loss" in r]
    # guards enabled suite-wide: a clean run (live adversary + stragglers
    # all inside budget) must never trip — and never skip an update
    for r in train:
        assert r["guard_trips"] == 0.0, r
        assert r["skipped_steps"] == 0.0, r
    status_guard = json.load(
        open(os.path.join(run_dir, "status.json"))).get("guard")
    assert status_guard == {"trips": 0.0, "skipped_steps": 0.0}
    if kw.get("approach") == "cyclic":
        from draco_tpu.obs import forensics as fx

        n = kw["num_workers"]
        adv = drng.adversary_schedule(428, 8, n, kw["adversary_count"])
        strag = drng.straggler_schedule(428, 8, n, kw["straggle_count"])
        for r in train:
            want = int((adv[r["step"]] & ~strag[r["step"]]).sum())
            assert r["det_adv"] == want
            assert r["det_tp"] == want  # recall = 1.0
            assert r["located_errors"] == want  # precision = 1.0
            assert r["decode_residual"] < 1e-3
            # numerics observatory + bf16 shadow (ISSUE 10): finite range
            # stats, flag agreement exactly 1.0, detection P/R preserved
            # under quantization — on the REAL folded w×tp GSPMD mesh
            assert r["nx_wire_absmax"] > 0 and r["nx_wire_rms"] > 0
            assert r["nx_grad_nonfinite"] == 0.0
            assert r["shadow_flag_agree"] == 1.0, r
            assert 0.0 <= r["shadow_err"] < 0.05, r
            assert r["shadow_det_flagged"] == want
            assert r["shadow_det_tp"] == want
            # per-worker attribution exact (packed forensics masks, ISSUE
            # 7): accused == adversarial ∧ present, bit for bit — an
            # absent worker is never an accused worker
            masks = fx.record_masks(r, n)
            assert masks is not None, r
            assert masks["adv"] == tuple(adv[r["step"]])
            assert masks["present"] == tuple(~strag[r["step"]])
            assert masks["accused"] == tuple(
                adv[r["step"]] & ~strag[r["step"]]), (r["step"], masks)
        status = json.load(open(os.path.join(run_dir, "status.json")))
        health = status["decode_health"]
        assert health["precision"] == 1.0 and health["recall"] == 1.0
        assert health["adv_total"] > 0
        # the per-worker ledger block + versioned schema (ISSUE 7)
        fxb = status["forensics"]
        assert fxb["num_workers"] == n and fxb["accused_total"] > 0
        assert fxb["top_suspects"]
        assert status["schema"] == 5
    elif kw.get("approach") == "approx":
        from draco_tpu.obs import forensics as fx

        n = kw["num_workers"]
        strag = drng.straggler_schedule(428, 8, n, kw["straggle_count"])
        for r in train:
            # the residual-vs-bound certificate per record (ISSUE 8) + no
            # located-error machinery on this family
            assert r["decode_residual"] <= \
                r["decode_residual_bound"] + 1e-5, r
            assert 0.0 < r["recovered_fraction"] <= 1.0
            assert "det_tp" not in r and "located_errors" not in r
            # watch columns on this family too (ISSUE 10): shadow flag
            # surface is the non-finite wire rows — empty on a clean run
            assert r["nx_wire_absmax"] > 0
            assert r["shadow_flag_agree"] == 1.0 and \
                r["shadow_det_flagged"] == 0.0
            assert 0.0 <= r["shadow_err"] < 0.05, r
            masks = fx.record_masks(r, n)
            assert masks is not None, r
            assert masks["present"] == tuple(~strag[r["step"]])
            assert masks["adv"] == (False,) * n
            # a scheduled straggler is never an accused worker
            assert masks["accused"] == (False,) * n, (r["step"], masks)
        status = json.load(open(os.path.join(run_dir, "status.json")))
        health = status["decode_health"]
        assert health["decode_residual"] <= \
            health["decode_residual_bound"] + 1e-5
        # the ledger holds: absence decays nothing — no accusations, no
        # episodes, full trust on every worker
        fxb = status["forensics"]
        assert fxb["accused_total"] == 0 and fxb["episodes_total"] == 0
        assert fxb["trust"] == [1.0] * n
        assert status["schema"] == 5
    else:
        assert all("det_tp" not in r for r in train)
        assert all("wmask_accused0" not in r for r in train)
    trace = json.load(open(os.path.join(run_dir, "trace.json")))
    events = trace["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    names = {e["name"] for e in spans}
    assert {"gather", "dispatch", "flush", "prefetch.assemble"} <= names
    lanes = {e["tid"]: e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assembles = {e["tid"] for e in spans if e["name"] == "prefetch.assemble"}
    dispatches = {e["tid"] for e in spans if e["name"] == "dispatch"}
    # the worker thread has its own labeled lane, distinct from the main
    # loop's dispatch lane (cold-start assembly runs on main; steady-state
    # chunks on the worker)
    worker_tids = {t for t in assembles
                   if lanes.get(t) == "token-chunk-prefetch"}
    assert worker_tids, lanes  # the worker thread got its own labeled lane
    assert not (worker_tids & dispatches)  # ...distinct from the main loop's
    assert any(e["ph"] == "C" and e["name"] == "prefetch_depth"
               for e in events)
    # compile sentinel surface (ISSUE 5): status.json carries the counters,
    # the ledger attributes the chunked driver's builds per chunk shape
    # (main chunks k=3 snapped to eval_freq=3 + remainder k=1), and the
    # trace grew a compile-category lane
    status = json.load(open(os.path.join(run_dir, "status.json")))
    assert status["compiles"] >= 1 and status["compile_s"] > 0
    assert status["steady_recompiles"] == 0
    # the incident engine (ISSUE 13) ran on every cell of this suite and a
    # CLEAN run — live adversary + stragglers all inside budget — raises
    # ZERO incidents (no-flapping contract), while the bitwise assertions
    # above prove the watch perturbs nothing; no event → no incidents.jsonl
    inc = status["incidents"]
    assert inc["total"] == 0 and inc["open"] == [] and inc["by_type"] == {}
    assert not os.path.exists(os.path.join(run_dir, "incidents.jsonl"))
    ledger = [json.loads(l)
              for l in open(os.path.join(run_dir, "compiles.jsonl"))]
    labels = {r["program"] for r in ledger if r["program"]}
    assert {"train_token_many[3]", "train_token_many[1]"} <= labels
    assert not any(r["steady_recompile"] for r in ledger)
    assert any(e.get("cat") == "compile" for e in events)
    # the profiled window's device surface (ISSUE 9): capture + shared-clock
    # anchor landed, and the heartbeat folded the capture into the
    # ``device`` status block (no scope map on a plain --profile-dir run,
    # so attribution honestly reads 0 — everything in the unattributed row)
    from draco_tpu.obs import device_attr

    assert device_attr.find_capture(str(run_dir)) is not None
    anchor = device_attr.load_anchor(str(run_dir))
    assert anchor is not None and anchor["steps_profiled"] == 7
    assert anchor["tracer_ts_us"] is not None
    dev = status["device"]
    assert dev["profiled_steps"] == 7 and dev["total_device_us"] > 0
    assert dev["attributed_frac"] == 0.0 and dev["decode_share"] == 0.0


def test_device_token_gen_bitwise_and_distinct():
    """cfg.token_gen='device' regenerates the batches in-graph: K=1 and K=4
    agree bitwise (both run the scanned driver), and the device stream is a
    different deterministic draw from the host stream."""
    mesh = make_folded_wtp_mesh(8)
    vecs = {}
    for k in (1, 4):
        cfg = make_cfg(approach="cyclic", worker_fail=1, redundancy="shared",
                       steps_per_call=k, token_gen="device")
        setup = build_tp_train_setup(cfg, mesh)
        state, metrics = run_token_loop(setup, cfg, quiet=True)
        assert np.isfinite(float(metrics["loss"]))
        vecs[k] = params_vec(state)
    np.testing.assert_array_equal(vecs[1], vecs[4])

    # the two streams are distinct deterministic draws with the same shape/
    # range contract (ramp mod vocab)
    from draco_tpu.parallel.sp_step import synthetic_text, synthetic_text_in_graph

    host = synthetic_text(428, 1, 8, 4, 16, 32)
    dev = np.asarray(synthetic_text_in_graph(428, 1, 8, 4, 16, 32))
    assert host.shape == dev.shape and dev.dtype == np.int32
    assert dev.min() >= 0 and dev.max() < 32
    assert not np.array_equal(host, dev)


@pytest.mark.core
def test_chunked_token_loop_smoke_fast():
    """Tier-1/core smoke: tiny LM, K=3 with a remainder chunk, live
    adversary — the chunked loop trains and the loss moves."""
    kw = dict(approach="cyclic", worker_fail=1, redundancy="shared",
              steps_per_call=3)
    mesh = make_folded_wtp_mesh(8)
    cfg = make_cfg(**kw)
    setup = build_tp_train_setup(cfg, mesh)
    _, first = run_token_loop(setup, cfg, steps=1, quiet=True)
    cfg2 = make_cfg(**kw)
    setup2 = build_tp_train_setup(cfg2, mesh)
    state, last = run_token_loop(setup2, cfg2, steps=7, quiet=True)
    assert int(state.step) == 8
    assert np.isfinite(last["loss"])
    assert last["loss"] < float(first["loss"])


def test_resume_from_checkpoint_mid_chunk(tmp_path):
    """A K=4 run checkpoints at eval boundaries (3, 6, 9); resuming from
    step 3 — mid-chunk relative to the K grid — must land on the exact same
    parameters as the uninterrupted run."""
    kw = dict(approach="cyclic", worker_fail=1, redundancy="shared",
              steps_per_call=4, eval_freq=3, train_dir=str(tmp_path),
              max_steps=10)
    cfg = make_cfg(**kw)
    state_full, _ = train_tp(cfg, make_folded_wtp_mesh(8), quiet=True)
    assert ckpt.available_steps(str(tmp_path)) == [3, 6, 9]
    cfg_res = make_cfg(**kw, checkpoint_step=3)
    state_res, _ = train_tp(cfg_res, make_folded_wtp_mesh(8), steps=7,
                            quiet=True)
    np.testing.assert_array_equal(params_vec(state_full),
                                  params_vec(state_res))


# --------------------------------------------------------------------------
# the eval/checkpoint guard split (previously one `eval_freq and train_dir`
# guard: no checkpoints without eval, no eval without a train_dir)
# --------------------------------------------------------------------------

def test_checkpoint_without_eval(tmp_path):
    """eval_freq=0 with a train_dir still saves the final state — in both
    regimes, at the same step."""
    for k in (1, 4):
        d = str(tmp_path / f"k{k}")
        cfg = make_cfg(steps_per_call=k, eval_freq=0, train_dir=d)
        train_tp(cfg, make_folded_wtp_mesh(8), steps=5, quiet=True)
        assert ckpt.available_steps(d) == [5]


def test_eval_without_train_dir_runs():
    """eval_freq without a train_dir evaluates (records print-only) instead
    of silently skipping; no checkpoint dir appears."""
    cfg = make_cfg(eval_freq=2, train_dir="", steps_per_call=4)
    state, metrics = train_tp(cfg, make_folded_wtp_mesh(8), steps=4,
                              quiet=True)
    assert int(state.step) == 5
    assert np.isfinite(float(metrics["loss"]))


# --------------------------------------------------------------------------
# config surface: the TransformerLM steps_per_call ban is lifted
# --------------------------------------------------------------------------

def test_validate_accepts_steps_per_call_on_all_lm_routes():
    """config.validate passes steps_per_call > 1 for every LM route config
    (single-shard, sp, tp, pp, ep) — the pre-PR ban is gone."""
    routes = [
        dict(),                                        # single-shard
        dict(num_workers=4, seq_shards=2),             # sp
        dict(num_workers=4, tensor_shards=2),          # tp
        dict(num_workers=2, pipeline_shards=2,
             model_layers=2),                          # pp
        dict(num_workers=4, moe_experts=2,
             expert_shards=2),                         # ep
    ]
    for kw in routes:
        make_cfg(**kw, steps_per_call=8).validate()


def test_token_gen_validation():
    with pytest.raises(ValueError, match="token_gen"):
        make_cfg(token_gen="banana").validate()
    with pytest.raises(ValueError, match="TransformerLM"):
        TrainConfig(network="FC", token_gen="device").validate()
