"""Sequence parallelism: ring attention exactness (fwd + grad) and the 2-D
mesh (w × sp) coded training step, on the 8-device virtual CPU mesh."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from draco_tpu.runtime import shard_map

from draco_tpu.config import TrainConfig
from draco_tpu.parallel import make_mesh_2d, ring_attention
from draco_tpu.parallel.ring_attention import dense_attention
from draco_tpu.parallel.sp_step import build_sp_train_setup, synthetic_text, train_sp


def _qkv(rng, b=2, t=32, h=2, dh=8):
    return tuple(rng.normal(size=(b, t, h, dh)).astype(np.float32) for _ in range(3))


def _softmax_attn(q, k, v, causal):
    dh = q.shape[-1]
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(dh)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = np.arange(tq)[:, None] >= np.arange(tk)[None, :]
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [True, False])
def test_dense_attention_matches_softmax(rng, causal):
    q, k, v = _qkv(rng)
    out = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal)
    np.testing.assert_allclose(np.asarray(out), _softmax_attn(q, k, v, causal),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("sp,causal", [(4, True), (8, True), (4, False)])
def test_ring_attention_matches_dense(rng, sp, causal):
    q, k, v = _qkv(rng, t=32)
    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))
    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,
    )
    out = ring(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), _softmax_attn(q, k, v, causal),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_gradient_matches_dense(rng):
    """d/dq,k,v of a scalar of ring attention == dense attention's — the
    ppermute transpose routing that the SP gradient psum relies on."""
    q, k, v = _qkv(rng, t=16)
    sp = 4
    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))

    def ring_scalar(q, k, v):
        f = shard_map(
            functools.partial(ring_attention, axis_name="sp", causal=True),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
        return jnp.sum(jnp.sin(f(q, k, v)))

    def dense_scalar(q, k, v):
        return jnp.sum(jnp.sin(dense_attention(q, k, v, causal=True)))

    g_ring = jax.grad(ring_scalar, argnums=(0, 1, 2))(*map(jnp.asarray, (q, k, v)))
    g_dense = jax.grad(dense_scalar, argnums=(0, 1, 2))(*map(jnp.asarray, (q, k, v)))
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), rtol=1e-4, atol=1e-5)


def test_synthetic_text_deterministic():
    a = synthetic_text(428, 7, 2, 3, 16, 64)
    b = synthetic_text(428, 7, 2, 3, 16, 64)
    assert np.array_equal(a, b)
    assert a.shape == (2, 3, 16)
    # ramps: t_{i+1} - t_i constant per sequence
    d = np.diff(a, axis=-1) % 64
    assert np.all(d == d[..., :1])


def _sp_cfg(**kw):
    base = dict(
        network="TransformerLM", dataset="synthetic-text", batch_size=2,
        num_workers=2, seq_shards=4, seq_len=32, vocab=32, model_dim=32,
        model_heads=2, model_layers=1, approach="baseline", mode="normal",
        worker_fail=0, max_steps=3, lr=0.05, momentum=0.9, eval_freq=0,
        train_dir="", log_every=1000,
    )
    base.update(kw)
    return TrainConfig(**base)


def test_sp_step_runs_and_learns():
    cfg = _sp_cfg()
    mesh = make_mesh_2d(2, 4)
    state, metrics = train_sp(cfg, mesh, steps=8, quiet=True)
    assert int(state.step) == 9
    assert np.isfinite(float(metrics["loss"]))


def test_sp_matches_single_shard():
    """Same config on (2 w × 4 sp) and (2 w × 1 sp): ring attention must not
    change the training trajectory."""
    cfg = _sp_cfg()
    mesh_sp = make_mesh_2d(2, 4)
    state_sp, m_sp = train_sp(cfg, mesh_sp, steps=3, quiet=True)

    cfg1 = _sp_cfg(seq_shards=1)
    mesh_1 = make_mesh_2d(2, 1)
    state_1, m_1 = train_sp(cfg1, mesh_1, steps=3, quiet=True)

    np.testing.assert_allclose(float(m_sp["loss"]), float(m_1["loss"]), rtol=1e-4)
    flat_sp = np.concatenate([np.ravel(x) for x in jax.tree.leaves(state_sp.params)])
    flat_1 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(state_1.params)])
    np.testing.assert_allclose(flat_sp, flat_1, rtol=1e-3, atol=1e-5)


def test_sp_cyclic_tolerates_adversary():
    """cyclic s=1 on a (8 w × 1 sp) mesh vs no-attack run: decode must null
    the Byzantine rows (exact recovery), trajectories must match."""
    cfg_atk = _sp_cfg(num_workers=8, seq_shards=1, approach="cyclic",
                      worker_fail=1, err_mode="rev_grad")
    mesh = make_mesh_2d(8, 1)
    state_a, m_a = train_sp(cfg_atk, mesh, steps=3, quiet=True)

    cfg_clean = _sp_cfg(num_workers=8, seq_shards=1, approach="baseline",
                        worker_fail=0)
    state_c, m_c = train_sp(cfg_clean, mesh, steps=3, quiet=True)

    flat_a = np.concatenate([np.ravel(x) for x in jax.tree.leaves(state_a.params)])
    flat_c = np.concatenate([np.ravel(x) for x in jax.tree.leaves(state_c.params)])
    np.testing.assert_allclose(flat_a, flat_c, rtol=2e-2, atol=2e-4)


def test_sp_geomedian_under_attack():
    """Robust aggregation composed with ring attention: (4 w × 2 sp) mesh,
    one rev_grad adversary, geometric-median aggregation — must stay finite
    and make progress. (Full cyclic × sp needs n > 4s mesh rows and runs in
    the driver's dryrun_multichip instead — 8 CPU devices only fit w=4×sp=2.)"""
    cfg = _sp_cfg(num_workers=4, seq_shards=2, mode="geometric_median",
                  worker_fail=1, err_mode="rev_grad")
    mesh = make_mesh_2d(4, 2)
    state, metrics = train_sp(cfg, mesh, steps=6, quiet=True)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 7


def test_sp_checkpoint_resume(tmp_path):
    """train_sp honours train_dir/eval_freq/checkpoint_step: checkpoints are
    written at cadence and a resumed run continues from the saved state."""
    d = str(tmp_path / "out")
    cfg = _sp_cfg(train_dir=d, eval_freq=2)
    mesh = make_mesh_2d(2, 4)
    state_full, _ = train_sp(cfg, mesh, steps=4, quiet=True)

    from draco_tpu.utils import checkpoint as ckpt

    assert ckpt.available_steps(d) == [2, 4]
    cfg_resume = _sp_cfg(train_dir=d, eval_freq=2, checkpoint_step=2)
    state_res, _ = train_sp(cfg_resume, mesh, steps=2, quiet=True)
    assert int(state_res.step) == int(state_full.step)
    a = np.concatenate([np.ravel(x) for x in jax.tree.leaves(state_res.params)])
    b = np.concatenate([np.ravel(x) for x in jax.tree.leaves(state_full.params)])
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_transformer_rejected_on_image_paths():
    from draco_tpu.models import build_model

    with pytest.raises(ValueError, match="token model"):
        build_model("TransformerLM")


def test_config_validates_transformer_knobs():
    with pytest.raises(ValueError, match="divisible"):
        _sp_cfg(model_dim=48, model_heads=5).validate()
    with pytest.raises(ValueError, match="rotary"):
        _sp_cfg(model_dim=6, model_heads=2).validate()
    with pytest.raises(ValueError, match="maj_vote"):
        _sp_cfg(approach="maj_vote").validate()
    with pytest.raises(ValueError, match="seq_shards"):
        TrainConfig(network="LeNet", seq_shards=2).validate()


def test_sp_bf16_matches_trajectory_loosely():
    """bf16 compute must train: loss decreases and stays finite on the
    2-D (w × sp) mesh with ring attention."""
    import numpy as np

    from draco_tpu.parallel import make_mesh_2d
    from draco_tpu.parallel.sp_step import train_sp

    cfg = _sp_cfg(compute_dtype="bfloat16", max_steps=10)
    mesh = make_mesh_2d(cfg.num_workers, cfg.seq_shards)
    state, metrics = train_sp(cfg, mesh, steps=10, quiet=True)
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.parametrize("sp,causal", [(2, True), (4, True), (4, False)])
def test_a2a_attention_matches_dense(rng, sp, causal):
    from draco_tpu.parallel import a2a_attention

    q, k, v = _qkv(rng, t=32, h=4)
    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))
    a2a = shard_map(
        functools.partial(a2a_attention, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,
    )
    out = a2a(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), _softmax_attn(q, k, v, causal),
                               rtol=1e-4, atol=1e-5)


def test_a2a_attention_gradient_matches_dense(rng):
    """The all_to_all transpose routing: d/dq,k,v through the head-scatter
    layout swap must equal dense attention's gradients."""
    from draco_tpu.parallel import a2a_attention

    q, k, v = _qkv(rng, t=16, h=4)
    sp = 4
    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))

    def a2a_scalar(q, k, v):
        f = shard_map(
            functools.partial(a2a_attention, axis_name="sp", causal=True),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
        return jnp.sum(jnp.sin(f(q, k, v)))

    def dense_scalar(q, k, v):
        return jnp.sum(jnp.sin(dense_attention(q, k, v, causal=True)))

    g_a2a = jax.grad(a2a_scalar, argnums=(0, 1, 2))(*map(jnp.asarray, (q, k, v)))
    g_dense = jax.grad(dense_scalar, argnums=(0, 1, 2))(*map(jnp.asarray, (q, k, v)))
    for ga, gd in zip(g_a2a, g_dense):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gd), rtol=1e-4, atol=1e-5)


def test_sp_a2a_matches_ring_trajectory():
    """sp_attn=a2a and sp_attn=ring compute the same exact attention, so the
    whole coded-SP training trajectory must agree (f32 tolerance)."""
    cfg_r = _sp_cfg(sp_attn="ring", model_heads=4)
    cfg_a = _sp_cfg(sp_attn="a2a", model_heads=4)
    mesh = make_mesh_2d(2, 4)
    state_r, m_r = train_sp(cfg_r, mesh, steps=3, quiet=True)
    state_a, m_a = train_sp(cfg_a, mesh, steps=3, quiet=True)
    np.testing.assert_allclose(float(m_a["loss"]), float(m_r["loss"]), rtol=1e-4)
    flat_r = np.concatenate([np.ravel(x) for x in jax.tree.leaves(state_r.params)])
    flat_a = np.concatenate([np.ravel(x) for x in jax.tree.leaves(state_a.params)])
    np.testing.assert_allclose(flat_a, flat_r, rtol=1e-3, atol=1e-5)


def test_a2a_head_divisibility_validated():
    with pytest.raises(ValueError, match="model_heads"):
        _sp_cfg(sp_attn="a2a", seq_shards=4, model_heads=3,
                model_dim=36).validate()


def test_sp_worker_folding_matches_full_mesh():
    """num_workers=4 folded onto a (w=2 × sp=2) mesh (2 vmapped lanes per
    device) must reproduce the full (w=4 × sp=2) mesh trajectory — the
    worker-folding discipline tp_step already has, extended to sp so a
    single chip can run the n-lane coded SP step (advisor r2)."""
    cfg = _sp_cfg(num_workers=4, seq_shards=2)
    state_full, m_full = train_sp(cfg, make_mesh_2d(4, 2), steps=3, quiet=True)
    state_fold, m_fold = train_sp(cfg, make_mesh_2d(2, 2), steps=3, quiet=True)

    np.testing.assert_allclose(float(m_fold["loss"]), float(m_full["loss"]),
                               rtol=1e-4)
    flat_full = np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(state_full.params)])
    flat_fold = np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(state_fold.params)])
    np.testing.assert_allclose(flat_fold, flat_full, rtol=1e-3, atol=1e-5)


def test_sp_cyclic_simulate_matches_shared():
    """Reference-parity r× redundant compute under sequence parallelism:
    redundancy='simulate' (each worker evaluates its 2s+1 assigned rows,
    sequence-sharded) must match the 'shared' fast path trajectory; one
    live rev_grad adversary is decoded away in both. n=8 workers fold onto
    the (w=4 × sp=2) mesh."""
    kw = dict(num_workers=8, seq_shards=2, approach="cyclic", worker_fail=1,
              err_mode="rev_grad")
    mesh = make_mesh_2d(4, 2)
    st_sim, m_sim = train_sp(_sp_cfg(redundancy="simulate", **kw), mesh,
                             steps=3, quiet=True)
    st_sh, m_sh = train_sp(_sp_cfg(redundancy="shared", **kw), mesh,
                           steps=3, quiet=True)
    np.testing.assert_allclose(float(m_sim["loss"]), float(m_sh["loss"]),
                               rtol=1e-4)
    flat_sim = np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(st_sim.params)])
    flat_sh = np.concatenate(
        [np.ravel(x) for x in jax.tree.leaves(st_sh.params)])
    np.testing.assert_allclose(flat_sim, flat_sh, rtol=1e-3, atol=1e-5)


# ---------------------------------------------------------------------------
# ring + flash composition (ring_flash_attention)
# ---------------------------------------------------------------------------

def _flash_inner():
    from draco_tpu.ops.flash_attention import flash_attention_with_lse

    return functools.partial(flash_attention_with_lse, force=True,
                             interpret=True)


@pytest.mark.parametrize("sp,causal", [(4, True), (4, False), (8, True)])
def test_ring_flash_matches_dense(rng, sp, causal):
    """The blockwise kernel as the ring inner (causal self hop, unmasked
    past hops, cond-skipped future hops, lse-weighted merge) must equal
    full-sequence softmax attention."""
    from draco_tpu.parallel.ring_attention import ring_flash_attention

    q, k, v = _qkv(rng, t=8 * sp)  # T_local = 8: the kernel's sublane tile
    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))
    ring = shard_map(
        functools.partial(ring_flash_attention, axis_name="sp", causal=causal,
                          attn_with_lse=_flash_inner()),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,
    )
    out = ring(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), _softmax_attn(q, k, v, causal),
                               rtol=1e-4, atol=1e-5)


def test_ring_flash_gradient_matches_dense(rng):
    """Grad flows through the lse merge (the kernels' dlse backward term)
    and the cond-skipped hops; must equal dense attention's gradient."""
    from draco_tpu.parallel.ring_attention import ring_flash_attention

    q, k, v = _qkv(rng, t=32)
    sp = 4
    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))

    def ring_scalar(q, k, v):
        f = shard_map(
            functools.partial(ring_flash_attention, axis_name="sp",
                              causal=True, attn_with_lse=_flash_inner()),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
        return jnp.sum(jnp.sin(f(q, k, v)))

    def dense_scalar(q, k, v):
        return jnp.sum(jnp.sin(dense_attention(q, k, v, causal=True)))

    g_ring = jax.grad(ring_scalar, argnums=(0, 1, 2))(*map(jnp.asarray, (q, k, v)))
    g_dense = jax.grad(dense_scalar, argnums=(0, 1, 2))(*map(jnp.asarray, (q, k, v)))
    for name, gr, gd in zip("qkv", g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), rtol=1e-4,
                                   atol=1e-5, err_msg=f"d{name}")
