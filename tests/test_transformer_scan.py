"""scan_layers: the LM layer stack compiled as ONE nn.scan body.

Why this exists: the d≈159M LM perf point died repeatedly in the tunnel's
remote-compile service at ~27 min (PERF.md §4) because the unrolled
12-layer remat program is ~12× the size it needs to be. ``scan_layers``
compiles the stack as a single scanned block over stacked weights —
identical math, ~layers× smaller XLA program. These tests pin:

  1. output parity with the unrolled model (restacking per-block params
     along a leading layer axis reproduces the scanned model exactly);
  2. the coded train step (tp path) runs under scan_layers + remat and
     matches the unrolled step's loss;
  3. the Megatron partition specs shift right by one under the stacked
     "blocks" subtree (tp sharding stays on the correct dims).

No reference counterpart (reference is CNN-only); this is TPU-build
compile-scaling infrastructure.
"""

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest

from draco_tpu.models.transformer import TransformerLM

pytestmark = pytest.mark.core


def _toks(b=2, t=16, vocab=64):
    return jnp.asarray(np.random.RandomState(0).randint(0, vocab, (b, t)),
                       jnp.int32)


def _restack(p_unroll, p_scan, layers):
    """Unrolled block0..N-1 params stacked into the scan layout."""
    stacked = jtu.tree_map(lambda *xs: jnp.stack(xs),
                           *[p_unroll[f"block{i}"] for i in range(layers)])
    out = dict(p_scan)
    out["blocks"] = stacked
    for k in p_unroll:
        if not k.startswith("block"):
            out[k] = p_unroll[k]
    return out


def test_scan_layers_output_parity():
    kw = dict(vocab=64, dim=32, heads=4, layers=3)
    toks = _toks()
    m_u = TransformerLM(**kw)
    m_s = TransformerLM(**kw, scan_layers=True)
    p_u = m_u.init({"params": jax.random.key(0)}, toks, train=True)["params"]
    p_s = m_s.init({"params": jax.random.key(0)}, toks, train=True)["params"]
    assert p_s["blocks"]["qkv"]["kernel"].shape == (3, 32, 96)
    p_mix = _restack(p_u, p_s, 3)
    o_u = m_u.apply({"params": p_u}, toks, train=True)
    o_s = m_s.apply({"params": p_mix}, toks, train=True)
    np.testing.assert_allclose(np.asarray(o_u), np.asarray(o_s),
                               rtol=0, atol=1e-5)


def test_scan_layers_remat_grad_parity():
    """remat inside the scan body (prevent_cse=False) must not change
    gradients vs the unrolled remat model."""
    kw = dict(vocab=64, dim=32, heads=4, layers=2)
    toks = _toks()
    m_u = TransformerLM(**kw, remat=True)
    m_s = TransformerLM(**kw, scan_layers=True, remat=True)
    p_u = m_u.init({"params": jax.random.key(1)}, toks, train=True)["params"]
    p_s = m_s.init({"params": jax.random.key(1)}, toks, train=True)["params"]
    p_mix = _restack(p_u, p_s, 2)

    def loss_u(p):
        return jnp.mean(m_u.apply({"params": p}, toks, train=True) ** 2)

    def loss_s(p):
        return jnp.mean(m_s.apply({"params": p}, toks, train=True) ** 2)

    g_u = jax.grad(loss_u)(p_u)
    g_s = jax.grad(loss_s)(p_mix)
    g_u_stacked = jtu.tree_map(lambda *xs: jnp.stack(xs),
                               *[g_u[f"block{i}"] for i in range(2)])
    flat_u = jnp.concatenate([x.ravel() for x in jtu.tree_leaves(g_u_stacked)])
    flat_s = jnp.concatenate([x.ravel() for x in
                              jtu.tree_leaves(g_s["blocks"])])
    np.testing.assert_allclose(np.asarray(flat_u), np.asarray(flat_s),
                               rtol=1e-4, atol=1e-5)


def test_tp_train_step_scan_layers_matches_unrolled():
    """The full coded LM train step (cyclic, folded mesh) under scan_layers
    produces the same loss trajectory as the unrolled program."""
    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel.mesh import make_folded_wtp_mesh
    from draco_tpu.parallel.tp_step import build_tp_train_setup
    from tools.tpu_lm_perf import make_scan_loop, stage_scan_inputs

    common = dict(
        network="TransformerLM", dataset="synthetic-text",
        approach="cyclic", redundancy="shared",
        batch_size=2, lr=0.01, momentum=0.9,
        num_workers=8, worker_fail=1, err_mode="rev_grad",
        seq_len=32, vocab=64, model_dim=32, model_heads=4, model_layers=2,
        max_steps=3, eval_freq=0, train_dir="", log_every=10**9,
        remat=True,
    )
    mesh = make_folded_wtp_mesh(8)
    cfg_u = TrainConfig(**common, scan_layers=False)
    cfg_s = TrainConfig(**common, scan_layers=True)
    setup_u = build_tp_train_setup(cfg_u, mesh)
    setup_s = build_tp_train_setup(cfg_s, mesh)
    # nn.scan's split_rngs draws different init streams than the unrolled
    # block0..N-1 modules, so equalise by restacking the unrolled params
    # into the scan layout (momentum slots are zeros at init either way)
    p_u = jax.device_get(setup_u.state.params)
    p_s = jax.device_get(setup_s.state.params)
    state_s = setup_s.state._replace(
        params=jtu.tree_map(jnp.asarray,
                            _restack(p_u, p_s, common["model_layers"])))
    xs, ms = stage_scan_inputs(cfg_u, 2)
    losses = {}
    with mesh:
        _, ls = jax.jit(make_scan_loop(setup_u))(setup_u.state, xs, ms)
        losses["unroll"] = np.asarray(jax.device_get(ls))
        _, ls = jax.jit(make_scan_loop(setup_s))(state_s, xs, ms)
        losses["scan"] = np.asarray(jax.device_get(ls))
    for v in losses.values():
        assert np.all(np.isfinite(v))
    # same params, same data, same math — trajectories agree to f32 noise
    np.testing.assert_allclose(losses["unroll"], losses["scan"],
                               rtol=2e-4, atol=2e-4)


def test_ep_partition_spec_shifts_under_blocks():
    """scan_layers stacks expert weights as (layers, E, ...) — the ep spec
    must shard E (now axis 1), not the new leading layer axis (review
    finding: P(EP_AXIS) on the stacked tree sharded layers over ep)."""
    from jax.sharding import PartitionSpec as P

    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel import EP_AXIS, make_mesh_wep
    from draco_tpu.parallel.ep_step import (
        build_ep_train_setup, ep_partition_spec,
    )

    cfg = TrainConfig(
        network="TransformerLM", dataset="synthetic-text", batch_size=2,
        num_workers=4, moe_experts=4, expert_shards=2, seq_len=32, vocab=32,
        model_dim=32, model_heads=4, model_layers=2, approach="baseline",
        mode="normal", worker_fail=0, max_steps=3, lr=0.05, momentum=0.9,
        eval_freq=0, train_dir="", log_every=1000, scan_layers=True,
    )
    mesh = make_mesh_wep(4, 2)
    setup = build_ep_train_setup(cfg, mesh)
    seen = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            setup.state.params)[0]:
        names = [getattr(k, "key", str(k)) for k in path]
        seen["/".join(names)] = (ep_partition_spec(path),
                                 leaf.sharding.spec, leaf.shape)
    spec, placed, shape = seen["blocks/moe/w1"]
    assert spec == P(None, EP_AXIS)
    assert placed == spec
    assert shape[0] == 2 and shape[1] == 4  # (layers, E, ...)
    assert seen["blocks/moe/router/kernel"][0] == P()
    for key, (want, got, _) in seen.items():
        assert got == want, (key, want, got)


def test_partition_spec_shifts_under_blocks():
    from jax.sharding import PartitionSpec as P

    from draco_tpu.parallel.mesh import TP_AXIS
    from draco_tpu.parallel.tp_step import param_partition_spec

    class K:  # stand-in for jtu.DictKey
        def __init__(self, key):
            self.key = key

    unrolled = [K("block0"), K("qkv"), K("kernel")]
    scanned = [K("blocks"), K("qkv"), K("kernel")]
    assert param_partition_spec(unrolled) == P(None, TP_AXIS)
    assert param_partition_spec(scanned) == P(None, None, TP_AXIS)
    assert param_partition_spec([K("blocks"), K("proj"), K("kernel")]) == \
        P(None, TP_AXIS, None)
    assert param_partition_spec([K("blocks"), K("mlp_in"), K("bias")]) == \
        P(None, TP_AXIS)
    assert param_partition_spec([K("embed"), K("embedding")]) == P()
