"""CLI surface, evaluator process, single-machine path, cluster tooling.

Covers the reference's L6/L7 layers (SURVEY.md §1): distributed_nn.py flag
surface, distributed_evaluator.py's checkpoint-polling loop,
single_machine.py, and tools/pytorch_ec2.py's command structure (ours:
tools/tpu_pod.py in --dry-run mode — control flow without GCP credentials).
"""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_single_machine_smoke(tmp_path):
    from draco_tpu import single_machine

    last = single_machine.main([
        "--network", "FC", "--dataset", "synthetic-mnist",
        "--batch-size", "16", "--max-steps", "15",
        "--eval-freq", "0", "--train-dir", "", "--log-every", "1000",
    ])
    assert np.isfinite(last["loss"])


def test_evaluator_reads_checkpoints(tmp_path):
    """Train with checkpointing, then run the evaluator once over train_dir —
    the reference's NFS-polling evaluate path (distributed_evaluator.py:75-90)."""
    from draco_tpu.config import TrainConfig
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training import evaluator
    from draco_tpu.training.trainer import Trainer

    d = str(tmp_path / "run")
    ds = load_dataset("synthetic-mnist", synthetic_train=128, synthetic_test=64)
    cfg = TrainConfig(network="FC", dataset="synthetic-mnist", batch_size=4,
                      num_workers=4, approach="baseline", max_steps=4,
                      eval_freq=2, train_dir=d, log_every=1000,
                      test_batch_size=64)
    tr = Trainer(cfg, mesh=make_mesh(4), dataset=ds, quiet=True)
    tr.run()
    tr.close()

    from draco_tpu.utils import checkpoint as ckpt
    assert ckpt.available_steps(d) == [2, 4]

    out = []
    import contextlib, io
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        evaluator.main([
            "--network", "FC", "--dataset", "synthetic-mnist",
            "--num-workers", "4", "--train-dir", d,
            "--test-batch-size", "64", "--once",
        ])
    out = buf.getvalue()
    # one line per checkpoint with top-1/top-5 (reference print format)
    steps = re.findall(r"Cur Step:(\d+)", out)
    assert steps == ["2", "4"]
    assert all(0.0 <= float(p) <= 1.0 for p in re.findall(r"Prec@1: ([0-9.]+)", out))


def test_tpu_pod_dry_run_command_structure():
    def run(*args):
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "tpu_pod.py"),
             "--dry-run", *args],
            capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == 0, p.stderr
        return p.stdout

    out = run("launch", "--name", "pod1", "--type", "v5litepod-16", "--spot")
    assert "gcloud compute tpus tpu-vm create pod1" in out and "--spot" in out

    out = run("train", "--name", "pod1", "--", "--approach", "cyclic",
              "--num-workers", "16")
    assert "--worker=all" in out and "draco_tpu.cli" in out and "cyclic" in out

    out = run("kill", "--name", "pod1")
    assert "pkill" in out

    out = run("terminate", "--name", "pod1")
    assert "delete pod1" in out


def test_cli_rejects_bad_flag_combination():
    from draco_tpu import cli

    with pytest.raises(ValueError, match="straggler budget"):
        cfg = cli.config_from_args(
            cli.add_fit_args(__import__("argparse").ArgumentParser()).parse_args([
                "--approach", "cyclic", "--num-workers", "9",
                "--worker-fail", "2", "--straggle-mode", "drop",
                "--straggle-count", "5",
            ])
        )


def test_profile_flag_writes_trace(tmp_path):
    from draco_tpu.config import TrainConfig
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.trainer import Trainer

    ds = load_dataset("synthetic-mnist", synthetic_train=64, synthetic_test=16)
    cfg = TrainConfig(network="FC", dataset="synthetic-mnist", batch_size=4,
                      num_workers=4, approach="baseline", max_steps=6,
                      eval_freq=0, train_dir="", log_every=1000)
    tr = Trainer(cfg, mesh=make_mesh(4), dataset=ds, quiet=True)
    prof = str(tmp_path / "trace")
    tr.run(profile_dir=prof, profile_steps=(2, 4))
    tr.close()
    found = []
    for root, _, files in os.walk(prof):
        found.extend(f for f in files if f.endswith((".pb", ".json.gz", ".trace.json.gz")))
    assert found, f"no profiler artifacts under {prof}"


def test_compressed_checkpoint_roundtrip_and_evaluator(tmp_path):
    """--compress-ckpt writes .dcg archives; resume and the evaluator's
    train_dir polling must both auto-detect them (the reference's
    --compress-grad wire toggle, re-homed to where bytes still cross a
    slow link in the SPMD design)."""
    import contextlib
    import io

    import jax

    from draco_tpu.config import TrainConfig
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training import evaluator
    from draco_tpu.training.trainer import Trainer
    from draco_tpu.utils import checkpoint as ckpt

    d = str(tmp_path / "run")
    ds = load_dataset("synthetic-mnist", synthetic_train=128, synthetic_test=64)
    base = dict(network="FC", dataset="synthetic-mnist", batch_size=4,
                num_workers=4, approach="baseline", max_steps=4,
                eval_freq=2, train_dir=d, log_every=1000,
                test_batch_size=64, compress_ckpt=True)
    mesh = make_mesh(4)
    tr = Trainer(TrainConfig(**base), mesh=mesh, dataset=ds, quiet=True)
    tr.run()
    tr.close()

    assert os.path.isfile(os.path.join(d, "model_step_2.dcg"))
    assert ckpt.available_steps(d) == [2, 4]

    # resume from the compressed archive: params must match exactly
    tr2 = Trainer(TrainConfig(**{**base, "checkpoint_step": 4}),
                  mesh=mesh, dataset=ds, quiet=True)
    assert tr2._start_step == 5
    a = np.concatenate([np.ravel(x) for x in jax.tree.leaves(
        jax.device_get(tr.state.params))])
    b = np.concatenate([np.ravel(x) for x in jax.tree.leaves(
        jax.device_get(tr2.state.params))])
    np.testing.assert_array_equal(a, b)
    tr2.close()

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        evaluator.main([
            "--network", "FC", "--dataset", "synthetic-mnist",
            "--num-workers", "4", "--train-dir", d,
            "--test-batch-size", "64", "--once",
        ])
    assert re.findall(r"Cur Step:(\d+)", buf.getvalue()) == ["2", "4"]


def test_compressed_checkpoint_rejects_multihost(monkeypatch):
    import jax

    from draco_tpu.utils import checkpoint as ckpt

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ValueError, match="single-host"):
        ckpt.save("/tmp/nowhere", 1, {"a": np.zeros(3)}, compress=True)


def test_timing_protocol_helpers():
    """fetch_scalar syncs through pytrees; timeit_device returns a sane
    per-call time for a known-cost function (utils/timing.py — the honest
    protocol bench.py and the TPU tools rely on)."""
    import jax.numpy as jnp

    from draco_tpu.utils import timing

    out = {"a": jnp.arange(4.0), "b": (jnp.ones((2, 2)),)}
    assert timing.fetch_scalar(out) == 0.0

    rtt = timing.measure_rtt(reps=5)
    assert 0.0 <= rtt < 5.0

    def f(x):
        return x * 2.0

    dt = timing.timeit_device(f, jnp.ones((8, 8)), reps=5, rtt=rtt)
    assert 0.0 <= dt < 5.0


def test_tpu_attn_check_tool(tmp_path):
    """tools/tpu_attn_check.py smoke: interpret-mode parity row on CPU."""
    import json

    from tools import tpu_attn_check

    out = tmp_path / "attn.json"
    rc = tpu_attn_check.main([
        "--out", str(out), "--cpu-interpret", "--seq-lens", "128",
        "--batch", "1", "--heads", "2", "--reps", "2",
    ])
    rep = json.loads(out.read_text())
    assert rc == 0
    row = rep["rows"][0]
    assert row["fwd_max_abs_err"] < 1e-4 and row["grad_max_abs_err"] < 1e-3


def test_tpu_lm_perf_tool(tmp_path):
    """tools/tpu_lm_perf.py smoke on the CPU mesh: all four variants emit
    per-step timings and the cyclic-vs-geomedian ratio."""
    import json

    from tools import tpu_lm_perf

    out = tmp_path / "lm.json"
    rc = tpu_lm_perf.main([
        "--out", str(out), "--cpu-mesh", "4", "--num-workers", "8",
        "--model-dim", "32", "--model-heads", "2", "--model-layers", "1",
        "--vocab", "32", "--seq-len", "16", "--batch-size", "2",
        "--steps", "2", "--reps", "1",
    ])
    rep = json.loads(out.read_text())
    assert rc == 0
    for v in ("lm_cyclic_s1_shared_bf16", "lm_geomedian_bf16",
              "lm_krum_bf16", "lm_mean_no_attack_bf16"):
        assert rep[f"{v}_step_ms"] > 0
    assert rep["lm_cyclic_vs_geomedian_step_speedup"] > 0


def test_time_to_acc_tool(tmp_path):
    """tools/time_to_acc.py converges on the synthetic set and records a
    monotone wall-clock curve (stand-in for the reference's evaluator
    convergence oracle, distributed_evaluator.py:92-110)."""
    import json

    from tools import time_to_acc

    out = tmp_path / "tta.json"
    rc = time_to_acc.main([
        "--out", str(out), "--network", "FC", "--dataset", "synthetic-mnist",
        "--approach", "baseline", "--worker-fail", "0", "--err-mode", "rev_grad",
        "--num-workers", "4", "--batch-size", "16", "--lr", "0.05",
        "--target", "0.5", "--eval-every", "10", "--max-steps", "120",
    ])
    rep = json.loads(out.read_text())
    assert rc == 0 and rep["reached"] is not None
    assert rep["reached"]["prec1_test"] >= 0.5
    walls = [c["train_wall_s"] for c in rep["curve"]]
    assert walls == sorted(walls)
    assert rep["real_data_available"] is False


def test_tpu_lm_perf_simulate_variant(tmp_path):
    """The simulate variant (reference-parity 2s+1-lane compute) runs and
    reports more FLOPs than shared at identical loss (exact decode)."""
    import json

    try:
        from jax._src import xla_bridge
        initialized = xla_bridge.backends_are_initialized()
    except Exception:  # private API — if it moves, don't fail collection;
        initialized = True  # assume initialized (skip) rather than flake
    if initialized:
        # --cpu-mesh 4 appends to XLA_FLAGS, which is inert once another
        # test has initialized jax (conftest pins an 8-device mesh); the
        # >2x flops threshold below is partition-count sensitive (measured:
        # 2.21x on the intended 4-device mesh, 1.93x on 8), so the assert
        # is only meaningful when the tool really gets its 4-device mesh
        pytest.skip("jax already initialized; --cpu-mesh 4 cannot apply")

    from tools import tpu_lm_perf

    out = tmp_path / "lm_sim.json"
    rc = tpu_lm_perf.main([
        "--out", str(out), "--cpu-mesh", "4", "--num-workers", "8",
        "--model-dim", "32", "--model-heads", "2", "--model-layers", "1",
        "--vocab", "32", "--seq-len", "16", "--batch-size", "2",
        "--steps", "2", "--reps", "1",
        "--variants", "lm_cyclic_s1_shared_bf16,lm_cyclic_s1_simulate_bf16",
    ])
    rep = json.loads(out.read_text())
    assert rc == 0
    assert rep["lm_cyclic_s1_simulate_bf16_step_ms"] > 0
    assert (rep["lm_cyclic_s1_simulate_bf16_flops_per_step"]
            > 2.0 * rep["lm_cyclic_s1_shared_bf16_flops_per_step"])
    assert abs(rep["lm_cyclic_s1_simulate_bf16_loss"]
               - rep["lm_cyclic_s1_shared_bf16_loss"]) < 1e-3


def test_tpu_sweep_tool(tmp_path):
    """tools/tpu_sweep.py smoke: one grid point, incremental JSON."""
    import json

    from tools import tpu_sweep

    out = tmp_path / "sweep.json"
    rc = tpu_sweep.main([
        "--out", str(out), "--cpu-mesh", "4", "--network", "LeNet",
        "--num-workers", "8", "--batches", "4", "--dtypes", "float32",
        "--steps", "2",
    ])
    rep = json.loads(out.read_text())
    assert rc == 0
    assert rep["points"][0]["step_ms"] > 0
    assert rep["points"][0]["label"] == "b4_float32"


def test_decode_study_tool(tmp_path):
    """tools/decode_study.py smoke: one (n, s) scaling row with the
    decode-vs-geomedian ratio."""
    import json

    from tools import decode_study

    out = tmp_path / "study.json"
    rc = decode_study.main([
        "--out", str(out), "--cpu-mesh", "4", "--d", "4096",
        "--ns", "8", "--ss", "1", "--reps", "2", "--skip-granularity",
    ])
    rep = json.loads(out.read_text())
    assert rc == 0
    row = rep["scaling"][0]
    assert row["decode_ms"] > 0 and row["geomedian_ms_same_n"] > 0
    assert row["decode_vs_geomedian"] > 0


def test_convergence_grid_tool(tmp_path):
    """tools/convergence_grid.py smoke: one row produces a multi-point
    curve under the shared schedule."""
    import json

    from tools import convergence_grid

    out = tmp_path / "grid.json"
    rc = convergence_grid.main([
        "--out", str(out), "--cpu-mesh", "4", "--network", "FC",
        "--num-workers", "4", "--batch-size", "8", "--rows", "mean_clean",
        "--eval-every", "5", "--max-steps", "15", "--target", "0.99",
    ])
    rep = json.loads(out.read_text())
    assert rc == 0
    curve = rep["rows"]["mean_clean"]["curve"]
    assert len(curve) >= 2
    assert [c["step"] for c in curve] == sorted(c["step"] for c in curve)


def test_lm_time_to_loss_tool(tmp_path):
    """tools/lm_time_to_loss.py: the LM-scale convergence-under-attack
    oracle — cyclic decode learns past the undefended mean under one
    rev_grad adversary, and the wall-clock curve is monotone."""
    import json

    from tools import lm_time_to_loss

    out = tmp_path / "lm_tta.json"
    lm_time_to_loss.main([
        "--out", str(out), "--cpu-mesh", "4", "--num-workers", "8",
        "--batch-size", "1", "--seq-len", "32", "--model-dim", "32",
        "--model-heads", "2", "--model-layers", "1", "--vocab", "32",
        "--max-steps", "20", "--eval-every", "10", "--target", "0.2",
        "--eval-batches", "2",
        "--variants", "lm_cyclic_s1_shared,lm_mean_under_attack",
    ])
    rep = json.loads(out.read_text())
    cyc = rep["variants"]["lm_cyclic_s1_shared"]
    mean = rep["variants"]["lm_mean_under_attack"]
    assert "error" not in cyc and "error" not in mean
    # cyclic improves on its own start; the poisoned mean ends up worse
    assert cyc["curve"][-1]["eval_loss"] < cyc["curve"][0]["eval_loss"]
    assert cyc["final_eval_loss"] < mean["final_eval_loss"]
    walls = [c["train_wall_s"] for c in cyc["curve"]]
    assert walls == sorted(walls)


def test_perf_watch_snapshot_and_injected_regression(tmp_path):
    """tools/perf_watch.py (jax-free): folds synthetic round artifacts,
    snapshots a baseline, passes clean, exits nonzero on an injected 20%
    ms/step regression (and on a peak-memory jump / a steady-state build in
    the timed window), and treats improvements as non-fatal."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()
    rec = {"metric": "resnet_step", "value": 100.0, "unit": "ms/step",
           "vs_baseline": 2.0,
           "extra": {"flops_per_step": 1e9, "compile_ms": 900.0}}
    (root / "BENCH_r01.json").write_text(json.dumps(
        {"n": 1, "rc": 0,
         "tail": "driver noise\n" + json.dumps(rec) + "\n"}))
    (root / "MULTICHIP_r01.json").write_text(
        json.dumps({"n_devices": 8, "rc": 0, "ok": True}))
    host_loop = {
        "ms_per_step_by_steps_per_call": {"1": 50.0, "8": 30.0},
        "compile_ms_by_steps_per_call": {"1": 1000.0, "8": 1500.0},
        "timed_builds_by_steps_per_call": {"1": 0, "8": 0},
    }
    (root / "baselines_out" / "host_loop_overhead.json").write_text(
        json.dumps(host_loop))
    lint = {"all_ok": True, "rows": [
        {"name": "p1", "ok": True,
         "rules": {"constant_bloat": {"ok": True, "module_bytes": 1000},
                   "memory_budget": {"ok": True, "flops": 1e6,
                                     "memory": {"peak_bytes": 5000}}}},
        {"name": "control_x", "ok": True, "control": True, "rules": {}},
    ]}
    (root / "baselines_out" / "program_lint.json").write_text(
        json.dumps(lint))

    # no baseline yet -> distinct exit code with the --snapshot hint
    assert perf_watch.main(["--root", str(root)]) == 2
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    assert "bench.resnet_step.ms_per_step" in snap["metrics"]
    assert "lint.p1.peak_bytes" in snap["metrics"]
    assert "lint.control_x.peak_bytes" not in str(snap)  # controls excluded
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    # a later round 20% slower: nonzero exit, the metric is named
    (root / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "rc": 0, "tail": json.dumps(dict(rec, value=120.0))}))
    out = root / "report.json"
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    rep = json.loads(out.read_text())
    assert [r["metric"] for r in rep["regressions"]] == \
        ["bench.resnet_step.ms_per_step"]
    assert rep["regressions"][0]["rel_change"] == pytest.approx(0.2)

    # 20% faster: improvements never gate
    (root / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "rc": 0, "tail": json.dumps(dict(rec, value=80.0))}))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 0
    rep = json.loads(out.read_text())
    assert any(r["metric"] == "bench.resnet_step.ms_per_step"
               for r in rep["improvements"])

    # a peak-memory jump and a build inside the timed window both gate
    lint["rows"][0]["rules"]["memory_budget"]["memory"]["peak_bytes"] = 9000
    (root / "baselines_out" / "program_lint.json").write_text(
        json.dumps(lint))
    host_loop["timed_builds_by_steps_per_call"]["8"] = 1
    (root / "baselines_out" / "host_loop_overhead.json").write_text(
        json.dumps(host_loop))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in
            json.loads(out.read_text())["regressions"]}
    assert {"lint.p1.peak_bytes", "host_loop.cnn.k8_timed_builds"} <= regs


def test_forensics_report_smoke(tmp_path, capsys):
    """tools/forensics_report.py (jax-free): folds a metrics.jsonl with
    packed mask columns into the per-worker table + episode list and
    writes forensics.json; tolerates a torn tail line, an empty file, and
    a missing file exactly like trace_report."""
    import json

    from tools import forensics_report

    def rec(step, accused, present, adv):
        words = lambda bits: sum(1 << i for i, b in enumerate(bits) if b)
        return {"step": step, "loss": 1.0,
                "wmask_accused0": words(accused),
                "wmask_present0": words(present),
                "wmask_adv0": words(adv)}

    d = tmp_path / "run"
    d.mkdir()
    ones = [1] * 4
    with open(d / "metrics.jsonl", "w") as fh:
        # worker 2 adversarial for steps 1-2 (one episode), clean step 3;
        # worker 0 absent at step 2; an eval record and a torn tail ride
        fh.write(json.dumps(rec(1, [0, 0, 1, 0], ones, [0, 0, 1, 0])) + "\n")
        fh.write(json.dumps(rec(2, [0, 0, 1, 0], [0, 1, 1, 1],
                                [0, 0, 1, 0])) + "\n")
        fh.write(json.dumps(rec(3, [0, 0, 0, 0], ones, [0, 0, 0, 0])) + "\n")
        fh.write(json.dumps({"step": 3, "split": "eval", "loss": 0.9})
                 + "\n\n")
        fh.write('{"step": 4, "los')  # torn tail of a killed run

    rc = forensics_report.main([str(d), "--num-workers", "4"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "3/3 records carried masks" in out
    assert "worker 2: steps 1-2 (2 accused)" in out
    assert "top suspects: w2" in out
    rep = json.loads((d / "forensics.json").read_text())
    w2 = rep["workers"][2]
    assert w2["accused"] == 2 and w2["tp"] == 2 and w2["precision"] == 1.0
    assert rep["workers"][0]["present"] == 2  # absent step not counted
    assert len(rep["episodes"]) == 1 and not rep["episodes"][0]["open"]
    # worker count can come from the present masks when the flag is absent
    rep2 = forensics_report.make_report(str(d / "metrics.jsonl"))
    assert rep2["num_workers"] == 4

    # empty + missing files fold to an empty report, not a crash
    e = tmp_path / "empty"
    e.mkdir()
    (e / "metrics.jsonl").write_text("")
    assert forensics_report.main([str(e)]) == 0
    assert "no forensics columns" in capsys.readouterr().out
    m = tmp_path / "missing"
    m.mkdir()
    assert forensics_report.main([str(m)]) == 0


def test_perf_watch_gates_on_flipped_chaos_attribution(tmp_path):
    """A worker-targeted chaos cell whose forensics attribution flips to
    false must gate perf_watch nonzero (tolerance 0) and name the cell."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()
    matrix = {"all_ok": True, "rows": [
        {"loop": "cnn_k4", "fault": "nan_grad", "ok": True,
         "outcome": "guarded", "injected": [3], "accused": [3],
         "attributed": True},
        {"loop": "cnn_k4", "fault": "sigterm", "ok": True,
         "outcome": "preempted_resumed"},
    ]}
    (root / "baselines_out" / "chaos_matrix.json").write_text(
        json.dumps(matrix))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    assert "chaos.cnn_k4.nan_grad.attributed" in snap["metrics"]
    assert "chaos.cnn_k4.sigterm.attributed" not in snap["metrics"]
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    matrix["rows"][0]["attributed"] = False  # the forensics regression
    matrix["rows"][0]["accused"] = [0, 7]
    (root / "baselines_out" / "chaos_matrix.json").write_text(
        json.dumps(matrix))
    out = root / "report.json"
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = [r["metric"] for r in json.loads(out.read_text())["regressions"]]
    assert "chaos.cnn_k4.nan_grad.attributed" in regs


def test_perf_watch_gates_on_flipped_chaos_incident(tmp_path):
    """ISSUE 13 acceptance control: a chaos cell whose expected incident
    goes absent or mis-attributed (``incident.ok`` flips false) must gate
    perf_watch nonzero at tolerance 0 and name cell + metric — the proof
    the incident gate is live, not decorative."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()
    matrix = {"all_ok": True, "rows": [
        {"loop": "cnn_k4", "fault": "nan_grad", "ok": True,
         "outcome": "guarded", "injected": [3], "accused": [3],
         "attributed": True,
         "incident": {"ok": True, "raised": ["guard", "nonfinite"],
                      "required": ["nonfinite"]}},
        {"loop": "approx_k4", "fault": "straggle", "ok": True,
         "outcome": "degraded_bounded",
         "incident": {"ok": True, "raised": [], "required": []}},
    ]}
    path = root / "baselines_out" / "chaos_matrix.json"
    path.write_text(json.dumps(matrix))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    assert "chaos.cnn_k4.nan_grad.incident_ok" in snap["metrics"]
    assert "chaos.approx_k4.straggle.incident_ok" in snap["metrics"]
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    # the detector goes blind: the expected incident is no longer raised
    matrix["rows"][0]["incident"] = {
        "ok": False, "raised": ["guard"], "required": ["nonfinite"],
        "detail": "expected incident 'nonfinite' not raised"}
    path.write_text(json.dumps(matrix))
    out = root / "report.json"
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = [r["metric"] for r in json.loads(out.read_text())["regressions"]]
    assert "chaos.cnn_k4.nan_grad.incident_ok" in regs
    # ...and a SPURIOUS incident on a clean-telemetry cell gates too
    matrix["rows"][0]["incident"]["ok"] = True
    matrix["rows"][1]["incident"] = {
        "ok": False, "raised": ["throughput"], "required": [],
        "detail": "spurious incident(s): ['throughput']"}
    path.write_text(json.dumps(matrix))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = [r["metric"] for r in json.loads(out.read_text())["regressions"]]
    assert "chaos.approx_k4.straggle.incident_ok" in regs


def test_straggler_study_tool(tmp_path):
    """tools/straggler_study.py smoke (ISSUE 8): approx cells at e ∈ {0, 2}
    train on the chunked production loop, carry the residual-vs-bound
    certificate, and the compute-to-target column scales by the family's
    redundancy."""
    import json

    from tools import straggler_study

    out = tmp_path / "study.json"
    rc = straggler_study.main([
        "--out", str(out), "--cpu-mesh", "8", "--families", "approx",
        "--drops", "0,2", "--max-steps", "14", "--target-loss", "1.9",
    ])
    rep = json.loads(out.read_text())
    assert rc == 0 and rep["all_ok"]
    assert len(rep["rows"]) == 2
    for row in rep["rows"]:
        assert row["family"] == "approx" and row["feasible"]
        assert row["reached_target"] and row["residual_within_bound"]
        assert row["guard_trips_total"] == 0.0
        # compute axis = steps x round(r*n) = steps x 12 at r=1.5, n=8
        assert row["compute_to_target"] == row["steps_to_target"] * 12
        assert 0.0 < row["recovered_fraction_min"] <= 1.0
        assert row["ms_per_step"] > 0
    # full participation decodes exactly; two drops pay a real residual
    e0, e2 = rep["rows"]
    assert e0["residual_max"] < 1e-4 <= e2["residual_max"]
    # a partial sweep (--families approx) must NOT claim the unswept
    # exact family was infeasible
    assert rep["crossover"]["0"] == "approx (only family swept)"


def test_perf_watch_gates_on_flipped_straggler_bound(tmp_path):
    """A straggler-study cell whose measured residual exceeds its analytic
    bound (residual_within_bound flipping false) must gate perf_watch
    nonzero at tolerance 0 and name the cell — same for a lost batch
    coverage and an exact-code cell silently claiming feasibility it does
    not have."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()
    study = {"all_ok": True, "rows": [
        {"family": "approx", "drop_count": 2, "feasible": True,
         "reached_target": True, "residual_within_bound": True,
         "recovered_fraction_min": 1.0, "ms_per_step": 50.0, "ok": True},
        {"family": "cyclic", "drop_count": 3, "feasible": False},
    ]}
    (root / "baselines_out" / "straggler_study.json").write_text(
        json.dumps(study))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    assert "straggler.approx.e2.residual_within_bound" in snap["metrics"]
    # infeasible cells fold ONLY their feasibility flag
    assert "straggler.cyclic.e3.feasible" in snap["metrics"]
    assert "straggler.cyclic.e3.reached_target" not in snap["metrics"]
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    study["rows"][0]["residual_within_bound"] = False
    study["rows"][0]["recovered_fraction_min"] = 0.875
    study["all_ok"] = False
    (root / "baselines_out" / "straggler_study.json").write_text(
        json.dumps(study))
    out = root / "report.json"
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert {"straggler.approx.e2.residual_within_bound",
            "straggler.approx.e2.recovered_fraction_min",
            "straggler.all_ok"} <= regs

    # the feasibility flag is kind "pinned": the budget-infeasible cyclic
    # cell silently claiming feasibility (0 -> 1, the "good" direction for
    # an ok-kind bool) must ALSO gate — feasibility changes are semantic,
    # never improvements
    study["rows"][0]["residual_within_bound"] = True
    study["rows"][0]["recovered_fraction_min"] = 1.0
    study["all_ok"] = True
    study["rows"][1]["feasible"] = True
    (root / "baselines_out" / "straggler_study.json").write_text(
        json.dumps(study))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "straggler.cyclic.e3.feasible" in regs


def test_autopilot_study_infeasible_cell_fast(tmp_path):
    """tools/autopilot_study.py partial sweep: the fixed-approx cell is
    infeasible BY CONSTRUCTION under the adversary scenario (config.
    validate: no Byzantine certificate) and a partial sweep can never
    certify beats_fixed — exit 1 with the structure intact."""
    import json

    from tools import autopilot_study

    out = tmp_path / "ap.json"
    rc = autopilot_study.main(["--cells", "approx_r1.5",
                               "--out", str(out)])
    assert rc == 1
    data = json.loads(out.read_text())
    (row,) = data["rows"]
    assert row["cell"] == "approx_r1.5" and row["feasible"] is False
    assert "adversary" in row["detail"]
    assert data["infeasible_fixed"] == ["approx_r1.5"]
    assert data["autopilot_beats_fixed"] is False
    assert data["scenario"].count("@") == 3  # the committed 3-episode plan


def test_perf_watch_gates_on_flipped_autopilot_certificates(tmp_path):
    """The autopilot-study certificates gate at tolerance 0 in BOTH
    directions: beats_fixed or quarantine_clean flipping false is a
    control-loop regression; the infeasible fixed-approx cell silently
    claiming feasibility (the 'good' direction) is a semantic change in
    the family's validation and must gate too (kind 'pinned')."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()
    study = {"all_ok": True, "autopilot_beats_fixed": True, "rows": [
        {"cell": "autopilot", "feasible": True, "reached_target": True,
         "remediations_attributed": True, "dialed_down": True,
         "dialed_up": True, "quarantine_clean": True, "ok": True},
        {"cell": "cyclic_r3", "feasible": True, "reached_target": True,
         "ok": True},
        {"cell": "approx_r1.5", "feasible": False},
    ]}
    path = root / "baselines_out" / "autopilot_study.json"
    path.write_text(json.dumps(study))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    assert "autopilot.autopilot_beats_fixed" in snap["metrics"]
    assert "autopilot.autopilot.quarantine_clean" in snap["metrics"]
    # infeasible cells fold ONLY their (pinned) feasibility flag
    assert "autopilot.approx_r1.5.feasible" in snap["metrics"]
    assert "autopilot.approx_r1.5.reached_target" not in snap["metrics"]
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    study["autopilot_beats_fixed"] = False
    study["all_ok"] = False
    study["rows"][0]["quarantine_clean"] = False
    path.write_text(json.dumps(study))
    out = root / "report.json"
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert {"autopilot.autopilot_beats_fixed",
            "autopilot.autopilot.quarantine_clean",
            "autopilot.all_ok"} <= regs

    # the pinned direction: fixed approx silently becoming feasible gates
    study["autopilot_beats_fixed"] = True
    study["all_ok"] = True
    study["rows"][0]["quarantine_clean"] = True
    study["rows"][2]["feasible"] = True
    path.write_text(json.dumps(study))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "autopilot.approx_r1.5.feasible" in regs


def test_perf_watch_passes_on_committed_artifacts():
    """The committed baselines_out/perf_watch.json snapshot must match the
    committed round artifacts — the same gate a future round runs."""
    from tools import perf_watch

    assert perf_watch.main(["--root", REPO]) == 0


def test_lm_lowering_audit_matches_r5_rung():
    """Drift guard (r5 review): the offline lowering audit hardcodes the
    lm_big rung shapes because the chain script cannot be edited while it
    runs — so this test is the sync mechanism. If either side changes, it
    fails and points at the other."""
    import re

    from tools.tpu_lm_lowering_check import (
        LM_BIG, LM_BIG_VARIANTS_B1, LM_BIG_VARIANTS_B2,
    )

    sh = open(os.path.join(os.path.dirname(__file__), "..",
                           "tools", "chip_jobs_r5.sh")).read()
    m = re.search(r"rung lm_big .*?'(.*?)'", sh, re.S)
    assert m, "lm_big rung not found in chip_jobs_r5.sh"
    rung = m.group(1)

    def flag(name, text):
        fm = re.search(rf"--{name}\s+(\S+)", text)
        return fm and fm.group(1)

    legs = rung.split("&&")
    assert len(legs) == 2, "expected the b=2 leg and the b=1 simulate leg"
    for leg, bsz, variants in ((legs[0], "2", LM_BIG_VARIANTS_B2),
                               (legs[1], "1", LM_BIG_VARIANTS_B1)):
        assert flag("model-dim", leg) == str(LM_BIG["model_dim"])
        assert flag("model-heads", leg) == str(LM_BIG["model_heads"])
        assert flag("model-layers", leg) == str(LM_BIG["model_layers"])
        assert flag("seq-len", leg) == str(LM_BIG["seq_len"])
        assert flag("batch-size", leg) == bsz
        assert "--remat" in leg
        # steps+1 == max_steps (run_lm convention)
        assert int(flag("steps", leg)) + 1 == LM_BIG["max_steps"]
        got = set(flag("variants", leg).split(","))
        assert got >= set(variants), (got, variants)


def test_device_profile_check_gates_on_flipped_decode_share(tmp_path,
                                                            capsys):
    """tools/device_profile.py --check (jax-free): the committed artifact
    passes its self-consistency gate; a flipped decode-share row exits 1
    and names the cell + metric; a broken phase sum and an un-tripped
    mismatch control gate too (ISSUE 9 acceptance)."""
    import json

    from tools import device_profile

    committed = os.path.join(REPO, "baselines_out", "device_profile.json")
    assert device_profile.main(["--check", "--artifact", committed]) == 0
    capsys.readouterr()

    data = json.load(open(committed))
    cell = next(r for r in data["cells"] if not r.get("control"))
    # flip the decode-share column without touching the phase rows it is
    # derived from — the check recomputes and names the drift
    cell["programs"][0]["decode_share"] = round(
        cell["programs"][0]["decode_share"] + 0.25, 4)
    bad = tmp_path / "device_profile.json"
    bad.write_text(json.dumps(data))
    assert device_profile.main(["--check", "--artifact", str(bad)]) == 1
    out = capsys.readouterr().out
    assert cell["cell"] in out and "decode_share" in out

    # a phase row edited out from under the total breaks the sums contract
    data = json.load(open(committed))
    cell = next(r for r in data["cells"] if not r.get("control"))
    cell["programs"][0]["phases"]["draco_comp"]["time_us"] = 0.0
    bad.write_text(json.dumps(data))
    assert device_profile.main(["--check", "--artifact", str(bad)]) == 1
    assert "phase rows sum" in capsys.readouterr().out

    # the seeded mismatch control must have tripped
    data = json.load(open(committed))
    next(r for r in data["cells"] if r.get("control"))["ok"] = False
    bad.write_text(json.dumps(data))
    assert device_profile.main(["--check", "--artifact", str(bad)]) == 1
    assert "control did not trip" in capsys.readouterr().out


def test_perf_watch_gates_on_flipped_device_metrics(tmp_path):
    """A decode-share regression in device_profile.json gates perf_watch
    at the time tolerance and names the metric; the explicit-collective
    instruction count is pinned at tolerance 0 in BOTH directions (a
    collective vanishing from the trace is as much a semantic change as
    one appearing)."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()

    def artifact(decode_share, ar_instr, control_ok=True):
        phases = {
            "draco_comp": {"time_us": 700.0, "frac": 0.7, "events": 10},
            "draco_encode": {"time_us": 50.0, "frac": 0.05, "events": 2},
            "draco_decode": {"time_us": decode_share * 1000.0,
                             "frac": decode_share, "events": 5},
            "draco_update": {"time_us": 30.0, "frac": 0.03, "events": 1},
            "other": {"time_us": 20.0, "frac": 0.02, "events": 1},
            "unattributed": {"time_us": 0.0, "frac": 0.0, "events": 0},
        }
        counts = {"all_reduce": ar_instr, "all_gather": 0, "all_to_all": 0,
                  "collective_permute": 5, "reduce_scatter": 0}
        led = {k: {"instructions": counts[k], "events": counts[k] * 8,
                   "bytes": counts[k] * 4096, "time_us": 1.0}
               for k in counts}
        return {"schema": 1, "all_ok": True, "cells": [
            {"cell": "lm_sp_k4", "steps_per_call": 4, "ok": True,
             "programs": [{
                 "module": "jit_many_body", "total_device_us": 1000.0,
                 "phases": phases, "decode_share": decode_share,
                 "collectives": {"explicit": led,
                                 "gspmd": {}},
                 "cross_check": {"ok": True, "expected": counts,
                                 "observed": counts},
             }]},
            {"cell": "control_extra_all_gather", "control": True,
             "ok": control_ok},
        ]}

    path = root / "baselines_out" / "device_profile.json"
    path.write_text(json.dumps(artifact(0.20, 2)))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    assert "device.lm_sp_k4.draco_decode_share" in snap["metrics"]
    assert "device.lm_sp_k4.coll.all_reduce.instructions" in snap["metrics"]
    assert "device.control_extra_all_gather.tripped" in snap["metrics"]
    # zero-count kinds with a zero manifest don't spam the metric set
    assert "device.lm_sp_k4.coll.all_to_all.instructions" \
        not in snap["metrics"]
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    # decode share grows 30% relative: gates at the 10% time tolerance
    path.write_text(json.dumps(artifact(0.26, 2)))
    out = root / "report.json"
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "device.lm_sp_k4.draco_decode_share" in regs

    # an explicit collective VANISHING (2 -> 1, the "good" direction for a
    # lower-better kind) still gates: the ledger is pinned, not scored
    path.write_text(json.dumps(artifact(0.20, 1)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert {"device.lm_sp_k4.coll.all_reduce.instructions",
            "device.lm_sp_k4.coll.all_reduce.bytes"} <= regs

    # the mismatch control silently not tripping gates too
    path.write_text(json.dumps(artifact(0.20, 2, control_ok=False)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "device.control_extra_all_gather.tripped" in regs


@pytest.mark.slow
def test_wire_study_tool(tmp_path):
    """tools/wire_study.py smoke (ISSUE 10; slow-marked — the live-cell
    behavior is already pinned in tier 1 by the watch-enabled K∈{1,4}
    equivalence suites, and the committed artifact by --check +
    check_artifacts + the perf_watch flipped-row gates): a cyclic bf16
    cell runs the shadow-quantized wire with a LIVE adversary, and
    detection survives quantization — flag agreement 1.0, shadow P/R 1.0,
    bounded end-to-end error, and the logical bytes ledger at the
    program's real dimension."""
    import json

    from tools import wire_study

    out = tmp_path / "wire.json"
    rc = wire_study.main([
        "--out", str(out), "--cpu-mesh", "8", "--families", "cyclic",
        "--dtypes", "bf16", "--ks", "1", "--max-steps", "6",
    ])
    rep = json.loads(out.read_text())
    assert rc == 0 and rep["all_ok"]
    row = rep["rows"][0]
    assert row["family"] == "cyclic" and row["dtype"] == "bf16"
    assert row["steps"] == 6
    assert row["det_preserved"]
    assert row["shadow_flag_agree_min"] == 1.0
    assert row["det_precision_shadow"] == 1.0
    assert row["det_recall_shadow"] == 1.0
    assert row["adv_total"] > 0  # the adversary was really live
    assert 0.0 <= row["shadow_err_max"] < 0.05
    assert row["guard_trips_total"] == 0.0
    per = row["wire"]["bytes_per_worker"]
    assert per["bf16"] * 2 == per["f32"] and per["int8"] < per["bf16"]
    # the REAL-wire cell (ISSUE 15) rides the same invocation: bounded
    # end-to-end error vs the f32 twin, P/R 1.0 on the narrow wire's own
    # flags, and the materialized bytes ARE the logical bf16 candidate
    real = next(r for r in rep["rows"] if r.get("mode") == "real")
    assert real["det_precision"] == 1.0 and real["det_recall"] == 1.0
    assert 0.0 < real["end_to_end_err"] < 2e-2
    assert real["wire"]["wire_dtype"] == "bf16"
    assert real["wire"]["physical_bytes_per_worker"] \
        == real["wire"]["bytes_per_worker"]["bf16"]
    # the locator cells replay the PR 10 blocker: λ=0 reproduces it, the
    # committed λ solves it
    locs = {bool(r["regularized"]): r for r in rep["rows"]
            if r.get("mode") == "locator" and r["dtype"] == "bf16"}
    assert not locs[False]["usable"] and locs[True]["usable"]


def test_wire_study_check_names_failures(tmp_path):
    """--check (jax-free) trips on a stale ledger, a lost bf16 detection
    pin, and a false all_ok — naming the cell."""
    import json

    from tools import wire_study

    committed = os.path.join(REPO, "baselines_out", "wire_study.json")
    data = json.load(open(committed))
    assert wire_study.main(["--check", "--artifact", committed]) == 0

    bad = tmp_path / "wire_study.json"
    # ledger bytes inconsistent with dim
    d2 = json.loads(json.dumps(data))
    d2["rows"][0]["wire"]["bytes_per_worker"]["f32"] += 4
    bad.write_text(json.dumps(d2))
    assert wire_study.main(["--check", "--artifact", str(bad)]) == 1

    # a bf16 row losing detection must fail even if its ok flag lies
    d2 = json.loads(json.dumps(data))
    row = next(r for r in d2["rows"] if r["dtype"] == "bf16")
    row["det_preserved"] = False
    bad.write_text(json.dumps(d2))
    assert wire_study.main(["--check", "--artifact", str(bad)]) == 1

    d2 = json.loads(json.dumps(data))
    d2["all_ok"] = False
    bad.write_text(json.dumps(d2))
    assert wire_study.main(["--check", "--artifact", str(bad)]) == 1


def test_perf_watch_gates_on_flipped_wire_metrics(tmp_path):
    """The wire-study fold (ISSUE 10): shadow residual / flag agreement
    are PINNED at tolerance 0 — a flipped row gates in BOTH directions
    (the live flipped-row control of the acceptance criteria) — and a
    det_preserved flip or shadow-recall drop gates as 0-tolerance ok."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()

    def artifact(residual=0.0001, agree=1.0, preserved=True, recall=1.0):
        row = {"family": "cyclic", "dtype": "bf16", "k": 4,
               "shadow_err_max": 0.005, "shadow_residual_max": residual,
               "shadow_flag_agree_min": agree, "det_preserved": preserved,
               "det_precision_shadow": 1.0, "det_recall_shadow": recall,
               "wire": {"bytes_per_worker": {"f32": 800, "bf16": 400,
                                             "int8": 214}},
               "ok": True}
        return {"all_ok": True, "rows": [row]}

    path = root / "baselines_out" / "wire_study.json"
    path.write_text(json.dumps(artifact()))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    assert "wire.cyclic.bf16.k4.shadow_residual_max" in snap["metrics"]
    assert "wire.cyclic.bf16.k4.bytes_per_worker" in snap["metrics"]
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    out = root / "report.json"
    # the flipped shadow-residual row: a DECREASE also gates (pinned)
    path.write_text(json.dumps(artifact(residual=0.00005)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "wire.cyclic.bf16.k4.shadow_residual_max" in regs

    # flag agreement dipping below 1.0 gates
    path.write_text(json.dumps(artifact(agree=0.875)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "wire.cyclic.bf16.k4.shadow_flag_agree_min" in regs

    # detection lost under quantization gates
    path.write_text(json.dumps(artifact(preserved=False, recall=0.8)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert {"wire.cyclic.bf16.k4.det_preserved",
            "wire.cyclic.bf16.k4.det_recall_shadow"} <= regs


def test_perf_watch_gates_on_flipped_real_wire_metrics(tmp_path):
    """The ISSUE 15 real-wire fold: narrow-wire detection P/R and the
    pinned end-to-end error gate at tolerance 0 in BOTH directions; the
    physical bytes ride at the bytes tolerance (a ballooning wire gates,
    an honest dim change inside tolerance does not); the locator cells'
    blocker certificate is pinned BOTH ways — the λ=0 row silently
    becoming usable gates exactly like the regularized row losing it."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()

    def artifact(err=0.0002, prec=1.0, phys=214, unreg_usable=False,
                 reg_usable=True):
        rows = [
            {"mode": "real", "family": "cyclic", "dtype": "int8", "k": 4,
             "end_to_end_err": err, "det_precision": prec,
             "det_recall": 1.0, "det_preserved": prec == 1.0,
             "wire": {"bytes_per_worker": {"f32": 800, "bf16": 400,
                                           "int8": 214},
                      "wire_dtype": "int8",
                      "physical_bytes_per_worker": phys},
             "ok": True},
            {"mode": "locator", "n": 32, "s": 3, "dtype": "int8",
             "lam": 0.0, "regularized": False, "usable": unreg_usable,
             "honest_dev_max_noadv": 136.9, "adv_dev_min": 0.333,
             "ok": not unreg_usable},
            {"mode": "locator", "n": 32, "s": 3, "dtype": "int8",
             "lam": 0.015625, "regularized": True, "usable": reg_usable,
             "honest_dev_max_noadv": 0.24, "adv_dev_min": 0.333,
             "ok": reg_usable},
        ]
        return {"all_ok": all(r["ok"] for r in rows), "rows": rows}

    path = root / "baselines_out" / "wire_study.json"
    path.write_text(json.dumps(artifact()))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    for key in ("wire.real.cyclic.int8.k4.det_precision",
                "wire.real.cyclic.int8.k4.end_to_end_err",
                "wire.real.cyclic.int8.k4.physical_bytes_per_worker",
                "wire.locator.n32s3.int8.unreg.usable",
                "wire.locator.n32s3.int8.reg.usable"):
        assert key in snap["metrics"], key
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    out = root / "report.json"
    # end-to-end err is PINNED: an IMPROVEMENT gates too
    path.write_text(json.dumps(artifact(err=0.0001)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "wire.real.cyclic.int8.k4.end_to_end_err" in regs

    # lost precision on the real wire gates as ok-kind
    path.write_text(json.dumps(artifact(prec=0.8)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "wire.real.cyclic.int8.k4.det_precision" in regs

    # a ballooning physical wire gates at the bytes tolerance
    path.write_text(json.dumps(artifact(phys=800)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "wire.real.cyclic.int8.k4.physical_bytes_per_worker" in regs

    # the blocker certificate flips BOTH ways
    path.write_text(json.dumps(artifact(unreg_usable=True)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "wire.locator.n32s3.int8.unreg.usable" in regs
    path.write_text(json.dumps(artifact(reg_usable=False)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "wire.locator.n32s3.int8.reg.usable" in regs


def test_wire_study_check_real_and_locator_rows(tmp_path):
    """wire_study --check (ISSUE 15): the committed artifact passes; a
    mutated real row (physical bytes diverging from the ledger, P/R
    dropping) or a flipped locator certificate is caught and named."""
    import copy
    import json

    from tools import wire_study

    committed = os.path.join(REPO, "baselines_out", "wire_study.json")
    data = json.load(open(committed))
    assert wire_study.main(["--check", "--artifact", committed]) == 0

    bad = tmp_path / "wire_study.json"

    def mutate(fn):
        d = copy.deepcopy(data)
        fn(d)
        bad.write_text(json.dumps(d))
        return wire_study.main(["--check", "--artifact", str(bad)])

    def first(d, mode):
        return next(r for r in d["rows"] if r.get("mode") == mode)

    # materialized bytes diverging from the logical candidate row
    assert mutate(lambda d: first(d, "real")["wire"].update(
        physical_bytes_per_worker=999999)) == 1
    # detection lost on the real wire
    def drop_pr(d):
        r = next(r for r in d["rows"] if r.get("mode") == "real"
                 and r["family"] == "cyclic")
        r["det_precision"] = 0.5
    assert mutate(drop_pr) == 1
    # the λ=0 blocker "solved" (exact path changed) trips
    def flip_unreg(d):
        r = next(r for r in d["rows"] if r.get("mode") == "locator"
                 and not r["regularized"])
        r["usable"] = True
    assert mutate(flip_unreg) == 1
    # the regularized threshold drifting off the committed table trips
    def drift_thr(d):
        r = next(r for r in d["rows"] if r.get("mode") == "locator"
                 and r["regularized"])
        r["threshold"] = r["threshold"] * 2
    assert mutate(drift_thr) == 1


def test_perf_watch_gates_on_flipped_chaos_numerics(tmp_path):
    """The nan_grad cells' ISSUE 10 NaN-safety flags (numerics_finite /
    fault_visible) gate perf_watch at tolerance 0."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()
    matrix = {"all_ok": True, "rows": [
        {"loop": "cnn_k4", "fault": "nan_grad", "ok": True,
         "outcome": "guarded", "attributed": True,
         "numerics_finite": True, "fault_visible": True},
    ]}
    (root / "baselines_out" / "chaos_matrix.json").write_text(
        json.dumps(matrix))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    assert "chaos.cnn_k4.nan_grad.numerics_finite" in snap["metrics"]
    assert perf_watch.main(["--root", str(root)]) == 0

    matrix["rows"][0]["numerics_finite"] = False
    (root / "baselines_out" / "chaos_matrix.json").write_text(
        json.dumps(matrix))
    out = root / "report.json"
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = [r["metric"] for r in json.loads(out.read_text())["regressions"]]
    assert "chaos.cnn_k4.nan_grad.numerics_finite" in regs


def test_check_artifacts_tool(tmp_path, capsys):
    """tools/check_artifacts.py (jax-free, ISSUE 10 satellite): one
    command re-verifies every committed artifact and exits 0 on the
    repo; a root with a broken artifact exits 1 NAMING the first
    failing check."""
    from tools import check_artifacts

    assert check_artifacts.main(["--root", REPO]) == 0
    out = capsys.readouterr().out
    assert "all" in out and "passed" in out

    # an empty root has no perf_watch baseline: the first check fails
    # and is named
    (tmp_path / "baselines_out").mkdir()
    assert check_artifacts.main(["--root", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "FAILED at 'perf_watch'" in out

    # a root whose perf_watch passes but whose wire study is broken names
    # THAT check: copy the committed snapshot world minus wire_study
    import json
    import shutil

    for f in ("perf_watch.json", "program_lint.json", "chaos_matrix.json",
              "straggler_study.json", "device_profile.json",
              "wire_study.json"):
        src = os.path.join(REPO, "baselines_out", f)
        if os.path.exists(src):
            shutil.copy(src, tmp_path / "baselines_out" / f)
    study = json.load(open(tmp_path / "baselines_out" / "wire_study.json"))
    # break the ledger ARITHMETIC of a column perf_watch does not fold
    # (the f32 bytes of a bf16 row), so the perf_watch check still passes
    # and the failure is attributed to the wire_study verifier
    study["rows"][0]["wire"]["bytes_per_worker"]["f32"] += 4
    (tmp_path / "baselines_out" / "wire_study.json").write_text(
        json.dumps(study))
    # BENCH_r*/MULTICHIP_r* are read from the root: absent here, their
    # metrics fold as missing (non-fatal without --strict-missing)
    assert check_artifacts.main(["--root", str(tmp_path)]) == 1
    assert "FAILED at 'wire_study --check'" in capsys.readouterr().out


def test_decode_kernel_bench_check_gates(tmp_path, capsys):
    """tools/decode_kernel_bench.py --check (jax-free, ISSUE 12): the
    committed artifact passes; a gated rung whose fused decode went
    slower than XLA exits 1 naming the rung, and broken ratio arithmetic
    gates too."""
    import json

    from tools import decode_kernel_bench

    committed = os.path.join(REPO, "baselines_out",
                             "decode_kernel_bench.json")
    assert decode_kernel_bench.main(
        ["--check", "--artifact", committed]) == 0
    capsys.readouterr()

    data = json.load(open(committed))
    row = next(r for r in data["rows"] if r.get("gate"))
    # the fused path regressing slower than XLA at a committed gated rung
    row["pallas_ms"] = round(row["xla_ms"] * 1.5, 3)
    row["pallas_over_xla"] = round(row["pallas_ms"] / row["xla_ms"], 4)
    row["kernel_not_slower"] = False
    bad = tmp_path / "decode_kernel_bench.json"
    bad.write_text(json.dumps(data))
    assert decode_kernel_bench.main(["--check", "--artifact",
                                     str(bad)]) == 1
    out = capsys.readouterr().out
    assert row["rung"] in out and "slower than XLA" in out

    # ratio arithmetic drifting from the recorded timings gates
    data = json.load(open(committed))
    data["rows"][0]["pallas_over_xla"] = 0.123
    bad.write_text(json.dumps(data))
    assert decode_kernel_bench.main(["--check", "--artifact",
                                     str(bad)]) == 1
    assert "ratio" in capsys.readouterr().out


def test_perf_watch_gates_on_flipped_decode_bench(tmp_path):
    """The decode-bench fold: a gated rung's kernel_not_slower flipping
    1 -> 0 gates at tolerance 0, and a ratio regression past the time
    tolerance gates too (ISSUE 12 acceptance: the flipped-row proof that
    the kernel-slower-than-XLA gate is live)."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()

    def artifact(ratio, not_slower):
        rows = [{"rung": "cyclic_layer_n8", "family": "cyclic", "n": 8,
                 "s": 1, "d": 400000, "granularity": "layer", "layers": 10,
                 "gate": True, "xla_ms": 8.0,
                 "pallas_ms": round(8.0 * ratio, 3),
                 "pallas_over_xla": ratio,
                 "pallas_lowering": "fused_xla",
                 "kernel_not_slower": not_slower}]
        return {"schema": 1, "all_ok": not_slower, "rows": rows}

    path = root / "baselines_out" / "decode_kernel_bench.json"
    path.write_text(json.dumps(artifact(0.9, True)))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    assert perf_watch.main(["--root", str(root)]) == 0

    # fused decode now slower than xla: the 0-tolerance ok flag gates
    path.write_text(json.dumps(artifact(1.2, False)))
    assert perf_watch.main(["--root", str(root)]) == 1

    # ratio creep past the time tolerance gates even while not slower yet
    # (0.9 -> 1.0 is +11% against the 10% time tolerance)
    path.write_text(json.dumps(artifact(1.0, True)))
    assert perf_watch.main(["--root", str(root)]) == 1


def test_device_profile_check_gates_on_pallas_claim(tmp_path, capsys):
    """The ISSUE 12 acceptance gate: every PALLAS_CLAIMS pair in the
    committed device profile shows the fused-decode cell's decode share
    STRICTLY below its same-shape xla pair; a flipped pallas cell exits 1
    naming the pair, and a half-missing pair gates too."""
    import json

    from tools import device_profile

    committed = os.path.join(REPO, "baselines_out", "device_profile.json")
    data = json.load(open(committed))
    cells = {r.get("cell") for r in data["cells"]}
    for p, x in device_profile.PALLAS_CLAIMS.items():
        assert {p, x} <= cells, "committed artifact must hold EVERY pair"
    pal, xla = next(iter(sorted(device_profile.PALLAS_CLAIMS.items())))
    assert device_profile.main(["--check", "--artifact", committed]) == 0
    capsys.readouterr()

    # flip the pallas cell's decode share above its xla pair — keep the
    # phase rows consistent so ONLY the claim gate trips
    bad_data = json.load(open(committed))
    pal_row = next(r for r in bad_data["cells"] if r.get("cell") == pal)
    xla_row = next(r for r in bad_data["cells"] if r.get("cell") == xla)
    xla_share = xla_row["programs"][0]["decode_share"]
    prog = pal_row["programs"][0]
    dec = prog["phases"]["draco_decode"]
    comp = prog["phases"]["draco_comp"]
    total = prog["total_device_us"]
    new_frac = round(xla_share + 0.1, 4)
    moved = new_frac * total - dec["time_us"]
    dec["time_us"] = round(dec["time_us"] + moved, 1)
    comp["time_us"] = round(comp["time_us"] - moved, 1)
    dec["frac"] = new_frac
    comp["frac"] = round(comp["time_us"] / total, 4)
    prog["decode_share"] = new_frac
    bad = tmp_path / "device_profile.json"
    bad.write_text(json.dumps(bad_data))
    assert device_profile.main(["--check", "--artifact", str(bad)]) == 1
    out = capsys.readouterr().out
    assert pal in out and "not strictly below" in out

    # a claim pair with its xla half missing is incomplete, never
    # skipped — and a regeneration that drops BOTH cells of a claimed
    # pair fails too (the claim may never silently go unenforced)
    bad_data = json.load(open(committed))
    bad_data["cells"] = [r for r in bad_data["cells"]
                         if r.get("cell") != xla]
    bad.write_text(json.dumps(bad_data))
    assert device_profile.main(["--check", "--artifact", str(bad)]) == 1
    assert "claim pair missing/incomplete" in capsys.readouterr().out
    bad_data = json.load(open(committed))
    bad_data["cells"] = [r for r in bad_data["cells"]
                         if r.get("cell") not in (pal, xla)]
    bad.write_text(json.dumps(bad_data))
    assert device_profile.main(["--check", "--artifact", str(bad)]) == 1
    assert pal in capsys.readouterr().out


def test_perf_watch_gates_on_flipped_sharding_axis_ledger(tmp_path):
    """The sharding auditor's per-axis collective ledger (lint rule 8,
    ISSUE 18) folds as ``lint.<program>.coll.<axis>.{ops,bytes}`` and is
    PINNED at tolerance 0 in BOTH directions: an all-reduce moving to a
    different mesh axis — or vanishing, the 'good' direction for a
    lower-better kind — is a topology change, never an improvement."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()

    def lint(sp_ops=7, sp_bytes=500745, w_bytes=1024):
        rules = {
            "constant_bloat": {"ok": True, "module_bytes": 1000},
            "memory_budget": {"ok": True, "flops": 1e6,
                              "memory": {"peak_bytes": 5000}},
            "collective_axes": {
                "ok": True,
                "axis_ledger": {"sp": {"ops": sp_ops, "bytes": sp_bytes},
                                "w": {"ops": 2, "bytes": w_bytes}}},
        }
        return {"all_ok": True, "rows": [
            {"name": "lm_sp_ring_step", "ok": True, "rules": rules},
            {"name": "control_wrong_axis_psum", "ok": True,
             "control": True, "expected_fail": "collective_axes",
             "rules": {}},
        ]}

    path = root / "baselines_out" / "program_lint.json"
    path.write_text(json.dumps(lint()))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    for key in ("lint.lm_sp_ring_step.coll.sp.ops",
                "lint.lm_sp_ring_step.coll.sp.bytes",
                "lint.lm_sp_ring_step.coll.w.bytes"):
        assert key in snap["metrics"], key
        assert snap["metrics"][key]["kind"] == "pinned", key
    # control rows never fold ledger metrics
    assert "lint.control_wrong_axis_psum.coll" not in str(snap)
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    out = root / "report.json"
    # bytes growing on an axis gates...
    path.write_text(json.dumps(lint(sp_bytes=600000)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "lint.lm_sp_ring_step.coll.sp.bytes" in regs

    # ...and an op VANISHING from an axis (7 -> 6, the 'good' direction)
    # gates identically: the ledger is pinned, not scored
    path.write_text(json.dumps(lint(sp_ops=6)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "lint.lm_sp_ring_step.coll.sp.ops" in regs

    # w-axis bytes shrinking gates too (both-direction on a second axis)
    path.write_text(json.dumps(lint(w_bytes=512)))
    assert perf_watch.main(["--root", str(root), "--json", str(out)]) == 1
    regs = {r["metric"] for r in json.loads(out.read_text())["regressions"]}
    assert "lint.lm_sp_ring_step.coll.w.bytes" in regs


def test_check_artifacts_sharding_audit_and_lint_config(tmp_path):
    """check_artifacts' ISSUE 18 checks (jax-free): a stale six-rule
    artifact, a program row missing a rule-7 verdict, and a blunted
    negative control each fail 'sharding audit coverage' with the first
    failure named; a repo root without a lint config fails 'lint config
    present'."""
    import json

    from tools.check_artifacts import (
        _check_lint_config, _check_sharding_audit,
    )

    root = tmp_path
    (root / "baselines_out").mkdir()
    path = root / "baselines_out" / "program_lint.json"

    def artifact():
        rules7_9 = {"sharding_contract": {"ok": True},
                    "collective_axes": {"ok": True},
                    "replication_leaks": {"ok": True}}
        controls = [
            {"name": n, "control": True, "ok": True, "expected_fail": f}
            for n, f in (
                ("control_resharded_carry", "sharding_contract"),
                ("control_unnormalized_spec", "sharding_contract"),
                ("control_unmatched_param", "sharding_contract"),
                ("control_wrong_axis_psum", "collective_axes"),
                ("control_replicated_wire", "replication_leaks"),
            )]
        return {"all_ok": True,
                "rules": ["sharding_contract", "collective_axes",
                          "replication_leaks"],
                "rows": [{"name": "p1", "ok": True,
                          "rules": dict(rules7_9)}] + controls}

    path.write_text(json.dumps(artifact()))
    assert _check_sharding_audit(str(root)) is None

    # stale rule list (regenerated from a six-rule checkout)
    art = artifact()
    art["rules"] = ["constant_bloat"]
    path.write_text(json.dumps(art))
    assert "regenerate" in _check_sharding_audit(str(root))

    # a program row without the rule-9 verdict
    art = artifact()
    del art["rows"][0]["rules"]["replication_leaks"]
    path.write_text(json.dumps(art))
    err = _check_sharding_audit(str(root))
    assert "p1" in err and "replication_leaks" in err

    # a red verdict on a program row names the rule
    art = artifact()
    art["rows"][0]["rules"]["collective_axes"] = {
        "ok": False, "error": "psum over 'w' not in the manifest"}
    path.write_text(json.dumps(art))
    err = _check_sharding_audit(str(root))
    assert "p1" in err and "collective_axes" in err

    # a live control silently going green (blunted defect) fails
    art = artifact()
    ctrl = next(r for r in art["rows"]
                if r["name"] == "control_replicated_wire")
    ctrl["ok"] = False
    path.write_text(json.dumps(art))
    assert "control_replicated_wire" in _check_sharding_audit(str(root))

    # ...and a missing control fails by name
    art = artifact()
    art["rows"] = [r for r in art["rows"]
                   if r["name"] != "control_wrong_axis_psum"]
    path.write_text(json.dumps(art))
    assert "control_wrong_axis_psum" in _check_sharding_audit(str(root))

    # lint config: absent fails; present-with-line-length passes; a
    # config that pins no line budget fails
    assert "no ruff.toml" in _check_lint_config(str(root))
    (root / "ruff.toml").write_text("line-length = 79\n")
    assert _check_lint_config(str(root)) is None
    (root / "ruff.toml").write_text("[lint]\nselect = ['E']\n")
    assert "line-length" in _check_lint_config(str(root))


def test_fleet_report_and_study_check_on_committed_artifact(tmp_path):
    """ISSUE 19: tools/fleet_report.py runs jax-free on a bare checkout
    (empty root exits 0), and the committed fleet_slo.json passes its
    own --check re-verification — the same gate check_artifacts runs."""
    import json

    from tools import fleet_report, fleet_study

    empty = tmp_path / "none"
    empty.mkdir()
    assert fleet_report.main(["--runs-root", str(empty)]) == 0

    payload = json.load(
        open(os.path.join(REPO, "baselines_out", "fleet_slo.json")))
    assert fleet_study.verify_payload(payload) == []
    rows = payload["rows"]
    assert len(rows) >= 6
    assert {r["loop"] for r in rows} == {"cnn", "lm"}
    kinds = {r["kind"] for r in rows}
    assert {"clean", "adversary", "straggler", "autopilot"} <= kinds
    assert all(r["budget_burned"] == 0.0 for r in rows)
    for r in rows:
        if r["kind"] in ("adversary", "autopilot"):
            det = r["slo"]["detection_quality"]
            assert det["precision"] == det["recall"] == 1.0
            assert det["adv_total"] > 0  # live, not vacuous
        if r["kind"] == "autopilot":
            mttr = r["slo"]["incident_mttr"]
            assert mttr["mttr_s"] is not None and mttr["mttr_s"] >= 0
            assert mttr["unattributed"] == 0


def test_fleet_study_check_gates_on_flipped_rows(tmp_path):
    """The flipped-row controls: every certificate the committed fleet
    artifact pins must FAIL verify_payload when hand-flipped — stale
    status schema refused, budget burn, detection P/R, MTTR
    attribution, and an ok bool disagreeing with its own row."""
    import copy
    import json

    from tools import fleet_study

    base = json.load(
        open(os.path.join(REPO, "baselines_out", "fleet_slo.json")))

    def flip(mut):
        p = copy.deepcopy(base)
        mut(p)
        return "\n".join(fleet_study.verify_payload(p))

    assert fleet_study.verify_payload(copy.deepcopy(base)) == []

    def stale(p):
        p["status_schema"] -= 1
    assert "stale artifact" in flip(stale)

    def burn(p):
        p["rows"][0]["budget_burned"] = 2.0
    assert "burned 2" in flip(burn)

    def bad_precision(p):
        row = next(r for r in p["rows"] if r["kind"] == "adversary")
        row["slo"]["detection_quality"]["precision"] = 0.9
    assert "P/R 0.9" in flip(bad_precision)

    def vacuous(p):
        row = next(r for r in p["rows"] if r["kind"] == "adversary")
        row["slo"]["detection_quality"]["adv_total"] = 0
    assert "vacuous" in flip(vacuous)

    def unattributed(p):
        row = next(r for r in p["rows"] if r["kind"] == "autopilot")
        row["slo"]["incident_mttr"]["unattributed"] = 1
    assert "unattributed" in flip(unattributed)

    def ok_disagrees(p):
        p["rows"][0]["ok"] = False
    out = flip(ok_disagrees)
    assert "disagrees" in out or "all_ok" in out

    def crashed(p):
        p["rows"][0]["state"] = "crashed"
    assert "terminal state 'crashed'" in flip(crashed)

    # ...and check_artifacts surfaces the same failure by check name
    import io
    from contextlib import redirect_stdout

    from tools import check_artifacts

    root = tmp_path / "root"
    (root / "baselines_out").mkdir(parents=True)
    stale_p = copy.deepcopy(base)
    stale_p["status_schema"] -= 1
    (root / "baselines_out" / "fleet_slo.json").write_text(
        json.dumps(stale_p))
    err = check_artifacts._check_fleet_slo(str(root))
    assert err and "stale artifact" in err


def test_perf_watch_gates_on_flipped_fleet_certificates(tmp_path):
    """The fleet_slo gate at tolerance 0 in BOTH directions: an SLO
    verdict flipping false, a clean cell starting to burn budget, and
    the detection P/R certificate moving off 1.0 are regressions; a
    burning row silently going quiet (the 'good' direction of a pinned
    metric) must gate too, as must the cell count changing."""
    import json

    from tools import perf_watch

    root = tmp_path
    (root / "baselines_out").mkdir()

    def artifact(ok=True, burned=0.0, precision=1.0, cells=2):
        rows = [{
            "cell": "cnn_adversary", "kind": "adversary",
            "state": "done", "run_id": "rid1", "ok": ok,
            "budget_burned": burned,
            "slo": {
                "detection_quality": {
                    "evaluated": True, "ok": precision == 1.0,
                    "verdict": "ok" if precision == 1.0 else "violated",
                    "precision": precision, "recall": 1.0},
                "incident_mttr": {
                    "evaluated": True, "ok": True, "verdict": "ok",
                    "mttr_s": 2.5, "unattributed": 0,
                    "attributed": 1},
            }}]
        if cells > 1:
            rows.append({"cell": "lm_clean", "kind": "clean",
                         "state": "done", "run_id": "rid2", "ok": True,
                         "budget_burned": 0.0, "slo": {}})
        return {"all_ok": ok, "rows": rows[:cells]}

    path = root / "baselines_out" / "fleet_slo.json"
    path.write_text(json.dumps(artifact()))
    assert perf_watch.main(["--root", str(root), "--snapshot"]) == 0
    snap = json.loads(
        (root / "baselines_out" / "perf_watch.json").read_text())
    for key in ("fleet_slo.all_ok", "fleet_slo.cells",
                "fleet_slo.cnn_adversary.ok",
                "fleet_slo.cnn_adversary.budget_burned",
                "fleet_slo.cnn_adversary.detection.precision",
                "fleet_slo.cnn_adversary.mttr_s",
                "fleet_slo.cnn_adversary.mttr_attributed",
                "fleet_slo.lm_clean.budget_burned"):
        assert key in snap["metrics"], key
    assert perf_watch.main(["--root", str(root)]) == 0  # clean

    out = root / "report.json"

    def regs():
        assert perf_watch.main(
            ["--root", str(root), "--json", str(out)]) == 1
        return {r["metric"]
                for r in json.loads(out.read_text())["regressions"]}

    # direction 1: a cell starts burning + its SLO verdict flips
    path.write_text(json.dumps(artifact(ok=False, burned=3.0,
                                        precision=0.9)))
    assert {"fleet_slo.all_ok", "fleet_slo.cnn_adversary.ok",
            "fleet_slo.cnn_adversary.budget_burned",
            "fleet_slo.cnn_adversary.detection.precision",
            "fleet_slo.cnn_adversary.detection_quality.ok"} <= regs()

    # direction 2 (pinned): P/R drifting ABOVE the pinned value is a
    # contract change, not an improvement — rebaseline consciously
    path.write_text(json.dumps(artifact(precision=1.1)))
    assert "fleet_slo.cnn_adversary.detection.precision" in regs()

    # a cell disappearing gates on the pinned cell count
    path.write_text(json.dumps(artifact(cells=1)))
    assert "fleet_slo.cells" in regs()
