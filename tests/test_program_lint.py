"""Program-linter tests: the registry covers every route, the fast CLI
subset is green, and — the part that keeps the linter honest — every
seeded-defect negative control trips exactly its rule.

Reference stake: none of these invariants is visible to an output-level
test. The round-5 d-sized-constant regression trained bit-identically and
wedged a 27-minute chip window anyway (PERF.md §4); donation loss doubles
carry HBM silently; an extra all-gather changes the communication
structure the gradient-coding line treats as the algorithm (PAPERS.md).
"""

import json
import os

import pytest

from draco_tpu.analysis import RULE_NAMES, collect, lint_program
from draco_tpu.analysis.controls import control_programs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.core
class TestNegativeControls:
    """One deliberately-defective program per rule (analysis/controls.py):
    each must trip exactly its rule, with every other rule staying green —
    the proving-the-harness-is-live discipline of the mis-tiled pallas_call
    in tools/tpu_attn_lowering_check.py."""

    @pytest.mark.parametrize(
        "control", control_programs(), ids=lambda c: c.program.name)
    def test_control_trips_exactly_its_rule(self, control):
        row = lint_program(control.program)
        assert row["failed_rules"] == [control.expected_fail], (
            f"{control.program.name} must trip exactly "
            f"[{control.expected_fail}], tripped {row['failed_rules']}: "
            f"{ {n: r for n, r in row['rules'].items() if not r['ok']} }"
        )
        for name, res in row["rules"].items():
            if name != control.expected_fail:
                assert res["ok"], (name, res)

    def test_controls_cover_every_rule(self):
        covered = {c.expected_fail for c in control_programs()}
        assert covered == set(RULE_NAMES)


@pytest.mark.core
def test_registry_covers_every_route():
    """Each route module registers at least its train_step and its K-fused
    scan driver; names are unique (collect() raises on dupes)."""
    programs = collect()
    routes = {p.route for p in programs}
    assert routes >= {"cnn", "sp", "tp", "pp", "ep"}
    names = {p.name for p in programs}
    for route_pair in (("cnn_cyclic_step", "cnn_cyclic_many_k2"),
                       ("lm_sp_ring_step", "lm_sp_ring_many_k2"),
                       ("lm_tp2_step", "lm_tp2_many_k2"),
                       ("lm_pp_step", "lm_pp_many_k2"),
                       ("lm_ep_step", "lm_ep_many_k2")):
        assert names >= set(route_pair), (route_pair, names)
    # the production chunked drivers with device token-gen and the big-d
    # constant-bloat guard are registered too
    assert "lm_fold_devgen_many_k2" in names
    # the kernel-bearing rows (ISSUE 12) ride the fast sweep — their TPU
    # export IS the per-commit Mosaic lowering check
    assert {"kernel_cyclic_locator", "kernel_approx_decode"} <= {
        p.name for p in programs if p.fast}
    # the ISSUE 17 mesh-sub-axis tree combine programs ride the fast
    # sweep — their collectives manifest pins one psum per level
    assert {"tree_combine_g2_l3", "tree_combine_g4_l2"} <= {
        p.name for p in programs if p.fast}
    # out of the --fast budget: the big-d constant-bloat guard (~3.3M
    # params), the ISSUE 12 fused/approx impl VARIANTS of fast-swept
    # step bodies, the ISSUE 16 segmented-wire variants, and the ISSUE 17
    # tree-topology step variants (the full tool + the committed-artifact
    # coverage test still guard them)
    big = {p.name for p in programs if not p.fast}
    assert big == {"lm_fold_big_bf16_many_k2",
                   "cnn_cyclic_layer_step", "cnn_cyclic_layer_pallas_step",
                   "cnn_approx_pallas_step",
                   "lm_sp_ring_approx_pallas_many_k2",
                   "lm_tp2_approx_many_k2", "lm_tp2_approx_pallas_many_k2",
                   "cnn_cyclic_seg2_many_k2",
                   "cnn_cyclic_seg2_wire_bf16_many_k2",
                   "cnn_approx_seg2_step",
                   "cnn_approx_seg2_wire_int8_step",
                   "cnn_cyclic_tree_g4_step", "cnn_cyclic_tree_g4_many_k2",
                   "cnn_cyclic_tree_g4_wire_bf16_many_k2",
                   "cnn_approx_tree_g4_step"}


@pytest.mark.core
def test_fast_subset_all_green(tmp_path):
    """The core-tier wiring of ``tools/program_lint.py --fast``: every fast
    registered program passes all nine rules, through the CLI's own main()
    (controls skipped here — they have their own test above). Runtime is
    the bulk of this module's core budget: ~60 s on the 1-core CI host
    (PERF.md §6)."""
    from tools.program_lint import main

    out = tmp_path / "program_lint.json"
    rc = main(["--fast", "--skip-controls", "--out", str(out)])
    report = json.loads(out.read_text())
    failed = {r["name"]: r.get("failed_rules") or r.get("error")
              for r in report["rows"] if not r["ok"]}
    assert rc == 0 and report["all_ok"], failed
    fast_names = {p.name for p in collect() if p.fast}
    assert {r["name"] for r in report["rows"]} == fast_names
    for row in report["rows"]:
        assert set(RULE_NAMES) <= set(row["rules"]), row["name"]


@pytest.mark.core
def test_committed_artifact_is_consistent_with_registry():
    """baselines_out/program_lint.json (the committed artifact) must cover
    every registered program, be green, and carry live controls — catches
    adding a program without re-running the tool."""
    path = os.path.join(REPO, "baselines_out", "program_lint.json")
    report = json.load(open(path))
    assert report["all_ok"], [r["name"] for r in report["rows"]
                              if not r["ok"]]
    rows = {r["name"]: r for r in report["rows"]}
    missing = {p.name for p in collect()} - set(rows)
    assert not missing, (
        f"programs registered but absent from the committed artifact "
        f"{sorted(missing)} — rerun tools/program_lint.py")
    controls = [r for r in report["rows"] if r.get("control")]
    assert {c["expected_fail"] for c in controls} == set(RULE_NAMES)
    # every registered (non-control) row carries the memory/cost ledger
    # columns the memory_budget rule records (ISSUE 5) — the round-over-
    # round series tools/perf_watch.py diffs. The pallas_call-bearing
    # kernel rows (ISSUE 12, route "decode_kernel") are the one legal
    # exception: tpu_custom_call cannot compile for the CPU host, so they
    # register with the memory-capture opt-out (capture_memory=False,
    # like the chip-tier flash rows) and their memory_budget row reports
    # skipped-with-reason instead of columns.
    from draco_tpu.analysis.registry import collect as _collect

    kernel_rows = {p.name for p in _collect() if p.route == "decode_kernel"}
    for r in report["rows"]:
        if r.get("control"):
            continue
        mb = r["rules"]["memory_budget"]
        if r["name"] in kernel_rows:
            assert mb.get("skipped") and mb.get("ok"), (r["name"], mb)
            continue
        assert not mb.get("skipped"), (r["name"], mb)
        mem = mb["memory"]
        for col in ("argument_bytes", "output_bytes", "temp_bytes",
                    "generated_code_bytes", "alias_bytes", "peak_bytes"):
            assert isinstance(mem.get(col), int), (r["name"], col, mem)
        assert mem["peak_bytes"] > 0
        assert mb["flops"] > 0, (r["name"], mb)


def test_bench_refuses_chip_run_on_lint_violation(tmp_path):
    """bench.py must refuse to touch the chip window while the lint
    artifact reports a constant-bloat or host-traffic violation for the
    CNN program family it times (ISSUE: a wedged window costs more than
    any data point). Uses the fake-probe hook so no test touches the real
    tunnel, and DRACO_PROGRAM_LINT_PATH to point at a violating artifact."""
    import subprocess
    import sys

    bad = {"all_ok": False, "rows": [
        {"name": "cnn_cyclic_many_k2", "route": "cnn", "ok": False,
         "failed_rules": ["constant_bloat"]},
        # control rows and non-CNN routes must NOT gate
        {"name": "control_baked_constant", "route": "controls", "ok": True,
         "control": True, "failed_rules": ["constant_bloat"]},
        {"name": "lm_fold_bf16_step", "route": "tp", "ok": False,
         "failed_rules": ["host_traffic"]},
    ]}
    art = tmp_path / "program_lint.json"
    art.write_text(json.dumps(bad))
    env = dict(os.environ, DRACO_BENCH_FAKE_PROBE="ok",
               DRACO_PROGRAM_LINT_PATH=str(art))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--budget", "60"],
        capture_output=True, text=True, cwd=REPO, timeout=120, env=env)
    records = [json.loads(ln) for ln in proc.stdout.splitlines()
               if ln.strip().startswith("{")]
    assert records, proc.stdout + proc.stderr[-400:]
    rec = records[-1]
    assert rec["error"] == "program_lint_violation", rec
    assert "cnn_cyclic_many_k2: constant_bloat" in rec["detail"]
    # the non-CNN violation is not in this bench's family -> not named
    assert "lm_fold_bf16_step" not in rec["detail"]
    assert rec["value"] is None

    # green artifact -> the gate stays open (the run proceeds to the probe
    # and fails fast on the fake-ok-but-cpu-only backend, NOT on lint)
    art.write_text(json.dumps({"all_ok": True, "rows": [
        {"name": "cnn_cyclic_many_k2", "route": "cnn", "ok": True,
         "failed_rules": []}]}))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--budget", "60",
         "--no-cpu-fallback"],
        capture_output=True, text=True, cwd=REPO, timeout=120, env=env)
    records = [json.loads(ln) for ln in proc.stdout.splitlines()
               if ln.strip().startswith("{")]
    assert records and records[-1]["error"] == "tpu_unavailable", records
