"""Multi-host (multi-process) training over the distributed backend.

The reference scales across hosts with one MPI rank per node over TCP
(reference: README.md:16, src/README.md:10). Here multi-host = multiple JAX
processes sharing one global mesh; gradients cross the process boundary via
gloo/DCN collectives inside the jitted step. These tests spawn real separate
processes (tools/local_cluster.py) — the same wiring a TPU pod uses — and
run the actual CLI end-to-end.

Kept intentionally small: 2 processes × 2 virtual CPU devices, a few steps.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import local_cluster  # noqa: E402


def _run_cluster(cmd, n=2, d=2, timeout=600):
    """Run via the launcher in-process but capture child output."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "local_cluster.py"),
         "-n", str(n), "-d", str(d), "--", *cmd],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env={k: v for k, v in os.environ.items()
             if k not in ("XLA_FLAGS", "JAX_PLATFORMS")},
    )
    return proc


@pytest.mark.slow
def test_cli_cyclic_two_processes():
    proc = _run_cluster([
        sys.executable, "-m", "draco_tpu.cli",
        "--approach", "cyclic", "--network", "LeNet",
        "--dataset", "synthetic-mnist",
        "--num-workers", "4", "--worker-fail", "0",
        "--batch-size", "4", "--max-steps", "6",
        "--redundancy", "shared",
        "--eval-freq", "0", "--train-dir", "", "--log-every", "1",
        "--cpu-mesh", "2",
    ])
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    # only process 0 emits metrics; parse its per-step losses
    losses = [float(m) for m in re.findall(r"loss: ([0-9.]+)", proc.stdout)]
    assert len(losses) >= 6
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_cli_baseline_krum_two_processes():
    proc = _run_cluster([
        sys.executable, "-m", "draco_tpu.cli",
        "--approach", "baseline", "--mode", "krum",
        "--network", "FC", "--dataset", "synthetic-mnist",
        "--num-workers", "4", "--worker-fail", "1", "--err-mode", "constant",
        "--batch-size", "4", "--max-steps", "6",
        "--eval-freq", "0", "--train-dir", "", "--log-every", "1",
        "--cpu-mesh", "2",
    ])
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    losses = [float(m) for m in re.findall(r"loss: ([0-9.]+)", proc.stdout)]
    assert len(losses) >= 6
    assert losses[-1] < losses[0]


def test_launcher_propagates_failure():
    proc = _run_cluster([sys.executable, "-c", "import sys; sys.exit(3)"],
                        n=2, d=1, timeout=120)
    assert proc.returncode == 3


def test_free_port_is_usable():
    port = local_cluster._free_port()
    assert 0 < port < 65536


def test_launcher_survives_large_child_output():
    """Regression: children used to write to pipes drained sequentially in pid
    order; a child emitting more than the OS pipe buffer (~64KB) deadlocked
    the launcher. Files have no backpressure."""
    proc = _run_cluster(
        [sys.executable, "-c",
         "import sys\n"
         "for _ in range(4000): print('x' * 120)\n"
         "sys.exit(0)"],
        n=2, d=1, timeout=120,
    )
    assert proc.returncode == 0
    assert proc.stdout.count("x" * 120) >= 8000


@pytest.mark.slow
def test_cli_checkpoint_resume_two_processes(tmp_path):
    """Multi-host save -> restore roundtrip: save() writes global jax.Arrays
    collectively; restore must rebuild them with sharding info (the abstract
    tree carries each leaf's sharding)."""
    train_dir = str(tmp_path / "ckpt_run")
    common = [
        sys.executable, "-m", "draco_tpu.cli",
        "--approach", "baseline", "--network", "FC",
        "--dataset", "synthetic-mnist",
        "--num-workers", "4", "--batch-size", "4",
        "--eval-freq", "4", "--train-dir", train_dir,
        "--log-every", "1", "--cpu-mesh", "2",
    ]
    proc = _run_cluster(common + ["--max-steps", "4"])
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    proc = _run_cluster(common + ["--max-steps", "8", "--checkpoint-step", "4"])
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    steps = [int(m) for m in re.findall(r"Step: (\d+)", proc.stdout)]
    assert steps and min(steps) >= 5  # resumed past the checkpoint
