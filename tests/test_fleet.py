"""Fleet observatory (draco_tpu/obs/fleet.py, ISSUE 19).

Registry layer: fold_run tolerates every partial-artifact state a killed
or half-synced run leaves behind (torn incidents tail, missing metrics,
pre-run_id status files, unknown future schemas) with a visible note,
never a traceback; a crashed run folds as an SLO violation, not a parse
error; a resumed run — incident seq reset inside one dir, or the same
run_id across dirs — folds as ONE run. SLO layer: the declarative
registry mirrors obs/incidents (enumerable table, '<slo>.<key>=<float>'
threshold overrides rejected loudly on unknown names), each SLO returns
the typed error-budget verdict, and the burn-window fold separates a
spike from a slow leak. Roll-up layer: a worker accused in 3 of 4 runs
outranks a one-run spike, and compute-to-target folds worker-steps.
Run identity: status.json schema 5 carries a run_id that survives a
resume into the same train_dir, and incident events carry wall-clock
``ts`` without breaking the replay diff (tools/incident_report.py).

Everything here is synthesized + jax-free — the same artifacts-only
contract tools/fleet_report.py runs under on a bare checkout.
"""

import json
import os

import pytest

from draco_tpu.obs import fleet, replay
from draco_tpu.obs.heartbeat import (
    STATUS_SCHEMA,
    RunHeartbeat,
    check_status_schema,
)


def write_status(d, **over):
    payload = {"schema": STATUS_SCHEMA, "state": "running",
               "run_id": "rid-" + os.path.basename(str(d)),
               "step": 9, "total_steps": 10, "updated_at": 100.0}
    payload.update(over)
    payload = {k: v for k, v in payload.items() if v is not None}
    with open(os.path.join(str(d), "status.json"), "w") as fh:
        json.dump(payload, fh)
    return payload


def write_jsonl(d, name, rows, torn_tail=""):
    with open(os.path.join(str(d), name), "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
        if torn_tail:
            fh.write(torn_tail)


def train_records(n=10, t0=0.0, dt=1.0, adv_steps=(), loss0=2.0):
    recs = []
    for step in range(n):
        adv = step in adv_steps
        recs.append({
            "step": step, "loss": loss0 - 0.1 * step,
            "time": t0 + dt * step,
            "det_tp": 1 if adv else 0, "det_adv": 1 if adv else 0,
            "located_errors": 1 if adv else 0,
            "decode_residual": 1e-7, "decode_residual_bound": 1e-3,
        })
    return recs


def test_fold_run_basic(tmp_path):
    write_status(tmp_path, state="done", job_name="cellA", step=9,
                 loss=1.1)
    write_jsonl(tmp_path, "metrics.jsonl", train_records(10, adv_steps=(3,)))
    write_jsonl(tmp_path, "incidents.jsonl", [
        {"v": 1, "event": "onset", "seq": 0, "ts": 3.0,
         "type": "trust", "onset_step": 3},
        {"v": 1, "event": "offset", "seq": 1, "ts": 4.0,
         "type": "trust", "onset_step": 3},
    ])
    run = fleet.fold_run(str(tmp_path))
    assert run.run_id and run.job_name == "cellA"
    assert run.state == "done" and run.schema == STATUS_SCHEMA
    assert run.records == 10 and run.steps_observed == 10
    assert run.detection == {"precision": 1.0, "recall": 1.0,
                             "flagged_total": 1.0, "adv_total": 1.0}
    assert len(run.events) == 2 and not run.remediations
    assert not run.resumed and run.attempts == 1 and not run.notes
    # the fold also resolves a direct metrics.jsonl path to the run dir
    via_file = fleet.fold_run(os.path.join(str(tmp_path), "metrics.jsonl"))
    assert via_file.run_id == run.run_id


def test_fold_tolerates_torn_incidents_tail(tmp_path):
    """A run killed mid-write leaves half a JSON line — the registry
    folds the intact prefix and never raises (obs/replay rules)."""
    write_status(tmp_path)
    write_jsonl(tmp_path, "metrics.jsonl", train_records(5))
    write_jsonl(tmp_path, "incidents.jsonl", [
        {"v": 1, "event": "onset", "seq": 0, "ts": 1.0, "type": "trust",
         "onset_step": 2}],
        torn_tail='{"v": 1, "event": "offs')
    run = fleet.fold_run(str(tmp_path))
    assert len(run.events) == 1
    results = fleet.evaluate_run(run)
    assert results["step_availability"]["verdict"] == "ok"


def test_fold_missing_metrics_degrades_with_note(tmp_path):
    """status.json alone still folds: availability falls back to the
    status step counter, record-tail SLOs report not_evaluated."""
    write_status(tmp_path, step=50)
    run = fleet.fold_run(str(tmp_path))
    assert "metrics.jsonl missing or empty" in run.notes
    assert run.steps_observed == 50
    results = fleet.evaluate_run(run)
    assert results["step_availability"]["verdict"] == "ok"
    assert results["decode_health"]["verdict"] == "not_evaluated"
    assert results["throughput"]["verdict"] == "not_evaluated"


def test_fold_pre_run_id_status(tmp_path):
    """A schema-4 (pre-fleet) status.json folds with run_id None and a
    visible note — consumers must tolerate fleets of older runs."""
    write_status(tmp_path, schema=4, run_id=None)
    write_jsonl(tmp_path, "metrics.jsonl", train_records(5))
    run = fleet.fold_run(str(tmp_path))
    assert run.run_id is None and run.schema == 4
    assert any("pre-run_id" in n for n in run.notes)


def test_mixed_schema_fleet_never_crashes(tmp_path):
    """One current run, one pre-run_id run, one UNKNOWN future schema:
    the registry folds all three; the unknown one degrades to
    metrics-only with the rejection note (check_status_schema wording),
    and the fleet fold still produces a report."""
    cur, old, future = (tmp_path / n for n in ("cur", "old", "future"))
    for d in (cur, old, future):
        d.mkdir()
        write_jsonl(d, "metrics.jsonl", train_records(5))
    write_status(cur)
    write_status(old, schema=4, run_id=None)
    write_status(future, schema=99)
    reg = fleet.RunRegistry([str(cur), str(old), str(future)])
    assert len(reg.summaries) == 3
    by_dir = {os.path.basename(s.run_dir): s for s in reg.summaries}
    assert by_dir["cur"].run_id
    assert any("rejected" in n for n in by_dir["future"].notes)
    assert by_dir["future"].state is None  # degraded, not trusted
    report = fleet.fleet_fold(reg.summaries)
    assert len(report["runs"]) == 3
    assert report["status_schema"] == STATUS_SCHEMA


def test_crashed_run_is_slo_violation_not_parse_error(tmp_path):
    write_status(tmp_path, state="crashed", cause="boom at step 7",
                 step=7)
    write_jsonl(tmp_path, "metrics.jsonl", train_records(7))
    run = fleet.fold_run(str(tmp_path))
    assert run.state == "crashed" and not run.notes
    res = fleet.evaluate_run(run)["step_availability"]
    assert res["verdict"] == "violated" and res["crashed"]
    assert "boom at step 7" in res["detail"]
    report = fleet.fleet_fold([run])
    assert not report["all_ok"]
    assert report["slo_compliance"]["step_availability"]["violated"] == 1


def test_incident_seq_reset_folds_as_one_resumed_run(tmp_path):
    write_status(tmp_path)
    write_jsonl(tmp_path, "metrics.jsonl", train_records(5))
    write_jsonl(tmp_path, "incidents.jsonl", [
        {"v": 1, "event": "onset", "seq": 0, "ts": 1.0},
        {"v": 1, "event": "offset", "seq": 1, "ts": 2.0},
        {"v": 1, "event": "onset", "seq": 0, "ts": 9.0},  # resume
    ])
    run = fleet.fold_run(str(tmp_path))
    assert run.resumed and run.attempts == 2
    assert len(run.events) == 3


def test_registry_merges_attempts_sharing_run_id(tmp_path):
    """Two dirs carrying the same run_id are ONE run in every roll-up;
    the primary is the freshest attempt (updated_at, then records)."""
    a, b, other = (tmp_path / n for n in ("a", "b", "other"))
    for d in (a, b, other):
        d.mkdir()
    write_status(a, run_id="shared", updated_at=50.0, step=4)
    write_jsonl(a, "metrics.jsonl", train_records(4))
    write_status(b, run_id="shared", updated_at=90.0, step=9)
    write_jsonl(b, "metrics.jsonl", train_records(9))
    write_status(other, run_id="solo")
    write_jsonl(other, "metrics.jsonl", train_records(5))
    reg = fleet.RunRegistry(fleet.RunRegistry.discover(str(tmp_path)))
    assert len(reg.summaries) == 2
    merged = next(s for s in reg.summaries if s.run_id == "shared")
    assert merged.resumed and merged.attempts == 2
    assert merged.run_dir.endswith("b")  # freshest attempt won
    assert any("2 dirs" in n for n in merged.notes)
    solo = next(s for s in reg.summaries if s.run_id == "solo")
    assert not solo.resumed


def test_run_id_survives_resume_and_passes_schema(tmp_path):
    """Satellite: the heartbeat mints a run_id once per train_dir, a
    resume into the same dir re-reads it, and the beat payload passes
    the central schema contract with the new blocks present."""
    hb = RunHeartbeat(str(tmp_path), job_name="jobX")
    payload = hb.beat(step=1)
    assert payload["schema"] == STATUS_SCHEMA == 5
    assert payload["run_id"] == hb.run_id and payload["job_name"] == "jobX"
    check_status_schema(payload, tool="tests/test_fleet.py")
    hb.terminal("preempted")
    hb2 = RunHeartbeat(str(tmp_path))  # resume, no job_name this time
    assert hb2.run_id == hb.run_id
    p2 = hb2.beat(step=2)
    assert p2["run_id"] == hb.run_id and "job_name" not in p2
    # a fresh dir mints a DIFFERENT id
    assert RunHeartbeat(str(tmp_path / "new")).run_id != hb.run_id


def test_slo_registry_table_and_threshold_overrides():
    names = [s["name"] for s in fleet.slo_table()]
    assert names == ["step_availability", "detection_quality",
                     "decode_health", "throughput", "incident_mttr",
                     "wire_bytes"]
    assert all(s["doc"] and s["thresholds"] for s in fleet.slo_table())
    ov = fleet.parse_slo_thresholds(
        "throughput.floor_frac=0.25, incident_mttr.mttr_max_s=60")
    assert ov == {"throughput.floor_frac": 0.25,
                  "incident_mttr.mttr_max_s": 60.0}
    slos = fleet.make_slos(ov)
    assert slos["throughput"].th["floor_frac"] == 0.25
    assert slos["incident_mttr"].th["mttr_max_s"] == 60.0
    # defaults untouched elsewhere
    assert slos["throughput"].th["budget_frac"] == 0.1
    with pytest.raises(ValueError, match="unknown SLO"):
        fleet.parse_slo_thresholds("nope.x=1")
    with pytest.raises(ValueError, match="no threshold"):
        fleet.parse_slo_thresholds("throughput.nope=1")
    with pytest.raises(ValueError, match="<float>"):
        fleet.parse_slo_thresholds("throughput.floor_frac=abc")


def test_detection_quality_slo_verdicts(tmp_path):
    run = fleet.RunSummary(run_dir=str(tmp_path))
    slo = fleet.make_slos()["detection_quality"]
    # baseline route: no columns -> never evaluated, never violated
    res = slo.evaluate(run)
    assert res["verdict"] == "not_evaluated" and res["ok"] is None
    run.detection = {"precision": 1.0, "recall": 1.0,
                     "flagged_total": 8.0, "adv_total": 8.0}
    res = slo.evaluate(run)
    assert res["verdict"] == "ok" and res["burned"] == 0.0
    # one false accusation: flagged 9, tp 8 -> burn 1, zero budget
    run.detection = {"precision": 8.0 / 9.0, "recall": 1.0,
                     "flagged_total": 9.0, "adv_total": 8.0}
    res = slo.evaluate(run)
    assert res["verdict"] == "violated" and res["burned"] == \
        pytest.approx(1.0)
    assert res["burn_frac"] is None  # zero budget burned -> JSON-clean


def test_burn_windows_separates_spike_from_leak():
    spike = [(10, 1.0), (11, 1.0), (12, 1.0)]
    leak = [(10, 1.0), (40, 1.0), (70, 1.0)]
    w = {"fast": 8, "slow": 100}
    ws, wl = fleet.burn_windows(spike, w), fleet.burn_windows(leak, w)
    assert ws["fast"]["max_burn"] == 3.0 and ws["fast"]["at_step"] == 12
    assert wl["fast"]["max_burn"] == 1.0
    assert ws["slow"]["max_burn"] == wl["slow"]["max_burn"] == 3.0
    assert fleet.burn_windows([], w)["fast"]["max_burn"] == 0.0


def test_wire_bytes_slo_self_consistency(tmp_path):
    wire = {"wire_dtype": "bf16",
            "bytes_per_worker": {"f32": 400, "bf16": 200, "int8": 100},
            "physical_bytes_per_worker": 200,
            "physical_bytes_per_step": 1600, "num_workers": 8,
            "segments": {"count": 2,
                         "physical_bytes_per_worker": [120, 80]}}
    run = fleet.RunSummary(run_dir=str(tmp_path), wire=wire)
    slo = fleet.make_slos()["wire_bytes"]
    assert slo.evaluate(run)["verdict"] == "ok"
    broken = dict(wire, physical_bytes_per_step=999)
    run.wire = broken
    res = slo.evaluate(run)
    assert res["verdict"] == "violated" and "per_worker x 8" in \
        res["detail"]
    run.wire = dict(wire, segments={"count": 2,
                                    "physical_bytes_per_worker": [120, 99]})
    assert "segment bytes sum" in slo.evaluate(run)["detail"]
    run.wire = None
    assert slo.evaluate(run)["verdict"] == "not_evaluated"


def test_incident_mttr_slo_join(tmp_path):
    run = fleet.RunSummary(run_dir=str(tmp_path))
    run.record_times = {5: 100.0}
    onset = {"event": "onset", "type": "trust", "onset_step": 5,
             "ts": 101.0}
    rem = {"event": "remediation", "ts": 104.0,
           "trigger": {"type": "trust", "onset_step": 5}}
    run.events = [onset, rem]
    run.remediations = [rem]
    slo = fleet.make_slos()["incident_mttr"]
    res = slo.evaluate(run)
    assert res["verdict"] == "ok"
    assert res["mttr_s"] == pytest.approx(3.0)
    assert res["mttd_s"] == pytest.approx(1.0)
    assert res["attributed"] == 1 and res["unattributed"] == 0
    # a remediation pointing at an unseen onset is unattributed -> burn
    run.remediations = [{"event": "remediation", "ts": 104.0,
                         "trigger": {"type": "trust", "onset_step": 99}}]
    res = slo.evaluate(run)
    assert res["verdict"] == "violated" and res["unattributed"] == 1
    # no remediations at all: nothing to measure, not a violation
    run.remediations = []
    assert slo.evaluate(run)["verdict"] == "not_evaluated"


def test_worker_rollup_cross_run_ranking(tmp_path):
    """A worker accused in 3 of 4 runs outranks a single-run spike with
    more raw accusations."""
    def summary(i, rows):
        s = fleet.RunSummary(run_dir=str(tmp_path / str(i)))
        s.worker_rows = rows
        return s

    def row(w, accused, trust=1.0):
        return {"worker": w, "accused": accused, "trust": trust}

    runs = [summary(i, [row(2, 2, 0.4), row(5, 0), row(0, 0)])
            for i in range(3)]
    runs.append(summary(3, [row(2, 0), row(5, 50, 0.1), row(0, 0)]))
    top = fleet.worker_rollup(runs)
    assert [w["worker"] for w in top[:2]] == [2, 5]
    w2 = top[0]
    assert w2["runs_accusing"] == 3 and w2["runs_seen"] == 4
    assert w2["accused_total"] == 6 and w2["min_trust"] == \
        pytest.approx(0.4)
    # degraded path: no records to replay, status forensics block only
    deg = fleet.RunSummary(run_dir=str(tmp_path / "deg"))
    deg.forensics = {"trust": [1.0, 1.0, 0.2],
                     "top_suspects": [{"worker": 2, "accused": 7}]}
    top = fleet.worker_rollup([deg])
    assert top[0]["worker"] == 2 and top[0]["accused_total"] == 7


def test_compute_rollup_to_target(tmp_path):
    s = fleet.RunSummary(run_dir=str(tmp_path))
    s.num_workers = 8
    s.first_step, s.last_step = 0, 9
    s.losses = [(i, 2.0 - 0.2 * i) for i in range(10)]
    roll = fleet.compute_rollup([s], target_loss=1.0)
    assert roll["total_worker_steps"] == 80.0
    assert roll["runs_reaching_target"] == 1
    # loss 1.0 first reached at step 5 -> 6 steps * 8 workers
    assert roll["worker_steps_to_target_total"] == 48.0
    assert fleet.compute_rollup([s])["runs_reaching_target"] is None


def test_incident_events_carry_ts_and_replay_diffs_clean(tmp_path):
    """Satellite: every incidents.jsonl line now carries wall-clock
    ``ts`` (MTTR joins need it), incident_report carries it through,
    and the replayed-vs-committed ledger diff stays clean — ts is
    attempt-local and excluded from episode identity."""
    from draco_tpu.obs import incidents as incidents_mod
    from tools import incident_report

    recs = []
    for step in range(1, 11):
        accused = 0b0100 if step <= 6 else 0
        recs.append({"step": step, "loss": 1.0, "time": float(step),
                     "wmask_accused0": accused,
                     "wmask_present0": 0b1111, "wmask_adv0": accused})
    write_jsonl(tmp_path, "metrics.jsonl", recs)
    engine = incidents_mod.IncidentEngine(
        num_workers=4,
        out_path=os.path.join(str(tmp_path), "incidents.jsonl"))
    for r in recs:
        engine.observe(r)
    engine.finalize()
    lines = list(replay.iter_jsonl(
        os.path.join(str(tmp_path), "incidents.jsonl")))
    assert lines and all(
        isinstance(ev.get("ts"), float) for ev in lines)
    assert incident_report.main([str(tmp_path),
                                 "--num-workers", "4"]) == 0
    rep = json.load(open(os.path.join(str(tmp_path),
                                      "incidents_report.json")))
    assert rep["diff"]["match"]
    assert rep["ledger"] and all("ts" in ep for ep in rep["ledger"])
    # and the fleet MTTD join sees the stamps
    run = fleet.fold_run(str(tmp_path))
    res = fleet.evaluate_run(run)["incident_mttr"]
    assert res["mttd_s"] is not None and res["mttd_s"] >= 0.0


def test_fleet_report_tool_bare_and_populated(tmp_path, capsys):
    """tools/fleet_report.py: empty root prints a note and exits 0;
    a populated root writes fleet.json; --strict exits 1 when a run
    violates an SLO; threshold overrides reach the verdicts."""
    from tools import fleet_report

    empty = tmp_path / "empty"
    empty.mkdir()
    assert fleet_report.main(["--runs-root", str(empty)]) == 0
    assert "no run directories found" in capsys.readouterr().out
    good, bad = tmp_path / "good", tmp_path / "bad"
    good.mkdir(), bad.mkdir()
    write_status(good, state="done", job_name="good")
    write_jsonl(good, "metrics.jsonl", train_records(10))
    write_status(bad, state="crashed", cause="oom", step=3)
    write_jsonl(bad, "metrics.jsonl", train_records(3))
    out = tmp_path / "fleet.json"
    rc = fleet_report.main(["--runs-root", str(tmp_path),
                            "--json", str(out), "--strict"])
    assert rc == 1  # crashed run violates step_availability in CI mode
    text = capsys.readouterr().out
    assert "VIOL" in text and "terminal state 'crashed'" in text
    payload = json.loads(out.read_text())
    assert payload["fleet_schema"] == fleet.FLEET_SCHEMA
    assert not payload["all_ok"] and len(payload["runs"]) == 2
    states = {r["run"]: r["state"] for r in payload["runs"]}
    assert states["good"] == "done"
    # an override makes the detection floor lenient fleet-wide
    assert fleet_report.main(
        [str(good), "--strict", "--slo-thresholds",
         "detection_quality.precision_floor=0.5"]) == 0


def test_fleet_is_importable_without_jax():
    """The obs contract: the registry/SLO fold must run on a bare
    checkout (laptop, scp'd artifacts). Re-importing in a subprocess
    with jax poisoned proves no transitive jax dependency."""
    import subprocess
    import sys

    code = (
        "import sys; sys.modules['jax'] = None\n"
        "from draco_tpu.obs import fleet\n"
        "assert len(fleet.slo_table()) == 6\n"
        "print('ok')\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", code], cwd=repo,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "ok"
