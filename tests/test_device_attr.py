"""Device-time attribution (obs/device_attr.py + obs/profiling.py, ISSUE 9):
scope-map parsing from optimized HLO, per-thread self-time accounting, the
phase ledger's sums-to-window contract, the collective cross-check (proven
live on a seeded extra-all-gather mismatch), the merged host+device
timeline, the heartbeat ``device`` status block, and a core-marked live
capture smoke on the CPU mesh.

The committed fixture (tests/data/device_profile_fixture/) is a synthetic
jax.profiler capture in the XLA:CPU fallback trace shape this container
produces (PERF.md §12): hlo_module/hlo_op args on each complete event, the
named-scope path only in the runner-dumped scope map, a nested ``call``
wrapper on one thread, a GSPMD collective, and an op absent from the scope
map entirely (the honest ``unattributed`` row).
"""

import json
import os

import pytest

from draco_tpu.obs import device_attr as da

FIXTURE = os.path.join(os.path.dirname(__file__), "data",
                       "device_profile_fixture")

# hand-computable ledger of the fixture (see the generator values):
#   draco_comp  = dot.1 400
#   draco_decode= sine.2 280 + all-reduce.3 100 = 380
#   draco_encode= fusion.7 250
#   other       = call self (300-280=20) + all-gather.9 150 = 170
#   unattributed= copy.5 50
FIX_EXPECT = {"draco_comp": 400.0, "draco_encode": 250.0,
              "draco_decode": 380.0, "draco_update": 0.0,
              "other": 170.0, "unattributed": 50.0}


def _fixture_events():
    with open(os.path.join(FIXTURE, "plugins", "profile", "0001",
                           "fixture.trace.json")) as fh:
        return json.load(fh)["traceEvents"]


def _fixture_scope():
    with open(os.path.join(FIXTURE, "device_scope_map.json")) as fh:
        return json.load(fh)["programs"][0]


# --------------------------------------------------------------------------
# scope map parsing
# --------------------------------------------------------------------------

HLO_TEXT = """HloModule jit_step_body, entry_computation_layout={()->f32[]}

%region_0.5 (Arg_0.6: f32[], Arg_1.7: f32[]) -> f32[] {
  ROOT %add.8 = f32[] add(f32[] %a, f32[] %b), metadata={op_name="jit(f)/jit(main)/draco_decode/reduce_sum"}
}

ENTRY %main {
  %dot.3 = f32[256,256]{1,0} dot(f32[256,256]{1,0} %x, f32[256,256]{1,0} %x), metadata={op_name="jit(f)/jit(main)/draco_comp/dot_general"}
  %all-reduce.2 = f32[64]{0} all-reduce(f32[64]{0} %g), replica_groups={{0,1}}, metadata={op_name="jit(f)/draco_comp/psum"}
  %all-gather = f32[8,64]{1,0} all-gather(f32[64]{0} %g), dimensions={0}, metadata={op_name="jit(f)/draco_encode/dot_general"}
  %collective-permute.9 = f32[4]{0} collective-permute(f32[4]{0} %t), metadata={op_name="jit(f)/draco_comp/ppermute"}
  ROOT %copy.1 = f32[] copy(f32[] %r)
}
"""


@pytest.mark.core
def test_scope_map_from_hlo():
    sm = da.scope_map_from_hlo(HLO_TEXT)
    assert sm["module"] == "jit_step_body"
    assert sm["ops"]["dot.3"] == "draco_comp"
    assert sm["ops"]["add.8"] == "draco_decode"
    assert sm["ops"]["copy.1"] == ""  # no metadata: mapped, phaseless
    colls = sm["collectives"]
    # explicit iff the op_name path ends in the jax collective primitive
    assert colls["all-reduce.2"] == {
        "kind": "all_reduce", "bytes": 256, "explicit": True,
        "phase": "draco_comp"}
    assert colls["all-gather"]["explicit"] is False  # GSPMD-inserted
    assert colls["all-gather"]["kind"] == "all_gather"
    assert colls["all-gather"]["bytes"] == 8 * 64 * 4
    assert colls["collective-permute.9"]["explicit"] is True


@pytest.mark.core
def test_self_times_nesting_and_threads():
    """A wrapper event pays out its nested children's time on the SAME
    thread; partial overlaps on different threads stay independent."""
    events = [
        {"ph": "X", "tid": 1, "ts": 0.0, "dur": 100.0, "name": "outer"},
        {"ph": "X", "tid": 1, "ts": 10.0, "dur": 30.0, "name": "inner_a"},
        {"ph": "X", "tid": 1, "ts": 50.0, "dur": 40.0, "name": "inner_b"},
        {"ph": "X", "tid": 2, "ts": 20.0, "dur": 60.0, "name": "other_tid"},
    ]
    got = {ev["name"]: dur for ev, dur in da.self_times(events)}
    assert got == {"outer": 30.0, "inner_a": 30.0, "inner_b": 40.0,
                   "other_tid": 60.0}


# --------------------------------------------------------------------------
# fixture: phase ledger sums, collective ledger, cross-check
# --------------------------------------------------------------------------

@pytest.mark.core
def test_fixture_attribution_sums_to_window():
    row = da.attribute_phases(_fixture_events(), _fixture_scope())
    assert row["module"] == "jit_many_body"
    got = {k: v["time_us"] for k, v in row["phases"].items()}
    assert got == FIX_EXPECT
    # the provably-sums contract: phase rows + explicit residual rows ==
    # total device self-time, nothing absorbed, nothing double-counted
    assert sum(got.values()) == pytest.approx(row["total_device_us"])
    assert row["total_device_us"] == pytest.approx(1250.0)
    # wall is the envelope of the module's events (1000 .. 1950), and the
    # other module's event did not leak in
    assert row["wall_us"] == pytest.approx(950.0)
    assert row["matched_events"] == 7  # jit_other's event stayed out
    fr = {k: v["frac"] for k, v in row["phases"].items()}
    assert sum(fr.values()) == pytest.approx(1.0)
    assert fr["draco_decode"] == pytest.approx(380.0 / 1250.0)
    # a draco_* token OUTSIDE the ledger rows (a repo file path in a
    # python-tracer frame name, or a future named scope) lands in the
    # unattributed residual instead of crashing the fold
    stray = [{"ph": "X", "name": "$/repo/draco_tpu/loop.py:28 _run",
              "ts": 100.0, "dur": 10.0, "tid": 9}]
    srow = da.attribute_phases(stray, _fixture_scope())
    assert srow["phases"]["unattributed"]["time_us"] == pytest.approx(10.0)


@pytest.mark.core
def test_fixture_collective_ledger_and_cross_check():
    led = da.collective_ledger(_fixture_events(), _fixture_scope())
    assert led["explicit"]["all_reduce"] == {
        "instructions": 1, "events": 1, "bytes": 1024, "time_us": 100.0}
    assert led["gspmd"]["all_gather"]["instructions"] == 1
    assert led["gspmd"]["all_gather"]["bytes"] == 2048
    # reconciles against the linted manifest (missing kinds default 0)
    ok = da.cross_check(led, {"all_reduce": 1}, "fixture")
    assert ok["ok"] and ok["observed"]["all_reduce"] == 1
    # TPU scope-in-name shape: an untagged event (no hlo_module) whose
    # name carries the scope path uses the SAME selection as the phase
    # ledger — the collective is counted, not dropped into an empty
    # ledger that would then hard-fail the manifest cross-check
    tpu = [{"ph": "X", "name": "jit(f)/draco_decode/psum",
            "args": {"hlo_op": "all-reduce.3"},
            "ts": 50.0, "dur": 20.0, "tid": 3}]
    tled = da.collective_ledger(tpu, _fixture_scope())
    assert tled["explicit"]["all_reduce"]["instructions"] == 1
    assert tled["explicit"]["all_reduce"]["time_us"] == pytest.approx(20.0)
    # manifest-skipped programs check nothing
    assert da.cross_check(led, None, "fixture")["skipped"]


@pytest.mark.core
def test_cross_check_trips_on_seeded_extra_all_gather():
    """The negative control (PR 3 controls.py pattern): an extra explicit
    all-gather appearing in the runtime trace that the static Manifest does
    not pin must raise, naming the drifted kind both ways."""
    scope = _fixture_scope()
    seeded = json.loads(json.dumps(scope))
    # the GSPMD all-gather drifts to explicit — i.e. the executed program
    # grew a shard_map all_gather the manifest never audited
    seeded["collectives"]["all-gather.9"]["explicit"] = True
    led = da.collective_ledger(_fixture_events(), seeded)
    with pytest.raises(da.CollectiveMismatchError) as ei:
        da.cross_check(led, {"all_reduce": 1}, "seeded_control")
    msg = str(ei.value)
    assert "all_gather" in msg and "seeded_control" in msg
    assert "'manifest': 0" in msg and "'trace': 1" in msg
    # the opposite direction (manifest expects more than the trace ran)
    # trips the same hard error
    led_ok = da.collective_ledger(_fixture_events(), scope)
    with pytest.raises(da.CollectiveMismatchError):
        da.cross_check(led_ok, {"all_reduce": 1, "collective_permute": 2},
                       "seeded_control")


# --------------------------------------------------------------------------
# fold_capture + merged timeline + heartbeat device block
# --------------------------------------------------------------------------

@pytest.mark.core
def test_fold_capture_fixture_end_to_end():
    fold = da.fold_capture(FIXTURE)
    assert fold is not None and fold["cell"] == "fixture"
    (prog,) = fold["programs"]
    assert prog["phases"]["draco_comp"]["time_us"] == 400.0
    assert prog["lint_row"] == "fixture_row"
    assert fold["anchor"]["steps_profiled"] == 5
    block = da.device_status_block(fold)
    assert block["decode_share"] == pytest.approx(380.0 / 1250.0, abs=1e-4)
    assert block["attributed_frac"] == pytest.approx(1 - 50.0 / 1250.0,
                                                     abs=1e-4)
    assert block["profiled_steps"] == 5
    # the fixture's scope map stamps flops_per_step, so the achieved rate
    # is computable; the CPU fallback has no honest peak so the fraction
    # stays None (PERF.md §12)
    assert block["achieved_flops_per_s"] == pytest.approx(
        1.0e6 * 5 / (1250.0 / 1e6))
    assert block["achieved_flops_frac"] is None


@pytest.mark.core
def test_fold_capture_missing_and_torn(tmp_path):
    assert da.fold_capture(str(tmp_path)) is None  # no capture: tolerated
    d = tmp_path / "plugins" / "profile" / "0001"
    d.mkdir(parents=True)
    (d / "torn.trace.json").write_text('{"traceEvents": [{"ph": "X"')
    assert da.fold_capture(str(tmp_path)) is None  # torn: tolerated
    with pytest.raises(ValueError):
        da.fold_capture(str(tmp_path), strict=True)  # tools demand it


@pytest.mark.core
def test_merge_timeline_anchored_shared_clock():
    events = _fixture_events()
    with open(os.path.join(FIXTURE, "trace.json")) as fh:
        host = json.load(fh)["traceEvents"]
    with open(os.path.join(FIXTURE, "host_anchor.json")) as fh:
        anchor = json.load(fh)
    merged = da.merge_timeline(host, events, _fixture_scope(), anchor)
    mt = merged["mergedTimeline"]
    assert mt["anchored"] is True
    assert mt["anchor_kind"] == "start_trace"
    # device origin = END of the python tracer's start_trace frame (900);
    # the anchor pins that instant at host-tracer ts 5000
    assert mt["device_offset_us"] == pytest.approx(5000.0 - 900.0)
    by_name = {}
    for ev in merged["traceEvents"]:
        by_name.setdefault(ev.get("name"), []).append(ev)
    # host lanes unchanged, device lanes shifted + namespaced + phased
    assert by_name["dispatch"][0]["ts"] == 4000.0
    dot = [e for e in by_name["dot.1"] if e.get("cat") == "device"][0]
    assert dot["ts"] == pytest.approx(1000.0 + 4100.0)
    assert dot["pid"] == 701 + da.DEVICE_PID_BASE
    assert dot["args"]["phase"] == "draco_comp"
    # device process metadata renamed so Perfetto shows both sides apart
    names = [e for e in merged["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    assert any(e["args"]["name"].startswith("device: ") for e in names)
    assert mt["droppedDeviceEvents"] == 0
    # quiet capture (python tracer off — the production default): no
    # start_trace event, so the DRAIN stamp anchors the capture's LAST
    # event END (all-reduce.3 at 1950) to the host instant the devices
    # went idle, instead of over-shifting early via the earliest event
    quiet = [e for e in events if "start_trace" not in e.get("name", "")]
    qm = da.merge_timeline([], quiet, _fixture_scope(), anchor)
    qmt = qm["mergedTimeline"]
    assert qmt["anchored"] is True and qmt["anchor_kind"] == "drain"
    assert qmt["device_offset_us"] == pytest.approx(1005000.0 - 1950.0)
    # unanchored merge (no host tracer ran): device lanes keep own origin
    un = da.merge_timeline([], events, _fixture_scope(), None)
    assert un["mergedTimeline"]["anchored"] is False
    assert un["mergedTimeline"]["anchor_kind"] is None


@pytest.mark.core
def test_merge_timeline_caps_device_events_loudly():
    events = [{"ph": "X", "pid": 1, "tid": 1, "ts": float(i),
               "dur": float(i % 7 + 1), "name": f"op.{i}"}
              for i in range(50)]
    merged = da.merge_timeline([], events, None, None, max_device_events=10)
    xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 10
    assert merged["mergedTimeline"]["droppedDeviceEvents"] == 40
    # longest events survive the cap (7 events of dur 7, then dur 6)
    assert min(e["dur"] for e in xs) == 6.0


@pytest.mark.core
def test_heartbeat_device_block(tmp_path):
    """RunHeartbeat.observe_device folds the capture into the ``device``
    status block on the next beat — consumers tolerate it missing, assert
    it when present (STATUS_SCHEMA stays 2; the block is additive)."""
    from draco_tpu.obs.heartbeat import STATUS_SCHEMA, RunHeartbeat

    hb = RunHeartbeat(str(tmp_path), num_workers=8)
    hb.observe({"step": 1, "loss": 1.0})
    payload = hb.beat(1, total_steps=4)
    assert "device" not in payload  # no capture observed yet
    hb.observe_device(FIXTURE)
    payload = hb.beat(2, total_steps=4)
    assert payload["schema"] == STATUS_SCHEMA
    dev = payload["device"]
    assert dev["decode_share"] == pytest.approx(0.304, abs=1e-3)
    assert dev["profile_dir"] == FIXTURE
    on_disk = json.loads((tmp_path / "status.json").read_text())
    assert on_disk["device"]["profiled_steps"] == 5
    # a dir with no capture folds nothing and never raises
    hb.observe_device(str(tmp_path))
    assert hb.beat(3)["device"]["decode_share"] == dev["decode_share"]


# --------------------------------------------------------------------------
# live capture smoke on the CPU mesh
# --------------------------------------------------------------------------

@pytest.mark.core
def test_live_capture_smoke_cpu_mesh(tmp_path):
    """The whole spine live on the 8-device CPU mesh: profiler_window
    captures a real jitted program with draco named scopes, the AOT scope
    map attributes its trace events, phases sum to the window, and the
    zero-collective cross-check agrees with an empty manifest."""
    import jax
    import jax.numpy as jnp

    from draco_tpu.obs.profiling import ANCHOR_FILE, profiler_window

    def f(x):
        with jax.named_scope("draco_comp"):
            y = x @ x
        with jax.named_scope("draco_decode"):
            z = jnp.tanh(y).sum()
        return z

    jf = jax.jit(f)
    x = jnp.ones((128, 128), jnp.float32)
    jf(x).block_until_ready()  # warm: the window must not pay the compile
    sm = da.scope_map_from_hlo(jf.lower(x).compile().as_text())
    assert any(v == "draco_comp" for v in sm["ops"].values())

    prof = str(tmp_path / "prof")
    win = profiler_window(prof, (1, 4))
    assert win.active is False
    for step in range(1, 6):
        win.maybe_start(step)
        r = jf(x)
        win.maybe_stop(step, r)
    assert win.profiled and not win.active
    assert os.path.exists(os.path.join(prof, ANCHOR_FILE))
    trace = da.find_capture(prof)
    assert trace is not None, "no capture landed"
    events, _ = da.load_trace(trace)
    row = da.attribute_phases(events, sm)
    assert row["total_device_us"] > 0
    assert row["phases"]["draco_comp"]["time_us"] > 0
    assert sum(v["time_us"] for v in row["phases"].values()) == \
        pytest.approx(row["total_device_us"])
    led = da.collective_ledger(events, sm)
    assert da.cross_check(led, {}, "smoke")["ok"]  # zero-collective program

    anchor = da.load_anchor(prof)
    assert anchor["steps_profiled"] == 3  # steps 1..3 under window (1, 4)
    merged = da.merge_timeline([], events, sm, anchor)
    assert any(e.get("cat") == "device" for e in merged["traceEvents"])


@pytest.mark.core
def test_trace_report_appends_device_table(capsys):
    """tools/trace_report.py (jax-free): a run dir holding a profiler
    capture grows the per-phase device table + comms ledger; a dir without
    one folds the host half only, no note, no error."""
    from tools import trace_report

    report = trace_report.make_report(
        os.path.join(FIXTURE, "trace.json"),
        metrics_path=None, profile_dir=FIXTURE)
    dev = report["device"]
    assert dev["programs"][0]["module"] == "jit_many_body"
    assert dev["programs"][0]["phases"]["draco_decode"]["time_us"] == 380.0
    assert dev["steps_profiled"] == 5
    trace_report.print_table(report)
    out = capsys.readouterr().out
    assert "device program jit_many_body" in out
    assert "draco_decode" in out
    assert "collective explicit/all_reduce: instructions=1" in out
    # no capture → no device section (the common case, tolerated silently)
    report2 = trace_report.make_report(os.path.join(FIXTURE, "trace.json"),
                                       metrics_path=None,
                                       profile_dir=os.path.dirname(FIXTURE))
    assert "device" not in report2


def test_profiler_window_stop_survives_poisoned_drain(tmp_path):
    """stop() runs from the loops' finally blocks: a poisoned carry (fault
    injection, device error) raising on the drain await must not mask the
    original exception or leak the profiler session — the capture is
    truncated, the session still closes."""
    from draco_tpu.obs.profiling import profiler_window

    class Poisoned:
        def block_until_ready(self):
            raise RuntimeError("device error surfaced at drain")

    win = profiler_window(str(tmp_path / "prof"), (1, 4))
    win.maybe_start(1)
    assert win.active
    win.stop(Poisoned())  # must not raise
    assert win.profiled and not win.active


def test_null_window_is_inert():
    from draco_tpu.obs.profiling import NULL_PROFILER_WINDOW, profiler_window

    win = profiler_window(None)
    assert win is NULL_PROFILER_WINDOW
    assert profiler_window("", (1, 2)) is NULL_PROFILER_WINDOW
    assert profiler_window("/tmp/x", enabled=False) is NULL_PROFILER_WINDOW
    win.maybe_start(1)
    win.maybe_stop(1)
    win.stop()
    assert win.active is False and win.profiled is False
