"""Approximate gradient code (coding/approx.py + coding/assignment.py):
assignment algebra, full-participation exactness, and the partial-recovery
residual-vs-bound certificate.

The family's contract (ISSUE 8, arXiv:2006.09638): at redundancy r ∈ [1, n]
the decode recovers the EXACT batch-gradient mean whenever every worker
arrives (v = 1 is feasible because the encode weights have unit column
sums), and under drops the optimal-decoding least squares bounds the error
by ‖u − 1‖₂ · ‖G‖_F / n — an in-graph scalar the health dict ships next to
the *measured* residual, so residual ≤ bound is checkable per decode.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu.coding import approx, assignment
from draco_tpu.config import TrainConfig


@pytest.fixture
def rng():
    return np.random.RandomState(17)


# --------------------------------------------------------------------------
# assignment algebra
# --------------------------------------------------------------------------

@pytest.mark.parametrize("n,r", [(8, 1.0), (8, 1.5), (8, 2.0), (9, 2.5),
                                 (6, 1.25), (9, 1.5)])
def test_pairwise_assignment_properties(n, r):
    a = assignment.pairwise_assignment(n, r)
    assert a.shape == (n, n) and set(np.unique(a)) <= {0.0, 1.0}
    loads = a.sum(axis=1)
    # per-worker loads are ⌊r⌋ or ⌊r⌋+1 and total compute rounds half-UP
    # to ⌊r·n + ½⌋ — never below the advertised redundancy (the (9, 1.5)
    # preset case: 14 batch-gradients, not banker's-rounded 13)
    assert set(loads) <= {math.floor(r), math.floor(r) + 1}
    assert loads.sum() == math.floor(r * n + 0.5)
    # every batch covered (encode_weights would raise otherwise) and
    # replication counts are balanced within one unit
    counts = a.sum(axis=0)
    assert counts.min() >= 1
    assert counts.max() - counts.min() <= 1
    # cyclic windows: worker i's support is consecutive mod n from i
    for i in range(n):
        ks = np.where(a[i])[0]
        want = (i + np.arange(len(ks))) % n
        assert sorted(ks) == sorted(want)


@pytest.mark.parametrize("n,c", [(8, 2), (9, 3), (8, 4), (6, 1)])
def test_clustered_assignment_properties(n, c):
    a = assignment.clustered_assignment(n, float(c))
    # workers partition into n/c clusters; cluster j computes batch group j
    for i in range(n):
        ks = np.where(a[i])[0]
        j = i // c
        assert sorted(ks) == list(range(j * c, (j + 1) * c))
    # every batch replicated exactly c times
    np.testing.assert_array_equal(a.sum(axis=0), np.full(n, c))


def test_assignment_rejects_bad_parameters():
    with pytest.raises(ValueError, match="redundancy"):
        assignment.build_assignment(8, 0.5, "pairwise")
    with pytest.raises(ValueError, match="redundancy"):
        assignment.build_assignment(8, 9.0, "pairwise")
    with pytest.raises(ValueError, match="integer"):
        assignment.build_assignment(8, 1.5, "clustered")
    with pytest.raises(ValueError, match="divide"):
        assignment.build_assignment(8, 3.0, "clustered")
    with pytest.raises(ValueError, match="unknown assignment scheme"):
        assignment.build_assignment(8, 2.0, "banana")
    with pytest.raises(ValueError, match="uncovered"):
        assignment.encode_weights(np.zeros((4, 4)))


def test_encode_weights_unit_column_sums(rng):
    for n, r, scheme in [(8, 1.5, "pairwise"), (8, 2.0, "clustered"),
                         (9, 2.5, "pairwise")]:
        a = assignment.build_assignment(n, r, scheme)
        w = assignment.encode_weights(a)
        # unit column sums: v = 1 decodes the exact sum at full
        # participation, for ANY r including the mixed ⌊r⌋/⌊r⌋+1 case
        np.testing.assert_allclose(w.sum(axis=0), np.ones(n), atol=1e-12)
        # support preserved: weights live exactly where the assignment does
        np.testing.assert_array_equal(w > 0, a > 0)


def test_build_approx_code_lane_constants():
    code = approx.build_approx_code(8, 1.5, "pairwise")
    assert code.max_load == 2
    # lane weights replay the dense weight matrix at batch_ids; padded
    # lanes carry weight 0 (inert recompute, never out-of-range)
    dense = np.zeros((8, 8), np.float32)
    for i in range(8):
        for j in range(code.max_load):
            dense[i, code.batch_ids[i, j]] += code.lane_weights[i, j]
    np.testing.assert_allclose(dense, code.weights, atol=1e-7)
    # ragged encode == shared encode on per-lane gathered gradients
    rng = np.random.RandomState(3)
    G = rng.randn(8, 33).astype(np.float32)
    shared = np.asarray(approx.encode_shared(code, jnp.asarray(G)))
    ragged = np.asarray(approx.encode(code, jnp.asarray(G[code.batch_ids])))
    np.testing.assert_allclose(ragged, shared, rtol=1e-6, atol=1e-6)


# --------------------------------------------------------------------------
# decode: exactness + the residual-vs-bound certificate
# --------------------------------------------------------------------------

@pytest.mark.parametrize("n,r,scheme", [
    (8, 1.0, "pairwise"), (8, 1.5, "pairwise"), (8, 2.0, "pairwise"),
    (9, 2.5, "pairwise"), (8, 2.0, "clustered"), (9, 3.0, "clustered"),
])
def test_full_participation_exact(n, r, scheme, rng):
    code = approx.build_approx_code(n, r, scheme)
    G = rng.randn(n, 128).astype(np.float32)
    rows = approx.encode_shared(code, jnp.asarray(G))
    dec, v, health = approx.decode(code, rows, with_health=True,
                                   batch_grads=jnp.asarray(G))
    want = G.sum(axis=0) / n
    np.testing.assert_allclose(np.asarray(dec), want, rtol=1e-5, atol=1e-5)
    # the certificate agrees: bound ≈ 0 (u = 1 feasible), residual at f32
    # solve noise, full coverage
    assert float(health["bound"]) < 1e-4
    assert float(health["residual"]) < 1e-4
    assert float(health["recovered_fraction"]) == 1.0


@pytest.mark.parametrize("n,r,scheme,missing", [
    (8, 1.5, "pairwise", (0,)), (8, 1.5, "pairwise", (1, 5)),
    (8, 2.0, "pairwise", (0, 3)), (9, 2.5, "pairwise", (2, 4, 7)),
    (8, 2.0, "clustered", (0, 2, 5)), (8, 1.0, "pairwise", (6,)),
])
def test_partial_recovery_residual_le_bound(n, r, scheme, missing, rng):
    code = approx.build_approx_code(n, r, scheme)
    G = rng.randn(n, 96).astype(np.float32)
    present = np.ones(n, bool)
    present[list(missing)] = False
    rows = np.asarray(approx.encode_shared(code, jnp.asarray(G)))
    rows = rows * present[:, None]  # absent rows arrive as zeros
    dec, v, health = approx.decode(code, jnp.asarray(rows),
                                   present=jnp.asarray(present),
                                   with_health=True,
                                   batch_grads=jnp.asarray(G))
    # absent workers never carry decode weight
    assert not np.asarray(v)[list(missing)].any()
    # the measured residual is the TRUE relative error...
    want = G.sum(axis=0) / n
    scale = np.sqrt((G ** 2).sum()) / n
    true_rel = np.sqrt(((np.asarray(dec) - want) ** 2).sum()) / scale
    assert float(health["residual"]) == pytest.approx(true_rel, rel=1e-4,
                                                      abs=1e-6)
    # ...and it sits under the analytic optimal-decoding bound (algebra —
    # Cauchy-Schwarz over the arrived support; f32 noise margin only)
    assert float(health["residual"]) <= float(health["bound"]) + 1e-5


def test_clustered_single_survivor_exact(rng):
    """FRC's selling point (arXiv:1903.01974): any one survivor per cluster
    keeps the decode exact — here all but one member of every cluster
    drops."""
    n, c = 8, 4
    code = approx.build_approx_code(n, float(c), "clustered")
    G = rng.randn(n, 64).astype(np.float32)
    present = np.zeros(n, bool)
    present[[1, 6]] = True  # one survivor in each of the two clusters
    rows = np.asarray(approx.encode_shared(code, jnp.asarray(G)))
    rows = rows * present[:, None]
    dec, _v, health = approx.decode(code, jnp.asarray(rows),
                                    present=jnp.asarray(present),
                                    with_health=True,
                                    batch_grads=jnp.asarray(G))
    want = G.sum(axis=0) / n
    np.testing.assert_allclose(np.asarray(dec), want, rtol=1e-4, atol=1e-4)
    assert float(health["bound"]) < 1e-4
    assert float(health["recovered_fraction"]) == 1.0


def test_dead_cluster_loses_its_group_boundedly(rng):
    """A fully-absent cluster loses its whole batch group: coverage drops,
    the bound goes loud, and the residual still sits under it (the
    rank-deficient solve stays finite via the SVD rcond truncation)."""
    n, c = 8, 2
    code = approx.build_approx_code(n, float(c), "clustered")
    G = rng.randn(n, 64).astype(np.float32)
    present = np.ones(n, bool)
    present[[2, 3]] = False  # cluster 1 entirely gone
    rows = np.asarray(approx.encode_shared(code, jnp.asarray(G)))
    rows = rows * present[:, None]
    dec, _v, health = approx.decode(code, jnp.asarray(rows),
                                    present=jnp.asarray(present),
                                    with_health=True,
                                    batch_grads=jnp.asarray(G))
    assert np.all(np.isfinite(np.asarray(dec)))
    assert float(health["recovered_fraction"]) == pytest.approx(6 / 8)
    # the two lost batches show up as √2 in the bound (u = 0 there)
    assert float(health["bound"]) == pytest.approx(np.sqrt(2.0), rel=1e-4)
    assert float(health["residual"]) <= float(health["bound"]) + 1e-5


def test_recovered_fraction_counts_covered_batches():
    code = approx.build_approx_code(8, 1.0, "pairwise")  # identity assignment
    pres = np.ones(8, bool)
    pres[[0, 4]] = False
    assert float(approx.recovered_fraction(
        code, jnp.asarray(pres))) == pytest.approx(6 / 8)
    assert float(approx.recovered_fraction(code)) == 1.0


def test_decode_with_health_requires_batch_grads():
    code = approx.build_approx_code(8, 1.5, "pairwise")
    rows = jnp.zeros((8, 4))
    with pytest.raises(ValueError, match="batch_grads"):
        approx.decode(code, rows, with_health=True)


# --------------------------------------------------------------------------
# config surface
# --------------------------------------------------------------------------

def _cfg(**kw):
    base = dict(network="FC", dataset="synthetic-mnist", approach="approx",
                num_workers=8, worker_fail=0, redundancy="shared",
                batch_size=4, max_steps=4, eval_freq=0, train_dir="")
    base.update(kw)
    return TrainConfig(**base)


def test_config_accepts_and_rejects_approx_parameters():
    _cfg(code_redundancy=1.5, straggler_alpha=0.25).validate()
    _cfg(code_redundancy=2.0, assignment_scheme="clustered").validate()
    # worker_fail as a nominal parameter is fine with adversary_count=0
    _cfg(worker_fail=1, adversary_count=0).validate()
    with pytest.raises(ValueError, match="Byzantine certificate"):
        _cfg(worker_fail=1).validate()
    with pytest.raises(ValueError, match="shared"):
        _cfg(redundancy="simulate").validate()
    with pytest.raises(ValueError, match="code_redundancy"):
        _cfg(code_redundancy=0.5).validate()
    with pytest.raises(ValueError, match="straggler_alpha"):
        _cfg(straggler_alpha=1.5).validate()
    # construction-time errors surface at config time, not mid-run
    with pytest.raises(ValueError, match="integer"):
        _cfg(code_redundancy=1.5, assignment_scheme="clustered").validate()
    with pytest.raises(ValueError, match="unknown assignment scheme"):
        _cfg(assignment_scheme="banana").validate()


def test_config_enforces_straggler_alpha_budget():
    _cfg(straggler_alpha=0.25, straggle_mode="drop",
         straggle_count=2).validate()  # ceil(0.25 * 8) = 2
    with pytest.raises(ValueError, match="straggler budget"):
        _cfg(straggler_alpha=0.25, straggle_mode="drop",
             straggle_count=3).validate()
