import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu import aggregation
from draco_tpu.attacks import inject_plain
from draco_tpu.coding import repetition


class TestMajorityVote:
    def test_recovers_honest_under_minority_corruption(self, rng):
        n, r, d = 9, 3, 40
        code = repetition.build_repetition_code(n, r)
        honest = rng.randn(code.num_groups, d).astype(np.float32)
        grads = np.repeat(honest, r, axis=0)  # identical within group
        # corrupt one member per group (minority)
        adv = np.zeros(n, dtype=bool)
        adv[[0, 4, 8]] = True
        g = inject_plain(jnp.asarray(grads), jnp.asarray(adv), "rev_grad")
        out = repetition.majority_vote(code, g)
        np.testing.assert_allclose(np.asarray(out), honest.mean(axis=0), rtol=1e-6)

    def test_constant_attack(self, rng):
        n, r, d = 6, 3, 8
        code = repetition.build_repetition_code(n, r)
        honest = rng.randn(code.num_groups, d).astype(np.float32)
        grads = np.repeat(honest, r, axis=0)
        adv = np.zeros(n, dtype=bool)
        adv[[1, 5]] = True
        g = inject_plain(jnp.asarray(grads), jnp.asarray(adv), "constant")
        out = repetition.majority_vote(code, g)
        np.testing.assert_allclose(np.asarray(out), honest.mean(axis=0), rtol=1e-6)

    def test_rejects_bad_group_size(self):
        with pytest.raises(ValueError):
            repetition.build_repetition_code(7, 3)

    def test_vote_on_bfloat16_rows(self, rng):
        """The O(r·d) fingerprint vote bitcasts rows; cover the 2-byte-dtype
        path (bf16 lanes hand the vote bf16 gradients)."""
        n, r, d = 6, 3, 33
        code = repetition.build_repetition_code(n, r)
        honest = rng.randn(code.num_groups, d).astype(np.float32)
        grads = jnp.asarray(np.repeat(honest, r, axis=0)).astype(jnp.bfloat16)
        grads = grads.at[2].set(-grads[2])  # minority corruption in group 0
        out = repetition.majority_vote(code, grads)
        want = np.asarray(jnp.asarray(honest).astype(jnp.bfloat16)
                          .astype(jnp.float32)).mean(axis=0)
        np.testing.assert_allclose(np.asarray(out.astype(jnp.float32)), want,
                                   rtol=2e-2, atol=1e-2)

    def test_vote_tiebreak_is_lowest_index(self):
        """r=2 with one adversary ties the agreement counts; argmax must
        deterministically pick the lowest row index (documented tie-break)."""
        code = repetition.build_repetition_code(2, 2)
        rows = np.stack([np.full(5, 7.0), np.full(5, -7.0)]).astype(np.float32)
        out = repetition.majority_vote(code, jnp.asarray(rows))
        np.testing.assert_array_equal(np.asarray(out), rows[0])


class TestFingerprintCollisionResistance:
    """Adversarial collision properties of the vote fingerprints — attacks
    OUTSIDE the in-scope oblivious error modes (VERDICT r4 #10 / the r4
    advisor's constructed-collision finding)."""

    def _fps(self, rows, key=None):
        h1, h2 = repetition._row_fingerprints(jnp.asarray(rows), key=key)
        return np.asarray(h1), np.asarray(h2)

    def test_top_bit_pair_flip_does_not_collide(self, rng):
        """The killer attack on any LINEAR hash mod 2^32 (keyed or not):
        flipping the sign/top bit at two positions shifts the hash by
        2^31·(w_i + w_j) ≡ 0 whenever the weights have equal parity — a
        constructible, key-independent collision. The nonlinear avalanche
        must not exhibit it, at any position pair tried."""
        d = 64
        row = rng.randn(1, 1, d).astype(np.float32)
        bits = row.view(np.uint32)
        for (i, j) in [(0, 1), (3, 40), (62, 63), (17, 18)]:
            forged = bits.copy()
            forged[0, 0, i] ^= np.uint32(0x80000000)
            forged[0, 0, j] ^= np.uint32(0x80000000)
            both = np.concatenate([bits, forged], axis=1).view(np.float32)
            h1, h2 = self._fps(both)
            assert (h1[0, 0] != h1[0, 1]) or (h2[0, 0] != h2[0, 1])

    def test_position_swap_forgery_does_not_collide(self, rng):
        """The attack that killed the first salted construction (r5 review):
        with position entering by XOR next to the salt — mix(bits ^ pos ^ s)
        — setting forged[i] = honest[j] ^ pos[j] ^ pos[i] (and vice versa)
        swaps the (bits ^ pos) values between the two positions, the salt
        XORs out, and BOTH hashes collide for EVERY salt. The shipped
        construction (position added between two avalanche rounds) must not
        collide on this forgery, under the public salts and under keys."""
        import jax

        d = 48
        pos = (np.arange(d, dtype=np.uint64) * 2654435761) % (1 << 32)
        pos = pos.astype(np.uint32)
        row = rng.randn(1, 1, d).astype(np.float32)
        bits = row.view(np.uint32)
        for (i, j) in [(0, 1), (5, 33), (46, 47)]:
            forged = bits.copy()
            forged[0, 0, i] = bits[0, 0, j] ^ pos[j] ^ pos[i]
            forged[0, 0, j] = bits[0, 0, i] ^ pos[i] ^ pos[j]
            both = np.concatenate([bits, forged], axis=1).view(np.float32)
            for key in (None, jax.random.key(7)):
                h1, h2 = self._fps(both, key=key)
                assert (h1[0, 0] != h1[0, 1]) or (h2[0, 0] != h2[0, 1]), (
                    f"swap forgery at ({i},{j}) collided, key={key}"
                )

    def test_exact_mode_matches_fingerprint_on_attacks_and_defeats_swaps(
            self, rng):
        """vote_check='exact' must (a) agree with the fingerprint vote on
        honest + oblivious-attack inputs, and (b) reject ANY bitwise-distinct
        forgery by construction — including collision forgeries no hash can
        promise to stop (repetition.py threat-model tier 3)."""
        n, r, d = 6, 3, 24
        code = repetition.build_repetition_code(n, r)
        honest = rng.randn(2, d).astype(np.float32)
        grads = np.repeat(honest, r, axis=0)
        adv = np.zeros(n, dtype=bool)
        adv[[1, 5]] = True
        g = inject_plain(jnp.asarray(grads), jnp.asarray(adv), "rev_grad")
        out_fp = repetition.majority_vote(code, g)
        out_ex = repetition.majority_vote(code, g, method="exact")
        np.testing.assert_array_equal(np.asarray(out_fp), np.asarray(out_ex))
        # One-bit forgery in the LOWEST-index row of an otherwise-honest
        # group: the honest majority sits at rows 1-2, so the argmax
        # tie-break can't rescue a broken comparator — an eq-all-True bug
        # would elect the forged row 0 and fail this assertion.
        forged = grads.copy()
        fbits = forged[0].view(np.uint32)
        fbits[11] ^= np.uint32(1)
        out = repetition.majority_vote(code, jnp.asarray(forged),
                                       method="exact")
        # winners are bit-identical honest rows, so equality is exact; a
        # forged-row win would shift group 0's mean and fail bitwise
        np.testing.assert_array_equal(
            np.asarray(out),
            np.asarray(repetition.majority_vote(code, jnp.asarray(grads),
                                                method="exact")))
        with pytest.raises(ValueError, match="fingerprint.*exact|exact"):
            repetition.majority_vote(code, g, method="boyer")

    def test_vote_rejects_forged_row_under_keyed_fingerprints(self, rng):
        """End-to-end: a minority row forged by the top-bit pair-flip attack
        must still lose the vote when the step passes a PRNG key (the
        training-step configuration)."""
        import jax

        n, r, d = 3, 3, 32
        code = repetition.build_repetition_code(n, r)
        honest = rng.randn(1, d).astype(np.float32)
        grads = np.repeat(honest, r, axis=0)
        forged = grads[2].view(np.uint32).copy()
        forged[[5, 21]] ^= np.uint32(0x80000000)
        grads[2] = forged.view(np.float32)
        out = repetition.majority_vote(code, jnp.asarray(grads),
                                       key=jax.random.key(123))
        np.testing.assert_allclose(np.asarray(out), honest[0], rtol=1e-6)

    def test_fingerprint_vote_equals_exact_vote_randomized(self, rng):
        """Property check: over random group contents with crafted duplicate
        patterns (the full input domain of the vote), the fingerprint path
        and the exact path must elect bitwise-identical winners — the two
        methods differ only in collision surface, never in semantics."""
        import jax

        n, r, d = 8, 4, 17
        code = repetition.build_repetition_code(n, r)
        for trial in range(8):
            rows = rng.randn(n, d).astype(np.float32)
            # plant duplicate patterns: copy random rows over random rows
            # within each group so agreement counts take nontrivial values
            for g0 in range(code.num_groups):
                base = g0 * r
                for _ in range(rng.randint(0, 4)):
                    src, dst = rng.randint(0, r, size=2)
                    rows[base + dst] = rows[base + src]
            present = (rng.rand(n) > 0.2) if trial % 2 else None
            kw = dict(present=None if present is None
                      else jnp.asarray(present))
            out_fp = repetition.majority_vote(
                code, jnp.asarray(rows), key=jax.random.key(trial), **kw)
            out_ex = repetition.majority_vote(
                code, jnp.asarray(rows), method="exact", **kw)
            np.testing.assert_array_equal(
                np.asarray(out_fp), np.asarray(out_ex),
                err_msg=f"trial {trial} (present={present})")

    def test_vote_check_config_validation(self):
        from draco_tpu.config import TrainConfig

        with pytest.raises(ValueError, match="vote_check"):
            TrainConfig(approach="maj_vote", num_workers=9, group_size=3,
                        vote_check="sha256").validate()

    def test_key_changes_fingerprints_but_not_vote(self, rng):
        """Salts drawn from different keys must change the hash values
        (else the key isn't live) while the vote outcome — a function only
        of the equality pattern — stays identical."""
        import jax

        n, r, d = 6, 3, 16
        code = repetition.build_repetition_code(n, r)
        honest = rng.randn(2, d).astype(np.float32)
        grads = np.repeat(honest, r, axis=0)
        rows = jnp.asarray(grads).reshape(2, r, d)
        fp_a = self._fps(rows, key=jax.random.key(0))
        fp_b = self._fps(rows, key=jax.random.key(1))
        assert not np.array_equal(fp_a[0], fp_b[0])
        out_a = repetition.majority_vote(code, jnp.asarray(grads),
                                         key=jax.random.key(0))
        out_b = repetition.majority_vote(code, jnp.asarray(grads),
                                         key=jax.random.key(1))
        np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))


def krum_oracle(grad_list, n, s):
    """Direct transcription of the reference loop semantics
    (baseline_master.py:278-291) as a float64 oracle."""
    score = []
    for i, g_i in enumerate(grad_list):
        dists = [np.linalg.norm(g_i - g_j) ** 2 for j, g_j in enumerate(grad_list) if i != j]
        score.append(sum(np.sort(dists)[: n - s - 2]))
    return grad_list[int(np.argmin(score))]


class TestAggregators:
    def test_mean(self, rng):
        g = rng.randn(8, 10).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(aggregation.mean(jnp.asarray(g))), g.mean(axis=0), rtol=1e-6
        )

    def test_krum_matches_oracle(self, rng):
        n, s, d = 8, 2, 30
        g = rng.randn(n, d).astype(np.float32)
        g[3] *= -100  # an attacked row
        out = aggregation.krum(jnp.asarray(g), s)
        want = krum_oracle(list(g), n, s)
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)

    def test_krum_discards_adversary(self, rng):
        n, s, d = 10, 2, 16
        base = rng.randn(d).astype(np.float32)
        g = base[None, :] + 0.01 * rng.randn(n, d).astype(np.float32)
        g[[2, 7]] = -100.0 * g[[2, 7]]
        out = np.asarray(aggregation.krum(jnp.asarray(g), s))
        assert np.linalg.norm(out - base) < 1.0

    def test_geometric_median_point_cloud(self, rng):
        # for a cloud with an extreme outlier, the geometric median stays
        # near the honest cluster while the mean does not
        n, d = 9, 12
        base = rng.randn(d).astype(np.float32)
        g = base[None, :] + 0.05 * rng.randn(n, d).astype(np.float32)
        g[4] = 1000.0
        gm = np.asarray(aggregation.geometric_median(jnp.asarray(g)))
        assert np.linalg.norm(gm - base) < 1.0
        assert np.linalg.norm(g.mean(axis=0) - base) > 50.0

    def test_geometric_median_weiszfeld_fixpoint(self, rng):
        # 1-D: geometric median == coordinate-wise median for odd count
        g = np.array([[1.0], [2.0], [3.0], [4.0], [100.0]], dtype=np.float32)
        gm = np.asarray(aggregation.geometric_median(jnp.asarray(g), iters=200))
        assert abs(gm[0] - 3.0) < 1e-2

    def test_aggregate_dispatch(self, rng):
        g = jnp.asarray(rng.randn(8, 5).astype(np.float32))
        for mode in aggregation.MODES:
            out = aggregation.aggregate(g, mode, s=1)
            assert out.shape == (5,)
        with pytest.raises(ValueError):
            aggregation.aggregate(g, "bogus")

    def test_coordinate_median_oracle(self, rng):
        g = rng.randn(9, 17).astype(np.float32)
        out = np.asarray(aggregation.coordinate_median(jnp.asarray(g)))
        np.testing.assert_allclose(out, np.median(g, axis=0), rtol=1e-6)

    def test_coordinate_median_present_stays_in_range(self, rng):
        g = rng.randn(9, 8).astype(np.float32)
        present = np.ones(9, bool)
        present[[1, 6]] = False
        g[[1, 6]] = 1e6  # absent rows hold garbage
        out = np.asarray(aggregation.coordinate_median(
            jnp.asarray(g), present=jnp.asarray(present)))
        kept = g[present]
        assert (out >= kept.min(axis=0) - 1e-6).all()
        assert (out <= kept.max(axis=0) + 1e-6).all()

    def test_trimmed_mean_oracle(self, rng):
        n, s = 9, 2
        g = rng.randn(n, 13).astype(np.float32)
        out = np.asarray(aggregation.trimmed_mean(jnp.asarray(g), s))
        want = np.sort(g, axis=0)[s:n - s].mean(axis=0)
        np.testing.assert_allclose(out, want, rtol=1e-6)
        with pytest.raises(ValueError):
            aggregation.trimmed_mean(jnp.asarray(g), 5)

    def test_trimmed_mean_kills_outliers(self, rng):
        base = rng.randn(12).astype(np.float32)
        g = base[None, :] + 0.01 * rng.randn(9, 12).astype(np.float32)
        g[[0, 5]] = -100.0 * g[[0, 5]]
        out = np.asarray(aggregation.trimmed_mean(jnp.asarray(g), 2))
        assert np.linalg.norm(out - base) < 1.0

    def test_multi_krum_averages_honest_selection(self, rng):
        n, s = 10, 2
        base = rng.randn(16).astype(np.float32)
        g = base[None, :] + 0.01 * rng.randn(n, 16).astype(np.float32)
        g[[2, 7]] = -100.0 * g[[2, 7]]
        out = np.asarray(aggregation.multi_krum(jnp.asarray(g), s))
        assert np.linalg.norm(out - base) < 1.0
        # m honest rows averaged: closer to base than single-row krum noise
        one = np.asarray(aggregation.krum(jnp.asarray(g), s))
        honest = np.delete(g, [2, 7], axis=0)
        assert np.linalg.norm(out - honest.mean(axis=0)) \
            <= np.linalg.norm(one - honest.mean(axis=0)) + 1e-5

    def test_bulyan_discards_adversaries(self, rng):
        n, s = 11, 2  # n >= 4s+3
        base = rng.randn(16).astype(np.float32)
        g = base[None, :] + 0.01 * rng.randn(n, 16).astype(np.float32)
        g[[1, 8]] = -100.0 * g[[1, 8]]
        out = np.asarray(aggregation.bulyan(jnp.asarray(g), s))
        assert np.linalg.norm(out - base) < 1.0

    def test_multi_krum_present_still_excludes_adversary(self, rng):
        """Regression: with stragglers the kept count derives from the
        present count — n - s - 2 could select every present row and
        degenerate to a contaminated mean."""
        n, s = 10, 1
        base = rng.randn(16).astype(np.float32)
        g = base[None, :] + 0.01 * rng.randn(n, 16).astype(np.float32)
        g[4] = 1e4  # one Byzantine present row
        present = np.ones(n, bool)
        present[[0, 1, 2]] = False  # 3 stragglers: 7 present >= s+3
        out = np.asarray(aggregation.multi_krum(
            jnp.asarray(g), s, present=jnp.asarray(present)))
        assert np.linalg.norm(out - base) < 1.0

    def test_trimmed_mean_joint_straggler_adversary(self, rng):
        """Regression: the trim runs over present rows only, so absent-row
        garbage never votes and a Byzantine present row is still trimmed."""
        n, s = 9, 2
        base = rng.randn(16).astype(np.float32)
        g = base[None, :] + 0.01 * rng.randn(n, 16).astype(np.float32)
        g[[0, 5]] = -1e6  # Byzantine, count == s
        present = np.ones(n, bool)
        present[[1, 6]] = False  # absent rows hold garbage
        g[[1, 6]] = 777.0
        out = np.asarray(aggregation.trimmed_mean(
            jnp.asarray(g), s, present=jnp.asarray(present)))
        assert np.linalg.norm(out - base) < 1.0

    def test_coordinate_median_present_oracle(self, rng):
        g = rng.randn(9, 11).astype(np.float32)
        present = np.ones(9, bool)
        present[[2, 5, 8]] = False
        g[[2, 5, 8]] = 1e6
        out = np.asarray(aggregation.coordinate_median(
            jnp.asarray(g), present=jnp.asarray(present)))
        np.testing.assert_allclose(out, np.median(g[present], axis=0),
                                   rtol=1e-6)

    def test_bulyan_present_mask(self, rng):
        n, s = 11, 2
        base = rng.randn(16).astype(np.float32)
        g = base[None, :] + 0.01 * rng.randn(n, 16).astype(np.float32)
        g[3] = -100.0 * g[3]
        present = np.ones(n, bool)
        present[9] = False
        g[9] = 1e6
        out = np.asarray(aggregation.bulyan(
            jnp.asarray(g), s, present=jnp.asarray(present)))
        assert np.linalg.norm(out - base) < 1.0

    def test_bulyan_many_stragglers_still_filters(self, rng):
        """Regression: θ/β derive from the present count — with 4 of 11 rows
        absent, the Krum stage must still exclude the Byzantine present row
        rather than degenerate to a plain present-mean."""
        n, s = 11, 1
        base = rng.randn(16).astype(np.float32)
        g = base[None, :] + 0.01 * rng.randn(n, 16).astype(np.float32)
        g[4] = 1e4  # Byzantine present row
        present = np.ones(n, bool)
        present[[0, 1, 2, 3]] = False
        g[[0, 1, 2, 3]] = 555.0
        out = np.asarray(aggregation.bulyan(
            jnp.asarray(g), s, present=jnp.asarray(present)))
        assert np.linalg.norm(out - base) < 1.0

    def test_median_rules_reject_over_straggled_config(self):
        from draco_tpu.config import TrainConfig

        with pytest.raises(ValueError, match="> 2 \\* worker_fail"):
            TrainConfig(approach="baseline", mode="trimmed_mean",
                        num_workers=9, worker_fail=2, straggle_mode="drop",
                        straggle_count=6).validate()

    def test_trimmed_mean_present_only_oracle(self, rng):
        """With a present mask the trim is exactly the numpy trimmed mean of
        the present rows — no fill values enter the kept middle (advisor r2:
        a median fill lands e copies inside the middle and biases the mean
        toward the median as straggle_count grows)."""
        n, s = 9, 2
        g = rng.randn(n, 13).astype(np.float32)
        present = np.ones(n, bool)
        present[[1, 6]] = False
        g[[1, 6]] = 1e6  # absent-row garbage must not vote
        out = np.asarray(aggregation.trimmed_mean(
            jnp.asarray(g), s, present=jnp.asarray(present)))
        kept = np.sort(g[present], axis=0)[s:present.sum() - s]
        np.testing.assert_allclose(out, kept.mean(axis=0), rtol=1e-6)

    def test_bulyan_warns_below_guarantee_threshold(self, rng):
        """n < 4s+3 runs but warns that the Byzantine guarantee is degraded
        (advisor r2: silent beta clamp)."""
        g = rng.randn(7, 8).astype(np.float32)
        with pytest.warns(UserWarning, match="4s\\+3"):
            aggregation.bulyan(jnp.asarray(g), 2)

    def test_excluded_nonfinite_rows_cannot_poison(self, rng):
        """A non-finite excluded row (overflowed Byzantine present row, or
        NaN garbage in an absent row) must not leak into trimmed_mean /
        bulyan / the aggregate() dispatch via 0·inf = NaN products
        (code-review r3)."""
        n, s = 9, 2
        base = rng.randn(16).astype(np.float32)
        g0 = base[None, :] + 0.01 * rng.randn(n, 16).astype(np.float32)
        present = np.ones(n, bool)
        present[6] = False

        # absent-row NaN garbage: every rule must stay finite (aggregate()
        # zeroes absent rows before dispatch)
        g = g0.copy()
        g[6] = np.nan
        for mode in ("normal", "geometric_median", "krum", "coord_median",
                     "trimmed_mean", "multi_krum", "bulyan"):
            out = np.asarray(aggregation.aggregate(
                jnp.asarray(g), mode, s=s, present=jnp.asarray(present)))
            assert np.isfinite(out).all(), f"{mode} poisoned by absent NaN"

        # non-finite Byzantine PRESENT row: the rank/selection rules exclude
        # it by weight and must not let 0·inf products reintroduce it
        # (mean is legitimately inf there; Weiszfeld-on-inf matches the
        # reference's hdmedians behaviour — neither is asserted)
        g = g0.copy()
        g[6] = np.nan
        g[0] = np.inf
        for mode in ("krum", "coord_median", "trimmed_mean", "multi_krum",
                     "bulyan"):
            out = np.asarray(aggregation.aggregate(
                jnp.asarray(g), mode, s=s, present=jnp.asarray(present)))
            assert np.isfinite(out).all(), f"{mode} poisoned by present inf"
        out = np.asarray(aggregation.trimmed_mean(
            jnp.asarray(g), s, present=jnp.asarray(present)))
        assert np.linalg.norm(out - base) < 1.0

    def test_bulyan_no_warning_at_full_guarantee(self, rng):
        import warnings as _w

        g = rng.randn(11, 8).astype(np.float32)
        with _w.catch_warnings():
            _w.simplefilter("error")
            aggregation.bulyan(jnp.asarray(g), 2)


class TestAttacks:
    def test_plain_modes(self, rng):
        from draco_tpu import attacks

        g = jnp.asarray(rng.randn(4, 6).astype(np.float32))
        mask = jnp.asarray(np.array([True, False, False, True]))
        out = np.asarray(attacks.inject_plain(g, mask, "rev_grad"))
        np.testing.assert_allclose(out[0], -100 * np.asarray(g)[0], rtol=1e-6)
        np.testing.assert_allclose(out[1], np.asarray(g)[1], rtol=1e-6)
        out = np.asarray(attacks.inject_plain(g, mask, "constant"))
        np.testing.assert_allclose(out[3], -100.0)
        # the random attack is REAL now (ISSUE 14 satellite — the
        # reference left it a passthrough TODO): a seeded N(0,1) payload
        # scaled by the magnitude, drawn from the (seed, step) schedule
        # discipline — deterministic, worker rows independent, honest
        # rows untouched
        out = np.asarray(attacks.inject_plain(g, mask, "random",
                                              step=3, seed=428))
        np.testing.assert_allclose(out[1], np.asarray(g)[1], rtol=1e-6)
        np.testing.assert_allclose(out[2], np.asarray(g)[2], rtol=1e-6)
        assert not np.allclose(out[0], np.asarray(g)[0])
        assert not np.allclose(out[0], out[3])  # per-row independent draws
        assert np.abs(out[0]).max() > 10  # magnitude-scaled, not a nudge
        again = np.asarray(attacks.inject_plain(g, mask, "random",
                                                step=3, seed=428))
        np.testing.assert_array_equal(out, again)  # same (seed, step) draw
        other = np.asarray(attacks.inject_plain(g, mask, "random",
                                                step=4, seed=428))
        assert not np.array_equal(out, other)  # distinct per step
        # a keyless call has no stream to draw from — named config error
        with pytest.raises(ValueError, match="random"):
            attacks.attack_plain(g, "random")
        # cyclic wire form: additive on the encoded rows, seeded the same
        re_ = jnp.asarray(np.asarray(g)[:3])
        o_re, o_im = attacks.inject_cyclic(re_, re_, jnp.asarray(
            np.array([False, True, False])), "random", step=3, seed=428)
        np.testing.assert_allclose(np.asarray(o_re)[0], np.asarray(re_)[0])
        assert not np.allclose(np.asarray(o_re)[1], np.asarray(re_)[1])
        # independent re/im draws
        assert not np.allclose(np.asarray(o_re)[1] - np.asarray(re_)[1],
                               np.asarray(o_im)[1] - np.asarray(re_)[1])

    def test_cyclic_additive(self, rng):
        from draco_tpu import attacks

        re = jnp.asarray(rng.randn(3, 4).astype(np.float32))
        im = jnp.asarray(rng.randn(3, 4).astype(np.float32))
        mask = jnp.asarray(np.array([False, True, False]))
        o_re, o_im = attacks.inject_cyclic(re, im, mask, "rev_grad")
        np.testing.assert_allclose(np.asarray(o_re)[1], -99 * np.asarray(re)[1], rtol=1e-5)
        o_re, o_im = attacks.inject_cyclic(re, im, mask, "constant")
        np.testing.assert_allclose(np.asarray(o_re)[1], np.asarray(re)[1] - 100.0, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(o_im)[1], np.asarray(im)[1], rtol=1e-6)


class TestColludingAttacks:
    """alie / ipm (beyond-reference): omniscient colluders computing their
    payload from honest-row statistics."""

    def test_ipm_payload_and_honest_rows(self, rng):
        from draco_tpu import attacks

        g = jnp.asarray(rng.randn(8, 16).astype(np.float32))
        mask = jnp.asarray(np.arange(8) == 3)
        out = np.asarray(attacks.inject_plain(g, mask, "ipm", n_mal=1))
        honest = np.asarray(g)[np.arange(8) != 3]
        np.testing.assert_allclose(out[3], -0.5 * honest.mean(0), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(out[np.arange(8) != 3], honest, rtol=1e-6)

    def test_alie_payload_hides_in_variance(self, rng):
        from draco_tpu import attacks
        from draco_tpu.attacks import _alie_z

        g = jnp.asarray(rng.randn(8, 16).astype(np.float32))
        mask = jnp.asarray(np.arange(8) < 3)  # z(8,3)=0.253 > 0: a REAL payload
        out = np.asarray(attacks.inject_plain(g, mask, "alie", n_mal=3))
        honest = np.asarray(g)[3:]
        mu, sigma = honest.mean(0), honest.std(0)
        z = _alie_z(8, 3)
        assert z > 0, "test premise: quantile must be positive at (8, 3)"
        np.testing.assert_allclose(out[0], mu - z * sigma, rtol=1e-4,
                                   atol=1e-5)
        # the payload stays inside the honest spread (that is the attack)
        assert np.all(np.abs(out[0] - mu) <= 3.1 * sigma + 1e-6)

    def test_alie_warns_when_inert(self, rng):
        import warnings

        from draco_tpu import attacks

        g = jnp.asarray(rng.randn(8, 4).astype(np.float32))
        mask = jnp.asarray(np.arange(8) == 0)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            attacks.inject_plain(g, mask, "alie", n_mal=1)  # z(8,1) < 0
        assert any("inert" in str(w.message) for w in caught)

    def test_sign_of_magnitude_cannot_invert_payload(self, rng):
        """A positive --adversarial must not flip alie/ipm direction (the
        knob's sign encodes direction only for rev_grad's multiplicative
        payload) — regression for the r3 advisor finding."""
        from draco_tpu import attacks

        g = jnp.asarray(rng.randn(8, 16).astype(np.float32))
        mask = jnp.asarray(np.arange(8) < 3)
        for mode in ("alie", "ipm"):
            neg = np.asarray(attacks.inject_plain(g, mask, mode,
                                                  magnitude=-100.0, n_mal=3))
            pos = np.asarray(attacks.inject_plain(g, mask, mode,
                                                  magnitude=100.0, n_mal=3))
            np.testing.assert_array_equal(pos, neg)

    def test_ipm_poisons_mean_but_not_coord_median(self, rng):
        from draco_tpu import attacks

        # tight honest cluster so the robust rule has signal
        g = jnp.asarray((rng.randn(8, 32) * 0.01 + 1.0).astype(np.float32))
        mask = jnp.asarray(np.arange(8) < 2)
        out = attacks.inject_plain(g, mask, "ipm", n_mal=2)
        honest_mean = np.asarray(g)[2:].mean(0)
        mean_agg = np.asarray(jnp.mean(out, axis=0))
        med_agg = np.asarray(aggregation.coordinate_median(out))
        # mean dragged toward -0.5*mu by the colluders; median stays put
        assert np.abs(mean_agg - honest_mean).max() > 0.3
        assert np.abs(med_agg - honest_mean).max() < 0.05

    def test_jit_static_quantile(self, rng):
        """n_mal is static config, so alie traces under jit."""
        import jax

        from draco_tpu import attacks

        g = jnp.asarray(rng.randn(8, 8).astype(np.float32))
        mask = jnp.asarray(np.arange(8) == 1)
        f = jax.jit(lambda g, m: attacks.inject_plain(g, m, "alie", n_mal=1))
        out = np.asarray(f(g, mask))
        assert np.isfinite(out).all()

    def test_cyclic_rejects_colluding_modes(self):
        from draco_tpu.config import TrainConfig

        with pytest.raises(ValueError, match="decode is exact"):
            TrainConfig(network="LeNet", dataset="synthetic-mnist",
                        approach="cyclic", num_workers=8, worker_fail=1,
                        err_mode="ipm", batch_size=4).validate()

    def test_mean_under_ipm_trains_worse_than_median(self):
        """End-to-end under a strong ipm (magnitude 8x the canonical eps,
        2/8 colluders): the mean update's direction REVERSES
        ((6*mu - 8*mu)/8 = -0.25*mu) so the undefended run must stall or
        diverge, while coord-median discards the colluders and learns."""
        from draco_tpu.config import TrainConfig
        from draco_tpu.data.datasets import load_dataset
        from draco_tpu.runtime import make_mesh
        from draco_tpu.training.trainer import Trainer

        losses = {}
        for mode in ("normal", "coord_median"):
            cfg = TrainConfig(
                network="FC", dataset="synthetic-mnist", batch_size=16,
                lr=0.05, num_workers=8, approach="baseline", mode=mode,
                worker_fail=2, err_mode="ipm", adversarial=-800.0,
                max_steps=30, eval_freq=0, train_dir="", log_every=1000,
            )
            ds = load_dataset("synthetic-mnist")
            tr = Trainer(cfg, mesh=make_mesh(8), dataset=ds, quiet=True)
            last = tr.run()
            losses[mode] = float(last["loss"])
            tr.close()
        # the attack must visibly bite the mean AND median must beat it
        assert losses["coord_median"] < 2.0, losses
        assert losses["normal"] > losses["coord_median"] + 0.2, losses


class TestSchedules:
    def test_adversary_schedule_deterministic(self):
        from draco_tpu import rng as drng

        a = drng.adversary_schedule(428, 50, 8, 2)
        b = drng.adversary_schedule(428, 50, 8, 2)
        np.testing.assert_array_equal(a, b)
        assert (a.sum(axis=1) == 2).all()

    def test_group_seeds_agree(self):
        from draco_tpu import rng as drng

        np.testing.assert_array_equal(drng.group_seeds(428, 4), drng.group_seeds(428, 4))

    def test_epoch_permutation(self):
        from draco_tpu import rng as drng

        p1 = drng.epoch_permutation(5, 0, 100)
        p2 = drng.epoch_permutation(5, 1, 100)
        assert not np.array_equal(p1, p2)
        assert sorted(p1) == list(range(100))


class TestKrumPenaltyBounded:
    def test_many_absent_rows_do_not_overflow(self, rng):
        """Regression: a finfo.max-scale absent penalty overflowed the score
        sum to inf once >= 4 absent entries landed in a row's k nearest slots,
        degenerating argmin to index 0."""
        n, s = 10, 1
        base = rng.randn(16).astype(np.float32)
        g = base[None, :] + 0.01 * rng.randn(n, 16).astype(np.float32)
        g[0] = 1e6  # index 0 is an outlier — the degenerate argmin would pick it
        present = np.ones(n, dtype=bool)
        present[[1, 2, 3, 4, 5]] = False  # 5 absent > s+1, permitted by baseline
        out = np.asarray(aggregation.krum(jnp.asarray(g), s,
                                          present=jnp.asarray(present)))
        assert np.all(np.isfinite(out))
        assert not np.allclose(out, g[0])
        assert any(np.allclose(out, g[i]) for i in range(6, n))

    def test_still_matches_oracle_after_penalty_change(self, rng):
        n, s, d = 8, 2, 30
        g = rng.randn(n, d).astype(np.float32)
        g[5] *= 77.0
        out = aggregation.krum(jnp.asarray(g), s)
        want = krum_oracle(list(g), n, s)
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)


class TestWeiszfeldIterationBudget:
    """Justifies config.geomedian_iters=80 (the bench's vs_baseline divides
    by geo-median cost, which is linear in this knob): on representative
    gradient stacks — honest cluster + reference-style -100x attacked rows —
    80 float32 Weiszfeld iterations match a float64 run iterated to
    convergence (the hdmedians stand-in) to float32 resolution."""

    @staticmethod
    def _converged_f64(g, tol=1e-14, cap=20000):
        y = g.mean(axis=0)
        for _ in range(cap):
            dist = np.linalg.norm(g - y[None, :], axis=1)
            w = 1.0 / np.maximum(dist, 1e-300)
            y_new = (w @ g) / w.sum()
            if np.linalg.norm(y_new - y) <= tol * max(np.linalg.norm(y), 1e-30):
                return y_new
            y = y_new
        return y

    @pytest.mark.parametrize("n,d,n_adv", [(8, 1000, 0), (8, 1000, 2),
                                           (16, 5000, 3), (32, 2000, 5)])
    def test_80_iters_matches_converged_float64(self, n, d, n_adv, rng):
        base = rng.randn(d).astype(np.float32) * 0.1
        g = base[None, :] + 0.02 * rng.randn(n, d).astype(np.float32)
        if n_adv:
            g[:n_adv] = -100.0 * g[:n_adv]  # reference rev_grad magnitude
        want = self._converged_f64(g.astype(np.float64))
        got = np.asarray(aggregation.geometric_median(jnp.asarray(g), iters=80))
        scale = max(np.linalg.norm(want), 1e-30)
        rel = np.linalg.norm(got - want) / scale
        assert rel < 5e-5, f"rel err {rel:.2e} after 80 iters"

    def test_40_iters_would_not_suffice_under_attack(self, rng):
        """The knob is not slack: fewer iterations measurably lag the
        converged point on the attacked stacks the bench times."""
        n, d = 8, 1000
        base = rng.randn(d).astype(np.float32) * 0.1
        g = base[None, :] + 0.02 * rng.randn(n, d).astype(np.float32)
        g[:2] = -100.0 * g[:2]
        want = self._converged_f64(g.astype(np.float64))
        scale = max(np.linalg.norm(want), 1e-30)
        rel = lambda it: np.linalg.norm(
            np.asarray(aggregation.geometric_median(jnp.asarray(g), iters=it)) - want
        ) / scale
        assert rel(80) < rel(10) or rel(10) < 5e-5
