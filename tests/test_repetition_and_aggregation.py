import jax.numpy as jnp
import numpy as np
import pytest

from draco_tpu import aggregation
from draco_tpu.attacks import inject_plain
from draco_tpu.coding import repetition


class TestMajorityVote:
    def test_recovers_honest_under_minority_corruption(self, rng):
        n, r, d = 9, 3, 40
        code = repetition.build_repetition_code(n, r)
        honest = rng.randn(code.num_groups, d).astype(np.float32)
        grads = np.repeat(honest, r, axis=0)  # identical within group
        # corrupt one member per group (minority)
        adv = np.zeros(n, dtype=bool)
        adv[[0, 4, 8]] = True
        g = inject_plain(jnp.asarray(grads), jnp.asarray(adv), "rev_grad")
        out = repetition.majority_vote(code, g)
        np.testing.assert_allclose(np.asarray(out), honest.mean(axis=0), rtol=1e-6)

    def test_constant_attack(self, rng):
        n, r, d = 6, 3, 8
        code = repetition.build_repetition_code(n, r)
        honest = rng.randn(code.num_groups, d).astype(np.float32)
        grads = np.repeat(honest, r, axis=0)
        adv = np.zeros(n, dtype=bool)
        adv[[1, 5]] = True
        g = inject_plain(jnp.asarray(grads), jnp.asarray(adv), "constant")
        out = repetition.majority_vote(code, g)
        np.testing.assert_allclose(np.asarray(out), honest.mean(axis=0), rtol=1e-6)

    def test_rejects_bad_group_size(self):
        with pytest.raises(ValueError):
            repetition.build_repetition_code(7, 3)


def krum_oracle(grad_list, n, s):
    """Direct transcription of the reference loop semantics
    (baseline_master.py:278-291) as a float64 oracle."""
    score = []
    for i, g_i in enumerate(grad_list):
        dists = [np.linalg.norm(g_i - g_j) ** 2 for j, g_j in enumerate(grad_list) if i != j]
        score.append(sum(np.sort(dists)[: n - s - 2]))
    return grad_list[int(np.argmin(score))]


class TestAggregators:
    def test_mean(self, rng):
        g = rng.randn(8, 10).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(aggregation.mean(jnp.asarray(g))), g.mean(axis=0), rtol=1e-6
        )

    def test_krum_matches_oracle(self, rng):
        n, s, d = 8, 2, 30
        g = rng.randn(n, d).astype(np.float32)
        g[3] *= -100  # an attacked row
        out = aggregation.krum(jnp.asarray(g), s)
        want = krum_oracle(list(g), n, s)
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)

    def test_krum_discards_adversary(self, rng):
        n, s, d = 10, 2, 16
        base = rng.randn(d).astype(np.float32)
        g = base[None, :] + 0.01 * rng.randn(n, d).astype(np.float32)
        g[[2, 7]] = -100.0 * g[[2, 7]]
        out = np.asarray(aggregation.krum(jnp.asarray(g), s))
        assert np.linalg.norm(out - base) < 1.0

    def test_geometric_median_point_cloud(self, rng):
        # for a cloud with an extreme outlier, the geometric median stays
        # near the honest cluster while the mean does not
        n, d = 9, 12
        base = rng.randn(d).astype(np.float32)
        g = base[None, :] + 0.05 * rng.randn(n, d).astype(np.float32)
        g[4] = 1000.0
        gm = np.asarray(aggregation.geometric_median(jnp.asarray(g)))
        assert np.linalg.norm(gm - base) < 1.0
        assert np.linalg.norm(g.mean(axis=0) - base) > 50.0

    def test_geometric_median_weiszfeld_fixpoint(self, rng):
        # 1-D: geometric median == coordinate-wise median for odd count
        g = np.array([[1.0], [2.0], [3.0], [4.0], [100.0]], dtype=np.float32)
        gm = np.asarray(aggregation.geometric_median(jnp.asarray(g), iters=200))
        assert abs(gm[0] - 3.0) < 1e-2

    def test_aggregate_dispatch(self, rng):
        g = jnp.asarray(rng.randn(8, 5).astype(np.float32))
        for mode in ("normal", "geometric_median", "krum"):
            out = aggregation.aggregate(g, mode, s=1)
            assert out.shape == (5,)
        with pytest.raises(ValueError):
            aggregation.aggregate(g, "bogus")


class TestAttacks:
    def test_plain_modes(self, rng):
        from draco_tpu import attacks

        g = jnp.asarray(rng.randn(4, 6).astype(np.float32))
        mask = jnp.asarray(np.array([True, False, False, True]))
        out = np.asarray(attacks.inject_plain(g, mask, "rev_grad"))
        np.testing.assert_allclose(out[0], -100 * np.asarray(g)[0], rtol=1e-6)
        np.testing.assert_allclose(out[1], np.asarray(g)[1], rtol=1e-6)
        out = np.asarray(attacks.inject_plain(g, mask, "constant"))
        np.testing.assert_allclose(out[3], -100.0)
        out = np.asarray(attacks.inject_plain(g, mask, "random"))
        np.testing.assert_allclose(out, np.asarray(g))

    def test_cyclic_additive(self, rng):
        from draco_tpu import attacks

        re = jnp.asarray(rng.randn(3, 4).astype(np.float32))
        im = jnp.asarray(rng.randn(3, 4).astype(np.float32))
        mask = jnp.asarray(np.array([False, True, False]))
        o_re, o_im = attacks.inject_cyclic(re, im, mask, "rev_grad")
        np.testing.assert_allclose(np.asarray(o_re)[1], -99 * np.asarray(re)[1], rtol=1e-5)
        o_re, o_im = attacks.inject_cyclic(re, im, mask, "constant")
        np.testing.assert_allclose(np.asarray(o_re)[1], np.asarray(re)[1] - 100.0, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(o_im)[1], np.asarray(im)[1], rtol=1e-6)


class TestSchedules:
    def test_adversary_schedule_deterministic(self):
        from draco_tpu import rng as drng

        a = drng.adversary_schedule(428, 50, 8, 2)
        b = drng.adversary_schedule(428, 50, 8, 2)
        np.testing.assert_array_equal(a, b)
        assert (a.sum(axis=1) == 2).all()

    def test_group_seeds_agree(self):
        from draco_tpu import rng as drng

        np.testing.assert_array_equal(drng.group_seeds(428, 4), drng.group_seeds(428, 4))

    def test_epoch_permutation(self):
        from draco_tpu import rng as drng

        p1 = drng.epoch_permutation(5, 0, 100)
        p2 = drng.epoch_permutation(5, 1, 100)
        assert not np.array_equal(p1, p2)
        assert sorted(p1) == list(range(100))
