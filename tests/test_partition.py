"""Partition-rule table unit tests (the static sharding auditor's base
layer, draco_tpu/parallel/partition.py): the canonical normalizer is
idempotent and strips exactly trailing Nones (the PR 6 retrace bug's
fix, now deduped), the regex matcher is first-match-wins with scalar
short-circuit and raise-on-uncovered, and every committed route table is
DISJOINT and normalized — the properties lint rule 7 (sharding_contract)
leans on for its exactly-one-match check."""

import re

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from draco_tpu.parallel.partition import (
    CNN_STEP_RULES,
    EP_STEP_RULES,
    PP_STEP_RULES,
    REPLICATED,
    SEQ_TOKENS,
    SP_STEP_RULES,
    TP_STEP_RULES,
    WORKER_ROWS,
    match_partition_rules,
    match_report,
    norm_spec,
    override,
    spec_axes,
    tree_combine_rules,
    tree_rows,
)

pytestmark = pytest.mark.core


class TestNormSpec:
    def test_strips_trailing_nones(self):
        assert norm_spec(P("tp", None)) == P("tp")
        assert norm_spec(P("tp", None, None)) == P("tp")
        assert norm_spec(P(None, "tp", None)) == P(None, "tp")

    def test_none_and_empty_normalize_to_p(self):
        assert norm_spec(None) == P()
        assert norm_spec(P()) == P()
        assert norm_spec(P(None, None)) == P()

    def test_interior_nones_survive(self):
        # P(None, 'tp') is already XLA-normal: dim 0 replicated, dim 1
        # sharded — stripping it would change meaning
        assert norm_spec(P(None, "tp")) == P(None, "tp")

    def test_idempotent(self):
        for spec in (None, P(), P("w"), P("w", None), P(None, "tp"),
                     P(("tl2", "tl1")), SEQ_TOKENS):
            once = norm_spec(spec)
            assert norm_spec(once) == once

    def test_committed_tables_declare_normalized_specs(self):
        # rule 7 rejects unnormalized table specs; the committed tables
        # must never trip their own auditor
        for table in (CNN_STEP_RULES, SP_STEP_RULES, TP_STEP_RULES,
                      EP_STEP_RULES, PP_STEP_RULES,
                      tree_combine_rules(("tl1", "tl2"))):
            for pat, spec in table:
                assert spec == norm_spec(spec), (pat, spec)


class TestSpecAxes:
    def test_flattens_tuple_entries(self):
        assert spec_axes(P(("tl2", "tl1"))) == {"tl2", "tl1"}
        assert spec_axes(P("w", None, "sp")) == {"w", "sp"}
        assert spec_axes(P()) == frozenset()
        assert spec_axes(None) == frozenset()


class TestMatcher:
    RULES = (
        (r"^state/.*qkv/kernel$", P(None, "tp")),
        (r"^state/", REPLICATED),
        (r"^tokens$", WORKER_ROWS),
    )

    def test_first_match_wins(self):
        tree = {"state": {"qkv": {"kernel": np.zeros((4, 4))},
                          "bias": np.zeros(4)}}
        specs = match_partition_rules(self.RULES, tree)
        assert specs["state"]["qkv"]["kernel"] == P(None, "tp")
        assert specs["state"]["bias"] == REPLICATED

    def test_scalars_bypass_the_table(self):
        # scalar and size-1 leaves are replicated by construction — they
        # map to P() even when no rule covers their path
        tree = {"uncovered_scalar": np.float32(3.0),
                "size_one": np.zeros((1, 1)),
                "tokens": np.zeros((8, 2), np.int32)}
        specs = match_partition_rules(self.RULES, tree)
        assert specs["uncovered_scalar"] == P()
        assert specs["size_one"] == P()
        assert specs["tokens"] == WORKER_ROWS

    def test_unmatched_array_leaf_raises(self):
        with pytest.raises(ValueError, match="mystery"):
            match_partition_rules(self.RULES, {"mystery": np.zeros(8)})

    def test_prefix_joins_paths(self):
        specs = match_partition_rules(
            self.RULES, {"qkv": {"kernel": np.zeros((4, 4))}},
            prefix="state")
        assert specs["qkv"]["kernel"] == P(None, "tp")

    def test_match_report_counts_and_normalization(self):
        rules = (
            (r"^a$", P("w")),
            (r"a", REPLICATED),           # overlaps ^a$ -> n_matches 2
            (r"^b$", P("tp", None)),      # unnormalized on purpose
        )
        rows = {r["path"]: r for r in match_report(
            rules, [("a", np.zeros(4)), ("b", np.zeros(4)),
                    ("c", np.zeros(4)), ("s", np.float32(0))])}
        assert rows["a"]["n_matches"] == 2
        assert rows["a"]["spec"] == str(P("w"))  # first match reported
        assert rows["b"]["normalized"] is False
        assert rows["c"]["n_matches"] == 0 and rows["c"]["spec"] is None
        assert "s" not in rows  # scalars excluded


class TestOverride:
    def test_override_drops_the_original_row(self):
        new = override(SP_STEP_RULES, (r"^tokens$", REPLICATED))
        assert sum(1 for p, _ in new if p == r"^tokens$") == 1
        assert dict(new)[r"^tokens$"] == REPLICATED
        # untouched rows survive in order
        assert dict(new)[r"^adv_mask$"] == WORKER_ROWS


ROUTE_PATHS = {
    "cnn": (CNN_STEP_RULES,
            ["state/params/conv1/kernel", "state/step",
             "state/opt_state/0/momentum_buf/conv1/kernel",
             "state/batch_stats/bn1/mean", "x", "y", "adv_mask"]),
    "sp": (SP_STEP_RULES,
           ["state/params/block0/qkv/kernel",
            "state/opt_state/0/momentum_buf/block0/qkv/kernel",
            "tokens", "adv_mask"]),
    "tp": (TP_STEP_RULES,
           ["state/params/block0/qkv/kernel",
            "state/params/block0/proj/kernel",
            "state/params/block0/mlp_in/kernel",
            "state/params/block0/mlp_in/bias",
            "state/params/block0/mlp_out/kernel",
            "state/params/block0/mlp_out/bias",
            "state/params/embed/embedding",
            "state/opt_state/0/momentum_buf/block0/qkv/kernel",
            "tokens", "adv_mask"]),
    "ep": (EP_STEP_RULES,
           ["state/params/block0/moe/w1",
            "state/params/block0/moe/b2",
            "state/params/block0/moe/router/kernel",
            "state/opt_state/0/momentum_buf/block0/moe/w1",
            "tokens", "adv_mask"]),
    "pp": (PP_STEP_RULES,
           ["state/params/blocks/loop/b/attn/qkv/kernel",
            "state/params/embed/embedding",
            "state/opt_state/0/momentum_buf/blocks/loop/b/attn/qkv/kernel",
            "tokens", "adv_mask"]),
    "tree": (tree_combine_rules(("tl1", "tl2")),
             ["r_re", "r_im", "rand_factor", "present"]),
}


@pytest.mark.parametrize("route", sorted(ROUTE_PATHS))
def test_route_tables_are_disjoint_on_representative_paths(route):
    """Exactly-one-match is rule 7's coverage invariant: the negative
    lookaheads keep each table's rows DISJOINT, so a leaf's spec never
    depends on table order."""
    rules, paths = ROUTE_PATHS[route]
    for path in paths:
        n = sum(1 for pat, _ in rules if re.search(pat, path))
        assert n == 1, (route, path, n)


def test_tp_table_matches_megatron_layout():
    specs = dict(
        (p, next(s for pat, s in TP_STEP_RULES if re.search(pat, p)))
        for p in ROUTE_PATHS["tp"][1])
    assert specs["state/params/block0/qkv/kernel"] == P(None, "tp")
    assert specs["state/params/block0/proj/kernel"] == P("tp")
    assert specs["state/params/block0/mlp_in/bias"] == P("tp")
    assert specs["state/params/block0/mlp_out/bias"] == REPLICATED
    assert specs["state/params/embed/embedding"] == REPLICATED
    # momentum slots inherit the layout (prefix-insensitive patterns)
    assert specs["state/opt_state/0/momentum_buf/block0/qkv/kernel"] \
        == P(None, "tp")


def test_tree_rows_reverses_level_axes():
    # C-order folding: dim 0 over the REVERSED level axes so leaf group j
    # lands at grid multi-index unravel(j) (coding/topology.tree_mesh)
    assert tree_rows(("tl1", "tl2")) == P(("tl2", "tl1"))
    assert spec_axes(tree_rows(("tl1", "tl2"))) == {"tl1", "tl2"}
