"""Tensor parallelism (GSPMD param-sharding path): exactness vs tp=1,
actual shard placement, and coded-DP composition on the (w, tp) mesh."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from draco_tpu.config import TrainConfig
from draco_tpu.parallel import TP_AXIS, make_mesh_wtp
from draco_tpu.parallel.tp_step import (
    build_tp_train_setup,
    param_partition_spec,
    train_tp,
)


def _tp_cfg(**kw):
    base = dict(
        network="TransformerLM", dataset="synthetic-text", batch_size=2,
        num_workers=4, tensor_shards=2, seq_len=32, vocab=32, model_dim=32,
        model_heads=4, model_layers=1, approach="baseline", mode="normal",
        worker_fail=0, max_steps=3, lr=0.05, momentum=0.9, eval_freq=0,
        train_dir="", log_every=1000,
    )
    base.update(kw)
    return TrainConfig(**base)


def _flat(params):
    return np.concatenate([np.ravel(x) for x in jax.tree.leaves(params)])


def test_partition_rules():
    """Megatron rules: column-parallel qkv/mlp_in, row-parallel proj/mlp_out,
    everything else replicated."""
    cfg = _tp_cfg()
    mesh = make_mesh_wtp(4, 2)
    setup = build_tp_train_setup(cfg, mesh)
    seen = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(setup.state.params)[0]:
        names = [getattr(k, "key", str(k)) for k in path]
        seen["/".join(names)] = (param_partition_spec(path), leaf.sharding.spec)
    assert seen["block0/qkv/kernel"][0] == P(None, TP_AXIS)
    assert seen["block0/proj/kernel"][0] == P(TP_AXIS, None)
    assert seen["block0/mlp_in/kernel"][0] == P(None, TP_AXIS)
    assert seen["block0/mlp_out/kernel"][0] == P(TP_AXIS, None)
    assert seen["embed/embedding"][0] == P()
    # the placement actually applied, not just computed — in the NORMALIZED
    # spelling (trailing Nones stripped, tp_step._norm_spec): the applied
    # shardings are pinned to the form XLA reports back, so the K-fused
    # carry cannot retrace against its own output layout (PERF.md §9)
    from draco_tpu.parallel.tp_step import _norm_spec

    for key, (want, got) in seen.items():
        assert got == _norm_spec(want), (key, want, got)


def test_tp_matches_single_shard():
    """(4 w × 2 tp) and (4 w × 1 tp) must produce the same trajectory —
    tensor parallelism is a layout choice, not a math change."""
    mesh_tp = make_mesh_wtp(4, 2)
    state_tp, m_tp = train_tp(_tp_cfg(), mesh_tp, steps=3, quiet=True)

    mesh_1 = make_mesh_wtp(4, 1, devices=jax.devices()[:4])
    state_1, m_1 = train_tp(_tp_cfg(tensor_shards=1), mesh_1, steps=3, quiet=True)

    np.testing.assert_allclose(float(m_tp["loss"]), float(m_1["loss"]), rtol=1e-4)
    np.testing.assert_allclose(
        _flat(jax.device_get(state_tp.params)),
        _flat(jax.device_get(state_1.params)),
        rtol=1e-3, atol=1e-5,
    )


def test_tp_params_stay_sharded_after_steps():
    cfg = _tp_cfg()
    mesh = make_mesh_wtp(4, 2)
    state, _ = train_tp(cfg, mesh, steps=2, quiet=True)
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    qkv = next(l for p, l in flat
               if [getattr(k, "key", "") for k in p][-2:] == ["qkv", "kernel"])
    assert qkv.sharding.spec == P(None, TP_AXIS)


def test_tp_geomedian_under_attack():
    """Robust aggregation composed with tensor parallelism: (4 w × 2 tp),
    one rev_grad adversary, geometric median — finite and progressing.
    (Cyclic × tp needs n > 4s mesh rows, i.e. ≥ 10 devices with tp=2 —
    exercised by dryrun_multichip(16) instead; the 8-device CI mesh only
    fits w=4 × tp=2.)"""
    cfg = _tp_cfg(mode="geometric_median", worker_fail=1, err_mode="rev_grad")
    mesh = make_mesh_wtp(4, 2)
    state, metrics = train_tp(cfg, mesh, steps=6, quiet=True)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 7


def test_tp_validation():
    with pytest.raises(ValueError, match="tensor_shards"):
        _tp_cfg(tensor_shards=3).validate()
    with pytest.raises(ValueError, match="separate paths"):
        _tp_cfg(tensor_shards=2, seq_shards=2).validate()


def test_tp_cyclic_simulate_matches_shared():
    """Reference-parity r× redundant compute (redundancy='simulate',
    cyclic_worker.py:122-146) and the one-copy 'shared' fast path must give
    the same trajectory — per-batch gradients are deterministic under XLA,
    so the encoded rows are algebraically identical. n=8 workers fold onto
    the (w=4 × tp=2) mesh, 2 lanes/device; one live rev_grad adversary is
    decoded away in both."""
    kw = dict(num_workers=8, approach="cyclic", worker_fail=1,
              err_mode="rev_grad")
    mesh = make_mesh_wtp(4, 2)
    st_sim, m_sim = train_tp(_tp_cfg(redundancy="simulate", **kw), mesh,
                             steps=3, quiet=True)
    st_sh, m_sh = train_tp(_tp_cfg(redundancy="shared", **kw), mesh,
                           steps=3, quiet=True)
    np.testing.assert_allclose(float(m_sim["loss"]), float(m_sh["loss"]),
                               rtol=1e-4)
    np.testing.assert_allclose(
        _flat(jax.device_get(st_sim.params)),
        _flat(jax.device_get(st_sh.params)),
        rtol=1e-3, atol=1e-5,
    )


def test_tp_folded_accepts_flash():
    """The folded (tp=1) LM regime — what the perf/convergence tools run —
    accepts attn_impl=flash; the kernel (dense fallback off-TPU) slots in
    as the Block attention with an unchanged training contract."""
    import numpy as np

    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel.mesh import make_folded_wtp_mesh
    from draco_tpu.parallel.sp_step import synthetic_text
    from draco_tpu.parallel.tp_step import build_tp_train_setup

    cfg = TrainConfig(
        network="TransformerLM", dataset="synthetic-text", batch_size=2,
        num_workers=4, approach="baseline", mode="normal", worker_fail=0,
        seq_len=16, vocab=32, model_dim=32, model_heads=2, model_layers=1,
        attn_impl="flash", max_steps=2, eval_freq=0,
        train_dir="", log_every=1000,
    )
    cfg.validate()
    mesh = make_folded_wtp_mesh(4)
    setup = build_tp_train_setup(cfg, mesh)
    toks = synthetic_text(cfg.seed, 1, 4, 2, 16, 32)
    import jax.numpy as jnp
    st, metrics = setup.train_step(setup.state, jnp.asarray(toks),
                                   jnp.zeros((4,), bool))
    assert np.isfinite(float(metrics["loss"]))
