#!/usr/bin/env python
"""Perf-regression watch: fold the committed round artifacts into one flat
metric set and diff it against the committed baseline snapshot.

VERDICT r4's core complaint is that evidence does not accumulate across
rounds: every BENCH_r*.json is a point measurement and nothing notices when
a round's ms/step, module bytes, peak-memory estimate, or compile time
quietly drifts from the last committed state. This tool is the accumulation
point — jax-free (pure artifact folding, runs on a laptop against scp'd
files), so it can gate a round without touching a backend:

  python tools/perf_watch.py --snapshot        # (re)write the baseline
                                               #  baselines_out/perf_watch.json
  python tools/perf_watch.py                   # diff current artifacts vs
                                               #   baseline; exit 1 on any
                                               #   out-of-tolerance regression
  python tools/perf_watch.py --json report.json

Folded sources (all optional — a missing artifact folds nothing):

  BENCH_r*.json                 driver bench records (the tail's last JSON
                                line per metric, highest round wins):
                                ms/step, vs_baseline ratio, flops/step, and
                                the compile_ms field bench.py now records
  MULTICHIP_r*.json             the multichip dry-run verdict (ok flag +
                                device count)
  baselines_out/host_loop_overhead*.json
                                the K-sweep: eager & per-K steady-state
                                ms/step, plus the compile-vs-steady split
                                (compile_ms / timed-run builds per K)
  baselines_out/program_lint.json
                                per-program module bytes (constant_bloat
                                rule), the memory/cost ledger columns
                                (memory_budget rule: peak_bytes, flops)
                                and the per-axis collective wire ledger
                                (collective_axes rule: ops/bytes per mesh
                                axis, pinned at tolerance 0)
  baselines_out/chaos_matrix.json
                                the resilience fault × loop matrix
                                (tools/chaos_run.py): per-cell ok flags —
                                a fault class silently flipping from
                                masked/guarded to FAILED gates nonzero
                                (kind "ok", tolerance 0)
  baselines_out/straggler_study.json
                                the exact-vs-approx crossover sweep
                                (tools/straggler_study.py, ISSUE 8):
                                per-cell reached_target /
                                residual_within_bound / full-recovery
                                bools at tolerance 0 (a residual
                                exceeding its analytic bound is never
                                noise), feasibility flags pinned in BOTH
                                directions (kind "pinned" — a budget-
                                infeasible cell silently becoming
                                feasible is a semantic change, not an
                                improvement), wall ms/step at the time
                                tolerance
  baselines_out/autopilot_study.json
                                the adaptive-autopilot-vs-fixed scenario
                                study (tools/autopilot_study.py, ISSUE
                                14): beats-fixed / remediation-
                                attribution / quarantine-clean
                                certificates at tolerance 0, cell
                                feasibility pinned both directions
  baselines_out/wire_study.json
                                the shadow-quantized wire matrix
                                (tools/wire_study.py, ISSUE 10): shadow
                                residual / flag agreement pinned at
                                tolerance 0 (deterministic decode of a
                                deterministically quantized wire), shadow
                                detection P/R + det_preserved as
                                0-tolerance ok flags, logical wire bytes
                                at the bytes tolerance
  baselines_out/segment_study.json
                                the streaming segmented wire's pipeline
                                evidence (tools/segment_study.py, ISSUE
                                16): the winning S>1 cell's positive
                                overlap fraction and ms/step win as
                                0-tolerance ok flags, the measured
                                fractions at the ratio tolerance, segment
                                counts + per-segment physical bytes
                                pinned tolerance-0 in both directions
  baselines_out/tree_study.json
                                the hierarchical tree-aggregation
                                evidence (tools/tree_study.py, ISSUE 17):
                                the per-cell win / bytes_ok / detection-
                                parity bools at tolerance 0, the
                                crossover n pinned in both directions,
                                per-LEVEL ingest bytes pinned tolerance-0
                                both ways (the leaf level must keep
                                summing exactly to the flat per-step
                                bytes), decode/critical-path ms at the
                                time tolerance
  baselines_out/decode_kernel_bench.json
                                the fused-decode microbench
                                (tools/decode_kernel_bench.py, ISSUE 12):
                                per-rung xla/pallas decode ms and their
                                ratio at the time tolerance, plus the
                                gated rungs' kernel_not_slower flag at
                                tolerance 0 — the fused path regressing
                                slower than the XLA path at a committed
                                rung fails the round
  baselines_out/device_profile.json
                                the device-time attribution ledger
                                (tools/device_profile.py, ISSUE 9):
                                per-cell draco phase shares at the time
                                tolerance (decode-share regressions gate),
                                explicit-collective instruction/byte
                                counts pinned at tolerance 0 both ways,
                                manifest cross-check + seeded mismatch
                                control as 0-tolerance ok flags

Tolerances are per metric KIND (relative change vs baseline): time metrics
default 10% (ms/step, a 20% regression trips loudly), bytes 10%, flops 2%
(analytic flops should not drift at all without an algorithm change),
ratios (higher-better) 10%, compile time 50% (host-load noisy), booleans 0
(a multichip ok that goes false is always a regression). Improvements and
new metrics are reported, never fatal; metrics that disappear are reported
as missing (fatal only under --strict-missing, so artifact sets can evolve).

Exit codes: 0 clean / snapshot written; 1 regression(s); 2 no baseline
(run --snapshot first and commit it).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

SNAPSHOT_REL = os.path.join("baselines_out", "perf_watch.json")

# metric kinds: comparison direction + default relative tolerance
KINDS = {
    "time_ms": {"dir": "lower_better", "tol": 0.10},
    "compile_ms": {"dir": "lower_better", "tol": 0.50},
    "bytes": {"dir": "lower_better", "tol": 0.10},
    "flops": {"dir": "lower_better", "tol": 0.02},
    "count": {"dir": "lower_better", "tol": 0.0},  # e.g. steady-state builds
    "ratio": {"dir": "higher_better", "tol": 0.10},
    "ok": {"dir": "higher_better", "tol": 0.0},
    # semantic flags with no good direction: ANY flip is a regression
    # (e.g. a budget-infeasible straggler cell silently becoming feasible)
    "pinned": {"dir": "equal", "tol": 0.0},
}


def _read_json(path):
    try:
        with open(path) as fh:
            return json.load(fh)
    except Exception:
        return None


def _tail_records(tail: str) -> list:
    """The structured JSON lines a bench emitted into the driver tail."""
    out = []
    for line in (tail or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except Exception:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            out.append(rec)
    return out


def _round_of(path: str):
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else -1


def fold_bench(root: str, metrics: dict) -> None:
    """Latest round's record per bench metric name (the driver keeps the
    tail line, so the LAST record in a tail is the most complete one)."""
    latest: dict = {}  # metric name -> (round, record)
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        data = _read_json(path)
        if not isinstance(data, dict):
            continue
        rnd = _round_of(path)
        for rec in _tail_records(data.get("tail", "")):
            name = rec["metric"]
            if name not in latest or rnd >= latest[name][0]:
                latest[name] = (rnd, rec)
    for name, (rnd, rec) in sorted(latest.items()):
        src = f"BENCH_r{rnd:02d}"
        extra = rec.get("extra") or {}
        if isinstance(rec.get("value"), (int, float)):
            metrics[f"bench.{name}.ms_per_step"] = {
                "value": float(rec["value"]), "kind": "time_ms",
                "source": src}
        if isinstance(rec.get("vs_baseline"), (int, float)):
            metrics[f"bench.{name}.vs_baseline"] = {
                "value": float(rec["vs_baseline"]), "kind": "ratio",
                "source": src}
        if isinstance(extra.get("flops_per_step"), (int, float)):
            metrics[f"bench.{name}.flops_per_step"] = {
                "value": float(extra["flops_per_step"]), "kind": "flops",
                "source": src}
        if isinstance(extra.get("compile_ms"), (int, float)):
            metrics[f"bench.{name}.compile_ms"] = {
                "value": float(extra["compile_ms"]), "kind": "compile_ms",
                "source": src}
        if isinstance(extra.get("wire_bytes"), (int, float)):
            # logical codeword bytes per step (obs/numerics.wire_ledger,
            # ISSUE 10) — the series that will show the item-4 win when
            # the real narrow wire lands
            metrics[f"bench.{name}.wire_bytes"] = {
                "value": float(extra["wire_bytes"]), "kind": "bytes",
                "source": src}


def fold_multichip(root: str, metrics: dict) -> None:
    paths = sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json")),
                   key=_round_of)
    if not paths:
        return
    data = _read_json(paths[-1])
    if not isinstance(data, dict):
        return
    src = os.path.basename(paths[-1]).rsplit(".", 1)[0]
    if "ok" in data:
        metrics["multichip.ok"] = {"value": float(bool(data["ok"])),
                                   "kind": "ok", "source": src}
    if isinstance(data.get("n_devices"), (int, float)):
        metrics["multichip.n_devices"] = {
            "value": float(data["n_devices"]), "kind": "ratio", "source": src}


def fold_host_loop(root: str, metrics: dict) -> None:
    for fname, mode in (("host_loop_overhead.json", "cnn"),
                        ("host_loop_overhead_lm.json", "lm")):
        path = os.path.join(root, "baselines_out", fname)
        data = _read_json(path)
        if not isinstance(data, dict):
            continue
        src = f"baselines_out/{fname}"
        rows = data.get("ms_per_step_by_steps_per_call") or {}
        for k, ms in sorted(rows.items(), key=lambda kv: int(kv[0])):
            if isinstance(ms, (int, float)):
                metrics[f"host_loop.{mode}.k{k}_ms_per_step"] = {
                    "value": float(ms), "kind": "time_ms", "source": src}
        for k, ms in sorted((data.get("compile_ms_by_steps_per_call")
                             or {}).items(), key=lambda kv: int(kv[0])):
            if isinstance(ms, (int, float)):
                metrics[f"host_loop.{mode}.k{k}_compile_ms"] = {
                    "value": float(ms), "kind": "compile_ms", "source": src}
        for k, n in sorted((data.get("timed_builds_by_steps_per_call")
                            or {}).items(), key=lambda kv: int(kv[0])):
            if isinstance(n, (int, float)):
                # steady-state executable builds during the timed window —
                # must stay 0; any growth is a retrace regression
                metrics[f"host_loop.{mode}.k{k}_timed_builds"] = {
                    "value": float(n), "kind": "count", "source": src}


def fold_program_lint(root: str, metrics: dict) -> None:
    path = os.path.join(root, "baselines_out", "program_lint.json")
    data = _read_json(path)
    if not isinstance(data, dict):
        return
    src = "baselines_out/program_lint.json"
    if "all_ok" in data:
        metrics["lint.all_ok"] = {"value": float(bool(data["all_ok"])),
                                  "kind": "ok", "source": src}
    for row in data.get("rows", []):
        if row.get("control"):
            continue
        name = row.get("name")
        rules = row.get("rules") or {}
        module_bytes = (rules.get("constant_bloat") or {}).get("module_bytes")
        if isinstance(module_bytes, (int, float)):
            metrics[f"lint.{name}.module_bytes"] = {
                "value": float(module_bytes), "kind": "bytes", "source": src}
        mem = (rules.get("memory_budget") or {}).get("memory") or {}
        if isinstance(mem.get("peak_bytes"), (int, float)):
            metrics[f"lint.{name}.peak_bytes"] = {
                "value": float(mem["peak_bytes"]), "kind": "bytes",
                "source": src}
        flops = (rules.get("memory_budget") or {}).get("flops")
        if isinstance(flops, (int, float)):
            metrics[f"lint.{name}.flops"] = {
                "value": float(flops), "kind": "flops", "source": src}
        # the per-axis wire ledger (sharding auditor, rule 8): ops and
        # bytes per mesh axis are structural — ANY drift is a topology
        # change, so they ride pinned (tol 0) in both directions
        ledger = (rules.get("collective_axes") or {}).get("axis_ledger")
        for axis, led in sorted((ledger or {}).items()):
            for col in ("ops", "bytes"):
                if isinstance(led.get(col), (int, float)):
                    metrics[f"lint.{name}.coll.{axis}.{col}"] = {
                        "value": float(led[col]), "kind": "pinned",
                        "source": src}


def fold_chaos(root: str, metrics: dict) -> None:
    """Resilience chaos matrix: one ok-flag per (loop, fault) cell plus the
    roll-up — masked→crashed is a 1→0 flip on a 0-tolerance "ok" metric.
    Worker-targeted cells additionally carry a forensics ``attributed``
    flag (the accused set named every injected worker, tools/chaos_run.py):
    an attribution silently flipping false gates at tolerance 0 too.
    Every cell now also carries an ``incident`` verdict (obs/incidents.py,
    ISSUE 13 — the expected incident type raised with the right worker
    attribution, nothing spurious): the per-cell ``incident.ok`` folds at
    tolerance 0, so a detector silently going blind (or flapping) on a
    committed fault class gates nonzero — the flipped-row control test in
    tests/test_cli_tools.py proves that gate live."""
    path = os.path.join(root, "baselines_out", "chaos_matrix.json")
    data = _read_json(path)
    if not isinstance(data, dict):
        return
    src = "baselines_out/chaos_matrix.json"
    if "all_ok" in data:
        metrics["chaos.all_ok"] = {"value": float(bool(data["all_ok"])),
                                   "kind": "ok", "source": src}
    for row in data.get("rows", []):
        loop, fault = row.get("loop"), row.get("fault")
        if not loop or not fault:
            continue
        metrics[f"chaos.{loop}.{fault}.ok"] = {
            "value": float(bool(row.get("ok"))), "kind": "ok",
            "source": src}
        if "attributed" in row:
            metrics[f"chaos.{loop}.{fault}.attributed"] = {
                "value": float(bool(row["attributed"])), "kind": "ok",
                "source": src}
        # ISSUE 10 NaN-safety flags on the nan_grad cells: the numerics
        # columns staying finite-sentineled (and the fault staying
        # visible in the nonfinite fraction) gate at tolerance 0 too
        for flag in ("numerics_finite", "fault_visible"):
            if flag in row:
                metrics[f"chaos.{loop}.{fault}.{flag}"] = {
                    "value": float(bool(row[flag])), "kind": "ok",
                    "source": src}
        # ISSUE 13 incident verdict: the cell's expected incident type
        # raised + attributed, nothing spurious — 0-tolerance gate
        if isinstance(row.get("incident"), dict):
            metrics[f"chaos.{loop}.{fault}.incident_ok"] = {
                "value": float(bool(row["incident"].get("ok"))),
                "kind": "ok", "source": src}


def fold_straggler(root: str, metrics: dict) -> None:
    """Straggler-study crossover artifact (tools/straggler_study.py): the
    certificate bools gate at tolerance 0 — a cell whose measured residual
    creeps past its analytic bound, stops reaching the target loss, or
    loses full batch recovery is a correctness regression, never noise.
    The wall column rides at the ordinary time tolerance. Infeasible cells
    (exact-code budget exceeded) fold only their feasibility flag — a
    budget-exceeded scenario silently becoming "feasible" (or vice versa)
    is a semantic change worth tripping on too."""
    path = os.path.join(root, "baselines_out", "straggler_study.json")
    data = _read_json(path)
    if not isinstance(data, dict):
        return
    src = "baselines_out/straggler_study.json"
    if "all_ok" in data:
        metrics["straggler.all_ok"] = {
            "value": float(bool(data["all_ok"])), "kind": "ok",
            "source": src}
    for row in data.get("rows", []):
        family, drops = row.get("family"), row.get("drop_count")
        if family is None or drops is None:
            continue
        key = f"straggler.{family}.e{drops}"
        metrics[f"{key}.feasible"] = {
            "value": float(bool(row.get("feasible"))), "kind": "pinned",
            "source": src}
        if not row.get("feasible"):
            continue
        for flag in ("reached_target", "residual_within_bound"):
            metrics[f"{key}.{flag}"] = {
                "value": float(bool(row.get(flag))), "kind": "ok",
                "source": src}
        if isinstance(row.get("recovered_fraction_min"), (int, float)):
            # ok-kind at its raw value: any coverage LOSS gates at 0
            # tolerance, recoveries never do (higher_better)
            metrics[f"{key}.recovered_fraction_min"] = {
                "value": float(row["recovered_fraction_min"]),
                "kind": "ok", "source": src}
        if isinstance(row.get("ms_per_step"), (int, float)):
            metrics[f"{key}.ms_per_step"] = {
                "value": float(row["ms_per_step"]), "kind": "time_ms",
                "source": src}


def fold_autopilot(root: str, metrics: dict) -> None:
    """Autopilot-study artifact (tools/autopilot_study.py, ISSUE 14): the
    adaptive-control certificates gate at tolerance 0 — the autopilot
    beating every fixed configuration on compute-to-target
    (``beats_fixed``), every remediation naming its triggering incident
    (``remediations_attributed``), the dial actually moving both
    directions, and the quarantined worker never corrupting the aggregate
    (``quarantine_clean``). Cell feasibility is pinned BOTH directions:
    the fixed-approx row silently becoming feasible under the adversary
    scenario would mean the family's Byzantine-certificate validation
    regressed."""
    path = os.path.join(root, "baselines_out", "autopilot_study.json")
    data = _read_json(path)
    if not isinstance(data, dict):
        return
    src = "baselines_out/autopilot_study.json"
    for flag in ("all_ok", "autopilot_beats_fixed"):
        if flag in data:
            metrics[f"autopilot.{flag}"] = {
                "value": float(bool(data[flag])), "kind": "ok",
                "source": src}
    for row in data.get("rows", []):
        cell = row.get("cell")
        if not cell:
            continue
        key = f"autopilot.{cell}"
        metrics[f"{key}.feasible"] = {
            "value": float(bool(row.get("feasible"))), "kind": "pinned",
            "source": src}
        if not row.get("feasible"):
            continue
        metrics[f"{key}.reached_target"] = {
            "value": float(bool(row.get("reached_target"))), "kind": "ok",
            "source": src}
        for flag in ("remediations_attributed", "dialed_down", "dialed_up",
                     "quarantine_clean"):
            if flag in row:
                metrics[f"{key}.{flag}"] = {
                    "value": float(bool(row[flag])), "kind": "ok",
                    "source": src}


def fold_wire_study(root: str, metrics: dict) -> None:
    """Wire-study artifact (tools/wire_study.py, ISSUES 10 + 15): the
    shadow residual and flag-agreement columns are PINNED at tolerance 0
    in both directions — a deterministic seeded decode of a
    deterministically quantized wire moving AT ALL is a semantic change
    (the flipped-row control in tests/test_cli_tools.py proves the gate
    live). The detection-preserved bool and shadow detection P/R gate as
    0-tolerance ok-kind; wire bytes ride at the bytes tolerance so a
    ledger drift (dim change) shows up without gating honest model edits.
    The ISSUE 15 REAL-wire rows add narrow-wire detection P/R (ok-kind),
    the pinned end-to-end error, and PHYSICAL bytes/worker; the locator
    cells pin the n=32 s=3 blocker certificate in both directions."""
    path = os.path.join(root, "baselines_out", "wire_study.json")
    data = _read_json(path)
    if not isinstance(data, dict):
        return
    src = "baselines_out/wire_study.json"
    if "all_ok" in data:
        metrics["wire.all_ok"] = {"value": float(bool(data["all_ok"])),
                                  "kind": "ok", "source": src}
    for row in data.get("rows", []):
        mode = row.get("mode", "shadow")
        if mode == "locator":
            # ISSUE 15 locator cells: the blocker certificate is PINNED in
            # both directions — the λ=0 row silently becoming usable means
            # the exact path changed; the regularized row losing usability
            # means the blocker is back. Margins pin too (deterministic
            # seeded trials).
            n, s, dtype = row.get("n"), row.get("s"), row.get("dtype")
            if n is None or dtype is None:
                continue
            reg = "reg" if row.get("regularized") else "unreg"
            key = f"wire.locator.n{n}s{s}.{dtype}.{reg}"
            metrics[f"{key}.usable"] = {
                "value": float(bool(row.get("usable"))), "kind": "pinned",
                "source": src}
            for col in ("honest_dev_max_noadv", "adv_dev_min"):
                if isinstance(row.get(col), (int, float)):
                    metrics[f"{key}.{col}"] = {
                        "value": float(row[col]), "kind": "pinned",
                        "source": src}
            continue
        fam, dtype, k = row.get("family"), row.get("dtype"), row.get("k")
        if fam is None or dtype is None or k is None:
            continue
        if mode == "real":
            # ISSUE 15 real-wire rows: detection P/R on the narrow wire's
            # own flags + the end-to-end error pinned at tolerance 0
            # (deterministic seeded runs of a deterministic quantizer);
            # PHYSICAL bytes at the bytes tolerance
            key = f"wire.real.{fam}.{dtype}.k{k}"
            for col in ("det_precision", "det_recall"):
                if isinstance(row.get(col), (int, float)):
                    metrics[f"{key}.{col}"] = {
                        "value": float(row[col]), "kind": "ok",
                        "source": src}
            if isinstance(row.get("end_to_end_err"), (int, float)):
                metrics[f"{key}.end_to_end_err"] = {
                    "value": float(row["end_to_end_err"]),
                    "kind": "pinned", "source": src}
            metrics[f"{key}.det_preserved"] = {
                "value": float(bool(row.get("det_preserved"))),
                "kind": "ok", "source": src}
            w = row.get("wire") or {}
            if isinstance(w.get("physical_bytes_per_worker"),
                          (int, float)):
                metrics[f"{key}.physical_bytes_per_worker"] = {
                    "value": float(w["physical_bytes_per_worker"]),
                    "kind": "bytes", "source": src}
            continue
        key = f"wire.{fam}.{dtype}.k{k}"
        for col in ("shadow_err_max", "shadow_residual_max",
                    "shadow_flag_agree_min"):
            if isinstance(row.get(col), (int, float)):
                metrics[f"{key}.{col}"] = {
                    "value": float(row[col]), "kind": "pinned",
                    "source": src}
        metrics[f"{key}.det_preserved"] = {
            "value": float(bool(row.get("det_preserved"))), "kind": "ok",
            "source": src}
        for col in ("det_precision_shadow", "det_recall_shadow"):
            if isinstance(row.get(col), (int, float)):
                metrics[f"{key}.{col}"] = {
                    "value": float(row[col]), "kind": "ok", "source": src}
        per = (row.get("wire") or {}).get("bytes_per_worker") or {}
        if isinstance(per.get(dtype), (int, float)):
            metrics[f"{key}.bytes_per_worker"] = {
                "value": float(per[dtype]), "kind": "bytes", "source": src}


def fold_segment_study(root: str, metrics: dict) -> None:
    """Segment-study artifact (tools/segment_study.py, ISSUE 16): the
    streaming segmented wire's pipeline evidence. The ACCEPTANCE bools
    gate at tolerance 0 — the winning pipelined S>1 cell must keep a
    strictly positive wire/decode overlap fraction and a strictly
    positive ms/step win over the S=1 base (the flipped-row control in
    tests/test_segments.py proves both gates live). The measured overlap
    and win fractions ride as ratio-kind (wall-clock noisy, 10%); the
    per-cell segment COUNTS and per-segment physical bytes are PINNED at
    tolerance 0 in BOTH directions — a segment silently appearing,
    vanishing, or changing size is a wire-format change, never noise.
    S=1 rows pin overlap at exactly 0: the no-pipeline base measuring
    overlap would mean the overlap metric itself broke."""
    path = os.path.join(root, "baselines_out", "segment_study.json")
    data = _read_json(path)
    if not isinstance(data, dict):
        return
    src = "baselines_out/segment_study.json"
    if "all_ok" in data:
        metrics["segment.all_ok"] = {"value": float(bool(data["all_ok"])),
                                     "kind": "ok", "source": src}
    win = data.get("win") or {}
    if win:
        metrics["segment.win.positive"] = {
            "value": float(float(win.get("ms_per_step_win", 0.0)) > 0.0),
            "kind": "ok", "source": src}
        metrics["segment.win.overlap_positive"] = {
            "value": float(float(win.get("overlap_frac", 0.0)) > 0.0),
            "kind": "ok", "source": src}
        for col in ("win_frac", "overlap_frac"):
            if isinstance(win.get(col), (int, float)):
                metrics[f"segment.win.{col}"] = {
                    "value": float(win[col]), "kind": "ratio",
                    "source": src}
    for row in data.get("rows", []):
        dtype, s = row.get("dtype"), row.get("segments")
        if dtype is None or s is None:
            continue
        key = f"segment.{dtype}.s{s}"
        if isinstance(row.get("ms_per_step"), (int, float)):
            metrics[f"{key}.ms_per_step"] = {
                "value": float(row["ms_per_step"]), "kind": "time_ms",
                "source": src}
        if s == 1:
            metrics[f"{key}.overlap_frac"] = {
                "value": float(row.get("overlap_frac", 0.0)),
                "kind": "pinned", "source": src}
        elif isinstance(row.get("overlap_frac"), (int, float)):
            metrics[f"{key}.overlap_frac"] = {
                "value": float(row["overlap_frac"]), "kind": "ratio",
                "source": src}
        seg = (row.get("wire") or {}).get("segments") or {}
        if isinstance(seg.get("count"), (int, float)):
            metrics[f"{key}.segments_count"] = {
                "value": float(seg["count"]), "kind": "pinned",
                "source": src}
        for i, b in enumerate(seg.get("physical_bytes_per_worker") or []):
            if isinstance(b, (int, float)):
                metrics[f"{key}.seg{i}_bytes_per_worker"] = {
                    "value": float(b), "kind": "pinned", "source": src}


def fold_tree_study(root: str, metrics: dict) -> None:
    """Tree-study artifact (tools/tree_study.py, ISSUE 17): the
    hierarchical CodedReduce evidence. The per-cell ACCEPTANCE bools gate
    at tolerance 0 — win (critical path beats flat decode), bytes_ok
    (leaf-level ingest sums exactly to the flat per-step bytes), and the
    detection-parity pin on every s_g >= 1 cell (tree flags == flat
    flags under the same live adversary; the flipped-row control in
    tests/test_tree.py proves the gate live). The crossover n and the
    per-LEVEL byte columns are PINNED in both directions — the tree
    silently winning earlier/later or a level's bytes moving at all is a
    topology/wire-format change, never noise. Decode and critical-path
    ms ride at the time tolerance."""
    path = os.path.join(root, "baselines_out", "tree_study.json")
    data = _read_json(path)
    if not isinstance(data, dict):
        return
    src = "baselines_out/tree_study.json"
    if "all_ok" in data:
        metrics["tree.all_ok"] = {"value": float(bool(data["all_ok"])),
                                  "kind": "ok", "source": src}
    cx = data.get("crossover") or {}
    for col in ("critical_path_n", "sequential_n"):
        if isinstance(cx.get(col), (int, float)):
            metrics[f"tree.crossover.{col}"] = {
                "value": float(cx[col]), "kind": "pinned", "source": src}
    for row in data.get("rows", []):
        n = row.get("n")
        if row.get("kind") == "flat":
            if isinstance(row.get("decode_ms"), (int, float)):
                metrics[f"tree.flat.n{n}.decode_ms"] = {
                    "value": float(row["decode_ms"]), "kind": "time_ms",
                    "source": src}
            continue
        g = row.get("fanout")
        if n is None or g is None:
            continue
        key = f"tree.n{n}.g{g}"
        for col, kind in (("critical_path_ms", "time_ms"),
                          ("leaf_decode_ms", "time_ms"),
                          ("sequential_total_ms", "time_ms")):
            if isinstance(row.get(col), (int, float)):
                metrics[f"{key}.{col}"] = {
                    "value": float(row[col]), "kind": kind, "source": src}
        metrics[f"{key}.win"] = {"value": float(bool(row.get("win"))),
                                 "kind": "ok", "source": src}
        metrics[f"{key}.bytes_ok"] = {
            "value": float(bool(row.get("bytes_ok"))), "kind": "ok",
            "source": src}
        det = row.get("detection") or {}
        if det.get("checked"):
            metrics[f"{key}.detection_ok"] = {
                "value": float(bool(det.get("ok"))), "kind": "ok",
                "source": src}
            for col in ("precision_tree", "recall_tree"):
                if isinstance(det.get(col), (int, float)):
                    metrics[f"{key}.{col}"] = {
                        "value": float(det[col]), "kind": "ok",
                        "source": src}
        tb = (row.get("ledger") or {}).get("tree") or {}
        for i, b in enumerate(tb.get("level_bytes_per_step") or []):
            if isinstance(b, (int, float)):
                metrics[f"{key}.level{i}_bytes_per_step"] = {
                    "value": float(b), "kind": "pinned", "source": src}


def fold_decode_bench(root: str, metrics: dict) -> None:
    """Fused-decode microbench (tools/decode_kernel_bench.py, ISSUE 12):
    absolute per-impl decode times and the pallas/xla ratio ride at the
    time tolerance; gated rungs additionally pin ``kernel_not_slower``
    (ratio ≤ 1) as a 0-tolerance ok flag — the flipped-row test in
    tests/test_cli_tools.py proves that gate live."""
    path = os.path.join(root, "baselines_out", "decode_kernel_bench.json")
    data = _read_json(path)
    if not isinstance(data, dict):
        return
    src = "baselines_out/decode_kernel_bench.json"
    if "all_ok" in data:
        metrics["decode_bench.all_ok"] = {
            "value": float(bool(data["all_ok"])), "kind": "ok",
            "source": src}
    for row in data.get("rows", []):
        rung = row.get("rung")
        if not rung:
            continue
        key = f"decode_bench.{rung}"
        for col in ("xla_ms", "pallas_ms", "pallas_over_xla"):
            if isinstance(row.get(col), (int, float)):
                metrics[f"{key}.{col}"] = {
                    "value": float(row[col]), "kind": "time_ms",
                    "source": src}
        if "kernel_not_slower" in row:
            metrics[f"{key}.kernel_not_slower"] = {
                "value": float(bool(row["kernel_not_slower"])),
                "kind": "ok", "source": src}


def fold_device_profile(root: str, metrics: dict) -> None:
    """Device-time attribution artifact (tools/device_profile.py, ISSUE 9):
    per-cell phase SHARES at the ordinary time tolerance — a decode-share
    creep past 10% relative is exactly the regression ROADMAP items 1-2
    must develop under — and the explicit-collective instruction/byte
    ledger pinned at tolerance 0 in BOTH directions (the runtime trace and
    the static Manifest must agree; a collective appearing OR vanishing is
    a semantic change, never noise). Cross-check flags and the seeded
    mismatch control gate as 0-tolerance ok-kind."""
    path = os.path.join(root, "baselines_out", "device_profile.json")
    data = _read_json(path)
    if not isinstance(data, dict):
        return
    src = "baselines_out/device_profile.json"
    if "all_ok" in data:
        metrics["device.all_ok"] = {"value": float(bool(data["all_ok"])),
                                    "kind": "ok", "source": src}
    for row in data.get("cells", []):
        cell = row.get("cell")
        if not cell:
            continue
        if row.get("control"):
            metrics[f"device.{cell}.tripped"] = {
                "value": float(bool(row.get("ok"))), "kind": "ok",
                "source": src}
            continue
        programs = row.get("programs", [])
        for pi, prog in enumerate(programs):
            # today every cell profiles ONE program and the key is the bare
            # cell; a multi-program cell suffixes the module so a second
            # program can never silently overwrite the first's gate rows
            base = f"device.{cell}" if len(programs) == 1 else \
                f"device.{cell}.{prog.get('module') or pi}"
            for phase in ("draco_comp", "draco_encode", "draco_decode",
                          "draco_update"):
                frac = (prog.get("phases", {}).get(phase) or {}).get("frac")
                if isinstance(frac, (int, float)):
                    metrics[f"{base}.{phase}_share"] = {
                        "value": float(frac), "kind": "time_ms",
                        "source": src}
            check = prog.get("cross_check") or {}
            metrics[f"{base}.cross_check_ok"] = {
                "value": float(bool(check.get("ok"))), "kind": "ok",
                "source": src}
            expl = (prog.get("collectives") or {}).get("explicit") or {}
            for kind, led in sorted(expl.items()):
                if not led.get("instructions") and not (
                        check.get("expected") or {}).get(kind):
                    continue
                metrics[f"{base}.coll.{kind}.instructions"] = {
                    "value": float(led.get("instructions", 0)),
                    "kind": "pinned", "source": src}
                metrics[f"{base}.coll.{kind}.bytes"] = {
                    "value": float(led.get("bytes", 0)),
                    "kind": "pinned", "source": src}


def fold_fleet(root: str, metrics: dict) -> None:
    """Fleet-SLO artifact (tools/fleet_study.py, ISSUE 19): the fleet
    observatory's certificates gate at tolerance 0 — every cell's SLO
    verdict bool, the deterministic error-budget burn PINNED at zero
    (a clean or in-budget cell starting to burn is a regression; a
    burning cell silently going quiet is a contract change that must
    re-baseline consciously), and the detection SLO's P/R pinned at
    the certificate 1.0 on the adversary cells. The remediated cells'
    MTTR gates at the time tolerance (wall-clock measure)."""
    path = os.path.join(root, "baselines_out", "fleet_slo.json")
    data = _read_json(path)
    if not isinstance(data, dict):
        return
    src = "baselines_out/fleet_slo.json"
    if "all_ok" in data:
        metrics["fleet_slo.all_ok"] = {
            "value": float(bool(data["all_ok"])), "kind": "ok",
            "source": src}
    rows = data.get("rows", [])
    metrics["fleet_slo.cells"] = {
        "value": float(len(rows)), "kind": "pinned", "source": src}
    for row in rows:
        cell = row.get("cell")
        if not cell:
            continue
        key = f"fleet_slo.{cell}"
        metrics[f"{key}.ok"] = {
            "value": float(bool(row.get("ok"))), "kind": "ok",
            "source": src}
        metrics[f"{key}.state_done"] = {
            "value": float(row.get("state") == "done"), "kind": "ok",
            "source": src}
        metrics[f"{key}.run_id_present"] = {
            "value": float(bool(row.get("run_id"))), "kind": "ok",
            "source": src}
        if "budget_burned" in row:
            metrics[f"{key}.budget_burned"] = {
                "value": float(row["budget_burned"]), "kind": "pinned",
                "source": src}
        slo = row.get("slo") or {}
        for name, res in sorted(slo.items()):
            if not isinstance(res, dict) or not res.get("evaluated"):
                continue
            metrics[f"{key}.{name}.ok"] = {
                "value": float(bool(res.get("ok"))), "kind": "ok",
                "source": src}
        det = slo.get("detection_quality") or {}
        if det.get("evaluated"):
            for col in ("precision", "recall"):
                if det.get(col) is not None:
                    metrics[f"{key}.detection.{col}"] = {
                        "value": float(det[col]), "kind": "pinned",
                        "source": src}
        mttr = slo.get("incident_mttr") or {}
        if mttr.get("mttr_s") is not None:
            metrics[f"{key}.mttr_s"] = {
                "value": float(mttr["mttr_s"]), "kind": "time_ms",
                "source": src}
            metrics[f"{key}.mttr_attributed"] = {
                "value": float(mttr.get("unattributed", 0) == 0
                               and bool(mttr.get("attributed"))),
                "kind": "ok", "source": src}


def fold_all(root: str) -> dict:
    metrics: dict = {}
    fold_bench(root, metrics)
    fold_multichip(root, metrics)
    fold_host_loop(root, metrics)
    fold_program_lint(root, metrics)
    fold_chaos(root, metrics)
    fold_straggler(root, metrics)
    fold_autopilot(root, metrics)
    fold_fleet(root, metrics)
    fold_wire_study(root, metrics)
    fold_segment_study(root, metrics)
    fold_tree_study(root, metrics)
    fold_decode_bench(root, metrics)
    fold_device_profile(root, metrics)
    return metrics


def compare(baseline: dict, current: dict, tols: dict) -> dict:
    """Per-metric verdicts. A metric regresses when its relative change in
    the kind's bad direction exceeds the kind's tolerance."""
    regressions, improvements, unchanged, missing, new = [], [], [], [], []
    for name in sorted(set(baseline) | set(current)):
        if name not in baseline:
            new.append({"metric": name, **current[name]})
            continue
        if name not in current:
            missing.append({"metric": name, **baseline[name]})
            continue
        base, cur = baseline[name], current[name]
        kind = cur.get("kind", base.get("kind", "time_ms"))
        spec = KINDS.get(kind, KINDS["time_ms"])
        tol = tols.get(kind, spec["tol"])
        b, c = float(base["value"]), float(cur["value"])
        if b == 0.0:
            rel = 0.0 if c == 0.0 else float("inf") * (1 if c > 0 else -1)
        else:
            rel = (c - b) / abs(b)
        if spec["dir"] == "equal":
            bad, good = abs(rel) > tol, False
        else:
            bad = rel > tol if spec["dir"] == "lower_better" else rel < -tol
            good = rel < -tol if spec["dir"] == "lower_better" else rel > tol
        row = {"metric": name, "kind": kind, "baseline": b, "current": c,
               "rel_change": (round(rel, 4) if rel == rel
                              and abs(rel) != float("inf") else None),
               "tolerance": tol}
        (regressions if bad else improvements if good else unchanged
         ).append(row)
    return {"regressions": regressions, "improvements": improvements,
            "unchanged": unchanged, "missing": missing, "new": new,
            "ok": not regressions}


def _print_report(cmp_report: dict, out=None) -> None:
    out = out if out is not None else sys.stdout  # resolve at call time

    def show(rows, tag):
        for r in rows:
            rel = r["rel_change"]
            # rel is None when the baseline was 0 (e.g. timed_builds going
            # 0 -> 1): an infinite relative change, not a no-op
            pct = ("inf%" if rel is None
                   else f"{'+' if rel >= 0 else ''}{rel * 100:.1f}%")
            print(f"  [{tag}] {r['metric']} ({r['kind']}): "
                  f"{r['baseline']:g} -> {r['current']:g} "
                  f"({pct} vs tol {r['tolerance'] * 100:.0f}%)", file=out)

    print(f"perf_watch: {len(cmp_report['regressions'])} regression(s), "
          f"{len(cmp_report['improvements'])} improvement(s), "
          f"{len(cmp_report['unchanged'])} unchanged, "
          f"{len(cmp_report['missing'])} missing, "
          f"{len(cmp_report['new'])} new", file=out)
    show(cmp_report["regressions"], "REGRESSION")
    show(cmp_report["improvements"], "improved")
    for r in cmp_report["missing"]:
        print(f"  [missing] {r['metric']} (was {r['value']:g}, "
              f"{r['source']})", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", type=str, default=".",
                    help="repo root holding BENCH_r*.json / baselines_out/")
    ap.add_argument("--baseline", type=str, default="",
                    help=f"baseline snapshot (default <root>/{SNAPSHOT_REL})")
    ap.add_argument("--snapshot", action="store_true",
                    help="write the current fold as the new baseline "
                         "snapshot instead of comparing")
    ap.add_argument("--json", type=str, default="",
                    help="also write the comparison report as JSON here")
    ap.add_argument("--tol-time", type=float, default=KINDS["time_ms"]["tol"])
    ap.add_argument("--tol-bytes", type=float, default=KINDS["bytes"]["tol"])
    ap.add_argument("--tol-flops", type=float, default=KINDS["flops"]["tol"])
    ap.add_argument("--tol-compile", type=float,
                    default=KINDS["compile_ms"]["tol"])
    ap.add_argument("--tol-ratio", type=float, default=KINDS["ratio"]["tol"])
    ap.add_argument("--strict-missing", action="store_true",
                    help="treat metrics that disappeared from the artifacts "
                         "as regressions")
    args = ap.parse_args(argv)

    baseline_path = args.baseline or os.path.join(args.root, SNAPSHOT_REL)
    current = fold_all(args.root)

    if args.snapshot:
        payload = {
            "schema": 1,
            "tool": "tools/perf_watch.py --snapshot",
            "metrics": current,
        }
        os.makedirs(os.path.dirname(baseline_path) or ".", exist_ok=True)
        with open(baseline_path, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True)
        print(f"perf_watch: snapshot of {len(current)} metrics -> "
              f"{baseline_path}")
        return 0

    snap = _read_json(baseline_path)
    if not isinstance(snap, dict) or "metrics" not in snap:
        print(f"perf_watch: no baseline snapshot at {baseline_path} — run "
              f"`python tools/perf_watch.py --snapshot` and commit it",
              file=sys.stderr)
        return 2

    tols = {"time_ms": args.tol_time, "bytes": args.tol_bytes,
            "flops": args.tol_flops, "compile_ms": args.tol_compile,
            "ratio": args.tol_ratio}
    report = compare(snap["metrics"], current, tols)
    if args.strict_missing and report["missing"]:
        report["ok"] = False
    _print_report(report)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=1)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
