#!/bin/bash
# Round-5 chip chain: the r3b/r3c/r4b queue REORDERED into an evidence
# ladder (VERDICT r4 next-round #1) — smallest, highest-value artifact
# first, and EVERY rung git-commits its artifact the moment it lands, so
# even a minutes-long tunnel window leaves committed on-chip evidence.
# Two straight rounds of total tunnel outage taught us the window may be
# short or absent; the ladder's contract is: any nonempty prefix = evidence.
#
# Rung order (mirrors VERDICT r4 items 1-7):
#   1  attn_t256        flash kernel compiles on hardware at all (~1 min)
#   2  bench_warm       bench.py --budget 1200: warms the compile cache
#   3  bench_280        bench.py at driver budget: whole record, warmed
#   4  attn_full        flash kernel T=256..4096 vs dense oracle
#   5  lm_flash         LM flash-vs-dense on the training path, T=1024
#   6  vote_retime      rep-resnet18 after the O(r·d) fingerprint vote
#   7  lm_big           d~159M LM point (T=2048, remat+flash) + simulate leg
#   8  remat_sweep      b128/256/512 remat MFU frontier
#   9  tta_cyclic       TPU time-to-accuracy, cyclic
#   10 tta_geomedian    TPU time-to-accuracy, geomedian baseline
#   11 lm_ttl           LM time-to-loss, 4 variants
#   12 decode_n32       decode study n=32 scaling rows
#   13 granularity      decode granularity (global vs per-layer) timings
#
# Launch detached (no tmux in this image):
#   setsid nohup bash tools/chip_jobs_r5.sh > baselines_out/chip_jobs_r5.log 2>&1 &
# NEVER edit this file while it runs (bash reads by byte offset).
# Rungs are marker-gated (baselines_out/.r5_<rung>_done) so outer retries
# resume, and each rung's tool rewrites its artifact incrementally, so a
# flap mid-rung keeps finished rows.
set -u
cd "$(dirname "$0")/.."
mkdir -p baselines_out

stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

commit_evidence() {
  # Commit the rung's artifacts; retry briefly in case the interactive
  # session holds the index lock at that instant. The commit is pathspec-
  # limited to baselines_out so anything the interactive session has staged
  # elsewhere is never swept into a chain commit. Globs expand under
  # nullglob into an explicit file list: a bare unmatched pattern would
  # make `git add` abort without staging ANY of the matched files, and a
  # silently-failed add must fall through to the retry sleep, not
  # early-return as "nothing new" (r5 review finding).
  local msg="$1"
  local files
  shopt -s nullglob
  files=(baselines_out/*.json baselines_out/*.jsonl baselines_out/*.log)
  shopt -u nullglob
  if [ "${#files[@]}" = 0 ]; then
    echo "[r5 $(stamp)] no artifact files exist yet for: $msg"
    return 0
  fi
  for i in 1 2 3; do
    if ! git add -- "${files[@]}"; then
      echo "[r5 $(stamp)] git add failed (attempt $i), retrying"
      sleep 5
      continue
    fi
    if git diff --cached --quiet -- baselines_out 2>/dev/null; then
      echo "[r5 $(stamp)] nothing new to commit for: $msg"
      return 0
    fi
    if git commit -q -m "$msg" -- baselines_out; then
      echo "[r5 $(stamp)] committed: $msg"
      return 0
    fi
    echo "[r5 $(stamp)] git commit failed (attempt $i), retrying"
    sleep 5
  done
  echo "[r5 $(stamp)] WARNING: commit failed for: $msg (evidence still on disk)"
  return 0
}

bench_ok() {
  # bench.py exits 0 even for a tpu_unavailable record; a rung only counts
  # when the tail JSON line is an on-TPU record with no error key.
  python - "$1" <<'EOF'
import json, sys
rec = None
for line in open(sys.argv[1]):
    line = line.strip()
    if line.startswith("{"):
        try:
            rec = json.loads(line)
        except Exception:
            pass
sys.exit(0 if rec and not rec.get("error")
         and rec.get("extra", {}).get("platform") not in (None, "cpu") else 1)
EOF
}

tpu_up() {
  # one bounded probe (never an unbounded in-process jax.devices(): it can
  # block ~25 min against a wedged lease, PERF.md §4)
  timeout -k 30 120 python - <<'EOF'
import sys, jax
try:
    d = jax.devices()
    sys.exit(0 if d and d[0].platform != "cpu" else 3)
except Exception:
    sys.exit(3)
EOF
}

# rung <name> <commit-msg> <cmd...>  — marker-gated, committing on success.
# A failing rung probes the tunnel; if it's down the whole pass aborts back
# to the outer wait loop instead of hanging 12 more tools against a dead
# lease (the r3 chain burned hours exactly that way).
ABORT_PASS=0
rung() {
  local name="$1" msg="$2"; shift 2
  local marker="baselines_out/.r5_${name}_done"
  if [ -f "$marker" ] || [ "$ABORT_PASS" = 1 ]; then
    return 0
  fi
  echo "[r5 $(stamp)] ===== rung $name: $* ====="
  local rc=0
  "$@" || rc=$?
  if [ "$rc" = 0 ]; then
    touch "$marker"
    commit_evidence "$msg"
  else
    echo "[r5 $(stamp)] rung $name FAILED (rc=$rc); probing tunnel"
    # commit whatever partial rows the tool wrote anyway — error rows with
    # provenance beat silence (decode_study r3 precedent)
    commit_evidence "$msg (partial: rung exited rc=$rc)"
    FAILURES=$((FAILURES + 1))
    if ! tpu_up; then
      echo "[r5 $(stamp)] tunnel down — aborting this pass, back to wait loop"
      ABORT_PASS=1
    fi
  fi
}

run_bench() {  # $1 = budget, $2 = out file
  DRACO_BENCH_BUDGET="$1" python bench.py --budget "$1" --no-cpu-fallback \
    > "$2" && bench_ok "$2"
}

all_done() {
  for m in attn_t256 bench_warm bench_280 attn_full lm_flash vote_retime \
           lm_big remat_sweep tta_cyclic tta_geomedian lm_ttl decode_n32 \
           granularity; do
    [ -f "baselines_out/.r5_${m}_done" ] || return 1
  done
  return 0
}

for outer in 1 2 3 4 5 6; do
  echo "[r5 $(stamp)] ===== outer attempt $outer ====="
  if all_done; then break; fi
  tools/wait_tpu.sh 60 150 120 || { echo "[r5 $(stamp)] tunnel never came up this window"; continue; }
  FAILURES=0
  ABORT_PASS=0

  rung attn_t256 "chip evidence: flash-attention T=256 hardware compile row" \
    timeout -k 60 2400 python tools/tpu_attn_check.py --seq-lens 256 \
      --out baselines_out/tpu_attn_t256.json

  rung bench_warm "chip evidence: warmed wide-budget driver bench on TPU" \
    run_bench 1200 baselines_out/bench_warm_r5.json

  rung bench_280 "chip evidence: driver-budget (280s) bench record on TPU, cache warm" \
    run_bench 280 baselines_out/bench_280_r5.json

  rung attn_full "chip evidence: flash-attention T=256..4096 vs dense oracle on TPU" \
    timeout -k 60 3600 python tools/tpu_attn_check.py --out baselines_out/tpu_attn.json

  rung lm_flash "chip evidence: LM flash-vs-dense training-path perf, T=1024" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 \
      --variants lm_cyclic_s1_shared_bf16_flash,lm_cyclic_s1_shared_bf16 \
      --seq-len 1024 --batch-size 4 --remat \
      --out baselines_out/tpu_lm_perf_flash.json

  rung vote_retime "chip evidence: rep-resnet18 re-time with O(r·d) keyed fingerprint vote" \
    timeout -k 60 2400 python tools/run_baselines.py --max-steps 12 --protocol scan \
      --only rep-resnet18

  rung lm_big "chip evidence: d~159M LM perf point (T=2048, remat+flash) + simulate leg" \
    timeout -k 60 7200 bash -c 'python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 2048 --batch-size 2 --remat \
      --variants lm_cyclic_s1_shared_bf16_flash,lm_cyclic_s1_shared_bf16,lm_geomedian_bf16 \
      --out baselines_out/tpu_lm_perf_big.json && \
    python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 2048 --batch-size 1 --remat \
      --variants lm_cyclic_s1_simulate_bf16 \
      --out baselines_out/tpu_lm_perf_big_simulate.json'

  rung remat_sweep "chip evidence: remat MFU frontier b128/256/512 bf16" \
    timeout -k 60 5400 python tools/tpu_sweep.py --remat --batches 128,256,512 \
      --dtypes bfloat16 --out baselines_out/tpu_sweep_remat.json

  rung tta_cyclic "chip evidence: TPU time-to-accuracy, ResNet18/CIFAR10 cyclic" \
    timeout -k 60 5400 python tools/time_to_acc.py --network ResNet18 --dataset Cifar10 \
      --approach cyclic --redundancy simulate --eval-every 5 --max-steps 300 \
      --target 0.9 --out baselines_out/tpu_tta_resnet_cyclic.json

  rung tta_geomedian "chip evidence: TPU time-to-accuracy, ResNet18/CIFAR10 geomedian" \
    timeout -k 60 5400 python tools/time_to_acc.py --network ResNet18 --dataset Cifar10 \
      --approach baseline --mode geometric_median --eval-every 5 \
      --max-steps 300 --target 0.9 \
      --out baselines_out/tpu_tta_resnet_geomedian.json

  rung lm_ttl "chip evidence: LM time-to-loss, 4 variants" \
    timeout -k 60 5400 python tools/lm_time_to_loss.py --eval-every 10 --max-steps 100 \
      --out baselines_out/lm_time_to_loss.json \
      --variants lm_cyclic_s1_simulate,lm_geomedian,lm_mean_under_attack,lm_mean_no_attack

  rung decode_n32 "chip evidence: decode study n=32 scaling rows" \
    timeout -k 60 3600 python tools/decode_study.py --ns 32 \
      --out baselines_out/decode_study_n32.json

  rung granularity "chip evidence: decode granularity (global vs per-layer) timings" \
    timeout -k 60 3600 python tools/decode_study.py --ns 8 --ss 1 \
      --out baselines_out/decode_study_granularity.json

  if all_done; then
    echo "[r5 $(stamp)] LADDER COMPLETE"
    break
  fi
  echo "[r5 $(stamp)] ladder incomplete ($FAILURES rung failures this pass); retrying failed rungs"
  sleep 120
done
all_done && exit 0 || exit 1
