#!/usr/bin/env python
"""Flash-attention block-size autotune on real hardware.

Sweeps (block_q, block_k) over a grid at one (T, B, H, Dh) point and times
fwd and fwd+bwd for each, plus the XLA dense path and jax's bundled TPU
flash op as yardsticks. The kernel ships with 128x128 defaults chosen for
lowering safety, not measured speed; this tool finds whether bigger blocks
(fewer grid steps, more VMEM per step) buy anything on the actual chip.

Parity per config is asserted against the dense streaming-softmax oracle
when it fits, else against the 128x128 kernel output (all configs compute
the same math; a mis-tiled config raises at lowering, not silently).

Writes --out (default baselines_out/tpu_attn_tune.json) after every row,
so a tunnel loss keeps finished rows (decode_study r3 precedent).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/tpu_attn_tune.json")
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--dtype", type=str, default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--blocks-q", type=str, default="128,256,512")
    ap.add_argument("--blocks-k", type=str, default="128,256,512,1024")
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--cpu-interpret", action="store_true",
                    help="smoke: run tiny shapes in interpret mode on CPU")
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    import jax

    if args.cpu_interpret:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.ops.flash_attention import flash_attention
    from draco_tpu.parallel.ring_attention import dense_attention
    from draco_tpu.utils.timing import timeit_chained

    t, b, h, dh = args.seq_len, args.batch, args.heads, args.head_dim
    r = np.random.RandomState(0)
    dt = jnp.dtype(args.dtype)
    q = jnp.asarray(r.normal(size=(b, t, h, dh)).astype(np.float32)).astype(dt)
    k = jnp.asarray(r.normal(size=(b, t, h, dh)).astype(np.float32)).astype(dt)
    v = jnp.asarray(r.normal(size=(b, t, h, dh)).astype(np.float32)).astype(dt)

    dev = jax.devices()[0]
    report = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "seq_len": t, "batch": b, "heads": h, "head_dim": dh,
        "dtype": args.dtype,
        "rows": [],
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)

    def save():
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1)

    def fwd_step(attn):
        def step(qc, k, v):
            o = attn(qc, k, v)
            return qc + (1e-30 * jnp.sum(o.astype(jnp.float32) ** 2)).astype(
                qc.dtype)
        return step

    def fb_step(attn):
        g = jax.grad(
            lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v).astype(jnp.float32))),
            argnums=0)

        def step(qc, k, v):
            return qc + (1e-30 * g(qc, k, v).astype(jnp.float32) ** 2).astype(
                qc.dtype)
        return step

    # reference output for parity: dense oracle if it fits, else 128x128
    ref_name, o_ref = "dense", None
    try:
        o_ref = jax.jit(
            lambda q, k, v: dense_attention(q, k, v, causal=True))(q, k, v)
        o_ref = jax.block_until_ready(o_ref)
        report["parity_reference"] = "dense"
    except Exception:
        ref_name = "flash_128x128"
        report["parity_reference"] = ref_name

    tol = 5e-2 if args.dtype == "bfloat16" else 5e-3

    for bq in [int(x) for x in args.blocks_q.split(",")]:
        for bk in [int(x) for x in args.blocks_k.split(",")]:
            if t % bq or t % bk:
                continue
            rec = {"block_q": bq, "block_k": bk}
            print(f"[tune] bq={bq} bk={bk} ...", file=sys.stderr, flush=True)
            try:
                attn = lambda q, k, v: flash_attention(
                    q, k, v, block_q=bq, block_k=bk, force=True,
                    interpret=args.cpu_interpret)
                o = jax.block_until_ready(jax.jit(attn)(q, k, v))
                if o_ref is None and bq == bk == 128:
                    o_ref = o
                if o_ref is not None:
                    err = float(jnp.max(jnp.abs(
                        o.astype(jnp.float32) - o_ref.astype(jnp.float32))))
                    rec["max_abs_err_vs_" + ref_name] = err
                    rec["parity_ok"] = bool(err < tol)
                rec["fwd_ms"] = round(
                    timeit_chained(fwd_step(attn), q, (k, v),
                                   reps=args.reps) * 1e3, 3)
                rec["fwdbwd_ms"] = round(
                    timeit_chained(fb_step(attn), q, (k, v),
                                   reps=args.reps) * 1e3, 3)
            except Exception as e:
                rec["error"] = f"{type(e).__name__}: {e}"[:2500]
            print(f"[tune] {json.dumps(rec)}", file=sys.stderr, flush=True)
            report["rows"].append(rec)
            save()

    # yardsticks
    try:
        rec = {"yardstick": "dense"}
        rec["fwd_ms"] = round(
            timeit_chained(fwd_step(
                lambda q, k, v: dense_attention(q, k, v, causal=True)),
                q, (k, v), reps=args.reps) * 1e3, 3)
        rec["fwdbwd_ms"] = round(
            timeit_chained(fb_step(
                lambda q, k, v: dense_attention(q, k, v, causal=True)),
                q, (k, v), reps=args.reps) * 1e3, 3)
        report["rows"].append(rec)
    except Exception as e:
        report["rows"].append(
            {"yardstick": "dense", "error": f"{type(e).__name__}: {e}"[:800]})
    save()
    try:
        if args.cpu_interpret:
            raise RuntimeError("jaxref yardstick skipped in CPU smoke")
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_flash,
        )
        scale = 1.0 / (dh ** 0.5)
        qh, kh, vh = (jnp.moveaxis(x, 2, 1) for x in (q, k, v))
        ref = lambda q, k, v: jax_flash(q, k, v, causal=True, sm_scale=scale)
        rec = {"yardstick": "jaxref"}
        rec["fwd_ms"] = round(
            timeit_chained(fwd_step(ref), qh, (kh, vh),
                           reps=args.reps) * 1e3, 3)
        rec["fwdbwd_ms"] = round(
            timeit_chained(fb_step(ref), qh, (kh, vh),
                           reps=args.reps) * 1e3, 3)
        report["rows"].append(rec)
    except Exception as e:
        report["rows"].append(
            {"yardstick": "jaxref", "error": f"{type(e).__name__}: {e}"[:800]})
    save()

    flash_rows = [r for r in report["rows"]
                  if "fwdbwd_ms" in r and "block_q" in r
                  and r.get("parity_ok", True)]
    if flash_rows:
        best = min(flash_rows, key=lambda r: r["fwdbwd_ms"])
        report["best"] = {"block_q": best["block_q"],
                          "block_k": best["block_k"],
                          "fwdbwd_ms": best["fwdbwd_ms"]}
        save()
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
