#!/usr/bin/env python
"""Tree-vs-flat aggregation study: the hierarchical CodedReduce evidence
(ISSUE 17).

The flat coded path decodes all n codewords at ONE logical aggregation
point — decode time and ingest bytes at that point grow with n (the
committed decode_study scaling rows: 1.8 ms at n=8 to 6.3 ms at n=32 on
the flagship d). The tree topology (coding/topology.py) caps per-node
fan-in at g: leaf nodes decode their OWN (g, d) block with the small
per-group code and parents combine decoded (d,) partials level by level.
This study measures that trade at the study d for every valid
(n, fanout) cell:

  * **flat decode ms** — the small-code decode at (n, d), the per-step
    cost of today's star aggregation point (chained-feedback timing,
    utils/timing.py protocol);
  * **per-node critical path** — what ONE tree node pays per step: the
    leaf decode at (g, d) plus each combine level's fan-in-f partial sum.
    This is the deployment quantity CodedReduce optimises (every level
    runs in parallel across nodes), and the headline crossover column;
  * **sequential total** — the HONEST single-host number: all G leaf
    decodes plus the full combine run back to back, which is how this
    repo's one-process routes actually execute the tree. Flat can win
    this column (total work favors one big decode) and the artifact
    records it when it does;
  * **detection equality** — at cells whose per-group budget s_g >= 1,
    the tree's folded flagged mask must equal the flat decode's under the
    SAME live rev_grad adversary, and under a straggler drop the victim
    must never be accused — detection P/R identical to flat, pinned;
  * **per-level bytes** — the wire ledger's tree sub-block
    (obs/numerics.wire_ledger): leaf-level ingest bytes must SUM EXACTLY
    to the flat ledger's physical_bytes_per_step (same n codeword rows,
    partitioned), pinned tolerance-0 by tools/perf_watch.py.

The winning tree cell re-runs once under the span tracer + a jax
profiler capture and the host/device event streams merge onto one clock
(obs/device_attr.merge_timeline, the PR 9 machinery) — per-group decode
and per-level combine spans land in the committed merged-timeline block.

``--check`` re-verifies a committed artifact jax-free (byte sums, plan
algebra, detection pins, the crossover honesty columns) — wired into
tools/check_artifacts.py.

Usage (CPU, ~2-4 min):
  python tools/tree_study.py
  python tools/tree_study.py --check
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NS = (8, 16, 32)
FANOUTS = (4, 8)
WORKER_FAIL = 1
D_DEFAULT = 1_048_576
D_DETECT = 4096
SEED = 1729


def _valid_tree(n: int, g: int) -> bool:
    return n % g == 0 and n // g >= 2


def _study_cfg(n: int, g: int, d: int):
    """The TrainConfig a tree cell names — the ONE source of the committed
    ledger and the per-group code shape (config.validate has the final
    word on the (n, g) cells the study may claim)."""
    from draco_tpu.config import TrainConfig

    kw = dict(network="LeNet", dataset="synthetic-mnist", batch_size=2,
              num_workers=n, approach="cyclic", redundancy="shared",
              worker_fail=WORKER_FAIL, adversary_count=0,
              err_mode="rev_grad", max_steps=2, eval_freq=0, train_dir="",
              log_every=10 ** 9)
    if g:
        kw.update(topology="tree", tree_fanout=g)
    return TrainConfig(**kw)


def _decode_ms(code, d: int, reps: int) -> float:
    """Chained-feedback decode cost of one cyclic code at (code.n, d)."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.coding import cyclic as cyc
    from draco_tpu.utils.timing import timeit_chained

    r = np.random.RandomState(SEED)
    g = jnp.asarray(r.randn(code.n, d).astype(np.float32) * 0.05)
    rf = jnp.asarray(r.randn(d).astype(np.float32))
    e_re, e_im = cyc.encode_shared(code, g)

    def dec_step(carry, rf):
        er, ei = carry
        dec, _honest = cyc.decode(code, er, ei, rf)
        return (er.at[0, 0].add(1e-30 * jnp.sum(dec ** 2)), ei)

    return timeit_chained(dec_step, (e_re, e_im), (rf,), reps=reps) * 1e3


def _combine_node_ms(fan_in: int, d: int, reps: int) -> float:
    """One combine node's per-step cost: the fan-in-f partial sum."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.utils.timing import timeit_chained

    r = np.random.RandomState(SEED)
    parts = jnp.asarray(r.randn(fan_in, d).astype(np.float32))

    def node_step(pc):
        s = jnp.sum(pc, axis=0)
        return pc.at[0, 0].add(1e-30 * jnp.sum(s ** 2))

    return timeit_chained(node_step, parts, reps=reps) * 1e3


def _combine_full_ms(plan, d: int, reps: int) -> float:
    """The WHOLE level-structured fold (G, d) -> (d,) on one host — the
    sequential-total column's combine share."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.coding import topology as topo
    from draco_tpu.utils.timing import timeit_chained

    r = np.random.RandomState(SEED)
    parts = jnp.asarray(r.randn(plan.num_groups, d).astype(np.float32))

    def fold_step(pc):
        s = topo.combine_partials(plan, pc)
        return pc.at[0, 0].add(1e-30 * jnp.sum(s ** 2))

    return timeit_chained(fold_step, parts, reps=reps) * 1e3


def _pr(flagged, adv_mask):
    """Detection precision/recall of a flagged mask against truth."""
    import numpy as np

    flagged = np.asarray(flagged, bool)
    adv = np.asarray(adv_mask, bool)
    tp = int((flagged & adv).sum())
    fp = int((flagged & ~adv).sum())
    fn = int((~flagged & adv).sum())
    prec = tp / (tp + fp) if tp + fp else 1.0
    rec = tp / (tp + fn) if tp + fn else 1.0
    return round(prec, 4), round(rec, 4)


def detection_cell(n: int, g: int) -> dict:
    """Tree-vs-flat detection equality at (n, g): the SAME live rev_grad
    adversary decoded both ways must flag the SAME rows (P/R identical),
    and a straggler drop's victim must never be accused either way.
    Requires s_g >= 1 (the g=4 cells have no per-group error budget and
    skip — recorded, not hidden)."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.coding import cyclic as cyc, topology as topo

    s_g = topo.group_worker_fail(g, WORKER_FAIL)
    if s_g < 1:
        return {"checked": False, "reason": f"s_g={s_g} (no per-group "
                                            f"error budget at g={g})"}
    d = D_DETECT
    cfg = _study_cfg(n, g, d)
    tcode = topo.build_tree_code(cfg)
    flat = cyc.build_cyclic_code(n, WORKER_FAIL)
    r = np.random.RandomState(SEED)
    grads = jnp.asarray(r.randn(n, d).astype(np.float32) * 0.05)
    rf = jnp.asarray(r.randn(d).astype(np.float32))
    adv_row = n - 2  # lives in the LAST leaf group — the fold must map it
    adv = jnp.zeros((n, 1), bool).at[adv_row, 0].set(True)

    # live adversary: rev_grad on the encoded rows, both topologies
    fr, fi = cyc.encode_shared(flat, grads)
    tr, ti = topo.encode_tree(tcode, grads)
    fr, fi = (jnp.where(adv, -100.0 * fr, fr),
              jnp.where(adv, -100.0 * fi, fi))
    tr, ti = (jnp.where(adv, -100.0 * tr, tr),
              jnp.where(adv, -100.0 * ti, ti))
    _dec_f, _hon_f, hl_f = cyc.decode(flat, fr, fi, rf, with_health=True)
    _dec_t, _hon_t, hl_t = topo.decode_tree_cyclic(tcode, tr, ti, rf)
    fl_f = np.asarray(hl_f["flagged"], bool)
    fl_t = np.asarray(hl_t["flagged"], bool)
    p_f, r_f = _pr(fl_f, np.asarray(adv).ravel())
    p_t, r_t = _pr(fl_t, np.asarray(adv).ravel())

    # straggler drop: one worker absent (erasure), nobody gets accused
    drop_row = 1
    present = jnp.ones((n,), bool).at[drop_row].set(False)
    fr2, fi2 = cyc.encode_shared(flat, grads)
    tr2, ti2 = topo.encode_tree(tcode, grads)
    dec_f2, _h, hl_f2 = cyc.decode(flat, fr2, fi2, rf, present=present,
                                   with_health=True)
    dec_t2, _h, hl_t2 = topo.decode_tree_cyclic(tcode, tr2, ti2, rf,
                                                present=present)
    dfl_f = np.asarray(hl_f2["flagged"], bool)
    dfl_t = np.asarray(hl_t2["flagged"], bool)
    true_mean = np.asarray(jnp.mean(grads, axis=0))
    err_f = float(np.max(np.abs(np.asarray(dec_f2) - true_mean)))
    err_t = float(np.max(np.abs(np.asarray(dec_t2) - true_mean)))
    return {
        "checked": True, "adv_row": adv_row, "drop_row": drop_row,
        "precision_flat": p_f, "recall_flat": r_f,
        "precision_tree": p_t, "recall_tree": r_t,
        "flags_equal": bool((fl_f == fl_t).all()),
        "drop_victim_accused_flat": bool(dfl_f[drop_row]),
        "drop_victim_accused_tree": bool(dfl_t[drop_row]),
        "drop_flags_equal": bool((dfl_f == dfl_t).all()),
        "drop_decode_err_flat": round(err_f, 7),
        "drop_decode_err_tree": round(err_t, 7),
        "ok": bool((fl_f == fl_t).all() and (dfl_f == dfl_t).all()
                   and p_t == p_f and r_t == r_f and r_t == 1.0
                   and not dfl_t[drop_row] and err_t < 1e-3),
    }


def run_tree_cell(n: int, g: int, d: int, flat_ms: float, reps: int) -> dict:
    from draco_tpu.coding import topology as topo
    from draco_tpu.obs import numerics as nx

    cfg = _study_cfg(n, g, d)
    flat_cfg = _study_cfg(n, 0, d)
    tcode = topo.build_tree_code(cfg)
    plan = tcode.plan

    leaf_ms = _decode_ms(tcode.group_code, d, reps)
    node_combine = [round(_combine_node_ms(f, d, reps), 3)
                    for f in plan.level_fanouts]
    combine_full_ms = _combine_full_ms(plan, d, reps)
    critical_ms = leaf_ms + sum(node_combine)
    sequential_ms = plan.num_groups * leaf_ms + combine_full_ms

    ledger = nx.wire_ledger(cfg, d)
    flat_ledger = nx.wire_ledger(flat_cfg, d)
    tree_block = ledger.get("tree") or {}
    level_bytes = tree_block.get("level_bytes_per_step") or []
    # the honesty pin: leaf-level ingest == the flat star's per-step bytes
    bytes_ok = bool(
        level_bytes
        and level_bytes[0] == flat_ledger["physical_bytes_per_step"]
        and level_bytes[0] == ledger["physical_bytes_per_step"]
        and tree_block.get("ingest_bytes_per_group", 0) * plan.num_groups
        == level_bytes[0])

    det = detection_cell(n, g)
    row = {
        "kind": "tree", "n": n, "fanout": g, "levels": plan.levels,
        "num_groups": plan.num_groups, "s_g": tcode.s, "d": d,
        "leaf_decode_ms": round(leaf_ms, 3),
        "node_combine_ms": node_combine,
        "critical_path_ms": round(critical_ms, 3),
        "sequential_total_ms": round(sequential_ms, 3),
        "flat_decode_ms": round(flat_ms, 3),
        "win": bool(critical_ms < flat_ms),
        "win_frac": round((flat_ms - critical_ms) / flat_ms, 4),
        "sequential_win": bool(sequential_ms < flat_ms),
        "ledger": {
            "flat_physical_bytes_per_step":
                flat_ledger["physical_bytes_per_step"],
            "tree": tree_block,
        },
        "bytes_ok": bytes_ok,
        "detection": det,
    }
    row["ok"] = bool(bytes_ok and (det["ok"] if det.get("checked")
                                   else True))
    return row


def capture_timeline(row: dict, reps: int, work_dir: str) -> dict:
    """Re-run the winning tree cell once under the span tracer + a jax
    profiler capture: per-group leaf decodes and per-level combines land
    as tree_* spans, merged onto one clock with any device events."""
    import gzip

    import jax
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.coding import cyclic as cyc, topology as topo
    from draco_tpu.obs import device_attr
    from draco_tpu.obs.profiling import ANCHOR_FILE, ProfilerWindow
    from draco_tpu.obs.tracer import make_tracer

    n, g, d = row["n"], row["fanout"], row["d"]
    cfg = _study_cfg(n, g, d)
    tcode = topo.build_tree_code(cfg)
    plan = tcode.plan
    r = np.random.RandomState(SEED)
    grads = jnp.asarray(r.randn(n, d).astype(np.float32) * 0.05)
    rf = jnp.asarray(r.randn(d).astype(np.float32))
    e_re, e_im = topo.encode_tree(tcode, grads)
    dec = jax.jit(lambda er, ei, f: cyc.decode(tcode.group_code, er, ei, f))
    jax.block_until_ready(dec(e_re[: g], e_im[: g], rf))  # compile outside

    cell_dir = os.path.join(work_dir, "tree_decode")
    os.makedirs(cell_dir, exist_ok=True)
    tracer = make_tracer(cell_dir)
    win = ProfilerWindow(cell_dir, (0, 10 ** 9), tracer=tracer)
    win.maybe_start(0, first_step=0)
    try:
        parts = []
        for j, (lo, hi) in enumerate(plan.group_slices):
            with tracer.span(f"tree_leaf_decode_g{j}", fan_in=g):
                out, _ = dec(e_re[lo:hi], e_im[lo:hi], rf)
                jax.block_until_ready(out)
            parts.append(out)
        x = jnp.stack(parts)
        for l, f in enumerate(plan.level_fanouts):
            with tracer.span(f"tree_combine_l{l + 1}", fan_in=f):
                x = jax.block_until_ready(
                    x.reshape(-1, f, x.shape[-1]).sum(axis=1))
        jax.block_until_ready(x[0] / plan.num_groups)
    finally:
        win.stop()
        tracer.close()

    host = device_attr.load_json(os.path.join(cell_dir, "trace.json"))
    host_events = (host or {}).get("traceEvents") or []
    anchor = device_attr.load_json(os.path.join(cell_dir, ANCHOR_FILE))
    cap = device_attr.find_capture(cell_dir)
    dev_events = []
    if cap is not None:
        dev_events, _ = device_attr.load_trace(cap)
    merged = device_attr.merge_timeline(host_events, dev_events, None,
                                        anchor, max_device_events=50_000)
    out_path = os.path.join(cell_dir, "merged_timeline.json.gz")
    with gzip.open(out_path, "wt") as fh:
        json.dump(merged, fh)
    mt = merged["mergedTimeline"]
    tree_spans = sum(1 for e in host_events
                     if str(e.get("name", "")).startswith("tree_"))
    rel = os.path.join(os.path.basename(cell_dir.rstrip(os.sep)),
                       os.path.basename(out_path))
    return {"path": rel, "cell": f"n{n}.g{g}",
            "anchored": mt["anchored"], "anchor_kind": mt.get("anchor_kind"),
            "host_events": len(host_events), "tree_spans": tree_spans,
            "device_events": sum(1 for e in merged["traceEvents"]
                                 if e.get("cat") == "device")}


# --------------------------------------------------------------------------
# --check: jax-free artifact re-verification (tools/check_artifacts.py)
# --------------------------------------------------------------------------


def check_artifact(path: str) -> int:
    """Re-verify a committed tree_study.json: plan algebra, the per-level
    byte sums, the detection pins, and the crossover honesty columns.
    Exits nonzero naming the first failure."""
    from draco_tpu.coding.topology import tree_plan  # jax-free header

    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"tree_study --check: cannot read {path}: {e}")
        return 1
    rows = data.get("rows", [])
    flat = {r["n"]: r for r in rows if r.get("kind") == "flat"}
    trees = [r for r in rows if r.get("kind") == "tree"]
    want = {(n, g) for n in NS for g in FANOUTS if _valid_tree(n, g)}
    got = {(r.get("n"), r.get("fanout")) for r in trees}
    if not want <= got:
        print(f"tree_study --check: missing tree cells {sorted(want - got)}")
        return 1
    if set(flat) != set(NS):
        print(f"tree_study --check: flat rows cover {sorted(flat)}, "
              f"want {list(NS)}")
        return 1
    detect_checked = 0
    for r in trees:
        cell = f"n{r['n']}.g{r['fanout']}"
        plan = tree_plan(r["n"], r["fanout"], r.get("levels", 0))
        if (plan.levels != r["levels"]
                or plan.num_groups != r["num_groups"]):
            print(f"tree_study --check: {cell}: plan algebra disagrees "
                  f"(levels {r['levels']}, groups {r['num_groups']})")
            return 1
        led = r.get("ledger") or {}
        tb = led.get("tree") or {}
        lb = tb.get("level_bytes_per_step") or []
        if len(lb) != plan.levels:
            print(f"tree_study --check: {cell}: {len(lb)} byte levels for "
                  f"a {plan.levels}-level tree")
            return 1
        if lb[0] != led.get("flat_physical_bytes_per_step"):
            print(f"tree_study --check: {cell}: leaf-level bytes {lb[0]} "
                  f"!= flat per-step bytes "
                  f"{led.get('flat_physical_bytes_per_step')} — the "
                  f"partition must sum exactly")
            return 1
        if tb.get("ingest_bytes_per_group", 0) * plan.num_groups != lb[0]:
            print(f"tree_study --check: {cell}: per-group ingest bytes do "
                  f"not tile the leaf level")
            return 1
        if not r.get("bytes_ok"):
            print(f"tree_study --check: {cell}: bytes_ok is false")
            return 1
        base = flat.get(r["n"], {}).get("decode_ms")
        if base is None or abs(base - r.get("flat_decode_ms", -1)) > 1e-9:
            print(f"tree_study --check: {cell}: flat_decode_ms does not "
                  f"match the n={r['n']} flat row")
            return 1
        want_win = r["critical_path_ms"] < r["flat_decode_ms"]
        if bool(r.get("win")) != want_win:
            print(f"tree_study --check: {cell}: win column disagrees with "
                  f"its own timings")
            return 1
        det = r.get("detection") or {}
        if det.get("checked"):
            detect_checked += 1
            if not (det.get("flags_equal") and det.get("drop_flags_equal")
                    and det.get("precision_tree") == det.get(
                        "precision_flat")
                    and det.get("recall_tree") == det.get("recall_flat")
                    and det.get("recall_tree") == 1.0
                    and not det.get("drop_victim_accused_tree")
                    and det.get("ok")):
                print(f"tree_study --check: {cell}: detection parity pin "
                      f"failed ({det})")
                return 1
        if not r.get("ok"):
            print(f"tree_study --check: {cell}: row not ok")
            return 1
    if detect_checked == 0:
        print("tree_study --check: no cell ran the live-adversary "
              "detection parity check (need an s_g >= 1 cell)")
        return 1
    cx = data.get("crossover") or {}
    n_max = max(NS)
    best = [r for r in trees if r["n"] == n_max and r.get("win")]
    if not best:
        print(f"tree_study --check: no tree cell beats flat decode at "
              f"n={n_max} — the ISSUE 17 acceptance pin")
        return 1
    if cx.get("critical_path_n") not in [n for n, _g in sorted(got)]:
        print(f"tree_study --check: crossover block names no measured "
              f"cell ({cx})")
        return 1
    mt = data.get("merged_timeline") or {}
    if not mt.get("tree_spans", 0) > 0:
        print("tree_study --check: merged timeline carries no tree_* "
              "spans")
        return 1
    if not data.get("all_ok"):
        print("tree_study --check: all_ok is false")
        return 1
    print(f"tree_study --check: {len(rows)} rows verified ({path})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", type=str,
                    default=os.path.join("baselines_out", "tree_study.json"))
    ap.add_argument("--d", type=int, default=D_DEFAULT)
    ap.add_argument("--trials", type=int, default=10)
    ap.add_argument("--work-dir", type=str, default="",
                    help="dir for the merged-timeline artifact "
                         "(default: a temp dir, printed at exit)")
    ap.add_argument("--check", action="store_true",
                    help="re-verify a committed artifact (jax-free)")
    ap.add_argument("--artifact", type=str, default="",
                    help="artifact path for --check (default --out)")
    args = ap.parse_args(argv)
    if args.check:
        return check_artifact(args.artifact or args.out)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from draco_tpu.coding import cyclic as cyc

    dev = jax.devices()[0]
    d = args.d
    print(f"tree_study: d={d} worker_fail={WORKER_FAIL} on {dev.platform}",
          flush=True)
    rows = []
    flat_ms = {}
    for n in NS:
        t0 = time.time()
        flat = cyc.build_cyclic_code(n, WORKER_FAIL)
        ms = _decode_ms(flat, d, args.trials)
        flat_ms[n] = ms
        rows.append({"kind": "flat", "n": n, "s": WORKER_FAIL, "d": d,
                     "decode_ms": round(ms, 3),
                     "measure_s": round(time.time() - t0, 1)})
        print(f"tree_study: flat n={n} -> {ms:.3f} ms", flush=True)
    for n in NS:
        for g in FANOUTS:
            if not _valid_tree(n, g):
                continue
            t0 = time.time()
            row = run_tree_cell(n, g, d, flat_ms[n], args.trials)
            row["measure_s"] = round(time.time() - t0, 1)
            rows.append(row)
            det = row["detection"]
            print(f"tree_study: tree n={n} g={g} -> "
                  f"critical={row['critical_path_ms']:.3f} ms "
                  f"(leaf {row['leaf_decode_ms']:.3f}) "
                  f"sequential={row['sequential_total_ms']:.3f} ms "
                  f"flat={row['flat_decode_ms']:.3f} ms "
                  f"win={row['win']} bytes_ok={row['bytes_ok']} "
                  f"detect={'ok' if det.get('ok') else det.get('reason', 'FAIL')}",
                  flush=True)

    trees = [r for r in rows if r["kind"] == "tree"]
    # crossover honesty: the smallest n whose best tree cell wins each
    # column; sequential may have NO crossover on one host — recorded
    cp_wins = sorted({r["n"] for r in trees if r["win"]})
    sq_wins = sorted({r["n"] for r in trees if r["sequential_win"]})
    crossover = {
        "critical_path_n": cp_wins[0] if cp_wins else None,
        "sequential_n": sq_wins[0] if sq_wins else None,
        "flat_wins_sequential_at": sorted(
            {r["n"] for r in trees if not r["sequential_win"]}),
    }
    print(f"tree_study: crossover {crossover}", flush=True)

    best = None
    for r in trees:
        if r["win"] and (best is None
                         or r["win_frac"] > best["win_frac"]):
            best = r
    work_dir = args.work_dir or tempfile.mkdtemp(prefix="tree_study_")
    merged = {}
    if best is not None:
        merged = capture_timeline(best, args.trials, work_dir)
        print(f"tree_study: merged timeline -> "
              f"{os.path.join(work_dir, merged['path'])} "
              f"(anchored={merged['anchored']}, "
              f"{merged['tree_spans']} tree spans)", flush=True)

    n_max = max(NS)
    payload = {
        "schema": 1,
        "tool": "tools/tree_study.py",
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "d": d, "worker_fail": WORKER_FAIL, "trials": args.trials,
        "rows": rows,
        "crossover": crossover,
        "merged_timeline": merged,
        "all_ok": bool(trees) and all(r["ok"] for r in trees)
        and any(r["n"] == n_max and r["win"] for r in trees)
        and any((r["detection"] or {}).get("checked") for r in trees)
        and merged.get("tree_spans", 0) > 0,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    print(f"tree_study: {len(rows)} rows -> {args.out} "
          f"(all_ok={payload['all_ok']})")
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
