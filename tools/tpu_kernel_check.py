#!/usr/bin/env python
"""Prove the Pallas coded-ops kernels on real TPU hardware.

For each of the three fused kernels in draco_tpu/ops/coded.py
(complex_matmul / complex_project / complex_recombine — the O(n·d) work of a
cyclic encode/decode step, reference src/c_coding.cpp:15-84 re-homed to the
MXU):

  1. numerical parity vs the plain-jnp path on the same device,
  2. wall-clock microbench fused vs unfused at ResNet-18 gradient size
     (d ≈ 11.2M) and a smaller LeNet-ish size,
  3. optional TILE_D sweep (--sweep) to check the tile choice.

Writes one JSON report (default baselines_out/tpu_kernels.json) and prints it.
CPU fallback (--cpu-mesh) runs the same protocol in Pallas interpret mode so
the harness itself stays testable anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _loop_time(step, carry, consts=(), reps=20):
    """Chained in-jit per-iteration timing — see
    draco_tpu.utils.timing.timeit_chained for the protocol and its
    feedback-discipline requirements (non-linear full-output feedback,
    operands via consts, adaptive trip count)."""
    from draco_tpu.utils.timing import timeit_chained

    return timeit_chained(step, carry, consts, reps=reps)


def check_kernels(d, n=8, interpret=False, reps=10):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.ops import coded

    r = np.random.RandomState(0)
    w_re = jnp.asarray(r.randn(n, n).astype(np.float32))
    w_im = jnp.asarray(r.randn(n, n).astype(np.float32))
    g = jnp.asarray(r.randn(n, d).astype(np.float32))
    # distinct imaginary-part matrix: passing the SAME array for re and im
    # lets XLA CSE the duplicate read in the transparent jnp path (one HBM
    # pass instead of two) while the opaque Pallas kernel still streams both
    # block inputs — which would bias the comparison
    g2 = jnp.asarray(r.randn(n, d).astype(np.float32))
    f = jnp.asarray(r.randn(d).astype(np.float32))
    v_re = jnp.asarray(r.randn(n).astype(np.float32))
    v_im = jnp.asarray(r.randn(n).astype(np.float32))
    jax.block_until_ready((w_re, w_im, g, g2, f, v_re, v_im))

    fused = dict(force=True, interpret=interpret) if interpret else dict(force=True)
    out = {"d": d, "n": n, "interpret": interpret, "kernels": {}}

    def bench_pair(fused_step, unfused_step, carry, consts):
        t_f = _loop_time(fused_step, carry, consts, reps=reps)
        t_u = _loop_time(unfused_step, carry, consts, reps=reps)
        return t_f, t_u

    # ---- complex_matmul (encode) ----
    a_re, a_im = coded.complex_matmul(w_re, w_im, g, **fused)
    b_re, b_im = coded.complex_matmul(w_re, w_im, g, force=False)
    err = max(
        float(jnp.max(jnp.abs(a_re - b_re))),
        float(jnp.max(jnp.abs(a_im - b_im))),
    )
    scale = float(jnp.max(jnp.abs(b_re))) or 1.0

    # Feedback discipline (timing.timeit_chained): carry the full output or
    # feed back a NON-LINEAR reduction of every output. Slice feedbacks get
    # the op partially dead-code-eliminated; plain sums of these *linear*
    # ops get reassociated and hoisted (sum(R@f) == colsum(R)·f — observed
    # as 0.0 ms unfused readings). Squared sums force the full computation
    # each iteration on the transparent XLA path, matching what the opaque
    # Pallas call is already forced to do.
    def _mm_step(kw):
        def step(gc, wr, wi):
            o_re, o_im = coded.complex_matmul(wr, wi, gc, **kw)
            return o_re + 1e-30 * o_im  # full outputs feed the next iter
        return step

    t_f, t_u = bench_pair(_mm_step(fused), _mm_step(dict(force=False)),
                          g, (w_re, w_im))
    out["kernels"]["complex_matmul"] = {
        "max_abs_err": err, "rel_err": err / scale,
        "fused_ms": round(t_f * 1e3, 4), "unfused_ms": round(t_u * 1e3, 4),
        "speedup": round(t_u / t_f, 3) if t_f > 0 else None,
    }

    # ---- complex_project (decode in) ----
    p_re, p_im = coded.complex_project(g, g2, f, **fused)
    q_re, q_im = coded.complex_project(g, g2, f, force=False)
    err = max(
        float(jnp.max(jnp.abs(p_re - q_re))),
        float(jnp.max(jnp.abs(p_im - q_im))),
    )
    scale = float(jnp.max(jnp.abs(q_re))) or 1.0

    def _pj_step(kw):
        def step(fv, g, g2):
            e_re, e_im = coded.complex_project(g, g2, fv, **kw)
            return fv + 1e-30 * (jnp.sum(e_re**2) + jnp.sum(e_im**2))
        return step

    t_f, t_u = bench_pair(_pj_step(fused), _pj_step(dict(force=False)),
                          f, (g, g2))
    out["kernels"]["complex_project"] = {
        "max_abs_err": err, "rel_err": err / scale,
        "fused_ms": round(t_f * 1e3, 4), "unfused_ms": round(t_u * 1e3, 4),
        "speedup": round(t_u / t_f, 3) if t_f > 0 else None,
    }

    # ---- complex_recombine (decode out) ----
    c = coded.complex_recombine(v_re, v_im, g, g2, **fused)
    e = coded.complex_recombine(v_re, v_im, g, g2, force=False)
    err = float(jnp.max(jnp.abs(c - e)))
    scale = float(jnp.max(jnp.abs(e))) or 1.0

    def _rc_step(kw):
        def step(cv, g, g2):
            vr, vi = cv
            s = jnp.sum(coded.complex_recombine(vr, vi, g, g2, **kw) ** 2)
            return (vr + 1e-30 * s, vi - 1e-30 * s)
        return step

    t_f, t_u = bench_pair(_rc_step(fused), _rc_step(dict(force=False)),
                          (v_re, v_im), (g, g2))
    out["kernels"]["complex_recombine"] = {
        "max_abs_err": err, "rel_err": err / scale,
        "fused_ms": round(t_f * 1e3, 4), "unfused_ms": round(t_u * 1e3, 4),
        "speedup": round(t_u / t_f, 3) if t_f > 0 else None,
    }
    return out


def sweep_tile(d, n=8, interpret=False, tiles=(1024, 2048, 4096, 8192, 16384)):
    import numpy as np
    import jax.numpy as jnp

    from draco_tpu.ops import coded

    r = np.random.RandomState(0)
    g = jnp.asarray(r.randn(n, d).astype(np.float32))
    g2 = jnp.asarray(r.randn(n, d).astype(np.float32))
    f = jnp.asarray(r.randn(d).astype(np.float32))
    rows = []
    orig = coded.TILE_D
    kw = dict(force=True, interpret=interpret) if interpret else dict(force=True)
    def step(fv, g, g2):
        e_re, e_im = coded.complex_project(g, g2, fv, **kw)
        return fv + 1e-30 * (jnp.sum(e_re**2) + jnp.sum(e_im**2))

    try:
        for tile in tiles:
            coded.TILE_D = tile
            # new tile -> new trace (jit caches key on static shapes only, so
            # clear to force re-trace with the module-level tile)
            coded._project_pallas.clear_cache()
            coded._matmul_pallas.clear_cache()
            try:
                t = _loop_time(step, f, (g, g2), reps=10)
                rows.append({"tile_d": tile, "project_ms": round(t * 1e3, 4)})
            except Exception as exc:  # a tile can fail compile (vmem limits)
                rows.append({"tile_d": tile, "error": repr(exc)[:200]})
    finally:
        coded.TILE_D = orig
        coded._project_pallas.clear_cache()
        coded._matmul_pallas.clear_cache()
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str, default="baselines_out/tpu_kernels.json")
    ap.add_argument("--cpu-mesh", type=int, default=0,
                    help="run in Pallas interpret mode on a CPU mesh")
    ap.add_argument("--sweep", action="store_true", help="TILE_D sweep")
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--small-d", type=int, default=62006)   # LeNet-ish
    ap.add_argument("--large-d", type=int, default=11173962)  # ResNet-18
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)  # shared bootstrap: compile cache (+ cpu mesh)

    interpret = bool(args.cpu_mesh)

    import jax

    dev = jax.devices()[0]
    from draco_tpu.ops import coded

    report = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "pallas_supported": coded.use_pallas(),
        "pallas_interpret": interpret,
        "sizes": [],
    }
    small_d = args.small_d if not interpret else min(args.small_d, 20000)
    large_d = args.large_d if not interpret else min(args.large_d, 100000)
    for d in (small_d, large_d):
        report["sizes"].append(check_kernels(d, interpret=interpret, reps=args.reps))
    if args.sweep:
        report["tile_sweep_d"] = large_d
        report["tile_sweep"] = sweep_tile(large_d, interpret=interpret)

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=1)
    print(json.dumps(report))
    # parity gate: fused and unfused must agree to float32 accumulation noise
    worst = max(
        k["rel_err"] for s in report["sizes"] for k in s["kernels"].values()
    )
    return 0 if worst < 1e-4 else 1


if __name__ == "__main__":
    raise SystemExit(main())
