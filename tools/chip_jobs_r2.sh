#!/bin/bash
# One-shot round-2 chip job chain: wait for the tunnel TPU to come back,
# then run the two pending hardware benchmarks sequentially (one client at
# a time per the tunnel discipline). Safe to re-run; artifacts land in
# baselines_out/.
set -eu
cd "$(dirname "$0")/.."

for attempt in $(seq 1 40); do
  # bounded probe: an unbounded in-process jax.devices() blocks ~25 min
  # inside the plugin's retry loop against a wedged tunnel (PERF.md §4);
  # timeout exit 124 counts as down
  if timeout -k 30 300 python - <<'EOF'
import sys, jax
try:
    d = jax.devices()
    sys.exit(0 if d and d[0].platform != "cpu" else 3)
except Exception:
    sys.exit(3)
EOF
  then
    echo "[chip_jobs] TPU up (attempt $attempt)"
    break
  fi
  echo "[chip_jobs] attempt $attempt: TPU still down"
  if [ "$attempt" = 40 ]; then
    echo "[chip_jobs] giving up"
    exit 3
  fi
  sleep 180
done

echo "[chip_jobs] running tpu_attn_check (flash vs dense, T=1024..4096)"
python tools/tpu_attn_check.py --out baselines_out/tpu_attn.json
echo "[chip_jobs] running tpu_lm_perf long-context remat variant"
python tools/tpu_lm_perf.py --remat --batch-size 8 --seq-len 1024 --steps 3 \
  --variants lm_cyclic_s1_shared_bf16,lm_mean_no_attack_bf16 \
  --out baselines_out/tpu_lm_perf_long.json
echo "[chip_jobs] done"
