#!/bin/bash
# Round-2 chip jobs (superseded by tools/chip_jobs_r3.sh, which includes
# both of these plus the round-3 studies — prefer that). Kept as the
# documented two-job chain: flash-attention hardware check + long-context
# remat LM run. Waits for the tunnel via the shared bounded probe.
set -eu
cd "$(dirname "$0")/.."

tools/wait_tpu.sh 40 180 300

echo "[chip_jobs] running tpu_attn_check (flash vs dense, T=1024..4096)"
python tools/tpu_attn_check.py --out baselines_out/tpu_attn.json
echo "[chip_jobs] running tpu_lm_perf long-context remat variant"
python tools/tpu_lm_perf.py --remat --batch-size 8 --seq-len 1024 --steps 3 \
  --variants lm_cyclic_s1_shared_bf16,lm_mean_no_attack_bf16 \
  --out baselines_out/tpu_lm_perf_long.json
echo "[chip_jobs] done"
