#!/usr/bin/env python
"""Multi-point convergence-under-attack curves for every approach and
aggregator under one identical schedule (VERDICT r2 item 8).

Runs tools/time_to_acc.py's measurement for each row of the grid — LeNet /
synthetic-MNIST, n=8 workers, one rev_grad adversary (seeded schedule shared
across rows), eval every ``--eval-every`` steps from step 1 — and writes one
JSON with all curves side by side (baselines_out/convergence_grid.json), the
routine artifact the reference establishes with its convergence oracle
(src/distributed_evaluator.py:92-110).

Rows: cyclic simulate + shared, maj_vote (r=4 | n=8), the three
reference-parity baselines (mean / geo-median / krum) and the four
beyond-reference aggregators (coord_median / trimmed_mean / multi_krum /
bulyan) — all under attack — plus a clean mean run as the matched-accuracy
anchor.

Usage: python tools/convergence_grid.py --cpu-mesh 8 [--eval-every 5]
       [--max-steps 150] [--rows cyclic_sim,geomedian,...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# sibling tool sharing the measurement loop; resolves in both contexts (the
# sys.path.insert above puts the repo root first)
from tools import time_to_acc  # noqa: E402

ROWS = {
    # label -> extra argv for time_to_acc.main
    "mean_clean": ["--approach", "baseline", "--mode", "normal",
                   "--worker-fail", "0"],
    "mean_attacked": ["--approach", "baseline", "--mode", "normal"],
    "geomedian": ["--approach", "baseline", "--mode", "geometric_median"],
    "krum": ["--approach", "baseline", "--mode", "krum"],
    "coord_median": ["--approach", "baseline", "--mode", "coord_median"],
    "trimmed_mean": ["--approach", "baseline", "--mode", "trimmed_mean"],
    "multi_krum": ["--approach", "baseline", "--mode", "multi_krum"],
    "bulyan": ["--approach", "baseline", "--mode", "bulyan"],
    "maj_vote": ["--approach", "maj_vote", "--group-size", "4"],
    "cyclic_sim": ["--approach", "cyclic", "--redundancy", "simulate"],
    "cyclic_shared": ["--approach", "cyclic", "--redundancy", "shared"],
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/convergence_grid.json")
    ap.add_argument("--network", type=str, default="LeNet")
    ap.add_argument("--dataset", type=str, default="synthetic-mnist")
    ap.add_argument("--eval-every", type=int, default=5)
    ap.add_argument("--max-steps", type=int, default=150)
    ap.add_argument("--target", type=float, default=0.98)
    ap.add_argument("--num-workers", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--cpu-mesh", type=int, default=0)
    ap.add_argument("--rows", type=str, default="",
                    help="comma-separated subset of row labels (default all)")
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    labels = [s for s in args.rows.split(",") if s] or list(ROWS)
    tmp_dir = os.path.join(os.path.dirname(args.out) or ".", "_grid_tmp")
    os.makedirs(tmp_dir, exist_ok=True)

    grid = {}
    for label in labels:
        extra = ROWS[label]
        tmp = os.path.join(tmp_dir, f"{label}.json")
        argv_row = [
            "--out", tmp,
            "--network", args.network, "--dataset", args.dataset,
            "--num-workers", str(args.num_workers),
            "--batch-size", str(args.batch_size),
            "--eval-every", str(args.eval_every),
            "--max-steps", str(args.max_steps),
            "--target", str(args.target),
        ] + extra
        print(f"grid: running {label} ...", flush=True)
        time_to_acc.main(argv_row)
        with open(tmp) as fh:
            grid[label] = json.load(fh)
        r = grid[label]["reached"]
        pts = len(grid[label]["curve"])
        print(f"grid: {label}: {pts} curve points, "
              f"reached={r and (r['step'], r['prec1_test'])}", flush=True)

    report = {
        "schedule": {
            "network": args.network, "dataset": args.dataset,
            "num_workers": args.num_workers,
            "batch_size_per_worker": args.batch_size,
            "eval_every": args.eval_every, "max_steps": args.max_steps,
            "target_prec1": args.target,
            "attack": "rev_grad, 1 adversary (seeded schedule shared "
                      "across rows; mean_clean row is the no-attack anchor)",
        },
        "rows": grid,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=1)
    print(json.dumps({k: {"points": len(v["curve"]),
                          "reached_step": v["reached"] and v["reached"]["step"],
                          "final_prec1": v["curve"][-1]["prec1_test"]
                          if v["curve"] else None}
                      for k, v in grid.items()}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
