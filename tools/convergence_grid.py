#!/usr/bin/env python
"""Multi-point convergence-under-attack curves for every approach and
aggregator under one identical schedule (VERDICT r2 item 8).

Runs tools/time_to_acc.py's measurement for each row of the grid — LeNet /
synthetic-MNIST, n=8 workers, one rev_grad adversary (seeded schedule shared
across rows), eval every ``--eval-every`` steps from step 1 — and writes one
JSON with all curves side by side (baselines_out/convergence_grid.json), the
routine artifact the reference establishes with its convergence oracle
(src/distributed_evaluator.py:92-110).

Rows: cyclic simulate + shared, maj_vote (r=4 | n=8), the three
reference-parity baselines (mean / geo-median / krum) and the four
beyond-reference aggregators (coord_median / trimmed_mean / multi_krum /
bulyan) — all under one rev_grad adversary — plus a clean mean anchor, and
a colluding-attack block (ipm / alie rows with their own worker_fail and
magnitude, recorded per row in the artifact's config blocks).

Usage: python tools/convergence_grid.py --cpu-mesh 8 [--eval-every 5]
       [--max-steps 150] [--rows cyclic_sim,geomedian,...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# sibling tool sharing the measurement loop; resolves in both contexts (the
# sys.path.insert above puts the repo root first)
from tools import time_to_acc  # noqa: E402

ROWS = {
    # label -> extra argv for time_to_acc.main
    "mean_clean": ["--approach", "baseline", "--mode", "normal",
                   "--worker-fail", "0"],
    "mean_attacked": ["--approach", "baseline", "--mode", "normal"],
    "geomedian": ["--approach", "baseline", "--mode", "geometric_median"],
    "krum": ["--approach", "baseline", "--mode", "krum"],
    "coord_median": ["--approach", "baseline", "--mode", "coord_median"],
    "trimmed_mean": ["--approach", "baseline", "--mode", "trimmed_mean"],
    "multi_krum": ["--approach", "baseline", "--mode", "multi_krum"],
    "bulyan": ["--approach", "baseline", "--mode", "bulyan"],
    "maj_vote": ["--approach", "maj_vote", "--group-size", "4"],
    "cyclic_sim": ["--approach", "cyclic", "--redundancy", "simulate"],
    "cyclic_shared": ["--approach", "cyclic", "--redundancy", "shared"],
    # --- colluding attacks (beyond-reference, attacks.py) -----------------
    # strong ipm (8x canonical eps) with 2/8 colluders REVERSES the plain
    # mean's update ((6 - 8)/8 = -0.25 mu); the robust rules must hold.
    "mean_ipm": ["--approach", "baseline", "--mode", "normal",
                 "--err-mode", "ipm", "--adversarial", "-800",
                 "--worker-fail", "2"],
    "geomedian_ipm": ["--approach", "baseline", "--mode", "geometric_median",
                      "--err-mode", "ipm", "--adversarial", "-800",
                      "--worker-fail", "2"],
    "coord_median_ipm": ["--approach", "baseline", "--mode", "coord_median",
                         "--err-mode", "ipm", "--adversarial", "-800",
                         "--worker-fail", "2"],
    # alie's evasion quantile needs colluder mass to be positive at n=8:
    # z(8,3)=0.253 (z(8,1) is NEGATIVE and z(8,2)=0 — an inert payload,
    # attacks.py warns); 8x magnitude makes it a real ~2-sigma deviation
    "krum_alie": ["--approach", "baseline", "--mode", "krum",
                  "--err-mode", "alie", "--worker-fail", "3",
                  "--adversarial", "-800"],
    # vote vs colluders: 2 identical -4mu payloads inside ONE group of 8 —
    # a bitwise minority against 6 identical honest rows
    "maj_vote_ipm": ["--approach", "maj_vote", "--group-size", "8",
                     "--worker-fail", "2", "--err-mode", "ipm",
                     "--adversarial", "-800"],
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/convergence_grid.json")
    ap.add_argument("--network", type=str, default="LeNet")
    ap.add_argument("--dataset", type=str, default="synthetic-mnist")
    ap.add_argument("--eval-every", type=int, default=5)
    ap.add_argument("--max-steps", type=int, default=150)
    ap.add_argument("--target", type=float, default=0.98)
    ap.add_argument("--num-workers", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--cpu-mesh", type=int, default=0)
    ap.add_argument("--rows", type=str, default="",
                    help="comma-separated subset of row labels (default all)")
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    labels = [s for s in args.rows.split(",") if s] or list(ROWS)
    tmp_dir = os.path.join(os.path.dirname(args.out) or ".", "_grid_tmp")
    os.makedirs(tmp_dir, exist_ok=True)

    grid = {}
    for label in labels:
        extra = ROWS[label]
        tmp = os.path.join(tmp_dir, f"{label}.json")
        argv_row = [
            "--out", tmp,
            "--network", args.network, "--dataset", args.dataset,
            "--num-workers", str(args.num_workers),
            "--batch-size", str(args.batch_size),
            "--eval-every", str(args.eval_every),
            "--max-steps", str(args.max_steps),
            "--target", str(args.target),
        ] + extra
        print(f"grid: running {label} ...", flush=True)
        time_to_acc.main(argv_row)
        with open(tmp) as fh:
            grid[label] = json.load(fh)
        r = grid[label]["reached"]
        pts = len(grid[label]["curve"])
        print(f"grid: {label}: {pts} curve points, "
              f"reached={r and (r['step'], r['prec1_test'])}", flush=True)

    report = {
        "schedule": {
            "network": args.network, "dataset": args.dataset,
            "num_workers": args.num_workers,
            "batch_size_per_worker": args.batch_size,
            "eval_every": args.eval_every, "max_steps": args.max_steps,
            "target_prec1": args.target,
            "attack": "per-row (each row's config block records err_mode/"
                      "worker_fail/adversarial; default rows: rev_grad, 1 "
                      "adversary on the shared seeded schedule; mean_clean "
                      "is the no-attack anchor)",
        },
        "rows": grid,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=1)
    print(json.dumps({k: {"points": len(v["curve"]),
                          "reached_step": v["reached"] and v["reached"]["step"],
                          "final_prec1": v["curve"][-1]["prec1_test"]
                          if v["curve"] else None}
                      for k, v in grid.items()}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
