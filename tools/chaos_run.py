#!/usr/bin/env python
"""Chaos harness: drive the deterministic fault × loop matrix and commit
``baselines_out/chaos_matrix.json``.

Every fault class the resilience layer (draco_tpu/resilience, ISSUE 6)
claims to handle is injected into real production-loop runs — the coded-DP
CNN Trainer and two TransformerLM routes (single-shard fold + GSPMD tp),
eager (K=1) and scan-chunked (K=4) — and the outcome is CLASSIFIED, not
eyeballed:

  masked              final params bitwise-equal to the fault-free run of
                      the same loop (supervision/vote absorbed the fault)
  guarded             run completed with guard_trips > 0 and finite final
                      params (the in-graph guard skipped the poisoned
                      update; bounded degradation, training continued)
  preempted_resumed   SIGTERM produced the "preempted" terminal heartbeat
                      state + a resumable boundary checkpoint, and resuming
                      from it reproduced the fault-free final params
                      bitwise (the elasticity round trip)
  recovered_walkback  a corrupt/truncated newest checkpoint raised the
                      named CheckpointCorruptError on direct load, and the
                      checkpoint_step=-1 walk-back resume retrained from
                      the previous good one to the bitwise fault-free state
  degraded_bounded    approx-family straggle cells (ISSUE 8): the run
                      completed finite with zero guard trips, the victim
                      really stayed absent (and was never accused —
                      absence is an erasure, not evidence), and every
                      step's measured decode_residual sat under its
                      analytic decode_residual_bound — the bounded,
                      measurable degradation the family trades exactness
                      for
  degraded_error      a NAMED error propagated and the terminal heartbeat
                      says "crashed" with a cause (graceful: diagnosable,
                      no hang, no raw traceback class)
  FAILED              anything else — an unnamed error, a wrong terminal
                      state, a divergent resume, or (worker-targeted
                      faults) an unattributed survival. ``all_ok`` goes
                      false.

Worker-targeted faults (``nan_grad:w<k>``, ``over_budget``) additionally
must ATTRIBUTE: the per-worker forensics columns (obs/forensics.py, ISSUE
7) at the fault step have to accuse every injected worker — the cell
records ``injected`` / ``accused`` / ``attributed`` and an unattributed
survival is a FAILED cell, because "the guard saved the run but nobody
knows whose fault it was" is exactly the observability gap this layer
closes.

Every cell also runs the incident engine (``incident_watch="on"``,
obs/incidents.py, ISSUE 13) and carries an ``incident`` verdict: the
injected fault class must raise EXACTLY the expected incident type(s) —
nan_grad the attributed ``nonfinite`` incident, over_budget the attributed
``guard`` incident, prefetch faults ``starvation`` where the supervision
restart is observable — and fault classes the resilience layer absorbs
with clean telemetry (straggle inside budget, sigterm, checkpoint
corruption) must raise NONE. An unraised, mis-typed, mis-attributed, or
spurious incident is a FAILED cell.

``tools/perf_watch.py`` folds the committed matrix, so a fault class
silently flipping from masked/guarded to FAILED — or an ``attributed`` /
``incident.ok`` flag flipping false — gates nonzero.

Usage (CPU, ~10 min):
  python tools/chaos_run.py --cpu-mesh 8
  python tools/chaos_run.py --cpu-mesh 8 --loops cnn_k4 --faults nan_grad
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from draco_tpu.cli import maybe_force_cpu_mesh  # noqa: E402

FAULTS = ("nan_grad", "over_budget", "prefetch_crash", "prefetch_hang",
          "sigterm", "ckpt_corrupt", "ckpt_truncate", "straggle",
          "adversary", "drift_grad", "subtree_straggle")
# the autopilot REAL-wire cell (ISSUE 15): an int8-wire run under the
# declarative drift_grad window must raise the numerics_drift incident AND
# the autopilot must actuate — a `wire_widen` remediation moving the wire
# dtype one f32-ward step as a warm program swap, recorded + attributed in
# incidents.jsonl. Only the dedicated ap_wire loop runs it.
WIRE_FAULTS = ("drift_grad",)
# drift window end: covers the second chunk so the widened regime actually
# dispatches (boundaries at 4/8/12; the episode opens ~step 7, the widen
# fires at boundary 8, chunk 9-12 runs on the widened wire)
WIRE_MAX_STEPS = 12
# the declarative within-budget adversary episode (faults.apply_adversary)
# runs on the dedicated random-attack loops: cfg.err_mode="random" (the
# seeded random-gradient attack, ISSUE 14 satellite — a reference TODO
# until now), base adversary_count=0 so the event's worker is the ONLY
# live adversary. Expected outcome: the cyclic decode detects, attributes
# AND excises the attack (detection P/R 1.0 at the fault step, named
# worker accused, zero guard trips) — `attributed_excised`.
RAND_FAULTS = ("adversary",)
# eager loops have no chunk prefetcher thread and ckpt rows ride the
# chunked regime; the in-graph + signal faults cover both regimes
EAGER_FAULTS = ("nan_grad", "over_budget", "sigterm")
# the approx code family's cells (ISSUE 8): straggle is ITS fault model
# (a sustained drop is a scheduled erasure the decode absorbs boundedly —
# the expected outcome is degraded_bounded, not masked/guarded); nan_grad
# must still be guarded + attributed, sigterm must still round-trip. The
# exact-code loops skip straggle — their budget arithmetic already has
# dedicated cells (the over_budget class) and a sustained drop on top of
# the live adversary would just re-test the same locator failure.
APPROX_FAULTS = ("straggle", "nan_grad", "sigterm")
STRAGGLE_WORKER = 3  # the named straggle victim (absent ≠ accused target)
# the segmented-wire loops (ISSUE 16): the same production loops with the
# wire split into S=2 segments and the live-adversary budget released
# (adversary_count=0), so the cell's fault is the only one in play.
# `sigterm` lands between chunk dispatches of the SEGMENTED regime and
# must round-trip through the existing preemption/resume machinery
# bitwise against the loop's own S=2 clean run (`preempted_resumed`).
# `straggle` runs on the vote-family segmented loop (mv_seg2), where a
# mid-stream drop is bitwise-MASKED — the vote picks among bitwise-equal
# replicas, so segmenting the wire must leave the clean-run equality
# intact. The cyclic segmented loops skip straggle here: per-segment
# recombination legitimately rounds differently from S=1 once the honest
# support shifts, so their straggle/adversary equivalence is the
# tolerance-based pin in tests/test_segments.py, not a bitwise chaos cell.
SEG_FAULTS = ("straggle", "sigterm")
# the tree-topology loops (ISSUE 17): sigterm lands BETWEEN chunk
# dispatches of the hierarchical regime and must round-trip through the
# existing preemption/resume machinery bitwise against the loop's own tree
# clean run (`preempted_resumed` — the level structure lives inside the
# jitted program, so a boundary checkpoint is level-consistent by
# construction). `subtree_straggle` drops an ENTIRE leaf group (the
# worst-case-one-group shape the per-group budget is sized for) on the
# approx tree loop: the group's partial recovers nothing, the root
# residual must still sit under the Cauchy-Schwarz-folded bound every
# step, and NO member of the victim group is ever accused — absence is an
# erasure, not evidence, even when a whole subtree goes dark.
TREE_FAULTS = ("sigterm", "subtree_straggle")
SUBTREE_WORKERS = (4, 5, 6, 7)  # the whole second leaf group at g=4, n=8

FAULT_STEP = 5  # mid-run, between the two eval/ckpt boundaries (4 and 8)
# sigterm lands ON the first chunk boundary so the K=4 loops stop with
# half the run still ahead (a step strictly inside (4, 8) would only be
# honored at the final chunk's end — a degenerate "preemption" at step 8)
SIGTERM_STEP = 4
MAX_STEPS = 8
EVAL_FREQ = 4
NUM_WORKERS = 8
# worker-targeted in-graph faults name their victim explicitly so the cell
# can assert the forensics columns (obs/forensics.py) attribute the fault
# to exactly this worker; faults that attribute are checked against the
# run's own metrics.jsonl at the fault step (ISSUE 7)
NAN_WORKER = 3
ATTRIBUTED_FAULTS = ("nan_grad", "over_budget", "adversary")


def _base_cfg_kw():
    return dict(
        approach="cyclic", worker_fail=1, redundancy="shared",
        batch_size=4, num_workers=NUM_WORKERS, max_steps=MAX_STEPS,
        eval_freq=EVAL_FREQ, log_every=1, lr=0.05, compress_ckpt=True,
        step_guard="on", prefetch_timeout_s=2.0, prefetch_restarts=2,
        # numerics observatory on in EVERY cell (obs/numerics.py, ISSUE
        # 10): the columns must stay finite-sentineled under each fault
        # class — the nan_grad cells assert it (_numerics_verdict)
        numerics_watch="on",
        # incident engine on in EVERY cell (obs/incidents.py, ISSUE 13):
        # each fault class must raise exactly its expected incident type
        # with the right worker attribution (_incident_verdict)
        incident_watch="on",
    )


def _loops():
    """loop name -> (make_cfg(**kw), run(cfg, steps=None) -> params_vec)."""
    import jax
    import numpy as np

    from draco_tpu.config import TrainConfig

    def pv(state):
        return np.concatenate([
            np.ravel(x) for x in jax.tree.leaves(jax.device_get(state.params))
        ])

    def cnn_cfg(**kw):
        base = dict(_base_cfg_kw(), network="FC", dataset="synthetic-mnist")
        base.update(kw)
        return TrainConfig(**base)

    def cnn_run(cfg, steps=None):
        # Trainer.run's max_steps is ABSOLUTE; the matrix passes a step
        # COUNT (the LM routes' convention), so resume runs translate via
        # the restored cursor
        from draco_tpu.training.trainer import Trainer

        t = Trainer(cfg, quiet=True)
        try:
            t.run(max_steps=None if steps is None
                  else t._start_step - 1 + steps)
        finally:
            t.close()
        return pv(t.state)

    def lm_cfg(**kw):
        base = dict(_base_cfg_kw(), network="TransformerLM",
                    dataset="synthetic-text", seq_len=16, vocab=32,
                    model_dim=32, model_heads=2, model_layers=1)
        base.update(kw)
        return TrainConfig(**base)

    def lm_fold_run(cfg, steps=None):
        from draco_tpu.parallel import make_mesh_2d
        from draco_tpu.parallel.sp_step import train_sp

        state, _ = train_sp(cfg, make_mesh_2d(cfg.num_workers, 1),
                            steps=steps, quiet=True)
        return pv(state)

    def lm_tp_run(cfg, steps=None):
        from draco_tpu.parallel.mesh import make_mesh_wtp
        from draco_tpu.parallel.tp_step import train_tp

        state, _ = train_tp(cfg, make_mesh_wtp(4, 2), steps=steps,
                            quiet=True)
        return pv(state)

    def with_k(cfg_fn, k, **fixed):
        return lambda **kw: cfg_fn(steps_per_call=k, **fixed, **kw)

    # the segmented-wire loops (ISSUE 16): wire_segments rides as a
    # DEFAULT so the straggle cell can rebuild the same loop at S=1 for
    # its bitwise segment-invariance reference
    def with_seg(cfg_fn, k, **fixed):
        def make(**kw):
            kw.setdefault("wire_segments", 2)
            return cfg_fn(steps_per_call=k, **fixed, **kw)
        return make

    # the tree-topology loops (ISSUE 17): topology/fanout ride as DEFAULTS
    # so resume runs rebuild the identical hierarchical program
    def with_tree(cfg_fn, k, **fixed):
        def make(**kw):
            kw.setdefault("topology", "tree")
            kw.setdefault("tree_fanout", 4)
            return cfg_fn(steps_per_call=k, **fixed, **kw)
        return make

    # the approx family rejects live adversaries (config.validate: no
    # Byzantine certificate), so its cells run worker_fail=0 with the
    # ISSUE 8 design point r=1.5 / α=0.25 on the same FC loop
    approx_kw = dict(approach="approx", worker_fail=0,
                     redundancy="shared", code_redundancy=1.5,
                     straggler_alpha=0.25)

    # the random-attack loops (ISSUE 14 satellite): err_mode="random" with
    # the code budget reserved (adversary_count=0), so the `adversary`
    # fault event's worker is the only live adversary and the clean run
    # trains attack-free
    rand_kw = dict(err_mode="random", adversary_count=0)

    # the autopilot wire-dial loop (ISSUE 15): a REAL int8 wire with the
    # policy engine live — drift_grad must widen it (WIRE_FAULTS).
    # adversary_count=0 isolates the drift: the cell's surface is the
    # numerics_drift → wire_widen chain, not the (separately-celled)
    # Byzantine detection path — a live adversary would legitimately
    # collapse trust and blur the incident contract
    ap_wire_kw = dict(wire_dtype="int8", autopilot="on",
                      adversary_count=0, max_steps=WIRE_MAX_STEPS)

    return {
        "cnn_k1": (with_k(cnn_cfg, 1), cnn_run),
        "cnn_k4": (with_k(cnn_cfg, 4), cnn_run),
        "lm_k1": (with_k(lm_cfg, 1), lm_fold_run),
        "lm_k4": (with_k(lm_cfg, 4), lm_fold_run),
        "lm_tp_k4": (with_k(lm_cfg, 4, tensor_shards=2), lm_tp_run),
        "approx_k1": (with_k(cnn_cfg, 1, **approx_kw), cnn_run),
        "approx_k4": (with_k(cnn_cfg, 4, **approx_kw), cnn_run),
        "cnn_rand_k1": (with_k(cnn_cfg, 1, **rand_kw), cnn_run),
        "cnn_rand_k4": (with_k(cnn_cfg, 4, **rand_kw), cnn_run),
        "ap_wire_k4": (with_k(cnn_cfg, 4, **ap_wire_kw), cnn_run),
        # the segmented-wire loops (ISSUE 16): adversary_count=0 releases
        # the code budget so the cell's injected fault is the only one in
        # play; mv_seg2 is the vote family (group replication), where the
        # straggle drop must stay bitwise-masked under the segmented wire
        "cnn_seg2_k4": (with_seg(cnn_cfg, 4, adversary_count=0), cnn_run),
        "lm_seg2_k4": (with_seg(lm_cfg, 4, adversary_count=0), lm_fold_run),
        "mv_seg2_k4": (with_seg(cnn_cfg, 4, approach="maj_vote",
                                group_size=4, adversary_count=0), cnn_run),
        # the tree-topology loops (ISSUE 17): adversary_count=0 (the g=4
        # per-group budget s_g = min(1, 0) carries no live adversary — the
        # detection-parity pin lives in tests/test_tree.py at g=8);
        # approx_tree runs the whole-leaf-group drop at the α=0.5 design
        # point that covers it
        "cnn_tree_k4": (with_tree(cnn_cfg, 4, adversary_count=0), cnn_run),
        "approx_tree_k4": (with_tree(cnn_cfg, 4, approach="approx",
                                     worker_fail=0, redundancy="shared",
                                     code_redundancy=2.0,
                                     assignment_scheme="pairwise",
                                     straggler_alpha=0.5), cnn_run),
    }


def _status(train_dir):
    try:
        with open(os.path.join(train_dir, "status.json")) as fh:
            status = json.load(fh)
    except Exception:
        return {}
    # versioned payloads must satisfy the central schema contract table
    # (obs/heartbeat.check_status_schema); pre-versioning files carry no
    # field (tolerated). An unknown schema means the harness and the loops
    # disagree on the payload shape, and folding it silently would
    # misclassify every cell
    from draco_tpu.obs.heartbeat import check_status_schema

    return check_status_schema(status, f"{train_dir}/status.json",
                               "tools/chaos_run.py")


def _accusation(train_dir, fault, step):
    """(injected, accused, attributed) at the fault step, from the run's
    own metrics.jsonl forensics columns (obs/forensics.py; log_every=1, so
    every step's record is on disk). ``injected``: the worker(s) the fault
    plan targeted — the named :w victim for nan_grad, the over-budget
    step's live adversary row (packed in-graph as the seeded ground truth)
    for over_budget. ``attributed``: every injected worker is in the
    step's accused set."""
    from draco_tpu.obs import replay
    from draco_tpu.obs.forensics import record_masks

    rec = replay.record_at_step(os.path.join(train_dir, "metrics.jsonl"),
                                step)
    masks = record_masks(rec, NUM_WORKERS) if rec else None
    if masks is None:
        return None, None, False
    accused = sorted(i for i, b in enumerate(masks["accused"]) if b)
    if fault == "nan_grad":
        injected = [NAN_WORKER]
    else:  # over_budget: the mutated schedule row IS the injected set
        injected = sorted(i for i, b in enumerate(masks["adv"]) if b)
    attributed = bool(injected) and set(injected) <= set(accused)
    return injected, accused, attributed


def _straggle_verdict(train_dir, workers, step):
    """The approx straggle cell's bounded-degradation evidence, from the
    run's own metrics.jsonl (log_every=1): ``dropped`` — every victim's
    present bit is off on every record from the fault step on (the
    sustained drop really landed); ``bounded`` — every train record's
    measured decode_residual sits under its analytic
    decode_residual_bound (the ISSUE 8 certificate; under topology="tree"
    the bound is the Cauchy-Schwarz fold across groups and must hold even
    with a whole leaf group dark); ``never_accused`` — no scheduled
    straggler's accused bit ever fires (absence is an erasure, not
    evidence; obs/forensics). ``workers``: the victim set — one worker for
    the classic cell, a whole leaf group for subtree_straggle."""
    from draco_tpu.obs import replay
    from draco_tpu.obs.forensics import record_masks

    workers = list(workers)
    recs = replay.train_records(os.path.join(train_dir, "metrics.jsonl"))
    if not recs:
        return {"dropped": False, "bounded": False, "never_accused": False}
    dropped = bounded = never_accused = True
    for r in recs:
        masks = record_masks(r, NUM_WORKERS)
        if masks is None:
            dropped = bounded = never_accused = False
            break
        if r.get("step", 0) >= step \
                and any(masks["present"][w] for w in workers):
            dropped = False
        if any(masks["accused"][w] for w in workers):
            never_accused = False
        if not (r.get("decode_residual", float("nan"))
                <= r.get("decode_residual_bound", float("-inf")) + 1e-5):
            bounded = False
    return {"dropped": dropped, "bounded": bounded,
            "never_accused": never_accused}


def _numerics_verdict(train_dir, step):
    """ISSUE 10 NaN-safety at the fault step: the numerics columns carry
    FINITE sentinel values (stats are computed over the finite elements
    only — the fault's signature is the nonfinite fraction going loud,
    never a NaN column), and no scalar column of the record is NaN/Inf —
    i.e. an injected non-finite gradient does not poison the metric
    block. Returns {numerics_finite, fault_visible}."""
    import math

    from draco_tpu.obs import replay

    rec = replay.record_at_step(os.path.join(train_dir, "metrics.jsonl"),
                                step)
    if rec is None or "nx_grad_nonfinite" not in rec:
        return {"numerics_finite": False, "fault_visible": False}
    # the observatory columns + the training metrics must be finite; the
    # decode-health residual is deliberately NOT in this set — a NaN
    # decode_residual at the fault step IS the guard's loud signal
    # (resilience/guards.py), not poisoning
    finite = all(
        math.isfinite(float(v)) for k, v in rec.items()
        if isinstance(v, (int, float))
        and (k.startswith("nx_") or k.startswith("shadow_")
             or k in ("loss", "prec1")))
    return {"numerics_finite": bool(finite),
            "fault_visible": bool(rec["nx_grad_nonfinite"] > 0.0)}


def _expected_incidents(loop, fault):
    """The cell's incident contract (obs/incidents.py, ISSUE 13):
    ``required`` = [(type, attribution)] that MUST be raised — attribution
    is a worker list, "injected" (the cell's injected set must be a subset
    of the incident's workers), or None (no attribution expected);
    ``allowed`` = extra types tolerated alongside. Any raised type outside
    required ∪ allowed is a spurious incident and FAILS the cell."""
    if fault == "nan_grad":
        # the non-finite ingest incident, attributed to the named victim;
        # the guard trip + loud residual + a trust dip ride along
        return ([("nonfinite", [NAN_WORKER])],
                {"guard", "decode_residual", "trust"})
    if fault == "over_budget":
        # the guard skips the poisoned update: the incident must name (at
        # least) every injected adversary; the loud residual rides along
        return ([("guard", "injected")],
                {"decode_residual", "nonfinite", "trust"})
    if fault == "prefetch_crash":
        # supervised restart (resilience/supervisor.py) surfaces at the
        # next beat as the starvation incident — no worker to name
        return [("starvation", None)], set()
    if fault == "prefetch_hang":
        # the LM token prefetcher stalls (PrefetchStallError → restart);
        # the CNN chunk gather pays the sleep inline on the main thread,
        # so there is no restart and nothing to detect
        if loop.startswith("lm"):
            return [("starvation", None)], set()
        return [], {"starvation", "throughput"}
    if fault == "straggle":
        # a SUSTAINED drop (the spot-instance shape): the straggle
        # detector (ISSUE 14 — the autopilot's dial-down evidence) must
        # fire once the victim's absence streak crosses its threshold,
        # attributed to the named victim; the decode itself stays clean
        return [("straggle", [STRAGGLE_WORKER])], set()
    if fault == "subtree_straggle":
        # an ENTIRE leaf group drops (ISSUE 17): the detector must fire
        # naming every member of the dark subtree — and nobody else
        return [("straggle", list(SUBTREE_WORKERS))], set()
    if fault == "adversary":
        # a single within-budget attack step: detected, attributed and
        # excised by the decode — one accusation cannot collapse EW trust
        # (the hysteresis), so NO incident may open
        return [], set()
    if fault == "drift_grad":
        # the declarative drift window must raise numerics_drift (no
        # worker to name — the whole wire drifts); the regime swap's
        # compile pause may dent a beat (throughput tolerated)
        return [("numerics_drift", None)], {"throughput"}
    # sigterm (graceful preemption), ckpt_* (offline recovery): the
    # resilience layer absorbs these with clean telemetry, and a spurious
    # incident is exactly the flapping the hysteresis exists to prevent
    return [], set()


def _incident_verdict(train_dir, loop, fault, injected=None):
    """Diff the cell's incidents.jsonl onsets against the contract. The
    ledger is torn/empty/missing tolerated (obs/replay) — an expected
    incident that never made it to disk is exactly a FAILED verdict."""
    from draco_tpu.obs import replay

    onsets = [e for e in replay.iter_jsonl(
        os.path.join(train_dir, "incidents.jsonl"))
        if e.get("event") == "onset" and e.get("type")]
    raised = sorted({e["type"] for e in onsets})
    required, allowed = _expected_incidents(loop, fault)
    ok, details = True, []
    for typ, attr in required:
        ons = [e for e in onsets if e["type"] == typ]
        if not ons:
            ok = False
            details.append(f"expected incident {typ!r} not raised")
            continue
        if attr is not None:
            want = set(injected or []) if attr == "injected" else set(attr)
            got = set()
            for e in ons:
                got |= set(e.get("workers") or [])
            if not want or not want <= got:
                ok = False
                details.append(f"{typ} attributed {sorted(got)}, expected "
                               f"superset of {sorted(want)}")
    unexpected = set(raised) - {t for t, _ in required} - allowed
    if unexpected:
        ok = False
        details.append(f"spurious incident(s): {sorted(unexpected)}")
    verdict = {"ok": ok, "raised": raised,
               "required": [t for t, _ in required]}
    if details:
        verdict["detail"] = "; ".join(details)
    return verdict


def _attempt(run, cfg, steps=None):
    """(params_vec | None, error | None) — a run either finishes or raises."""
    try:
        return run(cfg, steps), None
    except Exception as e:  # noqa: BLE001 — classification IS the point
        return None, e


NAMED_ERRORS = ("InjectedFaultError", "PrefetchStallError",
                "CheckpointCorruptError")


def run_case(loop: str, fault: str, make_cfg, run, clean_vec, workdir):
    """Execute one (loop, fault) cell and classify the outcome."""
    import numpy as np

    from draco_tpu.utils import checkpoint as ckpt

    d = os.path.join(workdir, f"{loop}_{fault}")
    row = {"loop": loop, "fault": fault, "ok": False, "outcome": "FAILED"}
    # a REUSED --workdir must not let a previous invocation's onsets
    # satisfy (or violate) this run's incident contract — the verdict
    # folds every onset in the cell's incidents.jsonl
    try:
        os.remove(os.path.join(d, "incidents.jsonl"))
    except OSError:
        pass

    if fault in ("ckpt_corrupt", "ckpt_truncate"):
        # victim run (no injection during training), then corrupt the
        # NEWEST checkpoint and resume with walk-back
        vec, err = _attempt(run, make_cfg(train_dir=d))
        if err is not None:
            row["detail"] = f"victim run failed: {type(err).__name__}: {err}"
            return row
        newest = ckpt.available_steps(d)[-1]
        path = os.path.join(d, f"model_step_{newest}.dcg")
        with open(path, "rb") as fh:
            raw = bytearray(fh.read())
        if fault == "ckpt_corrupt":
            raw[len(raw) // 2] ^= 0xFF
            with open(path, "wb") as fh:
                fh.write(bytes(raw))
        else:
            with open(path, "wb") as fh:
                fh.write(bytes(raw[: len(raw) // 2]))
        # the corrupt bytes must surface as the NAMED error, not
        # struct/zlib guts (resume itself auto-walks-back, so probe the
        # integrity check directly)
        try:
            ckpt.verify(d, newest)
            row["detail"] = "corrupt checkpoint verified clean"
            return row
        except ckpt.CheckpointCorruptError as e:
            row["named_error"] = f"{type(e).__name__}"
            row["error_detail"] = str(e)[:200]
        except Exception as e:
            row["detail"] = (f"corrupt load raised unnamed "
                             f"{type(e).__name__}: {e}")
            return row
        # walk-back resume: -1 skips the corrupt newest, reloads the
        # previous good one, retrains to the end — must be bitwise clean
        prev_good = ckpt.available_steps(d)[-2]
        vec2, err2 = _attempt(run, make_cfg(train_dir=d, checkpoint_step=-1),
                              steps=MAX_STEPS - prev_good)
        if err2 is not None:
            row["detail"] = f"walk-back resume failed: {err2}"
            return row
        row["walked_back_to"] = prev_good
        row["resume_bitwise_equal"] = bool(np.array_equal(clean_vec, vec2))
        if row["resume_bitwise_equal"]:
            row.update(ok=True, outcome="recovered_walkback")
        return row

    # injected-fault run. prefetch_hang duration: on the LM token loop the
    # sleep lands on the prefetch WORKER thread, so it must outlast the
    # queue-wait timeout (2 s) plus the device's chunk — 20 s forces the
    # stall + supervised-restart path; the CNN chunk gather computes its
    # indices on the main thread, where the sleep is an inline delay the
    # loop simply rides out (4 s keeps the matrix quick)
    step = SIGTERM_STEP if fault == "sigterm" else FAULT_STEP
    spec = f"{fault}@{step}"
    if fault == "subtree_straggle":
        # one sustained straggle event per member of the victim leaf group
        # (the fault grammar attributes per-event :w victims) — the whole
        # subtree goes dark at once
        spec = ",".join(f"straggle@{step}:w{w}" for w in SUBTREE_WORKERS)
    if fault == "drift_grad":
        # declarative window covering the rest of the run, so the widened
        # regime's chunk dispatches while the drift is still live
        spec = f"drift_grad@{step}-{WIRE_MAX_STEPS}"
    if fault == "nan_grad":
        spec += f":w{NAN_WORKER}"  # named victim — the attribution target
    if fault == "adversary":
        spec += f":w{NAN_WORKER}"  # named attacker — attribution target
    if fault == "straggle":
        # named victim, no :d — sustained to the end of the run (the
        # spot-instance shape the approx family exists for)
        spec += f":w{STRAGGLE_WORKER}"
    if fault == "prefetch_hang":
        spec += ":d20" if loop.startswith("lm") else ":d4"
    vec, err = _attempt(run, make_cfg(train_dir=d, fault_spec=spec))
    status = _status(d)
    row["terminal_state"] = status.get("state")
    guard = status.get("guard") or {}
    row["guard_trips"] = guard.get("trips", 0.0)
    if fault in ATTRIBUTED_FAULTS:
        # per-worker forensics must point at the injected worker(s) —
        # degrading boundedly is not enough, the ledger has to NAME them
        injected, accused, attributed = _accusation(d, fault, step)
        row["injected"] = injected
        row["accused"] = accused
        row["attributed"] = attributed
    if fault == "nan_grad":
        # ISSUE 10 NaN-safety pin: the numerics columns at the fault step
        # are finite sentinels and the injected non-finite gradient is
        # VISIBLE in the nonfinite-fraction column
        row.update(_numerics_verdict(d, step))

    if err is not None:
        name = type(err).__name__
        row["named_error"] = name
        row["error_detail"] = str(err)[:200]
        if name in NAMED_ERRORS and status.get("state") == "crashed":
            row.update(ok=True, outcome="degraded_error")
        else:
            row["detail"] = f"unnamed error {name} or wrong terminal state"
        return row

    if status.get("state") == "preempted":
        resumable = status.get("resumable_step")
        row["resumable_step"] = resumable
        if resumable is None:
            row["detail"] = "preempted without a resumable checkpoint"
            return row
        vec2, err2 = _attempt(run,
                              make_cfg(train_dir=d, checkpoint_step=resumable),
                              steps=MAX_STEPS - resumable)
        if err2 is not None:
            row["detail"] = f"resume failed: {err2}"
            return row
        row["resume_bitwise_equal"] = bool(np.array_equal(clean_vec, vec2))
        if row["resume_bitwise_equal"]:
            row.update(ok=True, outcome="preempted_resumed")
        return row

    # completed: masked (bitwise clean), guarded (skipped, finite), or —
    # straggle on the approx family — degraded_bounded (the decode
    # diverges from the fault-free run BY DESIGN, but every step's
    # measured residual sat under its analytic bound, the victim really
    # stayed absent, and absence was never accused)
    row["bitwise_equal_clean"] = bool(np.array_equal(clean_vec, vec))
    row["final_finite"] = bool(np.all(np.isfinite(vec)))
    if fault == "straggle" and "_seg" in loop:
        # the segmented-wire straggle cell (ISSUE 16, vote family): the
        # mid-stream drop must stay bitwise-MASKED with the wire split
        # into segments — the vote picks among bitwise-equal replicas, so
        # the S=2 run's final params land on the fault-free clean run of
        # the same loop; plus the victim really stayed absent and absence
        # was never accused (erasure, not evidence)
        from draco_tpu.obs import replay
        from draco_tpu.obs.forensics import record_masks

        recs = replay.train_records(os.path.join(d, "metrics.jsonl"))
        dropped = never_accused = bool(recs)
        for r in recs:
            masks = record_masks(r, NUM_WORKERS)
            if masks is None:
                dropped = never_accused = False
                break
            if (r.get("step", 0) >= step
                    and masks["present"][STRAGGLE_WORKER]):
                dropped = False
            if masks["accused"][STRAGGLE_WORKER]:
                never_accused = False
        row["dropped"] = dropped
        row["never_accused"] = never_accused
        if (row["final_finite"] and status.get("state") == "done"
                and row["guard_trips"] == 0 and dropped and never_accused
                and row["bitwise_equal_clean"]):
            row.update(ok=True, outcome="masked")
        else:
            row["detail"] = (f"segmented straggle not masked: "
                             f"bitwise={row['bitwise_equal_clean']} "
                             f"dropped={dropped} "
                             f"never_accused={never_accused} "
                             f"guard_trips={row['guard_trips']}")
        return row
    if fault in ("straggle", "subtree_straggle"):
        victims = (SUBTREE_WORKERS if fault == "subtree_straggle"
                   else [STRAGGLE_WORKER])
        verdict = _straggle_verdict(d, victims, step)
        row.update(verdict)
        if (row["final_finite"] and status.get("state") == "done"
                and row["guard_trips"] == 0 and all(verdict.values())):
            row.update(ok=True, outcome="degraded_bounded")
        else:
            row["detail"] = (f"{fault} cell not bounded-degraded: "
                             f"{verdict}")
        return row
    if fault == "drift_grad":
        # the autopilot wire-dial cell (ISSUE 15): the injected numerics
        # drift must be SEEN (numerics_drift incident — checked by the
        # incident contract) and ACTED ON — a `wire_widen` remediation in
        # incidents.jsonl moving the regime's wire dtype one f32-ward
        # step, attributed to the drift episode. The drift itself is
        # finite by construction, so the run must finish clean (no guard
        # trips — a guarded drift cell would mean the injection broke the
        # decode instead of the numerics).
        from draco_tpu.obs import replay

        rems = [e for e in replay.iter_jsonl(
            os.path.join(d, "incidents.jsonl"))
            if e.get("event") == "remediation"]
        widens = [r for r in rems if r.get("action") == "wire_widen"]
        row["remediations"] = [r.get("action") for r in rems]
        row["widened"] = bool(widens)
        row["widen_attributed"] = bool(widens) and all(
            (r.get("trigger") or {}).get("type")
            in ("numerics_drift", "decode_residual") for r in widens)
        row["wire_dtype_after"] = (
            ((widens[-1].get("regime") or {}).get("wire_dtype"))
            if widens else None)
        if (row["final_finite"] and status.get("state") == "done"
                and row["guard_trips"] == 0 and row["widened"]
                and row["widen_attributed"]):
            row.update(ok=True, outcome="wire_widened")
        else:
            row["detail"] = (f"drift cell not widened cleanly: "
                             f"widened={row['widened']} attributed="
                             f"{row['widen_attributed']} "
                             f"guard_trips={row['guard_trips']}")
        return row
    if fault == "adversary":
        # the random-attack cell (ISSUE 14 satellite): the seeded random
        # gradient must be DETECTED (in-graph detection columns score
        # P/R 1.0 at the fault step), ATTRIBUTED (checked above) and
        # EXCISED (decode exact → no guard trip, run finishes clean).
        # Bitwise equality with the clean run is NOT expected: locating
        # an error changes which honest rows the recombination solves
        # from (different f32 rounding), not the algebraic value.
        from draco_tpu.obs import replay

        rec = replay.record_at_step(os.path.join(d, "metrics.jsonl"),
                                    step)
        detected = bool(rec
                        and rec.get("det_adv") == 1
                        and rec.get("det_tp") == 1
                        and rec.get("located_errors") == 1)
        row["detected"] = detected
        if (row["final_finite"] and status.get("state") == "done"
                and row["guard_trips"] == 0 and detected
                and row["attributed"]):
            row.update(ok=True, outcome="attributed_excised")
        else:
            row["detail"] = (f"random attack not excised cleanly: "
                             f"detected={detected} "
                             f"attributed={row.get('attributed')} "
                             f"guard_trips={row['guard_trips']}")
        return row
    if row["bitwise_equal_clean"] and status.get("state") == "done":
        row.update(ok=True, outcome="masked")
    elif (row["guard_trips"] > 0 and row["final_finite"]
          and status.get("state") == "done"):
        row.update(ok=True, outcome="guarded")
    else:
        row["detail"] = ("completed but neither masked nor guarded "
                         "(silent divergence)")
    if row["ok"] and fault in ATTRIBUTED_FAULTS and not row["attributed"]:
        # survived the fault but could not NAME the culprit — that is a
        # forensics regression, not an ok cell
        row.update(ok=False, outcome="FAILED",
                   detail=f"fault survived but unattributed: injected "
                          f"{row['injected']} vs accused {row['accused']}")
    if row["ok"] and fault == "nan_grad" and not (
            row["numerics_finite"] and row["fault_visible"]):
        # survived the fault but the observatory either went NaN (block
        # poisoned) or failed to show the non-finite ingest — the ISSUE
        # 10 NaN-safety contract, not an ok cell
        row.update(ok=False, outcome="FAILED",
                   detail=f"numerics columns under nan_grad: finite="
                          f"{row['numerics_finite']} visible="
                          f"{row['fault_visible']}")
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", type=str,
                    default=os.path.join("baselines_out",
                                         "chaos_matrix.json"))
    ap.add_argument("--loops", type=str, default="",
                    help="comma-separated loop subset (default: all)")
    ap.add_argument("--faults", type=str, default="",
                    help="comma-separated fault subset (default: all)")
    ap.add_argument("--workdir", type=str, default="",
                    help="train dirs land here (default: a temp dir)")
    ap.add_argument("--cpu-mesh", type=int, default=0, metavar="N",
                    help="force an N-device virtual CPU mesh")
    args = ap.parse_args(argv)
    # cpu-mesh bootstrap only, NEVER the persistent compile cache: the
    # chaos matrix classifies outcomes by BITWISE final-state comparison,
    # and cache-enabled XLA:CPU executables corrupt donated carries
    # (mutating output state, NaNs in later checkpoints — caught by this
    # very harness; runtime.enable_compile_cache docstring). Runs are tiny,
    # so compiling uncached costs seconds.
    if args.cpu_mesh:
        maybe_force_cpu_mesh(args)  # skips the cache in explicit CPU mode

    loops = _loops()
    pick_loops = [s for s in args.loops.split(",") if s] or list(loops)
    pick_faults = [s for s in args.faults.split(",") if s] or list(FAULTS)
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_run_")

    rows = []
    for loop in pick_loops:
        make_cfg, run = loops[loop]
        eager = loop.endswith("_k1")
        if "_tree" in loop:
            # the tree-topology loops (ISSUE 17): sigterm round-trips on
            # both; the whole-leaf-group drop is the approx tree's cell
            # (its bounded certificate is what absorbs a dark subtree) —
            # checked FIRST so approx_tree does not fall into the flat
            # approx family's fault triple
            faults = [f for f in pick_faults if f in TREE_FAULTS
                      and (f != "subtree_straggle"
                           or loop.startswith("approx"))]
        elif loop.startswith("approx"):
            # both regimes run the family's own fault triple (ISSUE 8)
            faults = [f for f in pick_faults if f in APPROX_FAULTS]
        elif loop.startswith("cnn_rand"):
            # the random-attack loops run exactly the adversary episode
            faults = [f for f in pick_faults if f in RAND_FAULTS]
        elif loop.startswith("ap_wire"):
            # the autopilot wire-dial loop runs exactly the drift episode
            faults = [f for f in pick_faults if f in WIRE_FAULTS]
        elif "_seg" in loop:
            # the segmented-wire loops run the ISSUE 16 pair; straggle is
            # the vote loop's cell (bitwise-masked there — see SEG_FAULTS)
            faults = [f for f in pick_faults if f in SEG_FAULTS
                      and (f != "straggle" or loop.startswith("mv_"))]
        else:
            faults = [f for f in pick_faults
                      if f not in ("straggle", "subtree_straggle")
                      + RAND_FAULTS + WIRE_FAULTS
                      and not (eager and f not in EAGER_FAULTS)]
        if not faults:
            continue
        clean_dir = os.path.join(workdir, f"{loop}_clean")
        clean_vec, err = _attempt(run, make_cfg(train_dir=clean_dir))
        if err is not None:
            raise SystemExit(f"chaos_run: clean {loop} run failed: {err}")
        for fault in faults:
            row = run_case(loop, fault, make_cfg, run, clean_vec, workdir)
            # incident contract (ISSUE 13): exactly the expected incident
            # type(s), correctly attributed, nothing spurious — checked on
            # the cell's own incidents.jsonl (resume runs append to it)
            verdict = _incident_verdict(
                os.path.join(workdir, f"{loop}_{fault}"), loop, fault,
                row.get("injected"))
            row["incident"] = verdict
            if row["ok"] and not verdict["ok"]:
                row.update(ok=False, outcome="FAILED",
                           detail=f"incident verdict: "
                                  f"{verdict.get('detail', '?')}")
            rows.append(row)
            inc = "+".join(verdict["raised"]) or "-"
            print(f"chaos_run: {loop:9s} {fault:15s} -> "
                  f"{row['outcome']:18s} incidents: {inc}"
                  f"{'' if row['ok'] else '  ** FAILED'}",
                  flush=True)

    by_fault = {}
    for row in rows:
        by_fault.setdefault(row["fault"], []).append(row["ok"])
    summary = {f: {"cells": len(oks), "ok": all(oks)}
               for f, oks in sorted(by_fault.items())}
    payload = {
        "schema": 1,
        "tool": "tools/chaos_run.py",
        "fault_step": FAULT_STEP,
        "max_steps": MAX_STEPS,
        "rows": rows,
        "fault_classes": summary,
        "all_ok": all(r["ok"] for r in rows) and bool(rows),
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    print(f"chaos_run: {sum(r['ok'] for r in rows)}/{len(rows)} cells ok "
          f"-> {args.out}")
    if not args.workdir:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
