#!/usr/bin/env python
"""Replay a run's incident ledger offline and diff it against the live one.

The incident engine (draco_tpu/obs/incidents.py, PERF.md §15) folds the
per-step metric column families into typed, attributed incident episodes
live, streaming onset/offset events to ``train_dir/incidents.jsonl``. This
tool is its offline twin — the same discipline as forensics_report.py:
rebuild the ledger from ``metrics.jsonl`` with the SAME engine (one
implementation, so live and offline cannot drift), diff the two, print the
timeline, and write ``incidents_report.json`` next to the metrics file:

  python tools/incident_report.py train_out/           # a train dir
  python tools/incident_report.py train_out/ --thresholds trust.floor=0.4

Only the RECORD-sourced detectors (decode residual, trust, guard,
nonfinite, numerics drift) are recomputable — they see nothing but metric
columns, so the replay is bit-identical to the live fold whenever every
step was logged (log_every=1, the chaos/report discipline). BEAT-sourced
detectors (throughput, compile storm, prefetch starvation) depend on host
wall-clock and counters that are not columns; their episodes are carried
through from incidents.jsonl verbatim and labelled ``beat`` in the table.
A replay/ledger mismatch on the record-sourced set exits 1 naming the
divergence — that is the report's whole point. The strict diff applies
only when the JSONL covers every step (log_every=1): a subsampled stream
replays fewer firing observations by construction, so the diff degrades
to a labelled carry-through (exit 0) with a rerun hint instead of a false
DIVERGED.

No jax import. Tolerates every partial-artifact state a killed run leaves
behind (obs/replay.py): missing/empty/torn metrics.jsonl or
incidents.jsonl fold to the empty side of the diff, never a crash. The
status.json schema, when present, is validated against the central
contract table (obs/heartbeat.STATUS_BLOCKS).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# draco_tpu.obs is importable without jax — ONE engine implementation for
# the live heartbeat hook and this offline fold, so the two cannot drift
from draco_tpu.obs import incidents as incidents_mod  # noqa: E402
from draco_tpu.obs import replay  # noqa: E402


def infer_num_workers(records: list, status_path: str) -> int:
    """--num-workers fallback chain — the ONE shared implementation
    (obs/replay.infer_num_workers, same rule as forensics_report.py)."""
    return replay.infer_num_workers(records, status_path,
                                    "tools/incident_report.py")


def _episode_key(ep: dict) -> tuple:
    """The diffable identity of an episode: type, onset, offset (None =
    still open), implicated workers. A STILL-OPEN episode's worker set is
    excluded: the ledger's onset line carries the onset-time set while the
    set may have grown since (only the offset event records the final
    union), so comparing it would fail a correct ledger."""
    offset = ep.get("offset_step")
    workers = tuple(ep.get("workers") or ()) if offset is not None else ()
    return (ep.get("type"), ep.get("onset_step"), offset, workers)


def load_ledger_episodes(path: str) -> "tuple[list, bool]":
    """(episodes, multi_run) from incidents.jsonl: offset events are
    closed episodes; onset events with no matching offset are the open
    tails. ``multi_run``: the per-engine ``seq`` counter reset mid-file —
    a resumed run appended a SECOND engine instance's events (detectable
    even when the metrics step range is gap-free), so the strict
    single-engine replay diff does not apply. Torn/empty/missing
    tolerated (obs/replay.iter_jsonl)."""
    opens: dict = {}
    episodes = []
    last_seq = None
    multi_run = False
    for ev in replay.iter_jsonl(path):
        seq = ev.get("seq")
        if isinstance(seq, int):
            if last_seq is not None and seq <= last_seq:
                multi_run = True
            last_seq = seq
        kind, typ = ev.get("event"), ev.get("type")
        if typ is None:
            continue
        # the wall-clock ``ts`` stamp (ISSUE 19) is carried through
        # verbatim but is NOT part of _episode_key — replayed ledgers
        # (which have no wall clock) still diff clean against it
        body = {k: ev.get(k) for k in
                ("type", "severity", "source", "onset_step", "last_step",
                 "steps", "workers", "evidence", "ts")}
        if kind == "onset":
            opens[(typ, ev.get("onset_step"))] = body
        elif kind == "offset":
            body["offset_step"] = ev.get("offset_step")
            opens.pop((typ, ev.get("onset_step")), None)
            episodes.append(dict(body, open=False))
    episodes.extend(dict(b, offset_step=None, open=True)
                    for b in opens.values())
    return episodes, multi_run


def make_report(metrics_path: str, incidents_path: str,
                num_workers: int = 0, thresholds: str = "") -> dict:
    records = replay.train_records(metrics_path, require_loss=True)
    status_path = replay.find_run_files(metrics_path).status
    n = num_workers or infer_num_workers(records, status_path)
    # the run's own effective threshold overrides (the live engine stamps
    # its non-defaults into the status block — incl. make_engine's
    # cyclic_tol <- guard_residual_tol), then any explicit --thresholds on
    # top: the replay must fold with the thresholds the run USED, or a
    # non-default run would falsely diverge
    overrides = {}
    try:
        with open(status_path) as fh:
            status = json.load(fh)
        if isinstance(status, dict):
            overrides.update(
                ((status.get("incidents") or {}).get("thresholds")) or {})
    except (OSError, ValueError):
        pass
    overrides.update(incidents_mod.parse_thresholds(thresholds))
    engine = incidents_mod.IncidentEngine(num_workers=n,
                                          thresholds=overrides)
    for rec in records:
        engine.observe(rec)
    replayed = [dict(ep, offset_step=ep.get("offset_step"))
                for ep in engine.all_episodes()]
    for ep in replayed:
        ep.setdefault("offset_step", None)
    ledger, multi_run = load_ledger_episodes(incidents_path)
    have_ledger = os.path.exists(incidents_path)

    # the strict diff is only meaningful when the JSONL carries EVERY step
    # the live engine observed, exactly once, in order (log_every=1 on a
    # single uninterrupted run — the chaos/report discipline): a
    # subsampled stream (default log cadence), a missing metrics.jsonl,
    # or a RESUMED run re-appending overlapping steps (two live engine
    # instances with reset hysteresis/EW state, which one continuous
    # replay engine cannot reproduce) all degrade to a labelled
    # carry-through instead of a false DIVERGED verdict
    # ... and so does an AUTOPILOT run (control/autopilot.py): its
    # remediation events mark runtime-control state — quarantines mutate
    # the present-mask schedules and the straggle detector's exclusion
    # set, regime swaps change which columns exist — that a pure column
    # replay cannot reproduce, so the ledger is carried through
    controlled = any(e.get("event") == "remediation"
                     for e in replay.iter_jsonl(incidents_path))
    ordered = [r["step"] for r in records
               if isinstance(r.get("step"), int)]
    steps = sorted(set(ordered))
    full_coverage = bool(steps) \
        and len(steps) >= steps[-1] - steps[0] + 1 \
        and all(b > a for a, b in zip(ordered, ordered[1:])) \
        and not multi_run and not controlled

    # diff the RECORD-sourced halves; beat-sourced episodes are carried
    # through (not recomputable offline — module docstring)
    def rec_side(eps):
        return sorted((_episode_key(ep) for ep in eps
                       if incidents_mod.DETECTORS.get(ep.get("type"))
                       and incidents_mod.DETECTORS[ep["type"]].source
                       == "record"))

    replay_keys, ledger_keys = rec_side(replayed), rec_side(ledger)
    only_replay = [k for k in replay_keys if k not in ledger_keys]
    only_ledger = [k for k in ledger_keys if k not in replay_keys]
    match = have_ledger and not only_replay and not only_ledger
    return {
        "tool": "tools/incident_report.py",
        "schema": incidents_mod.INCIDENT_SCHEMA,
        "metrics": metrics_path,
        "incidents": incidents_path,
        "num_workers": n,
        "records_seen": len(records),
        "replayed": replayed,
        "ledger": ledger,
        "diff": {
            "have_ledger": have_ledger,
            "full_coverage": full_coverage,
            "multi_run_ledger": multi_run,
            "controlled_run": controlled,
            "match": match,
            "only_replay": [list(k) for k in only_replay],
            "only_ledger": [list(k) for k in only_ledger],
        },
        "detectors": incidents_mod.detector_table(),
    }


def print_table(report: dict, out=None) -> None:
    out = out if out is not None else sys.stdout  # resolve at call time
    diff = report["diff"]
    print(f"incidents: {report['incidents']}   replayed "
          f"{len(report['replayed'])} episode(s) over "
          f"{report['records_seen']} records   workers: "
          f"{report['num_workers']}", file=out)
    rows = report["ledger"] if diff["have_ledger"] else report["replayed"]
    if not rows:
        print("no incidents (clean run)", file=out)
    else:
        hdr = (f"{'type':<16}{'sev':<10}{'src':<8}{'onset':>7}{'offset':>8}"
               f"{'steps':>7}  workers")
        print(hdr, file=out)
        print("-" * len(hdr), file=out)
        for ep in sorted(rows, key=lambda e: (e.get("onset_step") or 0)):
            off = ep.get("offset_step")
            workers = ",".join(map(str, ep.get("workers") or ())) or "-"
            print(f"{ep['type']:<16}{ep.get('severity', '?'):<10}"
                  f"{ep.get('source', '?'):<8}"
                  f"{ep.get('onset_step', '?'):>7}"
                  f"{off if off is not None else 'open':>8}"
                  f"{ep.get('steps', '?'):>7}  {workers}", file=out)
    if not diff["have_ledger"]:
        print("no incidents.jsonl (pre-incident run or clean run with no "
              "events) — replay-only report", file=out)
    elif not diff["full_coverage"]:
        if diff.get("controlled_run"):
            print("autopilot-controlled run (remediation events in the "
                  "ledger): quarantines and regime swaps are runtime-"
                  "control state a pure column replay cannot reproduce — "
                  "ledger carried through unverified", file=out)
        else:
            print("metrics.jsonl is subsampled (log_every > 1), missing, "
                  "or a resumed run's appended stream — the live fold saw "
                  "observations the replay cannot reproduce, so the "
                  "ledger is carried through unverified (a single "
                  "log_every=1 run gets the strict diff)", file=out)
    elif diff["match"]:
        print("replay == ledger on every record-sourced episode", file=out)
    else:
        for k in diff["only_replay"]:
            print(f"DIVERGED: replay raised {k} but the ledger did not",
                  file=out)
        for k in diff["only_ledger"]:
            print(f"DIVERGED: ledger carries {k} but the replay did not "
                  f"reproduce it", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="train dir, or a metrics.jsonl path")
    ap.add_argument("--num-workers", type=int, default=0,
                    help="worker count (default: status.json, else "
                         "inferred from the present masks)")
    ap.add_argument("--thresholds", type=str, default="",
                    help="detector threshold overrides, the same "
                         "'<det>.<key>=<float>' grammar as "
                         "--incident-thresholds (must match the run's for "
                         "the diff to be meaningful)")
    ap.add_argument("--json", default="",
                    help="report output path (default: "
                         "incidents_report.json next to the metrics file)")
    args = ap.parse_args(argv)

    files = replay.find_run_files(args.path)
    metrics_path, incidents_path = files.metrics, files.incidents
    report = make_report(metrics_path, incidents_path, args.num_workers,
                         args.thresholds)
    print_table(report)
    out_path = args.json or os.path.join(os.path.dirname(metrics_path),
                                         "incidents_report.json")
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1)
    # a clean (empty ledger) run, a subsampled stream (strict diff not
    # applicable), and a matching ledger all exit 0; a record-sourced
    # divergence on a full stream is THE failure this tool exists to catch
    diff = report["diff"]
    return 0 if (not diff["have_ledger"] or not diff["full_coverage"]
                 or diff["match"]) else 1


if __name__ == "__main__":
    sys.exit(main())
