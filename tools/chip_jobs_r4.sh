#!/bin/bash
# Round-4 chip chain driver: run the two queued round-3 chains (r3b: flash
# kernel hardware compile + warmed driver bench + TTA; r3c: remat frontier +
# decode granularity) with an outer retry loop, so a tunnel flap mid-chain
# restarts the remaining work instead of abandoning it. All chain jobs are
# idempotent (artifacts rewritten incrementally; TTA legs skip if their
# artifact exists), so re-running a completed chain is cheap except for the
# bench warm leg.
#
# Launch detached — no tmux in this image:
#   setsid nohup bash tools/chip_jobs_r4.sh > baselines_out/chip_jobs_r4.log 2>&1 &
# A parked client can sit for hours; see .claude/skills/verify/SKILL.md
# "TPU tunnel discipline". NOTE: never edit this file while it is running —
# bash reads scripts by byte offset and an edit corrupts the continuation.
set -u
cd "$(dirname "$0")/.."
mkdir -p baselines_out

stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

for round in 1 2 3 4 5 6; do
  echo "[chip_jobs_r4 $(stamp)] ===== outer attempt $round ====="
  if [ ! -f baselines_out/.r3b_done ]; then
    bash tools/chip_jobs_r3b.sh >> baselines_out/chip_jobs_r3b.log 2>&1
    rc=$?
    echo "[chip_jobs_r4 $(stamp)] r3b exited rc=$rc"
    [ "$rc" = 0 ] && touch baselines_out/.r3b_done
  fi
  if [ -f baselines_out/.r3b_done ] && [ ! -f baselines_out/.r3c_done ]; then
    bash tools/chip_jobs_r3c.sh >> baselines_out/chip_jobs_r3c.log 2>&1
    rc=$?
    echo "[chip_jobs_r4 $(stamp)] r3c exited rc=$rc"
    [ "$rc" = 0 ] && touch baselines_out/.r3c_done
  fi
  if [ -f baselines_out/.r3b_done ] && [ -f baselines_out/.r3c_done ]; then
    echo "[chip_jobs_r4 $(stamp)] all chains complete"
    exit 0
  fi
  sleep 120
done
echo "[chip_jobs_r4 $(stamp)] gave up after 6 outer attempts"
exit 1
