#!/bin/bash
# Round-5 chain h: the d~159M LM point, HBM-fitted. Chain r5f proved the
# compile ceiling is GONE (in-graph projection + scan_layers: compiles
# finish in ~1 min) and converted the failure into quantified HBM OOMs:
# flash T=2048 b2 needs 16.04G of 15.75G (over by 304M), geomedian 16.73G,
# shared dense 16.87G. These rungs shave activations to fit:
#   1 lm159h_flash_b1   cyclic shared + flash, T=2048 b1 remat scan
#                       (b2->b1 drops ~1G of remat residuals + f32 logits)
#   2 lm159h_geomed_b1  geomedian, T=2048 b1 remat scan (needs ~1G back)
#   3 lm159h_flash_1k   cyclic shared + flash, T=1024 b2 remat scan
#                       (fallback at halved T; matched tokens with rung 4)
#   4 lm159h_geomed_1k  geomedian, T=1024 b2 remat scan
# Any (flash, geomed) pair at matched shapes yields the decode-vs-geomedian
# ratio at d~159M. The simulate variant is NOT retried at this scale: its
# (n, 2s+1, d) redundant gradient stack is 8*3*159M*4B ~ 15G alone —
# physically beyond one 16G chip; priced at d~63M instead (PERF 1b).
# Parks until chains r5/r5b/r5c/r5d/r5e/r5f are gone.
#
# Launch detached (variable indirection so the launch wrapper's cmdline
# does not match the chains' pgrep predecessor tests — SKILL.md round-5
# note):
#   s=tools/chip_jobs_r5h.sh; setsid nohup bash "$s" > baselines_out/chip_jobs_r5h.log 2>&1 &
# NEVER edit this file while it runs. Markers: baselines_out/.r5h_<rung>_done
set -u
cd "$(dirname "$0")/.."
mkdir -p baselines_out

stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

commit_evidence() {
  local msg="$1"
  local files
  shopt -s nullglob
  files=(baselines_out/*.json baselines_out/*.jsonl baselines_out/*.log)
  shopt -u nullglob
  if [ "${#files[@]}" = 0 ]; then
    echo "[r5h $(stamp)] no artifact files exist yet for: $msg"
    return 0
  fi
  for i in 1 2 3; do
    if ! git add -- "${files[@]}"; then
      echo "[r5h $(stamp)] git add failed (attempt $i), retrying"
      sleep 5
      continue
    fi
    if git diff --cached --quiet -- baselines_out 2>/dev/null; then
      echo "[r5h $(stamp)] nothing new to commit for: $msg"
      return 0
    fi
    if git commit -q -m "$msg" -- baselines_out; then
      echo "[r5h $(stamp)] committed: $msg"
      return 0
    fi
    echo "[r5h $(stamp)] git commit failed (attempt $i), retrying"
    sleep 5
  done
  echo "[r5h $(stamp)] WARNING: commit failed for: $msg (evidence still on disk)"
  return 0
}

tpu_up() {
  timeout -k 30 120 python - <<'EOF'
import sys, jax
try:
    d = jax.devices()
    sys.exit(0 if d and d[0].platform != "cpu" else 3)
except Exception:
    sys.exit(3)
EOF
}

others_running() {
  for s in chip_jobs_r5.sh chip_jobs_r5b.sh chip_jobs_r5c.sh \
           chip_jobs_r5d.sh chip_jobs_r5e.sh chip_jobs_r5f.sh; do
    pgrep -f "bash tools/$s" > /dev/null 2>&1 && return 0
  done
  return 1
}

echo "[r5h $(stamp)] waiting for chains r5..r5f to finish"
while others_running; do
  sleep 60
done
echo "[r5h $(stamp)] predecessors gone; proceeding"

ABORT_PASS=0
FAILURES=0
rung() {
  local name="$1" msg="$2"; shift 2
  local marker="baselines_out/.r5h_${name}_done"
  if [ -f "$marker" ] || [ "$ABORT_PASS" = 1 ]; then
    return 0
  fi
  echo "[r5h $(stamp)] ===== rung $name: $* ====="
  local rc=0
  "$@" || rc=$?
  if [ "$rc" = 0 ]; then
    touch "$marker"
    commit_evidence "$msg"
  else
    echo "[r5h $(stamp)] rung $name FAILED (rc=$rc); probing tunnel"
    commit_evidence "$msg (partial: rung exited rc=$rc)"
    FAILURES=$((FAILURES + 1))
    if ! tpu_up; then
      echo "[r5h $(stamp)] tunnel down — aborting this pass, back to wait loop"
      ABORT_PASS=1
    fi
  fi
}

all_done() {
  for m in flash_b1 geomed_b1 flash_1k geomed_1k; do
    [ -f "baselines_out/.r5h_${m}_done" ] || return 1
  done
  return 0
}

for outer in 1 2; do
  echo "[r5h $(stamp)] ===== outer attempt $outer ====="
  if all_done; then break; fi
  tools/wait_tpu.sh 60 150 120 || { echo "[r5h $(stamp)] tunnel never came up this window"; continue; }
  FAILURES=0
  ABORT_PASS=0

  rung flash_b1 "chip evidence: d~159M LM cyclic+flash T=2048 b1 (scan, HBM-fitted)" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 2048 --batch-size 1 --remat --scan-layers \
      --variants lm_cyclic_s1_shared_bf16_flash \
      --out baselines_out/tpu_lm_perf_159_flash_b1.json

  rung geomed_b1 "chip evidence: d~159M LM geomedian T=2048 b1 (scan, HBM-fitted)" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 2048 --batch-size 1 --remat --scan-layers \
      --variants lm_geomedian_bf16 \
      --out baselines_out/tpu_lm_perf_159_geomed_b1.json

  rung flash_1k "chip evidence: d~159M LM cyclic+flash T=1024 b2 (scan)" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 1024 --batch-size 2 --remat --scan-layers \
      --variants lm_cyclic_s1_shared_bf16_flash \
      --out baselines_out/tpu_lm_perf_159_flash_1k.json

  rung geomed_1k "chip evidence: d~159M LM geomedian T=1024 b2 (scan)" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 1024 --batch-size 2 --remat --scan-layers \
      --variants lm_geomedian_bf16 \
      --out baselines_out/tpu_lm_perf_159_geomed_1k.json

  if all_done; then
    echo "[r5h $(stamp)] D~159M HBM-FITTED EVIDENCE COMPLETE"
    break
  fi
  echo "[r5h $(stamp)] incomplete ($FAILURES rung failures this pass); retrying"
  sleep 120
done
all_done && exit 0 || exit 1
