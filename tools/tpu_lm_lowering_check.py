#!/usr/bin/env python
"""Offline TPU-lowering audit of the d≈159M LM chip programs (round 5).

The `lm_big` rung of tools/chip_jobs_r5.sh stakes a large slice of the one
tunnel window on programs that have NEVER compiled anywhere: TransformerLM
dim=1024/heads=16/layers=12 (d ≈ 159M params), T=2048, bf16, remat, on the
folded w×tp GSPMD mesh — cyclic shared + Pallas flash, cyclic shared,
geomedian, and cyclic simulate (r=3 redundant lanes). A Python-side
lowering bug there (Pallas tiling, sharding rule, remat/scan interaction)
would burn the window for nothing.

This tool cross-platform exports the full scanned train-step programs for
`platforms=["tpu"]` on the CPU host (`jax.export`), which runs the whole
StableHLO + Pallas TPU lowering stack without a chip (methodology +
negative control: tools/tpu_attn_lowering_check.py). Drift-proofing: the
variant configs, input staging, and scan loop are IMPORTED from
tools/tpu_lm_perf.py (build_lm_variants / stage_scan_inputs /
make_scan_loop) — the audit lowers the same program the chip rung times,
by construction. The host runs with ONE virtual device, so
make_folded_wtp_mesh folds all 8 logical workers onto a single device —
the exact layout the single-chip rung uses (every on-chip artifact records
devices_used: 1); an 8-device layout would exercise different GSPMD
shardings than the chip will.

What it cannot prove: Mosaic machine-code compilation and HBM fit — the
chip rung closes those.

The scan_layers variants of these same shapes (chain r5f) are audited by
the sibling tools/tpu_lm_scan_lowering_check.py, which also records the
serialized program-size comparison driving that flag.

  python tools/tpu_lm_lowering_check.py \
      [--out baselines_out/tpu_lm_big_lowering.json]

Builds ~159M-param states on host RAM (~1-2 min per variant); the report
is rewritten after every row, so an interrupt keeps finished rows.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def lm_big_program(name, cfg_kw, steps=2):
    """Register one lm_big rung variant as a chip-tier LintProgram: the row
    now carries the full six-rule lint verdict on top of the lowering
    check, through the same machinery as the CI artifact
    (tools/_lowering_common.lint_row / draco_tpu/analysis).

    The audited program is unchanged: the exact scan loop the chip rung
    times (make_scan_loop over stage_scan_inputs — which deliberately does
    NOT donate its state, because the timing protocol re-runs the compiled
    loop on the same state; manifest.require_donated=None records that).
    Explicit-collective counts are also None: this is the GSPMD folded
    route, whose collectives exist only post-partitioner.
    """
    import jax

    from draco_tpu.analysis import BF16_DTYPES, BuiltProgram, LintProgram, Manifest

    def build():
        from draco_tpu.config import TrainConfig
        from draco_tpu.parallel.mesh import make_folded_wtp_mesh
        from draco_tpu.parallel.tp_step import build_tp_train_setup
        from tools.tpu_lm_perf import make_scan_loop, stage_scan_inputs

        cfg = TrainConfig(**cfg_kw)
        mesh = make_folded_wtp_mesh(cfg.num_workers)
        setup = build_tp_train_setup(cfg, mesh)
        xs, ms = stage_scan_inputs(cfg, steps)
        with mesh:
            loop = jax.jit(make_scan_loop(setup))
        n_params = sum(x.size for x in jax.tree.leaves(setup.state.params))
        manifest = Manifest(
            require_donated=None, collectives=None,
            allowed_dtypes=BF16_DTYPES,
            # a closed-over (d,) f32 adds 4d bytes (638 MB at this d — the
            # remote-compile ceiling, PERF.md §4); honest modules are ~1 MB
            max_module_bytes=2 * setup.dim, max_constant_bytes=1 << 20,
        )
        return BuiltProgram(name, loop, (setup.state, xs, ms), mesh,
                            manifest,
                            extra={"variant": name, "params": int(n_params),
                                   "devices_in_mesh":
                                       int(mesh.devices.size)},
                            # the lowering audit needs trace+export only; a
                            # CPU backend-compile of the d≈159M flagship
                            # costs real minutes per row
                            capture_memory=False)

    return LintProgram(name=name, build=build, route="lm_big", fast=False)


# The lm_big rung shapes, asserted in CI against the chip_jobs_r5.sh rung
# text (tests/test_cli_tools.py::test_lm_lowering_audit_matches_r5_rung) —
# the chain script cannot be edited while it runs, so drift is caught by
# the test rather than by sharing code with bash.
LM_BIG = dict(num_workers=8, seq_len=2048, vocab=8192, model_dim=1024,
              model_heads=16, model_layers=12, remat=True, max_steps=5)
LM_BIG_VARIANTS_B2 = ("lm_cyclic_s1_shared_bf16_flash",
                      "lm_cyclic_s1_shared_bf16", "lm_geomedian_bf16")
LM_BIG_VARIANTS_B1 = ("lm_cyclic_s1_simulate_bf16",)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/tpu_lm_big_lowering.json")
    args = ap.parse_args(argv)

    # ONE virtual device: the chip folds all logical workers onto a single
    # device and the audit must lower that exact layout (docstring)
    from tools._lowering_common import lint_row, run_rows, setup_cpu_host

    setup_cpu_host(1)

    from tools.tpu_lm_perf import build_lm_variants

    v_b2 = build_lm_variants(batch_size=2, **LM_BIG)
    v_b1 = build_lm_variants(batch_size=1, **LM_BIG)
    programs = ([lm_big_program(n, v_b2[n]) for n in LM_BIG_VARIANTS_B2]
                + [lm_big_program(n, v_b1[n]) for n in LM_BIG_VARIANTS_B1])
    named = [(p.name, (lambda p=p: lint_row(p))) for p in programs]
    report = run_rows(
        args.out,
        "jax.export cross-platform lowering, platforms=['tpu'], CPU host "
        "with ONE virtual device (the chip's folded layout), full scanned "
        "train-step programs at the exact chip_jobs_r5.sh lm_big rung "
        "shapes, configs imported from tools/tpu_lm_perf.py; each row "
        "carries the six-rule program-lint verdict (draco_tpu/analysis)",
        named,
    )
    print(json.dumps({"all_ok": report["all_ok"]}))
    return 0 if report["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
