#!/usr/bin/env python
"""Repo-wide static audit of every registered chip-bound program.

Runs the nine lint rules (draco_tpu/analysis/rules.py: constant_bloat,
donation, dtype, collectives, host_traffic, memory_budget, plus the
static sharding auditor's sharding_contract, collective_axes and
replication_leaks — draco_tpu/analysis/sharding.py against the partition
tables in draco_tpu/parallel/partition.py) against every program in the
registry (draco_tpu/analysis/registry.py — the coded-DP CNN
train_step/train_many and all five LM token routes including the K-fused
scan drivers), on the CPU-host mesh via the cross-platform-export
methodology of the lowering-check tools. Then runs the seeded-defect
NEGATIVE CONTROLS (analysis/controls.py); a control row is ``ok`` iff it
trips exactly its rule — a linter that stops seeing defects fails its own
artifact.

The memory_budget rows double as the per-program memory/cost LEDGER
(argument/output/temp/generated-code bytes, peak estimate, analytic
flops): the committed artifact is what tools/perf_watch.py diffs
round-over-round (PERF.md §8).

  python tools/program_lint.py [--out baselines_out/program_lint.json]
      [--fast] [--programs name|regex,...] [--only rule,...]
      [--skip-controls]

``--fast`` skips the non-fast programs (currently only the big-d
constant-bloat guard, which builds ~3.3M params); the fast subset runs in
roughly a minute on the CI host and is what the ``core``-tier test
exercises (tests/test_program_lint.py, PERF.md §6).

The report is rewritten after every row (incremental-artifact discipline);
bench.py refuses to record a chip run while this artifact reports a
constant_bloat or host_traffic violation for the program family being
timed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/program_lint.json")
    ap.add_argument("--fast", action="store_true",
                    help="skip programs registered fast=False (the big-d "
                         "constant-bloat guard, ~3.3M params)")
    ap.add_argument("--programs", type=str, default="",
                    help="comma-separated subset of registered programs; "
                         "each token is an exact name or a regex matched "
                         "with re.search (e.g. --programs 'lm_sp_.*,tree_')")
    ap.add_argument("--only", type=str, default="",
                    help="run only these comma-separated rules (e.g. "
                         "--only sharding_contract,collective_axes); "
                         "implies --skip-controls (controls assert the "
                         "full rule set) and does NOT overwrite the "
                         "default artifact unless --out is given")
    ap.add_argument("--skip-controls", action="store_true",
                    help="skip the seeded-defect negative controls")
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual CPU devices (the CI mesh size)")
    args = ap.parse_args(argv)

    from tools._lowering_common import lint_row, run_rows, setup_cpu_host

    setup_cpu_host(args.devices)

    from draco_tpu.analysis import RULE_NAMES, collect
    from draco_tpu.analysis.controls import control_programs

    only = None
    if args.only:
        only = tuple(v.strip() for v in args.only.split(",") if v.strip())
        unknown = set(only) - set(RULE_NAMES)
        if unknown:
            raise SystemExit(f"unknown rules {sorted(unknown)}; "
                             f"rules: {list(RULE_NAMES)}")
        # a partial-rule sweep is a scratch run, never the committed
        # artifact (whose rows must carry the full rule set)
        args.skip_controls = True
        if args.out == "baselines_out/program_lint.json":
            args.out = "baselines_out/program_lint_only.json"

    programs = collect()
    if args.fast:
        programs = [p for p in programs if p.fast]
    if args.programs:
        import re

        tokens = [v.strip() for v in args.programs.split(",") if v.strip()]
        names = {p.name for p in programs}
        keep = set()
        unknown = []
        for tok in tokens:
            if tok in names:  # exact-name compat
                keep.add(tok)
                continue
            hits = {n for n in names if re.search(tok, n)}
            if not hits:
                unknown.append(tok)
            keep |= hits
        if unknown:
            raise SystemExit(f"no registered program matches {unknown}; "
                             f"registered: {sorted(names)}")
        programs = [p for p in programs if p.name in keep]

    named = [(p.name, (lambda p=p: lint_row(p, only=only)))
             for p in programs]
    if not args.skip_controls:
        def control_thunk(c):
            row = lint_row(c.program)
            tripped = row.get("failed_rules", [])
            live = tripped == [c.expected_fail]
            return {**row, "ok": live, "expected_fail": c.expected_fail,
                    "control": True,
                    **({} if live else
                       {"error": f"control must trip exactly "
                                 f"[{c.expected_fail}], tripped {tripped}"})}

        named += [(c.program.name, (lambda c=c: control_thunk(c)))
                  for c in control_programs()]

    report = run_rows(
        args.out,
        "nine static rules (constant_bloat, donation, dtype, collectives, "
        "host_traffic, memory_budget, sharding_contract, collective_axes, "
        "replication_leaks) over jit.trace jaxprs + jax.export StableHLO + "
        "compiled memory/cost analysis + compiled I/O shardings on the "
        "CPU-host mesh; rows named control_* are seeded-defect negative "
        "controls whose ok means 'tripped exactly its rule'",
        named,
        extra={"fast": args.fast, "devices": args.devices,
               "rules": list(only or RULE_NAMES)},
    )
    print(json.dumps({"all_ok": report["all_ok"],
                      "rows": len(report["rows"])}))
    return 0 if report["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
