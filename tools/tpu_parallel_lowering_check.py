#!/usr/bin/env python
"""Offline TPU-lowering audit of the multi-device parallel axes (round 5).

The multichip dryrun (`__graft_entry__.dryrun_multichip`) compiles and RUNS
every coded-DP × model-parallel composition — but against the XLA **CPU**
backend. This tool closes the other half offline: it cross-platform
exports the same jitted train steps for ``platforms=["tpu"]``
(`jax.export` on CPU host, methodology + negative control:
tools/tpu_attn_lowering_check.py), so the GSPMD partitioning, ppermute
ring schedules, cond-skipped hops, and the Pallas flash kernel inside the
ring are all validated against the TPU lowering stack — the stack an
actual multi-chip pod would compile with, which no single-chip rung can
exercise.

Axes (16 virtual devices, w=8 cyclic s=1 coded DP × axis2=2 — the cyclic
n > 4s row the dryrun can only afford at its larger mesh):
  sp_ring_dense   shard_map + ppermute ring attention
  sp_ring_flash   ring with the Pallas flash kernel per hop
                  (ring_flash_attention — the §2.3-SP/§5.7 long-context row)
  tp              Megatron tensor parallelism (GSPMD annotations)
  pp              GPipe microbatch pipeline (shard_map + ppermute schedule)
  ep              Switch-MoE expert parallelism

What it cannot prove: Mosaic machine-code compilation, HBM fit, and real
ICI behavior — those need a pod (SURVEY §7.4). Report rewritten per row.

  python tools/tpu_parallel_lowering_check.py \
      [--out baselines_out/tpu_parallel_lowering.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def axis_program(name, tag, overrides, collectives, w=8):
    """Register one parallel-axis row as a chip-tier LintProgram. The
    audited program is the route builder's own donated ``train_step`` (the
    old bespoke thunk re-wrapped it in a fresh jit, which dropped the
    donation attrs — the lint donation rule needs the real program), and
    the row carries the six-rule verdict including the axis's explicit
    collective budget: the ring/pipeline hop structure IS the row's claim,
    so count drift fails the audit even when lowering succeeds."""
    from draco_tpu.analysis import BuiltProgram, LintProgram, Manifest
    from draco_tpu.analysis.registry import lm_example_tokens

    def build():
        from draco_tpu.config import TrainConfig
        from draco_tpu.parallel import (
            make_mesh_2d, make_mesh_wep, make_mesh_wpp, make_mesh_wtp,
        )
        from draco_tpu.parallel.ep_step import build_ep_train_setup
        from draco_tpu.parallel.pp_step import build_pp_train_setup
        from draco_tpu.parallel.sp_step import build_sp_train_setup
        from draco_tpu.parallel.tp_step import build_tp_train_setup

        builders = {
            "sp": (build_sp_train_setup, make_mesh_2d),
            "tp": (build_tp_train_setup, make_mesh_wtp),
            "pp": (build_pp_train_setup, make_mesh_wpp),
            "ep": (build_ep_train_setup, make_mesh_wep),
        }
        builder, make_mesh_fn = builders[tag]
        cfg = TrainConfig(
            network="TransformerLM", dataset="synthetic-text", batch_size=2,
            num_workers=w, approach="cyclic", mode="normal", worker_fail=1,
            err_mode="rev_grad", seq_len=64, vocab=64, model_dim=64,
            model_heads=2, max_steps=2, eval_freq=0, train_dir="",
            log_every=1000, **overrides)
        mesh = make_mesh_fn(w, 2)
        setup = builder(cfg, mesh)
        toks, mask = lm_example_tokens(cfg)
        manifest = Manifest(collectives=collectives)
        return BuiltProgram(name, setup.train_step,
                            (setup.state, toks, mask), mesh, manifest,
                            extra={"devices_in_mesh":
                                       int(mesh.devices.size)})

    return LintProgram(name=name, build=build, route=f"parallel_{tag}",
                       fast=False)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/tpu_parallel_lowering.json")
    args = ap.parse_args(argv)

    from tools._lowering_common import lint_row, run_rows, setup_cpu_host

    setup_cpu_host(16)

    # explicit-collective budgets per axis (the hop structure is the row's
    # claim), imported from the owning route modules so a legitimate
    # schedule change is a ONE-file manifest edit (PERF.md §6): the sp ring
    # budget covers both attention inners (dense and flash — the hop
    # structure is inner-independent), the pipeline brings its tick
    # schedule + loss/grad psums, and tp/ep are pure GSPMD (collectives
    # post-partitioner = none explicit).
    from draco_tpu.parallel import pp_step, sp_step

    axes = [
        ("sp_ring_dense", "sp", dict(seq_shards=2, model_layers=1),
         sp_step.LINT_COLLECTIVES),
        ("sp_ring_flash", "sp", dict(seq_shards=2, model_layers=1,
                                     attn_impl="flash"),
         sp_step.LINT_COLLECTIVES),
        ("tp", "tp", dict(tensor_shards=2, model_layers=1), {}),
        ("pp", "pp", dict(pipeline_shards=2, pp_microbatches=2,
                          model_layers=2),
         pp_step.LINT_COLLECTIVES),
        ("ep", "ep", dict(moe_experts=4, expert_shards=2, model_layers=1),
         {}),
    ]
    programs = [axis_program(name, tag, ov, colls)
                for name, tag, ov, colls in axes]
    named = [(p.name, (lambda p=p: lint_row(p))) for p in programs]
    report = run_rows(
        args.out,
        "jax.export cross-platform lowering, platforms=['tpu'], 16 virtual "
        "CPU devices, w=8 cyclic s=1 coded DP x axis2=2, the route "
        "builders' own donated train_step programs; each row carries the "
        "six-rule program-lint verdict incl. the axis's explicit "
        "collective budget (draco_tpu/analysis)",
        named,
    )
    print(json.dumps({"all_ok": report["all_ok"]}))
    return 0 if report["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
