#!/usr/bin/env python
"""Flash-attention kernel on real hardware: parity + micro-bench vs dense.

For each T in --seq-lens: numerical parity of the Pallas kernel against the
dense streaming-softmax oracle (fwd and input grads), then chained-loop
timing (utils/timing.py discipline: non-linear full-output feedback, big
operands via consts) of forward and forward+backward for both paths.
Writes --out (default baselines_out/tpu_attn.json).

The expected shape of the result: dense materialises (T, T) scores per
head, so its HBM traffic grows ~T² while flash stays ~T·Dh — the kernel's
advantage compounds with sequence length, and beyond some T the dense path
simply OOMs (recorded as {"dense": "oom"}).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_one(t, b, h, dh, reps, interpret=False):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.ops.flash_attention import flash_attention
    from draco_tpu.parallel.ring_attention import dense_attention
    from draco_tpu.utils.timing import timeit_chained

    r = np.random.RandomState(0)
    shape = (b, t, h, dh)
    q = jnp.asarray(r.normal(size=shape).astype(np.float32))
    k = jnp.asarray(r.normal(size=shape).astype(np.float32))
    v = jnp.asarray(r.normal(size=shape).astype(np.float32))

    flash = lambda q, k, v: flash_attention(q, k, v, force=True,
                                            interpret=interpret)
    dense = lambda q, k, v: dense_attention(q, k, v, causal=True)

    rec = {"seq_len": t, "batch": b, "heads": h, "head_dim": dh}

    def loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    def fwd_step(attn):
        def step(qc, k, v):
            o = attn(qc, k, v)
            return qc + 1e-30 * jnp.sum(o * o, axis=None, keepdims=False)
        return step

    def fb_step(attn):
        g = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v))),
                     argnums=0)

        def step(qc, k, v):
            return qc + 1e-30 * g(qc, k, v) ** 2
        return step

    # flash numbers first — they must survive a dense OOM at long T (the
    # regime the kernel exists for)
    o_f = jax.jit(flash)(q, k, v)
    g_f = jax.jit(jax.grad(loss(flash), argnums=(0, 1, 2)))(q, k, v)
    rec["flash_fwd_ms"] = round(
        timeit_chained(fwd_step(flash), q, (k, v), reps=reps) * 1e3, 3)
    rec["flash_fwdbwd_ms"] = round(
        timeit_chained(fb_step(flash), q, (k, v), reps=reps) * 1e3, 3)

    try:
        o_d = jax.jit(dense)(q, k, v)
        rec["fwd_max_abs_err"] = float(jnp.max(jnp.abs(o_f - o_d)))
        g_d = jax.jit(jax.grad(loss(dense), argnums=(0, 1, 2)))(q, k, v)
        rec["grad_max_abs_err"] = float(
            max(jnp.max(jnp.abs(a - b)) for a, b in zip(g_f, g_d))
        )
        rec["dense_fwd_ms"] = round(
            timeit_chained(fwd_step(dense), q, (k, v), reps=reps) * 1e3, 3)
        rec["dense_fwdbwd_ms"] = round(
            timeit_chained(fb_step(dense), q, (k, v), reps=reps) * 1e3, 3)
        if rec["flash_fwd_ms"] > 0:
            rec["fwd_speedup"] = round(
                rec["dense_fwd_ms"] / rec["flash_fwd_ms"], 3)
        if rec["flash_fwdbwd_ms"] > 0:
            rec["fwdbwd_speedup"] = round(
                rec["dense_fwdbwd_ms"] / rec["flash_fwdbwd_ms"], 3)
    except Exception as e:  # keep the flash row either way
        msg = f"{type(e).__name__}: {e}"
        is_oom = ("RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower()
                  or "OOM" in msg)
        rec["dense"] = "oom" if is_oom else "failed"
        rec["dense_error"] = msg[:2500]

    # optional third column: jax's bundled reference Pallas flash op (same
    # blockwise algorithm, upstream-tuned) — an external yardstick for the
    # in-repo kernel. Skipped silently where the bundled op can't run
    # (non-TPU backends, interpret smoke).
    if not interpret:
        try:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as jax_flash,
            )

            # upstream op wants (B,H,T,D) and defaults sm_scale=1.0 — feed
            # its native layout (pre-transposed OUTSIDE the timed step, so
            # the yardstick isn't padded with layout copies) and the same
            # 1/sqrt(dh) temperature the in-repo kernel applies
            scale = 1.0 / (dh ** 0.5)
            qh, kh, vh = (jnp.moveaxis(x, 2, 1) for x in (q, k, v))

            def ref(q, k, v):
                return jax_flash(q, k, v, causal=True, sm_scale=scale)

            o_r = jnp.moveaxis(jax.jit(ref)(qh, kh, vh), 1, 2)
            rec["jaxref_fwd_max_abs_err"] = float(jnp.max(jnp.abs(o_f - o_r)))
            rec["jaxref_fwd_ms"] = round(
                timeit_chained(fwd_step(ref), qh, (kh, vh), reps=reps) * 1e3,
                3)
            rec["jaxref_fwdbwd_ms"] = round(
                timeit_chained(fb_step(ref), qh, (kh, vh), reps=reps) * 1e3,
                3)
        except Exception as e:
            rec["jaxref_error"] = f"{type(e).__name__}: {e}"[:2500]
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str, default="baselines_out/tpu_attn.json")
    # T=256 first: the cheapest hardware compile of the kernel — separates
    # "Mosaic rejects the kernel at all" from long-T-specific failures
    ap.add_argument("--seq-lens", type=str, default="256,1024,2048,4096")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--cpu-interpret", action="store_true",
                    help="smoke: run tiny shapes in interpret mode on CPU")
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)  # shared bootstrap: compile cache (+ cpu mesh)

    if args.cpu_interpret:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax

    dev = jax.devices()[0]
    report = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "rows": [],
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    for t in [int(x) for x in args.seq_lens.split(",")]:
        print(f"[tpu_attn] T={t} ...", file=sys.stderr, flush=True)
        try:
            rec = check_one(t, args.batch, args.heads, args.head_dim,
                            args.reps, interpret=args.cpu_interpret)
        except Exception as e:
            # keep enough of a Mosaic/compile error to act on it within the
            # same tunnel window (300 chars cut the tiling detail in r3)
            rec = {"seq_len": t, "error": f"{type(e).__name__}: {e}"[:2500]}
        print(f"[tpu_attn] {json.dumps(rec)}", file=sys.stderr, flush=True)
        report["rows"].append(rec)
        # rewrite after every row: a mid-run tunnel loss keeps finished rows
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
