#!/usr/bin/env python
"""Transformer-scale perf evidence for the coded-DP step (single chip).

The CNN headline (tools/tpu_perf.py) is HBM-bound at 32×32 — MFU 11.5%
says nothing about the framework on MXU-shaped work. This tool measures the
TransformerLM coded step at a size where the matmuls dominate, in bfloat16,
and shows how the paper's decode-vs-geomedian gap (reference README.md:2,
baseline_master.py:271-276) grows with gradient dimension d: Weiszfeld is
80 full passes over the (n, d) stack per step, the cyclic decode a handful.

Variants (all n logical coded workers vmapped on the available devices via
the GSPMD LM path, parallel/tp_step.py):
  * cyclic s=1 in both redundancy regimes: shared (one-copy fast path) and
    simulate (reference-parity 2s+1-lane redundant compute)
  * geometric median (80 Weiszfeld iterations)
  * krum
  * plain mean, no attack (lower bound)

Timing: utils/timing.py protocol — steps folded into ONE jitted lax.scan
over pre-staged token batches, device→host fetch sync, minus RTT. FLOPs
from XLA cost analysis of the compiled scan (counts the body once). Run
with the host otherwise idle (PERF.md §4).

``--production-loop`` re-times the same variants on the PRODUCTION chunked
token loop (parallel/token_loop.run_token_loop driving train_token_many
with --steps-per-call, PERF.md §4b) instead of this tool's private scan
harness — since the production loop became scan-chunked the two measure the
same fold, and the artifact records ``steps_per_call``/``loop`` so which one
produced each number is explicit.

Usage: python tools/tpu_lm_perf.py [--cpu-mesh N for smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def stage_scan_inputs(cfg, steps):
    """Pre-staged (xs tokens, adversary masks) for `steps` scanned steps —
    the one source of truth for the LM timing/audit input protocol (also
    imported by tools/tpu_lm_lowering_check.py)."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu import rng as drng
    from draco_tpu.parallel.sp_step import synthetic_text

    adv = drng.adversary_schedule(cfg.seed, steps + 1, cfg.num_workers,
                                  cfg.num_adversaries)
    xs = jnp.asarray(np.stack([
        synthetic_text(cfg.seed, s, cfg.num_workers, cfg.batch_size,
                       cfg.seq_len, cfg.vocab)
        for s in range(1, steps + 1)
    ]))
    ms = jnp.asarray(np.stack([np.asarray(adv[s]) for s in range(1, steps + 1)]))
    return xs, ms


def make_scan_loop(setup):
    """The scanned multi-step train loop the timing protocol jits — shared
    with the lowering audit so both always export/compile the same program."""
    import jax

    def loop(state, xs, ms):
        def body(st, batch):
            toks, mask = batch
            st, metrics = setup.train_step(st, toks, mask)
            return st, metrics["loss"]
        return jax.lax.scan(body, state, (xs, ms))

    return loop


def run_lm(cfg, mesh, steps, warmup=1, reps=2):
    """(ms/step, flops/step, last loss) of the jitted LM train step scan."""
    import jax
    import numpy as np

    import bench
    from draco_tpu.parallel.tp_step import build_tp_train_setup
    from draco_tpu.utils.timing import time_scanned_steps

    setup = build_tp_train_setup(cfg, mesh)
    xs, ms = stage_scan_inputs(cfg, steps)
    loop = make_scan_loop(setup)

    with mesh:
        compiled = jax.jit(loop).lower(setup.state, xs, ms).compile()
    flops = bench._compiled_flops(compiled)

    if jax.devices()[0].platform == "cpu":
        # local CPU: block_until_ready is a real barrier; smoke only
        st, losses = compiled(setup.state, xs, ms)
        jax.block_until_ready(losses)
        t0 = time.perf_counter()
        st, losses = compiled(st, xs, ms)
        jax.block_until_ready(losses)
        dt = (time.perf_counter() - t0) / steps
        return dt * 1e3, flops, float(np.asarray(losses)[-1])

    dt, losses = time_scanned_steps(
        compiled, setup.state, (xs, ms), steps=steps, warmup=warmup, reps=reps
    )
    return dt * 1e3, flops, float(np.asarray(jax.device_get(losses))[-1])


def run_lm_production(cfg, mesh, steps):
    """(ms/step, flops/step, last loss) of the PRODUCTION chunked token loop
    (parallel/token_loop.run_token_loop with cfg.steps_per_call) — the loop
    users run, not this tool's private scan harness. A warmup pass on a
    deep-copied state settles compilation of the chunk-shaped programs
    (cached on the setup's jitted callables); the timed pass drives the
    setup's own state (the carries are donated, so each state tree feeds at
    most one loop). The loop's terminal metric flush is a device→host fetch
    (DeferredMetricWriter.sync), i.e. a true execution barrier even on
    remote-dispatch backends; the final fetch_scalar adds the state sync."""
    import jax
    import jax.numpy as jnp

    import bench
    from draco_tpu.parallel.token_loop import run_token_loop
    from draco_tpu.parallel.tp_step import build_tp_train_setup
    from draco_tpu.utils.timing import fetch_scalar, measure_rtt

    if steps % max(cfg.steps_per_call, 1):
        # a remainder chunk would compile its own program INSIDE the timed
        # region (the warmup only settles the K-sized chunk) — reject like
        # tools/host_loop_overhead.py rather than record the inflated number
        raise SystemExit(
            f"--production-loop: --steps {steps} must be divisible by "
            f"--steps-per-call {cfg.steps_per_call}"
        )
    setup = build_tp_train_setup(cfg, mesh)
    K = max(cfg.steps_per_call, 1)
    rtt = 0.0 if jax.devices()[0].platform == "cpu" else measure_rtt()
    warm = setup._replace(state=jax.tree.map(jnp.copy, setup.state))
    st, _ = run_token_loop(warm, cfg, steps=K, quiet=True)
    fetch_scalar(st.step)
    t0 = time.perf_counter()
    st, metrics = run_token_loop(setup, cfg, steps=steps, quiet=True)
    fetch_scalar(st.step)
    dt = max(time.perf_counter() - t0 - rtt, 0.0) / steps
    flops = None
    if K > 1:
        # flops of the actual chunked program, from an explicit lowering of
        # the same jitted callable the loop dispatches. AFTER the timed run
        # on purpose: AOT compile does not share the jit dispatch cache, so
        # doing it first would pay the flagship multi-minute compile twice
        # on a cold persistent cache (warm cache absorbs this one).
        from draco_tpu import rng as drng
        from draco_tpu.parallel.sp_step import synthetic_text
        import numpy as np

        adv = drng.adversary_schedule(cfg.seed, K + 1, cfg.num_workers,
                                      cfg.num_adversaries)
        if cfg.token_gen == "device":
            toks = np.arange(1, K + 1, dtype=np.int32)
        else:
            toks = np.stack([
                synthetic_text(cfg.seed, s, cfg.num_workers, cfg.batch_size,
                               cfg.seq_len, cfg.vocab)
                for s in range(1, K + 1)
            ])
        with mesh:
            # st is the live final state (setup/warm states were donated)
            compiled = setup.train_token_many.lower(
                st, toks, np.asarray(adv[1 : K + 1]), None
            ).compile()
        # XLA cost analysis counts a scan body ONCE regardless of trip count
        # (bench.py), so this already is the per-step figure
        flops = bench._compiled_flops(compiled)
    return dt * 1e3, flops, float(metrics["loss"])


def build_lm_variants(*, batch_size, num_workers, seq_len, vocab, model_dim,
                      model_heads, model_layers, remat, max_steps,
                      scan_layers=False):
    """The canonical LM benchmark variant configs (one source of truth —
    also imported by tools/tpu_lm_lowering_check.py so the offline lowering
    audit can never drift from what this tool measures on chip)."""
    common = dict(
        network="TransformerLM", dataset="synthetic-text",
        batch_size=batch_size, lr=0.01, momentum=0.9,
        num_workers=num_workers, worker_fail=1, err_mode="rev_grad",
        seq_len=seq_len, vocab=vocab, model_dim=model_dim,
        model_heads=model_heads, model_layers=model_layers,
        compute_dtype="bfloat16", remat=remat, scan_layers=scan_layers,
        max_steps=max_steps, eval_freq=0,
        train_dir="", log_every=10**9,
    )
    return {
        # redundancy must be EXPLICIT here: the LM paths honour it now
        # (parallel/tp_step.py simulate lanes); the shared variant would
        # otherwise silently inherit the config default "simulate"
        "lm_cyclic_s1_shared_bf16": dict(common, approach="cyclic",
                                         redundancy="shared"),
        # reference-parity r=2s+1 redundant compute at LM scale
        # (cyclic_worker.py:122-146) — the r-cost VERDICT r2 item 6 asks for
        "lm_cyclic_s1_simulate_bf16": dict(common, approach="cyclic",
                                           redundancy="simulate"),
        # the same coded step with the Pallas flash kernel in place of
        # dense attention — the long-context hot-op on the training path
        "lm_cyclic_s1_shared_bf16_flash": dict(common, approach="cyclic",
                                               redundancy="shared",
                                               attn_impl="flash"),
        "lm_geomedian_bf16": dict(common, approach="baseline",
                                  mode="geometric_median"),
        "lm_krum_bf16": dict(common, approach="baseline", mode="krum"),
        "lm_mean_no_attack_bf16": dict(common, approach="baseline",
                                       mode="normal", worker_fail=0),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str, default="baselines_out/tpu_lm_perf.json")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--num-workers", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--model-dim", type=int, default=768)
    ap.add_argument("--model-heads", type=int, default=12)
    ap.add_argument("--model-layers", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--remat", action="store_true",
                    help="per-block rematerialisation — buys bigger "
                         "batch × seq at ~1/3 extra fwd FLOPs")
    ap.add_argument("--scan-layers", action="store_true",
                    help="compile the layer stack as one nn.scan body — "
                         "~layers× smaller XLA program, for configs that "
                         "hit compile-time/service ceilings (PERF.md §4)")
    ap.add_argument("--variants", type=str, default="",
                    help="comma-separated subset of variants to run")
    ap.add_argument("--production-loop", action="store_true",
                    help="time the production chunked token loop "
                         "(parallel/token_loop.run_token_loop with "
                         "--steps-per-call) instead of this tool's private "
                         "scan harness — the §1b variants re-timed on the "
                         "path users run")
    ap.add_argument("--steps-per-call", type=int, default=0,
                    help="K for --production-loop (0 = --steps, i.e. the "
                         "whole timed run is one chunk, matching the "
                         "private harness's fold)")
    ap.add_argument("--token-gen", type=str, default="host",
                    choices=["host", "device"],
                    help="--production-loop token stream (config.token_gen)")
    ap.add_argument("--cpu-mesh", type=int, default=0)
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    import jax

    import bench
    from draco_tpu.config import TrainConfig
    from draco_tpu.parallel.mesh import make_folded_wtp_mesh

    mesh = make_folded_wtp_mesh(args.num_workers)
    dev = jax.devices()[0]
    n_dev = mesh.devices.size

    variants = build_lm_variants(
        batch_size=args.batch_size, num_workers=args.num_workers,
        seq_len=args.seq_len, vocab=args.vocab, model_dim=args.model_dim,
        model_heads=args.model_heads, model_layers=args.model_layers,
        remat=args.remat, max_steps=args.steps + 1,
        scan_layers=args.scan_layers,
    )

    if args.variants:
        keep = {v.strip() for v in args.variants.split(",")}
        variants = {k: v for k, v in variants.items() if k in keep}
        if not variants:
            raise SystemExit(f"no variants match {sorted(keep)}")

    steps_per_call = ((args.steps_per_call or args.steps)
                      if args.production_loop else 1)
    report = {
        "platform": dev.platform,
        "remat": args.remat,
        "scan_layers": args.scan_layers,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "num_workers": args.num_workers,
        "devices_used": n_dev,
        "batch_size_per_worker": args.batch_size,
        "seq_len": args.seq_len,
        "model_dim": args.model_dim,
        "model_layers": args.model_layers,
        "vocab": args.vocab,
        "tokens_per_step": args.num_workers * args.batch_size * args.seq_len,
        "steps_per_scan": args.steps,
        # which loop produced the numbers (bench.py records the same key):
        # production = parallel/token_loop.run_token_loop chunked driver;
        # 1 = this tool's private scan harness folding --steps eagerly
        "steps_per_call": steps_per_call,
        "loop": ("production_run_token_loop" if args.production_loop
                 else "private_scan_harness"),
        "token_gen": args.token_gen if args.production_loop else "host",
    }
    peak = bench._peak_flops(report["device_kind"])
    for name, kw in variants.items():
        print(f"[tpu_lm_perf] measuring {name} ...", file=sys.stderr, flush=True)
        t0 = time.time()
        if args.production_loop:
            cfg = TrainConfig(**dict(kw, steps_per_call=steps_per_call,
                                     token_gen=args.token_gen,
                                     max_steps=args.steps + steps_per_call))
            ms, flops, loss = run_lm_production(cfg, mesh, args.steps)
        else:
            ms, flops, loss = run_lm(TrainConfig(**kw), mesh, args.steps,
                                     reps=args.reps)
        print(f"[tpu_lm_perf] {name}: {ms:.2f} ms/step ({time.time()-t0:.0f}s)",
              file=sys.stderr, flush=True)
        report[f"{name}_step_ms"] = round(ms, 3)
        report[f"{name}_loss"] = round(loss, 4)
        if flops:
            report[f"{name}_flops_per_step"] = flops
            if peak:
                report[f"{name}_mfu_vs_bf16_peak"] = round(
                    flops / (ms * 1e-3) / peak, 4
                )
    if ("lm_geomedian_bf16_step_ms" in report
            and "lm_cyclic_s1_shared_bf16_step_ms" in report):
        report["lm_cyclic_vs_geomedian_step_speedup"] = round(
            report["lm_geomedian_bf16_step_ms"]
            / report["lm_cyclic_s1_shared_bf16_step_ms"], 3
        )

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=1)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
