#!/bin/bash
# Poll the TPU tunnel with bounded probes until it answers; log transitions.
# Usage: tools/tpu_watch.sh [interval_s] — writes /tmp/tpu_watch.log
INT=${1:-120}
while true; do
  if timeout -k 10 90 python -c "import jax; d=jax.devices(); assert d[0].platform!='cpu'" 2>/dev/null; then
    echo "$(date +%H:%M:%S) TPU UP" >> /tmp/tpu_watch.log
    exit 0
  else
    echo "$(date +%H:%M:%S) tpu down" >> /tmp/tpu_watch.log
  fi
  sleep "$INT"
done
