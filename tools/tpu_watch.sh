#!/bin/bash
# Poll the TPU tunnel (via the shared bounded probe, tools/wait_tpu.sh)
# until it answers; log transitions to /tmp/tpu_watch.log.
# Usage: tools/tpu_watch.sh [interval_s]
INT=${1:-150}
cd "$(dirname "$0")/.."
while true; do
  if tools/wait_tpu.sh 1 0 90 > /dev/null 2>&1; then
    echo "$(date +%H:%M:%S) TPU UP" >> /tmp/tpu_watch.log
    exit 0
  fi
  echo "$(date +%H:%M:%S) tpu down" >> /tmp/tpu_watch.log
  sleep "$INT"
done
