#!/usr/bin/env python
"""Fold a run's telemetry artifacts into a per-phase wall-clock table.

Reads the host span trace (``trace.json``, Chrome trace events written by
draco_tpu/obs/tracer.py) and, when present, ``metrics.jsonl`` from the same
train_dir, and prints where the run's host wall-clock went:

  python tools/trace_report.py train_out/            # a train/trace dir
  python tools/trace_report.py path/to/trace.json --json report.json

Per phase (gather/upload/dispatch/sync/flush/eval/ckpt + the prefetcher
lanes): call count, total/mean/max milliseconds, and share of the traced
wall. The metrics side contributes the device-facing per-step averages the
records already carry (t_fetch / t_comp) and the step count, so one table
answers the question the chunked regime's dark host otherwise hides: how
much of a chunk's wall-clock was host work vs device execution.

No jax import — this is a pure-host artifact folder usable on a laptop
against artifacts scp'd from a chip job. It tolerates the partial-artifact
states a killed run leaves behind (missing/empty metrics.jsonl, a torn
JSONL tail) and surfaces the tracer's top-level ``droppedEvents`` count in
the header — a long run's trace is a sliding window of its newest spans,
and a report that hid the drop count would present the window as the run.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_trace(path: str) -> "tuple[list, int]":
    """(events, droppedEvents). The tracer's bounded buffer drops the
    oldest spans on very long runs and records the count top-level
    (obs/tracer.py); a report that hid it would present a sliding window
    as the whole run."""
    with open(path) as fh:
        payload = json.load(fh)
    if isinstance(payload, list):  # bare event-array form of the format
        return payload, 0
    events = payload.get("traceEvents", [])
    if not isinstance(events, list):
        raise SystemExit(f"{path}: no traceEvents array")
    return events, int(payload.get("droppedEvents", 0) or 0)


def fold_spans(events: list) -> "tuple[dict, float]":
    """name -> {count, total_ms, mean_ms, max_ms, share}; traced wall is the
    envelope of all complete events (ts..ts+dur, microseconds)."""
    by_name = collections.defaultdict(lambda: {"count": 0, "total_ms": 0.0,
                                               "max_ms": 0.0})
    t_lo, t_hi = float("inf"), float("-inf")
    for ev in events:
        if ev.get("ph") != "X":
            continue
        dur_ms = float(ev.get("dur", 0.0)) / 1e3
        row = by_name[ev["name"]]
        row["count"] += 1
        row["total_ms"] += dur_ms
        row["max_ms"] = max(row["max_ms"], dur_ms)
        t_lo = min(t_lo, float(ev["ts"]))
        t_hi = max(t_hi, float(ev["ts"]) + float(ev.get("dur", 0.0)))
    wall_ms = (t_hi - t_lo) / 1e3 if t_hi > t_lo else 0.0
    for row in by_name.values():
        row["mean_ms"] = row["total_ms"] / row["count"]
        row["share"] = row["total_ms"] / wall_ms if wall_ms else 0.0
    return dict(by_name), wall_ms


def fold_counters(events: list) -> dict:
    """counter name -> {samples, last, max}."""
    out = {}
    for ev in events:
        if ev.get("ph") != "C":
            continue
        val = list(ev.get("args", {}).values())
        if not val:
            continue
        row = out.setdefault(ev["name"], {"samples": 0, "last": 0, "max": 0})
        row["samples"] += 1
        row["last"] = val[0]
        row["max"] = max(row["max"], val[0])
    return out


def fold_metrics(path: str) -> dict:
    """Step count + summed per-step segment seconds from metrics.jsonl
    (t_fetch/t_comp are per-step amortized values, so their sums are the
    regime's host-gather and device-execution wall respectively), plus the
    cumulative guard totals and the run's final decode-health detection
    precision/recall folded from the per-step columns (the PR 6 guard
    columns and PR 4 health counts used to be invisible to this jax-free
    path). Torn/empty/missing states are the shared replay scaffold's job
    (draco_tpu/obs/replay.py — one tolerance rule for every report tool)."""
    steps = 0
    sums = collections.defaultdict(float)
    first = last = None
    guard_seen = health_seen = False
    for rec in _train_records(path):
        steps += 1
        last = rec
        if first is None:
            first = rec
        for key in ("t_fetch", "t_comp"):
            if key in rec:
                sums[key] += float(rec[key])
        if "guard_trips" in rec:
            guard_seen = True
            sums["guard_trips"] += float(rec["guard_trips"])
            sums["skipped_steps"] += float(rec.get("skipped_steps", 0.0))
        if "det_tp" in rec:
            health_seen = True
            sums["det_tp"] += float(rec["det_tp"])
            sums["det_adv"] += float(rec.get("det_adv", 0.0))
            for k in ("located_errors", "det_flagged"):
                if k in rec:
                    sums["det_flagged"] += float(rec[k])
                    break
    out = {"train_records": steps}
    out.update({f"{k}_total_s": round(v, 4) for k, v in sums.items()
                if k in ("t_fetch", "t_comp")})
    if guard_seen:
        out["guard_trips"] = sums["guard_trips"]
        out["skipped_steps"] = sums["skipped_steps"]
    if health_seen:
        # same empty-denominator convention as obs/heartbeat.decode_health:
        # nothing flagged / no live adversary is a healthy 1.0
        tp, fl, adv = sums["det_tp"], sums["det_flagged"], sums["det_adv"]
        out["det_precision"] = round(tp / fl, 4) if fl else 1.0
        out["det_recall"] = round(tp / adv, 4) if adv else 1.0
    if first is not None:
        out["first_loss"] = first.get("loss")
        out["last_loss"] = last.get("loss")
    return out


# The status.json schema contract lives in ONE table now —
# obs/heartbeat.STATUS_BLOCKS / check_status_schema (ISSUE 13 satellite:
# previously this tool carried its own accepted-set literal, and a schema
# bump could strand it). draco_tpu/obs imports without jax; only a BARE
# tools/ checkout (no package at all) degrades to unvalidated folding with
# a visible note, the same discipline as fold_device's capture probe.
try:
    from draco_tpu.obs.heartbeat import check_status_schema
    from draco_tpu.obs.replay import train_records as _train_records
except ImportError:  # bare tools/ checkout
    check_status_schema = None

    def _train_records(path):
        out = []
        try:
            fh = open(path)
        except OSError:
            return out
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail line of an interrupted run
                if not isinstance(rec, dict) or "loss" not in rec \
                        or rec.get("split") == "eval":
                    continue
                out.append(rec)
        return out


def fold_status(path: str) -> dict:
    """The run's heartbeat terminal state (obs/heartbeat.py): state
    done/preempted/crashed/running (+ cause / resumable_step) — how an
    operator tells a crash from a preemption from a finished run without a
    traceback. {} when no status.json exists. A ``schema`` field, when
    present, must satisfy the central contract table
    (obs/heartbeat.check_status_schema) — silently folding an unknown
    payload shape would misreport the run."""
    try:
        with open(path) as fh:
            status = json.load(fh)
    except (OSError, ValueError):
        return {}
    if not isinstance(status, dict):
        return {}
    if check_status_schema is not None:
        check_status_schema(status, path, "tools/trace_report.py")
    out = {}
    for key in ("schema", "state", "cause", "resumable_step", "step",
                "updated_at", "wire", "numerics", "incidents"):
        if key in status:
            out[key] = status[key]
    if check_status_schema is None and "schema" in status:
        out["schema_unvalidated"] = True  # bare checkout: note, don't guess
    return out


def fold_device(profile_dir: str):
    """The device half (ISSUE 9): when the run dir holds a jax.profiler
    capture, fold it into the per-phase device table + collective comms
    ledger via obs/device_attr (jax-free, but part of draco_tpu — this
    tool stays usable from a bare tools/ checkout by degrading to a note
    when the package is absent). Missing or torn captures are tolerated
    exactly like metrics.jsonl."""
    try:
        from draco_tpu.obs import device_attr
    except ImportError:
        # bare tools/ checkout: probe the capture layout inline (the one
        # place the package's find_capture glob can't be reused)
        import glob

        if glob.glob(os.path.join(profile_dir, "plugins", "profile", "*",
                                  "*.trace.json*")):
            return {"note": "profiler capture present but draco_tpu not "
                            "importable — device attribution skipped"}
        return None  # no capture at all — the common case, no note
    try:
        fold = device_attr.fold_capture(profile_dir)
    except Exception:
        return None
    if not fold:
        return None  # no capture (the common case) or a torn one
    out = {"trace": fold.get("trace"), "programs": []}
    anchor = fold.get("anchor") or {}
    if anchor.get("steps_profiled") is not None:
        out["steps_profiled"] = anchor["steps_profiled"]
    for prog in fold["programs"]:
        row = {
            "module": prog["module"],
            "total_device_us": round(prog["total_device_us"], 1),
            "wall_us": round(prog["wall_us"], 1),
            "phases": {k: {"time_us": round(v["time_us"], 1),
                           "frac": round(v["frac"], 4),
                           "events": v["events"]}
                       for k, v in prog["phases"].items()},
            "collectives": prog["collectives"],
        }
        out["programs"].append(row)
    return out


def make_report(trace_path: str, metrics_path=None, profile_dir=None) -> dict:
    events, dropped = load_trace(trace_path)
    phases, wall_ms = fold_spans(events)
    report = {
        "trace": trace_path,
        "traced_wall_ms": round(wall_ms, 3),
        "dropped_events": dropped,
        "phases": {
            name: {k: (round(v, 3) if isinstance(v, float) else v)
                   for k, v in row.items()}
            for name, row in sorted(phases.items())
        },
        "counters": fold_counters(events),
    }
    # status.json lives in train_dir, which may differ from trace_dir (the
    # CLI flags are independent) — probe both the trace's and the metrics
    # file's directory
    candidates = [os.path.join(os.path.dirname(trace_path), "status.json")]
    if metrics_path:
        candidates.append(os.path.join(os.path.dirname(metrics_path),
                                       "status.json"))
    for cand in candidates:
        status = fold_status(cand)
        if status:
            report["run_status"] = status
            break
    # a missing or empty metrics.jsonl is a normal state (no train_dir, or
    # a run killed before its first flush) — the trace half still folds
    if metrics_path and os.path.exists(metrics_path):
        try:
            report["metrics"] = fold_metrics(metrics_path)
            report["metrics"]["path"] = metrics_path
        except OSError:
            pass
    # device half (ISSUE 9): default probe is the trace's own directory —
    # runs that pointed --profile-dir at the train/trace dir get the device
    # table for free; a missing capture folds nothing
    probe = profile_dir or os.path.dirname(trace_path) or "."
    device = fold_device(probe)
    if device:
        report["device"] = device
    return report


def print_table(report: dict, out=None) -> None:
    # resolve stdout at call time: a default bound at import time pins
    # whatever stream was installed then (pytest capture, a redirect) and
    # outlives it
    out = out if out is not None else sys.stdout
    dropped = report.get("dropped_events", 0)
    print(f"trace: {report['trace']}   traced wall: "
          f"{report['traced_wall_ms']:.1f} ms"
          + (f"   DROPPED EVENTS: {dropped} (sliding window — totals "
             f"undercount the run)" if dropped else ""), file=out)
    status = report.get("run_status")
    if status:
        line = f"run state: {status.get('state', '?')}"
        if status.get("cause"):
            line += f"   cause: {status['cause']}"
        if status.get("resumable_step") is not None:
            line += f"   resumable from step {status['resumable_step']}"
        print(line, file=out)
    # wire ledger + numerics observatory (ISSUE 10): the status blocks a
    # watch-enabled run stamps — logical bytes per worker per step with
    # the narrow-dtype candidates, and the folded range/shadow extremes
    wire = (status or {}).get("wire")
    if wire:
        b = wire.get("bytes_per_worker", {})
        f32 = b.get("f32")
        parts = [f"wire[{wire.get('family')}]: d={wire.get('dim')}"]
        if f32:
            parts.append(f"f32 {f32 / 1024:.1f} KiB/worker/step")
            for dt in ("bf16", "int8"):
                if b.get(dt):
                    parts.append(f"{dt} {b[dt] / 1024:.1f} KiB "
                                 f"({b[dt] / f32:.2f}x)")
        if wire.get("shadow_wire", "off") != "off":
            parts.append(f"shadow={wire['shadow_wire']}")
        # the MATERIALIZED wire (ISSUE 15): what the run physically ships
        if wire.get("wire_dtype", "f32") != "f32":
            phys = wire.get("physical_bytes_per_worker")
            tag = f"materialized={wire['wire_dtype']}"
            if phys:
                tag += f" ({phys / 1024:.1f} KiB/worker/step physical)"
            parts.append(tag)
        print("   ".join(parts), file=out)
    nx = (status or {}).get("numerics")
    if nx:
        bits = []
        for k in ("nx_wire_absmax", "nx_wire_rms", "shadow_err_max",
                  "shadow_residual_max", "shadow_flag_agree_min",
                  "nx_wire_uf_int8_max", "nx_grad_nonfinite_max"):
            if k in nx:
                bits.append(f"{k.replace('nx_', '')}={nx[k]:.4g}")
        if bits:
            print("numerics: " + "  ".join(bits), file=out)
    # incident engine roll-up (obs/incidents.py, ISSUE 13): the status
    # block a watch-enabled run stamps — open episodes are the headline
    inc = (status or {}).get("incidents")
    if inc:
        line = f"incidents: {inc.get('total', 0)} total"
        by_type = inc.get("by_type") or {}
        if by_type:
            line += " (" + ", ".join(f"{k}:{v}" for k, v
                                     in sorted(by_type.items())) + ")"
        for ep in inc.get("open") or []:
            workers = ",".join(map(str, ep.get("workers") or ())) or "-"
            line += (f"   OPEN {ep.get('type')}@{ep.get('onset_step')} "
                     f"workers={workers}")
        print(line, file=out)
    # guard + decode-health header (folded from the per-step columns —
    # previously invisible to this jax-free path)
    m = report.get("metrics") or {}
    if "guard_trips" in m:
        print(f"guard: trips={m['guard_trips']:g} "
              f"skipped_steps={m['skipped_steps']:g}", file=out)
    if "det_precision" in m:
        print(f"decode health: precision={m['det_precision']:.4f} "
              f"recall={m['det_recall']:.4f}", file=out)
    hdr = f"{'phase':<22}{'count':>7}{'total ms':>12}{'mean ms':>10}" \
          f"{'max ms':>10}{'share':>8}"
    print(hdr, file=out)
    print("-" * len(hdr), file=out)
    rows = sorted(report["phases"].items(),
                  key=lambda kv: -kv[1]["total_ms"])
    for name, r in rows:
        print(f"{name:<22}{r['count']:>7}{r['total_ms']:>12.2f}"
              f"{r['mean_ms']:>10.3f}{r['max_ms']:>10.2f}"
              f"{r['share']:>8.1%}", file=out)
    for name, c in sorted(report.get("counters", {}).items()):
        print(f"counter {name}: samples={c['samples']} last={c['last']} "
              f"max={c['max']}", file=out)
    m = report.get("metrics")
    if m:
        bits = [f"train_records={m['train_records']}"]
        bits += [f"{k}={m[k]}" for k in sorted(m)
                 if k.endswith("_total_s")]
        if "last_loss" in m:
            bits.append(f"loss {m.get('first_loss'):.4f} -> "
                        f"{m.get('last_loss'):.4f}")
        print("metrics: " + "  ".join(bits), file=out)
    # per-phase device table + comms ledger (ISSUE 9) — only when the run
    # dir holds a profiler capture
    dev = report.get("device")
    if dev and dev.get("note"):
        print(f"device: {dev['note']}", file=out)
    elif dev:
        steps = dev.get("steps_profiled")
        for prog in dev.get("programs", []):
            print(f"device program {prog['module']}: "
                  f"{prog['total_device_us'] / 1e3:.1f} ms device self-time"
                  + (f" over {steps} profiled steps" if steps else ""),
                  file=out)
            hdr = f"  {'device phase':<20}{'events':>8}{'total ms':>12}" \
                  f"{'share':>8}"
            print(hdr, file=out)
            print("  " + "-" * (len(hdr) - 2), file=out)
            rows = sorted(prog["phases"].items(),
                          key=lambda kv: -kv[1]["time_us"])
            for name, r in rows:
                print(f"  {name:<20}{r['events']:>8}"
                      f"{r['time_us'] / 1e3:>12.2f}{r['frac']:>8.1%}",
                      file=out)
            for side in ("explicit", "gspmd"):
                for kind, row in sorted(
                        (prog.get("collectives", {}).get(side) or {})
                        .items()):
                    if not row.get("instructions"):
                        continue
                    print(f"  collective {side}/{kind}: "
                          f"instructions={row['instructions']} "
                          f"events={row['events']} bytes={row['bytes']} "
                          f"time_ms={row['time_us'] / 1e3:.2f}", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="trace.json, or a directory holding "
                                 "trace.json (+ metrics.jsonl)")
    ap.add_argument("--metrics", default="",
                    help="metrics.jsonl path (default: next to the trace)")
    ap.add_argument("--json", default="",
                    help="also write the folded report as JSON here")
    ap.add_argument("--profile-dir", default="",
                    help="jax.profiler capture dir for the device table "
                         "(default: probe the trace's own directory)")
    args = ap.parse_args(argv)

    trace_path = args.path
    if os.path.isdir(trace_path):
        trace_path = os.path.join(trace_path, "trace.json")
    metrics_path = args.metrics or os.path.join(
        os.path.dirname(trace_path), "metrics.jsonl")
    report = make_report(trace_path, metrics_path,
                         profile_dir=args.profile_dir or None)
    print_table(report)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
