#!/bin/bash
# Round-5 chain i: the d~159M cyclic point at n=6 logical workers.
# Chain r5h proved the d~159M cyclic OOM is batch-independent: 16.04G of
# 15.75G at b2 AND b1 (T=2048) — the peak is the coded-path buffers
# (grad stack (n,d) 5.1G + encode re/im 10.2G at n=8), not activations.
# n=6 (still s=1-valid: n > 4s) shrinks those to ~11.5G with ZERO
# semantic/precision changes; geomedian is re-measured at the same n so
# the decode-vs-geomedian ratio stays matched. Shapes otherwise the
# flagship T=2048 remat+flash+scan.
#   1 flash_n6   cyclic shared + flash, n=6, T=2048 b1 remat scan
#   2 geomed_n6  geomedian,            n=6, T=2048 b1 remat scan
# Parks until chains r5..r5h are gone.
#
# Launch detached (variable indirection — SKILL.md round-5 note):
#   s=tools/chip_jobs_r5i.sh; setsid nohup bash "$s" > baselines_out/chip_jobs_r5i.log 2>&1 &
# NEVER edit this file while it runs. Markers: baselines_out/.r5i_<rung>_done
set -u
cd "$(dirname "$0")/.."
mkdir -p baselines_out

stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

commit_evidence() {
  local msg="$1"
  local files
  shopt -s nullglob
  files=(baselines_out/*.json baselines_out/*.jsonl baselines_out/*.log)
  shopt -u nullglob
  if [ "${#files[@]}" = 0 ]; then
    echo "[r5i $(stamp)] no artifact files exist yet for: $msg"
    return 0
  fi
  for i in 1 2 3; do
    if ! git add -- "${files[@]}"; then
      echo "[r5i $(stamp)] git add failed (attempt $i), retrying"
      sleep 5
      continue
    fi
    if git diff --cached --quiet -- baselines_out 2>/dev/null; then
      echo "[r5i $(stamp)] nothing new to commit for: $msg"
      return 0
    fi
    if git commit -q -m "$msg" -- baselines_out; then
      echo "[r5i $(stamp)] committed: $msg"
      return 0
    fi
    echo "[r5i $(stamp)] git commit failed (attempt $i), retrying"
    sleep 5
  done
  echo "[r5i $(stamp)] WARNING: commit failed for: $msg (evidence still on disk)"
  return 0
}

tpu_up() {
  timeout -k 30 120 python - <<'EOF'
import sys, jax
try:
    d = jax.devices()
    sys.exit(0 if d and d[0].platform != "cpu" else 3)
except Exception:
    sys.exit(3)
EOF
}

others_running() {
  for s in chip_jobs_r5.sh chip_jobs_r5b.sh chip_jobs_r5c.sh \
           chip_jobs_r5d.sh chip_jobs_r5e.sh chip_jobs_r5f.sh \
           chip_jobs_r5h.sh; do
    pgrep -f "bash tools/$s" > /dev/null 2>&1 && return 0
  done
  return 1
}

echo "[r5i $(stamp)] waiting for chains r5..r5h to finish"
while others_running; do
  sleep 60
done
echo "[r5i $(stamp)] predecessors gone; proceeding"

ABORT_PASS=0
FAILURES=0
rung() {
  local name="$1" msg="$2"; shift 2
  local marker="baselines_out/.r5i_${name}_done"
  if [ -f "$marker" ] || [ "$ABORT_PASS" = 1 ]; then
    return 0
  fi
  echo "[r5i $(stamp)] ===== rung $name: $* ====="
  local rc=0
  "$@" || rc=$?
  if [ "$rc" = 0 ]; then
    touch "$marker"
    commit_evidence "$msg"
  else
    echo "[r5i $(stamp)] rung $name FAILED (rc=$rc); probing tunnel"
    commit_evidence "$msg (partial: rung exited rc=$rc)"
    FAILURES=$((FAILURES + 1))
    if ! tpu_up; then
      echo "[r5i $(stamp)] tunnel down — aborting this pass, back to wait loop"
      ABORT_PASS=1
    fi
  fi
}

all_done() {
  for m in flash_n6 geomed_n6; do
    [ -f "baselines_out/.r5i_${m}_done" ] || return 1
  done
  return 0
}

for outer in 1 2; do
  echo "[r5i $(stamp)] ===== outer attempt $outer ====="
  if all_done; then break; fi
  tools/wait_tpu.sh 60 150 120 || { echo "[r5i $(stamp)] tunnel never came up this window"; continue; }
  FAILURES=0
  ABORT_PASS=0

  rung flash_n6 "chip evidence: d~159M LM cyclic+flash n=6 T=2048 (scan, coded buffers fit)" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --num-workers 6 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 2048 --batch-size 1 --remat --scan-layers \
      --variants lm_cyclic_s1_shared_bf16_flash \
      --out baselines_out/tpu_lm_perf_159_flash_n6.json

  rung geomed_n6 "chip evidence: d~159M LM geomedian n=6 T=2048 (scan, matched pair)" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --num-workers 6 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 2048 --batch-size 1 --remat --scan-layers \
      --variants lm_geomedian_bf16 \
      --out baselines_out/tpu_lm_perf_159_geomed_n6.json

  if all_done; then
    echo "[r5i $(stamp)] D~159M N=6 MATCHED PAIR COMPLETE"
    break
  fi
  echo "[r5i $(stamp)] incomplete ($FAILURES rung failures this pass); retrying"
  sleep 120
done
all_done && exit 0 || exit 1
