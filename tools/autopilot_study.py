#!/usr/bin/env python
"""Autopilot study: the adaptive coding autopilot vs every fixed (family,
redundancy) configuration under ONE time-varying adversary + churn
scenario — ROADMAP item 5's committed evidence that closing the control
loop pays.

The scenario (declarative, resilience/faults.py episode grammar):

  adversary@5-20:w2      a sustained Byzantine EPISODE: worker 2 attacks
                         with cfg.err_mode for steps 5-20 (within the
                         s=1 budget — the regime that REQUIRES an exact
                         family; approx has no certificate and is
                         rejected by config.validate → recorded as the
                         infeasible row, which is the point)
  straggle@26-44:w5      a sustained drop (spot instance) for steps 26-44
  straggle@36-42:w6:d2:every6
                         CHURN: 2-step drops recurring through 36-42

No fixed point is right for all three phases: exact cyclic r=3 survives
everything but pays 3× fleet compute on the quiet tail; approx r=1.5
cannot run the adversary phase at all. The autopilot starts cyclic,
quarantines the trust-collapsed worker 2, re-admits it after the clean
window, dials down to approx r=1.5 when the sustained straggle episode
opens (adversary evidence quiet), and dials back up when it clears.

Each cell trains the same FC/synthetic-mnist workload on the production
chunked Trainer loop (steps_per_call=4, guards + incident watch on) and
records, from the run's own metrics.jsonl + incidents.jsonl:

  steps_to_target      first step whose 5-step smoothed train loss
                       reaches --target-loss (deterministic on a fixed
                       backend — schedules, data, decode all seeded)
  compute_to_target    Σ over steps to target of n × load(step), where
                       load is the PER-STEP per-worker batch load read
                       from the record's own column family (cyclic
                       records → r=2s+1, approx records → r_low): the
                       metric a real fleet pays, and the axis the
                       autopilot wins on
  remediations         every autopilot decision, each carrying its
                       triggering incident (attribution coverage is a
                       certificate bool)
  quarantine_clean     the quarantined worker's rows really stopped
                       arriving (present bit off through the quarantine
                       window) and no guard trip ever fired — the
                       "quarantined workers never corrupt the aggregate"
                       acceptance pin

``tools/perf_watch.py`` folds the committed artifact (certificate bools
at tolerance 0 — autopilot_beats_fixed flipping false gates) and
``tools/check_artifacts.py`` re-verifies it jax-free.

Usage (CPU, ~2 min):
  python tools/autopilot_study.py --cpu-mesh 8
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from draco_tpu.cli import maybe_force_cpu_mesh  # noqa: E402

NUM_WORKERS = 8
ADV_WORKER = 2
STRAGGLE_WORKER = 5
SCENARIO = ("adversary@5-20:w2,straggle@26-44:w5,"
            "straggle@36-42:w6:d2:every6")
R_LOW = 1.5
R_EXACT = 3.0  # cyclic s=1 -> r = 2s+1

CELLS = {
    # the autopilot starts at the exact base point and moves the dial
    "autopilot": dict(approach="cyclic", worker_fail=1, adversary_count=0,
                      redundancy="shared", autopilot="on"),
    # fixed exact point: survives every phase, pays r=3 forever
    "cyclic_r3": dict(approach="cyclic", worker_fail=1, adversary_count=0,
                      redundancy="shared"),
    # fixed approx point: CANNOT run the adversary phase (no Byzantine
    # certificate — config.validate rejects adversary fault events);
    # recorded infeasible rather than skipped, because "this scenario is
    # CLOSED to the cheap family" is the study's point
    "approx_r1.5": dict(approach="approx", worker_fail=0,
                        redundancy="shared", code_redundancy=R_LOW,
                        straggler_alpha=0.25),
}
# boundary hysteresis tuned to the 64-step cell (defaults are sized for
# long production runs); committed verbatim so the artifact is replayable
POLICY = "readmit_boundaries=6,dial_up_boundaries=3"


def _load_of(record) -> float:
    """Per-worker batch load of the step that produced ``record``, read
    from its OWN column family: approx records carry the residual-bound
    certificate, cyclic records the located-errors machinery."""
    return R_LOW if "decode_residual_bound" in record else R_EXACT


def run_cell(name: str, args, mesh, ds) -> dict:
    import numpy as np

    from draco_tpu.config import TrainConfig
    from draco_tpu.obs import replay
    from draco_tpu.obs.forensics import record_masks
    from draco_tpu.training.trainer import Trainer

    kw = CELLS[name]
    row = {"cell": name, "feasible": True,
           "fleet_load": (None if name == "autopilot"
                          else kw.get("code_redundancy", R_EXACT))}
    d = tempfile.mkdtemp(prefix=f"autopilot_{name}_")
    try:
        cfg = TrainConfig(
            network="FC", dataset="synthetic-mnist", batch_size=4, lr=0.012,
            momentum=0.9, num_workers=NUM_WORKERS, max_steps=args.max_steps,
            eval_freq=4, train_dir=d, log_every=1,
            steps_per_call=args.steps_per_call, step_guard="on",
            incident_watch="on", err_mode=args.err_mode,
            fault_spec=SCENARIO, autopilot_policy=POLICY, **kw,
        )
        try:
            cfg.validate()
        except ValueError as e:
            row.update(feasible=False, detail=str(e)[:300])
            return row
        tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
        try:
            t0 = time.perf_counter()
            tr.run()
            wall_s = time.perf_counter() - t0
        finally:
            tr.close()

        recs = [r for r in replay.train_records(
            os.path.join(d, "metrics.jsonl")) if "loss" in r]
        status = json.load(open(os.path.join(d, "status.json")))
        rems = [e for e in replay.iter_jsonl(
            os.path.join(d, "incidents.jsonl"))
            if e.get("event") == "remediation"]

        losses = [r["loss"] for r in recs]
        smooth = [float(np.mean(losses[max(0, i - 4):i + 1]))
                  for i in range(len(losses))]
        steps_to = next((i + 1 for i, v in enumerate(smooth)
                         if v <= args.target_loss), None)
        loads = [_load_of(r) for r in recs]
        compute_to = (round(sum(loads[:steps_to]) * NUM_WORKERS)
                      if steps_to is not None else None)
        guard_trips = sum(r.get("guard_trips", 0.0) for r in recs)
        row.update({
            "steps": len(recs),
            "steps_to_target": steps_to,
            "reached_target": steps_to is not None,
            "compute_to_target": compute_to,
            "final_loss_smoothed": round(smooth[-1], 6),
            "guard_trips_total": guard_trips,
            "terminal_state": status.get("state"),
            "wall_s": round(wall_s, 3),
            "mean_load": round(float(np.mean(loads)), 4),
        })
        if name != "autopilot":
            row["ok"] = bool(row["reached_target"]
                             and status.get("state") == "done"
                             and guard_trips == 0.0)
            return row

        # --- autopilot-only certificates --------------------------------
        control = status.get("control") or {}
        row["regime_final"] = (control.get("regime") or {}).get("tag")
        row["swaps"] = control.get("swaps", 0)
        actions = [e.get("action") for e in rems]
        row["remediations"] = [
            {"action": e.get("action"), "step": e.get("step"),
             "worker": e.get("worker"),
             "regime": (e.get("regime") or {}).get("tag"),
             "trigger": ((e.get("trigger") or {}).get("type")),
             "trigger_onset": ((e.get("trigger") or {}).get("onset_step"))}
            for e in rems]
        # every decision names its triggering incident
        row["remediations_attributed"] = bool(rems) and all(
            (e.get("trigger") or {}).get("type")
            and (e.get("trigger") or {}).get("onset_step") is not None
            for e in rems)
        row["dialed_down"] = "dial_down" in actions
        row["dialed_up"] = "dial_up" in actions
        # quarantine never corrupts the aggregate: the quarantined
        # worker's rows stop arriving (present bit off from the effective
        # step + one pipeline chunk, until re-admission), the run never
        # trips a guard, and the worker was truly the scenario's adversary
        q = [e for e in rems if e.get("action") == "quarantine"]
        clean = bool(q) and guard_trips == 0.0
        for e in q:
            w = e.get("worker")
            lo = e.get("effective_step", 0) + args.steps_per_call
            hi = min((r.get("step") for r in rems
                      if r.get("action") == "readmit"
                      and r.get("worker") == w), default=len(recs))
            window = [r for r in recs if lo <= r.get("step", 0) <= hi]
            masks = [record_masks(r, NUM_WORKERS) for r in window]
            clean = clean and bool(window) and all(
                m is not None and not m["present"][w] for m in masks)
            clean = clean and w == ADV_WORKER
        row["quarantine_clean"] = clean
        row["ok"] = bool(row["reached_target"]
                         and status.get("state") == "done"
                         and guard_trips == 0.0
                         and row["remediations_attributed"]
                         and row["dialed_down"] and clean)
        return row
    finally:
        shutil.rmtree(d, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", type=str,
                    default=os.path.join("baselines_out",
                                         "autopilot_study.json"))
    ap.add_argument("--max-steps", type=int, default=64)
    ap.add_argument("--steps-per-call", type=int, default=4)
    ap.add_argument("--target-loss", type=float, default=1.50,
                    help="5-step smoothed train-loss target (calibrated "
                         "for the 64-step FC/synthetic-mnist scenario: "
                         "reached in the post-churn tail, where the dial "
                         "has already paid)")
    ap.add_argument("--err-mode", type=str, default="rev_grad")
    ap.add_argument("--cells", type=str, default="",
                    help="comma-separated subset (default: all)")
    ap.add_argument("--cpu-mesh", type=int, default=0, metavar="N",
                    help="force an N-device virtual CPU mesh")
    args = ap.parse_args(argv)
    if args.cpu_mesh:
        maybe_force_cpu_mesh(args)

    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh

    cells = [c for c in args.cells.split(",") if c] or list(CELLS)
    ds = load_dataset("synthetic-mnist", synthetic_train=512,
                      synthetic_test=128)
    mesh = make_mesh(NUM_WORKERS)
    rows = []
    for name in cells:
        row = run_cell(name, args, mesh, ds)
        rows.append(row)
        tag = ("infeasible" if not row["feasible"] else
               f"steps_to_target={row['steps_to_target']} "
               f"compute={row['compute_to_target']} ok={row.get('ok')}")
        print(f"autopilot_study: {name:12s} -> {tag}", flush=True)

    by = {r["cell"]: r for r in rows}
    ap_row = by.get("autopilot")
    fixed_live = {c: r["compute_to_target"] for c, r in by.items()
                  if c != "autopilot" and r.get("compute_to_target")
                  is not None}
    beats = bool(ap_row and ap_row.get("compute_to_target") is not None
                 and fixed_live
                 and all(ap_row["compute_to_target"] < v
                         for v in fixed_live.values()))
    infeasible_fixed = sorted(c for c, r in by.items()
                              if c != "autopilot" and not r["feasible"])
    payload = {
        "schema": 1,
        "tool": "tools/autopilot_study.py",
        "num_workers": NUM_WORKERS,
        "max_steps": args.max_steps,
        "steps_per_call": args.steps_per_call,
        "target_loss": args.target_loss,
        "scenario": SCENARIO,
        "policy": POLICY,
        "rows": rows,
        "fixed_compute_to_target": fixed_live,
        "infeasible_fixed": infeasible_fixed,
        # the headline certificate: strictly less fleet compute to target
        # than EVERY fixed configuration that can run the scenario at all
        "autopilot_beats_fixed": beats,
        "all_ok": bool(rows) and all(r.get("ok", True) for r in rows
                                     if r["feasible"]) and beats,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    print(f"autopilot_study: {len(rows)} cells -> {args.out} "
          f"(beats_fixed={beats}, infeasible={infeasible_fixed})")
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
