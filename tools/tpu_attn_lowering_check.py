#!/usr/bin/env python
"""Offline TPU-lowering audit of the flash-attention kernel (round 5).

Every recorded hardware failure of the kernel (baselines_out/tpu_attn.json,
rows all `ValueError: The Pallas TPU lowering currently requires that the
last two dimensions of your block shape are divisible by 8 and 128 ...`)
was raised by the *Python-side Pallas TPU lowering*, not by the Mosaic
machine-code compiler. That stage runs during cross-platform export
(`jax.export.export(..., platforms=["tpu"])`) on a CPU-only host, so the
fixed kernel can be audited against it with zero chip time:

  python tools/tpu_attn_lowering_check.py \
      [--out baselines_out/tpu_attn_lowering.json]

The audit covers fwd and fwd+bwd, causal (training path) and the
non-causal `flash_attention_with_lse` pair the ring hops use
(parallel/ring_attention.py), f32 and bf16, T in {256, 1024, 2048, 4096},
plus a NEGATIVE control: a deliberately mis-tiled pallas_call that must
raise the same ValueError the chip produced pre-fix — proving the harness
exercises the real check rather than silently skipping it.

What this cannot prove: the Mosaic -> machine-code stage (scoped-vmem
budgets, codegen bugs) still needs the one real chip; that is the
`attn_t256`/`attn_full` rungs of tools/chip_jobs_r5.sh. This audit bounds
the remaining hardware risk to exactly that stage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default="baselines_out/tpu_attn_lowering.json")
    ap.add_argument("--seq-lens", type=str, default="256,1024,2048,4096")
    args = ap.parse_args(argv)

    from tools._lowering_common import lint_row, run_rows, setup_cpu_host

    setup_cpu_host(1)
    import jax
    import jax.export
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from draco_tpu.analysis import (
        BF16_DTYPES, BuiltProgram, LintProgram, Manifest,
    )
    from draco_tpu.ops import flash_attention as fa

    # kernel-level rows: no state carry to donate and no cross-device
    # collectives, so those rules are manifest-skipped; constant-bloat,
    # dtype, and host-traffic still apply (a kernel baking a T-sized table
    # or upcasting to f64 should fail here, not on chip). The kernel's MXU
    # matmuls accumulate f32 in-op (dot_general preferred_element_type —
    # "the kernel accumulates f32 regardless", ops/flash_attention.py), so
    # dot_general joins the promotion whitelist here; the LM route
    # manifests stay convert-only.
    kernel_manifest = Manifest(require_donated=None, collectives=None,
                               allowed_dtypes=BF16_DTYPES,
                               bf16_promotion_whitelist=(
                                   "convert_element_type", "dot_general"))

    def kernel_program(name, fn, T, B=4, H=12, Dh=64, dtype=jnp.float32,
                       grad=False):
        def build():
            q = jnp.zeros((B, T, H, Dh), dtype)
            if grad:
                f = jax.jit(lambda q, k, v: jax.grad(
                    lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32))
                )(q, k, v))
            else:
                f = jax.jit(fn)
            # Pallas tpu_custom_call cannot compile for the CPU backend —
            # skip the memory capture instead of paying a guaranteed failure
            return BuiltProgram(name, f, (q, q, q), None, kernel_manifest,
                                capture_memory=False)

        return LintProgram(name=name, build=build, route="attn_kernel",
                           fast=False)

    fwd = lambda q, k, v: fa.flash_attention(q, k, v, force=True)  # noqa: E731
    ring = lambda q, k, v: fa.flash_attention_with_lse(  # noqa: E731
        q, k, v, causal=False, force=True)[0]

    named = []
    for t in [int(x) for x in args.seq_lens.split(",")]:
        for label, fn, kw in [
            ("causal_fwd_f32", fwd, {}),
            ("causal_fwdbwd_f32", fwd, {"grad": True}),
            ("causal_fwd_bf16", fwd, {"dtype": jnp.bfloat16}),
            ("ring_noncausal_fwdbwd_f32", ring, {"grad": True}),
        ]:
            p = kernel_program(f"T{t}_{label}", fn, t, **kw)
            named.append((p.name, (
                lambda p=p, t=t, label=label:
                    lint_row(p, extra_row={"seq_len": t, "variant": label}))))

    # negative control: this MUST fail with the historical ValueError
    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def bad(x):
        return pl.pallas_call(
            kern,
            grid=(4,),
            in_specs=[pl.BlockSpec((4, 12), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((4, 12), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 48), jnp.float32),
        )(x)

    x = jnp.zeros((16, 48), jnp.float32)
    try:
        jax.export.export(jax.jit(bad), platforms=["tpu"])(x)
        control = {"raised": False, "matches_historical": False}
    except Exception as e:  # record ANY failure type: a non-ValueError means
        # the lowering check moved/changed and the control must fail via the
        # matches_historical gate below, with the report still written
        control = {"raised": True,
                   "type": type(e).__name__,
                   "error_head": str(e)[:160],
                   "matches_historical": "Pallas TPU lowering" in str(e)}

    report = run_rows(
        args.out,
        "jax.export cross-platform lowering, platforms=['tpu'], CPU host — "
        "exercises the Pallas TPU lowering stage that produced every "
        "pre-fix hardware failure; each row carries the program-lint "
        "verdict (draco_tpu/analysis; donation/collectives manifest-skipped "
        "for kernel-level programs)",
        named,
        extra={"negative_control_bad_tiling": control},
    )
    print(json.dumps({"all_ok": report["all_ok"],
                      "negative_control_ok":
                          control.get("matches_historical", False)}))
    return 0 if (report["all_ok"]
                 and control.get("matches_historical")) else 1


if __name__ == "__main__":
    sys.exit(main())
