#!/usr/bin/env bash
# Checkpoint-polling evaluator — parity with the reference's
# evaluate_pytorch.sh (reference: src/evaluate_pytorch.sh:1-5): watches
# train_dir for step-indexed checkpoints and reports top-1/top-5.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m draco_tpu.training.evaluator \
  --network FC \
  --dataset MNIST \
  --train-dir ./train_out/ \
  --eval-freq 50 \
  "$@"
