#!/bin/bash
# Round-5 chain e: last-resort d~159M evidence. Every multi-variant attempt
# at the d~159M LM died in the tunnel's remote-compile service with
# "Broken pipe" at ~27 min (T=2048 remat ×2, T=1024 remat ×1 — records in
# tpu_lm_perf_big*.json / chain logs), and tpu_lm_perf aborts on its first
# variant, so the lighter variants behind the cyclic one never compiled.
# This chain tries ONE variant per rung, lightest compile first:
#   1 lm159_geomed     geomedian only, T=1024 b2, no remat (no coding
#                      graph, no remat graph — the lightest d~159M step)
#   2 lm159_shared     cyclic shared only, T=512 b4, no remat (the decode
#                      claim at d~159M with the smallest activation graph)
#   3 lm159_shared_1k  cyclic shared only, T=1024 b2, no remat
# Any rung that lands gives the decode-vs-geomedian comparison at d~159M
# (ratios compose across rungs at matched token counts).
# Parks until chains r5/r5b/r5c/r5d are gone.
#
# Launch detached:
#   setsid nohup bash tools/chip_jobs_r5e.sh > baselines_out/chip_jobs_r5e.log 2>&1 &
# NEVER edit this file while it runs. Markers: baselines_out/.r5e_<rung>_done
set -u
cd "$(dirname "$0")/.."
mkdir -p baselines_out

stamp() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

commit_evidence() {
  local msg="$1"
  local files
  shopt -s nullglob
  files=(baselines_out/*.json baselines_out/*.jsonl baselines_out/*.log)
  shopt -u nullglob
  if [ "${#files[@]}" = 0 ]; then
    echo "[r5e $(stamp)] no artifact files exist yet for: $msg"
    return 0
  fi
  for i in 1 2 3; do
    if ! git add -- "${files[@]}"; then
      echo "[r5e $(stamp)] git add failed (attempt $i), retrying"
      sleep 5
      continue
    fi
    if git diff --cached --quiet -- baselines_out 2>/dev/null; then
      echo "[r5e $(stamp)] nothing new to commit for: $msg"
      return 0
    fi
    if git commit -q -m "$msg" -- baselines_out; then
      echo "[r5e $(stamp)] committed: $msg"
      return 0
    fi
    echo "[r5e $(stamp)] git commit failed (attempt $i), retrying"
    sleep 5
  done
  echo "[r5e $(stamp)] WARNING: commit failed for: $msg (evidence still on disk)"
  return 0
}

tpu_up() {
  timeout -k 30 120 python - <<'EOF'
import sys, jax
try:
    d = jax.devices()
    sys.exit(0 if d and d[0].platform != "cpu" else 3)
except Exception:
    sys.exit(3)
EOF
}

others_running() {
  for s in chip_jobs_r5.sh chip_jobs_r5b.sh chip_jobs_r5c.sh chip_jobs_r5d.sh; do
    pgrep -f "bash tools/$s" > /dev/null 2>&1 && return 0
  done
  return 1
}

echo "[r5e $(stamp)] waiting for chains r5/r5b/r5c/r5d to finish"
while others_running; do
  sleep 60
done
echo "[r5e $(stamp)] predecessors gone; proceeding"

ABORT_PASS=0
FAILURES=0
rung() {
  local name="$1" msg="$2"; shift 2
  local marker="baselines_out/.r5e_${name}_done"
  if [ -f "$marker" ] || [ "$ABORT_PASS" = 1 ]; then
    return 0
  fi
  echo "[r5e $(stamp)] ===== rung $name: $* ====="
  local rc=0
  "$@" || rc=$?
  if [ "$rc" = 0 ]; then
    touch "$marker"
    commit_evidence "$msg"
  else
    echo "[r5e $(stamp)] rung $name FAILED (rc=$rc); probing tunnel"
    commit_evidence "$msg (partial: rung exited rc=$rc)"
    FAILURES=$((FAILURES + 1))
    if ! tpu_up; then
      echo "[r5e $(stamp)] tunnel down — aborting this pass, back to wait loop"
      ABORT_PASS=1
    fi
  fi
}

all_done() {
  for m in lm159_geomed lm159_shared lm159_shared_1k; do
    [ -f "baselines_out/.r5e_${m}_done" ] || return 1
  done
  return 0
}

for outer in 1 2; do
  echo "[r5e $(stamp)] ===== outer attempt $outer ====="
  if all_done; then break; fi
  tools/wait_tpu.sh 60 150 120 || { echo "[r5e $(stamp)] tunnel never came up this window"; continue; }
  FAILURES=0
  ABORT_PASS=0

  rung lm159_geomed "chip evidence: d~159M geomedian-only step (lightest compile)" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 1024 --batch-size 2 \
      --variants lm_geomedian_bf16 \
      --out baselines_out/tpu_lm_perf_159_geomed.json

  rung lm159_shared "chip evidence: d~159M cyclic-shared-only step, T=512" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 512 --batch-size 4 \
      --variants lm_cyclic_s1_shared_bf16 \
      --out baselines_out/tpu_lm_perf_159_shared.json

  rung lm159_shared_1k "chip evidence: d~159M cyclic-shared-only step, T=1024" \
    timeout -k 60 3600 python tools/tpu_lm_perf.py --steps 4 --reps 2 \
      --model-dim 1024 --model-heads 16 --model-layers 12 \
      --seq-len 1024 --batch-size 2 \
      --variants lm_cyclic_s1_shared_bf16 \
      --out baselines_out/tpu_lm_perf_159_shared_1k.json

  if all_done; then
    echo "[r5e $(stamp)] LAST-RESORT d159M COMPLETE"
    break
  fi
  echo "[r5e $(stamp)] incomplete ($FAILURES rung failures this pass); retrying"
  sleep 120
done
all_done && exit 0 || exit 1
