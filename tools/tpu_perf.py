#!/usr/bin/env python
"""Phase-level TPU performance evidence for the cyclic coded path.

Produces (default baselines_out/tpu_perf.json):

  * per-step wall-clock of the full cyclic train step vs the geo-median and
    Krum baseline steps and the plain (mode=normal) step — all as ONE jitted
    lax.scan each, fetch-synchronised (utils/timing.py protocol),
  * isolated encode / decode phase costs at the same (n, d) via chained
    in-jit loops — the TPU re-statement of the reference's per-phase timers
    (worker encode/comm counters src/worker/cyclic_worker.py:165-194, PS
    "method duration" src/master/baseline_master.py:145,276),
  * optionally (--trace) a jax.profiler trace of a few live steps for
    op-level inspection, saved under --trace-dir.

The decode-vs-geomedian ratio measured here is the paper's headline claim
(README.md:2) with both sides on the same chip and the same schedule.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def step_ms(cfg_kwargs, ds, mesh, steps=10, reps=2):
    """Scanned whole-train-step timing (same protocol as bench.run)."""
    import bench

    dt, loss, flops, _compile_s = bench.run(cfg_kwargs, ds, mesh, steps,
                                            warmup=1, reps=reps,
                                            want_flops=True)
    return dt * 1e3, flops


def phase_times(n, d, s, reps=20):
    """Isolated encode / decode costs at gradient dimension d.

    Timing and feedback discipline per draco_tpu.utils.timing.timeit_chained
    (non-linear full-output feedback, operands via consts)."""
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.coding import cyclic as cyc
    from draco_tpu.utils.timing import timeit_chained

    code = cyc.build_cyclic_code(n, s)
    r = np.random.RandomState(0)
    g = jnp.asarray(r.randn(n, d).astype(np.float32))
    rf = jnp.asarray(r.randn(d).astype(np.float32))

    def enc_step(gc):
        e_re, e_im = cyc.encode_shared(code, gc)
        return gc.at[0, 0].add(1e-30 * (jnp.sum(e_re**2) + jnp.sum(e_im**2)))

    enc_ms = timeit_chained(enc_step, g, reps=reps) * 1e3

    e_re, e_im = cyc.encode_shared(code, g)

    def dec_step(carry, rf):
        er, ei = carry
        dec, honest = cyc.decode(code, er, ei, rf)
        return (er.at[0, 0].add(1e-30 * jnp.sum(dec**2)), ei)

    dec_ms = timeit_chained(dec_step, (e_re, e_im), (rf,), reps=reps) * 1e3
    return enc_ms, dec_ms


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str, default="baselines_out/tpu_perf.json")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--network", type=str, default="ResNet18")
    ap.add_argument("--num-workers", type=int, default=8)
    ap.add_argument("--cpu-mesh", type=int, default=0)
    ap.add_argument("--trace", action="store_true",
                    help="also capture a jax.profiler trace of live steps")
    ap.add_argument("--trace-dir", type=str, default="baselines_out/trace")
    args = ap.parse_args(argv)

    from draco_tpu.cli import maybe_force_cpu_mesh

    maybe_force_cpu_mesh(args)

    import jax

    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh

    ds = load_dataset("Cifar10", data_dir="./data")
    mesh = make_mesh(args.num_workers)
    dev = jax.devices()[0]

    common = dict(
        network=args.network, dataset="Cifar10", batch_size=args.batch_size,
        lr=0.01, momentum=0.9, num_workers=args.num_workers, worker_fail=1,
        err_mode="rev_grad", max_steps=args.steps + 1, eval_freq=0,
        train_dir="", log_every=10**9,
    )

    report = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "network": args.network,
        "num_workers": args.num_workers,
        "batch_size_per_worker": args.batch_size,
        "steps_per_scan": args.steps,
    }

    variants = {
        # reference-parity semantics: every worker really computes 2s+1
        # redundant gradients (cyclic_worker.py:122-146)
        "cyclic_s1": dict(common, approach="cyclic", redundancy="simulate"),
        # TPU-native fast path: each batch gradient computed once, encode is
        # algebraically identical (coding/cyclic.py encode_shared) — the
        # r×-compute redundancy was only ever needed because the reference's
        # workers are mutually untrusting processes; in SPMD the adversary
        # model is simulated, so the framework can deliver the same decode
        # semantics at 1/r the FLOPs
        "cyclic_s1_shared": dict(common, approach="cyclic", redundancy="shared"),
        "cyclic_s1_bf16": dict(common, approach="cyclic", redundancy="simulate",
                               compute_dtype="bfloat16"),
        "geomedian": dict(common, approach="baseline", mode="geometric_median"),
        "krum": dict(common, approach="baseline", mode="krum"),
        "mean_no_attack": dict(common, approach="baseline", mode="normal",
                               worker_fail=0),
    }
    for name, kw in variants.items():
        print(f"[tpu_perf] measuring {name} ...", file=sys.stderr, flush=True)
        t_var = time.time()
        ms, flops = step_ms(kw, ds, mesh, steps=args.steps)
        print(f"[tpu_perf] {name}: {ms:.3f} ms/step ({time.time()-t_var:.0f}s)",
              file=sys.stderr, flush=True)
        report[f"{name}_step_ms"] = round(ms, 3)
        if flops:
            report[f"{name}_flops_per_step"] = flops
    report["decode_vs_geomedian_speedup"] = round(
        report["geomedian_step_ms"] / report["cyclic_s1_step_ms"], 3
    )

    # isolated phases at this model's gradient dimension
    from draco_tpu.config import TrainConfig
    from draco_tpu.training.step import build_train_setup

    setup = build_train_setup(
        TrainConfig(**variants["cyclic_s1"]), mesh, dataset_name=ds.name
    )
    d = setup.dim
    print(f"[tpu_perf] isolated encode/decode phases at d={d} ...",
          file=sys.stderr, flush=True)
    enc_ms, dec_ms = phase_times(args.num_workers, d, s=1)
    report["grad_dim"] = d
    report["encode_only_ms"] = round(enc_ms, 3)
    report["decode_only_ms"] = round(dec_ms, 3)

    if args.trace:
        from draco_tpu.training.trainer import Trainer

        os.makedirs(args.trace_dir, exist_ok=True)
        tr = Trainer(TrainConfig(**variants["cyclic_s1"]), mesh=mesh,
                     dataset=ds, quiet=True)
        try:
            tr.run(max_steps=min(args.steps, 6), profile_dir=args.trace_dir,
                   profile_steps=(2, 5))
            report["trace_dir"] = args.trace_dir
        except Exception as e:  # tracing may be unsupported on remote backends
            report["trace_error"] = repr(e)[:300]
        finally:
            tr.close()

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=1)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
