#!/usr/bin/env python
"""Straggler study: the time-to-accuracy crossover between the exact
cyclic code (r = 2s+1) and the approximate family (r ≈ 1.5) under 0-37.5%
per-step worker drop rates — ISSUE 8's committed evidence that the
speed/exactness dial (ROADMAP item 3) actually pays.

Each cell trains the same FC/synthetic-mnist workload on the production
chunked Trainer loop (steps_per_call=4, guards on) under e seeded drops
per step and records, from the run's own metrics.jsonl:

  steps_to_target     first step whose 5-step smoothed train loss reaches
                      --target-loss (deterministic on a fixed backend: the
                      schedules, data and decode are all seeded)
  compute_to_target   steps_to_target x round(r*n) worker batch-gradients —
                      the metric a REAL fleet pays. The simulated mesh
                      computes shared-redundancy rows once either way
                      (config.redundancy), so wall ms/step here does not
                      show the r x compute gap; the per-worker load does:
                      cyclic r = 2s+1 = 3 vs approx r = 1.5. This is the
                      crossover axis.
  residual_within_bound   every record's measured decode_residual sat
                      under its analytic decode_residual_bound (approx
                      rows; trivially true for the exact decode at f32
                      noise) — the paper's guarantee refereed per step
  recovered_fraction_min  worst-step batch coverage (approx rows)
  ms_per_step         measured host wall per step (t_fetch + t_comp means)

The exact code's cells go infeasible past its erasure budget (e > 2s,
config.validate) — recorded as feasible=false rather than skipped,
because "this scenario is CLOSED to exact codes" is the point of the
study. ``tools/perf_watch.py`` folds the committed artifact: the bool
columns (reached_target, residual_within_bound, full recovery) gate at
tolerance 0, wall metrics at the time tolerance.

Usage (CPU, ~1.5 min):
  python tools/straggler_study.py --cpu-mesh 8
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from draco_tpu.cli import maybe_force_cpu_mesh  # noqa: E402

NUM_WORKERS = 8
# exact family: cyclic s=1 (r = 3), pure-straggler regime (no live
# adversary) — erasure budget e <= 2s = 2; approx family at the ISSUE 8
# design point r=1.5, dimensioned for ceil(0.4*8) = 4 drops
FAMILIES = {
    "cyclic": dict(approach="cyclic", worker_fail=1, adversary_count=0,
                   redundancy="shared"),
    "approx": dict(approach="approx", worker_fail=0, redundancy="shared",
                   code_redundancy=1.5, straggler_alpha=0.4),
}
REDUNDANCY = {"cyclic": 3.0, "approx": 1.5}
DROP_COUNTS = (0, 1, 2, 3)  # of n=8: 0% / 12.5% / 25% / 37.5% per step


def _feasible(family: str, drops: int) -> bool:
    # the cyclic erasure-only budget is e <= 2s (config.validate) —
    # derived from the family's own s so the two stay in lockstep; the
    # approx design point covers every swept drop count
    return (family != "cyclic"
            or drops <= 2 * FAMILIES["cyclic"]["worker_fail"])


def run_cell(family: str, drops: int, args, mesh, ds) -> dict:
    import numpy as np

    from draco_tpu.config import TrainConfig
    from draco_tpu.training.trainer import Trainer

    row = {"family": family, "drop_count": drops,
           "drop_rate": drops / NUM_WORKERS,
           "code_redundancy": REDUNDANCY[family],
           "feasible": _feasible(family, drops)}
    if not row["feasible"]:
        row["detail"] = (f"cyclic erasure budget exceeded: e={drops} > "
                         f"2s=2 — the scenario the approx family opens")
        return row
    d = tempfile.mkdtemp(prefix=f"straggler_{family}_{drops}_")
    cfg = TrainConfig(
        network="FC", dataset="synthetic-mnist", batch_size=4, lr=0.05,
        momentum=0.9, num_workers=NUM_WORKERS, max_steps=args.max_steps,
        eval_freq=0, train_dir=d, log_every=1,
        steps_per_call=args.steps_per_call, step_guard="on",
        straggle_mode="drop" if drops else "none", straggle_count=drops,
        **FAMILIES[family],
    )
    tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
    try:
        t0 = time.perf_counter()
        tr.run()
        wall_s = time.perf_counter() - t0
        ev = tr.evaluate(args.max_steps)
    finally:
        tr.close()
    recs = []
    with open(os.path.join(d, "metrics.jsonl")) as fh:
        for line in fh:
            r = json.loads(line)
            if "loss" in r and r.get("split") != "eval":
                recs.append(r)
    shutil.rmtree(d, ignore_errors=True)

    losses = [r["loss"] for r in recs]
    smooth = [float(np.mean(losses[max(0, i - 4):i + 1]))
              for i in range(len(losses))]
    steps_to = next((i + 1 for i, v in enumerate(smooth)
                     if v <= args.target_loss), None)
    within = all(
        r["decode_residual"] <= r["decode_residual_bound"] + 1e-5
        for r in recs if "decode_residual_bound" in r
    ) if family == "approx" else all(
        r["decode_residual"] <= 1e-3 for r in recs  # exact decode: f32 noise
    )
    row.update({
        "steps": len(recs),
        "steps_to_target": steps_to,
        "reached_target": steps_to is not None,
        # the fleet-compute axis: worker batch-gradients spent to target
        "compute_to_target": (steps_to * round(REDUNDANCY[family]
                                               * NUM_WORKERS)
                              if steps_to is not None else None),
        "final_loss_smoothed": round(smooth[-1], 6),
        "prec1_test": ev["prec1_test"],
        "residual_within_bound": bool(within),
        "guard_trips_total": sum(r.get("guard_trips", 0.0) for r in recs),
        "wall_s": round(wall_s, 3),
        "ms_per_step": round(1000.0 * np.mean(
            [r.get("t_fetch", 0.0) + r.get("t_comp", 0.0) for r in recs]), 3),
    })
    if family == "approx":
        row["recovered_fraction_min"] = min(
            r["recovered_fraction"] for r in recs)
        row["residual_max"] = round(max(r["decode_residual"]
                                        for r in recs), 6)
        row["bound_max"] = round(max(r["decode_residual_bound"]
                                     for r in recs), 6)
    row["ok"] = bool(row["reached_target"] and row["residual_within_bound"]
                     and row["guard_trips_total"] == 0.0)
    return row


def crossover(rows) -> dict:
    """Per drop count: which family reached the target loss on less fleet
    compute (worker batch-gradients) — 'approx' winning under drops while
    'cyclic' goes infeasible past its budget is the study's headline."""
    out = {}
    for drops in sorted({r["drop_count"] for r in rows}):
        cell = {r["family"]: r for r in rows if r["drop_count"] == drops}
        live = {f: r["compute_to_target"] for f, r in cell.items()
                if r.get("compute_to_target") is not None}
        if not live:
            out[str(drops)] = None
        elif len(live) == 1:
            # name WHY the other families are out: budget-infeasible is
            # the study's headline, merely-not-converged is not, and a
            # partial sweep (--families) proves nothing about the rest
            winner = next(iter(live))
            others = [r for f, r in cell.items() if f != winner]
            if not others:
                out[str(drops)] = f"{winner} (only family swept)"
            elif all(not r.get("feasible", True) for r in others):
                out[str(drops)] = f"{winner} (only feasible)"
            else:
                out[str(drops)] = f"{winner} (only to reach target)"
        else:
            out[str(drops)] = min(live, key=live.get)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", type=str,
                    default=os.path.join("baselines_out",
                                         "straggler_study.json"))
    ap.add_argument("--max-steps", type=int, default=60)
    ap.add_argument("--steps-per-call", type=int, default=4)
    ap.add_argument("--target-loss", type=float, default=1.6,
                    help="5-step smoothed train-loss target (calibrated "
                         "for the 60-step FC/synthetic-mnist cell)")
    ap.add_argument("--families", type=str, default="",
                    help="comma-separated subset (default: all)")
    ap.add_argument("--drops", type=str, default="",
                    help="comma-separated drop counts (default: 0,1,2,3)")
    ap.add_argument("--cpu-mesh", type=int, default=0, metavar="N",
                    help="force an N-device virtual CPU mesh")
    args = ap.parse_args(argv)
    if args.cpu_mesh:
        maybe_force_cpu_mesh(args)

    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh

    families = [f for f in args.families.split(",") if f] or list(FAMILIES)
    drops = ([int(x) for x in args.drops.split(",") if x != ""]
             or list(DROP_COUNTS))
    ds = load_dataset("synthetic-mnist", synthetic_train=512,
                      synthetic_test=128)
    mesh = make_mesh(NUM_WORKERS)
    rows = []
    for e in drops:
        for family in families:
            row = run_cell(family, e, args, mesh, ds)
            rows.append(row)
            tag = ("infeasible" if not row["feasible"] else
                   f"steps_to_target={row['steps_to_target']} "
                   f"compute={row['compute_to_target']} "
                   f"ok={row['ok']}")
            print(f"straggler_study: {family:6s} e={e} -> {tag}", flush=True)

    payload = {
        "schema": 1,
        "tool": "tools/straggler_study.py",
        "num_workers": NUM_WORKERS,
        "max_steps": args.max_steps,
        "steps_per_call": args.steps_per_call,
        "target_loss": args.target_loss,
        "rows": rows,
        "crossover": crossover(rows),
        "all_ok": all(r["ok"] for r in rows if r["feasible"])
        and bool(rows),
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    print(f"straggler_study: {len(rows)} cells -> {args.out} "
          f"(crossover: {payload['crossover']})")
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
