#!/usr/bin/env python
"""Microbench of the fused decode kernels against the historical XLA
decode across (n, s, d) rungs — the committed evidence behind ISSUE 12's
"decode got faster" claim, and the perf_watch gate that keeps it true.

Each rung times ONE decode call (the whole coded decode: projection →
locator chain → recombination for cyclic, weight solve → masked combine →
residual-vs-bound health for approx) under two ``decode_impl`` lowerings:

  xla     the historical path, bit-for-bit what the K∈{1,4} bitwise
          suites pin
  pallas  the fused path — the hand-tiled Pallas kernels on a TPU
          backend; on other backends their reference lowering (the same
          fused algorithm through XLA, ops/decode_kernels
          .resolve_decode_impl), which is what this container measures
          (recorded per rung as ``pallas_lowering``)

Methodology: both impls jitted and warmed, then timed in INTERLEAVED
rounds (impl A chunk, impl B chunk, repeat) so host-load drift hits both
equally; per impl the minimum round mean is recorded (the same
minimum-of-chunks discipline as tools/host_loop_overhead.py). Outputs are
block_until_ready'd per chunk.

Gating (tools/perf_watch.py): every rung's ``pallas_over_xla`` ratio rides
at the time tolerance, and rungs marked ``gate: true`` additionally pin
``kernel_not_slower`` (ratio ≤ 1) at tolerance 0 — the fused path
regressing below the XLA path at a committed rung fails the round
(flipped-row tests in tests/test_cli_tools.py prove the gate live). Two
cyclic rung classes are deliberately ungated on CPU fallbacks (PERF.md
§14): the GLOBAL rungs — two near-memory-floor (n, d) matvec passes with
the locator at ~3% of them, nothing for the CPU fallback to win — and the
n=32 LAYER rung, where the per-segment matvec cost dominates both impls
identically (measured ratio ≈ 1.01) and the locator fusion's win
disappears into it. The n=8 layer rung (the device-profile cell shape)
and both approx rungs are where the fused path must and does win on this
backend too; the kernels' TPU-side win (HBM round-trips removed) is what
the ungated rungs exist to measure once a chip round runs this tool.

  python tools/decode_kernel_bench.py [--out baselines_out/decode_kernel_bench.json]
      [--reps 6] [--inner 4] [--rungs cyclic_layer_n8, ...]
  python tools/decode_kernel_bench.py --check   # jax-free artifact check
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ARTIFACT_REL = os.path.join("baselines_out", "decode_kernel_bench.json")

# name -> rung spec. ``gate``: pin kernel_not_slower (ratio <= 1) at tol 0
# in perf_watch — only set where the fused path wins on EVERY backend
# (see module docstring). n=32 s=3 is the wire-study noise-amplification
# shape ROADMAP item 3 tracks; d≈0.4M is the linter-CI LM gradient size.
RUNGS = {
    "cyclic_global_n8": dict(family="cyclic", n=8, s=1, d=400_000,
                             granularity="global", layers=0, gate=False),
    "cyclic_global_n32s3": dict(family="cyclic", n=32, s=3, d=400_000,
                                granularity="global", layers=0, gate=False),
    "cyclic_layer_n8": dict(family="cyclic", n=8, s=1, d=400_000,
                            granularity="layer", layers=10, gate=True),
    "cyclic_layer_n32s3": dict(family="cyclic", n=32, s=3, d=400_000,
                               granularity="layer", layers=10, gate=False),
    "approx_n8": dict(family="approx", n=8, r=1.5, d=400_000, gate=True),
    "approx_n32": dict(family="approx", n=32, r=1.5, d=400_000, gate=True),
}


def _build_cyclic(spec):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.attacks import inject_cyclic
    from draco_tpu.coding import cyclic as cyclic_mod

    n, s, d = spec["n"], spec["s"], spec["d"]
    code = cyclic_mod.build_cyclic_code(n, s)
    rng = np.random.RandomState(0)
    bg = rng.randn(n, d).astype(np.float32)
    enc_re, enc_im = cyclic_mod.encode_shared(code, jnp.asarray(bg))
    adv = np.zeros(n, bool)
    adv[rng.choice(n, size=s, replace=False)] = True
    enc_re, enc_im = inject_cyclic(enc_re, enc_im, jnp.asarray(adv),
                                   "rev_grad")
    rf = jnp.asarray(rng.normal(loc=1.0, size=d).astype(np.float32))
    if spec["granularity"] == "layer":
        offs = tuple(int(x) for x in
                     np.linspace(0, d, spec["layers"] + 1).astype(int))

        def fn(impl):
            return jax.jit(lambda a, b: cyclic_mod.decode_layers(
                code, a, b, rf, offs, with_health=True, impl=impl))
    else:
        def fn(impl):
            return jax.jit(lambda a, b: cyclic_mod.decode(
                code, a, b, rf, with_health=True, impl=impl))

    return fn, (enc_re, enc_im)


def _build_approx(spec):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from draco_tpu.coding import approx as approx_mod

    n, d = spec["n"], spec["d"]
    code = approx_mod.build_approx_code(n, spec["r"])
    rng = np.random.RandomState(0)
    bg = jnp.asarray(rng.randn(n, d).astype(np.float32))
    rows = approx_mod.encode_shared(code, bg)
    pres = jnp.asarray(np.ones(n, bool))

    def fn(impl):
        return jax.jit(lambda r, g: approx_mod.decode(
            code, r, present=pres, with_health=True, batch_grads=g,
            impl=impl))

    return fn, (rows, bg)


def _time_interleaved(fns, args, reps, inner):
    """Per-impl minimum round mean (ms) over interleaved rounds."""
    import jax

    for f in fns:  # compile + warm
        jax.block_until_ready(f(*args))
    mins = [float("inf")] * len(fns)
    for _ in range(reps):
        for i, f in enumerate(fns):
            t0 = time.perf_counter()
            for _ in range(inner):
                out = f(*args)
            jax.block_until_ready(out)
            mins[i] = min(mins[i],
                          (time.perf_counter() - t0) / inner * 1e3)
    return mins


def run(args) -> dict:
    from draco_tpu.ops.decode_kernels import resolve_decode_impl, use_pallas

    on_tpu = use_pallas()
    pallas_impl = resolve_decode_impl("pallas")
    rows = []
    names = ([r.strip() for r in args.rungs.split(",") if r.strip()]
             or list(RUNGS))
    unknown = [r for r in names if r not in RUNGS]
    if unknown:
        raise SystemExit(f"unknown rungs {unknown}; known: {list(RUNGS)}")
    for name in names:
        spec = RUNGS[name]
        build = _build_cyclic if spec["family"] == "cyclic" else _build_approx
        fn, data = build(spec)
        xla_ms, pallas_ms = _time_interleaved(
            [fn("xla"), fn(pallas_impl)], data, args.reps, args.inner)
        ratio = pallas_ms / xla_ms
        row = {"rung": name, **{k: v for k, v in spec.items()},
               "xla_ms": round(xla_ms, 3), "pallas_ms": round(pallas_ms, 3),
               "pallas_over_xla": round(ratio, 4),
               "pallas_lowering": "kernel" if on_tpu else "fused_xla"}
        if spec["gate"]:
            row["kernel_not_slower"] = bool(ratio <= 1.0)
        rows.append(row)
        print(f"decode_kernel_bench: {name}: xla {xla_ms:.2f} ms, "
              f"pallas({row['pallas_lowering']}) {pallas_ms:.2f} ms "
              f"(ratio {ratio:.3f})", flush=True)
    return {
        "schema": 1,
        "tool": "tools/decode_kernel_bench.py",
        "method": ("interleaved min-of-round-means over jitted whole-decode "
                   "calls, both impls warmed; pallas rows record which "
                   "lowering actually ran (kernel on TPU backends, the "
                   "fused reference through XLA elsewhere)"),
        "backend_pallas": on_tpu,
        "reps": args.reps, "inner": args.inner,
        "all_ok": all(r.get("kernel_not_slower", True) for r in rows),
        "rows": rows,
    }


def check_artifact(path, out=None) -> int:
    """jax-free self-check of the committed artifact: ratio arithmetic,
    gated rungs not slower, roll-up consistent. Exit 1 naming each
    violation (CI gate; tests/test_cli_tools.py drives a flipped row)."""
    out = out if out is not None else sys.stdout
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"decode_kernel_bench --check: cannot read {path}: {e}",
              file=out)
        return 1
    bad = []
    for row in data.get("rows", []):
        name = row.get("rung")
        xla, pal = row.get("xla_ms"), row.get("pallas_ms")
        ratio = row.get("pallas_over_xla")
        if not (isinstance(xla, (int, float)) and xla > 0
                and isinstance(pal, (int, float)) and pal > 0):
            bad.append(f"{name}: missing/non-positive timings")
            continue
        if not isinstance(ratio, (int, float)):
            bad.append(f"{name}: missing/non-numeric pallas_over_xla")
            continue
        if abs(ratio - pal / xla) > 0.01:
            bad.append(f"{name}: ratio {ratio} != pallas_ms/xla_ms "
                       f"{pal / xla:.4f}")
        if row.get("gate"):
            if "kernel_not_slower" not in row:
                bad.append(f"{name}: gated rung missing kernel_not_slower")
            elif bool(row["kernel_not_slower"]) != (ratio <= 1.0):
                bad.append(f"{name}: kernel_not_slower inconsistent with "
                           f"ratio {ratio}")
            elif not row["kernel_not_slower"]:
                bad.append(f"{name}: fused decode slower than XLA at a "
                           f"gated rung (ratio {ratio})")
    if not data.get("rows"):
        bad.append("no rows")
    if bool(data.get("all_ok")) != all(
            r.get("kernel_not_slower", True) for r in data.get("rows", [])):
        bad.append("all_ok inconsistent with rows")
    if bad:
        for b in bad:
            print(f"decode_kernel_bench FAIL: {b}", file=out)
        return 1
    print(f"decode_kernel_bench --check: {len(data['rows'])} rungs "
          f"consistent", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=ARTIFACT_REL)
    ap.add_argument("--reps", type=int, default=6)
    ap.add_argument("--inner", type=int, default=4)
    ap.add_argument("--rungs", default="",
                    help="comma-separated subset (default: all)")
    ap.add_argument("--check", action="store_true",
                    help="jax-free self-check of the committed artifact")
    ap.add_argument("--artifact", default="",
                    help=f"artifact path for --check (default {ARTIFACT_REL})")
    args = ap.parse_args(argv)
    if args.check:
        return check_artifact(args.artifact or args.out)
    payload = run(args)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    print(f"decode_kernel_bench: {len(payload['rows'])} rungs -> {args.out}")
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
