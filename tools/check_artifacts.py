#!/usr/bin/env python
"""Re-verify every committed ``baselines_out/`` artifact in one jax-free
command — ISSUE 10's "is the evidence still true?" button.

The repo's committed artifacts are load-bearing: perf_watch gates rounds
against them, tests assert they cover the registry, and PERF.md quotes
their numbers. Each artifact already has its own verifier; this tool runs
ALL of them (plus schema smokes of the jax-free report tools against
synthesized inputs, so a report-tool regression surfaces here too) and
exits nonzero NAMING THE FIRST FAILURE:

  perf_watch          diff current artifacts vs the committed snapshot
  device_profile      --check: sums/cross-check/control of the committed
                      device-time ledger
  wire_study          --check: ledger arithmetic + bf16 detection pins of
                      the committed shadow-wire matrix, plus (ISSUE 15)
                      the real-wire rows' P/R + physical-bytes pins and
                      the n=32 s=3 regularized-locator certificate
  decode_kernel_bench --check: ratio arithmetic + gated-rung
                      kernel-not-slower pins of the committed fused-decode
                      microbench (ISSUE 12)
  segment_study       --check: per-segment bytes sums + bounds algebra and
                      the overlap/ms-per-step-win acceptance pins of the
                      committed streaming-wire evidence (ISSUE 16)
  tree_study          --check: plan algebra + per-level byte sums +
                      detection-parity pins + crossover honesty of the
                      committed tree-aggregation evidence (ISSUE 17)
  decode_study        --check: no stale error rows, numeric granularity
                      cells, tree crossover columns self-consistent
                      (ISSUE 17)
  program_lint        committed all_ok roll-up
  sharding audit      every non-control lint row carries ok verdicts for
                      sharding_contract / collective_axes /
                      replication_leaks and the auditor's five live
                      controls are present and tripped (ISSUE 18)
  lint config         ruff.toml / pyproject.toml exists and pins the
                      repo's line-length (declarative; no ruff binary in
                      the image)
  chaos_matrix        committed all_ok roll-up
  straggler_study     committed all_ok roll-up
  chaos incident      every committed chaos cell carries an ``incident``
      coverage        verdict with ok true (expected type raised +
                      attributed, nothing spurious — ISSUE 13)
  trace_report smoke  folds a synthesized trace.json + metrics.jsonl +
                      schema-current status.json (incl. the ``incidents``
                      block) without error
  forensics_report    folds a synthesized packed-mask metrics.jsonl and
      smoke           reproduces the expected per-worker fold
  incident_report     live engine over a synthesized trust collapse →
      smoke           incidents.jsonl; the jax-free replay must reproduce
                      the ledger exactly, torn tail tolerated

Pure artifact folding — runs on a laptop against an scp'd checkout, no
accelerator stack. Wired into tests/test_cli_tools.py.

Usage:
  python tools/check_artifacts.py [--root .]
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _flag_check(relpath, flag="all_ok"):
    def check(root):
        path = os.path.join(root, relpath)
        try:
            with open(path) as fh:
                data = json.load(fh)
        except (OSError, ValueError) as e:
            return f"cannot read {relpath}: {e}"
        if not data.get(flag):
            return f"{relpath}: {flag} is false"
        return None
    return check


def _check_perf_watch(root):
    from tools import perf_watch

    rc = perf_watch.main(["--root", root])
    return None if rc == 0 else f"perf_watch exited {rc}"


def _check_device_profile(root):
    from tools import device_profile

    artifact = os.path.join(root, "baselines_out", "device_profile.json")
    rc = device_profile.main(["--check", "--artifact", artifact])
    return None if rc == 0 else f"device_profile --check exited {rc}"


def _check_wire_study(root):
    from tools import wire_study

    artifact = os.path.join(root, "baselines_out", "wire_study.json")
    rc = wire_study.main(["--check", "--artifact", artifact])
    return None if rc == 0 else f"wire_study --check exited {rc}"


def _check_segment_study(root):
    from tools import segment_study

    artifact = os.path.join(root, "baselines_out", "segment_study.json")
    rc = segment_study.main(["--check", "--artifact", artifact])
    return None if rc == 0 else f"segment_study --check exited {rc}"


def _check_decode_bench(root):
    from tools import decode_kernel_bench

    artifact = os.path.join(root, "baselines_out",
                            "decode_kernel_bench.json")
    rc = decode_kernel_bench.main(["--check", "--artifact", artifact])
    return None if rc == 0 else f"decode_kernel_bench --check exited {rc}"


def _check_trace_report(root):
    """Schema smoke: the jax-free report must fold a minimal-but-current
    run dir (trace + metrics + a STATUS_SCHEMA-versioned status.json) —
    a schema bump that forgot trace_report trips here, jax-free."""
    from draco_tpu.obs.heartbeat import STATUS_SCHEMA
    from tools import trace_report

    with tempfile.TemporaryDirectory(prefix="check_trace_") as d:
        events = [
            {"name": "dispatch", "ph": "X", "ts": 0.0, "dur": 5000.0,
             "pid": 1, "tid": 1},
            {"name": "flush", "ph": "X", "ts": 5000.0, "dur": 1000.0,
             "pid": 1, "tid": 1},
        ]
        with open(os.path.join(d, "trace.json"), "w") as fh:
            json.dump({"traceEvents": events}, fh)
        with open(os.path.join(d, "metrics.jsonl"), "w") as fh:
            fh.write(json.dumps({"step": 1, "loss": 1.0, "t_comp": 0.01})
                     + "\n")
        status = {"schema": STATUS_SCHEMA, "state": "done", "step": 1,
                  "updated_at": 0.0,
                  "wire": {"family": "cyclic", "dim": 10,
                           "bytes_per_worker": {"f32": 80, "bf16": 40,
                                                "int8": 14}},
                  "numerics": {"nx_wire_absmax": 1.0,
                               "shadow_err_max": 0.001,
                               "shadow_flag_agree_min": 1.0},
                  "incidents": {"total": 1, "open": [],
                                "by_type": {"guard": 1},
                                "last": {"type": "guard", "severity":
                                         "critical", "onset_step": 1,
                                         "workers": [2], "open": False}}}
        with open(os.path.join(d, "status.json"), "w") as fh:
            json.dump(status, fh)
        rc = trace_report.main([d])
        return None if rc == 0 else f"trace_report smoke exited {rc}"


def _check_forensics_report(root):
    from tools import forensics_report

    with tempfile.TemporaryDirectory(prefix="check_fx_") as d:
        rec = {"step": 1, "loss": 1.0, "wmask_accused0": 0b0100,
               "wmask_present0": 0b1111, "wmask_adv0": 0b0100}
        with open(os.path.join(d, "metrics.jsonl"), "w") as fh:
            fh.write(json.dumps(rec) + "\n")
        rc = forensics_report.main([d, "--num-workers", "4"])
        if rc != 0:
            return f"forensics_report smoke exited {rc}"
        rep = json.load(open(os.path.join(d, "forensics.json")))
        if rep["workers"][2]["accused"] != 1 \
                or rep["workers"][2]["tp"] != 1:
            return "forensics_report smoke: fold did not attribute w2"
        return None


def _check_chaos_incidents(root):
    """ISSUE 13: every committed chaos cell must carry an ``incident``
    verdict with ok true (the expected incident type raised, attributed,
    nothing spurious) — a matrix regenerated without the incident watch,
    or with a blind detector, trips here jax-free."""
    path = os.path.join(root, "baselines_out", "chaos_matrix.json")
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        return f"cannot read chaos_matrix.json: {e}"
    rows = data.get("rows") or []
    if not rows:
        return "chaos_matrix.json has no rows"
    for row in rows:
        verdict = row.get("incident")
        if not isinstance(verdict, dict):
            return (f"cell ({row.get('loop')}, {row.get('fault')}) carries "
                    f"no incident verdict — regenerate the matrix with "
                    f"tools/chaos_run.py (incident_watch is on in every "
                    f"cell)")
        if not verdict.get("ok"):
            return (f"cell ({row.get('loop')}, {row.get('fault')}) incident "
                    f"verdict failed: {verdict.get('detail', verdict)}")
    return None


def _check_incident_report(root):
    """Schema smoke: the live engine writes incidents.jsonl over a
    synthesized trust-collapse stream, and the jax-free replay
    (tools/incident_report.py) must reproduce the ledger EXACTLY — then a
    torn tail line must be tolerated. One engine implementation for the
    live fold and the replay, so a divergence here is a real defect."""
    from draco_tpu.obs import incidents as incidents_mod
    from tools import incident_report

    with tempfile.TemporaryDirectory(prefix="check_inc_") as d:
        recs = []
        for step in range(1, 11):
            accused = 0b0100 if step <= 6 else 0
            recs.append({"step": step, "loss": 1.0,
                         "wmask_accused0": accused,
                         "wmask_present0": 0b1111,
                         "wmask_adv0": accused})
        with open(os.path.join(d, "metrics.jsonl"), "w") as fh:
            fh.write("\n".join(json.dumps(r) for r in recs) + "\n")
        engine = incidents_mod.IncidentEngine(
            num_workers=4, out_path=os.path.join(d, "incidents.jsonl"))
        for r in recs:
            engine.observe(r)
        engine.finalize()
        if engine.total_onsets != 1:
            return (f"synthesized trust collapse raised "
                    f"{engine.total_onsets} incidents, expected 1")
        rc = incident_report.main([d, "--num-workers", "4"])
        if rc != 0:
            return f"incident_report replay diverged (exit {rc})"
        rep = json.load(open(os.path.join(d, "incidents_report.json")))
        if not rep["diff"]["match"]:
            return f"incident_report diff mismatch: {rep['diff']}"
        if rep["replayed"][0]["type"] != "trust" \
                or rep["replayed"][0]["workers"] != [2]:
            return f"replay mis-attributed: {rep['replayed'][0]}"
        # torn tail: killed mid-write must not take the report down
        with open(os.path.join(d, "incidents.jsonl"), "a") as fh:
            fh.write('{"v": 1, "event": "ons')
        rc = incident_report.main([d, "--num-workers", "4"])
        return None if rc == 0 else f"torn-tail replay exited {rc}"


def _check_autopilot_study(root):
    """ISSUE 14: the committed scenario artifact must certify the
    autopilot beating every fixed configuration on compute-to-target
    (with at least one fixed row recorded infeasible — the scenario must
    actually close a family out), every remediation attributed to its
    triggering incident, and the quarantine never corrupting the
    aggregate."""
    path = os.path.join(root, "baselines_out", "autopilot_study.json")
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        return f"cannot read autopilot_study.json: {e}"
    if not data.get("autopilot_beats_fixed"):
        return ("autopilot_beats_fixed is false — the adaptive dial lost "
                "to a fixed configuration")
    if not data.get("infeasible_fixed"):
        return ("no fixed configuration was infeasible — the scenario no "
                "longer exercises the certificate boundary")
    rows = {r.get("cell"): r for r in data.get("rows") or []}
    ap_row = rows.get("autopilot")
    if not isinstance(ap_row, dict):
        return "no autopilot row in the artifact"
    for flag in ("remediations_attributed", "dialed_down",
                 "quarantine_clean", "ok"):
        if not ap_row.get(flag):
            return f"autopilot row: {flag} is false"
    for rem in ap_row.get("remediations") or []:
        if not rem.get("trigger") or rem.get("trigger_onset") is None:
            return f"unattributed remediation in artifact: {rem}"
    if not data.get("all_ok"):
        return "autopilot_study.json: all_ok is false"
    return None


def _check_sharding_audit(root):
    """The static sharding audit (rules 7-9) must actually be IN the
    committed lint artifact: every non-control program row carries
    sharding_contract / collective_axes / replication_leaks verdicts with
    ok true, and the auditor's live negative controls are present and
    tripped. An artifact regenerated from a stale checkout (six-rule
    linter) or with blunted controls fails here, jax-free."""
    path = os.path.join(root, "baselines_out", "program_lint.json")
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        return f"cannot read program_lint.json: {e}"
    new_rules = ("sharding_contract", "collective_axes",
                 "replication_leaks")
    missing = [r for r in new_rules if r not in (data.get("rules") or [])]
    if missing:
        return (f"artifact rule list lacks {missing} — regenerate with "
                f"tools/program_lint.py")
    controls = {}
    for row in data.get("rows") or []:
        name = row.get("name")
        if row.get("control"):
            controls[name] = row
            continue
        rules = row.get("rules") or {}
        for rn in new_rules:
            verdict = rules.get(rn)
            if not isinstance(verdict, dict):
                return (f"program row {name!r} carries no {rn} verdict — "
                        f"stale artifact, regenerate")
            if not verdict.get("ok"):
                return (f"program row {name!r} fails {rn}: "
                        f"{verdict.get('error', verdict)}")
    expected_controls = {
        "control_resharded_carry": "sharding_contract",
        "control_unnormalized_spec": "sharding_contract",
        "control_unmatched_param": "sharding_contract",
        "control_wrong_axis_psum": "collective_axes",
        "control_replicated_wire": "replication_leaks",
    }
    for cname, rule in expected_controls.items():
        row = controls.get(cname)
        if row is None:
            return (f"sharding-audit control {cname!r} missing from the "
                    f"artifact")
        if row.get("expected_fail") != rule or not row.get("ok"):
            return (f"control {cname!r} must trip exactly [{rule}] "
                    f"(expected_fail={row.get('expected_fail')}, "
                    f"ok={row.get('ok')})")
    return None


def _check_lint_config(root):
    """Satellite of the static-auditor PR: the repo-wide lint config must
    exist and pin the 79-column limit the codebase is written to (a text
    presence check — the image has no ruff binary and py3.10 has no
    tomllib, so this is deliberately declarative)."""
    for rel in ("ruff.toml", "pyproject.toml"):
        path = os.path.join(root, rel)
        if os.path.exists(path):
            try:
                with open(path) as fh:
                    text = fh.read()
            except OSError as e:
                return f"cannot read {rel}: {e}"
            if "line-length" not in text:
                return f"{rel} exists but pins no line-length"
            return None
    return "no ruff.toml / pyproject.toml lint config at the repo root"


def _check_tree_study(root):
    from tools import tree_study

    artifact = os.path.join(root, "baselines_out", "tree_study.json")
    rc = tree_study.check_artifact(artifact)
    return None if rc == 0 else f"tree_study --check exited {rc}"


def _check_decode_study(root):
    from tools import decode_study

    artifact = os.path.join(root, "baselines_out", "decode_study.json")
    rc = decode_study.check_artifact(artifact)
    return None if rc == 0 else f"decode_study --check exited {rc}"


def _check_fleet_slo(root):
    """ISSUE 19: re-verify the committed fleet SLO matrix — every cell's
    acceptance bools recomputed from the cell's own SLO results (clean
    cells burned zero deterministic budget, adversary cells held P/R 1.0
    on live adversaries, remediated cells carry a finite attributed
    MTTR), both production loops covered, and a stale status schema
    REFUSED rather than silently re-blessed."""
    from tools import fleet_study

    path = os.path.join(root, "baselines_out", "fleet_slo.json")
    try:
        with open(path) as fh:
            payload = json.load(fh)
    except (OSError, ValueError) as e:
        return f"cannot read baselines_out/fleet_slo.json: {e}"
    problems = fleet_study.verify_payload(payload)
    return problems[0] if problems else None


CHECKS = (
    ("perf_watch", _check_perf_watch),
    ("device_profile --check", _check_device_profile),
    ("wire_study --check", _check_wire_study),
    ("decode_kernel_bench --check", _check_decode_bench),
    ("segment_study --check", _check_segment_study),
    ("tree_study --check", _check_tree_study),
    ("decode_study --check", _check_decode_study),
    ("program_lint all_ok",
     _flag_check(os.path.join("baselines_out", "program_lint.json"))),
    ("sharding audit coverage", _check_sharding_audit),
    ("lint config present", _check_lint_config),
    ("chaos_matrix all_ok",
     _flag_check(os.path.join("baselines_out", "chaos_matrix.json"))),
    ("chaos incident coverage", _check_chaos_incidents),
    ("straggler_study all_ok",
     _flag_check(os.path.join("baselines_out", "straggler_study.json"))),
    ("autopilot_study certificates", _check_autopilot_study),
    ("fleet_slo certificates", _check_fleet_slo),
    ("trace_report smoke", _check_trace_report),
    ("forensics_report smoke", _check_forensics_report),
    ("incident_report smoke", _check_incident_report),
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", type=str, default=".",
                    help="repo root holding baselines_out/ + BENCH_r*.json")
    ap.add_argument("--verbose", action="store_true",
                    help="show the sub-verifiers' own output")
    args = ap.parse_args(argv)
    root = os.path.abspath(args.root)
    for name, check in CHECKS:
        buf = io.StringIO()
        try:
            if args.verbose:
                err = check(root)
            else:
                with contextlib.redirect_stdout(buf), \
                        contextlib.redirect_stderr(buf):
                    err = check(root)
        except Exception as e:  # noqa: BLE001 — naming failures IS the job
            err = f"{type(e).__name__}: {e}"
        if err is not None:
            sub = buf.getvalue().strip()
            if sub:
                print(sub)
            print(f"check_artifacts: FAILED at {name!r}: {err}")
            return 1
        print(f"check_artifacts: ok  {name}")
    print(f"check_artifacts: all {len(CHECKS)} artifact checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
