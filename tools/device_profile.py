#!/usr/bin/env python
"""Device-time attribution driver: profile both production loops and fold
the captures into the committed per-phase / per-collective device ledger
(``baselines_out/device_profile.json``, ISSUE 9).

Each CELL is a short production-loop run (8 steps, jax.profiler window over
steps [3, 8) — chunk-snapped under K>1) of a registered chip-bound program
at the program-linter's CI shapes, so the fold can join the PR 5
``cost_analysis`` columns and cross-check the runtime trace's explicit
collectives against the SAME Manifest counts the static audit pinned
(``baselines_out/program_lint.json``). A mismatch is a hard error: the
static audit and the runtime trace must agree (obs/device_attr.cross_check).

  python tools/device_profile.py --run                 # drive all 10 cells
                                                       #  (subprocess each),
                                                       #  fold, write artifact
  python tools/device_profile.py --run --cells lm_sp_k4
  python tools/device_profile.py --fold --work DIR     # re-fold existing
                                                       #  cell dirs, no jax
  python tools/device_profile.py --check               # jax-free self-check
                                                       #  of the committed
                                                       #  artifact (sums,
                                                       #  cross-check rows,
                                                       #  control tripped)

The parent process is jax-free (pure artifact folding; usable on a laptop
against cell dirs scp'd from a chip job) — only the internal ``--run-cell``
subprocess imports jax. Each cell also runs with the host span tracer
(``trace_dir``) so the fold can emit the merged host+device Perfetto
timeline (``<cell>/merged_timeline.json``, obs/device_attr.merge_timeline):
host tracer lanes + device phase lanes on the shared clock the profiler
window anchored (obs/profiling.py).

Folded by ``tools/perf_watch.py``: phase-fraction metrics at the time-kind
tolerance (a decode-share regression gates round-over-round), collective
instruction/byte counts pinned at tolerance 0.

CPU-fallback caveat (PERF.md §8c/§12): on this container the capture is the
XLA:CPU trace shape — attribution works through the runner-dumped scope map
(optimized-HLO metadata), absolute times are not chip times, and there is
no honest hardware peak, so roofline rows carry achieved rates without
peak fractions.
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from draco_tpu.obs import device_attr  # noqa: E402  (jax-free module)

ARTIFACT_REL = os.path.join("baselines_out", "device_profile.json")
LINT_REL = os.path.join("baselines_out", "program_lint.json")

MAX_STEPS = 8           # two K=4 chunks; window [3, 8) profiles steps 3-7
PROFILE_STEPS = (3, 8)  # (K=1) or the whole chunked run 1-8 (K=4)
NUM_DEVICES = 8

# cell -> (loop kind, steps_per_call, lint row whose Manifest counts +
# cost columns the fold joins, config overrides). The K=4 cells join the
# closest registered row: collective counts are per-instruction (a K-fused
# scan compiles its body once, so they are K-independent) and the linter's
# flops column counts the scan body once (per-step figure) — PERF.md §8.
CELLS = {
    "cnn_cyclic_k1": ("cnn", 1, "cnn_cyclic_step", {}),
    "cnn_cyclic_k4": ("cnn", 4, "cnn_cyclic_many_k2", {}),
    "cnn_majvote_k1": ("cnn", 1, "cnn_majvote_step",
                       dict(approach="maj_vote", group_size=4)),
    "cnn_majvote_k4": ("cnn", 4, "cnn_majvote_step",
                       dict(approach="maj_vote", group_size=4)),
    "cnn_approx_k1": ("cnn", 1, "cnn_approx_step",
                      dict(approach="approx", worker_fail=0,
                           redundancy="shared", code_redundancy=1.5)),
    "cnn_approx_k4": ("cnn", 4, "cnn_approx_step",
                      dict(approach="approx", worker_fail=0,
                           redundancy="shared", code_redundancy=1.5)),
    "lm_sp_k1": ("lm_sp", 1, "lm_sp_ring_step", {}),
    "lm_sp_k4": ("lm_sp", 4, "lm_sp_ring_many_k2", {}),
    "lm_tp_k1": ("lm_tp", 1, "lm_tp2_step", {}),
    "lm_tp_k4": ("lm_tp", 4, "lm_tp2_many_k2", {}),
    # fused-decode cells (ISSUE 12): decode_impl="pallas" at the SAME
    # shapes as an xla-path pair cell, so the "decode share dropped"
    # claim is a committed, diffed artifact. On this container the pallas
    # dispatch runs the kernels' fused reference lowering (CPU fallback,
    # ops/decode_kernels.resolve_decode_impl; PERF.md §14).
    "cnn_approx_pallas_k1": ("cnn", 1, "cnn_approx_pallas_step",
                             dict(approach="approx", worker_fail=0,
                                  redundancy="shared", code_redundancy=1.5,
                                  decode_impl="pallas")),
    "cnn_approx_pallas_k4": ("cnn", 4, "cnn_approx_pallas_step",
                             dict(approach="approx", worker_fail=0,
                                  redundancy="shared", code_redundancy=1.5,
                                  decode_impl="pallas")),
    "lm_sp_approx_k4": ("lm_sp", 4, "lm_sp_ring_approx_many_k2",
                        dict(approach="approx", worker_fail=0,
                             code_redundancy=1.5, step_guard="on")),
    "lm_sp_approx_pallas_k4": ("lm_sp", 4,
                               "lm_sp_ring_approx_pallas_many_k2",
                               dict(approach="approx", worker_fail=0,
                                    code_redundancy=1.5, step_guard="on",
                                    decode_impl="pallas")),
    "lm_tp_approx_k4": ("lm_tp", 4, "lm_tp2_approx_many_k2",
                        dict(approach="approx", worker_fail=0,
                             code_redundancy=1.5, step_guard="on")),
    "lm_tp_approx_pallas_k4": ("lm_tp", 4, "lm_tp2_approx_pallas_many_k2",
                               dict(approach="approx", worker_fail=0,
                                    code_redundancy=1.5, step_guard="on",
                                    decode_impl="pallas")),
    # cyclic layer-granularity pair: committed as same-shape evidence of
    # the fused path running the production loop end-to-end; NO
    # share-drop claim on the CPU fallback (the layer decode there is at
    # the per-segment matvec floor, within noise of the xla path — the
    # cyclic kernel's win is TPU-side HBM traffic, PERF.md §14), so this
    # pair is absent from PALLAS_CLAIMS.
    "cnn_cyclic_layer_k1": ("cnn", 1, "cnn_cyclic_layer_step",
                            dict(decode_granularity="layer")),
    "cnn_cyclic_layer_pallas_k1": ("cnn", 1, "cnn_cyclic_layer_pallas_step",
                                   dict(decode_granularity="layer",
                                        decode_impl="pallas")),
}

# pallas cell -> same-shape xla-path cell whose decode self-time share the
# pallas cell's committed row must undercut STRICTLY (the ISSUE 12
# acceptance criterion; enforced by --check, proven live by the flipped-row
# test in tests/test_cli_tools.py). Only the SCANNED LM cells claim: the
# fused win reproduces there run-over-run, while the CNN cells' shares
# move ±3% with XLA:CPU fusion-attribution noise (eager k1 even inverts —
# the true-mean matvec cannot fuse into the grads producer the way the
# xla path's axis-0 reduction does), so those pallas cells are committed
# as same-shape evidence WITHOUT the claim (PERF.md §14; the robust CPU
# evidence for the decode itself is decode_kernel_bench.json).
PALLAS_CLAIMS = {
    "lm_sp_approx_pallas_k4": "lm_sp_approx_k4",
    "lm_tp_approx_pallas_k4": "lm_tp_approx_k4",
}


# --------------------------------------------------------------------------
# --run-cell: the only jax-touching path (always a subprocess of --run)
# --------------------------------------------------------------------------

def _dump_scope_map(cell: str, k: int, lint_row: str, fn, args, mesh,
                    out_dir: str) -> dict:
    """AOT-compile the cell's profiled program and dump the attribution
    scope map next to the (future) capture. Compiled BEFORE the run so the
    heartbeat's on-stop fold can already attribute; XLA:CPU compilation is
    deterministic for a fixed program, so the re-compiled instruction names
    match the names the executed trace will carry (obs/device_attr.py)."""
    with mesh:
        text = fn.lower(*args).compile().as_text()
    scope = device_attr.scope_map_from_hlo(text)
    scope["lint_row"] = lint_row
    payload = {"schema": 1, "cell": cell, "steps_per_call": k,
               "programs": [scope]}
    with open(os.path.join(out_dir, "device_scope_map.json"), "w") as fh:
        json.dump(payload, fh)
    return scope


def run_cell(cell: str, out_dir: str) -> int:
    """Drive one cell: scope-map dump + an 8-step production-loop run with
    the profiler window, host tracer, heartbeat, and compile_guard="raise"
    (the capture must observe, never perturb — a retrace here is a bug)."""
    import jax  # noqa: F401  (the jax-touching path)
    import jax.numpy as jnp
    import numpy as np

    kind, k, lint_row, overrides = CELLS[cell]
    os.makedirs(out_dir, exist_ok=True)
    common = dict(max_steps=MAX_STEPS, eval_freq=0, log_every=1,
                  steps_per_call=k, train_dir=out_dir, trace_dir=out_dir,
                  compile_guard="raise")

    if kind == "cnn":
        from draco_tpu import rng as drng
        from draco_tpu.config import TrainConfig
        from draco_tpu.data.datasets import load_dataset
        from draco_tpu.models import input_shape
        from draco_tpu.runtime import make_mesh
        from draco_tpu.training.trainer import Trainer

        kw = dict(network="LeNet", dataset="synthetic-mnist",
                  approach="cyclic", batch_size=2, num_workers=8,
                  worker_fail=1, err_mode="rev_grad", lr=0.01, momentum=0.9)
        kw.update(overrides)
        kw.update(common)
        cfg = TrainConfig(**kw)
        mesh = make_mesh(cfg.num_workers)
        ds = load_dataset(cfg.dataset, synthetic_train=512,
                          synthetic_test=64)
        tr = Trainer(cfg, mesh=mesh, dataset=ds, quiet=True)
        n, b = cfg.num_workers, cfg.batch_size
        shape = input_shape(cfg.dataset)
        adv = drng.adversary_schedule(cfg.seed, k + 1, n,
                                      cfg.num_adversaries)
        if k > 1:
            args = (tr.setup.state,
                    jnp.zeros((k, n, b) + shape, jnp.float32),
                    jnp.zeros((k, n, b), jnp.int32),
                    jnp.asarray(np.asarray(adv[1:k + 1])), None)
            fn = tr.setup.train_many
        else:
            args = (tr.setup.state,
                    jnp.zeros((n, b) + shape, jnp.float32),
                    jnp.zeros((n, b), jnp.int32),
                    jnp.asarray(np.asarray(adv[1])))
            fn = tr.setup.train_step
        _dump_scope_map(cell, k, lint_row, fn, args, mesh, out_dir)
        tr.run(profile_dir=out_dir, profile_steps=PROFILE_STEPS)
        tr.close()
        return 0

    from draco_tpu.analysis.registry import (
        Manifest, built_token_program, ci_lm_config,
    )
    from draco_tpu.parallel.token_loop import run_token_loop

    if kind == "lm_sp":
        from draco_tpu.parallel.mesh import make_mesh_2d
        from draco_tpu.parallel.sp_step import build_sp_train_setup

        cfg = ci_lm_config(seq_shards=2, **overrides, **common)
        mesh = make_mesh_2d(4, 2)
        setup = build_sp_train_setup(cfg, mesh)
        tag = "sp"
    elif kind == "lm_tp":
        from draco_tpu.parallel.mesh import make_mesh_wtp
        from draco_tpu.parallel.tp_step import build_tp_train_setup

        cfg = ci_lm_config(tensor_shards=2, **overrides, **common)
        mesh = make_mesh_wtp(4, 2)
        setup = build_tp_train_setup(cfg, mesh)
        tag = "tp"
    else:
        raise SystemExit(f"unknown cell kind {kind!r}")
    bp = built_token_program(cell, cfg, mesh, setup, Manifest(),
                             many=(k > 1), k=k)
    _dump_scope_map(cell, k, lint_row, bp.fn, bp.args, mesh, out_dir)
    run_token_loop(setup, cfg, quiet=True, tag=tag, profile_dir=out_dir,
                   profile_steps=PROFILE_STEPS)
    return 0


# --------------------------------------------------------------------------
# fold: capture dirs + program_lint.json -> the committed artifact (jax-free)
# --------------------------------------------------------------------------

def _lint_rows(root: str) -> dict:
    data = device_attr.load_json(os.path.join(root, LINT_REL))
    if not data:
        raise SystemExit(f"no {LINT_REL} under {root} — run "
                         f"tools/program_lint.py first (the fold joins its "
                         f"Manifest counts and cost columns)")
    return {r.get("name"): r for r in data.get("rows", [])}


def _expected_counts(lint_row: dict):
    """The program's linted Manifest collective counts. The linter records
    ``observed`` == the Manifest expectation on every green row (rules.py
    fails the row otherwise), so the committed artifact IS the manifest for
    a jax-free consumer; a row without the rule cross-checks nothing."""
    rule = (lint_row.get("rules") or {}).get("collectives")
    if not rule or not rule.get("ok"):
        return None
    return rule.get("observed")


def fold_cell(cell: str, cell_dir: str, lint_rows: dict) -> dict:
    """One committed-artifact row: phase ledger + collective ledger +
    manifest cross-check + roofline join + merged-timeline summary."""
    _, k, lint_name, _ = CELLS[cell]
    fold = device_attr.fold_capture(cell_dir, strict=True)
    if fold is None:
        raise SystemExit(f"{cell}: no profiler capture under {cell_dir}")
    anchor = fold.get("anchor") or {}
    steps = anchor.get("steps_profiled")
    lint_row = lint_rows.get(lint_name) or {}
    row = {"cell": cell, "steps_per_call": k, "lint_row": lint_name,
           "decode_impl": CELLS[cell][3].get("decode_impl", "xla"),
           "steps_profiled": steps, "programs": []}
    for prog in fold["programs"]:
        expected = _expected_counts(lint_row)
        # the hard-error contract: raises CollectiveMismatchError on drift
        check = device_attr.cross_check(prog["collectives"], expected,
                                        f"{cell}/{prog['module']}")
        entry = {
            "module": prog["module"],
            "total_device_us": round(prog["total_device_us"], 1),
            "wall_us": round(prog["wall_us"], 1),
            "phases": {name: {"time_us": round(r["time_us"], 1),
                              "frac": round(r["frac"], 4),
                              "events": r["events"]}
                       for name, r in prog["phases"].items()},
            "decode_share": round(
                prog["phases"]["draco_decode"]["frac"], 4),
            "collectives": prog["collectives"],
            "cross_check": check,
            "roofline": device_attr.roofline(
                prog["total_device_us"], steps or 0, lint_row),
        }
        row["programs"].append(entry)
    row["ok"] = all(p["cross_check"].get("ok") for p in row["programs"])
    # merged host+device timeline (run artifact, not committed): host
    # tracer lanes + device lanes on the anchored shared clock
    row["merged_timeline"] = _write_timeline(cell_dir, fold)
    return row


def _write_timeline(cell_dir: str, fold: dict):
    trace_path = os.path.join(cell_dir, "trace.json")
    host = device_attr.load_json(trace_path)
    host_events = (host or {}).get("traceEvents") or []
    cap = device_attr.find_capture(cell_dir)
    if cap is None:
        return None
    dev_events, _ = device_attr.load_trace(cap)
    scope = ((device_attr.load_scope_map(cell_dir) or {}).get("programs")
             or [None])[0]
    # cap the device lanes to the longest 100k slices (XLA:CPU conv thunks
    # emit ~1M sub-ms events on the CNN cells) — the drop count rides in
    # the payload AND the committed summary, never silently
    merged = device_attr.merge_timeline(host_events, dev_events, scope,
                                        fold.get("anchor"),
                                        max_device_events=100_000)
    out_path = os.path.join(cell_dir, "merged_timeline.json.gz")
    with gzip.open(out_path, "wt") as fh:
        json.dump(merged, fh)
    dev_n = sum(1 for e in merged["traceEvents"]
                if e.get("cat") == "device")
    mt = merged["mergedTimeline"]
    # path relative to the work dir: the committed artifact must not embed
    # a machine-local temp path (dead pointer + spurious diff per rerun) —
    # the driver prints the work dir holding the cells at exit
    rel_path = os.path.join(os.path.basename(cell_dir.rstrip(os.sep)),
                            os.path.basename(out_path))
    return {"path": rel_path, "anchored": mt["anchored"],
            "anchor_kind": mt.get("anchor_kind"),
            "device_offset_us": mt["device_offset_us"],
            "host_events": len(host_events), "device_events": dev_n,
            "dropped_device_events": mt["droppedDeviceEvents"]}


def seeded_mismatch_control(rows: list) -> dict:
    """The negative control proving the cross-check path live (the PR 3
    controls.py pattern): take a real cell's observed ledger, seed one
    EXTRA all-gather instruction into a copy, and demand the reconciliation
    against the true Manifest counts raises naming the kind. ``ok`` means
    "tripped exactly as required"."""
    base = next((p for r in rows if not r.get("control")
                 for p in r["programs"]
                 if p["cross_check"].get("expected") is not None), None)
    if base is None:
        return {"cell": "control_extra_all_gather", "control": True,
                "ok": False, "error": "no cell with manifest counts folded"}
    doctored = json.loads(json.dumps(base["collectives"]))
    doctored["explicit"]["all_gather"]["instructions"] += 1
    try:
        device_attr.cross_check(doctored, base["cross_check"]["expected"],
                                "control_extra_all_gather")
    except device_attr.CollectiveMismatchError as e:
        tripped = "all_gather" in str(e)
        return {"cell": "control_extra_all_gather", "control": True,
                "ok": tripped, "seeded_on": base["module"],
                "error": str(e)[:300]}
    return {"cell": "control_extra_all_gather", "control": True,
            "ok": False,
            "error": "seeded extra all-gather did NOT trip cross_check"}


def fold_all(work: str, cells: list, root: str) -> dict:
    lint_rows = _lint_rows(root)
    rows = [fold_cell(c, os.path.join(work, c), lint_rows) for c in cells]
    rows.append(seeded_mismatch_control(rows))
    return {
        "schema": 1,
        "tool": "tools/device_profile.py --run",
        "method": (
            "8-step production-loop runs (Trainer / run_token_loop) at the "
            "program-linter CI shapes with a jax.profiler window over steps "
            "[3, 8) (chunk-snapped under K>1), compile_guard=raise; device "
            "events attributed per-thread-self-time to the draco_* named "
            "scopes via the runner-dumped optimized-HLO scope map; explicit "
            "collectives cross-checked against the linted Manifest counts "
            "(mismatch = hard error, proven live by the seeded control row)"
        ),
        "profile_steps": list(PROFILE_STEPS),
        "devices": NUM_DEVICES,
        "cpu_fallback": True,  # this container has no TPU (PERF.md §8c)
        "all_ok": all(r.get("ok") for r in rows),
        "cells": rows,
    }


# --------------------------------------------------------------------------
# --check: jax-free self-consistency gate on the committed artifact
# --------------------------------------------------------------------------

def check_artifact(path: str, out=None) -> int:
    """Validate the committed artifact's internal contracts: per program
    the phase rows (incl. the explicit residual rows) sum to
    total_device_us, decode_share equals the decode row's fraction, every
    cross-check row agrees observed == expected, the seeded mismatch
    control actually tripped, and every PALLAS_CLAIMS pair shows the
    fused-decode cell's decode self-time share STRICTLY below its
    same-shape xla pair (the ISSUE 12 acceptance gate). Exit 1 naming
    each violated metric — the CI gate tests/test_cli_tools.py drives
    with flipped decode-share rows."""
    out = out if out is not None else sys.stdout
    data = device_attr.load_json(path)
    if not data:
        print(f"device_profile --check: no artifact at {path}", file=out)
        return 1
    bad = []
    shares = {}
    for row in data.get("cells", []):
        if not row.get("control") and len(row.get("programs", [])) == 1:
            shares[row.get("cell")] = float(
                row["programs"][0].get("decode_share", -1.0))
    for pal, xla in sorted(PALLAS_CLAIMS.items()):
        if pal not in shares or xla not in shares:
            # every claimed pair is REQUIRED in the committed artifact — a
            # regeneration that drops the cells must fail here, not let
            # the strictly-below claim silently go unenforced
            bad.append(f"{pal}: claim pair missing/incomplete (needs both "
                       f"{pal} and {xla} cells)")
            continue
        if not shares[pal] < shares[xla]:
            bad.append(f"{pal}: decode share {shares[pal]} not strictly "
                       f"below xla pair {xla} ({shares[xla]})")
    for row in data.get("cells", []):
        cell = row.get("cell")
        if row.get("control"):
            if not row.get("ok"):
                bad.append(f"{cell}: mismatch control did not trip "
                           f"({row.get('error')})")
            continue
        for prog in row.get("programs", []):
            total = float(prog.get("total_device_us", 0.0))
            phases = prog.get("phases", {})
            sum_us = sum(float(p.get("time_us", 0.0))
                         for p in phases.values())
            # rounded to 0.1 us per row in the artifact
            if abs(sum_us - total) > max(1e-6 * total,
                                         0.1 * (len(phases) + 1)):
                bad.append(f"{cell}: phase rows sum {sum_us:.1f} != "
                           f"total_device_us {total:.1f}")
            dec = phases.get("draco_decode", {})
            share = float(prog.get("decode_share", -1.0))
            if abs(share - float(dec.get("frac", 0.0))) > 5e-4:
                bad.append(f"{cell}: decode_share {share} != "
                           f"draco_decode frac {dec.get('frac')}")
            check = prog.get("cross_check", {})
            exp, obs = check.get("expected"), check.get("observed")
            if exp is not None and exp != obs:
                bad.append(f"{cell}: cross_check expected {exp} != "
                           f"observed {obs}")
            if not check.get("ok"):
                bad.append(f"{cell}: cross_check not ok")
    if not data.get("all_ok") and not bad:
        bad.append("all_ok is false")
    if bad:
        for b in bad:
            print(f"device_profile FAIL: {b}", file=out)
        return 1
    n = len([r for r in data.get('cells', []) if not r.get('control')])
    print(f"device_profile --check: {n} cells + control consistent", file=out)
    return 0


# --------------------------------------------------------------------------
# entry
# --------------------------------------------------------------------------

def _spawn_cells(cells: list, work: str) -> None:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={NUM_DEVICES}"
        ).strip()
    for cell in cells:
        out_dir = os.path.join(work, cell)
        print(f"device_profile: running cell {cell} -> {out_dir}",
              flush=True)
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--run-cell", cell, "--out", out_dir],
            env=env, capture_output=True, text=True, timeout=1800)
        if res.returncode != 0:
            sys.stderr.write(res.stdout[-2000:] + res.stderr[-4000:])
            raise SystemExit(f"cell {cell} failed (rc={res.returncode})")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--run", action="store_true",
                    help="drive the cells as subprocesses, then fold")
    ap.add_argument("--fold", action="store_true",
                    help="fold existing cell dirs under --work (no jax)")
    ap.add_argument("--check", action="store_true",
                    help="self-check the committed artifact (no jax)")
    ap.add_argument("--run-cell", default="", help=argparse.SUPPRESS)
    ap.add_argument("--out", default="", help=argparse.SUPPRESS)
    ap.add_argument("--cells", default="",
                    help="comma-separated cell subset (default: all)")
    ap.add_argument("--work", default="",
                    help="cell run dir (default: a temp dir under --run; "
                         "required for --fold)")
    ap.add_argument("--root", default=".",
                    help="repo root holding baselines_out/")
    ap.add_argument("--artifact", default="",
                    help=f"artifact path (default <root>/{ARTIFACT_REL})")
    args = ap.parse_args(argv)

    artifact = args.artifact or os.path.join(args.root, ARTIFACT_REL)
    if args.run_cell:
        return run_cell(args.run_cell, args.out or ".")
    if args.check:
        return check_artifact(artifact)

    cells = ([c.strip() for c in args.cells.split(",") if c.strip()]
             or list(CELLS))
    unknown = [c for c in cells if c not in CELLS]
    if unknown:
        raise SystemExit(f"unknown cells {unknown}; known: {list(CELLS)}")
    if args.run:
        work = args.work or tempfile.mkdtemp(prefix="device_profile_")
        _spawn_cells(cells, work)
    elif args.fold:
        if not args.work:
            raise SystemExit("--fold needs --work (the cell run dir)")
        work = args.work
    else:
        raise SystemExit("pick one of --run / --fold / --check")

    payload = fold_all(work, cells, args.root)
    os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
    with open(artifact, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    n_ok = sum(1 for r in payload["cells"] if r.get("ok"))
    print(f"device_profile: {n_ok}/{len(payload['cells'])} rows ok -> "
          f"{artifact}  (cells under {work})")
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
